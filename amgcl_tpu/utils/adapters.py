"""Problem adapters: reordering, symmetric scaling, complex→real.

Reference surface: amgcl/adapter/reorder.hpp + amgcl/reorder/cuthill_mckee.hpp
(permutation applied to matrix and vectors), amgcl/adapter/scaled_problem.hpp
(symmetric diagonal scaling), amgcl/adapter/complex.hpp (complex system as
its 2×2 real-block equivalent). The zero-copy/crs_tuple adapters of the
reference collapse to ``CSR.from_scipy`` / the (ptr, col, val) constructor,
which never copy device-side.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp
from scipy.sparse.csgraph import reverse_cuthill_mckee

from amgcl_tpu.ops.csr import CSR


def cuthill_mckee(A: CSR) -> np.ndarray:
    """Reverse Cuthill-McKee permutation (bandwidth reduction) — makes the
    DIA device format dramatically denser in diagonals for unstructured
    meshes. Returns perm such that B = A[perm][:, perm]."""
    m = A.to_scipy()
    return np.asarray(reverse_cuthill_mckee(m, symmetric_mode=True))


def permute(A: CSR, perm: np.ndarray) -> CSR:
    """B = P A Pᵀ with B[i, j] = A[perm[i], perm[j]]."""
    m = A.to_scipy()[perm][:, perm].tocsr()
    m.sort_indices()
    return CSR.from_scipy(m)


class Reordered:
    """Wrap any solver factory so callers never see the permutation
    (reference: adapter::reorder)."""

    def __init__(self, A, solver_factory, perm=None):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.perm = cuthill_mckee(A) if perm is None else np.asarray(perm)
        self.iperm = np.empty_like(self.perm)
        self.iperm[self.perm] = np.arange(len(self.perm))
        self.solve = solver_factory(permute(A, self.perm))

    def __call__(self, rhs, x0=None):
        rhs = np.asarray(rhs)[self.perm]
        if x0 is not None:
            x0 = np.asarray(x0)[self.perm]
        x, info = self.solve(rhs, x0)
        return np.asarray(x)[self.iperm], info


class Scaled:
    """Symmetric diagonal scaling: solve (D^-1/2 A D^-1/2) y = D^-1/2 b,
    return x = D^-1/2 y (reference: adapter::scaled_problem)."""

    def __init__(self, A, solver_factory):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        d = np.abs(A.diagonal().astype(np.float64))
        self.s = 1.0 / np.sqrt(np.where(d > 0, d, 1.0))
        m = A.to_scipy()
        S = sp.diags(self.s)
        ms = (S @ m @ S).tocsr()
        ms.sort_indices()
        self.solve = solver_factory(CSR.from_scipy(ms))

    def __call__(self, rhs, x0=None):
        rhs = np.asarray(rhs) * self.s
        if x0 is not None:
            x0 = np.asarray(x0) / self.s
        y, info = self.solve(rhs, x0)
        return np.asarray(y) * self.s, info


def complex_to_real(A: CSR, rhs=None):
    """Complex n×n system → real 2n×2n with 2×2 blocks [[re, -im],[im, re]];
    rhs interleaves (re, im) (reference: amgcl/adapter/complex.hpp)."""
    assert np.iscomplexobj(A.val)
    m = A.to_scipy()
    re, im = m.real.tocsr(), m.imag.tocsr()
    top = sp.hstack([re, -im])
    bot = sp.hstack([im, re])
    # interleave via permutation so the block structure is per-unknown
    n = A.nrows
    P = sp.csr_matrix(
        (np.ones(2 * n), (np.r_[0:2 * n:2, 1:2 * n:2], np.arange(2 * n))),
        shape=(2 * n, 2 * n))
    M = (P @ sp.vstack([top, bot]).tocsr() @ P.T).tocsr()
    M.sort_indices()
    Ar = CSR.from_scipy(M)
    if rhs is None:
        return Ar
    rr = np.empty(2 * n)
    rr[0::2] = np.real(rhs)
    rr[1::2] = np.imag(rhs)
    return Ar, rr


def real_to_complex(x) -> np.ndarray:
    x = np.asarray(x)
    return x[0::2] + 1j * x[1::2]
