"""Matrix/vector IO: MatrixMarket and a raw binary format.

Mirrors the reference's IO surface (amgcl/io/mm.hpp:52-383 — sparse+dense,
real+complex, general/symmetric; amgcl/io/binary.hpp:70-167 — read_crs/
read_dense/write). MatrixMarket parsing delegates to scipy (battle-tested C
fast path) rather than hand-rolling a reader; the binary format is
self-describing: magic, dtype codes, shapes, then raw arrays.
"""

from __future__ import annotations

import struct

import numpy as np
import scipy.io
import scipy.sparse as sp

from amgcl_tpu.ops.csr import CSR

_MAGIC = b"AMGTPU1\x00"
_DTYPES = {0: np.float64, 1: np.float32, 2: np.complex128, 3: np.int32,
           4: np.int64, 5: np.complex64, 6: np.float16}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def _storable(a: np.ndarray) -> np.ndarray:
    """Cast exotic accelerator dtypes (bfloat16, ...) to the nearest
    storable numpy dtype instead of raising KeyError mid-save."""
    if np.dtype(a.dtype) in _DTYPE_CODES:
        return a
    if np.issubdtype(np.asarray(a).dtype, np.complexfloating):
        return a.astype(np.complex128)
    return a.astype(np.float32)


# -- MatrixMarket -----------------------------------------------------------

def mm_read(path):
    """Read a MatrixMarket file -> CSR (sparse) or ndarray (dense array)."""
    m = scipy.io.mmread(path)
    if sp.issparse(m):
        return CSR.from_scipy(m.tocsr())
    a = np.asarray(m)
    return a.ravel() if a.ndim == 2 and 1 in a.shape else a


def mm_write(path, m, comment: str = ""):
    """Write CSR / scipy sparse / ndarray to MatrixMarket."""
    if isinstance(m, CSR):
        m = m.to_scipy()
    if sp.issparse(m):
        scipy.io.mmwrite(path, m, comment=comment)
    else:
        a = np.asarray(m)
        if a.ndim == 1:
            a = a[:, None]
        scipy.io.mmwrite(path, a, comment=comment)


# -- binary -----------------------------------------------------------------

def write_binary(path, m):
    """Self-describing binary dump of a CSR matrix or dense ndarray."""
    with open(path, "wb") as f:
        f.write(_MAGIC)
        if isinstance(m, CSR) or sp.issparse(m):
            if not isinstance(m, CSR):
                m = CSR.from_scipy(m.tocsr())
            f.write(struct.pack("<B", 1))                    # kind: sparse
            f.write(struct.pack("<qq", m.nrows, m.ncols))
            br, bc = m.block_size
            f.write(struct.pack("<qq", br, bc))
            for arr in (m.ptr.astype(np.int64), m.col.astype(np.int32),
                        _storable(np.ascontiguousarray(m.val))):
                code = _DTYPE_CODES[np.dtype(arr.dtype)]
                f.write(struct.pack("<Bq", code, arr.size))
                f.write(arr.tobytes())
        else:
            a = _storable(np.ascontiguousarray(m))
            f.write(struct.pack("<B", 0))                    # kind: dense
            f.write(struct.pack("<B", a.ndim))
            f.write(struct.pack("<%dq" % a.ndim, *a.shape))
            code = _DTYPE_CODES[np.dtype(a.dtype)]
            f.write(struct.pack("<Bq", code, a.size))
            f.write(a.tobytes())


def read_binary_reference_crs(path):
    """Reader for the reference toolchain's RAW headerless CRS layout
    (amgcl/io/binary.hpp:70-122, as written by examples/mm2bin.cpp):
    [n: u64][ptr: (n+1) x i64][col: nnz x i64][val: nnz x f64].
    The layout is not self-describing, so plausibility checks guard
    against misinterpreting arbitrary binaries."""
    with open(path, "rb") as f:
        raw_n = f.read(8)
        if len(raw_n) != 8:
            raise ValueError("%s: truncated file" % path)
        n = int(np.frombuffer(raw_n, dtype=np.uint64)[0])
        import os as _os
        fsize = _os.fstat(f.fileno()).st_size
        if n <= 0 or 8 + (n + 1) * 8 > fsize:
            raise ValueError("%s: not a reference raw CRS file" % path)
        ptr = np.frombuffer(f.read((n + 1) * 8), dtype=np.int64)
        nnz = int(ptr[-1])
        good = (ptr[0] == 0 and nnz >= n and np.all(np.diff(ptr) >= 0)
                and 8 + (n + 1) * 8 + nnz * 16 == fsize)
        if not good:
            raise ValueError("%s: not a reference raw CRS file" % path)
        col = np.frombuffer(f.read(nnz * 8), dtype=np.int64)
        val = np.frombuffer(f.read(nnz * 8), dtype=np.float64)
        if col.min(initial=0) < 0:
            raise ValueError("%s: negative column index" % path)
        # the reference layout stores square systems; keep ncols >= n
        return CSR(ptr, col.astype(np.int32), val.copy(),
                   max(n, int(col.max(initial=-1)) + 1))


def read_binary(path):
    """Read back what write_binary produced; falls back to the reference
    toolchain's raw CRS layout so .bin files produced by mm2bin load too
    (round-1 advisor finding: the two formats were not interchangeable)."""
    with open(path, "rb") as f:
        if f.read(len(_MAGIC)) != _MAGIC:
            try:
                return read_binary_reference_crs(path)
            except ValueError:
                raise ValueError(
                    "%s: neither an amgcl_tpu binary file nor a reference "
                    "raw CRS file" % path)
        kind = struct.unpack("<B", f.read(1))[0]
        if kind == 1:
            nrows, ncols = struct.unpack("<qq", f.read(16))
            br, bc = struct.unpack("<qq", f.read(16))
            arrs = []
            for _ in range(3):
                code, size = struct.unpack("<Bq", f.read(9))
                dt = np.dtype(_DTYPES[code])
                arrs.append(np.frombuffer(f.read(size * dt.itemsize),
                                          dtype=dt))
            ptr, col, val = arrs
            if (br, bc) != (1, 1):
                val = val.reshape(-1, br, bc)
            return CSR(ptr, col, val, ncols)
        ndim = struct.unpack("<B", f.read(1))[0]
        shape = struct.unpack("<%dq" % ndim, f.read(8 * ndim))
        code, size = struct.unpack("<Bq", f.read(9))
        dt = np.dtype(_DTYPES[code])
        return np.frombuffer(f.read(size * dt.itemsize),
                             dtype=dt).reshape(shape)
