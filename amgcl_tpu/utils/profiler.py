"""Hierarchical scoped profiler — the reference's tic/toc tree
(amgcl/profiler.hpp:53-216) with the same shape of report: a nested tree of
named scopes with absolute seconds and percentages. Device work is made
observable by an optional sync callback (block_until_ready) so the numbers
mean wall-clock, not dispatch time.

Usage::

    prof = Profiler()
    with prof.scope("setup"):
        with prof.scope("coarsening"):
            ...
    print(prof)

or ``prof.tic("setup") ... prof.toc("setup")`` like the reference macros.

``Profiler.device()`` builds a sync-aware instance: every tic/toc first
drains the default device's dispatch queue, so scope totals include the
device time of everything launched inside them (JAX is async — without the
sync a scope only measures Python dispatch). ``to_dict()`` exports the tree
for the JSONL telemetry sink.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional


class _Node:
    __slots__ = ("name", "total", "count", "children", "_started")

    def __init__(self, name):
        self.name = name
        self.total = 0.0
        self.count = 0
        self.children: Dict[str, "_Node"] = {}
        self._started = None


_sync_barrier = None


def device_sync():
    """Block until the default device has executed everything dispatched so
    far (JAX executes in dispatch order per device, so blocking on a fresh
    trivial computation drains the queue). The no-op barrier is compiled
    once and cached — a per-call jit(lambda) would retrace every sync and
    bill the compile time to the scope being measured. No-op when jax is
    unavailable."""
    global _sync_barrier
    try:
        import jax
        if _sync_barrier is None:
            _sync_barrier = jax.jit(lambda: 0.0)
        jax.block_until_ready(_sync_barrier())
    except Exception:
        pass


class Profiler:
    def __init__(self, sync: Optional[Callable[[], None]] = None):
        self.root = _Node("[root]")
        self._stack = [self.root]
        self._t0 = time.perf_counter()
        self._sync = sync

    @classmethod
    def device(cls) -> "Profiler":
        """Sync-aware profiler: scope boundaries drain the device queue so
        totals mean device wall-clock, not dispatch time."""
        return cls(sync=device_sync)

    def tic(self, name: str):
        if self._sync:
            self._sync()
        cur = self._stack[-1]
        node = cur.children.get(name)
        if node is None:
            node = cur.children[name] = _Node(name)
        node._started = time.perf_counter()
        self._stack.append(node)

    def toc(self, name: str):
        """Close the innermost scope, which must be ``name`` — a mismatch
        is a hard error (and leaves the stack untouched, so the report
        still shows where the pairing went wrong)."""
        if self._sync:
            self._sync()
        node = self._stack[-1]
        if node.name != name:
            raise RuntimeError("profiler scope mismatch: toc(%r) inside %r"
                               % (name, node.name))
        self._stack.pop()
        node.total += time.perf_counter() - node._started
        node.count += 1

    def _unwind(self, depth: int):
        """Close every scope above ``depth`` — abandoned by an exception
        that escaped between a tic and its toc inside a ``scope()``."""
        now = time.perf_counter()
        while len(self._stack) > depth:
            node = self._stack.pop()
            node.total += now - node._started
            node.count += 1

    @contextmanager
    def scope(self, name: str):
        depth = len(self._stack)
        self.tic(name)
        try:
            yield
        except BaseException:
            # the exception may have escaped between an inner tic and its
            # toc: close the abandoned scopes so this toc pairs correctly
            # and subsequent tic/toc pairing is not corrupted
            self._unwind(depth + 1)
            self.toc(name)
            raise
        else:
            # clean exit keeps strict pairing: a forgotten inner toc still
            # surfaces as the scope-mismatch RuntimeError
            self.toc(name)

    def to_dict(self) -> dict:
        """Nested export for the JSONL sink: {"total_s", "scopes": {name:
        {"total_s", "count", "children": {...}}}} — same tree as __str__."""
        def walk(node):
            return {name: {"total_s": ch.total, "count": ch.count,
                           **({"children": walk(ch)} if ch.children
                              else {})}
                    for name, ch in node.children.items()}

        return {"total_s": time.perf_counter() - self._t0,
                "scopes": walk(self.root)}

    def __str__(self):
        lines = ["Profile:"]
        total = time.perf_counter() - self._t0
        lines.append("%-40s %10.3f s" % ("[total]", total))

        def walk(node, depth):
            for name in node.children:
                ch = node.children[name]
                pct = 100.0 * ch.total / total if total > 0 else 0.0
                lines.append("%-40s %10.3f s %6.2f%%"
                             % ("  " * depth + name, ch.total, pct))
                walk(ch, depth + 1)

        walk(self.root, 1)
        return "\n".join(lines)


def aggregate(profilers):
    """min/avg/max of every scope total across a set of profilers — the
    single-controller rendition of the reference's
    ``perf_counter::mpi_aggregator`` (amgcl/perf_counter/
    mpi_aggregator.hpp:43-123, which reduces any counter across ranks).
    Useful for multi-process launches (jax.distributed) or repeated runs.

    Returns {scope_path: (min, avg, max)} and prints like the reference's
    aggregated profile when str()-ed via ``format_aggregate``."""
    totals = {}

    def walk(node, path):
        for name, ch in node.children.items():
            p = path + "/" + name if path else name
            totals.setdefault(p, []).append(ch.total)
            walk(ch, p)

    for pr in profilers:
        walk(pr.root, "")
    return {k: (min(v), sum(v) / len(v), max(v))
            for k, v in totals.items()}


def format_aggregate(agg) -> str:
    lines = ["Aggregated profile:",
             "%-40s %10s %10s %10s" % ("", "min", "avg", "max")]
    for k in sorted(agg):
        mn, av, mx = agg[k]
        lines.append("%-40s %9.3fs %9.3fs %9.3fs" % (k, mn, av, mx))
    return "\n".join(lines)


#: module-level default profiler, like the reference's global ``prof``
prof = Profiler()
