"""Hierarchical scoped profiler — the reference's tic/toc tree
(amgcl/profiler.hpp:53-216) with the same shape of report: a nested tree of
named scopes with absolute seconds and percentages. Device work is made
observable by an optional sync callback (block_until_ready) so the numbers
mean wall-clock, not dispatch time.

Usage::

    prof = Profiler()
    with prof.scope("setup"):
        with prof.scope("coarsening"):
            ...
    print(prof)

or ``prof.tic("setup") ... prof.toc("setup")`` like the reference macros.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional


class _Node:
    __slots__ = ("name", "total", "count", "children", "_started")

    def __init__(self, name):
        self.name = name
        self.total = 0.0
        self.count = 0
        self.children: Dict[str, "_Node"] = {}
        self._started = None


class Profiler:
    def __init__(self, sync: Optional[Callable[[], None]] = None):
        self.root = _Node("[root]")
        self._stack = [self.root]
        self._t0 = time.perf_counter()
        self._sync = sync

    def tic(self, name: str):
        if self._sync:
            self._sync()
        cur = self._stack[-1]
        node = cur.children.get(name)
        if node is None:
            node = cur.children[name] = _Node(name)
        node._started = time.perf_counter()
        self._stack.append(node)

    def toc(self, name: str):
        if self._sync:
            self._sync()
        node = self._stack.pop()
        if node.name != name:
            raise RuntimeError("profiler scope mismatch: toc(%r) inside %r"
                               % (name, node.name))
        node.total += time.perf_counter() - node._started
        node.count += 1

    @contextmanager
    def scope(self, name: str):
        self.tic(name)
        try:
            yield
        finally:
            self.toc(name)

    def __str__(self):
        lines = ["Profile:"]
        total = time.perf_counter() - self._t0
        lines.append("%-40s %10.3f s" % ("[total]", total))

        def walk(node, depth):
            for name in node.children:
                ch = node.children[name]
                pct = 100.0 * ch.total / total if total > 0 else 0.0
                lines.append("%-40s %10.3f s %6.2f%%"
                             % ("  " * depth + name, ch.total, pct))
                walk(ch, depth + 1)

        walk(self.root, 1)
        return "\n".join(lines)


def aggregate(profilers):
    """min/avg/max of every scope total across a set of profilers — the
    single-controller rendition of the reference's
    ``perf_counter::mpi_aggregator`` (amgcl/perf_counter/
    mpi_aggregator.hpp:43-123, which reduces any counter across ranks).
    Useful for multi-process launches (jax.distributed) or repeated runs.

    Returns {scope_path: (min, avg, max)} and prints like the reference's
    aggregated profile when str()-ed via ``format_aggregate``."""
    totals = {}

    def walk(node, path):
        for name, ch in node.children.items():
            p = path + "/" + name if path else name
            totals.setdefault(p, []).append(ch.total)
            walk(ch, p)

    for pr in profilers:
        walk(pr.root, "")
    return {k: (min(v), sum(v) / len(v), max(v))
            for k, v in totals.items()}


def format_aggregate(agg) -> str:
    lines = ["Aggregated profile:",
             "%-40s %10s %10s %10s" % ("", "min", "avg", "max")]
    for k in sorted(agg):
        mn, av, mx = agg[k]
        lines.append("%-40s %9.3fs %9.3fs %9.3fs" % (k, mn, av, mx))
    return "\n".join(lines)


#: module-level default profiler, like the reference's global ``prof``
prof = Profiler()
