"""Hierarchical scoped profiler — the reference's tic/toc tree
(amgcl/profiler.hpp:53-216) with the same shape of report: a nested tree of
named scopes with absolute seconds and percentages. Device work is made
observable by an optional sync callback (block_until_ready) so the numbers
mean wall-clock, not dispatch time.

Usage::

    prof = Profiler()
    with prof.scope("setup"):
        with prof.scope("coarsening"):
            ...
    print(prof)

or ``prof.tic("setup") ... prof.toc("setup")`` like the reference macros.

``Profiler.device()`` builds a sync-aware instance: every tic/toc first
drains the default device's dispatch queue, so scope totals include the
device time of everything launched inside them (JAX is async — without the
sync a scope only measures Python dispatch). ``to_dict()`` exports the tree
for the JSONL telemetry sink; ``to_chrome_trace()`` exports the recorded
scope occurrences as Chrome/Perfetto trace-event JSON (``cli.py --trace``)
so setup/solve profiles open in ui.perfetto.dev.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional


class _Node:
    __slots__ = ("name", "total", "count", "children", "_started")

    def __init__(self, name):
        self.name = name
        self.total = 0.0
        self.count = 0
        self.children: Dict[str, "_Node"] = {}
        self._started = None


_sync_barrier = None


def device_sync():
    """Block until the default device has executed everything dispatched so
    far (JAX executes in dispatch order per device, so blocking on a fresh
    trivial computation drains the queue). The no-op barrier is compiled
    once and cached — a per-call jit(lambda) would retrace every sync and
    bill the compile time to the scope being measured. No-op when jax is
    unavailable."""
    global _sync_barrier
    try:
        import jax
        if _sync_barrier is None:
            _sync_barrier = jax.jit(lambda: 0.0)
        jax.block_until_ready(_sync_barrier())
    except Exception:
        pass


class Profiler:
    #: per-occurrence event cap for the trace export (a profiler driven
    #: inside a long loop must not grow without bound; past the cap only
    #: the aggregated tree keeps accumulating and the export notes the
    #: drop count)
    MAX_EVENTS = 100_000

    def __init__(self, sync: Optional[Callable[[], None]] = None):
        self.root = _Node("[root]")
        self._stack = [self.root]
        self._t0 = time.perf_counter()
        self._sync = sync
        #: (path, start_s, end_s) per closed scope occurrence — the
        #: timeline the Chrome-trace export renders (to_chrome_trace)
        self.events = []
        self._events_dropped = 0

    def _record_event(self, node, start, end):
        if len(self.events) >= self.MAX_EVENTS:
            if self._events_dropped == 0:
                # the cap tripping must be loud ONCE: a silently truncated
                # trace looks complete in Perfetto and hides exactly the
                # tail a long-running loop was opened to inspect (the
                # aggregated tic/toc tree keeps accumulating regardless)
                import warnings
                warnings.warn(
                    "Profiler event cap reached (%d per-occurrence "
                    "events); further scope occurrences are dropped from "
                    "the trace export — aggregate totals stay complete"
                    % self.MAX_EVENTS)
            self._events_dropped += 1     # saturated: skip the path work
            return
        path = "/".join([n.name for n in self._stack[1:]] + [node.name])
        self.events.append((path, start, end))

    @classmethod
    def device(cls) -> "Profiler":
        """Sync-aware profiler: scope boundaries drain the device queue so
        totals mean device wall-clock, not dispatch time."""
        return cls(sync=device_sync)

    def tic(self, name: str):
        if self._sync:
            self._sync()
        cur = self._stack[-1]
        node = cur.children.get(name)
        if node is None:
            node = cur.children[name] = _Node(name)
        node._started = time.perf_counter()
        self._stack.append(node)

    def toc(self, name: str):
        """Close the innermost scope, which must be ``name`` — a mismatch
        is a hard error (and leaves the stack untouched, so the report
        still shows where the pairing went wrong)."""
        if self._sync:
            self._sync()
        node = self._stack[-1]
        if node.name != name:
            raise RuntimeError("profiler scope mismatch: toc(%r) inside %r"
                               % (name, node.name))
        self._stack.pop()
        now = time.perf_counter()
        node.total += now - node._started
        node.count += 1
        self._record_event(node, node._started, now)

    def _unwind(self, depth: int):
        """Close every scope above ``depth`` — abandoned by an exception
        that escaped between a tic and its toc inside a ``scope()``."""
        now = time.perf_counter()
        while len(self._stack) > depth:
            node = self._stack.pop()
            node.total += now - node._started
            node.count += 1
            self._record_event(node, node._started, now)

    @contextmanager
    def scope(self, name: str):
        depth = len(self._stack)
        self.tic(name)
        try:
            yield
        except BaseException:
            # the exception may have escaped between an inner tic and its
            # toc: close the abandoned scopes so this toc pairs correctly
            # and subsequent tic/toc pairing is not corrupted
            self._unwind(depth + 1)
            self.toc(name)
            raise
        else:
            # clean exit keeps strict pairing: a forgotten inner toc still
            # surfaces as the scope-mismatch RuntimeError
            self.toc(name)

    def to_dict(self) -> dict:
        """Nested export for the JSONL sink: {"total_s", "scopes": {name:
        {"total_s", "count", "children": {...}}}} — same tree as __str__."""
        def walk(node):
            return {name: {"total_s": ch.total, "count": ch.count,
                           **({"children": walk(ch)} if ch.children
                              else {})}
                    for name, ch in node.children.items()}

        return {"total_s": time.perf_counter() - self._t0,
                "scopes": walk(self.root)}

    def to_chrome_trace(self, tid: int = 0, tid_name: Optional[str] = None,
                        pid: int = 0,
                        epoch: Optional[float] = None,
                        counters: Optional[Dict[str, Dict[str, float]]]
                        = None) -> dict:
        """Chrome/Perfetto trace-event export of the recorded scope
        occurrences: ``json.dump`` the returned dict and open it in
        ui.perfetto.dev (or chrome://tracing, or the TensorBoard trace
        viewer). Each closed scope becomes a complete ('ph':'X') event
        with microsecond timestamps relative to the profiler's birth, so
        the nesting renders as the familiar flame graph of the tic/toc
        tree. ``tid``/``tid_name`` let multiple profilers (e.g. the CLI
        wall-clock profiler and the AMG setup profiler) merge into one
        trace as separate named tracks — concatenate their
        ``traceEvents`` and pass the SAME ``epoch`` (a
        ``time.perf_counter()`` reference, e.g. the main profiler's
        ``_t0``) to every export so the tracks share one timeline; the
        default epoch is this profiler's own birth.

        ``counters`` optionally adds Perfetto COUNTER tracks:
        ``{track_name: {scope_path: value}}`` — every recorded occurrence
        of ``scope_path`` emits the value at its start and 0 at its end
        (``ph:'C'``), so e.g. the roofline's achieved-GB/s per stage
        renders as a stepped bandwidth track above the flame graph
        (``telemetry.roofline.counter_map`` builds the mapping)."""
        t0 = self._t0 if epoch is None else epoch
        events = []
        if tid_name:
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": tid_name}})
        for path, start, end in self.events:
            events.append({
                "name": path.rsplit("/", 1)[-1],
                "cat": "amgcl",
                "ph": "X",
                "ts": round((start - t0) * 1e6, 3),
                "dur": round((end - start) * 1e6, 3),
                "pid": pid, "tid": tid,
                "args": {"path": path},
            })
            for track, by_path in (counters or {}).items():
                val = by_path.get(path)
                if val is None:
                    continue
                for ts, v in ((start, val), (end, 0.0)):
                    events.append({
                        "name": track, "cat": "amgcl", "ph": "C",
                        "ts": round((ts - t0) * 1e6, 3), "pid": pid,
                        "args": {track: v}})
        if self._events_dropped:
            # a visible instant event at the truncation point — the
            # otherData note alone never shows in the Perfetto UI, so a
            # truncated trace used to read as a complete one
            last_end = self.events[-1][2] if self.events else t0
            events.append({
                "name": "events_dropped", "cat": "amgcl", "ph": "i",
                "s": "g", "ts": round((last_end - t0) * 1e6, 3),
                "pid": pid, "tid": tid,
                "args": {"dropped": self._events_dropped,
                         "cap": self.MAX_EVENTS}})
        out = {"traceEvents": events, "displayTimeUnit": "ms"}
        if self._events_dropped:
            out["otherData"] = {"events_dropped": self._events_dropped}
        return out

    def __str__(self):
        lines = ["Profile:"]
        total = time.perf_counter() - self._t0
        lines.append("%-40s %10.3f s" % ("[total]", total))

        def walk(node, depth):
            for name in node.children:
                ch = node.children[name]
                pct = 100.0 * ch.total / total if total > 0 else 0.0
                lines.append("%-40s %10.3f s %6.2f%%"
                             % ("  " * depth + name, ch.total, pct))
                walk(ch, depth + 1)

        walk(self.root, 1)
        return "\n".join(lines)


def aggregate(profilers):
    """min/avg/max of every scope total across a set of profilers — the
    single-controller rendition of the reference's
    ``perf_counter::mpi_aggregator`` (amgcl/perf_counter/
    mpi_aggregator.hpp:43-123, which reduces any counter across ranks).
    Useful for multi-process launches (jax.distributed) or repeated runs.

    Returns {scope_path: (min, avg, max)} and prints like the reference's
    aggregated profile when str()-ed via ``format_aggregate``."""
    totals = {}

    def walk(node, path):
        for name, ch in node.children.items():
            p = path + "/" + name if path else name
            totals.setdefault(p, []).append(ch.total)
            walk(ch, p)

    for pr in profilers:
        walk(pr.root, "")
    return {k: (min(v), sum(v) / len(v), max(v))
            for k, v in totals.items()}


def format_aggregate(agg) -> str:
    lines = ["Aggregated profile:",
             "%-40s %10s %10s %10s" % ("", "min", "avg", "max")]
    for k in sorted(agg):
        mn, av, mx = agg[k]
        lines.append("%-40s %9.3fs %9.3fs %9.3fs" % (k, mn, av, mx))
    return "\n".join(lines)


#: module-level default profiler, like the reference's global ``prof``
prof = Profiler()
