"""Utilities: parameter handling, IO, profiling, sample problems."""
