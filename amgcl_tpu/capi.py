"""Python side of the C API (see csrc/c_api.cpp, include/amgcl_tpu.h).

The reference exposes its runtime registry over a plain C ABI
(/root/reference/lib/amgcl.h:47-157, lib/amgcl.cpp) so Fortran/Delphi/C
callers can build and apply solvers. The TPU-native equivalent keeps the
same surface: the shared library embeds CPython, this module does the
numpy/ctypes marshalling, and the solvers are the ordinary runtime-registry
compositions running on JAX.

All array arguments arrive as raw addresses (integers) plus lengths; the
wrappers view them zero-copy with ``np.ctypeslib`` and hand scipy a CSR.
Handles held by the C side are plain Python objects kept alive in a table.
"""

from __future__ import annotations

import ctypes
import json

import numpy as np

# NOTE: the C surface is double* end-to-end, so the embedded interpreter
# must run with jax_enable_x64 — c_api.cpp sets it during amgcl_tpu_init,
# BEFORE any JAX program compiles. It is deliberately not set here: an
# in-process Python import of this module must not flip process-global JAX
# config behind the host application's back.

import itertools

_handles = {}
# itertools.count.__next__ is atomic under the GIL — C API entry points may
# run on any thread (each takes the GIL independently), so id allocation
# must not be a read-modify-write pair
_next_id = itertools.count(1)


def _register(obj) -> int:
    h = next(_next_id)
    _handles[h] = obj
    return h


def _view(addr, n, ctype):
    return np.ctypeslib.as_array((ctype * n).from_address(addr))


def params_create() -> int:
    return _register({})


def params_set(h: int, name: str, value) -> None:
    _handles[h][name] = value


def params_read_json(h: int, fname: str) -> None:
    with open(fname) as f:
        _handles[h].update(json.load(f))


def handle_destroy(h: int) -> None:
    obj = _handles.pop(h, None)
    if hasattr(obj, "close"):
        obj.close()            # serve handles own a worker thread


def _csr_from_addrs(n, ptr_addr, col_addr, val_addr, one_based):
    ptr = _view(ptr_addr, n + 1, ctypes.c_int32).astype(np.int64)
    nnz = int(ptr[-1]) - (1 if one_based else 0)
    col = _view(col_addr, nnz, ctypes.c_int32).astype(np.int32)
    val = _view(val_addr, nnz, ctypes.c_double).copy()
    if one_based:               # Fortran convention (amgcl_*_create_f)
        ptr = ptr - 1
        col = col - 1
    from amgcl_tpu.ops.csr import CSR
    return CSR(ptr, col, val, n)


def _params_for(h) -> dict:
    prm = dict(_handles.get(h, {}) if h else {})
    # the C surface is f64 end-to-end (double* in, double* out)
    prm.setdefault("precond.dtype", "float64")
    return prm


def solver_create(n, ptr_addr, col_addr, val_addr, prm_h,
                  one_based=False) -> int:
    from amgcl_tpu.models.runtime import make_solver_from_config
    A = _csr_from_addrs(n, ptr_addr, col_addr, val_addr, one_based)
    prm = _params_for(prm_h)
    prm.setdefault("solver.type", "bicgstab")
    block_size = int(prm.pop("block_size", 1))
    solver = make_solver_from_config(A, prm, block_size=block_size)
    return _register(solver)


def precond_create(n, ptr_addr, col_addr, val_addr, prm_h,
                   one_based=False) -> int:
    from amgcl_tpu.models.runtime import precond_from_config, _as_dict
    A = _csr_from_addrs(n, ptr_addr, col_addr, val_addr, one_based)
    cfg = _as_dict(_params_for(prm_h))
    return _register(_PrecondApply(precond_from_config(
        A, cfg.get("precond", {})), n))


class _PrecondApply:
    """One-shot M^-1 application with a jit-compiled hierarchy apply."""

    def __init__(self, precond, n):
        self.precond = precond
        self.n = n
        self._compiled = None

    def __call__(self, r):
        import jax.numpy as jnp
        if self._compiled is None:
            # observed jit (telemetry/compile_watch.py): C-API precond
            # applications are repeat-call entry points — their compiles
            # must not land in the <unwatched> bucket
            from amgcl_tpu.telemetry.compile_watch import watched_jit
            self._compiled = watched_jit(lambda hier, v: hier.apply(v),
                                         name="capi.precond_apply")
        dtype = getattr(self.precond, "dtype", jnp.float64)
        z = self._compiled(self.precond.hierarchy,
                           jnp.asarray(r, dtype=dtype))
        return np.asarray(z, dtype=np.float64)


def precond_apply(h, rhs_addr, x_addr, n) -> None:
    p = _handles[h]
    rhs = _view(rhs_addr, n, ctypes.c_double)
    x = _view(x_addr, n, ctypes.c_double)
    x[:] = p(np.asarray(rhs))


def solver_solve(h, rhs_addr, x_addr, n):
    """Returns (iters, resid); x_addr holds the initial guess on entry and
    the solution on exit (reference: amgcl_solver_solve)."""
    s = _handles[h]
    rhs = np.asarray(_view(rhs_addr, n, ctypes.c_double))
    x = _view(x_addr, n, ctypes.c_double)
    x0 = np.asarray(x).copy()
    got, info = s(rhs, x0=x0 if np.any(x0) else None)
    x[:] = np.asarray(got, dtype=np.float64)
    return int(info.iters), float(info.resid)


def solver_solve_batch(h, rhs_addr, x_addr, n, nrhs):
    """Stacked multi-RHS solve (serve/batched.py): ``rhs``/``x`` are
    ``nrhs`` contiguous length-``n`` vectors (C layout: vector-major).
    One compiled dispatch retires every right-hand side; per-request
    convergence is masked per column on device. ``x`` holds the initial
    guesses on entry (all-zero = cold start) and the solutions on exit.
    Returns (max_iters, max_resid) across the batch — the latency-SLO
    numbers; per-request detail is on the Python-side report."""
    s = _handles[h]
    rhs = np.asarray(_view(rhs_addr, n * nrhs, ctypes.c_double))
    x = _view(x_addr, n * nrhs, ctypes.c_double)
    rhs2 = rhs.reshape(nrhs, n).T                     # -> (n, B) columns
    x2 = np.asarray(x).reshape(nrhs, n).T
    got, info = s(rhs2, x0=x2 if np.any(x2) else None)
    x[:] = np.asarray(got, dtype=np.float64).T.ravel()
    return int(info.iters), float(info.resid)


def serve_create(solver_h, batch=0, metrics_port=-1) -> int:
    """Resident solve loop over an existing solver handle
    (serve/service.py): compiled once per (shape, B) bucket, iterate
    buffers donated, device sync at batch boundaries. Returns a service
    handle; destroy with ``handle_destroy`` (drains + stops the
    worker and the scrape server).

    ``metrics_port >= 0`` serves live Prometheus metrics + /healthz on
    that port while the service runs (0 = ephemeral — read the bound
    port from the ``metrics_port`` field of ``serve_stats``); -1 falls
    back to the AMGCL_TPU_SERVE_METRICS_PORT env knob; any other
    negative forces the scrape server OFF for this service even when
    the env knob is set. The SLO watchdog thresholds ride the
    AMGCL_TPU_SLO_* env knobs."""
    from amgcl_tpu.serve import SolverService
    s = _handles[solver_h]
    if hasattr(s, "inner"):            # make_block_solver wraps
        s = s.inner
    mp = int(metrics_port)
    # C convention: -1 = fall back to the env knob; any other negative
    # = force the scrape server OFF for this service (the service's
    # negative sentinel — the opt-out when the env knob is fleet-wide);
    # >= 0 = bind this port (0 = ephemeral)
    return _register(SolverService(
        s, batch=int(batch) or None,
        metrics_port=None if mp == -1 else mp).start())


def serve_solve(h, rhs_addr, x_addr, n, nrhs):
    """Push ``nrhs`` requests (layout as ``solver_solve_batch``) through
    the service queue and wait for all of them — the batching/flush
    behavior is the service's. Returns (max_iters, max_resid)."""
    svc = _handles[h]
    rhs = np.asarray(_view(rhs_addr, n * nrhs, ctypes.c_double))
    x = _view(x_addr, n * nrhs, ctypes.c_double).reshape(nrhs, n)
    futs = [svc.submit(rhs[k * n:(k + 1) * n], block=True)
            for k in range(nrhs)]
    worst_it, worst_res = 0, 0.0
    for k, fut in enumerate(futs):
        xk, rep = fut.result(timeout=svc.timeout_s + 120)
        x[k, :] = np.asarray(xk, np.float64)
        worst_it = max(worst_it, int(rep.iters))
        worst_res = max(worst_res, float(rep.resid))
    return worst_it, worst_res


def serve_stats(h) -> str:
    """JSON text of the service's lifetime stats: requests/batches,
    solves/sec, latency percentiles, plus the serving-observability
    fields (timeouts, unhealthy count, mean span breakdown ``spans_ms``,
    ``batch_fill`` occupancy, ``padding_waste``, the compile-cache join,
    SLO watchdog state, and ``metrics_port`` when the scrape server
    runs)."""
    return json.dumps(_handles[h].stats())


def farm_create(max_bytes=0, batch=0, metrics_port=-1) -> int:
    """Multi-tenant solver farm (serve/farm.py): N tenants with
    different operators multiplexed over one device — registry-cached
    hierarchies (same-sparsity re-registrations take the numeric
    rebuild path), LRU HBM admission/eviction under ``max_bytes``
    (0 = the AMGCL_TPU_FARM_MAX_BYTES knob, unset = unlimited),
    cross-tenant batch packing, per-tenant SLOs. ``metrics_port``
    follows the serve_create convention (-1 = the
    AMGCL_TPU_FARM_METRICS_PORT knob, other negatives = off). Destroy
    with ``handle_destroy`` (drains + stops the dispatch thread)."""
    from amgcl_tpu.serve.farm import SolverFarm
    mp = int(metrics_port)
    return _register(SolverFarm(
        max_bytes=int(max_bytes) or None, batch=int(batch) or None,
        metrics_port=None if mp == -1 else mp).start())


def farm_register(h, tenant: str, n, ptr_addr, col_addr, val_addr,
                  prm_h, one_based=False) -> str:
    """Register (or re-register) ``tenant`` with a CSR operator on farm
    handle ``h``. ``prm_h`` carries the usual dotted config
    (``solver.type``, ``precond.*``; f64 end-to-end like the other C
    entry points). Returns JSON text: {tenant, outcome, fingerprint,
    bytes, setup_s[, rebuild_s]} — ``outcome`` is the registry path
    taken (hit / rebuild / miss)."""
    from amgcl_tpu.models.runtime import (_as_dict,
                                          precond_params_from_dict,
                                          solver_from_params)
    A = _csr_from_addrs(n, ptr_addr, col_addr, val_addr, one_based)
    cfg = _as_dict(_params_for(prm_h))
    solver = solver_from_params(dict(cfg.get("solver") or {}))
    prm = precond_params_from_dict(dict(cfg.get("precond") or {}))
    return json.dumps(_handles[h].register(str(tenant), A,
                                           solver=solver, precond=prm))


def farm_solve(h, tenant: str, rhs_addr, x_addr, n, nrhs):
    """Push ``nrhs`` requests for ``tenant`` (layout as
    ``solver_solve_batch``: ``x`` holds the initial guesses on entry —
    all-zero = cold start — and the solutions on exit) through the
    farm's fair-share queue and wait for all of them — co-tenant
    requests for the same operator pack into shared (n, B) buckets.
    Returns (max_iters, max_resid)."""
    farm = _handles[h]
    rhs = np.asarray(_view(rhs_addr, n * nrhs, ctypes.c_double))
    x = _view(x_addr, n * nrhs, ctypes.c_double).reshape(nrhs, n)
    futs = []
    for k in range(nrhs):
        # copy the guess BEFORE any solution lands in the shared buffer
        x0 = np.array(x[k], copy=True)
        futs.append(farm.submit(str(tenant), rhs[k * n:(k + 1) * n],
                                x0=x0 if np.any(x0) else None,
                                block=True))
    worst_it, worst_res = 0, 0.0
    for k, fut in enumerate(futs):
        xk, rep = fut.result(timeout=farm.timeout_s + 120)
        x[k, :] = np.asarray(xk, np.float64)
        worst_it = max(worst_it, int(rep.iters))
        worst_res = max(worst_res, float(rep.resid))
    return worst_it, worst_res


def farm_evict(h, tenant: str) -> int:
    """Explicitly evict ``tenant``'s operator from the device (host CSR
    + plans stay; the next solve readmits via rebuild). Returns 1 when
    something was evicted, 0 when it was not resident."""
    return int(_handles[h].evict(str(tenant)))


def farm_stats(h) -> str:
    """JSON text of the farm's lifetime stats: per-tenant rows
    (requests, timeouts, unhealthy, SLO trips, latency percentiles,
    residency + bytes), the registry hit/miss/rebuild counters, the
    HBM pool state, and the eviction/readmission totals."""
    return json.dumps(_handles[h].stats())


def handle_n(h) -> int:
    """Scalar system size of the solver/preconditioner behind a handle."""
    obj = _handles[h]
    if isinstance(obj, _PrecondApply):
        return obj.n
    from amgcl_tpu.serve.service import SolverService
    if isinstance(obj, SolverService):
        return obj.n
    if hasattr(obj, "inner"):          # make_block_solver wraps make_solver
        obj = obj.inner
    A = obj.A_host
    return A.nrows * A.block_size[0]


def report(h) -> str:
    return repr(_handles[h])
