"""Live metrics registry + scrape endpoint — the while-it-runs leg of
the serving observability stack.

Everything before this module reports AFTER the fact: ``SolveReport``
describes a finished solve, ``SolverService.stats()`` summarizes a
finished window, ``metrics.rollup_events`` aggregates a closed JSONL
file. A resident service under live traffic needs the numbers WHILE it
runs — queue depth, in-flight requests, batch occupancy, compile-cache
behavior, latency percentiles — scrapeable by Prometheus without
touching the worker thread. This module provides:

* :data:`METRICS` — THE declared metric-name table. Every live metric
  the registry accepts is a row here; the ``metric-name-literal`` lint
  rule (analysis/lint.py) statically asserts every ``inc``/
  ``set_gauge``/``observe`` call site under ``amgcl_tpu/`` uses a string
  literal from this table, and the registry enforces the same contract
  at runtime (unknown names raise). One table, no ad-hoc strings.
* :class:`LiveRegistry` — thread-safe counters (monotonic, optional
  labels), gauges (last-value), and bounded histograms (a deque of the
  last N observations, summarized with the same interpolated
  percentiles the fleet rollups use). All updates are a dict write
  under one lock — cheap enough for the serve worker's per-batch path.
* :class:`MetricsServer` — a daemon ``http.server`` thread serving
  ``/metrics`` (Prometheus exposition text, reusing
  :func:`metrics.prometheus_text` for the histogram summaries) and
  ``/healthz`` (JSON liveness). Bound to 127.0.0.1; port 0 binds an
  ephemeral port (the bound port is on ``.port``).

Enabled for the serving path by ``AMGCL_TPU_SERVE_METRICS_PORT`` or
``cli.py --serve --metrics-port`` (serve/service.py wires it).

The module body is stdlib + the sibling ``metrics.py`` only (jax never
appears here, and a file-path load falls back to loading metrics.py by
file path too, the sink.py discipline) — the scrape path must stay
responsive while the worker thread holds the device.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

try:
    from amgcl_tpu.telemetry import metrics as _metrics
except ImportError:          # loaded by file path (sink.py discipline):
    import importlib.util as _ilu    # pull the sibling the same way
    _spec = _ilu.spec_from_file_location(
        "_amgcl_tpu_metrics", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "metrics.py"))
    _metrics = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_metrics)

#: THE declared metric table: name -> (kind, help). ``kind`` is one of
#: "counter" | "gauge" | "histogram". The lint rule and the runtime
#: registry both validate against exactly this dict — adding a metric
#: means adding a row here first.
METRICS: Dict[str, Tuple[str, str]] = {
    "serve_queue_depth": (
        "gauge", "requests waiting in the serve queue right now"),
    "serve_inflight": (
        "gauge", "requests inside the current device batch"),
    "serve_requests_total": (
        "counter", "requests completed by the service"),
    "serve_batches_total": (
        "counter", "device batches dispatched"),
    "serve_timeouts_total": (
        "counter", "requests expired in the queue before dispatch"),
    "serve_unhealthy_total": (
        "counter", "requests whose health guards tripped or whose "
                   "batch dispatch raised"),
    "serve_health_flags_total": (
        "counter", "guard-flag trips by flag name (label: flag)"),
    "serve_padded_slots_total": (
        "counter", "zero-padded bucket columns dispatched (wasted)"),
    "serve_bucket_solves_total": (
        "counter", "requests retired by bucket size (label: bucket)"),
    "serve_slo_trips_total": (
        "counter", "SLO watchdog threshold trips"),
    "serve_batch_fill": (
        "histogram", "live columns / padded bucket B per batch"),
    "serve_latency_ms": (
        "histogram", "end-to-end per-request latency (submit->result)"),
    "serve_queue_ms": (
        "histogram", "per-request queue wait before batch assembly"),
    "serve_solve_ms": (
        "histogram", "per-batch device solve wall (compile excluded)"),
    "serve_compile_traces": (
        "gauge", "compile-watch traces of serve.solve_step"),
    "serve_compile_cache_hits": (
        "gauge", "compile-watch cache hits of serve.solve_step"),
    "serve_compile_s": (
        "gauge", "cumulative XLA compile seconds of serve.solve_step"),
    "flight_dumps_total": (
        "counter", "flight-recorder replay bundles written on incident "
                   "triggers (telemetry/flight.py)"),
    "dist_mesh_devices": (
        "gauge", "devices in the distributed solve mesh"),
    "dist_comm_fraction": (
        "gauge", "measured collective wall fraction of one distributed "
                 "iteration (telemetry/comm.py ablation)"),
    # -- multi-tenant solver farm (serve/farm.py) -------------------------
    "farm_tenants": (
        "gauge", "tenants registered with the farm"),
    "farm_resident_operators": (
        "gauge", "operator hierarchies currently device-resident"),
    "farm_hbm_used_bytes": (
        "gauge", "bytes charged against the farm HBM pool"),
    "farm_hbm_total_bytes": (
        "gauge", "farm HBM pool budget (0 = unlimited)"),
    "farm_batches_total": (
        "counter", "cross-tenant device batches dispatched by the farm"),
    "farm_evictions_total": (
        "counter", "resident hierarchies evicted under HBM pressure"),
    "farm_readmissions_total": (
        "counter", "evicted hierarchies readmitted via rebuild()"),
    "farm_registry_hits_total": (
        "counter", "operator-registry fingerprint hits (shared as-is)"),
    "farm_registry_misses_total": (
        "counter", "operator-registry misses (fresh hierarchy setup)"),
    "farm_registry_rebuilds_total": (
        "counter", "operator-registry numeric rebuilds (same sparsity, "
                   "new values, or readmission after eviction)"),
    "farm_latency_ms": (
        "histogram", "end-to-end per-request latency across all tenants"),
    "farm_tenant_requests_total": (
        "counter", "requests completed per tenant (label: tenant)"),
    "farm_tenant_timeouts_total": (
        "counter", "queue-expired requests per tenant (label: tenant)"),
    "farm_tenant_unhealthy_total": (
        "counter", "unhealthy/errored solves per tenant (label: tenant)"),
    "farm_tenant_slo_trips_total": (
        "counter", "per-tenant SLO watchdog trips (label: tenant)"),
    "farm_tenant_queue_depth": (
        "gauge", "requests waiting per tenant queue (label: tenant)"),
    "farm_tenant_resident": (
        "gauge", "1 when the tenant's hierarchy is device-resident "
                 "(label: tenant)"),
    "farm_tenant_bytes": (
        "gauge", "ledger bytes of the tenant's hierarchy (label: tenant)"),
    "farm_tenant_p99_ms": (
        "gauge", "rolling-window p99 latency per tenant (label: tenant)"),
    # -- fault injection + recovery (amgcl_tpu/faults/) -------------------
    "faults_injected_total": (
        "counter", "deterministic faults fired at serving-layer seams "
                   "(faults/inject.py; label: site)"),
    "recovery_retries_total": (
        "counter", "recovery retries scheduled (request re-dispatch "
                   "with backoff, farm admission backoff)"),
    "recoveries_total": (
        "counter", "retried requests that subsequently succeeded"),
    "recovery_checkpoint_age_s": (
        "gauge", "seconds since the newest host-side Krylov-iterate "
                 "checkpoint (AMGCL_TPU_CKPT_EVERY)"),
    "serve_worker_deaths_total": (
        "counter", "dispatch-worker threads that died on an unexpected "
                   "exception (futures failed, never stranded)"),
    "serve_worker_restarts_total": (
        "counter", "dispatch workers restarted by the supervisor"),
    "farm_load_shed_total": (
        "counter", "load-shedding episodes per tenant under sustained "
                   "SLO breach (label: tenant)"),
    # -- runtime lock witness (analysis/lockwitness.py) -------------------
    "lock_witness_edges": (
        "gauge", "distinct witnessed lock-acquisition-order edges "
                 "(AMGCL_TPU_LOCK_WITNESS=1; must stay a subset of "
                 "the static lock graph)"),
    "lock_witness_max_hold_ms": (
        "gauge", "longest witnessed lock hold in milliseconds "
                 "(condition waits excluded)"),
    "lock_witness_watchdog_trips": (
        "gauge", "starvation-watchdog trips: blocking acquires that "
                 "exceeded AMGCL_TPU_LOCK_WITNESS_TIMEOUT_S (zero is "
                 "the chaos-matrix acceptance bar)"),
    # -- open-loop storm harness (serve/storm.py) -------------------------
    "storm_offered_rps": (
        "gauge", "offered arrival rate of the storm rung currently "
                 "driving this target (open-loop schedule)"),
    "storm_submitted_total": (
        "counter", "storm requests submitted (every scheduled arrival, "
                   "whether accepted or shed)"),
    "storm_shed_total": (
        "counter", "storm requests rejected at submit (queue.Full / "
                   "load shed) — excluded from goodput"),
    "storm_sched_lag_ms": (
        "histogram", "generator lag: actual submit minus scheduled "
                     "arrival (a loaded generator under-drives the "
                     "target; large lag invalidates the open-loop "
                     "contract)"),
    # -- operator X-ray (telemetry/structure.py) --------------------------
    "xray_padding_waste_frac": (
        "gauge", "finest-level ELL lane-padding waste fraction from "
                 "the operator X-ray (stored-but-zero slots / stored)"),
    "xray_predicted_reorder_gain": (
        "gauge", "reorder-gain advisor's best predicted SpMV-byte "
                 "gain across hierarchy levels (1.0 = no gain)"),
    "xray_dia_fill": (
        "gauge", "finest-level DIA fill ratio (stored slots / nnz) "
                 "from the operator X-ray"),
    # -- memory observatory (telemetry/memwatch.py) -----------------------
    "memwatch_bytes_in_use": (
        "gauge", "measured device bytes in use at the last memwatch "
                 "sample (allocator stats, or the live-array census "
                 "on backends without memory_stats)"),
    "memwatch_peak_bytes_in_use": (
        "gauge", "measured peak device bytes (allocator peak, or the "
                 "census high-water mark)"),
    "memwatch_owner_bytes": (
        "gauge", "measured live-buffer bytes attributed to one "
                 "registered owner (label: owner)"),
    "memwatch_unattributed_bytes": (
        "gauge", "census remainder belonging to no registered owner "
                 "(workspaces, donated buffers, foreign arrays)"),
    "memwatch_drift_total": (
        "counter", "measured-vs-model divergences surfaced as "
                   "mem_drift events (bytes-hint sweeps, measured-"
                   "headroom admission cross-checks)"),
}

#: THE declared label-key table: metric name -> allowed label keys.
#: A labeled update whose metric is not a row here (or whose label key
#: is not listed) raises at runtime, and the ``metric-name-literal``
#: lint rule rejects the call site statically — same two-sided contract
#: as :data:`METRICS` itself. Label VALUES stay free-form (tenant names
#: arrive at runtime); only the keys are declared.
METRIC_LABELS: Dict[str, Tuple[str, ...]] = {
    "serve_health_flags_total": ("flag",),
    "serve_bucket_solves_total": ("bucket",),
    "farm_tenant_requests_total": ("tenant",),
    "farm_tenant_timeouts_total": ("tenant",),
    "farm_tenant_unhealthy_total": ("tenant",),
    "farm_tenant_slo_trips_total": ("tenant",),
    "farm_tenant_queue_depth": ("tenant",),
    "farm_tenant_resident": ("tenant",),
    "farm_tenant_bytes": ("tenant",),
    "farm_tenant_p99_ms": ("tenant",),
    "faults_injected_total": ("site",),
    "farm_load_shed_total": ("tenant",),
    "memwatch_owner_bytes": ("owner",),
}

# the ONE name-mangling rule, shared with the rollup exposition so the
# two halves of a /metrics payload can never disagree on base names
_prom_name = _metrics.prom_name


def _prom_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, str(v).replace('"', "'"))
                             for k, v in labels)


class LiveRegistry:
    """Thread-safe in-process metrics, validated against a declared
    table (:data:`METRICS` by default — an unknown name raises KeyError,
    a kind mismatch raises TypeError; the same contract the
    ``metric-name-literal`` lint rule enforces statically)."""

    def __init__(self, spec: Optional[Dict[str, Tuple[str, str]]] = None,
                 hist_cap: int = 2048,
                 labels_spec: Optional[Dict[str, Tuple[str, ...]]] = None):
        self.spec = dict(METRICS if spec is None else spec)
        self.labels_spec = dict(METRIC_LABELS if labels_spec is None
                                else labels_spec)
        self.hist_cap = int(hist_cap)
        self._lock = threading.Lock()
        # runtime lock witness seam (identity when the knob is
        # off); the registry lock is a LEAF of the static graph —
        # holding it must acquire nothing else
        try:
            from amgcl_tpu.analysis import lockwitness as _lw
            _lw.maybe_instrument(self, "live")
        except ImportError:       # file-path load (sink.py
            pass                  # discipline): coverage skipped
        #: (name, labels-tuple) -> float, labels sorted for identity
        self._counters: Dict[Tuple[str, Tuple], float] = {}
        #: (name, labels-tuple) -> float — unlabeled gauges key on
        #: (name, ()), so the pre-farm callers see unchanged behavior
        self._gauges: Dict[Tuple[str, Tuple], float] = {}
        self._hists: Dict[str, deque] = {}

    def _check(self, name: str, kind: str, labels=()) -> None:
        row = self.spec.get(name)
        if row is None:
            raise KeyError(
                "undeclared live metric %r — add it to telemetry/live.py"
                " METRICS (the metric-name-literal rule enforces the "
                "same table statically)" % name)
        if row[0] != kind:
            raise TypeError("metric %r is declared %r, not %r"
                            % (name, row[0], kind))
        if labels:
            allowed = self.labels_spec.get(name, ())
            for k in labels:
                if k not in allowed:
                    raise KeyError(
                        "label %r is not declared for metric %r — add "
                        "it to telemetry/live.py METRIC_LABELS (the "
                        "metric-name-literal rule enforces the same "
                        "table statically)" % (k, name))

    # -- updates (the worker's hot path: one lock, one dict write) ----------

    def inc(self, name: str, by: float = 1, **labels) -> None:
        self._check(name, "counter", labels)
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + by

    def set_gauge(self, name: str, value: float, **labels) -> None:
        self._check(name, "gauge", labels)
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = float(value)

    def observe(self, name: str, value: float) -> None:
        self._check(name, "histogram")
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = deque(maxlen=self.hist_cap)
            h.append(float(value))

    # -- reads ---------------------------------------------------------------

    def get(self, name: str, **labels) -> Optional[float]:
        """Current value: counter or gauge (with exact labels); the last
        observation for a histogram. None when never touched."""
        kind = self.spec.get(name, (None,))[0]
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            if kind == "counter":
                return self._counters.get(key)
            if kind == "gauge":
                return self._gauges.get(key)
            if kind == "histogram":
                h = self._hists.get(name)
                return h[-1] if h else None
        return None

    def snapshot(self) -> Dict[str, Any]:
        """JSON-clean copy: counters and gauges (labels flattened into
        the key), and histogram rollups ({count, min, p50, p90, p99,
        max, mean, last} via the fleet percentile helpers). Each rollup
        carries ``window``: the deque capacity — histogram percentiles
        cover AT MOST the last ``window`` observations; under sustained
        load older samples have been dropped, so a lifetime p99 is not
        recoverable from this surface (by design: bounded memory)."""
        with self._lock:
            counters = {name + _prom_labels(labels): v
                        for (name, labels), v in self._counters.items()}
            gauges = {name + _prom_labels(labels): v
                      for (name, labels), v in self._gauges.items()}
            hists = {name: list(h) for name, h in self._hists.items()}
        return {"counters": counters, "gauges": gauges,
                "histograms": {name: dict(_metrics.rollup(vals),
                                          window=self.hist_cap)
                               for name, vals in hists.items()
                               if vals}}

    def prometheus(self, prefix: str = "amgcl_tpu") -> str:
        """Prometheus exposition text of everything live: counters and
        gauges as typed scalar lines, histograms as the summary-style
        quantile gauges :func:`metrics.prometheus_text` renders."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = {name: list(h) for name, h in self._hists.items()}
        lines = []
        seen_type = set()
        for (name, labels), v in counters:
            metric = _prom_name(prefix, name)
            if metric not in seen_type:
                seen_type.add(metric)
                lines.append("# HELP %s %s"
                             % (metric, self.spec[name][1]))
                lines.append("# TYPE %s counter" % metric)
            lines.append("%s%s %s" % (metric, _prom_labels(labels), v))
        for (name, labels), v in gauges:
            metric = _prom_name(prefix, name)
            if metric not in seen_type:
                seen_type.add(metric)
                lines.append("# HELP %s %s"
                             % (metric, self.spec[name][1]))
                lines.append("# TYPE %s gauge" % metric)
            lines.append("%s%s %s" % (metric, _prom_labels(labels), v))
        rollups = {name: r for name, r in
                   ((name, _metrics.rollup(vals))
                    for name, vals in sorted(hists.items()))
                   if r is not None}
        for name in rollups:
            # histogram HELP carries the WINDOW: the backing deque keeps
            # only the last hist_cap observations, so the quantile
            # gauges below are rolling-window, not lifetime
            lines.append(
                "# HELP %s %s (rolling window: last %d observations)"
                % (_prom_name(prefix, name),
                   self.spec.get(name, ("", "histogram"))[1],
                   self.hist_cap))
        text = "\n".join(lines) + ("\n" if lines else "")
        if rollups:
            text += _metrics.prometheus_text(rollups, prefix=prefix)
        return text


def publish_dist_gauges(registry: "LiveRegistry",
                        devices: Optional[int] = None,
                        comm_fraction: Optional[float] = None) -> None:
    """Publish the distributed-solve gauges onto a live registry so a
    served distributed solver exposes them on ``/metrics``: the mesh
    size and the measured comm fraction of one iteration
    (``telemetry.comm.comm_attribution()['per_iteration']
    ['comm_fraction']``). Names are literals from :data:`METRICS` —
    the metric-name-literal contract (this module is the declaring
    site)."""
    if devices is not None:
        registry.set_gauge("dist_mesh_devices", float(devices))
    if comm_fraction is not None:
        registry.set_gauge("dist_comm_fraction", float(comm_fraction))


def publish_xray_gauges(registry: "LiveRegistry",
                        summary: Optional[Dict[str, Any]]) -> None:
    """Publish the operator X-ray gauges from a
    ``telemetry.structure.xray_summary`` dict onto a live registry
    (``cli --xray`` onto the serve registry / a dedicated scrape
    server). Names are literals from :data:`METRICS` — the
    metric-name-literal contract (this module is the declaring
    site). Missing summary fields publish nothing."""
    if not summary:
        return
    v = summary.get("padding_waste_frac")
    if v is not None:
        registry.set_gauge("xray_padding_waste_frac", float(v))
    v = summary.get("predicted_reorder_gain")
    if v is not None:
        registry.set_gauge("xray_predicted_reorder_gain", float(v))
    v = summary.get("dia_fill")
    if v is not None:
        registry.set_gauge("xray_dia_fill", float(v))


def publish_memwatch_gauges(registry: "LiveRegistry",
                            sample: Optional[Dict[str, Any]] = None,
                            owners: Optional[List[Dict[str, Any]]]
                            = None) -> None:
    """Publish the memory-observatory gauges onto a live registry:
    the measured device sample (``memwatch.device_sample()`` — taken
    here when not passed) and the per-owner attribution table
    (``memwatch.owner_table()``). Names are literals from
    :data:`METRICS` — the metric-name-literal contract (this module is
    the declaring site)."""
    from amgcl_tpu.telemetry import memwatch as _mw
    if not _mw.enabled():
        return
    if sample is None:
        sample = _mw.device_sample()
    v = sample.get("bytes_in_use")
    if v is not None:
        registry.set_gauge("memwatch_bytes_in_use", float(v))
    v = sample.get("peak_bytes_in_use")
    if v is not None:
        registry.set_gauge("memwatch_peak_bytes_in_use", float(v))
    if owners is None:
        owners = _mw.owner_table(sample)
    for row in owners or []:
        b = row.get("bytes_measured")
        if b is None:
            continue
        if row.get("owner") == "unattributed":
            registry.set_gauge("memwatch_unattributed_bytes", float(b))
        else:
            registry.set_gauge("memwatch_owner_bytes", float(b),
                               owner=row["owner"])


def metrics_port_from_env(
        var: str = "AMGCL_TPU_SERVE_METRICS_PORT") -> Optional[int]:
    """Scrape-port knob convention, shared by the serve and farm
    surfaces (``var`` selects the knob): unset/empty/unparseable = no
    scrape server; an integer (0 = ephemeral port) enables it."""
    raw = os.environ.get(var)
    if raw is None or raw == "":
        return None
    try:
        return int(raw)
    except ValueError:
        return None


class MetricsServer:
    """Daemon HTTP thread serving ``/metrics`` (Prometheus text) and
    ``/healthz`` (JSON) on 127.0.0.1. ``metrics_cb`` returns the
    exposition text, ``health_cb`` a JSON-able dict; both run on the
    scrape thread, so they must not block on the device (the registry's
    lock-and-copy reads never do). Port 0 binds an ephemeral port —
    read the real one from ``.port``."""

    def __init__(self, port: int,
                 metrics_cb: Callable[[], str],
                 health_cb: Optional[Callable[[], Dict[str, Any]]] = None,
                 host: str = "127.0.0.1"):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = server.metrics_cb().encode()
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    elif self.path.split("?")[0] == "/healthz":
                        payload = (server.health_cb()
                                   if server.health_cb else {"ok": True})
                        body = json.dumps(payload).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:   # noqa: BLE001 — a scrape must
                    self.send_error(500, repr(e)[:120])   # never crash
                    return                                # the server
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):    # scrapes are not log lines
                pass

        self.metrics_cb = metrics_cb
        self.health_cb = health_cb
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="amgcl-tpu-metrics")
        self._thread.start()

    @property
    def url(self) -> str:
        return "http://%s:%d/metrics" % (self.host, self.port)

    def close(self, timeout: float = 5.0) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout)
