"""Fleet metric rollups — percentile aggregation of telemetry events and
bench history, with Prometheus-text export.

One solve's report answers "what happened"; a fleet serving millions of
solves needs "what usually happens": p50/p90/p99 of iterations, solve
time, achieved bandwidth and compile time across a JSONL sink file or
across the committed ``BENCH_r*.json`` round history. This module is the
aggregation layer ``bench.py --trend`` and any scrape endpoint render
from.

IMPORTANT: stdlib-only AND free of package-relative imports, for the same
reason as ``telemetry/sink.py`` — ``bench.py``'s supervisor (which must
never import jax) loads it directly by file path with importlib. Keep it
that way.

Pieces:

* :func:`percentile` / :func:`rollup` — interpolated percentiles and the
  standard summary ({count, min, p50, p90, p99, max, mean, last}).
* :func:`extract` — dotted-path field lookup into nested records
  ("ledger.cycle_bytes", "compile.totals.compile_s"), None when absent —
  pre-ledger / pre-health / pre-roofline records degrade to gaps, never
  errors.
* :func:`bench_history` — the committed ``BENCH_r*.json`` rounds (each a
  driver record with the worker line under ``"parsed"``), sorted by
  round.
* :func:`trend` / :func:`format_trend` — the cross-PR trajectory table of
  the headline fields, one row per round.
* :func:`rollup_events` — percentile rollups over JSONL sink records
  grouped by event type.
* :func:`prometheus_text` — Prometheus exposition format (summary-style
  gauges with ``quantile`` labels) for scraping.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
from typing import Any, Dict, Iterable, List, Optional

#: headline trend fields: (column, dotted path into the parsed record)
TREND_FIELDS = [
    ("solve_s", "value"),
    ("vs_baseline", "vs_baseline"),
    ("iters", "iters"),
    ("setup_s", "setup_s"),
    ("gen_s", "gen_s"),
    ("achieved_gbps", "achieved_gbps"),
    ("hbm_frac", "hbm_frac"),
    ("ledger_bytes", "ledger.hierarchy_bytes"),
    ("compile_s", "compile.totals.compile_s"),
    ("roofline_frac", "roofline.frac_hbm_peak"),
]

#: multichip trend fields (the structured ``MULTICHIP_r*.json`` schema
#: emitted by ``bench.py --scaling``; legacy dryrun-log rounds degrade
#: to device-count-only rows with gaps)
MULTICHIP_TREND_FIELDS = [
    ("devices", "headline.devices"),
    ("weak_eff", "headline.weak_efficiency"),
    ("strong_eff", "headline.strong_efficiency"),
    ("comm_frac", "headline.comm_fraction"),
    ("imbalance", "headline.imbalance"),
    ("wire_gbps", "headline.wire_gbps"),
    ("iters", "headline.iters"),
]

#: storm trend fields (the structured ``STORM_r*.json`` schema emitted
#: by ``bench.py --storm`` — the open-loop saturation record)
STORM_TREND_FIELDS = [
    ("max_rps", "record.knee.max_sustainable_rps"),
    ("knee_rps", "record.knee.knee_offered_rps"),
    ("ref_p99_ms", "record.reference.p99_ms"),
    ("ref_rps", "record.reference.offered_rps"),
    ("good_frac", "record.goodput.good_frac"),
    ("requests", "record.goodput.requests"),
]

#: sink-event rollup spec: {event: [(metric, dotted path)]}
EVENT_FIELDS = {
    "solve": [("iters", "iters"), ("solve_time_s", "wall_time_s"),
              ("resid", "resid"),
              ("convergence_rate", "convergence_rate"),
              ("achieved_gbps", "resources.roofline.gbps"),
              ("compile_s", "compile.new_compile_s")],
    "bench": [("solve_time_s", "value"), ("iters", "iters"),
              ("achieved_gbps", "achieved_gbps")],
    "bench_worker": [("solve_time_s", "value"), ("iters", "iters"),
                     ("achieved_gbps", "achieved_gbps")],
    # serving path (serve/service.py): per-batch dispatch records and
    # the per-request span events — latency/occupancy trends scrape
    # from the same sink files as everything else
    "serve": [("requests", "requests"), ("wall_s", "wall_s"),
              ("solves_per_sec", "solves_per_sec"),
              ("batch_fill", "batch_fill"),
              ("iters_max", "iters_max")],
    "serve_request": [("latency_ms", "latency_ms"),
                      ("queue_ms", "queue_ms"),
                      ("solve_ms", "solve_ms"), ("iters", "iters")],
    # operator X-ray (telemetry/structure.py): the per-hierarchy
    # 'structure' event (cli --xray / AMG.structure_report) and the
    # bench --xray predicted-vs-measured reorder-gain join — declared
    # here so rollup_events / --trend aggregate the new event kinds
    # instead of silently skipping them
    "structure": [("padding_waste_frac", "summary.padding_waste_frac"),
                  ("predicted_reorder_gain",
                   "summary.predicted_reorder_gain"),
                  ("dia_fill", "summary.dia_fill"),
                  ("window_fill", "summary.window_fill"),
                  ("bandwidth_max", "summary.bandwidth_max")],
    "bench_xray": [("predicted_gain", "join.predicted_gain"),
                   ("measured_gain", "join.measured_gain"),
                   ("gain_ratio", "join.ratio")],
    # open-loop storm harness (serve/storm.py + bench.py --storm): the
    # per-storm traffic record and the assembled saturation record —
    # declared here so rollup_events / --trend aggregate them
    "storm": [("offered_rps", "offered_rps"),
              ("achieved_rps", "achieved_rps"),
              ("goodput_rps", "goodput_rps"),
              ("p99_ms", "p99_ms"),
              ("shed_rate", "shed_rate"),
              ("timeout_rate", "timeout_rate")],
    "bench_storm": [("max_sustainable_rps",
                     "record.knee.max_sustainable_rps"),
                    ("knee_offered_rps", "record.knee.knee_offered_rps"),
                    ("ref_p99_ms", "record.reference.p99_ms"),
                    ("good_frac", "record.goodput.good_frac")],
    # runtime lock witness (analysis/lockwitness.py): the per-run
    # witnessed-edge / hold-time / watchdog record the chaos matrix
    # emits under AMGCL_TPU_LOCK_WITNESS=1 — declared here so
    # rollup_events / --trend aggregate it instead of skipping it
    "lock_witness": [("witness_edges", "edges_total"),
                     ("witness_max_hold_ms", "max_hold_ms"),
                     ("witness_watchdog_trips", "watchdog_trips")],
}


def percentile(values: List[float], p: float) -> Optional[float]:
    """Linear-interpolated percentile of an (unsorted) list; None when
    empty."""
    vals = sorted(v for v in values if v is not None
                  and isinstance(v, (int, float)) and math.isfinite(v))
    if not vals:
        return None
    if len(vals) == 1:
        return float(vals[0])
    k = (len(vals) - 1) * (p / 100.0)
    lo = int(math.floor(k))
    hi = min(lo + 1, len(vals) - 1)
    return float(vals[lo] + (vals[hi] - vals[lo]) * (k - lo))


def rollup(values: Iterable[Any]) -> Optional[Dict[str, Any]]:
    """{count, min, p50, p90, p99, max, mean, last} of the finite
    numeric values; None when nothing numeric survives."""
    vals = [float(v) for v in values
            if isinstance(v, (int, float)) and not isinstance(v, bool)
            and math.isfinite(v)]
    if not vals:
        return None
    return {
        "count": len(vals),
        "min": min(vals),
        "p50": round(percentile(vals, 50), 6),
        "p90": round(percentile(vals, 90), 6),
        "p99": round(percentile(vals, 99), 6),
        "max": max(vals),
        "mean": round(sum(vals) / len(vals), 6),
        "last": vals[-1],
    }


def extract(record: Any, path: str) -> Any:
    """Dotted-path lookup ('a.b.c') into nested dicts; None on any
    missing hop — tolerant of records predating a field."""
    cur = record
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def iter_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL file, skipping unparseable lines (a torn tail from a
    crashed writer must not kill the rollup). Reads the rotated sibling
    ``path.1`` first when present, so a rotation mid-window keeps the
    full history."""
    out: List[Dict[str, Any]] = []
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    return out


_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def bench_history(repo: str) -> List[Dict[str, Any]]:
    """The committed per-round bench records, sorted by round number.
    Each returned dict: {"round": int, "path": str, **parsed-worker
    -record} — the driver wrapper's ``"parsed"`` payload is flattened
    (older rounds whose worker never produced a value keep whatever
    fields exist, e.g. only ``error``)."""
    rows = []
    for path in glob.glob(os.path.join(repo, "BENCH_r*.json")):
        m = _ROUND_RE.search(path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed") if isinstance(rec, dict) else None
        row = dict(parsed) if isinstance(parsed, dict) else {}
        row["round"] = int(m.group(1))
        row["path"] = os.path.basename(path)
        rows.append(row)
    rows.sort(key=lambda r: r["round"])
    return rows


_MC_ROUND_RE = re.compile(r"MULTICHIP_r(\d+)\.json$")


def multichip_history(repo: str) -> List[Dict[str, Any]]:
    """The committed per-round multichip records, sorted by round.
    Structured records (``bench.py --scaling``, ``schema`` >= 2) are
    returned whole; legacy rounds (pass/fail dryrun logs with an
    ``n_devices`` + ``tail``) normalize to ``legacy_dryrun`` rows whose
    only trend column is the device count — gaps, never errors, the
    ``bench_history`` discipline."""
    rows = []
    for path in glob.glob(os.path.join(repo, "MULTICHIP_r*.json")):
        m = _MC_ROUND_RE.search(path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict):
            continue
        if rec.get("schema"):
            row = dict(rec)
        else:
            # the one number a dryrun log carries is the mesh size it
            # ran on — surface it under the same headline key the
            # structured records use so the trend column joins
            row = {"legacy_dryrun": True, "ok": rec.get("ok"),
                   "headline": {"devices": rec.get("n_devices")}}
        row["round"] = int(m.group(1))
        row["path"] = os.path.basename(path)
        rows.append(row)
    rows.sort(key=lambda r: r["round"])
    return rows


_STORM_ROUND_RE = re.compile(r"STORM_r(\d+)\.json$")


def storm_history(repo: str) -> List[Dict[str, Any]]:
    """The committed per-round storm records, sorted by round — same
    shape discipline as :func:`multichip_history` (records are always
    structured; there is no legacy storm format)."""
    rows = []
    for path in glob.glob(os.path.join(repo, "STORM_r*.json")):
        m = _STORM_ROUND_RE.search(path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict):
            continue
        row = dict(rec)
        row["round"] = int(m.group(1))
        row["path"] = os.path.basename(path)
        rows.append(row)
    rows.sort(key=lambda r: r["round"])
    return rows


def trend(history: List[Dict[str, Any]],
          fields=None) -> List[Dict[str, Any]]:
    """One row per round with the headline fields extracted (None for
    fields that round predates) — the cross-PR trajectory."""
    fields = fields or TREND_FIELDS
    out = []
    for rec in history:
        row: Dict[str, Any] = {"round": rec.get("round")}
        for col, path in fields:
            v = extract(rec, path)
            row[col] = v if isinstance(v, (int, float)) \
                and not isinstance(v, bool) else None
        if rec.get("device_platform"):
            row["device"] = rec["device_platform"]
        if rec.get("error") and row.get("solve_s") is None:
            row["error"] = str(rec["error"])[:60]
        out.append(row)
    return out


def format_trend(rows: List[Dict[str, Any]], fields=None) -> str:
    """Text table of :func:`trend` rows; '-' for gaps."""
    fields = fields or TREND_FIELDS
    cols = ["round"] + [c for c, _ in fields] + ["device"]
    widths = {c: max(len(c), 9) for c in cols}

    def fmt(v):
        if v is None:
            return "-"
        if isinstance(v, float):
            return "%.4g" % v
        return str(v)

    lines = ["  ".join(c.rjust(widths[c]) for c in cols),
             "  ".join("-" * widths[c] for c in cols)]
    for r in rows:
        lines.append("  ".join(fmt(r.get(c)).rjust(widths[c])
                               for c in cols))
        if r.get("error"):
            lines.append("  (r%s: %s)" % (r.get("round"), r["error"]))
    return "\n".join(lines)


def trend_rollups(rows: List[Dict[str, Any]],
                  fields=None) -> Dict[str, Dict[str, Any]]:
    """Percentile rollups per trend column across rounds."""
    fields = fields or TREND_FIELDS
    out = {}
    for col, _ in fields:
        r = rollup(row.get(col) for row in rows)
        if r is not None:
            out[col] = r
    return out


def rollup_events(records: List[Dict[str, Any]],
                  spec=None) -> Dict[str, Dict[str, Any]]:
    """Rollups over sink records grouped by ``event`` type:
    {"solve.iters": {...}, "solve.solve_time_s": {...}, ...} per the
    spec (default :data:`EVENT_FIELDS`)."""
    spec = spec or EVENT_FIELDS
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        ev = rec.get("event")
        # final=True marks a lifetime-summary row (e.g. the serve
        # close() event) whose fields aggregate the whole run — mixing
        # it with the per-sample rows would skew every rollup
        if ev in spec and not rec.get("final"):
            groups.setdefault(ev, []).append(rec)
    out = {}
    for ev, recs in groups.items():
        for metric, path in spec[ev]:
            r = rollup(extract(rec, path) for rec in recs)
            if r is not None:
                out["%s.%s" % (ev, metric)] = r
    return out


def prom_name(prefix: str, name: str) -> str:
    """THE Prometheus metric-name mangling rule — prefix join +
    sanitize to [a-zA-Z0-9_]. One implementation shared by the rollup
    exposition below and the live registry (telemetry/live.py), so the
    two halves of one /metrics payload can never disagree on names."""
    return "%s_%s" % (prefix, re.sub(r"[^a-zA-Z0-9_]", "_", name))


def prometheus_text(rollups: Dict[str, Dict[str, Any]],
                    prefix: str = "amgcl_tpu") -> str:
    """Prometheus exposition format of a rollup table: summary-style
    gauges with ``quantile`` labels plus ``_count``/``_min``/``_max``.
    Metric names are sanitized to [a-zA-Z0-9_]."""
    lines = []
    for name in sorted(rollups):
        r = rollups[name]
        metric = prom_name(prefix, name)
        lines.append("# TYPE %s summary" % metric)
        for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
            if r.get(key) is not None:
                lines.append('%s{quantile="%s"} %s' % (metric, q, r[key]))
        lines.append("%s_count %d" % (metric, r["count"]))
        lines.append("%s_min %s" % (metric, r["min"]))
        lines.append("%s_max %s" % (metric, r["max"]))
    return "\n".join(lines) + ("\n" if lines else "")
