"""Resource ledger — where the hierarchy's bytes, FLOPs and messages go.

The reference reports *time* (profiler.hpp) and *structure* (the level
table of amg.hpp:560-598); what it never accounts is the resource side
that actually limits a sparse solver on an accelerator: device memory by
storage format, HBM traffic per cycle stage, and (distributed) halo
bytes on the wire. This module is the single place those models live:

* :class:`DeviceMemoryBudget` — a shared byte budget one hierarchy build
  threads through every ``to_device('auto')`` call, so storage-hungry
  formats (the dense-window blocks, ops/densewin.py) decrement ONE
  hierarchy-wide pool instead of each matrix consulting the per-matrix
  ``AMGCL_TPU_DWIN_MAX_BYTES`` cap independently.
* :func:`mv_cost` — analytic (flops, HBM bytes) of one SpMV per device
  format; :func:`cycle_cost_model` composes them into the per-stage
  FLOP/byte map of one multigrid cycle, :func:`krylov_iteration_model`
  into the per-iteration cost of the outer Krylov loop. Divide the two
  numbers and you have the roofline x-coordinate of each stage.
* :func:`hierarchy_ledger` — the per-level device-memory map (operator /
  transfer / smoother / fused-kernel bytes, by format) whose totals are
  DEFINED as the leaf-byte sum of the hierarchy pytree, so they can never
  drift from the live buffers (tests assert ledger total == AMG.bytes()).
* :func:`comm_model` / :func:`allreduce_model` — halo-exchange message
  counts and wire bytes per SpMV for the distributed matrix types, and
  the ring-allreduce model for psum'd dots.
* :func:`xla_cost_analysis` — optional cross-check of the analytic
  numbers against XLA's own compiled cost analysis, where the backend
  exposes one.

Everything returned is plain JSON-clean data (ints/floats/strings) so it
rides the telemetry sink unmodified.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np


# ---------------------------------------------------------------------------
# shared device-memory budget
# ---------------------------------------------------------------------------

def _charge_fault(budget_name: str) -> bool:
    """Allocation fault seam (faults/inject.py): an armed ``alloc.*``
    rule in ``AMGCL_TPU_FAULT_PLAN`` forces the next charge(s) to be
    refused — simulated HBM OOM at farm admission (``alloc.farm`` on
    the ``farm_hbm`` pool) or dense-window conversion (``alloc.dwin``
    on every other budget). One env read when no plan is set."""
    if not os.environ.get("AMGCL_TPU_FAULT_PLAN"):
        return False
    try:
        from amgcl_tpu.faults import inject as _inject
        site = "alloc.farm" if budget_name == "farm_hbm" \
            else "alloc.dwin"
        return _inject.should_fire(site, target=budget_name) is not None
    except Exception:
        return False


class DeviceMemoryBudget:
    """Byte budget shared across one hierarchy build.

    Consumers ask ``remaining()`` before materializing a storage-hungry
    buffer and ``try_charge(nbytes, tag)`` when they commit one; the
    charge log keeps per-matrix attribution for the ledger. Exceeding the
    budget is impossible by construction — ``try_charge`` refuses instead
    of overdrawing."""

    def __init__(self, total_bytes: int, name: str = "dense_window"):
        self.total = int(total_bytes)
        self.name = name
        self.used = 0
        self.charges = []           # [(tag, bytes), ...]

    def remaining(self) -> int:
        return self.total - self.used

    def try_charge(self, nbytes: int, tag: str = "") -> bool:
        nbytes = int(nbytes)
        if _charge_fault(self.name):
            return False
        if nbytes < 0 or self.used + nbytes > self.total:
            return False
        self.used += nbytes
        self.charges.append((tag, nbytes))
        return True

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "total_bytes": self.total,
                "used_bytes": self.used,
                "remaining_bytes": self.remaining(),
                "charges": [{"tag": t, "bytes": b}
                            for t, b in self.charges]}

    def __repr__(self):
        return "DeviceMemoryBudget(%s: %d/%d bytes)" % (
            self.name, self.used, self.total)


def dense_window_budget() -> DeviceMemoryBudget:
    """Fresh hierarchy-wide dense-window budget from
    ``AMGCL_TPU_DWIN_MAX_BYTES`` (same knob as before, new semantics: the
    cap now bounds the SUM over every dense-window conversion that
    shares the budget, not each matrix separately)."""
    from amgcl_tpu.ops.densewin import max_total_bytes
    return DeviceMemoryBudget(max_total_bytes(), name="dense_window")


class LruMemoryPool(DeviceMemoryBudget):
    """:class:`DeviceMemoryBudget` generalized to a farm-wide RESIDENT
    SET: named charges that can be released again (eviction returns the
    bytes) and re-charged (readmission), with least-recently-used
    ordering maintained by :meth:`touch` so the farm's admission loop
    can always name the coldest resident hierarchy to evict
    (serve/farm.py; ``AMG.bytes()`` is the accounting unit per charge).

    ``total_bytes <= 0`` means unlimited — the pool still tracks
    residency and LRU order, it just never refuses a charge. The charge
    log inherited from the base class stays append-only: a release
    appends a negative-byte row rather than rewriting history, so the
    ledger remains an audit trail."""

    def __init__(self, total_bytes: int = 0, name: str = "farm_hbm"):
        total = int(total_bytes or 0)
        self.unlimited = total <= 0
        super().__init__(total if total > 0 else (1 << 62), name)
        # the base class's append-only charge log was sized for ONE
        # hierarchy build; a farm pool lives for the process and under
        # eviction pressure appends ~2 rows per batch — bound it (the
        # recent tail is still an audit trail, the totals are exact)
        from collections import deque
        self.charges = deque(self.charges, maxlen=256)
        #: key -> bytes; insertion order IS the LRU order (coldest first)
        self._resident: Dict[str, int] = {}

    def charge(self, key: str, nbytes: int) -> bool:
        """Admit ``key`` at ``nbytes``. Re-charging a resident key
        swaps its charge ATOMICALLY — on failure the old charge is
        restored, never dropped: the key's buffers are still live, and
        a window where a resident operator looks evicted would let the
        farm's dispatch run a redundant readmission (and understate
        ``used``) while the caller waits to retry. False when it does
        not fit; the caller evicts ``coldest()`` and retries. A failed
        or successful re-charge both move the key to the warm end of
        the LRU order (it was just touched)."""
        nbytes = int(nbytes)
        old = self._resident.pop(key, None)
        if old is not None:
            self.used -= old
        if not self.try_charge(nbytes, tag=key):
            if old is not None:
                self.used += old
                self._resident[key] = old
            return False
        if old is not None:
            self.charges.append((key + ":released", -old))
        self._resident[key] = nbytes
        return True

    def release(self, key: str) -> int:
        """Evict ``key``: return its bytes to the pool (0 when it was
        not resident)."""
        nbytes = self._resident.pop(key, 0)
        if nbytes:
            self.used -= nbytes
            self.charges.append((key + ":released", -nbytes))
        return nbytes

    def touch(self, key: str) -> None:
        """Mark ``key`` most-recently-used (dict re-insertion moves it
        to the warm end of the LRU order)."""
        if key in self._resident:
            self._resident[key] = self._resident.pop(key)

    def coldest(self, exclude=()) -> Optional[str]:
        """The least-recently-used resident key outside ``exclude`` —
        the eviction victim; None when nothing is evictable."""
        for key in self._resident:
            if key not in exclude:
                return key
        return None

    def resident(self) -> Dict[str, int]:
        """Copy of the resident map in LRU order (coldest first)."""
        return dict(self._resident)

    def resize(self, total_bytes: int) -> None:
        """Change the budget in place (the CLI/bench demos size the cap
        from the tenants actually built). The caller evicts down to the
        new cap; the pool only re-arms the refusal threshold."""
        total = int(total_bytes or 0)
        self.unlimited = total <= 0
        self.total = total if total > 0 else (1 << 62)

    def to_dict(self) -> Dict[str, Any]:
        out = super().to_dict()
        if self.unlimited:
            out["total_bytes"] = 0
            out["remaining_bytes"] = None
        out["resident"] = dict(self._resident)
        return out


# ---------------------------------------------------------------------------
# per-format analytic SpMV cost
# ---------------------------------------------------------------------------

def _leaf_bytes(tree) -> int:
    """Device bytes of every array leaf in a pytree (0 for None)."""
    if tree is None:
        return 0
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += leaf.size * leaf.dtype.itemsize
    return total


def _vec_dims(M):
    """Scalar-expanded (rows, cols) of an operator (block-aware; a
    GridTentative's 3-D ``block`` names grid coarsening factors, not a
    value block — only 2-tuples scale the vector dims)."""
    blk = getattr(M, "block", None)
    br, bc = blk if isinstance(blk, tuple) and len(blk) == 2 else (1, 1)
    return M.shape[0] * br, M.shape[1] * bc


def _itemsize(M) -> int:
    try:
        return int(np.dtype(M.dtype).itemsize)
    except Exception:
        return 4


def mv_cost(M) -> Dict[str, int]:
    """Analytic cost of one ``y = M x``: ``{"flops", "bytes"}``.

    The byte count is the HBM-traffic model (stored operator streamed
    once + x read + y written), which is what bounds these kernels on
    TPU; gather-paying formats move more in practice — this is the
    roofline floor, not a measurement."""
    if M is None:
        return {"flops": 0, "bytes": 0}
    name = type(M).__name__
    rows, cols = _vec_dims(M)
    itemsize = _itemsize(M)
    stored = _leaf_bytes(M)
    vec = (rows + cols) * itemsize
    flops = None
    if name in ("DiaMatrix", "DistDiaMatrix"):
        flops = 2 * len(M.offsets) * rows
    elif name == "EllMatrix":
        flops = 2 * int(M.vals.size)
    elif name == "DenseMatrix":
        flops = 2 * rows * cols
    elif name == "DenseWindowMatrix":
        flops = 2 * int(M.blocks.size)
    elif name == "WindowedEllMatrix":
        flops = 2 * int(M.vals.size)
    elif name in ("GridTentative", "AggTentative"):
        # piecewise-constant transfer: one add per fine point
        flops = rows
    elif name in ("TentativeP", "TentativeR"):
        inner = mv_cost(M.T)
        return {"flops": inner["flops"], "bytes": inner["bytes"]}
    elif name == "ImplicitSmoothedP":
        inner = mv_cost(M.M)
        return {"flops": mv_cost(M.T)["flops"] + inner["flops"] + rows,
                "bytes": stored + vec}
    elif name == "ImplicitSmoothedR":
        inner = mv_cost(M.Mt)
        return {"flops": mv_cost(M.T)["flops"] + inner["flops"] + rows,
                "bytes": stored + vec}
    if flops is None:
        # generic fallback: two flops per stored value
        flops = 2 * max(stored // max(itemsize, 1), 1)
    return {"flops": int(flops), "bytes": int(stored + vec)}


# ---------------------------------------------------------------------------
# cycle / iteration cost models
# ---------------------------------------------------------------------------

def _add(a, b):
    return {"flops": a["flops"] + b["flops"], "bytes": a["bytes"] + b["bytes"]}


def _scale(a, k):
    return {"flops": a["flops"] * k, "bytes": a["bytes"] * k}


def _zero_sweep_cost(relax, n: int, vec: int) -> Optional[Dict[str, int]]:
    """Cost of ONE smoother application from a ZERO initial guess, where
    the smoother family makes that cheap: the scaled-residual smoothers
    (Jacobi/SPAI-0, relaxation/base.py) reduce to ``u = scale ∘ f`` — no
    operator stream at all (the residual of a zero guess IS f). None for
    smoother families whose from-zero application still streams the
    operator (Chebyshev, ILU, GS) — callers fall back to the full-sweep
    model. Keeping this stage-accurate is what lets the roofline's
    per-stage model bytes agree with ``xla_cost_analysis`` instead of
    over-charging the first pre-sweep a full operator pass."""
    scale = getattr(relax, "scale", None)
    if scale is None:
        return None
    b = int(scale.shape[-1]) if getattr(scale, "ndim", 1) == 3 else 1
    flops = 2 * n * b if b > 1 else n
    return {"flops": int(flops), "bytes": _leaf_bytes(relax) + 2 * vec}


def cycle_cost_model(hier) -> Dict[str, Any]:
    """Per-stage FLOPs/HBM-bytes of ONE multigrid cycle of ``hier``
    (models/amg.Hierarchy or compatible). Stage model per level is the
    STREAMING FLOOR — what a perfect single-pass kernel moves, which is
    what the fused sweep/residual kernels run on TPU and what XLA's
    elementwise fusion approaches elsewhere: a smoother sweep streams
    the operator and its own state once plus {x in, f in, x out}
    (the Ax intermediate is never materialized) — except the FIRST
    pre-sweep, which runs from a zero guess and for the scaled-residual
    family is just ``scale ∘ f`` (see :func:`_zero_sweep_cost`); the
    residual the operator plus {x, f in, r out}; transfers stream
    themselves plus their vectors. W-cycles visit level i ``ncycle**i``
    times.

    Levels carrying the whole-leg fused kernels (ops/pallas_vcycle.py,
    ``lv.down``/``lv.up``) are priced as the SINGLE passes the cycle
    actually runs — no double counting of the intermediate vectors the
    composed stages would re-stream: a ``down_fused`` row replaces
    pre_smooth + restrict when the zero-guess leg engages (npre == 1,
    scalar scaled-residual smoother), the ``restrict`` row becomes the
    one-pass residual+restrict kernel whenever ``lv.down`` exists, and
    an ``up_fused`` row absorbs prolong + the first post-sweep (the
    ``post_smooth`` row keeps the full-npost model for the roofline
    join, which rescales it — the level total charges only the
    remaining npost−1 sweeps)."""
    levels = getattr(hier, "levels", [])
    npre = getattr(hier, "npre", 1)
    npost = getattr(hier, "npost", 1)
    ncycle = max(getattr(hier, "ncycle", 1), 1)
    coarse = getattr(hier, "coarse", None)
    stages = []
    total = {"flops": 0, "bytes": 0}
    for i, lv in enumerate(levels):
        A = getattr(lv, "A", None)
        visits = ncycle ** i
        if A is None:
            stages.append({"level": i, "visits": visits, "skipped": True})
            continue
        n, _ = _vec_dims(A)
        itemsize = _itemsize(A)
        vec = n * itemsize
        a_cost = mv_cost(A)
        row: Dict[str, Any] = {"level": i, "visits": visits}
        if i == len(levels) - 1:
            if coarse is not None:
                cb = _leaf_bytes(coarse)
                row["coarse_solve"] = {"flops": 2 * n * n,
                                       "bytes": cb + 2 * vec}
            else:
                # smoother-as-coarse-solve: one standalone application
                row["coarse_solve"] = _add(
                    {"flops": n, "bytes": 2 * vec},
                    {"flops": 0, "bytes": _leaf_bytes(lv.relax)})
            level_total = row["coarse_solve"]
        else:
            rx_b = _leaf_bytes(getattr(lv, "relax", None))
            # streaming floors (what a perfect single-pass kernel moves
            # — and what the fused dia/windowed-ELL sweep kernels and
            # XLA's elementwise fusion actually run): a sweep reads
            # {x, f, smoother state}, streams A and writes x' — the Ax
            # intermediate is never materialized, so it is not charged
            # (a_cost already carries the x read + one vector write);
            # same for the residual's r and the prolong's correction add
            sweep = _add(a_cost, {"flops": 3 * n, "bytes": vec + rx_b})
            resid = _add(a_cost, {"flops": n, "bytes": vec})
            zero = _zero_sweep_cost(getattr(lv, "relax", None), n, vec)
            if npre > 0 and zero is not None:
                row["pre_smooth"] = _add(zero, _scale(sweep, npre - 1))
            else:
                row["pre_smooth"] = _scale(sweep, npre)
            row["restrict"] = _add(resid, mv_cost(lv.R))
            row["prolong"] = _add(mv_cost(lv.P),
                                  {"flops": n, "bytes": vec})
            row["post_smooth"] = _scale(sweep, npost)
            down = getattr(lv, "down", None)
            up = getattr(lv, "up", None)
            vec_c = _vec_dims(lv.R)[0] * itemsize   # coarse-vector bytes
            fused_zero = npre == 1 and down is not None \
                and getattr(down, "w", None) is not None
            if down is not None:
                # the one-pass kernel streams ITS operand copy once plus
                # {f, u} in and fc out — this is what the cycle runs for
                # its residual+restrict whenever the leg exists
                down_pass = {"flops": row["restrict"]["flops"],
                             "bytes": _leaf_bytes(down) + 2 * vec + vec_c}
                row["restrict"] = down_pass
                if fused_zero:
                    # zero-guess whole leg: same pass also emits the
                    # pre-smoothed iterate (writes u instead of reading
                    # it) — byte count identical, flops add the sweep's
                    row["down_fused"] = {
                        "flops": row["pre_smooth"]["flops"]
                        + down_pass["flops"],
                        "bytes": down_pass["bytes"]}
            fused_up = up is not None and npost >= 1
            if fused_up:
                row["up_fused"] = {
                    "flops": row["prolong"]["flops"]
                    + (row["post_smooth"]["flops"] / npost
                       if npost else 0),
                    "bytes": _leaf_bytes(up) + 3 * vec + vec_c}
            level_total = {"flops": 0, "bytes": 0}
            if fused_zero:
                level_total = _add(level_total, row["down_fused"])
            else:
                level_total = _add(level_total, row["pre_smooth"])
                level_total = _add(level_total, row["restrict"])
            if fused_up:
                level_total = _add(level_total, row["up_fused"])
                if npost > 1:
                    level_total = _add(level_total, _scale(
                        row["post_smooth"], (npost - 1) / npost))
            else:
                level_total = _add(level_total, row["prolong"])
                level_total = _add(level_total, row["post_smooth"])
        total = _add(total, _scale(level_total, visits))
        stages.append(row)
    out = {"stages": stages, "total": dict(total)}
    if total["bytes"]:
        out["total"]["flop_per_byte"] = round(
            total["flops"] / total["bytes"], 4)
    return out


#: per-iteration operation counts (spmv, precond applies, dots, axpys) —
#: the documented model behind krylov_iteration_model; approximate for the
#: restarted methods (counts are per inner step).
KRYLOV_OPS = {
    "CG":         (1, 1, 3, 3),
    "BiCGStab":   (2, 2, 7, 6),
    "BiCGStabL":  (2, 2, 8, 8),
    "GMRES":      (1, 1, 4, 4),
    "FGMRES":     (1, 1, 4, 4),
    "LGMRES":     (1, 1, 6, 6),
    "IDRs":       (2, 2, 8, 8),
    "Richardson": (1, 1, 1, 2),
    "PreOnly":    (0, 1, 0, 0),
}

#: n-vector HBM streams per iteration (reads + writes at working dtype)
#: of the FUSED iteration bodies (ops/fused_vec.py): every dot that
#: rides an update or an spmv pass costs zero extra streams, so the
#: vector traffic is just the distinct operand reads + result writes.
#: The unfused composition pays 2·dots + 3·axpys streams instead (each
#: dot re-reads its two operands, each axpby reads two and writes one).
#: CG: rho(2: r,s) + p-update(3) + fused xr tail(4r+2w) = 11.
#: BiCGStab: p-update(4) + s-update(3) + fused tail(6r+2w) = 15 (rho,
#: <rhat,v>, <t,t>, <t,s>, ‖r‖² all ride spmv/update passes).
#: Others estimated the same way from their rewritten bodies.
KRYLOV_VEC_STREAMS_FUSED = {
    "CG":         11,
    "BiCGStab":   15,
    "BiCGStabL":  24,
    "GMRES":      16,
    "FGMRES":     16,
    "LGMRES":     20,
    "IDRs":       30,
    "Richardson": 4,
    "PreOnly":    0,
}


#: fused-engagement CONTRACT per solver (audited statically by
#: analysis/jaxpr_audit.py): (fused `_fused_pass` call sites per
#: iteration body with the tier on, whether the per-iteration
#: vector-stream recount from the jaxpr must EXACTLY equal
#: KRYLOV_VEC_STREAMS_FUSED). Declared next to the byte model it
#: protects: if an iteration body loses its fused kernels (a silently
#: dead Pallas path, an accidental decomposition), the audit fails
#: before any benchmark runs. Solvers whose stream-table entry is per
#: INNER step or an estimate (the restarted/recycling methods carry
#: whole basis matrices through the outer body, which the audit weighs
#: as k streams each) pin only the fused-pass count; the GMRES family's
#: merged reductions are matvec ``stack_dots``, not ``_fused_pass``
#: kernels, hence 0 there.
KRYLOV_FUSED_PASSES = {
    "CG":         (1, True),
    "BiCGStab":   (1, True),
    "BiCGStabL":  (2, False),
    "GMRES":      (0, False),
    "FGMRES":     (0, False),
    "LGMRES":     (0, False),
    "IDRs":       (5, False),
    "Richardson": (0, False),
    "PreOnly":    (0, False),
}


#: collective CONTRACT of the distributed Krylov bodies (audited
#: statically): psums per iteration, elements the stacked psum carries,
#: halo SpMVs per iteration. parallel/dist_solver.py prices its
#: SolveReport comm model FROM this table (dots=psums,
#: elems_per_dot=elems_per_psum), so the model and the traced program
#: are checked against one declaration — a third psum sneaking back
#: into dist_cg_pipelined fails the audit, not a chip session.
DIST_CG_COLLECTIVES = {
    "dist_cg":           {"psums": 3, "elems_per_psum": 1, "spmvs": 1},
    "dist_cg_pipelined": {"psums": 1, "elems_per_psum": 3, "spmvs": 1},
}


#: collective CONTRACT of the comm-measurement stage pairs
#: (telemetry/comm.py, audited statically by
#: analysis/jaxpr_audit.audit_comm_stages): each measured stage must
#: contain EXACTLY the listed collectives (and zero of every other
#: kind), and every ``*_ablated`` stand-in must have a collective
#: census of EXACTLY 0 — the ablation subtraction
#: ``comm_s = t(measured) − t(ablated)`` is only an attribution of
#: collective wall time if the ablated program really dropped the
#: collectives and nothing else. A psum sneaking into a stand-in (or a
#: halo exchange falling out of a measured stage) fails the analysis
#: gate, not a measurement session.
COMM_STAGE_CONTRACTS = {
    "halo_dia":           {"ppermute": 2},
    "halo_ell":           {"all_to_all": 1},
    "psum":               {"psum": 1},
    "iter_classical_dia": {"psum": 3, "ppermute": 2},
    "iter_pipelined_dia": {"psum": 1, "ppermute": 2},
    "iter_classical_ell": {"psum": 3, "all_to_all": 1},
    "iter_pipelined_ell": {"psum": 1, "all_to_all": 1},
}


#: donation CONTRACT per jitted entry point: how many argument buffers
#: the lowered program is expected to alias into outputs. All zero
#: today — the audit's informational finding is the standing reminder
#: that ROADMAP item 1's resident solve loop wants donated x/r buffers;
#: when that lands, this table changes in the same commit (or the audit
#: fails CI).
DONATION_CONTRACTS = {
    "make_solver._solve_fn": 0,
    # the resident serve loop (serve/service.py) donates the iterate
    # buffer x0 into the solution output — exactly ONE aliased argument
    # buffer in the lowered program. The auditor (jaxpr_audit.
    # audit_serve) lowers the service's actual jit wrap and fails the
    # analysis gate if the aliasing is lost.
    "serve.solve_step": 1,
}


#: host-purity CONTRACT of the operator X-ray (telemetry/structure.py,
#: audited by analysis/jaxpr_audit.audit_structure): the X-ray path —
#: structure metrics, the format-decision candidate table, the
#: reorder-gain advisor — is host-side analytics ONLY. Statically, the
#: module may import neither jax nor any jax-importing ops module
#: (``jax_imports`` counts violations found by AST scan; ops.csr is
#: numpy-only and allowed). Dynamically, a full ``structure_report``
#: (+ advisor) over a built hierarchy must leave the process
#: compile/trace counters untouched — no new traces, no new backend
#: compiles beyond the spmv/solve entry points that already exist
#: (compile_watch delta 0). A violation is an error finding in the
#: analysis gate, not a slow chip-session surprise.
STRUCTURE_CONTRACTS = {
    "telemetry.structure": {"jax_imports": 0, "new_traces": 0,
                            "new_backend_compiles": 0},
}


#: setup CONTRACT of the traced device-setup entry points (audited
#: statically by analysis/jaxpr_audit.audit_setup): the per-level build
#: programs — MIS rounds, segment-Galerkin, smoothing SpGEMM, stencil
#: pair-Galerkin — must contain NO host callbacks (a host round trip per
#: level serializes the setup exactly like the VERDICT-r5 dispatch
#: overhead serialized the solve), no collectives (serial setup; the
#: sharded MIS has its own contract), and no float-width casts on
#: matrix-sized values (the numeric rebuild must stay bit-stable in the
#: build dtype — any mixing happens at the declared host seam, not
#: inside the kernels).
SETUP_CONTRACTS = {
    "coarsening.device_aggregates":
        {"host_callbacks": 0, "collectives": 0, "narrowing_casts": 0},
    "ops.segment_galerkin":
        {"host_callbacks": 0, "collectives": 0, "narrowing_casts": 0},
    "ops.segment_spgemm":
        {"host_callbacks": 0, "collectives": 0, "narrowing_casts": 0},
    "ops.transfer_smooth":
        {"host_callbacks": 0, "collectives": 0, "narrowing_casts": 0},
    "ops.stencil_galerkin":
        {"host_callbacks": 0, "collectives": 0, "narrowing_casts": 0},
}


#: census CONTRACT of the gather-SpMV pair (ops/pallas_gather.py,
#: audited statically by analysis/jaxpr_audit.audit_gather): the
#: per-slot unrolled kernel and its take-along XLA fallback are a pure
#: streaming SpMV — no host callbacks (a callback inside the Krylov
#: body would serialize every iteration on a device->host round trip),
#: no collectives (single-device operator; the sharded SpMV lives in
#: parallel/), and no float-width casts on matrix-sized values (the
#: kernel accumulates in the value dtype; widening happens only at the
#: declared ``preferred_element_type`` output seam). A violation fails
#: `python -m amgcl_tpu.analysis`, not a chip session.
GATHER_CONTRACTS = {
    "ops.gather_spmv":
        {"host_callbacks": 0, "collectives": 0, "narrowing_casts": 0},
    "ops.gather_spmv_xla":
        {"host_callbacks": 0, "collectives": 0, "narrowing_casts": 0},
}


# ---------------------------------------------------------------------------
# setup-phase cost model + stage attribution
# ---------------------------------------------------------------------------

def setup_cost_model(host_levels) -> Dict[str, Dict[str, int]]:
    """Analytic traffic model per setup stage, keyed by the
    ``models/amg.py`` setup-scope names (``level<i>/galerkin``, ...).
    Galerkin stages price the CACHED segment/stencil plan where one
    exists (gather + multiply + scatter-add ≈ 3 streams per multiply-
    list entry); plan-less stages fall back to an nnz-proportional
    SpGEMM estimate. Numbers are a traffic model for the attribution
    join (GB/s column), not a measurement."""
    rows: Dict[str, Dict[str, int]] = {}
    if not host_levels:
        return rows
    for i, (Ai, P, _R) in enumerate(host_levels[:-1]):
        try:
            itemsize = Ai.val.dtype.itemsize
            nnz = int(Ai.nnz)
        except Exception:
            continue
        plan = getattr(P, "_seg_plan", None)
        spec = getattr(P, "_implicit_spec", None)
        gplan = spec.get("_gplan") if isinstance(spec, dict) else None
        if plan is not None:
            flops = int(plan.flops)
        elif gplan is not None:
            flops = int(gplan.flops)
        else:
            flops = 4 * nnz            # host hash-SpGEMM estimate
        rows["level%d/galerkin" % i] = {
            "flops": 2 * flops, "bytes": 3 * flops * itemsize}
        # strength graph + aggregation: a few full passes over A
        rows["level%d/coarsening" % i] = {
            "flops": 2 * nnz, "bytes": 4 * nnz * itemsize}
        rows["level%d/transfer" % i] = {
            "flops": 0, "bytes": 2 * nnz * itemsize}
        rows["level%d/relax_setup" % i] = {
            "flops": 2 * nnz, "bytes": 2 * nnz * itemsize}
    try:
        Alast = host_levels[-1][0]
        nl = int(Alast.nrows)
        rows["coarse_solver"] = {"flops": 2 * nl ** 3 // 3,
                                 "bytes": 8 * nl * nl}
    except Exception:
        pass
    return rows


def setup_attribution(setup_profile, host_levels=None,
                      total_s: Optional[float] = None) -> Dict[str, Any]:
    """Stage-by-stage attribution of the measured setup/rebuild profile
    (``AMG.setup_profile``), joined to :func:`setup_cost_model` — the
    setup-phase counterpart of the solve roofline. Returns::

        {"rows": [{stage, seconds, frac, flops?, bytes?, gbps?}...],
         "total_s", "named_s", "coverage"}

    ``coverage`` is the fraction of the build's wall total inside NAMED
    top-level stages (nested substages don't double count) — the bench
    record's "attributed setup time" number. ``total_s`` should be the
    wall time of the build itself (models/amg.py records it): the
    profiler's own total keeps ticking after the build, so exporting it
    later would dilute coverage."""
    if setup_profile is None:
        return {"rows": [], "total_s": 0.0, "named_s": 0.0,
                "coverage": 0.0}
    prof = setup_profile.to_dict() if hasattr(setup_profile, "to_dict") \
        else dict(setup_profile)
    model = setup_cost_model(host_levels) if host_levels else {}
    rows: List[Dict[str, Any]] = []
    named = 0.0

    def walk(scopes, prefix, depth):
        nonlocal named
        for name, rec in scopes.items():
            # round BEFORE accumulating so named_s equals the sum of the
            # reported top-level row seconds exactly
            t = round(float(rec.get("total_s", 0.0)), 5)
            path = prefix + name
            if depth == 0:
                named += t
            row: Dict[str, Any] = {"stage": path, "seconds": round(t, 5),
                                   "nested": depth > 0}
            m = model.get(path)
            if m is not None:
                row.update(m)
                if t > 0 and m.get("bytes"):
                    row["gbps"] = round(m["bytes"] / t / 1e9, 3)
            rows.append(row)
            walk(rec.get("children", {}), path + "/", depth + 1)

    walk(prof.get("scopes", {}), "", 0)
    total = float(total_s) if total_s else \
        (float(prof.get("total_s") or named) or named)
    for row in rows:
        row["frac"] = round(row["seconds"] / total, 4) if total else 0.0
    rows.sort(key=lambda r: -r["seconds"])
    return {"rows": rows, "total_s": round(total, 5),
            "named_s": round(named, 9),
            "coverage": round(named / total, 4) if total else 0.0}


def fused_vec_modeled() -> bool:
    """Whether the iteration model should charge the fused vector-tier
    byte counts — mirrors ops.fused_vec.fused_vec_enabled without
    importing jax (this module stays stdlib+numpy-only)."""
    return os.environ.get("AMGCL_TPU_FUSED_VEC", "1") != "0"


def krylov_iteration_model(solver_name: str, A_dev,
                           cycle_total: Optional[Dict[str, int]] = None,
                           pre_cycles: int = 1,
                           fused: Optional[bool] = None,
                           batch: int = 1,
                           effective_batch: Optional[int] = None
                           ) -> Dict[str, Any]:
    """FLOPs/HBM-bytes of one outer Krylov iteration: the solver's SpMVs
    and vector work plus ``pre_cycles`` multigrid cycles per
    preconditioner application (``cycle_total`` from cycle_cost_model).

    ``fused`` selects the vector-traffic model: the fused tier
    (ops/fused_vec.py, default when ``AMGCL_TPU_FUSED_VEC`` is on)
    streams each iteration vector once per compound primitive
    (:data:`KRYLOV_VEC_STREAMS_FUSED`), so the dots are byte-free; the
    composed model charges every dot and axpby its own passes. FLOPs are
    identical either way — fusion moves bytes, not arithmetic.

    ``batch`` adds the stacked multi-RHS axis (serve/batched.py): FLOPs
    and per-vector streams scale with B, but the Krylov operator's
    STORED bytes are read once per SpMV regardless of B — the
    amortization that makes one stacked dispatch beat B single solves
    even before dispatch overhead. The multigrid-cycle bytes are scaled
    by B conservatively (the cycle total has no stored/vector split
    here), so the modeled amortization is a floor, not the full win.

    ``effective_batch`` prices padding: the serve path zero-pads
    partial batches up to a power-of-two bucket (serve/service.py), so
    only ``effective_batch`` of the ``batch`` columns are real work.
    The model then also reports ``batch_fill`` plus the effective and
    padding-waste splits of flops/bytes — wasted FLOPs scale with the
    padded columns, wasted bytes with their per-column vector traffic
    only (the stored operator is read once regardless), so the roofline
    can separate effective from padded throughput."""
    spmv, papp, dots, axpys = KRYLOV_OPS.get(solver_name, (1, 1, 4, 4))
    if fused is None:
        fused = fused_vec_modeled()
    batch = max(int(batch), 1)
    n, _ = _vec_dims(A_dev) if A_dev is not None else (0, 0)
    itemsize = _itemsize(A_dev) if A_dev is not None else 4
    vec = n * itemsize
    mv = mv_cost(A_dev)
    stored_once = 0
    if batch > 1 and A_dev is not None:
        stored = _leaf_bytes(A_dev)
        mv = {"flops": mv["flops"] * batch,
              "bytes": stored + batch * max(mv["bytes"] - stored, 0)}
        stored_once = stored * spmv
    cost = _scale(mv, spmv)
    streams = KRYLOV_VEC_STREAMS_FUSED.get(solver_name) if fused else None
    if streams is None:
        fused = False
        streams = 2 * dots + 3 * axpys
    cost = _add(cost, {"flops": (2 * dots + 2 * axpys) * n * batch,
                       "bytes": streams * vec * batch})
    if cycle_total:
        cost = _add(cost, _scale(
            {"flops": cycle_total["flops"], "bytes": cycle_total["bytes"]},
            papp * max(int(pre_cycles), 1) * batch))
    out = {"solver": solver_name, "spmvs": spmv, "precond_applies": papp,
           "dots": dots, "axpys": axpys, "vec_streams": streams,
           "fused_vec": bool(fused), **cost}
    if batch > 1:
        out["batch"] = batch
    if effective_batch is not None:
        eff = min(max(int(effective_batch), 0), batch)
        fill = eff / batch
        # wasted bytes: the per-column-scaled traffic only — the stored
        # operator read (stored_once) is paid once whatever the fill
        per_col_bytes = max(cost["bytes"] - stored_once, 0)
        waste_f = int(round(cost["flops"] * (1 - fill)))
        waste_b = int(round(per_col_bytes * (1 - fill)))
        out["effective_batch"] = eff
        out["batch_fill"] = round(fill, 4)
        out["padding_waste_flops"] = waste_f
        out["padding_waste_bytes"] = waste_b
        out["effective_flops"] = cost["flops"] - waste_f
        out["effective_bytes"] = cost["bytes"] - waste_b
    if cost["bytes"]:
        out["flop_per_byte"] = round(cost["flops"] / cost["bytes"], 4)
    return out


# ---------------------------------------------------------------------------
# hierarchy memory ledger
# ---------------------------------------------------------------------------

def hierarchy_ledger(hier, host_levels=None,
                     budget: Optional[DeviceMemoryBudget] = None,
                     setup_profile=None) -> Dict[str, Any]:
    """Per-level device-memory map of a hierarchy.

    Totals are the leaf-byte sums of exactly the pytree slots a Level
    carries (A, relax, P, R, down, up) plus the coarse solver — the same
    leaves ``AMG.bytes()`` walks, so ``totals.bytes`` equals the live
    buffer total by construction."""
    levels = []
    by_format: Dict[str, int] = {}
    tot = {"operator": 0, "transfer": 0, "relax": 0, "fused": 0}
    for i, lv in enumerate(getattr(hier, "levels", [])):
        A = getattr(lv, "A", None)
        op_b = _leaf_bytes(A)
        p_b = _leaf_bytes(getattr(lv, "P", None))
        r_b = _leaf_bytes(getattr(lv, "R", None))
        rx_b = _leaf_bytes(getattr(lv, "relax", None))
        fu_b = _leaf_bytes(getattr(lv, "down", None)) \
            + _leaf_bytes(getattr(lv, "up", None))
        fmt = type(A).__name__ if A is not None else None
        row = {
            "level": i,
            "format": fmt,
            "bytes": {"operator": op_b, "P": p_b, "R": r_b,
                      "relax": rx_b, "fused": fu_b,
                      "total": op_b + p_b + r_b + rx_b + fu_b},
            "spmv": mv_cost(A),
        }
        if host_levels is not None and i < len(host_levels):
            Ai = host_levels[i][0]
            row["rows"] = int(Ai.nrows)
            row["nnz"] = int(Ai.nnz)
        levels.append(row)
        if fmt:
            by_format[fmt] = by_format.get(fmt, 0) + op_b
        for Tm in (getattr(lv, "P", None), getattr(lv, "R", None)):
            if Tm is not None:
                tname = "transfer/" + type(Tm).__name__
                by_format[tname] = by_format.get(tname, 0) + _leaf_bytes(Tm)
        tot["operator"] += op_b
        tot["transfer"] += p_b + r_b
        tot["relax"] += rx_b
        tot["fused"] += fu_b
    coarse_b = _leaf_bytes(getattr(hier, "coarse", None))
    out: Dict[str, Any] = {
        "levels": levels,
        "coarse_solver_bytes": coarse_b,
        "totals": {**tot,
                   "bytes": sum(tot.values()) + coarse_b,
                   "by_format": by_format},
        "cycle": cycle_cost_model(hier),
    }
    if budget is not None:
        out["dense_window"] = budget.to_dict()
    if setup_profile is not None:
        to_dict = getattr(setup_profile, "to_dict", None)
        out["setup"] = to_dict() if callable(to_dict) else setup_profile
    return out


def summarize_ledger(led: Dict[str, Any]) -> Dict[str, Any]:
    """Compact one-record summary of a hierarchy ledger — what bench.py
    embeds (and the regression gate compares as 'peak ledger bytes')."""
    out = {
        "hierarchy_bytes": led["totals"]["bytes"],
        "by_format": led["totals"]["by_format"],
        "cycle_flops": led["cycle"]["total"]["flops"],
        "cycle_bytes": led["cycle"]["total"]["bytes"],
    }
    fpb = led["cycle"]["total"].get("flop_per_byte")
    if fpb is not None:
        out["cycle_flop_per_byte"] = fpb
    dw = led.get("dense_window")
    if dw is not None:
        out["dense_window_used"] = dw["used_bytes"]
        out["dense_window_total"] = dw["total_bytes"]
    return out


def _human_bytes(n: float) -> str:
    for unit in ("B", "K", "M", "G"):
        if abs(n) < 1024 or unit == "G":
            return "%.2f %s" % (n, unit)
        n /= 1024.0


def format_ledger(led: Dict[str, Any]) -> str:
    """Human-readable rendering of a hierarchy ledger (the CLI's
    ``--ledger`` table)."""
    lines = ["Resource ledger:",
             "level  format            operator  transfer     relax"
             "     fused   F/B(spmv)",
             "-" * 78]
    for row in led["levels"]:
        b = row["bytes"]
        sp = row["spmv"]
        fpb = (sp["flops"] / sp["bytes"]) if sp["bytes"] else 0.0
        lines.append("%5d  %-16s %9s %9s %9s %9s %9.3f" % (
            row["level"], row["format"] or "-",
            _human_bytes(b["operator"]), _human_bytes(b["P"] + b["R"]),
            _human_bytes(b["relax"]), _human_bytes(b["fused"]), fpb))
    t = led["totals"]
    lines.append("-" * 78)
    lines.append("total device bytes: %s  (operator %s, transfer %s, "
                 "relax %s, fused %s, coarse %s)" % (
                     _human_bytes(t["bytes"]), _human_bytes(t["operator"]),
                     _human_bytes(t["transfer"]), _human_bytes(t["relax"]),
                     _human_bytes(t["fused"]),
                     _human_bytes(led["coarse_solver_bytes"])))
    cyc = led["cycle"]["total"]
    lines.append("one cycle: %.3g MFLOP / %s streamed  ->  %.3f flop/byte"
                 % (cyc["flops"] / 1e6, _human_bytes(cyc["bytes"]),
                    cyc.get("flop_per_byte", 0.0)))
    dw = led.get("dense_window")
    if dw is not None:
        lines.append("dense-window budget: %s / %s used" % (
            _human_bytes(dw["used_bytes"]), _human_bytes(dw["total_bytes"])))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# distributed communication models
# ---------------------------------------------------------------------------

def comm_model(M, nd: int) -> Optional[Dict[str, Any]]:
    """Halo-exchange messages and wire bytes of ONE distributed SpMV.

    Delegates to the matrix's own ``halo_comm(nd)`` (dist_matrix /
    dist_ell define it next to the exchange they model); None when the
    operator has no distributed exchange."""
    fn = getattr(M, "halo_comm", None)
    if callable(fn):
        return fn(int(nd))
    return None


def allreduce_model(nd: int, count: int, itemsize: int) -> Dict[str, int]:
    """Ring-allreduce wire model of ``lax.psum`` over ``count`` elements:
    2(nd-1) steps, each moving count/nd elements per device pair —
    ~2·count·itemsize total on the wire for large nd."""
    nd = max(int(nd), 1)
    if nd == 1:
        return {"msgs": 0, "bytes": 0}
    msgs = 2 * (nd - 1)
    return {"msgs": msgs, "bytes": int(2 * (nd - 1) / nd * count * itemsize)}


def krylov_comm_model(spmv_comm: Optional[Dict[str, Any]], nd: int,
                      itemsize: int, spmvs: int = 1,
                      dots: int = 3,
                      elems_per_dot: int = 1) -> Dict[str, Any]:
    """Per-iteration comm of a distributed Krylov loop: the SpMV halo
    exchanges plus one allreduce per inner-product GROUP.

    ``dots`` counts the collectives (the latency-bearing quantity);
    ``elems_per_dot`` the scalars each one carries — a merged-reduction
    body like the pipelined CG psums ONE stacked 3-vector per iteration
    (``dots=1, elems_per_dot=3``) where the classical body pays three
    separate scalar collectives."""
    base = {"msgs": 0, "bytes": 0}
    if spmv_comm:
        base = {"msgs": spmv_comm["msgs"] * spmvs,
                "bytes": spmv_comm["bytes"] * spmvs}
    red = allreduce_model(nd, max(int(elems_per_dot), 1), itemsize)
    out = {"msgs": base["msgs"] + dots * red["msgs"],
           "bytes": base["bytes"] + dots * red["bytes"],
           "spmvs": spmvs, "dots": dots}
    if elems_per_dot != 1:
        out["elems_per_dot"] = int(elems_per_dot)
    return out


# ---------------------------------------------------------------------------
# XLA cross-check
# ---------------------------------------------------------------------------

def xla_cost_analysis(fn, *args) -> Optional[Dict[str, float]]:
    """Compile ``fn(*args)`` and read XLA's own cost analysis — the
    cross-check for the analytic models above. Returns
    ``{"flops", "bytes_accessed"}`` or None when the backend does not
    expose cost analysis (never raises)."""
    try:
        import jax
        c = jax.jit(fn).lower(*args).compile().cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0] if c else None
        if not c:
            return None
        out = {}
        if c.get("flops") is not None:
            out["flops"] = float(c["flops"])
        ba = c.get("bytes accessed", c.get("bytes_accessed"))
        if ba is not None:
            out["bytes_accessed"] = float(ba)
        return out or None
    except Exception:
        return None
