"""Flight recorder — per-solve capsules, incident replay bundles, and
deterministic solve replay.

Every observability layer so far describes a solve that already
happened; none of them leave a REPRODUCIBLE artifact behind when one
goes wrong. A health guard trips in the field, an SLO watchdog fires,
a batch dispatch raises — the operator gets flag names and ratios, but
re-creating the failing solve means reconstructing the matrix, the rhs,
the config and the env by hand. This module closes that loop:

* **Capsules** — a bounded process-global ring of per-solve records
  (``record_solve``, fed by ``make_solver.__call__``): a weak reference
  to the solver bundle, the (immutable) rhs/x0 arrays, the report, and
  a timestamp. Recording is O(1) — everything expensive (hashing,
  config capture, provenance) happens only at dump time.
* **Replay bundles** — on trigger (fatal health flag, serve/farm SLO
  trip or failed batch, ``--check`` gate failure, unhandled exception
  via the excepthook) ``dump()`` writes a self-contained directory:
  ``system.npz`` (CSR matrix + rhs + x0) and ``manifest.json`` (the
  operator sparsity fingerprint — the same blake2b key
  ``serve/registry.py`` uses — plus the stable config key, rhs/x0
  content hashes, the full ``AMGCL_TPU_*`` env snapshot,
  ``hw_provenance``, and the report's ledger/health/compile/roofline
  summaries). Each dump emits a ``flight_dump`` JSONL event; serving
  surfaces additionally bump the ``flight_dumps_total`` live counter.
* **Replay** — ``cli.py --replay <bundle>`` (and :func:`run_replay`)
  reconstructs the matrix and config, applies the recorded env
  deltas, re-runs the solve and asserts report parity: iteration count
  and health-flag identity EXACT on the same platform, residual within
  tolerance; cross-platform replays degrade to informational checks
  (the ``_record_platform`` discipline).

Knobs (README env table):

  AMGCL_TPU_FLIGHT            0 disables the recorder entirely (no ring,
                              no dumps, no ``--check`` self-replay)
  AMGCL_TPU_FLIGHT_DIR        directory replay bundles land in; UNSET =
                              capsules ring but nothing is written (the
                              AMGCL_TPU_TELEMETRY convention: opt into
                              disk artifacts explicitly)
  AMGCL_TPU_FLIGHT_MAX_DUMPS  bundle-count bound per directory (def 8);
                              at the bound new incidents are counted
                              but not written

Module level stays stdlib + numpy (jax and the model layer are imported
lazily inside the replay path) so recording can never add a device
sync to the solve hot path.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

#: capsule ring capacity — the newest N solves are dumpable post-hoc
#: (the excepthook path); refs are to immutable arrays, so the cost is
#: holding at most N rhs/x0 buffers alive
RING_CAPACITY = 8

#: manifest schema version
BUNDLE_SCHEMA = 1

# runtime lock witness seam (analysis/lockwitness.py, identity when
# the knob is off): frozen at import time — the chaos runner exports
# the knob before importing
from amgcl_tpu.analysis.lockwitness import maybe_wrap as _wit_wrap

_lock = _wit_wrap("flight._lock", threading.Lock())
_ring: deque = deque(maxlen=RING_CAPACITY)
_dumps_total = 0
_dump_seq = 0


def enabled() -> bool:
    """Kill switch: ``AMGCL_TPU_FLIGHT=0`` disables recording AND
    dumping (read per call — tests flip it)."""
    return os.environ.get("AMGCL_TPU_FLIGHT", "1") != "0"


def flight_dir() -> Optional[str]:
    """Dump directory, or None (= record capsules, write nothing)."""
    return os.environ.get("AMGCL_TPU_FLIGHT_DIR") or None


def max_dumps() -> int:
    try:
        return int(os.environ.get("AMGCL_TPU_FLIGHT_MAX_DUMPS", "8"))
    except ValueError:
        return 8


def dumps_total() -> int:
    """Bundles written by this process (the live-counter source)."""
    return _dumps_total


def _reset_for_tests() -> None:
    global _dumps_total, _dump_seq
    with _lock:
        _ring.clear()
        _dumps_total = 0
        _dump_seq = 0


# ---------------------------------------------------------------------------
# capsules
# ---------------------------------------------------------------------------

def record_solve(bundle, rhs, x0, report) -> None:
    """Ring one solve. O(1): refs only — rhs/x0 are immutable (numpy or
    jax) arrays, the bundle rides a weakref so the recorder never keeps
    a hierarchy alive. Called from ``make_solver.__call__`` on every
    guarded solve when the recorder is enabled.

    No-op while ``AMGCL_TPU_FLIGHT_DIR`` is unset: every ring consumer
    (the excepthook, ``dump_capsule``) can only ever write into that
    directory, so ringing without it would pin up to
    :data:`RING_CAPACITY` rhs/x0 buffer sets for the process lifetime
    with zero benefit."""
    if flight_dir() is None:
        return
    try:
        ref = weakref.ref(bundle)
    except TypeError:
        ref = (lambda b: (lambda: b))(bundle)
    with _lock:
        # same guard as _reset_for_tests/dump: solves record from any
        # thread (the serve worker included), and the ring's guard
        # contract is enforced by the guarded-by analysis
        _ring.append({"ts": time.time(), "bundle": ref, "rhs": rhs,
                      "x0": x0, "report": report})


def last_capsule() -> Optional[Dict[str, Any]]:
    return _ring[-1] if _ring else None


def fatal_health(health: Optional[Dict[str, Any]]) -> bool:
    """True when a decoded ``SolveReport.health`` carries a flag the
    guards treat as fatal — NaN, any Krylov breakdown, or divergence
    (the trigger condition for a health-trip dump). Stagnation and
    indefiniteness are informational and do not dump."""
    if not isinstance(health, dict) or health.get("ok", True):
        return False
    return bool(health.get("nan") or health.get("breakdown")
                or health.get("diverged"))


# ---------------------------------------------------------------------------
# capture: config, hashes, provenance
# ---------------------------------------------------------------------------

def _content_hash(arr) -> Optional[str]:
    if arr is None:
        return None
    try:
        a = np.ascontiguousarray(np.asarray(arr))
        return hashlib.blake2b(a.tobytes(), digest_size=16).hexdigest()
    except Exception:
        return None


def _scalar_fields(obj) -> Dict[str, Any]:
    import dataclasses
    out: Dict[str, Any] = {}
    if dataclasses.is_dataclass(obj):
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name, None)
            if v is None or isinstance(v, (int, float, str, bool)):
                out[f.name] = v
    return out


def _dtype_name(dtype) -> Optional[str]:
    try:
        return str(np.dtype(np.asarray([], dtype).dtype))
    except Exception:
        try:
            return str(dtype.__name__)
        except Exception:
            return None


def capture_config(bundle) -> Dict[str, Any]:
    """Replayable config of a ``make_solver`` bundle: solver type +
    scalar params, preconditioner class + params (AMG / dummy /
    relaxation — the ``precond_from_config`` classes), refine mode,
    dtypes. Marks ``replayable: False`` with a reason for compositions
    the runtime config layer cannot rebuild (Schur, CPR, nested, block
    engines) — those still get a manifest, just no replay contract."""
    cfg: Dict[str, Any] = {"replayable": True, "notes": []}
    try:
        from amgcl_tpu.models import runtime as rt
    except Exception as e:                       # pragma: no cover
        return {"replayable": False, "notes": ["runtime import: %r" % e]}
    solver = getattr(bundle, "solver", None)
    inv = {cls: name for name, cls in rt.SOLVERS.items()}
    sname = inv.get(type(solver))
    if sname is None:
        cfg["replayable"] = False
        cfg["notes"].append("solver %r has no runtime name"
                            % type(solver).__name__)
    else:
        cfg["solver"] = {"type": sname, **_scalar_fields(solver)}
    precond = getattr(bundle, "precond", None)
    prm = getattr(precond, "prm", None)
    pcfg: Optional[Dict[str, Any]] = None
    if prm is not None and type(prm).__name__ == "AMGParams":
        inv_c = {cls: n for n, cls in rt.COARSENING.items()}
        inv_r = {cls: n for n, cls in rt.RELAXATION.items()}
        cname = inv_c.get(type(prm.coarsening))
        rname = inv_r.get(type(prm.relax))
        pcfg = {"class": "amg",
                "coarse_enough": prm.coarse_enough,
                "direct_coarse": prm.direct_coarse,
                "max_levels": prm.max_levels, "npre": prm.npre,
                "npost": prm.npost, "ncycle": prm.ncycle,
                "pre_cycles": prm.pre_cycles,
                "matrix_format": prm.matrix_format,
                "dtype": _dtype_name(prm.dtype)}
        if cname is not None:
            pcfg["coarsening"] = {"type": cname,
                                  **_scalar_fields(prm.coarsening)}
        if rname is not None:
            pcfg["relax"] = {"type": rname,
                             **_scalar_fields(prm.relax)}
        if cname is None or rname is None:
            cfg["replayable"] = False
            cfg["notes"].append("coarsening/relax has no runtime name")
    elif type(precond).__name__ == "DummyPreconditioner":
        pcfg = {"class": "dummy",
                "dtype": _dtype_name(getattr(precond, "dtype", None))}
    elif type(precond).__name__ == "AsPreconditioner":
        inv_r = {cls: n for n, cls in rt.RELAXATION.items()}
        rname = inv_r.get(type(getattr(precond, "relax", None)))
        pcfg = {"class": "relaxation",
                "dtype": _dtype_name(getattr(precond, "dtype", None))}
        if rname is not None:
            pcfg["relax"] = {"type": rname,
                             **_scalar_fields(precond.relax)}
        else:
            cfg["replayable"] = False
            cfg["notes"].append("relaxation has no runtime name")
    else:
        cfg["replayable"] = False
        cfg["notes"].append("preconditioner %r is outside the runtime "
                            "config classes" % type(precond).__name__)
    if pcfg is not None:
        cfg["precond"] = pcfg
    cfg["refine"] = int(getattr(bundle, "refine", 0) or 0)
    rm = getattr(bundle, "refine_mode", None)
    if rm:
        cfg["refine_dtype"] = rm
    sd = _dtype_name(getattr(bundle, "solver_dtype", None))
    if sd:
        cfg["solver_dtype"] = sd
    cfg["matrix_format"] = getattr(bundle, "matrix_format", "auto")
    A = getattr(bundle, "A_host", None)
    if A is not None and getattr(A, "block_size", (1, 1)) != (1, 1):
        cfg["replayable"] = False
        cfg["notes"].append("block-valued system matrix")
    if not cfg["notes"]:
        del cfg["notes"]
    return cfg


def env_snapshot() -> Dict[str, str]:
    """Every ``AMGCL_TPU_*`` variable set right now — the knob state a
    replay re-applies (minus the recorder's own and the sink's, see
    :func:`_replay_env`)."""
    return {k: v for k, v in os.environ.items()
            if k.startswith("AMGCL_TPU_")}


def _provenance() -> Dict[str, Any]:
    # the ONE process-cached provenance helper (telemetry/report.py) —
    # a dump/replay must not re-enumerate the device set per call
    from amgcl_tpu.telemetry.report import _hw_provenance
    return _hw_provenance()


def _report_summary(report) -> Dict[str, Any]:
    """The manifest's compact report record: headline numbers + the
    ledger/health/compile/roofline summaries parity checks and
    ``diff.py`` consume."""
    if report is None:
        return {}
    to_dict = getattr(report, "to_dict", None)
    rec = to_dict(with_history=False) if callable(to_dict) \
        else dict(report)
    out = {k: rec.get(k) for k in ("iters", "resid", "convergence_rate",
                                   "wall_time_s", "solver", "health",
                                   "compile", "schema", "hw_provenance")
           if rec.get(k) is not None}
    res = rec.get("resources") or {}
    if isinstance(res, dict):
        for k in ("memory", "roofline", "per_iteration"):
            if res.get(k) is not None:
                out.setdefault("resources", {})[k] = res[k]
    return out


# ---------------------------------------------------------------------------
# dump
# ---------------------------------------------------------------------------

def _existing_bundles(dirpath: str) -> List[str]:
    try:
        return sorted(d for d in os.listdir(dirpath)
                      if d.startswith("flight-")
                      and os.path.isdir(os.path.join(dirpath, d)))
    except OSError:
        return []


def dump(reason: str, bundle=None, rhs=None, x0=None, report=None,
         tags: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Write one self-contained replay bundle; returns its directory
    path, or None when disabled / no ``AMGCL_TPU_FLIGHT_DIR`` / the
    per-directory bound is reached / the write fails. Never raises —
    an incident recorder that crashes the incident path is worse than
    none. Emits one ``flight_dump`` JSONL event per written bundle;
    with the dump dir configured but the bound reached (or the write
    failing), the event still fires with ``skipped`` naming the reason
    — an unset dir stays silent (no opt-in, no event spam)."""
    global _dumps_total, _dump_seq
    from amgcl_tpu.telemetry import sink as _sink
    if not enabled():
        return None
    dirpath = flight_dir()
    event: Dict[str, Any] = {"event": "flight_dump", "reason": reason}
    if tags:
        event.update({k: v for k, v in tags.items() if v is not None})
    if dirpath is None:
        # no dump dir = the operator never opted into disk artifacts:
        # stay silent (a skipped-event per unhealthy solve would spam
        # every telemetry stream); the bound-reached case below DOES
        # emit — there the operator opted in and must see saturation
        return None
    path = None
    try:
        os.makedirs(dirpath, exist_ok=True)
        bound = max_dumps()
        if bound > 0 and len(_existing_bundles(dirpath)) >= bound:
            event["skipped"] = "AMGCL_TPU_FLIGHT_MAX_DUMPS=%d reached" \
                % bound
            _sink.emit(event)
            return None
        with _lock:
            _dump_seq += 1
            seq = _dump_seq
        name = "flight-%s-%d-%d-%s" % (
            time.strftime("%Y%m%dT%H%M%S", time.gmtime()),
            os.getpid(), seq, reason)
        path = os.path.join(dirpath, name)
        os.makedirs(path, exist_ok=True)
        manifest: Dict[str, Any] = {
            "schema": BUNDLE_SCHEMA, "reason": reason,
            "ts": time.time(), "pid": os.getpid(),
            "env": env_snapshot(), "hw_provenance": _provenance(),
            "report": _report_summary(report),
        }
        if tags:
            manifest["tags"] = {k: v for k, v in tags.items()
                                if v is not None}
        arrays: Dict[str, Any] = {}
        A = getattr(bundle, "A_host", None) if bundle is not None \
            else None
        if A is not None:
            try:
                from amgcl_tpu.serve.registry import (
                    sparsity_fingerprint, stable_config_key)
                manifest["fingerprint"] = sparsity_fingerprint(A)
                manifest["config_key"] = stable_config_key(
                    getattr(bundle, "solver", None),
                    getattr(getattr(bundle, "precond", None), "prm",
                            None) or getattr(bundle, "precond", None))
            except Exception:
                pass
            manifest["config"] = capture_config(bundle)
            arrays.update(ptr=np.asarray(A.ptr), col=np.asarray(A.col),
                          val=np.asarray(A.val),
                          shape=np.asarray([A.nrows, A.ncols], np.int64))
            manifest["matrix"] = {"rows": int(A.nrows),
                                  "nnz": int(A.nnz)}
            plan = getattr(getattr(bundle, "precond", None),
                           "_reorder", None)
            if plan is not None:
                # executed-reorder provenance (ISSUE 20): the bundle's
                # arrays are the ORIGINAL-order system (A_host); replay
                # rebuilds from them and re-derives the same permutation
                # because env re-application restores AMGCL_TPU_REORDER
                # and the plan is a pure function of (pattern, mode) —
                # the variant/fingerprint here let a parity check assert
                # the replayed layout matches the recorded one
                manifest["reorder"] = {
                    "variant": plan["variant"],
                    "fingerprint": plan["fingerprint"],
                    "predicted_gain": plan["predicted_gain"]}
        else:
            manifest["config"] = {"replayable": False,
                                  "notes": ["solver bundle unavailable "
                                            "at dump time"]}
        if rhs is not None:
            rhs_np = np.asarray(rhs)
            arrays["rhs"] = rhs_np
            manifest["rhs_hash"] = _content_hash(rhs_np)
        if x0 is not None:
            x0_np = np.asarray(x0)
            arrays["x0"] = x0_np
            manifest["x0_hash"] = _content_hash(x0_np)
        if arrays:
            np.savez_compressed(os.path.join(path, "system.npz"),
                                **arrays)
        with open(os.path.join(path, "manifest.json"), "w") as f:
            json.dump(_sink._clean(manifest), f, indent=1,
                      default=_sink._jsonable)
        with _lock:
            _dumps_total += 1
        event.update(path=path, fingerprint=manifest.get("fingerprint"),
                     replayable=manifest["config"].get("replayable"),
                     dumps_total=_dumps_total)
        _sink.emit(event)
        return path
    except Exception as e:                       # noqa: BLE001
        # a half-written bundle would both crash a later replay AND
        # permanently consume a MAX_DUMPS slot (_existing_bundles
        # counts directories) — remove it before reporting the skip
        if path is not None:
            import shutil
            shutil.rmtree(path, ignore_errors=True)
        event["skipped"] = "dump failed: %r" % e
        try:
            _sink.emit(event)
        except Exception:
            pass
        return None


def dump_capsule(reason: str, capsule: Optional[Dict[str, Any]] = None,
                 tags: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Dump a ringed capsule (default: the newest) — the excepthook and
    post-hoc paths. A dead bundle weakref still dumps the manifest +
    rhs (marked non-replayable)."""
    capsule = capsule or last_capsule()
    if capsule is None:
        return None
    bundle = capsule["bundle"]()
    return dump(reason, bundle=bundle, rhs=capsule.get("rhs"),
                x0=capsule.get("x0"), report=capsule.get("report"),
                tags=tags)


# ---------------------------------------------------------------------------
# excepthook
# ---------------------------------------------------------------------------

_prev_excepthook = None


def install_excepthook() -> bool:
    """Chain a crash dumper into ``sys.excepthook``: an unhandled
    exception dumps the newest capsule (reason ``crash``, exception
    repr in the tags) before the previous hook runs. Idempotent;
    returns whether the hook is installed after the call."""
    global _prev_excepthook
    if not enabled():
        return False
    if _prev_excepthook is not None:
        return True
    _prev_excepthook = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            dump_capsule("crash", tags={
                "exception": "%s: %s" % (exc_type.__name__, exc)})
        except Exception:                        # noqa: BLE001
            pass                 # the original traceback must still print
        (_prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

    sys.excepthook = _hook
    return True


def uninstall_excepthook() -> None:
    global _prev_excepthook
    if _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
        _prev_excepthook = None


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------

def load_bundle(path: str):
    """(manifest, arrays) of a bundle directory (or a direct path to
    its ``manifest.json``)."""
    if os.path.isfile(path):
        path = os.path.dirname(path)
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    arrays = {}
    npz = os.path.join(path, "system.npz")
    if os.path.exists(npz):
        with np.load(npz) as z:
            arrays = {k: z[k] for k in z.files}
    return manifest, arrays


class _ReplayEnv:
    """Apply the manifest's ``AMGCL_TPU_*`` snapshot for the duration
    of the replay, then restore. The recorder's own knobs and the sink
    path are excluded from the snapshot — AND the recorder is forced
    OFF for the duration: a replayed health-trip solve re-trips the
    same fatal guard inside ``make_solver.__call__``, and without the
    kill switch every replay would recursively dump a fresh bundle
    (burning an ``AMGCL_TPU_FLIGHT_MAX_DUMPS`` slot per replay until
    real incidents are silently skipped)."""

    _EXCLUDE_PREFIXES = ("AMGCL_TPU_FLIGHT", "AMGCL_TPU_TELEMETRY")

    def __init__(self, snapshot: Dict[str, str]):
        self.apply = {k: v for k, v in (snapshot or {}).items()
                      if k.startswith("AMGCL_TPU_")
                      and not k.startswith(self._EXCLUDE_PREFIXES)}
        self.saved: Dict[str, Optional[str]] = {}

    def __enter__(self):
        live = {k for k in os.environ if k.startswith("AMGCL_TPU_")
                and not k.startswith(self._EXCLUDE_PREFIXES)}
        for k in live | set(self.apply):
            self.saved[k] = os.environ.get(k)
        for k in live - set(self.apply):
            del os.environ[k]
        os.environ.update(self.apply)
        # recorder off while the replayed solve runs (restored on exit)
        self.saved["AMGCL_TPU_FLIGHT"] = os.environ.get(
            "AMGCL_TPU_FLIGHT")
        os.environ["AMGCL_TPU_FLIGHT"] = "0"
        return self

    def __exit__(self, *exc):
        for k, v in self.saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        return False


def _flags_of(health: Optional[Dict[str, Any]]) -> List[str]:
    if not isinstance(health, dict):
        return []
    return sorted(str(f) for f in health.get("flags") or [])


def check_parity(recorded: Dict[str, Any], replayed: Dict[str, Any],
                 same_platform: bool,
                 rtol: float = 1e-4) -> Dict[str, Any]:
    """The replay contract: iteration count and health-flag identity
    EXACT on the same platform, residual within ``rtol`` relative; a
    cross-platform replay reports every check as skipped (informational
    values kept) and passes. Returns {ok, checks: [...]}."""
    checks: List[Dict[str, Any]] = []

    def row(name, a, b, ok, skipped=False):
        r: Dict[str, Any] = {"check": name, "recorded": a, "replayed": b}
        r["status"] = "skipped" if skipped else ("ok" if ok
                                                 else "mismatch")
        checks.append(r)

    skip = not same_platform
    it_a, it_b = recorded.get("iters"), replayed.get("iters")
    if it_a is None or it_b is None:
        row("iters", it_a, it_b, True, skipped=True)
    else:
        row("iters", int(it_a), int(it_b),
            int(it_a) == int(it_b), skipped=skip)
    fa = _flags_of(recorded.get("health"))
    fb = _flags_of(replayed.get("health"))
    row("health_flags", fa, fb, fa == fb,
        skipped=skip or (recorded.get("health") is None))
    ra, rb = recorded.get("resid"), replayed.get("resid")
    if ra is None or rb is None:
        row("resid", ra, rb, True, skipped=True)
    else:
        ra, rb = float(ra), float(rb)
        both_nonfinite = not (np.isfinite(ra) or np.isfinite(rb))
        close = both_nonfinite or (
            np.isfinite(ra) and np.isfinite(rb)
            and abs(ra - rb) <= rtol * max(abs(ra), abs(rb), 1e-300))
        row("resid", ra, rb, bool(close), skipped=skip)
    ok = not any(c["status"] == "mismatch" for c in checks)
    out = {"ok": ok, "platform_skip": skip, "checks": checks}
    if all(c["status"] == "skipped" for c in checks) and not skip:
        # a bundle dumped without a report (failed-batch incidents
        # resolve no report) compares NOTHING — say so instead of
        # printing a vacuous green parity verdict
        out["vacuous"] = True
    return out


def run_replay(path: str, rtol: float = 1e-4,
               apply_env: bool = True) -> Dict[str, Any]:
    """Load a bundle, rebuild the solve, re-run it under the recorded
    env, and score parity. Returns {ok, parity, report, diff,
    manifest_path, ...}; ``ok`` is False for a non-replayable bundle.
    Imports jax/the model layer — callers who must stay jax-free run
    this in a subprocess (``bench.py --check`` does)."""
    manifest, arrays = load_bundle(path)
    cfg = manifest.get("config") or {}
    out: Dict[str, Any] = {"manifest_path": path,
                           "reason": manifest.get("reason"),
                           "fingerprint": manifest.get("fingerprint")}
    if not cfg.get("replayable"):
        out.update(ok=False,
                   error="bundle is not replayable: %s"
                   % "; ".join(cfg.get("notes") or ["no config"]))
        return out
    if "ptr" not in arrays or "rhs" not in arrays:
        out.update(ok=False, error="bundle carries no matrix/rhs npz")
        return out
    env = manifest.get("env") or {}
    ctx = _ReplayEnv(env) if apply_env else _ReplayEnv({})
    with ctx:
        import jax
        needs_x64 = "float64" in (cfg.get("solver_dtype") or "") \
            or "float64" in ((cfg.get("precond") or {}).get("dtype")
                             or "")
        if needs_x64 and not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)
        from amgcl_tpu.models import runtime as rt
        from amgcl_tpu.models.make_solver import make_solver
        from amgcl_tpu.ops.csr import CSR
        A = CSR(arrays["ptr"], arrays["col"], arrays["val"],
                int(arrays["shape"][0]))
        for name in ("rhs", "x0"):
            want = manifest.get(name + "_hash")
            if want and name in arrays \
                    and _content_hash(arrays[name]) != want:
                out.update(ok=False,
                           error="%s content hash mismatch — the "
                                 "bundle was modified" % name)
                return out
        solver = rt.solver_from_params(dict(cfg.get("solver") or {}))
        pcfg = dict(cfg.get("precond") or {"class": "amg"})
        precond = rt.precond_from_config(A, pcfg)
        kw: Dict[str, Any] = {"refine": int(cfg.get("refine", 0))}
        if cfg.get("refine_dtype"):
            kw["refine_dtype"] = cfg["refine_dtype"]
        if cfg.get("solver_dtype"):
            kw["solver_dtype"] = rt.DTYPES.get(cfg["solver_dtype"],
                                               cfg["solver_dtype"])
        if cfg.get("matrix_format"):
            kw["matrix_format"] = cfg["matrix_format"]
        bundle = make_solver(A, precond, solver, **kw)
        x0 = arrays.get("x0")
        x, report = bundle(arrays["rhs"],
                           x0 if x0 is not None else None)
        import jax as _jax
        _jax.block_until_ready(x)
    recorded = manifest.get("report") or {}
    plat_rec = (manifest.get("hw_provenance") or {}).get(
        "device_platform")
    plat_now = _provenance().get("device_platform")
    same = plat_rec is None or plat_now is None or plat_rec == plat_now
    replayed = report.to_dict(with_history=False)
    out["parity"] = check_parity(recorded, replayed, same, rtol=rtol)
    out["ok"] = out["parity"]["ok"]
    out["report"] = {k: replayed.get(k)
                     for k in ("iters", "resid", "wall_time_s",
                               "solver", "health")
                     if replayed.get(k) is not None}
    out["platform"] = {"recorded": plat_rec, "current": plat_now}
    try:
        from amgcl_tpu.telemetry import diff as _diff
        out["diff"] = _diff.compact(_diff.diff(recorded, replayed))
    except Exception:
        pass
    return out


def format_replay(result: Dict[str, Any]) -> str:
    """Human rendering of a :func:`run_replay` result."""
    lines = ["Flight replay: %s" % result.get("manifest_path")]
    if result.get("reason"):
        lines.append("  incident reason: %s" % result["reason"])
    if result.get("error"):
        lines.append("  ERROR: %s" % result["error"])
        return "\n".join(lines)
    plat = result.get("platform") or {}
    if plat:
        lines.append("  platform: recorded=%s current=%s"
                     % (plat.get("recorded"), plat.get("current")))
    parity = result.get("parity") or {}
    for c in parity.get("checks") or []:
        lines.append("  %-13s %-24s vs %-24s %s"
                     % (c["check"], c["recorded"], c["replayed"],
                        c["status"].upper()))
    if parity.get("vacuous"):
        lines.append("  parity: NOT APPLICABLE — the bundle carries no "
                     "recorded report (failed-batch incidents resolve "
                     "none); the replay completed, nothing to compare")
    else:
        lines.append("  parity: %s%s"
                     % ("OK" if parity.get("ok") else "MISMATCH",
                        " (cross-platform: exact checks skipped)"
                        if parity.get("platform_skip") else ""))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# self-replay (bench.py --check determinism gate)
# ---------------------------------------------------------------------------

def selftest(n: int = 10, workdir: Optional[str] = None
             ) -> Dict[str, Any]:
    """Dump → replay → parity on a small generated problem: the
    determinism self-check ``bench.py --check`` gates every round on.
    Solves an n³ Poisson system with the headline CG+SA config, dumps
    a bundle into ``workdir`` (a temp dir by default), replays it, and
    returns the parity record."""
    import tempfile
    import jax.numpy as jnp
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.solver.cg import CG
    from amgcl_tpu.utils.sample_problem import poisson3d
    workdir = workdir or tempfile.mkdtemp(prefix="flight-selftest-")
    A, rhs = poisson3d(int(n))
    bundle = make_solver(A, AMGParams(dtype=jnp.float32,
                                      coarse_enough=200),
                         CG(maxiter=100, tol=1e-6))
    x, report = bundle(rhs.astype(np.float32))
    # the selftest dump is unbounded in ITS directory: a saturated
    # incident bound must not misreport the round as a determinism
    # failure (callers keep selftest bundles out of the incident dir —
    # bench.py --check uses a `check/` subdirectory)
    saved = {k: os.environ.get(k) for k in
             ("AMGCL_TPU_FLIGHT_DIR", "AMGCL_TPU_FLIGHT_MAX_DUMPS")}
    os.environ["AMGCL_TPU_FLIGHT_DIR"] = workdir
    os.environ["AMGCL_TPU_FLIGHT_MAX_DUMPS"] = "0"
    try:
        path = dump("selftest", bundle=bundle,
                    rhs=rhs.astype(np.float32), report=report)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if path is None:
        return {"ok": False, "error": "selftest dump failed "
                "(recorder disabled?)", "n": int(n)}
    result = run_replay(path)
    result["n"] = int(n)
    result["bundle"] = path
    return result


def _main(argv: Optional[List[str]] = None) -> int:
    """``python -m amgcl_tpu.telemetry.flight --selftest [n] [--dir D]``
    (the --check subprocess) or ``--replay <bundle>``. Prints ONE JSON
    line; exit 0 on parity."""
    args = list(argv if argv is not None else sys.argv[1:])
    if "--replay" in args:
        i = args.index("--replay")
        result = run_replay(args[i + 1])
    else:
        n = 10
        workdir = None
        if "--dir" in args:
            i = args.index("--dir")
            workdir = args[i + 1]
            del args[i:i + 2]
        nums = [a for a in args if a.isdigit()]
        if nums:
            n = int(nums[0])
        result = selftest(n=n, workdir=workdir)
    from amgcl_tpu.telemetry import sink as _sink
    print(json.dumps(_sink._clean(result), default=_sink._jsonable))
    return 0 if result.get("ok") else 1


if __name__ == "__main__":
    sys.exit(_main())
