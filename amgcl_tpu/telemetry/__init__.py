"""Telemetry — the uniform observability layer every part of the stack
reports through (the reference's profiler tree + per-level printouts +
per-iteration residual logging, amgcl/profiler.hpp / amg.hpp:560-598 /
cg.hpp:199, reworked as structured data instead of text).

Five pieces:

* :mod:`report`  — :class:`SolveReport`, the structured convergence record
  returned by every solver bundle (iters, final relative residual,
  per-iteration history, convergence rate, wall time, hierarchy stats).
* :mod:`history` — :class:`HistoryMixin`, per-iteration residual capture
  *inside* the ``lax.while_loop`` (no per-iteration host syncs), shared by
  all Krylov solvers.
* :mod:`tracing` — ``phase(name)`` named scopes so ``jax.profiler`` traces
  of the V-cycle read like the reference's profiler tree.
* :mod:`sink`    — JSONL metrics sink with a process-global default that
  bench.py, cli.py and the distributed solvers all emit through.
  Deliberately stdlib-only so the bench supervisor can load it without
  importing jax.
* :mod:`health`  — the numerics leg: in-loop guard detection (NaN,
  Krylov breakdowns, stagnation, divergence — a compact bitmask carried
  through every solver's ``lax.while_loop``, decoded into
  ``SolveReport.health``), per-level convergence probes
  (``AMG.probe_convergence()``) and the convergence doctor
  (:func:`diagnose`, ``cli.py --doctor``).

plus the efficiency leg (PR 4):

* :mod:`roofline` — measured per-stage times x the ledger's FLOP/byte
  models -> achieved GB/s / GFLOP/s vs device peaks, compute-/memory-
  bound classification, ranked bottlenecks (``AMG.roofline()``,
  ``cli.py --roofline``).
* :mod:`compile_watch` — process-global trace/compile/retrace observer
  over our jitted entry points (``SolveReport.compile``).
* :mod:`metrics` — stdlib-only percentile rollups of sink events and
  bench history, Prometheus-text export (``bench.py --trend``).
"""

from amgcl_tpu.telemetry.report import SolveReport
from amgcl_tpu.telemetry.history import HistoryMixin
from amgcl_tpu.telemetry.tracing import (phase, annotate, setup_scope,
                                         RequestSpans)
from amgcl_tpu.telemetry.sink import (JsonlSink, NullSink, emit,
                                      get_default_sink, set_default_sink)
from amgcl_tpu.telemetry.health import (HealthState, decode as decode_health,
                                        diagnose, format_findings,
                                        probe_hierarchy, serve_findings,
                                        two_grid_factor)
from amgcl_tpu.telemetry.ledger import (DeviceMemoryBudget,
                                        dense_window_budget,
                                        hierarchy_ledger, summarize_ledger,
                                        format_ledger, mv_cost,
                                        cycle_cost_model,
                                        krylov_iteration_model, comm_model,
                                        allreduce_model, krylov_comm_model,
                                        xla_cost_analysis)
# NOTE: the bare function names stay unshadowed — ``telemetry.roofline``
# / ``telemetry.compile_watch`` must keep naming the MODULES
from amgcl_tpu.telemetry.roofline import (device_peaks, measure_stages,
                                          format_roofline,
                                          solve_roofline, counter_map,
                                          xla_stage_check)
from amgcl_tpu.telemetry.compile_watch import (watched_jit,
                                               compile_snapshot,
                                               global_watch)
from amgcl_tpu.telemetry import metrics
# live registry + scrape endpoint (serve observability) — module-named
# like ``metrics``; the classes ride along for direct construction
from amgcl_tpu.telemetry import live
from amgcl_tpu.telemetry.live import LiveRegistry, MetricsServer
# forensics leg (PR 12): flight recorder + replay bundles, and the
# stdlib-only structured report diff (cross-run regression attribution)
from amgcl_tpu.telemetry import diff
from amgcl_tpu.telemetry import flight
# structure leg (PR 14): the operator X-ray — per-level structural
# analytics, the to_device('auto') format-decision ledger, and the
# predict-only reorder-gain advisor (host-side, never imports jax)
from amgcl_tpu.telemetry import structure
# memory observatory (PR 18): measured device-memory truth — sampling
# timeline, weakref ownership attribution, measured-vs-ledger joins,
# leak gate and OOM forensics (stdlib at module level, jax lazy)
from amgcl_tpu.telemetry import memwatch

__all__ = ["SolveReport", "HistoryMixin", "phase", "annotate",
           "setup_scope", "RequestSpans", "JsonlSink", "NullSink",
           "emit",
           "get_default_sink", "set_default_sink", "DeviceMemoryBudget",
           "dense_window_budget", "hierarchy_ledger", "summarize_ledger",
           "format_ledger", "mv_cost", "cycle_cost_model",
           "krylov_iteration_model", "comm_model", "allreduce_model",
           "krylov_comm_model", "xla_cost_analysis", "HealthState",
           "decode_health", "diagnose", "format_findings",
           "probe_hierarchy", "serve_findings", "two_grid_factor",
           "device_peaks",
           "measure_stages", "format_roofline",
           "solve_roofline", "counter_map", "xla_stage_check",
           "watched_jit", "compile_snapshot", "global_watch", "metrics",
           "live", "LiveRegistry", "MetricsServer", "diff", "flight",
           "structure", "memwatch"]
