"""Saturation observability — load-curve analytics over open-loop storm
samples (``serve/storm.py``).

The storm generator produces per-request SAMPLE rows timestamped at the
*scheduled* arrival (the open-loop contract: latency includes every
millisecond of queueing a closed-loop harness would hide by not
submitting while blocked — "coordinated omission"). This module turns
those rows into the saturation story ``bench --storm`` records and the
storm gate score:

* :func:`summarize_samples` — one rung's accounting: outcome counts,
  offered vs achieved vs GOODPUT rate (sheds/timeouts/unhealthy/errors
  excluded from goodput by definition), open-loop latency percentiles,
  scheduler-lag percentiles, and the mean serve-span breakdown with
  per-phase shares.
* :func:`ladder_curve` — the latency-vs-offered-load curve across an
  offered-load ladder of rungs.
* :func:`detect_knee` — the saturation knee: the first rung whose p99
  breaches the SLO, whose goodput collapses below the offered rate, or
  whose queue depth diverges; ``max_sustainable_rps`` is the best
  goodput seen below the knee (the number the storm gate protects).
* :func:`phase_attribution` — queue/pad/compile/solve/sync share as a
  function of offered load (the PR-8 request spans under load).
* :func:`gauge_rollup` — rollups of the concurrently scraped /metrics
  gauge time-series embedded in the record.
* :func:`storm_timeline_trace` — Chrome/Perfetto export: one complete
  event per request at its scheduled arrival, shed/timeout instants,
  and queue-depth counter tracks from the gauge series.
* :func:`build_record` — the schema-versioned ``bench_storm`` record
  body (curve + knee + goodput + attribution + reference-load p99).

Sample-row contract (what ``serve/storm.py`` records)::

    {"rid", "tenant", "phase", "rate_rps",      # schedule identity
     "t_sched_s",                # SCHEDULED arrival, storm-epoch seconds
     "t_submit_s", "lag_ms",     # actual submit + scheduler lag
     "outcome",                  # ok|shed|timeout|unhealthy|error
     "t_done_s", "latency_ms",   # completion; latency = done - SCHED
     "spans_ms": {queue,pad,compile,solve,sync}}   # ok rows only

IMPORTANT: stdlib-only AND free of package-relative imports, exactly
like ``telemetry/metrics.py`` — ``bench.py``'s supervisor (which must
never import jax) loads this by file path with importlib. Keep it that
way.
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, Iterable, List, Optional

try:
    from amgcl_tpu.telemetry import metrics as _metrics
except ImportError:          # loaded by file path (sink.py discipline):
    import importlib.util as _ilu    # pull the sibling the same way
    _spec = _ilu.spec_from_file_location(
        "_amgcl_tpu_metrics", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "metrics.py"))
    _metrics = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_metrics)

#: bench_storm record schema version — bump on breaking field changes
#: (the gate and the trend join key on fields by name, the
#: ``multichip_scaling`` discipline)
STORM_SCHEMA = 1

#: the serve-phase partition the PR-8 request spans carry
SPAN_KEYS = ("queue", "pad", "compile", "solve", "sync")

#: outcomes EXCLUDED from goodput — a shed, timed-out, unhealthy or
#: errored request consumed capacity without serving anyone
BAD_OUTCOMES = ("shed", "timeout", "unhealthy", "error")


def _pct(vals: List[float], p: float) -> Optional[float]:
    v = _metrics.percentile(vals, p)
    return round(v, 3) if v is not None else None


def summarize_samples(samples: List[Dict[str, Any]],
                      duration_s: Optional[float] = None
                      ) -> Dict[str, Any]:
    """One storm (or ladder-rung) summary from open-loop sample rows.

    ``offered_rps`` counts every SCHEDULED arrival over the schedule
    span (``duration_s`` overrides the span when the caller knows the
    configured phase length); ``achieved_rps`` counts completions of
    any outcome over the completion wall; ``goodput_rps`` counts only
    ``ok`` completions. Latency percentiles cover ok rows and are
    measured from the scheduled arrival — the open-loop contract."""
    n = len(samples)
    outcomes: Dict[str, int] = {}
    for s in samples:
        key = s.get("outcome") or "pending"
        outcomes[key] = outcomes.get(key, 0) + 1
    ok = [s for s in samples if s.get("outcome") == "ok"]
    lat = [s["latency_ms"] for s in ok
           if s.get("latency_ms") is not None]
    lag = [s["lag_ms"] for s in samples if s.get("lag_ms") is not None]
    sched = [s.get("t_sched_s") for s in samples
             if s.get("t_sched_s") is not None]
    dur = duration_s
    if dur is None and len(sched) > 1:
        dur = max(sched) - min(sched)
    done = [s.get("t_done_s") for s in samples
            if s.get("t_done_s") is not None]
    wall = (max(done) - min(sched)) if done and sched else None
    if wall is not None and dur:
        # the rate window never shrinks below the schedule span: an
        # underloaded rung whose few requests all finish early is
        # serving at the OFFERED rate, not at 1/completion-spread —
        # only drain time past the span stretches the window
        wall = max(wall, dur)
    completed = sum(v for k, v in outcomes.items()
                    if k not in ("pending", "shed"))
    out: Dict[str, Any] = {
        "requests": n,
        "outcomes": outcomes,
        "duration_s": round(dur, 4) if dur else None,
        "wall_s": round(wall, 4) if wall else None,
        "offered_rps": round(n / dur, 3) if dur else None,
        "achieved_rps": round(completed / wall, 3) if wall else None,
        "goodput_rps": round(len(ok) / wall, 3) if wall else None,
    }
    if out["offered_rps"] and out["goodput_rps"] is not None:
        out["goodput_frac"] = round(
            out["goodput_rps"] / out["offered_rps"], 4)
    bad = sum(outcomes.get(k, 0) for k in BAD_OUTCOMES)
    out["bad_frac"] = round(bad / n, 4) if n else 0.0
    for k in BAD_OUTCOMES:
        out["%s_rate" % k] = round(outcomes.get(k, 0) / n, 4) \
            if n else 0.0
    if lat:
        out["latency_ms"] = {
            "p50": _pct(lat, 50), "p90": _pct(lat, 90),
            "p99": _pct(lat, 99), "max": round(max(lat), 3),
            "count": len(lat)}
    if lag:
        out["sched_lag_ms"] = {"p50": _pct(lag, 50),
                               "p99": _pct(lag, 99),
                               "max": round(max(lag), 3)}
    spans: Dict[str, List[float]] = {k: [] for k in SPAN_KEYS}
    for s in ok:
        sp = s.get("spans_ms") or {}
        for k in SPAN_KEYS:
            v = sp.get(k)
            if isinstance(v, (int, float)) and math.isfinite(v):
                spans[k].append(float(v))
    means = {k: round(sum(v) / len(v), 3) if v else None
             for k, v in spans.items()}
    out["spans_ms"] = means
    total = sum(v for v in means.values() if v)
    if total > 0:
        out["span_share"] = {k: round((v or 0.0) / total, 4)
                             for k, v in means.items()}
    return out


def ladder_curve(rungs: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The latency-vs-offered-load curve: one row per ladder rung.

    ``rungs``: ``[{"offered_rps": <target rate>, "summary":
    summarize_samples(...), "gauges": [scrape rows]}, ...]`` (what
    ``serve.storm.run_ladder`` returns). Rows keep both the TARGET
    offered rate (the rung's configured Poisson rate — the x-axis the
    gate compares on) and the measured one."""
    curve = []
    for i, rung in enumerate(rungs):
        summ = rung.get("summary") or {}
        lat = summ.get("latency_ms") or {}
        depth = [g.get("queue_depth") for g in (rung.get("gauges") or [])
                 if isinstance(g.get("queue_depth"), (int, float))]
        row = {
            "rung": i,
            "offered_rps": rung.get("offered_rps"),
            "measured_offered_rps": summ.get("offered_rps"),
            "achieved_rps": summ.get("achieved_rps"),
            "goodput_rps": summ.get("goodput_rps"),
            "goodput_frac": summ.get("goodput_frac"),
            "p50_ms": lat.get("p50"), "p99_ms": lat.get("p99"),
            "max_ms": lat.get("max"),
            "shed_rate": summ.get("shed_rate"),
            "timeout_rate": summ.get("timeout_rate"),
            "unhealthy_rate": summ.get("unhealthy_rate"),
            "queue_depth_max": max(depth) if depth else None,
            "span_share": summ.get("span_share"),
        }
        curve.append(row)
    return curve


def detect_knee(curve: List[Dict[str, Any]],
                slo_p99_ms: Optional[float] = None,
                goodput_floor: float = 0.85,
                queue_depth_limit: Optional[float] = None
                ) -> Dict[str, Any]:
    """The saturation knee of a ladder curve: the FIRST rung (in
    offered-rate order) where

    * p99 latency breaches ``slo_p99_ms`` (when an SLO is set), or
    * goodput collapses below ``goodput_floor`` of the offered rate
      (the server is no longer keeping up — completions lag arrivals
      or requests are shed/timed out), or
    * the scraped queue depth exceeds ``queue_depth_limit`` (queue
      divergence — by Little's law an open-loop queue past saturation
      grows without bound; the scrape series catches it even while
      early percentiles still look fine).

    ``max_sustainable_rps`` is the best goodput of any rung BELOW the
    knee (the whole curve when no knee is found) — the round-over-round
    storm-gate metric."""
    rows = sorted([r for r in curve if r.get("offered_rps")],
                  key=lambda r: r["offered_rps"])
    knee = None
    reason = None
    for r in rows:
        if slo_p99_ms and r.get("p99_ms") is not None \
                and r["p99_ms"] > slo_p99_ms:
            knee, reason = r, "p99_slo_breach"
            break
        gf = r.get("goodput_frac")
        if gf is not None and gf < goodput_floor:
            knee, reason = r, "goodput_collapse"
            break
        qd = r.get("queue_depth_max")
        if queue_depth_limit and qd is not None \
                and qd > queue_depth_limit:
            knee, reason = r, "queue_divergence"
            break
    below = rows if knee is None \
        else [r for r in rows if r["offered_rps"] < knee["offered_rps"]]
    good = [r["goodput_rps"] for r in below
            if r.get("goodput_rps") is not None]
    return {
        "saturated": knee is not None,
        "reason": reason,
        "knee_offered_rps": knee["offered_rps"] if knee else None,
        "knee_rung": knee["rung"] if knee else None,
        "knee_p99_ms": knee.get("p99_ms") if knee else None,
        "max_sustainable_rps": round(max(good), 3) if good else None,
        "goodput_floor": goodput_floor,
        "slo_p99_ms": slo_p99_ms,
    }


def phase_attribution(curve: List[Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
    """Per-phase serve-span share as a function of offered load — the
    PR-8 request spans under traffic: a queue share that grows with the
    offered rate while solve share shrinks is the saturation signature
    (the device is busy; requests pay in line, not in compute)."""
    out = []
    for r in curve:
        share = r.get("span_share")
        if share:
            out.append({"offered_rps": r.get("offered_rps"),
                        "shares": share})
    return out


def gauge_rollup(series: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Rollups per scraped gauge across the storm's /metrics scrape
    time-series (rows ``{"t_s": .., <gauge>: value, ..}``)."""
    keys = set()
    for row in series:
        keys.update(k for k, v in row.items()
                    if k != "t_s" and isinstance(v, (int, float)))
    out: Dict[str, Any] = {"rows": len(series)}
    for k in sorted(keys):
        r = _metrics.rollup(row.get(k) for row in series)
        if r is not None:
            out[k] = r
    return out


def storm_timeline_trace(samples: List[Dict[str, Any]],
                         gauges: Optional[List[Dict[str, Any]]] = None,
                         pid: int = 0) -> Dict[str, Any]:
    """Chrome/Perfetto trace of a storm: per-tenant tracks of complete
    events spanning SCHEDULED arrival -> completion (so queueing is
    visible as event length), instant markers for sheds/timeouts, and
    counter tracks for every scraped gauge. Same trace-event shape as
    ``RequestSpans.to_chrome_trace`` — concatenate ``traceEvents`` to
    merge tracks."""
    events: List[Dict[str, Any]] = []
    tenants = sorted({s.get("tenant") or "t0" for s in samples})
    tid_of = {t: i + 1 for i, t in enumerate(tenants)}
    for t, tid in tid_of.items():
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": "storm/%s" % t}})
    for s in samples:
        tid = tid_of.get(s.get("tenant") or "t0", 0)
        ts = round(float(s.get("t_sched_s") or 0.0) * 1e6, 3)
        outcome = s.get("outcome")
        if outcome == "ok" and s.get("latency_ms") is not None:
            events.append({
                "name": s.get("phase") or "req",
                "cat": "amgcl/storm", "ph": "X", "ts": ts,
                "dur": round(float(s["latency_ms"]) * 1e3, 3),
                "pid": pid, "tid": tid,
                "args": {"rid": s.get("rid"),
                         "rate_rps": s.get("rate_rps"),
                         "lag_ms": s.get("lag_ms")}})
        elif outcome:
            events.append({
                "name": outcome, "cat": "amgcl/storm", "ph": "i",
                "s": "t", "ts": ts, "pid": pid, "tid": tid,
                "args": {"rid": s.get("rid")}})
    for row in gauges or []:
        ts = round(float(row.get("t_s") or 0.0) * 1e6, 3)
        for k, v in row.items():
            if k == "t_s" or not isinstance(v, (int, float)):
                continue
            events.append({"name": "storm/%s" % k, "ph": "C",
                           "ts": ts, "pid": pid, "args": {k: v}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def build_record(rungs: List[Dict[str, Any]],
                 slo_p99_ms: Optional[float] = None,
                 goodput_floor: float = 0.85,
                 queue_depth_limit: Optional[float] = None,
                 profile: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """The schema-versioned ``bench_storm`` record body from an
    offered-load ladder: curve, knee, aggregate goodput accounting,
    per-phase attribution, the reference-load row (the LOWEST offered
    rate — the gate's p99-at-reference-load comparison point), and the
    optional mixed-phase profile-storm summary."""
    curve = ladder_curve(rungs)
    knee = detect_knee(curve, slo_p99_ms=slo_p99_ms,
                       goodput_floor=goodput_floor,
                       queue_depth_limit=queue_depth_limit)
    total = sum((r.get("summary") or {}).get("requests", 0)
                for r in rungs)
    outcomes: Dict[str, int] = {}
    for r in rungs:
        for k, v in ((r.get("summary") or {}).get("outcomes")
                     or {}).items():
            outcomes[k] = outcomes.get(k, 0) + v
    good = outcomes.get("ok", 0)
    ref = None
    rows = [r for r in curve if r.get("offered_rps")]
    if rows:
        lo = min(rows, key=lambda r: r["offered_rps"])
        ref = {"offered_rps": lo["offered_rps"],
               "p50_ms": lo.get("p50_ms"), "p99_ms": lo.get("p99_ms"),
               "goodput_frac": lo.get("goodput_frac")}
    rec: Dict[str, Any] = {
        "schema": STORM_SCHEMA,
        "curve": curve,
        "knee": knee,
        "reference": ref,
        "goodput": {
            "requests": total,
            "ok": good,
            "outcomes": outcomes,
            "good_frac": round(good / total, 4) if total else None,
        },
        "attribution": phase_attribution(curve),
        "gauges": gauge_rollup([g for r in rungs
                                for g in (r.get("gauges") or [])]),
    }
    if profile is not None:
        rec["profile"] = profile
    return rec
