"""Measured distributed-communication attribution + per-shard imbalance.

The distributed solvers have carried *analytic* comm models since PR 2
(``ledger.comm_model`` / ``krylov_comm_model``) and static collective
contracts since PR 6 (``ledger.DIST_CG_COLLECTIVES``) — but nothing ever
*measured* where the wall time of a distributed iteration goes. HPCG's
lesson (PAPERS.md) is that the comm fraction is the quantity that
decides multi-chip viability, so this module is the mesh counterpart of
``telemetry/roofline.py``: it joins measured stage seconds to the comm
models the auditor already checks.

The measurement trick is **comm ablation**: every distributed stage is
timed twice from the same program skeleton — once with the real
collectives (ppermute ring / all_to_all slab / psum) and once with
*local stand-ins of identical shape and downstream compute*
(``dist_matrix._local_exchange`` et al.), so the difference of the two
device-synced medians is the collective's wall share, overlap included.
The stand-ins are numerically wrong at shard edges on purpose and are
never dispatched by a solve; the jaxpr auditor
(``analysis/jaxpr_audit.audit_comm_stages`` vs
``ledger.COMM_STAGE_CONTRACTS``) pins their collective census to
exactly 0 — an ablated variant that quietly kept a collective would
poison the subtraction.

Pieces:

* :func:`comm_stages` — the measured/ablated stage-pair plan for a
  distributed operator (``DistDiaMatrix`` ring halo / ``DistEllMatrix``
  all_to_all slab, the stacked psum, and one representative Krylov
  iteration per ``DIST_CG_COLLECTIVES`` body).
* :func:`measure_comm` / :func:`comm_attribution` — drive the pairs
  standalone under a device-synced profiler (the
  ``roofline.measure_stages`` discipline: compile + warmup outside the
  scopes, ``AMGCL_TPU_COMM_REPS`` reps) and join against the ledger
  models: achieved wire GB/s per collective, comm fraction per
  iteration, model-vs-measured divergence findings for
  ``telemetry.diagnose(comm=...)``.
* :func:`dist_resources` / :func:`shard_costs` / :func:`imbalance` —
  the per-shard side of the resource ledger: rows/nnz/halo-width/bytes
  per shard and the load-imbalance factor (max/mean shard cost).
* :func:`measure_shard_spread` — measured per-shard stage-time spread:
  each shard's local SpMV timed standalone under ``shard<i>/...``
  scopes (exported as a per-device Perfetto track group by
  ``cli.py --dist-report --trace``).
* :func:`hw_provenance` — the hardware stamp every bench/scaling record
  carries: device kind, mesh/topology shape, and the ICI vs
  CPU-fallback platform tag the gates key their platform-mismatch skip
  on.

Everything returned is JSON-clean; jax is imported lazily inside the
measurement functions (module import stays cheap).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from amgcl_tpu.telemetry import ledger as _ledger

#: collective census expected of each measured stage, keyed by the
#: stage's ``contract`` name — lives in ledger next to its siblings
COMM_STAGE_CONTRACTS = _ledger.COMM_STAGE_CONTRACTS


def comm_reps() -> int:
    """Timed repetitions per comm stage (``AMGCL_TPU_COMM_REPS``,
    default 5 — collective timings jitter more than kernel timings, the
    median needs a few samples)."""
    try:
        return max(int(os.environ.get("AMGCL_TPU_COMM_REPS", "5")), 1)
    except ValueError:
        return 5


# ---------------------------------------------------------------------------
# hardware provenance
# ---------------------------------------------------------------------------

def hw_provenance(mesh=None) -> Dict[str, Any]:
    """The hardware stamp of a measurement: device platform/kind, device
    counts, mesh shape, and ``platform_tag`` — ``"ici"`` on real TPU
    meshes (collectives ride the inter-chip interconnect) vs
    ``"cpu-fallback"`` on the host-virtual mesh (collectives are XLA
    shared-memory copies; absolute wire rates do NOT transfer to
    hardware). The gates use this for their platform-mismatch skip."""
    out: Dict[str, Any] = {"device_platform": None, "device_kind": None,
                           "device_count": None, "mesh_devices": None,
                           "mesh_shape": None, "platform_tag": None}
    try:
        import jax
        dev0 = jax.devices()[0]
        out["device_platform"] = dev0.platform
        out["device_kind"] = getattr(dev0, "device_kind", None)
        out["device_count"] = len(jax.devices())
    except Exception:
        return out
    if mesh is not None:
        try:
            out["mesh_devices"] = int(np.prod(list(mesh.shape.values())))
            out["mesh_shape"] = dict(mesh.shape)
        except Exception:
            pass
    out["platform_tag"] = "ici" if out["device_platform"] == "tpu" \
        else "cpu-fallback"
    return out


# ---------------------------------------------------------------------------
# per-shard imbalance (host-side, no measurement)
# ---------------------------------------------------------------------------

def imbalance(costs) -> Dict[str, Any]:
    """Load-imbalance summary of per-shard costs: ``factor`` is
    max/mean — 1.0 is perfectly balanced, 2.0 means the critical shard
    carries twice the average and the mesh runs at half its aggregate
    rate during that stage."""
    vals = [float(c) for c in costs if c is not None]
    if not vals or max(vals) <= 0:
        return {"max": 0.0, "mean": 0.0, "factor": 1.0}
    mean = sum(vals) / len(vals)
    return {"max": max(vals), "mean": round(mean, 6),
            "factor": round(max(vals) / mean, 4) if mean > 0 else 1.0}


def shard_costs(ptr, bounds) -> List[Dict[str, int]]:
    """Per-shard ``{shard, rows, nnz}`` of a CSR row partition: ``ptr``
    is the row pointer, ``bounds`` the partition boundaries
    ``[r0, r1, ..., rn]`` (len = shards + 1). This is the exact useful
    work per shard — a deliberately skewed strip partition shows up
    here, padding-uniform device buffers notwithstanding."""
    ptr = np.asarray(ptr)
    n = len(ptr) - 1
    out = []
    for s in range(len(bounds) - 1):
        r0 = min(max(int(bounds[s]), 0), n)
        r1 = min(max(int(bounds[s + 1]), r0), n)
        out.append({"shard": s, "rows": r1 - r0,
                    "nnz": int(ptr[r1] - ptr[r0])})
    return out


def even_bounds(n: int, nd: int, nloc: Optional[int] = None) -> List[int]:
    """Row-partition boundaries of the even (or ``nloc``-concentrated)
    strip split the distributed builders use: shard s owns rows
    ``[s*nloc, min((s+1)*nloc, n))`` — trailing shards may own nothing
    under a ``min_per_shard`` concentration."""
    nloc = -(-n // nd) if nloc is None else int(nloc)
    return [min(s * nloc, n) for s in range(nd + 1)]


def _dia_shard_rows(offsets, n: int, nd: int,
                    itemsize: int) -> List[Dict[str, Any]]:
    """Per-shard cost rows of an evenly strip-partitioned DIA operator,
    derived from the static structure alone: stored (padded) values,
    in-range values (the useful nnz — diagonals clip at the matrix
    edges, so edge shards carry slightly less), and the halo elements
    each shard exchanges per SpMV (interior shards both directions,
    edge shards one)."""
    offsets = tuple(int(o) for o in offsets)
    nloc = n // nd if nd and n % nd == 0 else -(-n // max(nd, 1))
    w = max(max(offsets), -min(offsets), 0) if offsets else 0
    out = []
    for s in range(nd):
        r0, r1 = s * nloc, min((s + 1) * nloc, n)
        nnz = 0
        for off in offsets:
            lo = max(r0, -off if off < 0 else 0)
            hi = min(r1, n - off if off > 0 else n)
            nnz += max(0, hi - lo)
        sides = 2 if 0 < s < nd - 1 else (1 if nd > 1 else 0)
        out.append({
            "shard": s, "rows": r1 - r0, "nnz": int(nnz),
            "stored_bytes": len(offsets) * (r1 - r0) * itemsize,
            "halo_elems": w * sides})
    return out


def dist_resources(A, nd: int) -> Optional[Dict[str, Any]]:
    """The per-shard ledger of one distributed operator — what rides
    ``SolveReport.resources["dist"]``: per-shard rows/nnz/bytes/halo
    rows, the load-imbalance factor over useful nnz, and the halo
    pattern. For ``DistEllMatrix`` the device buffers are
    padding-uniform by construction (every shard is padded to the same
    K slots), so the cost rows carry the padded slot count and the
    imbalance is reported over the padded cost — the *useful*-work
    imbalance of an uneven partition is visible through
    :func:`shard_costs` on the host CSR (dist_amg's ledger does that
    per level). None for operators with no distributed structure."""
    nd = int(nd)
    name = type(A).__name__
    if name == "DistDiaMatrix":
        itemsize = np.dtype(A.data.dtype).itemsize \
            if A.data is not None else 4
        rows = _dia_shard_rows(A.offsets, A.shape[0], nd, itemsize)
        return {
            "format": name, "devices": nd,
            "halo_width": int(A.halo), "pattern": "ring",
            "per_shard": rows,
            "imbalance": imbalance([r["nnz"] for r in rows]),
        }
    if name == "DistEllMatrix":
        itemsize = np.dtype(A.loc_vals.dtype).itemsize \
            if A.loc_vals is not None else 4
        k1 = int(A.loc_cols.shape[-1])
        k2 = int(A.rem_cols.shape[-1])
        c = int(A.send_idx.shape[-1]) if A.send_idx is not None else 0
        rows = [{"shard": s, "rows": A.nloc,
                 "padded_slots": A.nloc * (k1 + k2),
                 "stored_bytes": A.nloc * (k1 + k2) * itemsize,
                 "halo_elems": c * (nd - 1)}
                for s in range(nd)]
        return {
            "format": name, "devices": nd,
            "halo_slab": c, "pattern": "all_to_all",
            "per_shard": rows,
            "imbalance": imbalance([r["padded_slots"] for r in rows]),
            "padding_uniform": True,
        }
    return None


def level_shard_costs(host_csr, bounds) -> Dict[str, Any]:
    """One hierarchy level's useful-work shard table: exact per-shard
    rows/nnz from the host CSR at the EXECUTED partition (``bounds``
    from :func:`even_bounds`, min_per_shard concentration included) +
    the imbalance factor over nnz."""
    rows = shard_costs(host_csr.ptr, bounds)
    return {"per_shard": rows,
            "imbalance": imbalance([r["nnz"] for r in rows])}


# ---------------------------------------------------------------------------
# measured stages: comm-ablated pairs
# ---------------------------------------------------------------------------

def _rand_sharded(mesh, n, dtype, seed):
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from amgcl_tpu.parallel.mesh import ROWS_AXIS, put_with_sharding
    v = np.random.RandomState(seed).standard_normal(n)
    return put_with_sharding(
        np.asarray(v, np.dtype(jnp.dtype(dtype))),
        NamedSharding(mesh, P(ROWS_AXIS)))


def _iter_leg(spmv, r, x, di, pipelined: bool, ablate: bool):
    """ONE representative Jacobi-CG iteration leg, shared by the DIA and
    ELL stage builders so both measure the same program their
    ``COMM_STAGE_CONTRACTS`` entries describe — collective for
    collective the ``DIST_CG_COLLECTIVES`` body: classical = 3 scalar
    psums, pipelined = ONE stacked 3-element psum; the halo SpMV rides
    ``spmv``. ``ablate`` drops every psum (the halo ablation happens
    inside the caller's ``spmv``). Returns (x_n, r_n, rr(1,))."""
    import jax.numpy as jnp
    from jax import lax
    from amgcl_tpu.parallel.mesh import ROWS_AXIS
    s = di * r
    q = spmv(s)
    if pipelined:
        g = jnp.stack([jnp.vdot(r, s), jnp.vdot(q, s),
                       jnp.vdot(r, r)])
        if not ablate:
            g = lax.psum(g, ROWS_AXIS)
        rho, qp, rr = g[0], g[1], g[2]
    else:
        def dot(a, b):
            v = jnp.vdot(a, b)
            return v if ablate else lax.psum(v, ROWS_AXIS)
        rho = dot(r, s)
        qp = dot(q, s)
        alpha0 = rho / jnp.where(qp == 0, 1.0, qp)
        rr = dot(r - alpha0 * q, r - alpha0 * q)
    alpha = rho / jnp.where(qp == 0, 1.0, qp)
    return x + alpha * s, r - alpha * q, jnp.reshape(rr, (1,))


def _dia_stages(A, mesh, pipelined: bool) -> List[Dict[str, Any]]:
    from jax.sharding import PartitionSpec as P
    from amgcl_tpu.parallel.compat import shard_map
    from amgcl_tpu.parallel.mesh import ROWS_AXIS
    from amgcl_tpu.parallel import dist_matrix as DM
    from amgcl_tpu.telemetry.compile_watch import watched_jit

    offsets = tuple(A.offsets)
    nd = int(mesh.shape[ROWS_AXIS])
    n = int(A.shape[0])
    dtype = A.data.dtype
    itemsize = np.dtype(dtype).itemsize
    vspec = P(ROWS_AXIS)
    dspec = P(None, ROWS_AXIS)
    x = _rand_sharded(mesh, n, dtype, 0)
    f = _rand_sharded(mesh, n, dtype, 1)
    di = _rand_sharded(mesh, n, dtype, 2)

    def spmv_of(ablate):
        ex = DM._local_exchange if ablate else DM._ring_exchange
        ga = DM._gather_local if ablate else DM._gather_ring
        return lambda d, v: DM.dia_halo_mv(d, offsets, v,
                                           exchange=ex, gather=ga)

    def halo_fn(ablate):
        body = spmv_of(ablate)
        return shard_map(body, mesh=mesh, in_specs=(dspec, vspec),
                         out_specs=vspec, check_vma=False)

    def iter_fn(ablate):
        spmv = spmv_of(ablate)

        def body(d, ff, xx, dd):
            return _iter_leg(lambda v: spmv(d, v), ff, xx, dd,
                             pipelined, ablate)

        out3 = (vspec, vspec, vspec if ablate else P())
        return shard_map(body, mesh=mesh,
                         in_specs=(dspec, vspec, vspec, vspec),
                         out_specs=out3, check_vma=False)

    halo = watched_jit(halo_fn(False), name="telemetry.comm_halo")
    halo_ab = watched_jit(halo_fn(True),
                          name="telemetry.comm_halo_ablated")
    it = watched_jit(iter_fn(False), name="telemetry.comm_iter")
    it_ab = watched_jit(iter_fn(True),
                        name="telemetry.comm_iter_ablated")
    halo_model = A.halo_comm(nd) or {"msgs": 0, "bytes": 0}
    elems = 3 if pipelined else 1
    stages = [
        {"key": "halo", "contract": "halo_dia",
         "fn": halo, "fn_ablated": halo_ab, "args": (A.data, x),
         "model": halo_model},
        _psum_stage(mesh, n, dtype, elems),
        {"key": "iteration",
         "contract": "iter_pipelined_dia" if pipelined
         else "iter_classical_dia",
         "fn": it, "fn_ablated": it_ab, "args": (A.data, f, x, di),
         "model": _ledger.krylov_comm_model(
             halo_model, nd, itemsize, spmvs=1,
             dots=1 if pipelined else 3, elems_per_dot=elems)},
    ]
    return stages


def _ell_stages(A, mesh, pipelined: bool) -> List[Dict[str, Any]]:
    from jax.sharding import PartitionSpec as P
    from amgcl_tpu.parallel.compat import shard_map
    from amgcl_tpu.parallel.mesh import ROWS_AXIS
    from amgcl_tpu.telemetry.compile_watch import watched_jit

    nd = int(mesh.shape[ROWS_AXIS])
    n = int(A.shape[0])
    dtype = A.loc_vals.dtype
    itemsize = np.dtype(dtype).itemsize
    vspec = P(ROWS_AXIS)
    specs = A.specs()
    x = _rand_sharded(mesh, n, dtype, 0)
    f = _rand_sharded(mesh, n, dtype, 1)
    di = _rand_sharded(mesh, n, dtype, 2)
    ident = lambda send: send          # the all_to_all stand-in

    def halo_fn(ablate):
        def body(Ae, v):
            return Ae.shard_mv(v, exchange=ident if ablate else None)
        return shard_map(body, mesh=mesh, in_specs=(specs, vspec),
                         out_specs=vspec, check_vma=False)

    def iter_fn(ablate):
        def body(Ae, ff, xx, dd):
            return _iter_leg(
                lambda v: Ae.shard_mv(
                    v, exchange=ident if ablate else None),
                ff, xx, dd, pipelined, ablate)

        out3 = (vspec, vspec, vspec if ablate else P())
        return shard_map(body, mesh=mesh,
                         in_specs=(specs, vspec, vspec, vspec),
                         out_specs=out3, check_vma=False)

    halo = watched_jit(halo_fn(False), name="telemetry.comm_halo")
    halo_ab = watched_jit(halo_fn(True),
                          name="telemetry.comm_halo_ablated")
    it = watched_jit(iter_fn(False), name="telemetry.comm_iter")
    it_ab = watched_jit(iter_fn(True),
                        name="telemetry.comm_iter_ablated")
    halo_model = A.halo_comm(nd) or {"msgs": 0, "bytes": 0}
    elems = 3 if pipelined else 1
    return [
        {"key": "halo", "contract": "halo_ell",
         "fn": halo, "fn_ablated": halo_ab, "args": (A, x),
         "model": halo_model},
        _psum_stage(mesh, n, dtype, elems),
        {"key": "iteration",
         "contract": "iter_pipelined_ell" if pipelined
         else "iter_classical_ell",
         "fn": it, "fn_ablated": it_ab, "args": (A, f, x, di),
         "model": _ledger.krylov_comm_model(
             halo_model, nd, itemsize, spmvs=1,
             dots=1 if pipelined else 3, elems_per_dot=elems)},
    ]


def _psum_stage(mesh, n, dtype, elems: int) -> Dict[str, Any]:
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from amgcl_tpu.parallel.compat import shard_map
    from amgcl_tpu.parallel.mesh import ROWS_AXIS
    from amgcl_tpu.telemetry.compile_watch import watched_jit

    nd = int(mesh.shape[ROWS_AXIS])
    itemsize = np.dtype(dtype).itemsize
    vspec = P(ROWS_AXIS)
    x = _rand_sharded(mesh, n, dtype, 3)
    y = _rand_sharded(mesh, n, dtype, 4)

    def fn(ablate):
        def body(a, b):
            parts = jnp.stack([jnp.vdot(a, b), jnp.vdot(a, a),
                               jnp.vdot(b, b)][:elems])
            return parts if ablate else lax.psum(parts, ROWS_AXIS)
        return shard_map(body, mesh=mesh, in_specs=(vspec, vspec),
                         out_specs=vspec if ablate else P(),
                         check_vma=False)

    return {"key": "psum", "contract": "psum",
            "fn": watched_jit(fn(False), name="telemetry.comm_psum"),
            "fn_ablated": watched_jit(
                fn(True), name="telemetry.comm_psum_ablated"),
            "args": (x, y), "elems": elems,
            "model": _ledger.allreduce_model(nd, elems, itemsize)}


def comm_stages(A, mesh, pipelined: bool = False) -> List[Dict[str, Any]]:
    """The measured/ablated stage-pair plan for one distributed
    operator: halo SpMV, stacked psum, and one representative Krylov
    iteration (classical 3-psum or pipelined merged-reduction body per
    ``pipelined``). Each entry carries the two jitted variants, concrete
    sharded args, the contract key the auditor checks the traced pair
    against, and the ledger wire model of the real variant."""
    name = type(A).__name__
    if name == "DistDiaMatrix":
        return _dia_stages(A, mesh, pipelined)
    if name == "DistEllMatrix":
        return _ell_stages(A, mesh, pipelined)
    raise TypeError("no comm stages for operator type %r" % name)


# ---------------------------------------------------------------------------
# measurement + the model join
# ---------------------------------------------------------------------------

def measure_comm(A, mesh, reps: Optional[int] = None, prof=None,
                 pipelined: bool = False) -> Dict[str, Any]:
    """Time every stage pair standalone under a device-synced profiler
    (compile + warmup OUTSIDE the scopes, ``reps`` reps each at
    ``comm/<stage>`` / ``comm/<stage>_ablated``) and reduce to per-stage
    rows: the MEDIAN measured vs ablated microseconds, the collective
    wall share
    ``comm_us = max(measured − ablated, 0)`` (the two variants partition
    the stage by construction), comm fraction, the ledger wire model,
    and achieved wire GB/s where the share is resolvable."""
    import time as _time
    import jax
    from amgcl_tpu.utils.profiler import Profiler
    reps = comm_reps() if reps is None else max(int(reps), 1)
    prof = prof if prof is not None else Profiler.device()
    stages = comm_stages(A, mesh, pipelined=pipelined)
    # per-rep durations collected alongside the profiler scopes: the
    # reported numbers are MEDIANS (one GC/scheduler outlier in either
    # arm must not flip the ablation subtraction — the jitter is why
    # comm_reps() takes several samples); the scope tree keeps the
    # per-occurrence events for the Perfetto export
    medians: Dict[str, float] = {}
    for st in stages:
        for ablate in (False, True):
            fn = st["fn_ablated"] if ablate else st["fn"]
            jax.block_until_ready(fn(*st["args"]))     # compile + warm
            scope = st["key"] + ("_ablated" if ablate else "")
            ts = []
            for _ in range(reps):
                t0 = _time.perf_counter()
                with prof.scope("comm"):
                    with prof.scope(scope):
                        jax.block_until_ready(fn(*st["args"]))
                ts.append(_time.perf_counter() - t0)
            medians[scope] = float(np.median(ts))
    rows: List[Dict[str, Any]] = []
    for st in stages:
        t = medians.get(st["key"], 0.0)
        ta = medians.get(st["key"] + "_ablated", 0.0)
        comm_s = max(t - ta, 0.0)
        if not (st["model"] or {}).get("msgs"):
            # no modeled comm (single shard / zero halo): the pair is
            # structurally identical and any difference is jitter, not
            # a collective — report the zero the structure implies
            comm_s = 0.0
        row: Dict[str, Any] = {
            "stage": st["key"], "contract": st["contract"],
            "t_us": round(t * 1e6, 3),
            "ablated_us": round(ta * 1e6, 3),
            "comm_us": round(comm_s * 1e6, 3),
            "comm_fraction": round(comm_s / t, 4) if t > 0 else 0.0,
            "model": st["model"],
        }
        wire_bytes = (st["model"] or {}).get("bytes", 0)
        if comm_s > 0 and wire_bytes:
            row["wire_gbps"] = round(wire_bytes / comm_s / 1e9, 3)
        rows.append(row)
    from amgcl_tpu.parallel.mesh import ROWS_AXIS
    return {"devices": int(mesh.shape[ROWS_AXIS]),
            "reps": reps, "pipelined": bool(pipelined),
            "rows": rows, "_prof": prof}


def comm_attribution(A, mesh, solver: Optional[str] = None,
                     reps: Optional[int] = None,
                     prof=None) -> Dict[str, Any]:
    """The join: measured comm seconds vs the PR-2 comm models, per
    collective and per iteration, for the distributed Krylov body named
    by ``solver`` (``dist_cg`` / ``dist_cg_pipelined``; None reads the
    ``AMGCL_TPU_PIPELINED_CG`` dispatch like the solver itself). Returns
    a JSON-clean record with ``per_iteration`` carrying the headline
    numbers (comm fraction, achieved wire GB/s against the ICI peak
    where one is known) and ``findings`` carrying the divergence
    diagnostics ``telemetry.diagnose(comm=...)`` folds in."""
    from amgcl_tpu.parallel.mesh import ROWS_AXIS
    if solver is None:
        from amgcl_tpu.parallel.dist_solver import pipelined_cg_enabled
        solver = "dist_cg_pipelined" if pipelined_cg_enabled() \
            else "dist_cg"
    pipelined = solver == "dist_cg_pipelined"
    contract = _ledger.DIST_CG_COLLECTIVES[solver]
    meas = measure_comm(A, mesh, reps=reps, prof=prof,
                        pipelined=pipelined)
    nd = meas["devices"]
    by_key = {r["stage"]: r for r in meas["rows"]}
    it = by_key.get("iteration", {})
    halo = by_key.get("halo", {})
    psum = by_key.get("psum", {})
    stage_sum_us = (halo.get("comm_us", 0.0) * contract["spmvs"]
                    + psum.get("comm_us", 0.0) * contract["psums"])
    itemsize = 4
    try:
        itemsize = np.dtype(
            A.data.dtype if hasattr(A, "data") and A.data is not None
            else A.loc_vals.dtype).itemsize
    except Exception:
        pass
    model = _ledger.krylov_comm_model(
        _ledger.comm_model(A, nd), nd, itemsize,
        spmvs=contract["spmvs"], dots=contract["psums"],
        elems_per_dot=contract["elems_per_psum"])
    from amgcl_tpu.telemetry.roofline import ici_peak_gbps
    peak = ici_peak_gbps()
    per_iter: Dict[str, Any] = {
        "t_us": it.get("t_us"),
        "comm_us": it.get("comm_us"),
        "comm_fraction": it.get("comm_fraction"),
        "stage_sum_comm_us": round(stage_sum_us, 3),
        "model": model,
        "collectives": dict(contract),
    }
    comm_s = (it.get("comm_us") or 0.0) / 1e6
    if comm_s > 0 and model["bytes"]:
        per_iter["wire_gbps"] = round(model["bytes"] / comm_s / 1e9, 3)
    if peak is not None:
        per_iter["ici_peak_gbps"] = peak
        if per_iter.get("wire_gbps"):
            per_iter["frac_ici_peak"] = round(
                per_iter["wire_gbps"] / peak, 4)
    rec = {"solver": solver, "devices": nd,
           "provenance": hw_provenance(mesh),
           "stages": meas["rows"], "per_iteration": per_iter,
           "_prof": meas["_prof"]}
    rec["findings"] = comm_findings(rec)
    return rec


def comm_findings(rec: Dict[str, Any],
                  comm_bound_threshold: float = 0.5) -> List[Dict[str, Any]]:
    """Model-vs-measured divergence findings from one attribution record
    (``telemetry.diagnose()`` shape: severity/code/message/suggestion).
    Ranked: comm-bound iterations first, then wire-rate divergence from
    the ICI peak, then the provenance caveat on host-virtual meshes."""
    out: List[Dict[str, Any]] = []
    pi = rec.get("per_iteration") or {}
    frac = pi.get("comm_fraction")
    prov = rec.get("provenance") or {}
    if frac is not None and frac >= comm_bound_threshold:
        out.append({
            "severity": "warning", "code": "comm_bound",
            "message": "distributed iteration is %.0f%% collective wall "
                       "time (%s devices, %s body)"
                       % (100 * frac, rec.get("devices"),
                          rec.get("solver")),
            "suggestion": "merge reductions (dist_cg_pipelined psums "
                          "ONE stacked 3-vector/iter — "
                          "AMGCL_TPU_PIPELINED_CG=1), widen shards "
                          "(fewer devices per problem), or narrow the "
                          "band to shrink the halo"})
    peak = pi.get("ici_peak_gbps")
    wire = pi.get("wire_gbps")
    if peak and wire is not None:
        if wire < 0.05 * peak:
            out.append({
                "severity": "warning", "code": "comm_divergence",
                "message": "measured collective wire rate %.2f GB/s is "
                           "%.1f%% of the ICI peak (%.0f GB/s) — the "
                           "comm model's wire bytes and the measured "
                           "seconds diverge"
                           % (wire, 100 * wire / peak, peak),
                "suggestion": "small messages are latency-bound, not "
                              "bandwidth-bound: check message sizes in "
                              "the comm model, collective overlap "
                              "(the data-independent ordering), and "
                              "per-collective dispatch overhead"})
        elif wire > 1.5 * peak:
            out.append({
                "severity": "info", "code": "comm_overlapped",
                "message": "apparent wire rate %.0f GB/s exceeds the "
                           "ICI peak — the scheduler hides the "
                           "exchange behind local compute (the "
                           "ablation measures only the exposed "
                           "fraction)" % wire,
                "suggestion": None})
    if prov.get("platform_tag") == "cpu-fallback":
        out.append({
            "severity": "info", "code": "comm_platform",
            "message": "comm measured on the host-virtual mesh "
                       "(collectives are XLA shared-memory copies, "
                       "not ICI) — fractions are indicative, absolute "
                       "wire rates are not",
            "suggestion": "re-run on a TPU mesh for hardware numbers; "
                          "the gate skips cross-platform comparisons "
                          "via the provenance tag"})
    rows = rec.get("stages") or []
    if rows and all((r.get("comm_us") or 0) == 0 for r in rows):
        out.append({
            "severity": "info", "code": "comm_noise",
            "message": "every measured collective share is 0 — the "
                       "ablation difference is below timing noise on "
                       "this mesh",
            "suggestion": "raise AMGCL_TPU_COMM_REPS for more samples"})
    return out


# ---------------------------------------------------------------------------
# measured per-shard spread
# ---------------------------------------------------------------------------

def measure_shard_spread(A, mesh, reps: Optional[int] = None,
                         prof=None) -> Optional[Dict[str, Any]]:
    """Measured per-shard stage-time spread: each shard's LOCAL SpMV
    work timed standalone (no collectives) under ``shard<i>/spmv``
    scopes — the measured counterpart of the structural imbalance
    tables, and the per-device Perfetto track group
    (``cli.py --dist-report --trace``). DistDiaMatrix only (the ELL
    buffers are padding-uniform, every shard runs the same slot count
    by construction); None when the operator has no per-shard split."""
    if type(A).__name__ != "DistDiaMatrix":
        return None
    import time as _time
    import jax
    import jax.numpy as jnp
    from jax import lax
    from amgcl_tpu.parallel.mesh import ROWS_AXIS
    from amgcl_tpu.utils.profiler import Profiler
    reps = comm_reps() if reps is None else max(int(reps), 1)
    prof = prof if prof is not None else Profiler.device()
    nd = int(mesh.shape[ROWS_AXIS])
    offsets = tuple(A.offsets)
    w = int(A.halo)
    n = int(A.shape[0])
    nloc = n // nd
    data = np.asarray(A.data)

    def local_mv(d, v):
        xe = jnp.pad(v, (w, w))
        y = jnp.zeros(v.shape[0], jnp.result_type(d.dtype, v.dtype))
        for k, s in enumerate(offsets):
            y = y + d[k] * lax.dynamic_slice(xe, (w + s,), (nloc,))
        return y

    from amgcl_tpu.telemetry.compile_watch import watched_jit
    jf = watched_jit(local_mv, name="telemetry.comm_shard_spmv")
    rng = np.random.RandomState(0)
    per = []
    for s in range(nd):
        d_s = jnp.asarray(data[:, s * nloc:(s + 1) * nloc])
        x_s = jnp.asarray(rng.standard_normal(nloc), d_s.dtype)
        jax.block_until_ready(jf(d_s, x_s))            # compile + warm
        ts = []
        for _ in range(reps):
            t0 = _time.perf_counter()
            with prof.scope("shard%d" % s):
                with prof.scope("spmv"):
                    jax.block_until_ready(jf(d_s, x_s))
            ts.append(_time.perf_counter() - t0)
        per.append(float(np.median(ts)))               # outlier-robust
    return {"per_shard_us": [round(t * 1e6, 3) for t in per],
            "spread": imbalance(per), "_prof": prof}


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def format_dist_report(dist: Optional[Dict[str, Any]],
                       spread: Optional[Dict[str, Any]] = None) -> str:
    """Human-readable per-shard table (the CLI's ``--dist-report``)."""
    if not dist:
        return "(no per-shard ledger: operator exposes no " \
               "distributed structure)"
    lines = ["Per-shard ledger (%s, %d devices, %s halo):"
             % (dist.get("format"), dist.get("devices", 0),
                dist.get("pattern"))]
    lines.append("shard     rows        nnz/slots     halo elems"
                 "   measured us")
    lines.append("-" * 62)
    per_us = (spread or {}).get("per_shard_us") or []
    for r in dist.get("per_shard", []):
        s = r["shard"]
        lines.append("%5d %8d %16s %12s %12s" % (
            s, r.get("rows", 0),
            r.get("nnz", r.get("padded_slots", "-")),
            r.get("halo_elems", "-"),
            ("%.1f" % per_us[s]) if s < len(per_us) else "-"))
    lines.append("-" * 62)
    imb = dist.get("imbalance") or {}
    lines.append("load imbalance (max/mean shard cost): %.3f%s"
                 % (imb.get("factor", 1.0),
                    "  [padding-uniform device buffers]"
                    if dist.get("padding_uniform") else ""))
    if spread:
        lines.append("measured spmv spread (max/mean shard time): %.3f"
                     % spread["spread"]["factor"])
    return "\n".join(lines)


def format_comm(rec: Dict[str, Any]) -> str:
    """Human-readable comm attribution (the CLI's ``--dist-report``)."""
    lines = ["Comm attribution (%s body, %d devices, measured via "
             "comm-ablated stand-ins):"
             % (rec.get("solver"), rec.get("devices", 0))]
    lines.append("stage        measured us   ablated us     comm us"
                 "   comm frac   wire GB/s")
    lines.append("-" * 76)
    for r in rec.get("stages", []):
        lines.append("%-12s %12.1f %12.1f %11.1f %11.3f %11s" % (
            r["stage"], r["t_us"], r["ablated_us"], r["comm_us"],
            r["comm_fraction"],
            ("%.2f" % r["wire_gbps"]) if r.get("wire_gbps") else "-"))
    pi = rec.get("per_iteration") or {}
    lines.append("-" * 76)
    model = pi.get("model") or {}
    lines.append(
        "per iteration: %.1f us, comm fraction %.3f  (model: %d msgs / "
        "%s wire bytes%s)" % (
            pi.get("t_us") or 0.0, pi.get("comm_fraction") or 0.0,
            model.get("msgs", 0), model.get("bytes", 0),
            (", %.1f%% of ICI peak" % (100 * pi["frac_ici_peak"]))
            if pi.get("frac_ici_peak") is not None else ""))
    for f in rec.get("findings", []):
        lines.append("  [%s] %s" % (f["severity"].upper(), f["message"]))
        if f.get("suggestion"):
            lines.append("      -> %s" % f["suggestion"])
    return "\n".join(lines)
