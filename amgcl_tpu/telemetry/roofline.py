"""Roofline attribution — joining measured stage times to the resource
models.

PR 1 gave us *how long* a stage took (utils/profiler scopes), PR 2 *how
much* it should have cost (ledger.cycle_cost_model's per-stage FLOPs and
HBM bytes). This module joins the two into the number that actually says
whether a memory-bound sparse kernel is healthy: achieved GB/s (and
GFLOP/s) per V-cycle stage, per level, and per Krylov iteration, against
the device's peaks:

* :func:`device_peaks` — HBM GB/s + peak FLOP/s per platform:
  a public-figure table for TPUs (keyed on ``device_kind``, same table
  family as bench.py's), ``AMGCL_TPU_PEAK_GBPS`` / ``AMGCL_TPU_PEAK_FLOPS``
  env overrides for anything, and a MEASURED fallback on CPU/unknown
  backends (a stream triad for bandwidth, one dense matmul for FLOPs) so
  roofline fractions stay meaningful in CPU CI instead of comparing
  against a TPU number.
* :func:`measure_stages` — drive every stage of one multigrid cycle
  (mirroring ``Hierarchy.cycle``, fused legs included) standalone under a
  device-synced profiler, one scope occurrence per repetition at
  ``level<i>/<stage>``.
* :func:`roofline` — the join: per-stage achieved GB/s / GFLOP/s,
  arithmetic intensity, compute- vs memory-bound classification against
  the machine balance, fraction of the governing peak, and ranked
  bottleneck findings for ``telemetry.diagnose()``.
* :func:`xla_stage_check` — per-stage cross-check of the model bytes
  against XLA's own compiled cost analysis (``cli.py --roofline`` prints
  it). The model is a streaming floor: gather/roll-paying lowerings
  (DIA on CPU XLA) legitimately report more bytes accessed; dense and
  scaled-residual stages agree to ~1%.
* :func:`solve_roofline` — the per-Krylov-iteration variant from one
  solve's wall time and the ledger's iteration model
  (``SolveReport.resources["roofline"]``). The iteration model prices
  the fused tiers at their single-stream cost (fused V-cycle legs via
  ``cycle_cost_model``'s ``down_fused``/``up_fused`` rows, fused vector
  algebra via ``KRYLOV_VEC_STREAMS_FUSED``) — no double counting of
  intermediates the fused kernels never write, so achieved-GB/s numbers
  stay honest as kernels merge.
* :func:`counter_map` — the achieved-GB/s counter track for
  ``Profiler.to_chrome_trace(counters=...)``.

Everything returned is JSON-clean. Measurement reps:
``AMGCL_TPU_ROOFLINE_REPS`` (default 3).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from amgcl_tpu.telemetry import ledger as _ledger

#: (device_kind substring, HBM GB/s, dense-peak FLOP/s) — public figures;
#: the FLOPs column is the dense-unit (MXU) peak, i.e. an upper bound a
#: sparse kernel will not approach: the roofline's compute ceiling, not a
#: target. Substring order matters (v5p before v5).
TPU_PEAKS = [
    ("v6", 1640.0, 918e12),
    ("v5p", 2765.0, 459e12),
    ("v5 lite", 819.0, 197e12),
    ("v5e", 819.0, 197e12),
    ("v5", 2765.0, 459e12),
    ("v4", 1228.0, 275e12),
    ("v3", 900.0, 123e12),
    ("v2", 700.0, 45e12),
]


def _env_float(name: str) -> Optional[float]:
    v = os.environ.get(name)
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


def _measure_stream_gbps(n: int = 1 << 23, reps: int = 5) -> float:
    """STREAM-triad bandwidth of the default device: ``a + 2.5 b`` over
    two ``n``-element f32 arrays (3 streams = 12n bytes), median of
    ``reps`` synced runs."""
    import time
    import jax
    import jax.numpy as jnp
    a = jnp.ones(n, jnp.float32)
    b = jnp.full(n, 0.5, jnp.float32)
    f = jax.jit(lambda a, b: a + 2.5 * b)
    jax.block_until_ready(f(a, b))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(a, b))
        ts.append(time.perf_counter() - t0)
    return 12.0 * n / float(np.median(ts)) / 1e9


def _measure_matmul_flops(m: int = 768, reps: int = 5) -> float:
    """Dense f32 matmul FLOP/s of the default device — the measured
    compute ceiling for the CPU fallback."""
    import time
    import jax
    import jax.numpy as jnp
    A = jnp.ones((m, m), jnp.float32)
    f = jax.jit(lambda A: A @ A)
    jax.block_until_ready(f(A))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(A))
        ts.append(time.perf_counter() - t0)
    return 2.0 * m ** 3 / float(np.median(ts))


_peaks_cache: Optional[Dict[str, Any]] = None


def device_peaks(refresh: bool = False) -> Dict[str, Any]:
    """``{"gbps", "flops", "platform", "device_kind", "source"}`` for the
    default device. Resolution order per number: env override
    (``AMGCL_TPU_PEAK_GBPS`` in GB/s, ``AMGCL_TPU_PEAK_FLOPS`` in
    FLOP/s), the TPU table, a one-time measured fallback (cached
    process-global — the stream/matmul probes cost ~0.1 s once)."""
    global _peaks_cache
    if _peaks_cache is not None and not refresh:
        return _peaks_cache
    out: Dict[str, Any] = {"gbps": None, "flops": None,
                           "platform": None, "device_kind": None,
                           "source": {}}
    try:
        import jax
        dev0 = jax.devices()[0]
        out["platform"] = dev0.platform
        out["device_kind"] = getattr(dev0, "device_kind", None)
    except Exception:
        pass
    env_g = _env_float("AMGCL_TPU_PEAK_GBPS")
    env_f = _env_float("AMGCL_TPU_PEAK_FLOPS")
    if env_g is not None:
        out["gbps"], out["source"]["gbps"] = env_g, "env"
    if env_f is not None:
        out["flops"], out["source"]["flops"] = env_f, "env"
    kind = (out["device_kind"] or "").lower()
    if out["platform"] == "tpu":
        for key, gbps, flops in TPU_PEAKS:
            if key in kind:
                if out["gbps"] is None:
                    out["gbps"], out["source"]["gbps"] = gbps, "table"
                if out["flops"] is None:
                    out["flops"], out["source"]["flops"] = flops, "table"
                break
    if out["gbps"] is None:
        try:
            out["gbps"] = round(_measure_stream_gbps(), 2)
            out["source"]["gbps"] = "measured-stream"
        except Exception:
            pass
    if out["flops"] is None:
        try:
            out["flops"] = float("%.4g" % _measure_matmul_flops())
            out["source"]["flops"] = "measured-matmul"
        except Exception:
            pass
    _peaks_cache = out
    return out


#: (device_kind substring, aggregate per-chip ICI GB/s) — the public
#: Cloud figures (total inter-chip interconnect bandwidth per chip), the
#: wire ceiling for the comm attribution (telemetry/comm.py). Substring
#: order matters (v5p before v5).
TPU_ICI_GBPS = [
    ("v6", 448.0),
    ("v5p", 600.0),
    ("v5 lite", 200.0),
    ("v5e", 200.0),
    ("v4", 300.0),
]


def ici_peak_gbps() -> Optional[float]:
    """Aggregate per-chip ICI bandwidth ceiling: env override
    (``AMGCL_TPU_PEAK_ICI_GBPS``) first, then the public-figure table by
    ``device_kind``; None on CPU/unknown backends — a host-virtual mesh
    moves collectives through shared memory and has no meaningful wire
    peak (the comm attribution tags those runs via provenance instead
    of comparing against a fictitious number)."""
    env = _env_float("AMGCL_TPU_PEAK_ICI_GBPS")
    if env is not None:
        return env
    pk = device_peaks()
    if pk.get("platform") != "tpu":
        return None
    kind = (pk.get("device_kind") or "").lower()
    for key, gbps in TPU_ICI_GBPS:
        if key in kind:
            return gbps
    return None


# ---------------------------------------------------------------------------
# stage measurement
# ---------------------------------------------------------------------------

def _stage_plan(hier, seed: int = 0) -> List[Tuple[int, str, Any, tuple]]:
    """``[(level, stage, fn, args)]`` mirroring exactly the work
    ``Hierarchy.cycle`` runs per stage — fused down/up legs included when
    engaged, so what gets measured is what the solve runs. ``fn`` takes
    the hierarchy as its first argument (jit argument, not closure
    constant). Inputs chain level to level (the restricted rhs feeds the
    next level) so shapes and sparsity are the real ones."""
    import jax
    import jax.numpy as jnp
    from amgcl_tpu.ops import device as dev

    plan: List[Tuple[int, str, Any, tuple]] = []
    levels = hier.levels
    nl = len(levels)
    rng = np.random.RandomState(seed)

    def rand_vec(n, dtype):
        return jnp.asarray(rng.standard_normal(n), dtype)

    f = None
    for i, lv in enumerate(levels):
        A = lv.A
        if A is None:                 # device_filter placeholder level
            continue
        n, _ = _ledger._vec_dims(A)
        if f is None or int(f.shape[0]) != n:
            f = rand_vec(n, A.dtype)
        if i == nl - 1:
            if hier.coarse is not None:
                def coarse_f(h, ff):
                    return h.coarse.solve(ff)
            else:
                def coarse_f(h, ff, i=i):
                    return h.levels[i].relax.apply(h.levels[i].A, ff)
            plan.append((i, "coarse_solve", coarse_f, (f,)))
            break
        fused_down = (hier.npre == 1 and lv.down is not None
                      and getattr(lv.down, "w", None) is not None)
        if fused_down:
            def down_f(h, ff, i=i):
                return h.levels[i].down.zero(ff)
            plan.append((i, "down_fused", down_f, (f,)))
            u, fc = jax.jit(down_f)(hier, f)
        else:
            def pre_f(h, ff, i=i):
                lvl = h.levels[i]
                if h.npre > 0:
                    u = lvl.relax.apply(lvl.A, ff)
                    for _ in range(h.npre - 1):
                        u = lvl.relax.apply_pre(lvl.A, ff, u)
                else:
                    u = dev.clear(ff)
                return u
            plan.append((i, "pre_smooth", pre_f, (f,)))
            u = jax.jit(pre_f)(hier, f)
            if lv.down is not None:
                def res_f(h, ff, uu, i=i):
                    return h.levels[i].down(ff, uu)
            else:
                def res_f(h, ff, uu, i=i):
                    lvl = h.levels[i]
                    return dev.spmv(lvl.R, dev.residual(ff, lvl.A, uu))
            plan.append((i, "restrict", res_f, (f, u)))
            fc = jax.jit(res_f)(hier, f, u)
        uc = rand_vec(int(fc.shape[0]), fc.dtype)
        if lv.up is not None and hier.npost >= 1:
            def up_f(h, ff, uu, ucc, i=i):
                return h.levels[i].up(ff, uu, ucc)
            plan.append((i, "up_fused", up_f, (f, u, uc)))
            extra = hier.npost - 1
        else:
            def pro_f(h, uu, ucc, i=i):
                return uu + dev.spmv(h.levels[i].P, ucc)
            plan.append((i, "prolong", pro_f, (u, uc)))
            extra = hier.npost
        if extra > 0:
            def post_f(h, ff, uu, i=i, extra=extra):
                for _ in range(extra):
                    uu = h.levels[i].relax.apply_post(h.levels[i].A,
                                                      ff, uu)
                return uu
            plan.append((i, "post_smooth", post_f, (f, u)))
        f = fc
    return plan


def measure_stages(hier, reps: Optional[int] = None, prof=None, seed: int = 0):
    """Run every stage of one cycle standalone, ``reps`` timed
    repetitions each under a device-synced profiler scope
    ``level<i>/<stage>`` (compile + warmup happen OUTSIDE the scopes).
    Returns the profiler — :func:`roofline` joins its per-scope times to
    the cost model, and its per-occurrence events feed the Perfetto
    export."""
    import jax
    from amgcl_tpu.utils.profiler import Profiler
    if reps is None:
        try:
            reps = int(os.environ.get("AMGCL_TPU_ROOFLINE_REPS", "3"))
        except ValueError:
            reps = 3
    reps = max(int(reps), 1)
    prof = prof if prof is not None else Profiler.device()
    for lvl, stage, fn, args in _stage_plan(hier, seed=seed):
        jf = jax.jit(fn)
        jax.block_until_ready(jf(hier, *args))
        for _ in range(reps):
            with prof.scope("level%d" % lvl):
                with prof.scope(stage):
                    jax.block_until_ready(jf(hier, *args))
    return prof


def scope_times(prof) -> Dict[str, Tuple[float, int]]:
    """``{scope_path: (total_s, count)}`` from a profiler tree."""
    out: Dict[str, Tuple[float, int]] = {}

    def walk(node, path):
        for name, ch in node.children.items():
            p = path + "/" + name if path else name
            out[p] = (ch.total, ch.count)
            walk(ch, p)

    walk(prof.root, "")
    return out


def _stage_lookup(times: Dict[str, Tuple[float, int]], level: int,
                  stage: str) -> Optional[Tuple[float, int]]:
    """Find ``level<i>/<stage>`` by path suffix, so profilers that nest
    the measurement under outer scopes (a CLI run) still join."""
    suffix = "level%d/%s" % (level, stage)
    for path, tc in times.items():
        if path == suffix or path.endswith("/" + suffix):
            return tc
    return None


def _model_for(srow: Dict[str, Any], stage: str, npost: int,
               up_fused: bool) -> Optional[Dict[str, float]]:
    """Model cost of a MEASURED stage: direct for the five model stages,
    composed for the fused legs (down_fused = pre_smooth + restrict;
    up_fused = prolong + the first of the npost post-sweeps, the
    remaining post_smooth shrinking accordingly)."""
    if stage in srow:
        cost = dict(srow[stage])
        if stage == "post_smooth" and up_fused and npost > 1:
            frac = (npost - 1) / float(npost)
            cost = {"flops": cost["flops"] * frac,
                    "bytes": cost["bytes"] * frac}
        return cost
    if stage == "down_fused" and "pre_smooth" in srow:
        return _ledger._add(srow["pre_smooth"], srow["restrict"])
    if stage == "up_fused" and "prolong" in srow:
        cost = dict(srow["prolong"])
        ps = srow.get("post_smooth")
        if ps and npost > 0:
            cost = {"flops": cost["flops"] + ps["flops"] / float(npost),
                    "bytes": cost["bytes"] + ps["bytes"] / float(npost)}
        return cost
    return None


def _classify(flops: float, bytes_: float,
              peaks: Dict[str, Any]) -> Tuple[Optional[float], str]:
    """(machine balance flop/byte, 'memory'|'compute') from the peaks."""
    balance = None
    pk_f, pk_g = peaks.get("flops"), peaks.get("gbps")
    if pk_f and pk_g:
        balance = pk_f / (pk_g * 1e9)
    intensity = flops / bytes_ if bytes_ else 0.0
    bound = "compute" if balance is not None and intensity > balance \
        else "memory"
    return balance, bound


def roofline(hier, prof=None, peaks: Optional[Dict[str, Any]] = None,
             reps: Optional[int] = None) -> Dict[str, Any]:
    """The join: measured per-stage seconds (``prof`` — measured fresh
    via :func:`measure_stages` when None) x ``ledger.cycle_cost_model``
    -> achieved GFLOP/s and GB/s per stage and level, classification
    against the machine balance, fraction of the governing peak, and
    ranked bottlenecks."""
    if prof is None:
        prof = measure_stages(hier, reps=reps)
    peaks = peaks or device_peaks()
    model = _ledger.cycle_cost_model(hier)
    times = scope_times(prof)
    rows: List[Dict[str, Any]] = []
    tot_t = tot_flops = tot_bytes = 0.0
    for srow in model["stages"]:
        if srow.get("skipped"):
            continue
        lvl = srow["level"]
        visits = srow.get("visits", 1)
        up_fused = _stage_lookup(times, lvl, "up_fused") is not None
        for stage in ("down_fused", "pre_smooth", "restrict",
                      "coarse_solve", "up_fused", "prolong",
                      "post_smooth"):
            tc = _stage_lookup(times, lvl, stage)
            if tc is None:
                continue
            total_s, count = tc
            t = total_s / max(count, 1)
            cost = _model_for(srow, stage, getattr(hier, "npost", 1),
                              up_fused)
            if cost is None:
                continue
            flops, bytes_ = float(cost["flops"]), float(cost["bytes"])
            balance, bound = _classify(flops, bytes_, peaks)
            gflops = flops / t / 1e9 if t > 0 else None
            gbps = bytes_ / t / 1e9 if t > 0 else None
            row: Dict[str, Any] = {
                "level": lvl, "stage": stage, "visits": visits,
                "t_s": t, "model_flops": int(flops),
                "model_bytes": int(bytes_),
                "intensity": round(flops / bytes_, 4) if bytes_ else None,
                "gflops": round(gflops, 3) if gflops is not None else None,
                "gbps": round(gbps, 3) if gbps is not None else None,
                "bound": bound,
            }
            frac = None
            if bound == "memory" and gbps is not None and peaks.get("gbps"):
                frac = gbps / peaks["gbps"]
            elif gflops is not None and peaks.get("flops"):
                frac = gflops * 1e9 / peaks["flops"]
            row["frac_peak"] = round(frac, 4) if frac is not None else None
            rows.append(row)
            tot_t += t * visits
            tot_flops += flops * visits
            tot_bytes += bytes_ * visits
    out: Dict[str, Any] = {"peaks": peaks, "stages": rows,
                           "cycle_s": round(tot_t, 6)}
    balance, bound = _classify(tot_flops, tot_bytes, peaks)
    if balance is not None:
        out["machine_balance_flop_per_byte"] = round(balance, 4)
    if tot_t > 0:
        gbps = tot_bytes / tot_t / 1e9
        out["total"] = {
            "model_flops": int(tot_flops), "model_bytes": int(tot_bytes),
            "gflops": round(tot_flops / tot_t / 1e9, 3),
            "gbps": round(gbps, 3), "bound": bound,
            "frac_peak": round(gbps / peaks["gbps"], 4)
            if peaks.get("gbps") else None,
        }
    out["bottlenecks"] = findings(out, hier)
    return out


def findings(rf: Dict[str, Any], hier=None,
             frac_threshold: float = 0.25,
             max_items: int = 3) -> List[Dict[str, Any]]:
    """Ranked bottlenecks as ``telemetry.diagnose()``-style findings:
    stages below ``frac_threshold`` of their governing peak, worst
    time-share first. The suggestion names the likeliest cause — a
    disabled fused leg for the down/up stages on DIA levels, gather
    overhead otherwise."""
    rows = [r for r in rf.get("stages", [])
            if r.get("frac_peak") is not None
            and r["frac_peak"] < frac_threshold]
    cycle_s = rf.get("cycle_s") or sum(
        r["t_s"] * r.get("visits", 1) for r in rf.get("stages", [])) or 1.0
    rows.sort(key=lambda r: -(r["t_s"] * r.get("visits", 1)))
    out = []
    for r in rows[:max_items]:
        share = r["t_s"] * r.get("visits", 1) / cycle_s
        sev = "warning" if (r["frac_peak"] < 0.10 and share > 0.15) \
            else "info"
        peak_name = "HBM peak" if r["bound"] == "memory" \
            else "compute peak"
        msg = ("level %d %s at %.0f%% of %s (%.2f GB/s, %.1f%% of cycle "
               "time)" % (r["level"], r["stage"],
                          100 * r["frac_peak"], peak_name,
                          r["gbps"] or 0.0, 100 * share))
        sugg = None
        if hier is not None and r["level"] < len(hier.levels):
            lv = hier.levels[r["level"]]
            if r["stage"] in ("pre_smooth", "restrict") \
                    and lv.down is None:
                sugg = "fused down-leg disabled on this level — check " \
                       "AMGCL_TPU_FUSED_VCYCLE / AMGCL_TPU_PALLAS and " \
                       "the probe decline log"
            elif r["stage"] in ("prolong", "post_smooth") \
                    and lv.up is None:
                sugg = "fused up-leg disabled on this level — check " \
                       "AMGCL_TPU_FUSED_VCYCLE / AMGCL_TPU_PALLAS and " \
                       "the probe decline log"
        if sugg is None:
            sugg = "memory-bound stage far off the roofline: check the " \
                   "storage format (ledger by_format), gather overhead, " \
                   "per-dispatch latency at this level's size, and that " \
                   "the fused vector tier is engaged " \
                   "(AMGCL_TPU_FUSED_VEC)" \
                if r["bound"] == "memory" else \
                "compute-bound stage off peak: dense coarse levels this " \
                "small are dispatch-latency dominated"
        out.append({"severity": sev, "code": "roofline_stage",
                    "message": msg, "suggestion": sugg})
    return out


def counter_map(rf: Dict[str, Any],
                track: str = "achieved_gbps") -> Dict[str, Dict[str, float]]:
    """``Profiler.to_chrome_trace(counters=...)`` mapping: the achieved
    GB/s of each stage keyed by its ``level<i>/<stage>`` scope path."""
    by_path = {}
    for r in rf.get("stages", []):
        if r.get("gbps") is not None:
            by_path["level%d/%s" % (r["level"], r["stage"])] = r["gbps"]
    return {track: by_path}


def solve_roofline(per_iteration: Dict[str, Any], iters: int,
                   wall_s: float,
                   peaks: Optional[Dict[str, Any]] = None,
                   first_call: bool = False) -> Optional[Dict[str, Any]]:
    """Whole-solve roofline from the ledger's per-Krylov-iteration model
    and one solve's wall time — the cheap, measurement-free variant that
    rides every ``SolveReport.resources``. Wall time includes dispatch
    and fetch overhead (and compile, when ``first_call`` — flagged), so
    this is a lower bound on the achieved rate."""
    flops = per_iteration.get("flops")
    bytes_ = per_iteration.get("bytes")
    if not flops or not bytes_ or not wall_s or wall_s <= 0 or iters <= 0:
        return None
    peaks = peaks or device_peaks()
    t_iter = wall_s / iters
    gflops = flops / t_iter / 1e9
    gbps = bytes_ / t_iter / 1e9
    balance, bound = _classify(float(flops), float(bytes_), peaks)
    out: Dict[str, Any] = {
        "per_iteration_s": round(t_iter, 6),
        "gflops": round(gflops, 3), "gbps": round(gbps, 3),
        "intensity": round(flops / bytes_, 4), "bound": bound,
        "peaks": {k: peaks.get(k) for k in ("gbps", "flops", "source")},
    }
    if peaks.get("gbps"):
        out["frac_hbm_peak"] = round(gbps / peaks["gbps"], 4)
    if peaks.get("flops"):
        out["frac_flops_peak"] = round(gflops * 1e9 / peaks["flops"], 6)
    if first_call:
        out["first_call"] = True      # wall includes jit trace + compile
    return out


def xla_stage_check(hier, plan=None,
                    tolerance: float = 0.05) -> List[Dict[str, Any]]:
    """Per-stage model-bytes vs XLA's compiled ``bytes accessed``
    (``ledger.xla_cost_analysis`` of exactly the stage functions the
    measurement runs). ``within_tol`` marks agreement at ``tolerance``
    (the ledger's ~5% contract); stages whose lowering materializes
    gathers/rolls (DIA on CPU XLA) legitimately exceed the streaming
    floor and report their ratio for inspection. Empty list when the
    backend exposes no cost analysis."""
    import functools
    model = _ledger.cycle_cost_model(hier)
    srows = {r["level"]: r for r in model["stages"]}
    plan = plan or _stage_plan(hier)
    fused_up_levels = {p[0] for p in plan if p[1] == "up_fused"}
    rows = []
    for lvl, stage, fn, args in plan:
        srow = srows.get(lvl)
        if srow is None:
            continue
        cost = _model_for(srow, stage, getattr(hier, "npost", 1),
                          lvl in fused_up_levels)
        if cost is None:
            continue
        xc = _ledger.xla_cost_analysis(functools.partial(fn, hier), *args)
        if not xc or not xc.get("bytes_accessed"):
            continue
        ratio = cost["bytes"] / xc["bytes_accessed"]
        rows.append({
            "level": lvl, "stage": stage,
            "model_bytes": int(cost["bytes"]),
            "xla_bytes": int(xc["bytes_accessed"]),
            "ratio": round(ratio, 4),
            "within_tol": bool(abs(ratio - 1.0) <= tolerance),
        })
    return rows


def format_roofline(rf: Dict[str, Any],
                    xla_rows: Optional[List[Dict[str, Any]]] = None) -> str:
    """Human-readable roofline table (the CLI's ``--roofline``
    rendering)."""
    pk = rf.get("peaks", {})
    src = pk.get("source", {})
    head = "Roofline (peaks: %s GB/s HBM [%s], %s FLOP/s [%s]" % (
        pk.get("gbps"), src.get("gbps", "?"),
        ("%.3g" % pk["flops"]) if pk.get("flops") else "?",
        src.get("flops", "?"))
    if rf.get("machine_balance_flop_per_byte") is not None:
        head += "; balance %.2f F/B" % rf["machine_balance_flop_per_byte"]
    lines = [head + "):",
             "level  stage         t/visit    model MB   achieved GB/s"
             "   GFLOP/s    F/B  bound    %peak",
             "-" * 92]
    xla_by = {(r["level"], r["stage"]): r for r in (xla_rows or [])}
    for r in rf.get("stages", []):
        lines.append(
            "%5d  %-12s %8.1f us %9.3f %15.2f %9.2f %6.2f  %-7s %6s"
            % (r["level"], r["stage"], r["t_s"] * 1e6,
               r["model_bytes"] / 1e6, r["gbps"] or 0.0,
               r["gflops"] or 0.0, r["intensity"] or 0.0, r["bound"],
               ("%.1f%%" % (100 * r["frac_peak"]))
               if r.get("frac_peak") is not None else "-"))
        xr = xla_by.get((r["level"], r["stage"]))
        if xr is not None:
            lines.append(
                "       %-12s model %.3f MB vs XLA %.3f MB  (ratio "
                "%.3f%s)" % ("  xla-check:", xr["model_bytes"] / 1e6,
                             xr["xla_bytes"] / 1e6, xr["ratio"],
                             ", ok" if xr["within_tol"]
                             else " — gather/roll lowering exceeds the "
                                  "streaming floor"))
    tot = rf.get("total")
    if tot:
        lines.append("-" * 92)
        lines.append(
            "cycle: %.1f us/visit-sum, %.2f GB/s achieved (%s-bound%s)"
            % (rf.get("cycle_s", 0.0) * 1e6, tot["gbps"], tot["bound"],
               (", %.1f%% of HBM peak" % (100 * tot["frac_peak"]))
               if tot.get("frac_peak") is not None else ""))
    for f in rf.get("bottlenecks", []):
        lines.append("  [%s] %s" % (f["severity"].upper(), f["message"]))
        if f.get("suggestion"):
            lines.append("      -> %s" % f["suggestion"])
    return "\n".join(lines)
