"""SolveReport — the structured convergence record of one solve.

The reference reports convergence as three loose pieces: the
``(iters, error)`` pair out of ``make_solver::operator()``, the hierarchy
printout of ``amg::operator<<`` and the per-iteration residual prints of
``cg.hpp:199``. Here all of it lands in one dataclass so the text report,
the JSONL sink and programmatic consumers read the same numbers.

Constructor stays positionally compatible with the historical
``SolverInfo(iters, resid, history)`` so every existing call site and
tuple-unpack (``iters, error = info``) keeps working.

(Reached through the package import, which pulls in jax — supervisors
that must stay jax-free load ``telemetry/sink.py`` by file path instead;
see bench.py.)
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

# sink.py stays self-contained (bench.py loads it by file path, jax-free);
# this module is only ever imported through the package, so it shares the
# converter instead of duplicating it
from amgcl_tpu.telemetry.sink import _clean, _jsonable

#: schema version stamped onto every ``to_dict()`` (and the JSONL
#: ``solve`` events built from it) so ``telemetry/diff.py`` can refuse
#: or degrade comparisons across incompatible report layouts
REPORT_SCHEMA = 1

_hw_provenance_cache: Optional[Dict[str, Any]] = None


def _hw_provenance() -> Dict[str, Any]:
    """Process-cached hardware stamp (telemetry/comm.py): bench records
    already carry provenance, solve-level events did not — ``diff.py``
    needs it to platform-skip cross-platform comparisons the way the
    ``_record_platform`` gates do. Cached once: the device set of a
    process never changes."""
    global _hw_provenance_cache
    if _hw_provenance_cache is None:
        try:
            from amgcl_tpu.telemetry.comm import hw_provenance
            _hw_provenance_cache = hw_provenance()
        except Exception:
            _hw_provenance_cache = {"device_platform": None}
    return _hw_provenance_cache


@dataclass
class SolveReport:
    """Uniform solve outcome. ``resid`` is the final RELATIVE residual in
    whatever norm the solver tracks (preconditioned for left-preconditioned
    methods, true otherwise — same convention as the reference).

    ``len(history) == iters`` for a plain solve; under iterative
    refinement (``make_solver(..., refine>0)``) the history covers the
    INITIAL solve only while ``iters`` also counts the correction solves,
    so ``len(history) <= iters`` there (and ``convergence_rate``, derived
    from the history when present, describes the initial solve)."""

    iters: int
    resid: float
    history: Any = None           # per-iteration relative residuals, or None
    convergence_rate: Optional[float] = None  # avg per-iter reduction factor
    wall_time_s: Optional[float] = None
    solver: Optional[str] = None  # Krylov solver class name
    hierarchy: Optional[Dict[str, Any]] = None  # AMG.hierarchy_stats() dict
    #: resource ledger (telemetry/ledger.py): per-level device bytes by
    #: format, analytic FLOP/byte per cycle and per Krylov iteration,
    #: dense-window budget use, (distributed) halo bytes per iteration
    resources: Optional[Dict[str, Any]] = None
    #: numerical-health guard decode (telemetry/health.py): tripped flag
    #: names, per-flag first-trip iteration, and the headline booleans
    #: (``nan``/``diverged``/``stagnated``) + breakdown kind/iteration.
    #: ``{"ok": True, "flags": []}`` for a clean guarded solve, None when
    #: the solver ran with ``guard=False``
    health: Optional[Dict[str, Any]] = None
    #: compile-watch delta for this call (telemetry/compile_watch.py):
    #: new traces / backend compiles / compile seconds of the solve
    #: program, cumulative signature count, and whether this call was a
    #: compile-cache hit. None with AMGCL_TPU_COMPILE_WATCH=0
    compile: Optional[Dict[str, Any]] = None
    #: serving throughput: right-hand sides retired per second by this
    #: call (batched solves: B / wall) or by the service window it
    #: summarizes (serve/service.py). None for plain single solves
    solves_per_sec: Optional[float] = None
    #: per-request latency percentiles of the serve window this report
    #: summarizes ({"p50": s, "p99": s, ...} — telemetry/metrics.py
    #: interpolated percentiles). None outside the serving path
    latency: Optional[Dict[str, Any]] = None
    #: per-request serving-phase breakdown (serve/service.py):
    #: ``{request_id, queue_ms, pad_ms, compile_ms, solve_ms, sync_ms,
    #: bucket_B, batch_fill, latency_ms, lowering}`` — the phase wall
    #: times sum to the end-to-end latency by construction. None for
    #: reports born outside the SolverService queue
    serve: Optional[Dict[str, Any]] = None
    #: recovery-ladder trail (faults/recovery.py): ``{"recovered",
    #: "attempts": [{rung, solver, ok, iters, resid, flags, ...}],
    #: "final_rung", "runs"}`` — recorded when make_solver runs with
    #: recovery enabled and the ladder executed (even a clean first
    #: attempt records its row when a fault had to be absorbed). None
    #: outside the recovery path
    recovery: Optional[Dict[str, Any]] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.convergence_rate is None:
            self.convergence_rate = self._rate()

    def _rate(self):
        """Geometric-mean residual reduction per iteration. History (which
        starts from a relative residual of ~1 at a zero initial guess) is
        preferred; otherwise fall back to resid**(1/iters)."""
        try:
            if self.history is not None and len(self.history) > 0:
                last = float(self.history[-1])
                if last > 0 and math.isfinite(last):
                    return last ** (1.0 / len(self.history))
            if self.iters and self.resid and self.resid > 0 \
                    and math.isfinite(self.resid):
                return float(self.resid) ** (1.0 / int(self.iters))
        except (TypeError, ValueError, OverflowError):
            pass
        return None

    # (iters, resid) tuple-unpacking like the reference / pyamgcl shape
    def __iter__(self):
        yield self.iters
        yield self.resid

    def to_dict(self, with_history: bool = True) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "schema": REPORT_SCHEMA,
            "hw_provenance": _hw_provenance(),
            "iters": int(self.iters),
            "resid": float(self.resid),
            "convergence_rate": self.convergence_rate,
            "wall_time_s": self.wall_time_s,
            "solver": self.solver,
        }
        if with_history and self.history is not None:
            out["history"] = [float(v) for v in self.history]
        if self.hierarchy is not None:
            out["hierarchy"] = self.hierarchy
        if self.resources is not None:
            out["resources"] = self.resources
        if self.health is not None:
            out["health"] = self.health
        if self.compile is not None:
            out["compile"] = self.compile
        if self.solves_per_sec is not None:
            out["solves_per_sec"] = self.solves_per_sec
        if self.latency is not None:
            out["latency"] = self.latency
        if self.serve is not None:
            out["serve"] = self.serve
        if self.recovery is not None:
            out["recovery"] = self.recovery
        if self.extra:
            out.update(self.extra)
        return out

    def to_json(self, **kw) -> str:
        return json.dumps(_clean(self.to_dict(**kw)),
                          default=_jsonable)

    def __str__(self):
        lines = ["Iterations: %d" % self.iters,
                 "Error:      %.6e" % self.resid]
        if self.convergence_rate is not None:
            lines.append("Rate:       %.3g /iter" % self.convergence_rate)
        if self.wall_time_s is not None:
            lines.append("Wall time:  %.4f s" % self.wall_time_s)
        if self.solves_per_sec is not None:
            lines.append("Throughput: %.2f solves/s" % self.solves_per_sec)
        if self.health is not None and not self.health.get("ok", True):
            lines.append("Health:     %s"
                         % ", ".join(self.health.get("flags", [])))
        return "\n".join(lines)
