"""Operator X-ray — structure analytics, format-candidate costing, and
the reorder-gain advisor (ROADMAP item 2's measurement harness).

``to_device('auto')`` picks a device format per hierarchy level from a
handful of structural facts (diagonal count, window span, row-length
spread) and, until this module, recorded none of them: the ~31×
unstructured gap (poisson3Db-class operators) was invisible because
nothing measured *why* a windowed-ELL/DIA packing wastes bandwidth on a
given sparsity pattern or what a bandwidth-reducing reordering would
buy. This module is the per-level structural microscope:

* :func:`structure_metrics` — bandwidth profile and envelope,
  per-diagonal occupancy histogram and DIA fill ratio, ELL row-length
  distribution and padding waste, dense-window span/fill plus a density
  curve at TPU lane/sublane tile granularity, and a blake2b structure
  fingerprint byte-identical to the serve/registry scheme
  (:func:`fingerprint` — pinned by a parity test).
* :func:`candidate_table` — predicted ``{flops, bytes}`` per SpMV for
  every device format the level COULD take, priced from the host CSR
  with the PR-2 ledger byte models (``telemetry.ledger.mv_cost`` of the
  hypothetical packed matrix) — no conversion, no device work. Each
  candidate carries an eligibility verdict with the decline reason, and
  the dense-window candidate distinguishes "budget" (starved by earlier
  levels' draws on the shared pool) from "window" (no banded locality
  at any budget) — the satellite fix that makes budget-starved picks
  visible in the X-ray table.
* the **format-decision ledger** — ``ops/device.to_device('auto')``
  fills a decision record (this table + the winner + the margin + a
  ``reason`` in {"cost", "budget", "forced"}) and attaches it to the
  converted matrix; ``models/amg.py`` collects the records per level so
  the hierarchy carries its own decision history instead of deciding
  silently.
* :func:`advise` — the **reorder-gain advisor**: compute an RCM (and
  variant) permutation host-side, re-evaluate the structural metrics
  and the candidate table under the permutation WITHOUT building
  anything on device, and report the predicted densification (window
  fill, DIA ndiags, ELL padding) and predicted SpMV-byte gain.
  Predict-only by contract: the advisor never converts, never compiles,
  never touches the device (``STRUCTURE_CONTRACTS`` +
  ``analysis/jaxpr_audit.audit_structure`` enforce it).
* :func:`hierarchy_xray` / :func:`structure_findings` /
  :func:`format_xray` — the per-level report ``AMG.structure_report()``
  returns, ``cli.py --xray`` prints, the ``structure`` JSONL event
  carries, and ``telemetry.diagnose(structure=)`` folds into the
  doctor — including the predicted-vs-achieved cross-check against
  measured roofline rows, ranked by time share.

IMPORTANT: this module is host-side analytics ONLY — stdlib + numpy
(+ scipy inside the advisor), never jax and never ``amgcl_tpu.ops``
(those import jax at module scope). ``analysis/jaxpr_audit.
audit_structure`` statically scans this file for violations and asserts
a compile-watch delta of zero across a full ``structure_report`` run.
The window/tiling constants therefore MIRROR ``ops/unstructured.py``
(_TILE/_WIN_ALIGN/_ELL_PAD) instead of importing them; a parity test
pins :func:`tile_windows_host` against ``ops.unstructured.tile_windows``.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# mirrored from ops/unstructured.py (_TILE, _WIN_ALIGN), ops/densewin.py
# (_DWIN_TILE) and ops/device.py (_ELL_PAD) — kept equal by
# tests/test_structure.py so the X-ray prices exactly the windows the
# conversions would build
_TILE = 1024
_WIN_ALIGN = 1024
_DWIN_TILE = 64
_ELL_PAD = 4

#: TPU register-tile granularity for the density curve: a (sublane,
#: lane) = (8, 128) f32 tile is the unit the VPU/MXU actually moves —
#: window bytes whose (8, 128) granule holds no nonzero are pure waste
SUBLANE = 8
LANE = 128

#: density-curve granularities: element, the TPU (8, 128) register
#: tile, and a DMA-ish (64, 1024) super-tile
DENSITY_GRANULES: Tuple[Tuple[int, int], ...] = (
    (1, 1), (SUBLANE, LANE), (64, 1024))

#: candidate formats the X-ray prices, in to_device's auto preference
#: order; "ell" is the unconditional fallback
CANDIDATE_FORMATS = ("dense", "dia", "dwin", "well", "ell")

#: advisor gain below which a reorder is not worth reporting
GAIN_FLOOR = 1.15


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def advisor_variants() -> Tuple[str, ...]:
    """Advisor permutation variants (``AMGCL_TPU_XRAY_VARIANTS``,
    comma-separated, default ``rcm,cm``): ``rcm`` is scipy's reverse
    Cuthill-McKee, ``cm`` the un-reversed ordering (rcm flipped)."""
    raw = os.environ.get("AMGCL_TPU_XRAY_VARIANTS", "rcm,cm")
    out = tuple(v.strip() for v in raw.split(",")
                if v.strip() in ("rcm", "cm"))
    return out or ("rcm",)


def max_advise_nnz() -> int:
    """Advisor size ceiling for ``advise="auto"`` levels
    (``AMGCL_TPU_XRAY_MAX_ADVISE_NNZ``, default 3M nonzeros): RCM plus
    a symmetric permutation is O(nnz log nnz) host work per level — the
    bench worker's always-on summary must not stall on a 14M-nnz fine
    level. ``advise=True`` ignores the ceiling."""
    return _env_int("AMGCL_TPU_XRAY_MAX_ADVISE_NNZ", 3_000_000)


# ---------------------------------------------------------------------------
# fingerprint (the serve/registry scheme, byte-identical)
# ---------------------------------------------------------------------------

def fingerprint(A) -> str:
    """Hex digest of the sparsity PATTERN — the exact
    ``serve.registry.sparsity_fingerprint`` scheme (shape, block size,
    ``ptr``/``col``; values excluded), reimplemented here so the X-ray
    stays importable without jax (serve's package init pulls it in).
    Shares the ``_sparsity_fp`` cache attribute, so whichever side
    hashes first serves the other; a parity test pins the two digests
    equal."""
    cached = getattr(A, "_sparsity_fp", None)
    if cached is not None:
        return cached
    h = hashlib.blake2b(digest_size=16)
    br, bc = getattr(A, "block_size", (1, 1))
    h.update(np.asarray([A.nrows, A.ncols, A.nnz, br, bc],
                        np.int64).tobytes())
    h.update(np.ascontiguousarray(A.ptr).tobytes())
    h.update(np.ascontiguousarray(A.col).tobytes())
    fp = h.hexdigest()
    try:
        A._sparsity_fp = fp
    except AttributeError:
        pass
    return fp


# ---------------------------------------------------------------------------
# window tiling (host mirror of ops.unstructured.tile_windows)
# ---------------------------------------------------------------------------

def _row_min_max(A):
    """Per-row min/max column, O(n) against the canonical sorted-CSR
    convention (``CSR.from_scipy``/``sort_rows`` sort indices; every
    builder in this repo emits sorted rows): the first entry of a row
    is its min column, the last its max. Empty rows report (m, -1)."""
    n, m = A.shape
    row_min = np.full(n, m, dtype=np.int64)
    row_max = np.full(n, -1, dtype=np.int64)
    nz = np.flatnonzero(np.diff(A.ptr))
    if len(nz):
        col = A.col
        row_min[nz] = col[A.ptr[nz]]
        row_max[nz] = col[A.ptr[nz + 1] - 1]
    return row_min, row_max


def tile_windows_host(A, tile: int = _TILE):
    """(n_tiles, rows, tiles, starts, win) — the same aligned per-tile
    column windows ``ops.unstructured.tile_windows`` computes for the
    windowed-ELL / dense-window conversions (starts floored to
    ``_WIN_ALIGN``, ``win`` the alignment-rounded max span, empty tiles
    pointing past the matrix), duplicated here because that module
    imports jax at module scope — but O(n) instead of the packer's
    O(nnz) ``ufunc.at`` (the X-ray runs on every ``to_device('auto')``,
    so it must stay cheaper than the conversion it annotates).
    tests/test_structure.py pins the two implementations equal."""
    n, m = A.shape
    n_tiles = -(-n // tile)
    rows = A.expanded_rows()
    tiles = rows // tile
    row_min, row_max = _row_min_max(A)
    pad = n_tiles * tile - n
    grid_min = np.pad(row_min, (0, pad), constant_values=m) \
        .reshape(n_tiles, tile)
    grid_max = np.pad(row_max, (0, pad), constant_values=-1) \
        .reshape(n_tiles, tile)
    starts = grid_min.min(axis=1)
    ends = grid_max.max(axis=1) + 1
    empty = ends <= starts
    starts[empty] = m
    ends[empty] = m + 1
    starts = (starts // _WIN_ALIGN) * _WIN_ALIGN
    span = ends - starts
    win = int(span.max()) if n_tiles else 1
    win = -(-win // _WIN_ALIGN) * _WIN_ALIGN
    return n_tiles, rows, tiles, starts, win


def fast_facts(A, tile: int = _TILE, itemsize: int = 4
               ) -> Dict[str, Any]:
    """The cheap structural facts the candidate table prices from —
    O(nnz) bincount for the diagonal census (reusing the
    ``_dia_offsets_cache`` the device conversion leaves behind when
    present), O(n) row-length and window spans. Cached on the matrix
    (``_xray_facts``) so the decision ledger in ``to_device`` and a
    later full X-ray share one pass. The full
    :func:`structure_metrics` builds on these and adds the occupancy
    histogram, bandwidth profile and density curve."""
    cached = getattr(A, "_xray_facts", None)
    if cached is not None and cached.get("itemsize") == itemsize \
            and cached.get("tile") == tile:
        return cached
    n, m = A.shape
    nnz = A.nnz
    facts: Dict[str, Any] = {"itemsize": itemsize, "tile": tile,
                             "rows": int(n), "cols": int(m),
                             "nnz": int(nnz)}
    if n == 0 or nnz == 0:
        facts.update({"ndiags": 0, "dia_fill": 0.0, "k": 0,
                      "k_padded": _ELL_PAD, "tiles": 0, "win": 1,
                      "win_bytes": 0, "dwin_tiles": 0, "dwin_win": 1,
                      "dwin_bytes": 0})
        return facts
    off = getattr(A, "_dia_offsets_cache", None)
    if off is None:
        d = A.col.astype(np.int64) - A.expanded_rows()
        base = n - 1
        hits = np.bincount(d + base, minlength=base + m)
        off = np.flatnonzero(hits) - base
        # keep the occupancy counts for structure_metrics (underscore
        # keys: host-side cache only, never emitted) — the full X-ray
        # must not redo this O(nnz + n + m) census
        facts["_occ_off"] = off
        facts["_occ_cnt"] = hits[off + base]
        try:
            A._dia_offsets_cache = off
        except AttributeError:
            pass
    facts["ndiags"] = int(len(off))
    facts["dia_fill"] = round(len(off) * n / max(nnz, 1), 4)
    rnnz = np.diff(A.ptr)
    k_raw = int(rnnz.max())
    facts["k"] = k_raw
    facts["k_padded"] = max(_ELL_PAD, -(-k_raw // _ELL_PAD) * _ELL_PAD)
    n_tiles, _, _, _, win = tile_windows_host(A, tile)
    facts["tiles"] = int(n_tiles)
    facts["win"] = int(win)
    facts["win_bytes"] = int(n_tiles * tile * win * itemsize)
    # the dense-window packer tiles 64 rows at a time (ops/densewin.py
    # _TILE) — its storage footprint must be priced on ITS geometry,
    # not the windowed-ELL 1024-row tiling
    dw_tiles, _, _, _, dw_win = tile_windows_host(A, _DWIN_TILE)
    facts["dwin_tiles"] = int(dw_tiles)
    facts["dwin_win"] = int(dw_win)
    facts["dwin_bytes"] = int(dw_tiles * _DWIN_TILE * dw_win * itemsize)
    try:
        A._xray_facts = facts
    except AttributeError:
        pass
    return facts


# ---------------------------------------------------------------------------
# structural metrics
# ---------------------------------------------------------------------------

def _percentile(vals: np.ndarray, p: float) -> float:
    return float(np.percentile(vals, p)) if len(vals) else 0.0


def structure_metrics(A, tile: int = _TILE, itemsize: int = 4,
                      granules: Sequence[Tuple[int, int]] =
                      DENSITY_GRANULES) -> Dict[str, Any]:
    """Structural analytics of one host CSR (block units for BCSR —
    ``block`` records the value-block dims): bandwidth profile,
    per-diagonal occupancy, ELL row-length distribution and padding
    waste, dense-window span/fill and the tile-granularity density
    curve. Pure numpy over ``ptr``/``col`` — O(nnz log nnz) worst case,
    no values touched, nothing built."""
    n, m = A.shape
    nnz = A.nnz
    br, bc = getattr(A, "block_size", (1, 1))
    out: Dict[str, Any] = {
        "rows": int(n), "cols": int(m), "nnz": int(nnz),
        "block": [int(br), int(bc)], "fingerprint": fingerprint(A)}
    if n == 0 or nnz == 0:
        # full shape with zeroed sub-blocks: every consumer (format_xray,
        # the hierarchy_stats fold, xray_summary) indexes these keys
        # unconditionally — an empty level must not change the schema
        out.update({
            "empty": True,
            "bandwidth": {"max": 0, "mean": 0.0, "p90": 0,
                          "envelope": 0},
            "diagonals": {"ndiags": 0, "fill": 0.0,
                          "occupancy_top": [], "occupancy_p50": 0},
            "ell": {"k": 0, "k_padded": _ELL_PAD,
                    "row_nnz": {"min": 0, "mean": 0.0, "p50": 0,
                                "max": 0},
                    "pad_frac": 0.0, "lane_pad_frac": 0.0},
            "window": {"tiles": 0, "tile": int(tile), "win": 1,
                       "fill": 0.0, "bytes": 0, "density_curve": []},
        })
        return out
    facts = fast_facts(A, tile=tile, itemsize=itemsize)
    rows = A.expanded_rows()
    col = A.col.astype(np.int64)
    d = col - rows

    # bandwidth profile + envelope (the classic reordering objectives:
    # what RCM minimizes, what the window span pays for)
    row_min, row_max = _row_min_max(A)
    has = row_max >= 0
    half_bw = np.zeros(n, dtype=np.int64)
    span = np.zeros(n, dtype=np.int64)
    ridx = np.arange(n, dtype=np.int64)
    half_bw[has] = np.maximum(np.abs(row_max[has] - ridx[has]),
                              np.abs(ridx[has] - row_min[has]))
    span[has] = row_max[has] - row_min[has] + 1
    out["bandwidth"] = {
        "max": int(half_bw.max()),
        "mean": round(float(half_bw.mean()), 2),
        "p90": int(_percentile(half_bw, 90)),
        "envelope": int(span.sum()),
    }

    # per-diagonal occupancy (the DIA story): distinct diagonals, fill
    # ratio stored/nnz, and the top occupied diagonals — reusing the
    # census fast_facts cached when it ran the bincount itself (the
    # native-offsets path caches offsets only, so counts re-derive)
    occ_off = facts.get("_occ_off")
    occ_cnt = facts.get("_occ_cnt")
    if occ_cnt is None:
        base = n - 1
        hits = np.bincount(d + base, minlength=base + m)
        occ_off = np.flatnonzero(hits) - base
        occ_cnt = hits[occ_off + base]
    order = np.argsort(-occ_cnt, kind="stable")[:8]
    out["diagonals"] = {
        "ndiags": facts["ndiags"],
        "fill": facts["dia_fill"],
        "occupancy_top": [[int(occ_off[k]), int(occ_cnt[k]),
                           round(float(occ_cnt[k]) / nnz, 4)]
                          for k in order],
        "occupancy_p50": int(_percentile(occ_cnt, 50)),
    }

    # ELL row-length distribution + padding waste: pad_frac is the
    # row-length-variance waste (vs the raw max K), lane_pad_frac what
    # the packed (lane-padded) format actually stores
    rnnz = np.diff(A.ptr)
    k_raw, k_pad = facts["k"], facts["k_padded"]
    out["ell"] = {
        "k": k_raw, "k_padded": k_pad,
        "row_nnz": {"min": int(rnnz.min()),
                    "mean": round(float(rnnz.mean()), 2),
                    "p50": int(_percentile(rnnz, 50)),
                    "max": k_raw},
        "pad_frac": round(1.0 - nnz / (n * max(k_raw, 1)), 4),
        "lane_pad_frac": round(1.0 - nnz / (n * k_pad), 4),
    }

    # dense-window span/fill + the density curve at TPU tile
    # granularity: fraction of (sublane x lane) granules of the
    # (tile, win) band that hold at least one nonzero, and the fill
    # inside occupied granules — the two numbers that say whether the
    # window trade (HBM capacity for streaming) pays on this pattern
    n_tiles, _, tiles, starts, win = tile_windows_host(A, tile)
    local = col - starts[tiles]
    r_in_tile = rows - tiles * tile
    curve: List[Dict[str, Any]] = []
    for gr, gc in granules:
        key = (tiles * (-(-tile // gr)) + r_in_tile // gr) \
            * (-(-win // gc)) + local // gc
        occupied = int(len(np.unique(key)))
        total = n_tiles * (-(-tile // gr)) * (-(-win // gc))
        row_curve = {
            "granule": "%dx%d" % (gr, gc),
            "occupied_frac": round(occupied / max(total, 1), 6),
        }
        if (gr, gc) != (1, 1):
            row_curve["fill_in_occupied"] = round(
                nnz / max(occupied * gr * gc, 1), 6)
        curve.append(row_curve)
    out["window"] = {
        "tiles": int(n_tiles), "tile": int(tile), "win": int(win),
        "fill": round(nnz / max(n_tiles * tile * win, 1), 6),
        "bytes": int(n_tiles * tile * win * itemsize),
        "density_curve": curve,
    }
    return out


# ---------------------------------------------------------------------------
# candidate cost table (the PR-2 ledger byte models, predicted)
# ---------------------------------------------------------------------------

def candidate_table(A, itemsize: int = 4, on_tpu: bool = False,
                    dense_cutoff: int = 2048,
                    max_diags: Optional[int] = None,
                    max_fill: Optional[float] = None,
                    well_max_win_bytes: int = 4 << 20,
                    budget_remaining: Optional[int] = None,
                    budget_total: Optional[int] = None,
                    tile: int = _TILE) -> List[Dict[str, Any]]:
    """Predicted per-SpMV ``{flops, bytes}`` for every candidate device
    format of ``A``, priced from the host CSR exactly like
    ``ledger.mv_cost`` would price the packed matrix (stored operator
    streamed once + x read + y written — the roofline floor). Mirrors
    ``ops/device.to_device``'s auto eligibility rules (same thresholds,
    passed in by the caller when it resolved them differently); nothing
    is converted or compiled.

    The dense-window candidate's decline reason distinguishes
    ``"budget"`` (its bytes fit ``budget_total`` but not what earlier
    conversions left in ``budget_remaining`` — a budget-STARVED pick)
    from ``"window"`` (the aligned span is too wide for any budget — a
    structural decline a reorder might fix)."""
    n, m = A.shape
    nnz = max(A.nnz, 1)
    br, bc = getattr(A, "block_size", (1, 1))
    is_block = (br, bc) != (1, 1)
    vec = (n * br + m * bc) * itemsize
    if max_diags is None:
        max_diags = 512 if on_tpu else 40
    if max_fill is None:
        max_fill = 16.0 if on_tpu else 1.5
    facts = fast_facts(A, tile=tile, itemsize=itemsize)
    rows: List[Dict[str, Any]] = []

    def cand(fmt, eligible, why, flops, stored):
        rows.append({
            "format": fmt, "eligible": bool(eligible),
            **({"why": why} if why else {}),
            "predicted": {"flops": int(flops),
                          "bytes": int(stored + vec)},
            "stored_bytes": int(stored)})

    # dense (MXU matmul; small coarse levels)
    dense_ok = (not is_block and max(n, m) <= dense_cutoff
                and nnz > 0.02 * n * m)
    cand("dense", dense_ok,
         None if dense_ok else (
             "block values" if is_block else
             "%d > dense cutoff %d" % (max(n, m), dense_cutoff)
             if max(n, m) > dense_cutoff else
             "density below the 2% dense floor"),
         2 * n * m, n * m * itemsize)

    # dia (zero-gather shifted multiply-adds)
    nd = facts["ndiags"]
    fill = facts["dia_fill"] if nd else float("inf")
    dia_stored = nd * n * itemsize
    dia_ok = (not is_block and nd and nd <= max_diags
              and fill <= max_fill and dia_stored < 2 << 30)
    cand("dia", dia_ok,
         None if dia_ok else (
             "block values" if is_block else
             "%d diagonals > max_diags %d" % (nd, max_diags)
             if nd > max_diags else
             "fill %.3g > max_fill %.3g" % (fill, max_fill)
             if fill > max_fill else "data over the 2 GB guard"),
         2 * nd * n, dia_stored)

    # dwin (gather-free dense windows; TPU auto path, square scalar) —
    # priced on the dense-window packer's own 64-row tiling
    need = facts["dwin_bytes"]
    cap_total = budget_total
    if cap_total is None:
        cap_total = _env_int("AMGCL_TPU_DWIN_MAX_BYTES", 6 << 30)
    cap_now = cap_total if budget_remaining is None \
        else min(cap_total, budget_remaining)
    vmem_ok = (2 * _DWIN_TILE + 4) * facts["dwin_win"] * itemsize \
        <= 10 << 20
    dwin_why = None
    if is_block:
        dwin_why = "block values"
    elif n != m:
        dwin_why = "rectangular"
    elif need > cap_total:
        dwin_why = "window"        # too wide for ANY budget: structural
    elif need > cap_now:
        dwin_why = "budget"        # starved by earlier levels' draws
    elif not vmem_ok:
        dwin_why = "vmem"
    elif not on_tpu:
        dwin_why = "auto picks dense windows on TPU only"
    cand("dwin", dwin_why is None, dwin_why,
         2 * facts["dwin_tiles"] * _DWIN_TILE * facts["dwin_win"],
         need)

    # well (windowed ELL: per-tile VMEM windows + on-chip gather)
    k_pad = max(4, facts["k_padded"])
    win = facts["win"]
    well_ok = win * bc * 4 <= well_max_win_bytes
    n_tiles = facts["tiles"]
    well_stored = (n_tiles * 4
                   + n_tiles * tile * k_pad * (4 + itemsize * br * bc))
    cand("well", well_ok,
         None if well_ok else
         "window %d col x 4 B > %d B VMEM budget"
         % (win * bc, well_max_win_bytes),
         2 * n_tiles * tile * k_pad * br * bc, well_stored)

    # ell (global gather — the unconditional fallback)
    k_ell = max(_ELL_PAD, k_pad)
    cand("ell", True, None,
         2 * n * k_ell * br * bc,
         n * k_ell * (4 + itemsize * br * bc))
    return rows


def best_candidate(candidates: List[Dict[str, Any]],
                   eligible_only: bool = True
                   ) -> Optional[Dict[str, Any]]:
    """Predicted-byte argmin over the table (eligible rows only by
    default)."""
    rows = [c for c in candidates if c["eligible"]] if eligible_only \
        else list(candidates)
    return min(rows, key=lambda c: c["predicted"]["bytes"]) if rows \
        else None


def decision_record(candidates: List[Dict[str, Any]], winner_fmt: str,
                    forced: bool = False,
                    built_bytes: Optional[int] = None
                    ) -> Dict[str, Any]:
    """The format-decision ledger entry ``to_device`` attaches to the
    converted matrix: the candidate table, the winner, the margin
    (best other candidate's predicted bytes / winner's — > 1 means the
    winner also predicted cheapest), and the ``reason``:

    * ``"forced"`` — the caller named the format;
    * ``"budget"`` — a candidate the auto policy PREFERS to the winner
      (earlier in :data:`CANDIDATE_FORMATS`, to_device's preference
      order — dense-window buys gather-freedom, not fewer stored
      bytes, so byte ranking alone would never flag it) or one
      predicted cheaper lost solely on the shared HBM budget: the
      budget changed the outcome (the budget-starved pick the
      satellite fix makes distinguishable);
    * ``"cost"``   — everything else: the winner won on the cost/
      eligibility rules.
    """
    win = next((c for c in candidates if c["format"] == winner_fmt),
               None)
    reason = "forced" if forced else "cost"
    if not forced and win is not None:
        order = {f: i for i, f in enumerate(CANDIDATE_FORMATS)}
        wi = order.get(winner_fmt, len(CANDIDATE_FORMATS))
        wb = win["predicted"]["bytes"]
        for c in candidates:
            if c is win or c.get("why") != "budget":
                continue
            if order.get(c["format"], 99) < wi \
                    or c["predicted"]["bytes"] < wb:
                reason = "budget"
                break
    margin = None
    if win is not None:
        others = [c["predicted"]["bytes"] for c in candidates
                  if c is not win and c["eligible"]]
        if others and win["predicted"]["bytes"]:
            margin = round(min(others) / win["predicted"]["bytes"], 4)
    out: Dict[str, Any] = {"fmt": winner_fmt, "reason": reason,
                           "candidates": candidates, "margin": margin}
    if win is not None:
        out["predicted"] = dict(win["predicted"])
        out["stored_bytes"] = int(win["stored_bytes"])
    if built_bytes is not None:
        out["built_bytes"] = int(built_bytes)
    return out


# ---------------------------------------------------------------------------
# reorder-gain advisor (predict-only)
# ---------------------------------------------------------------------------

def _rcm_perm(A) -> np.ndarray:
    """Reverse Cuthill-McKee permutation of the symmetrized pattern —
    the same scipy routine ``utils.adapters.cuthill_mckee`` wraps (that
    module is host-only too, but imports the CSR class tree; the X-ray
    works from raw ptr/col)."""
    import scipy.sparse as sp
    from scipy.sparse.csgraph import reverse_cuthill_mckee
    mat = sp.csr_matrix(
        (np.ones(A.nnz, np.int8), A.col, A.ptr), shape=A.shape)
    return np.asarray(reverse_cuthill_mckee(mat, symmetric_mode=True))


def permute_pattern(A, perm: np.ndarray):
    """B = P A Pᵀ of the PATTERN (values dropped — the advisor never
    needs them), returned as a lightweight CSR-shaped host object."""
    import scipy.sparse as sp
    mat = sp.csr_matrix(
        (np.ones(A.nnz, np.float32), A.col, A.ptr), shape=A.shape)
    mat = mat[perm][:, perm].tocsr()
    mat.sort_indices()

    class _Pattern:
        pass

    B = _Pattern()
    B.ptr = mat.indptr.astype(np.int64)
    B.col = mat.indices.astype(np.int32)
    B.shape = mat.shape
    B.nrows = mat.shape[0]
    B.ncols = mat.shape[1]
    B.nnz = int(mat.nnz)
    B.block_size = getattr(A, "block_size", (1, 1))

    def _rows():
        # cached like CSR.expanded_rows — metrics + candidate pricing
        # call this several times per variant, and the O(nnz) repeat
        # must not multiply on exactly the large levels the advisor
        # ceiling keeps cheap
        r = getattr(B, "_rows_cache", None)
        if r is None:
            r = np.repeat(np.arange(B.nrows), np.diff(B.ptr))
            B._rows_cache = r
        return r

    B.expanded_rows = _rows
    return B


def advise(A, metrics: Optional[Dict[str, Any]] = None,
           variants: Optional[Sequence[str]] = None,
           itemsize: int = 4, on_tpu: bool = False,
           tile: int = _TILE,
           dense_cutoff: int = 2048) -> Dict[str, Any]:
    """The reorder-gain advisor for ONE operator: for each permutation
    variant, re-evaluate the structural metrics and the candidate cost
    table under the permutation — host-side, predict-only — and report
    the predicted densification and SpMV-byte gain vs the identity
    ordering. ``gain`` is best-eligible predicted bytes (identity) /
    best-eligible predicted bytes (permuted): the factor the format
    layer is predicted to win back if ``to_device`` saw the reordered
    operator (``cli --reorder`` / ``utils.adapters.Reordered``)."""
    met_id = metrics if metrics is not None else structure_metrics(
        A, tile=tile, itemsize=itemsize)
    cand_id = candidate_table(A, itemsize=itemsize, on_tpu=on_tpu,
                              dense_cutoff=dense_cutoff, tile=tile)
    best_id = best_candidate(cand_id)
    out: Dict[str, Any] = {
        "identity": {"best": best_id["format"] if best_id else None,
                     "bytes": best_id["predicted"]["bytes"]
                     if best_id else None},
        "variants": []}
    if A.nnz == 0 or A.nrows == 0:
        return out
    try:
        rcm = _rcm_perm(A)
    except Exception as e:      # scipy missing / disconnected pattern:
        out["error"] = repr(e)[:200]   # the advisor degrades to silence
        return out
    perms = {"rcm": rcm, "cm": rcm[::-1]}
    best_row = None
    for name in (variants if variants is not None
                 else advisor_variants()):
        perm = perms.get(name)
        if perm is None:
            continue
        B = permute_pattern(A, perm)
        met_p = structure_metrics(B, tile=tile, itemsize=itemsize)
        cand_p = candidate_table(B, itemsize=itemsize, on_tpu=on_tpu,
                                 dense_cutoff=dense_cutoff, tile=tile)
        best_p = best_candidate(cand_p)
        gain = None
        if best_id and best_p and best_p["predicted"]["bytes"]:
            gain = round(best_id["predicted"]["bytes"]
                         / best_p["predicted"]["bytes"], 4)
        # mechanism-matched gains: predicted bytes of each format under
        # identity / under the permutation, eligibility ignored — the
        # number ``bench --xray`` validates measured (same format both
        # sides, so time tracks bytes on any platform)
        by_id = {c["format"]: c["predicted"]["bytes"] for c in cand_id}
        per_format = {
            c["format"]: round(by_id[c["format"]]
                               / c["predicted"]["bytes"], 4)
            for c in cand_p
            if c["predicted"]["bytes"] and by_id.get(c["format"])}
        row = {
            "variant": name,
            "best": best_p["format"] if best_p else None,
            "bytes": best_p["predicted"]["bytes"] if best_p else None,
            "gain": gain,
            "per_format": per_format,
            "densify": {
                "ndiags": [met_id["diagonals"]["ndiags"],
                           met_p["diagonals"]["ndiags"]],
                "window_fill": [met_id["window"]["fill"],
                                met_p["window"]["fill"]],
                "window_win": [met_id["window"]["win"],
                               met_p["window"]["win"]],
                "ell_pad_frac": [met_id["ell"]["pad_frac"],
                                 met_p["ell"]["pad_frac"]],
                "bandwidth_max": [met_id["bandwidth"]["max"],
                                  met_p["bandwidth"]["max"]],
            },
            "candidates": cand_p,
        }
        out["variants"].append(row)
        # only a GAIN is a recommendation: a variant predicted to make
        # the structure worse (gain < 1, e.g. RCM on an already-banded
        # stencil) stays in the raw variants data but never becomes the
        # headline "best" the summary/gauges/print surface
        if gain is not None and gain > 1.0 and (
                best_row is None or gain > best_row["gain"]):
            best_row = row
    if best_row is not None:
        out["best"] = {"variant": best_row["variant"],
                       "gain": best_row["gain"],
                       "format": best_row["best"],
                       "per_format": best_row["per_format"],
                       "densify": best_row["densify"]}
    return out


# ---------------------------------------------------------------------------
# executed reorder (ISSUE 20): the advisor's prediction, turned into a plan
# ---------------------------------------------------------------------------

#: fingerprint-keyed plan cache: the permutation is a function of the
#: sparsity PATTERN only, so PR-9 ``rebuild()`` (same pattern, new
#: values) and farm re-registrations of the same system reuse the plan
#: for free instead of re-running scipy's RCM
_PERM_CACHE: Dict[Tuple[str, str], Optional[Dict[str, Any]]] = {}


def reorder_mode() -> str:
    """``AMGCL_TPU_REORDER``, normalized: ``auto`` (default — engage
    when the advisor predicts at least :data:`GAIN_FLOOR` byte gain),
    ``rcm``/``cm`` (force that variant regardless of predicted gain),
    or ``off``. Read per call so flight replay's env re-application and
    per-test monkeypatching see the live value."""
    raw = os.environ.get("AMGCL_TPU_REORDER", "auto").strip().lower()
    if raw in ("0", "off", "no", "false"):
        return "off"
    if raw in ("rcm", "cm"):
        return raw
    return "auto"


def reorder_plan(A, on_tpu: bool = False, mode: Optional[str] = None,
                 itemsize: int = 4) -> Optional[Dict[str, Any]]:
    """Decide whether to EXECUTE a reorder on ``A`` and, if so, return
    the plan — or ``None`` to keep the identity ordering.

    The plan dict carries everything the build/rebuild/solve seams
    need to make the permutation invisible:

    * ``perm``/``iperm`` — row/col permutation and its inverse
      (``A_perm = P A Pᵀ``; solve permutes rhs in by ``perm`` and
      un-permutes x out by ``iperm``),
    * ``val_perm`` — nnz-sized index array with
      ``A_perm.val = A.val[val_perm]``, so a same-pattern ``rebuild``
      re-permutes values without touching scipy again,
    * ``variant`` (``rcm``/``cm``), ``fingerprint`` (identity-pattern
      digest the plan is cached under), ``predicted_gain`` (advisor
      byte ratio, ``None`` when forced), ``n``, and the ORIGINAL
      pattern refs ``ptr``/``col`` (so rebuild can recognize a caller
      handing back an original-order CSR).

    Scalar matrices only (``block_size == (1, 1)``) — the advisor does
    not price block permutations — and patterns above
    :func:`max_advise_nnz` are left alone, same ceiling as the X-ray."""
    md = reorder_mode() if mode is None else str(mode).strip().lower()
    if md in ("off", "0", "no", "false"):
        return None
    if getattr(A, "block_size", (1, 1)) != (1, 1):
        return None
    if A.nnz == 0 or A.nrows == 0 or A.nrows != A.ncols:
        return None
    if A.nnz > max_advise_nnz():
        return None
    fp = fingerprint(A)
    key = (fp, md)
    if key in _PERM_CACHE:
        return _PERM_CACHE[key]
    plan: Optional[Dict[str, Any]] = None
    try:
        if md == "auto":
            # cheap pre-filter before the full advisor pass: an operator
            # that already packs into a handful of well-filled diagonals
            # (3D stencils: 7) is the structured regime the reorder
            # exists to RECOVER, not improve — RCM cannot beat the
            # identity there, and every AMG build would otherwise pay an
            # RCM + candidate-table pass at setup. O(nnz) unique() vs
            # the advisor's O(nnz log nnz + tables).
            offs = np.unique(
                np.repeat(np.arange(A.nrows, dtype=np.int64),
                          np.diff(A.ptr)) - A.col)
            if len(offs) <= 16 and \
                    len(offs) * A.nrows <= 1.5 * A.nnz:
                _PERM_CACHE[key] = None
                return None
            adv = advise(A, itemsize=itemsize, on_tpu=on_tpu)
            best = adv.get("best")
            if best is not None and best.get("gain") and \
                    best["gain"] >= GAIN_FLOOR:
                variant, gain = best["variant"], float(best["gain"])
            else:
                variant, gain = None, None
        else:
            variant, gain = md, None
        if variant is not None:
            rcm = _rcm_perm(A)
            perm = rcm if variant == "rcm" else rcm[::-1]
            perm = np.ascontiguousarray(perm, dtype=np.int64)
            iperm = np.empty_like(perm)
            iperm[perm] = np.arange(A.nrows, dtype=np.int64)
            # value map via a scipy pass whose "values" are positions:
            # row i of A_perm holds A.val[val_perm[ptr[i]:ptr[i+1]]]
            import scipy.sparse as sp
            # 1-based positions: position 0 as a stored value would be
            # indistinguishable from an explicit zero to scipy's pruning
            tag = sp.csr_matrix(
                (np.arange(1, A.nnz + 1, dtype=np.int64), A.col, A.ptr),
                shape=A.shape)
            tag = tag[perm][:, perm].tocsr()
            tag.sort_indices()
            plan = {"perm": perm, "iperm": iperm,
                    "val_perm": np.ascontiguousarray(tag.data) - 1,
                    "variant": variant, "fingerprint": fp,
                    "predicted_gain": gain, "n": int(A.nrows),
                    "ptr": A.ptr, "col": A.col}
    except Exception:
        plan = None          # scipy missing / degenerate pattern:
    _PERM_CACHE[key] = plan  # the executed reorder degrades to identity
    return plan


# ---------------------------------------------------------------------------
# the hierarchy X-ray
# ---------------------------------------------------------------------------

def _is_csr_like(A) -> bool:
    return (A is not None and hasattr(A, "ptr") and hasattr(A, "col")
            and hasattr(A, "nnz"))


def hierarchy_xray(host_levels, decisions: Optional[List] = None,
                   advise_mode: Any = "auto",
                   variants: Optional[Sequence[str]] = None,
                   itemsize: int = 4, on_tpu: bool = False,
                   tile: int = _TILE) -> Dict[str, Any]:
    """The operator X-ray over every hierarchy level: per-level
    structural metrics + the recorded format decision + (optionally)
    the reorder-gain advisor. ``host_levels`` is ``AMG.host_levels``
    (``(A, P, R)`` rows; non-CSR meta rows from device-built prefixes
    degrade to skipped entries); ``decisions`` the per-level decision
    records ``models/amg.py`` collected from ``to_device``.

    ``advise_mode``: True (every CSR level), False (none), or "auto"
    (levels up to :func:`max_advise_nnz` nonzeros — the always-on bench
    summary must stay cheap)."""
    levels: List[Dict[str, Any]] = []
    ceiling = max_advise_nnz()
    for i, row in enumerate(host_levels or []):
        Ai = row[0] if isinstance(row, (tuple, list)) and row else row
        if not _is_csr_like(Ai):
            levels.append({"level": i,
                           "skipped": "no host CSR (device-built or "
                           "filtered level)"})
            continue
        met = structure_metrics(Ai, tile=tile, itemsize=itemsize)
        lrow: Dict[str, Any] = {"level": i, "metrics": met}
        dec = decisions[i] if decisions is not None \
            and i < len(decisions) else None
        if dec is not None:
            lrow["decision"] = dec
        else:
            # no recorded decision (pre-xray build / device-built
            # level): the predicted table still renders the X-ray
            lrow["candidates"] = candidate_table(
                Ai, itemsize=itemsize, on_tpu=on_tpu, tile=tile)
        do_advise = bool(advise_mode) and met.get("nnz", 0) > 0
        if advise_mode == "auto" and met.get("nnz", 0) > ceiling:
            do_advise = False
            lrow["advisor"] = {"skipped": "nnz %d > advise ceiling %d "
                               "(AMGCL_TPU_XRAY_MAX_ADVISE_NNZ)"
                               % (met["nnz"], ceiling)}
        if do_advise:
            lrow["advisor"] = advise(Ai, metrics=met, variants=variants,
                                     itemsize=itemsize, on_tpu=on_tpu,
                                     tile=tile)
        levels.append(lrow)
    out = {"schema": 1, "levels": levels}
    out["summary"] = xray_summary(out)
    return out


def xray_summary(xray: Dict[str, Any]) -> Dict[str, Any]:
    """Compact roll-up of a hierarchy X-ray — what the bench worker
    embeds on every record, the live gauges publish, and the
    ``structure`` JSONL event's headline block. Finest-level waste
    numbers plus the best advisor gain across levels."""
    levels = xray.get("levels") or []
    rows = [r for r in levels if "metrics" in r]
    summary: Dict[str, Any] = {"n_levels": len(levels)}
    if not rows:
        return summary
    finest = rows[0]
    met = finest["metrics"]
    summary.update({
        "fingerprint": met.get("fingerprint"),
        "bandwidth_max": met.get("bandwidth", {}).get("max"),
        "ndiags": met.get("diagonals", {}).get("ndiags"),
        "dia_fill": met.get("diagonals", {}).get("fill"),
        "padding_waste_frac":
            met.get("ell", {}).get("lane_pad_frac"),
        "window_fill": met.get("window", {}).get("fill"),
    })
    fmts, reasons = [], []
    gain = None
    for r in levels:
        dec = r.get("decision")
        fmts.append((dec or {}).get("fmt", "-"))
        reasons.append((dec or {}).get("reason", "-"))
        g = ((r.get("advisor") or {}).get("best") or {}).get("gain")
        if g is not None and (gain is None or g > gain):
            gain = g
    summary["formats"] = "/".join(fmts)
    summary["reasons"] = "/".join(reasons)
    if gain is not None:
        summary["predicted_reorder_gain"] = gain
    return summary


# ---------------------------------------------------------------------------
# findings (the doctor fold) + the roofline cross-check
# ---------------------------------------------------------------------------

def _finding(severity, code, message, suggestion=None, **extra):
    out = {"severity": severity, "code": code, "message": message}
    if suggestion:
        out["suggestion"] = suggestion
    out.update(extra)
    return out


def decision_roofline_check(xray: Dict[str, Any],
                            roofline: Dict[str, Any]
                            ) -> List[Dict[str, Any]]:
    """Join the decision ledger's predicted per-SpMV bytes to the
    measured roofline rows: per level, the mean achieved GB/s over its
    operator-streaming stages vs the hierarchy median, ranked by time
    share — the predicted-vs-achieved divergence table. A level whose
    chosen format achieves far below the rest is where the auto
    decision (or its byte model) is wrong on this pattern."""
    stages = (roofline or {}).get("stages") or []
    if not stages:
        return []
    per_level: Dict[int, Dict[str, float]] = {}
    for r in stages:
        if r.get("gbps") is None:
            continue
        acc = per_level.setdefault(int(r["level"]),
                                   {"gbps": 0.0, "k": 0, "t": 0.0})
        acc["gbps"] += r["gbps"]
        acc["k"] += 1
        acc["t"] += r["t_s"] * r.get("visits", 1)
    if not per_level:
        return []
    total_t = sum(a["t"] for a in per_level.values()) or 1.0
    means = {lvl: a["gbps"] / a["k"] for lvl, a in per_level.items()}
    median = float(np.median(list(means.values())))
    dec_by_level = {r["level"]: r.get("decision")
                    for r in xray.get("levels") or []}
    rows = []
    for lvl, mean_gbps in means.items():
        dec = dec_by_level.get(lvl) or {}
        row = {"level": lvl, "format": dec.get("fmt"),
               "reason": dec.get("reason"),
               "achieved_gbps": round(mean_gbps, 3),
               "median_gbps": round(median, 3),
               "t_share": round(per_level[lvl]["t"] / total_t, 4),
               "predicted_bytes": (dec.get("predicted") or {}).get(
                   "bytes"),
               "built_bytes": dec.get("built_bytes")}
        row["deficit"] = round(1.0 - mean_gbps / median, 4) \
            if median > 0 else None
        rows.append(row)
    rows.sort(key=lambda r: -(max(r["deficit"] or 0.0, 0.0)
                              * r["t_share"]))
    return rows


def structure_findings(xray: Dict[str, Any],
                       roofline: Optional[Dict[str, Any]] = None
                       ) -> List[Dict[str, Any]]:
    """Doctor-shaped findings from a hierarchy X-ray: advisor gains,
    padding/fill waste, budget-starved decisions, predicted-vs-built
    ledger drift, and (with a measured roofline) the
    predicted-vs-achieved divergence per format, ranked. Pure dict
    crunching — never raises on missing pieces."""
    out: List[Dict[str, Any]] = []
    if not xray:
        return out
    for r in xray.get("levels") or []:
        lvl = r.get("level")
        dec = r.get("decision") or {}
        met = r.get("metrics") or {}
        best = (r.get("advisor") or {}).get("best") or {}
        gain = best.get("gain")
        if gain is not None and gain >= GAIN_FLOOR:
            dn = best.get("densify") or {}
            nd = dn.get("ndiags", [None, None])
            wf = dn.get("window_fill", [None, None])
            ep = dn.get("ell_pad_frac", [None, None])
            out.append(_finding(
                "warning" if (gain >= 1.5 and lvl == 0) else "info",
                "reorder_gain",
                "level %s: a %s reorder is predicted to cut the best "
                "format's SpMV bytes %.2fx (best format %s; ndiags "
                "%s -> %s, window fill %s -> %s, ELL padding "
                "%s -> %s)" % (
                    lvl, best.get("variant"), gain, best.get("format"),
                    nd[0], nd[1], wf[0], wf[1], ep[0], ep[1]),
                "apply the bandwidth-reducing reorder at setup "
                "(cli --reorder / utils.adapters.Reordered) — the "
                "hierarchy absorbs the permutation, the solve phase "
                "never pays it",
                level=lvl, predicted_gain=gain,
                variant=best.get("variant")))
        # mechanism-matched densification: the winning format's OWN
        # byte gain under the reorder (same packing both sides — the
        # number bench --xray validates measured, since same-format
        # time tracks bytes on any platform)
        fmt_gain = (best.get("per_format") or {}).get(
            best.get("format"))
        if fmt_gain is not None and fmt_gain >= GAIN_FLOOR:
            nd = (best.get("densify") or {}).get("ndiags",
                                                 [None, None])
            out.append(_finding(
                "info", "reorder_densification",
                "level %s: the %s packing itself densifies %.2fx "
                "under the %s ordering (predicted stored+streamed "
                "bytes per spmv, same format both sides; ndiags "
                "%s -> %s)" % (lvl, best.get("format"), fmt_gain,
                               best.get("variant"), nd[0], nd[1]),
                "bench --xray measures exactly this pair "
                "(identity-vs-reordered spmv per format) and joins "
                "predicted vs achieved",
                level=lvl, predicted_gain=fmt_gain,
                format=best.get("format"),
                variant=best.get("variant")))
        if dec.get("reason") == "budget":
            out.append(_finding(
                "warning", "budget_starved_format",
                "level %s: the predicted-cheapest format lost on the "
                "shared dense-window budget, not on cost — the level "
                "runs %s instead" % (lvl, dec.get("fmt")),
                "raise AMGCL_TPU_DWIN_MAX_BYTES (the hierarchy-wide "
                "pool) or reorder coarser levels off the dense-window "
                "format", level=lvl))
        pred = dec.get("stored_bytes")
        built = dec.get("built_bytes")
        if pred and built and not (0.75 <= built / pred <= 1.25):
            out.append(_finding(
                "info", "ledger_divergence",
                "level %s: the decision ledger predicted %d stored "
                "bytes for %s but the conversion built %d (%.2fx) — "
                "the candidate byte model drifted from the packer"
                % (lvl, pred, dec.get("fmt"), built, built / pred),
                level=lvl))
        ell = met.get("ell") or {}
        if lvl == 0 and (ell.get("lane_pad_frac") or 0) > 0.3 \
                and dec.get("fmt") in ("ell", "well"):
            out.append(_finding(
                "info", "ell_padding_waste",
                "finest level stores %.0f%% padding in its %s packing "
                "(row-length spread %s..%s)" % (
                    100 * ell["lane_pad_frac"], dec.get("fmt"),
                    ell.get("row_nnz", {}).get("min"),
                    ell.get("row_nnz", {}).get("max")),
                "a reorder or row binning that evens row lengths "
                "reclaims the padded bandwidth", level=lvl))
    rows = decision_roofline_check(xray, roofline) if roofline else []
    for row in rows:
        if (row.get("deficit") or 0) > 0.5 and row["t_share"] > 0.05:
            out.append(_finding(
                "warning", "format_underperforms",
                "level %d (%s, decided on %s) achieves %.3g GB/s vs "
                "the hierarchy median %.3g — %.0f%% below, carrying "
                "%.0f%% of the measured cycle time: the predicted "
                "cost and the achieved rate diverge on this pattern"
                % (row["level"], row.get("format"), row.get("reason"),
                   row["achieved_gbps"], row["median_gbps"],
                   100 * row["deficit"], 100 * row["t_share"]),
                "check the X-ray's advisor row for this level — a "
                "reorder that densifies windows usually closes "
                "exactly this gap", level=row["level"],
                t_share=row["t_share"]))
    sev = {"critical": 0, "warning": 1, "info": 2}
    out.sort(key=lambda f: (sev.get(f["severity"], 3),
                            -(f.get("t_share") or
                              f.get("predicted_gain") or 0)))
    return out


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def _human_bytes(x) -> str:
    x = float(x or 0)
    for unit in ("B", "K", "M", "G"):
        if abs(x) < 1024 or unit == "G":
            return "%.2f %s" % (x, unit)
        x /= 1024.0


def format_xray(xray: Dict[str, Any]) -> str:
    """Human rendering of a hierarchy X-ray: the per-level structure
    table, the format-decision candidate ledger, and the advisor rows
    (``cli.py --xray``)."""
    lines = ["Operator X-ray:",
             "level    rows       nnz    bw_max  ndiags  dia_fill  "
             "ell_pad  win_fill  decision",
             "-" * 86]
    for r in xray.get("levels") or []:
        if "metrics" not in r:
            lines.append("%5s  %s" % (r.get("level"),
                                      r.get("skipped", "-")))
            continue
        met = r["metrics"]
        dec = r.get("decision") or {}
        dtxt = "-"
        if dec:
            dtxt = "%s (%s%s)" % (
                dec.get("fmt"), dec.get("reason"),
                ", margin %.2f" % dec["margin"]
                if dec.get("margin") is not None else "")
        lines.append("%5d %7d %9d %9d %7d %9.3f %8.3f %9.4f  %s" % (
            r["level"], met["rows"], met["nnz"],
            met["bandwidth"]["max"], met["diagonals"]["ndiags"],
            met["diagonals"]["fill"], met["ell"]["lane_pad_frac"],
            met["window"]["fill"], dtxt))
    lines.append("")
    lines.append("Format-decision ledger (predicted bytes per spmv):")
    for r in xray.get("levels") or []:
        cands = (r.get("decision") or {}).get("candidates") \
            or r.get("candidates")
        if not cands:
            continue
        dec = r.get("decision") or {}
        cells = []
        for c in cands:
            mark = "*" if c["format"] == dec.get("fmt") else \
                ("" if c["eligible"] else "x")
            cells.append("%s%s %s" % (mark, c["format"],
                                      _human_bytes(c["predicted"]
                                                   ["bytes"])))
        lines.append("  level %s: %s" % (r.get("level"),
                                         "  ".join(cells)))
        rejected = [c for c in cands if not c["eligible"]
                    and c.get("why")]
        if rejected:
            lines.append("          rejected: " + "; ".join(
                "%s (%s)" % (c["format"], c["why"]) for c in rejected))
    adv_lines = []
    for r in xray.get("levels") or []:
        best = (r.get("advisor") or {}).get("best")
        if best and best.get("gain") is not None:
            dn = best.get("densify") or {}
            adv_lines.append(
                "  level %s: %s -> predicted gain %.2fx (best format "
                "%s; ndiags %s->%s, window fill %.4g->%.4g)" % (
                    r.get("level"), best.get("variant"), best["gain"],
                    best.get("format"),
                    dn.get("ndiags", ["-", "-"])[0],
                    dn.get("ndiags", ["-", "-"])[1],
                    dn.get("window_fill", [0, 0])[0],
                    dn.get("window_fill", [0, 0])[1]))
    if adv_lines:
        lines.append("")
        lines.append("Reorder-gain advisor (predict-only):")
        lines += adv_lines
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# test / bench fixture: a banded operator under a random permutation
# ---------------------------------------------------------------------------

def banded_pattern(n: int, bw: int = 4):
    """(ptr, col, val) of an SPD-ish Toeplitz band of half-bandwidth
    ``bw`` — every in-range diagonal in [-bw, bw] fully occupied, so
    the structure is exactly ``2*bw + 1`` diagonals."""
    offs = np.arange(-bw, bw + 1)
    rows_l, cols_l, vals_l = [], [], []
    ridx = np.arange(n, dtype=np.int64)
    for off in offs:
        c = ridx + off
        ok = (c >= 0) & (c < n)
        rows_l.append(ridx[ok])
        cols_l.append(c[ok])
        vals_l.append(np.full(ok.sum(),
                              2.0 * bw + 1.0 if off == 0 else -0.5))
    rows = np.concatenate(rows_l)
    cols = np.concatenate(cols_l)
    vals = np.concatenate(vals_l)
    order = np.lexsort((cols, rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    ptr = np.zeros(n + 1, np.int64)
    np.add.at(ptr, rows + 1, 1)
    ptr = np.cumsum(ptr)
    return ptr, cols.astype(np.int32), vals


def permuted_banded(n: int = 2048, bw: int = 4, seed: int = 0,
                    local: Optional[int] = None):
    """The advisor-validation fixture (tests + ``bench.py --xray``): a
    banded SPD matrix scrambled by a random symmetric permutation —
    RCM recovers the band, so the predicted densification (ndiags,
    window fill, ELL padding) is large and checkable. Returns
    ``(A_permuted, A_banded, perm)`` as ``ops.csr.CSR`` objects (the
    one place this module touches the CSR class — imported lazily;
    ops.csr is numpy-only).

    ``local`` shuffles within contiguous blocks of that size instead
    of globally: the bandwidth grows to ~2·local+bw instead of ~n, so
    the DIA packing stays BUILDABLE at identity (a few hundred
    diagonals, not thousands) while remaining badly wasteful — the
    bench microbenchmark uses this to measure the same format on both
    orderings (the mechanism-matched join)."""
    from amgcl_tpu.ops.csr import CSR
    import scipy.sparse as sp
    ptr, col, val = banded_pattern(n, bw)
    A0 = CSR(ptr, col, val, n)
    rng = np.random.RandomState(seed)
    if local:
        perm = np.arange(n)
        for s in range(0, n, int(local)):
            blk = perm[s:s + int(local)].copy()
            rng.shuffle(blk)
            perm[s:s + int(local)] = blk
    else:
        perm = rng.permutation(n)
    mat = sp.csr_matrix((A0.val, A0.col, A0.ptr), shape=(n, n))
    mat = mat[perm][:, perm].tocsr()
    mat.sort_indices()
    return CSR(mat.indptr, mat.indices, mat.data, n), A0, perm
