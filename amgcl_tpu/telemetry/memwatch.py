"""Memory observatory — measured device-memory truth, ownership
attribution, leak gates and OOM forensics.

Every memory decision made so far — farm admission/eviction
(``telemetry.ledger.LruMemoryPool``), dense-window budgets, the gate's
bytes ratios — trusts the ANALYTIC ledger (``AMG.bytes()`` and
``hierarchy_ledger``'s size*itemsize sums). Nothing ever measured what
the device actually holds, so model drift, transient workspace peaks,
buffers leaked across register/evict/rebuild cycles and allocator
fragmentation were all invisible until an opaque ``RESOURCE_EXHAUSTED``
killed a tenant. This module closes the loop with four pieces:

* **Measured sampling** — :func:`device_sample` reads the backend
  allocator (``device.memory_stats()``: ``bytes_in_use`` /
  ``peak_bytes_in_use`` on TPU/GPU) and falls back to a live-array
  census (``jax.live_arrays()``) on backends that expose no stats (the
  CPU mesh every test runs on). Samples land on a bounded timeline:
  event-driven via :func:`snapshot` at named phases (setup, solve
  dispatch, serve batch, farm register/evict/rebuild, allocation
  failures) plus an optional low-overhead daemon sampler thread
  (:func:`start_sampler`, paced by ``AMGCL_TPU_MEMWATCH_INTERVAL_MS``).
  The timeline exports as a Perfetto counter track
  (:func:`to_chrome_trace`) that ``cli --trace`` merges onto the shared
  epoch, and each phase snapshot emits one ``memory`` JSONL event when
  a sink is attached.
* **Ownership attribution** — a weakref registry
  (:func:`register_owner`) maps live device buffers to their owners
  (hierarchy pytrees, solver-bundle operators); :func:`owner_table`
  joins each owner's MEASURED bytes (live buffer ``nbytes``) against
  the ledger's analytic model with a ``provenance: model|measured``
  tag and computes the "unattributed" remainder of the census.
  ``AMG.memory_report()`` (:func:`hierarchy_report`) does the same
  join per level and slot, and ``SolveReport.resources
  ["bytes_measured"]`` (:func:`solve_resources`) carries it on every
  solve. Drift feeds ``telemetry.diagnose(memory=...)`` through
  :func:`memory_findings`.
* **Leak gate** — :func:`selftest` drives register -> evict ->
  re-register cycles through a real :class:`SolverFarm` on the
  8-virtual-device CPU mesh and asserts measured bytes return to
  baseline each cycle; ``bench.py --check`` wires it in as the
  ``memwatch`` record (``AMGCL_TPU_MEMWATCH_IN_CHECK``), and the
  ``AMGCL_TPU_GATE_MEMDRIFT`` ratio gates the join's drift against
  BENCH_LAST_GOOD. ``AMGCL_TPU_MEMWATCH_LEAK_BYTES`` deliberately
  plants a leak per cycle — the negative injection that proves the
  gate trips.
* **OOM forensics** — :func:`record_allocation_failure` is the shared
  tail of every typed :class:`~amgcl_tpu.faults.AllocationError` seam
  (make_solver dispatch, ``SolverService._dispatch``, farm admission):
  one ``memory`` JSONL event plus a flight-recorder bundle whose
  manifest embeds the memory timeline and the top-owner table
  (:func:`forensics_tags`).

Knobs (README env table):

  AMGCL_TPU_MEMWATCH              0 disables the observatory entirely
                                  (no snapshots, no joins, no sampler)
  AMGCL_TPU_MEMWATCH_INTERVAL_MS  daemon sampler period; unset/0 = no
                                  sampler thread (snapshots still fire)
  AMGCL_TPU_MEMWATCH_TIMELINE     bounded timeline capacity (def 512)
  AMGCL_TPU_MEMWATCH_TOL          declared measured-vs-model join
                                  tolerance as a relative fraction
                                  (def 0.25)
  AMGCL_TPU_MEMWATCH_IN_CHECK     0 skips the leak-cycle selftest arm
                                  in ``bench.py --check`` (default on)
  AMGCL_TPU_MEMWATCH_LEAK_BYTES   selftest negative injection: leak
                                  this many device bytes per cycle so
                                  the gate MUST trip (tests only)
  AMGCL_TPU_MEMWATCH_TIMEOUT      ``--check`` subprocess bound (def
                                  600 s)

Module level stays stdlib-only (jax is imported lazily inside the
measuring paths, flight/sink inside the emitting paths) so the bench
supervisor and the analysis layer can load it without a device
runtime. Thread contract (DESIGN §20, analysis/concurrency.py): ONE
module lock guarding the timeline/owner/peak state; the sampler thread
paces on a ``threading.Event`` and never measures or emits while
holding the lock.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional

# runtime lock witness seam (analysis/lockwitness.py, identity when
# the knob is off) — same discipline as telemetry/flight.py
from amgcl_tpu.analysis.lockwitness import maybe_wrap as _wit_wrap

#: default bounded-timeline capacity (AMGCL_TPU_MEMWATCH_TIMELINE)
TIMELINE_CAPACITY = 512

#: declared lock partial order (analysis/concurrency.py): the module
#: lock below is a LEAF — nothing else is ever acquired while it is
#: held (measuring, emitting and flight dumps all run lock-free), so
#: the order has no edges. Declared explicitly so the analyzer and the
#: runtime witness share the contract with the other concurrent
#: modules rather than inferring an absence.
LOCK_ORDER = ()

_lock = _wit_wrap("memwatch._lock", threading.Lock())
_timeline: deque = deque(maxlen=TIMELINE_CAPACITY)
_owners: Dict[str, "_Owner"] = {}
_peak_seen = 0            # census high-water (allocator-less backends)
_drift_events = 0
_sampler: Optional[threading.Thread] = None
#: sampler pace-maker AND stop signal in one — waited on LOCK-FREE
#: (an Event, not a Condition: no lock to hold, no predicate to loop)
_sampler_stop = threading.Event()
#: last census result (t, total_bytes, skipped) — written by a single
#: tuple assignment and read into a local before use, so concurrent
#: snapshots race benignly (worst case: two fresh censuses, never a
#: torn read); deliberately NOT under _lock to keep device_sample
#: lock-free per its contract
_census_cache: Optional[tuple] = None


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def enabled() -> bool:
    """Kill switch: ``AMGCL_TPU_MEMWATCH=0`` disables snapshots, joins
    and the sampler (read per call — tests flip it)."""
    return os.environ.get("AMGCL_TPU_MEMWATCH", "1") != "0"


def declared_tolerance() -> float:
    """The DECLARED measured-vs-model join tolerance (relative): a
    per-owner disagreement beyond it is a drift finding, within it the
    model is considered truthful (``AMGCL_TPU_MEMWATCH_TOL``)."""
    try:
        return float(os.environ.get("AMGCL_TPU_MEMWATCH_TOL", "0.25"))
    except ValueError:
        return 0.25


def _interval_s() -> float:
    try:
        return float(os.environ.get("AMGCL_TPU_MEMWATCH_INTERVAL_MS",
                                    "0")) / 1e3
    except ValueError:
        return 0.0


def _census_max_age_s() -> float:
    """How stale a live-array census may be before a PHASE SNAPSHOT
    re-walks ``jax.live_arrays()`` (``AMGCL_TPU_MEMWATCH_CENSUS_MS``,
    default 100 ms). The census is O(live arrays) and snapshots ride
    hot paths (every serve batch, every solve), so the walk is paced;
    direct :func:`device_sample` calls and the sampler thread always
    measure fresh, as does the ``allocation_failure`` forensics
    snapshot. 0 disables the cache entirely."""
    try:
        return float(os.environ.get("AMGCL_TPU_MEMWATCH_CENSUS_MS",
                                    "100")) / 1e3
    except ValueError:
        return 0.1


def _timeline_cap() -> int:
    try:
        cap = int(os.environ.get("AMGCL_TPU_MEMWATCH_TIMELINE",
                                 str(TIMELINE_CAPACITY)))
        return cap if cap > 0 else TIMELINE_CAPACITY
    except ValueError:
        return TIMELINE_CAPACITY


def _reset_for_tests() -> None:
    global _peak_seen, _drift_events, _census_cache
    stop_sampler()
    _census_cache = None
    with _lock:
        _timeline.clear()
        _owners.clear()
        _peak_seen = 0
        _drift_events = 0


# ---------------------------------------------------------------------------
# measured sampling
# ---------------------------------------------------------------------------

def measured_tree_bytes(tree) -> int:
    """MEASURED device bytes of every array leaf in a pytree: the live
    buffer's ``nbytes`` (what the runtime actually reports for the
    allocation), falling back to size*itemsize — the analytic number —
    for leaves that expose no ``nbytes``. 0 for None (an evicted
    hierarchy)."""
    if tree is None:
        return 0
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
        elif hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += leaf.size * leaf.dtype.itemsize
    return total


def device_sample(max_age_s: float = 0.0) -> Dict[str, Any]:
    """One measured point from the default device: backend allocator
    stats when the platform exposes them (``source: memory_stats`` —
    TPU/GPU ``bytes_in_use`` / ``peak_bytes_in_use``), else a live-array
    census (``source: census`` — the CPU fallback: the sum of every
    live jax array's ``nbytes``; the census peak is this module's own
    high-water across samples). ``source: none`` with None bytes when
    no runtime is importable. Never raises, never takes the module
    lock.

    ``max_age_s`` > 0 lets the CENSUS branch reuse the previous walk
    when it is at most that old (the allocator-stats branch is cheap
    and never cached) — phase snapshots pass
    :func:`_census_max_age_s` so hot paths pay O(live arrays) at a
    bounded rate; the default 0 always measures fresh."""
    global _census_cache
    out: Dict[str, Any] = {"t": time.perf_counter(), "ts": time.time(),
                           "source": "none", "bytes_in_use": None,
                           "peak_bytes_in_use": None}
    try:
        import jax
        dev = jax.devices()[0]
        stats = None
        ms = getattr(dev, "memory_stats", None)
        if callable(ms):
            try:
                stats = ms()
            except Exception:        # noqa: BLE001 — backend-optional
                stats = None
        if stats:
            out["source"] = "memory_stats"
            out["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
            out["peak_bytes_in_use"] = int(
                stats.get("peak_bytes_in_use",
                          stats.get("bytes_in_use", 0)))
        else:
            cache = _census_cache    # local read: benign race, see decl
            if (cache is not None and max_age_s > 0
                    and 0 <= out["t"] - cache[0] <= max_age_s):
                total, skipped = cache[1], cache[2]
                out["census_age_s"] = round(out["t"] - cache[0], 4)
            else:
                total = 0
                skipped = 0
                for arr in jax.live_arrays():
                    try:
                        total += int(getattr(arr, "nbytes", 0) or 0)
                    except Exception:  # noqa: BLE001 — a buffer deleted
                        skipped += 1   # mid-census is not an error
                _census_cache = (out["t"], total, skipped)
            out["source"] = "census"
            out["bytes_in_use"] = total
            if skipped:
                out["skipped_arrays"] = skipped
    except Exception as e:           # noqa: BLE001 — measurement must
        out["error"] = repr(e)[:120]  # never fail the caller
    return out


def snapshot(phase: str, *, fresh: bool = False,
             **tags) -> Optional[Dict[str, Any]]:
    """Event-driven sample at a named phase (``amg.setup``, ``solve``,
    ``serve.batch``, ``farm.register``, ...): measures OUTSIDE the
    lock, appends to the bounded timeline, and emits one ``memory``
    JSONL event when a sink is attached. Returns the sample (None when
    disabled). Extra keyword tags ride both the timeline row and the
    event. The CPU census may be paced (:func:`_census_max_age_s`);
    ``fresh=True`` forces a new walk — forensics snapshots use it so
    an OOM bundle never reports a pre-failure number."""
    if not enabled():
        return None
    global _peak_seen, _timeline
    s = device_sample(0.0 if fresh else _census_max_age_s())
    s["phase"] = str(phase)
    for k, v in tags.items():
        if v is not None:
            s[k] = v
    with _lock:
        if _timeline.maxlen != _timeline_cap():
            # capacity knob changed since import: rebind (clear+extend
            # would keep the OLD maxlen — deques cannot be resized)
            _timeline = deque(_timeline, maxlen=_timeline_cap())
        if s["bytes_in_use"] is not None:
            if s["bytes_in_use"] > _peak_seen:
                _peak_seen = s["bytes_in_use"]
            if s["peak_bytes_in_use"] is None:
                s["peak_bytes_in_use"] = _peak_seen
        _timeline.append(s)
    _maybe_emit(s)
    return s


def timeline(last: Optional[int] = None) -> List[Dict[str, Any]]:
    """Copy of the bounded timeline (newest-last); ``last`` bounds the
    tail returned."""
    with _lock:
        rows = list(_timeline)
    return rows[-int(last):] if last else rows


def _maybe_emit(row: Dict[str, Any]) -> None:
    """One ``memory`` JSONL event per phase snapshot — only when the
    operator attached a sink (the serve/farm convention), and never
    for sampler ticks (the timeline is their record; a 100 ms sampler
    would spam every stream)."""
    if row.get("phase") == "sampler":
        return
    try:
        from amgcl_tpu.telemetry.sink import (NullSink, emit,
                                              get_default_sink)
        if isinstance(get_default_sink(), NullSink):
            return
        emit({k: v for k, v in row.items() if k != "t"},
             event="memory")
    except Exception:                # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# daemon sampler thread
# ---------------------------------------------------------------------------

def _sampler_loop(interval_s: float) -> None:
    # Event.wait is the pace maker and the stop signal in one; the
    # measure/append split keeps the lock hold O(append) — the census
    # itself (which can briefly hold the GIL over many buffers) runs
    # lock-free
    global _peak_seen
    while not _sampler_stop.wait(interval_s):
        if not enabled():
            continue
        s = device_sample()
        s["phase"] = "sampler"
        with _lock:
            if s["bytes_in_use"] is not None:
                if s["bytes_in_use"] > _peak_seen:
                    _peak_seen = s["bytes_in_use"]
                if s["peak_bytes_in_use"] is None:
                    s["peak_bytes_in_use"] = _peak_seen
            _timeline.append(s)


def start_sampler(interval_s: Optional[float] = None) -> bool:
    """Start the daemon sampling thread (idempotent): one
    :func:`device_sample` per period onto the timeline. Period from
    ``AMGCL_TPU_MEMWATCH_INTERVAL_MS`` when not given; <= 0 (the
    default) starts nothing — phase snapshots alone cost nothing
    between events. Returns whether a sampler is running."""
    global _sampler
    if not enabled():
        return False
    if interval_s is None:
        interval_s = _interval_s()
    if interval_s <= 0:
        return False
    with _lock:
        if _sampler is not None and _sampler.is_alive():
            return True
        _sampler_stop.clear()
        t = threading.Thread(target=_sampler_loop,
                             args=(float(interval_s),),
                             name="memwatch-sampler", daemon=True)
        _sampler = t
    t.start()
    return True


def stop_sampler() -> None:
    """Stop the sampler thread (no-op when none runs). The join is
    bounded and runs outside the module lock."""
    global _sampler
    with _lock:
        t = _sampler
        _sampler = None
    _sampler_stop.set()
    if t is not None and t.is_alive():
        t.join(timeout=2.0)


# ---------------------------------------------------------------------------
# ownership attribution
# ---------------------------------------------------------------------------

class _Owner:
    """One attributed owner: a weakref to the owning object plus the
    measure/model callables resolved per kind. Dies with its object
    (the weakref callback unregisters it)."""

    __slots__ = ("name", "kind", "ref", "measure_fn", "model_fn")

    def __init__(self, name: str, kind: str, ref,
                 measure_fn: Callable[[Any], int],
                 model_fn: Optional[Callable[[Any], Optional[int]]]):
        self.name = name
        self.kind = kind
        self.ref = ref
        self.measure_fn = measure_fn
        self.model_fn = model_fn


def _measure_hierarchy(amg) -> int:
    return measured_tree_bytes(getattr(amg, "hierarchy", None))


def _model_hierarchy(amg) -> Optional[int]:
    """Analytic model bytes of a hierarchy — the PR-2 ledger total
    (size*itemsize over the declared Level slots), 0 while evicted."""
    if not getattr(amg, "device_resident", False):
        return 0
    try:
        led = amg.resource_ledger()
        return int(led["totals"]["bytes"])
    except Exception:                # noqa: BLE001
        return None


def _measure_operator(bundle) -> int:
    # when the bundle reuses the hierarchy's finest-level operator as
    # its Krylov system matrix (make_solver's shared fast path), those
    # buffers already belong to the hierarchy owner — charging them
    # here would double-count against the census
    hier = getattr(getattr(bundle, "precond", None), "hierarchy", None)
    shared = getattr(hier, "system_matrix", None)
    A_dev = getattr(bundle, "A_dev", None)
    total = measured_tree_bytes(getattr(bundle, "A_dev64", None))
    if A_dev is not None and A_dev is not shared:
        total += measured_tree_bytes(A_dev)
    return total


def register_owner(kind: str, obj, name: Optional[str] = None,
                   measure_fn: Optional[Callable[[Any], int]] = None,
                   model_fn: Optional[Callable[[Any], Optional[int]]]
                   = None) -> Optional[str]:
    """Attribute ``obj``'s live device buffers to a named owner row.

    ``kind`` selects the default measure/model pair: ``hierarchy`` (an
    AMG: measured = live hierarchy-leaf ``nbytes``, model = the ledger
    total), ``operator`` (a make_solver bundle: the device system
    operators), anything else must pass ``measure_fn``. The registry
    holds only a weakref — an owner dies with its object and its row
    disappears. Returns the owner name (``kind:<id>`` by default), or
    None when disabled/unmeasurable."""
    if not enabled():
        return None
    if measure_fn is None:
        measure_fn = {"hierarchy": _measure_hierarchy,
                      "operator": _measure_operator}.get(kind)
        if measure_fn is None:
            return None
    if model_fn is None and kind == "hierarchy":
        model_fn = _model_hierarchy
    name = name or "%s:%x" % (kind, id(obj))

    def _gone(_ref, _name=name):
        with _lock:
            _owners.pop(_name, None)

    try:
        ref = weakref.ref(obj, _gone)
    except TypeError:
        return None                  # unweakrefable: no attribution
    ow = _Owner(name, kind, ref, measure_fn, model_fn)
    with _lock:
        _owners[name] = ow
    return name


def unregister_owner(name: str) -> None:
    with _lock:
        _owners.pop(name, None)


def owner_table(sample: Optional[Dict[str, Any]] = None
                ) -> List[Dict[str, Any]]:
    """The measured-vs-model join per owner, plus the census
    remainder: one row per live owner with ``bytes_measured``,
    ``bytes_model`` (None when the owner has no analytic model),
    ``drift_ratio`` (measured/model) and ``provenance``; when the
    sample came from a census, a final ``unattributed`` row carries
    census-total minus everything attributed (workspaces, donated
    iterate buffers, foreign arrays). Rows sort largest-measured
    first — the "top owner table" the OOM bundles embed."""
    with _lock:
        owners = list(_owners.values())
    rows: List[Dict[str, Any]] = []
    attributed = 0
    for ow in owners:
        obj = ow.ref()
        if obj is None:
            continue
        try:
            measured = int(ow.measure_fn(obj))
        except Exception:            # noqa: BLE001
            continue
        model = None
        if ow.model_fn is not None:
            try:
                model = ow.model_fn(obj)
            except Exception:        # noqa: BLE001
                model = None
        row: Dict[str, Any] = {"owner": ow.name, "kind": ow.kind,
                               "bytes_measured": measured,
                               "bytes_model": model,
                               "provenance": "measured"}
        if model:
            row["drift_ratio"] = round(measured / model, 6)
        rows.append(row)
        attributed += measured
    sample = sample or device_sample()
    if sample.get("source") == "census" \
            and sample.get("bytes_in_use") is not None:
        rows.append({"owner": "unattributed", "kind": "remainder",
                     "bytes_measured": max(
                         int(sample["bytes_in_use"]) - attributed, 0),
                     "bytes_model": None, "provenance": "measured"})
    rows.sort(key=lambda r: -r["bytes_measured"])
    return rows


# ---------------------------------------------------------------------------
# joins: hierarchy report, per-solve resources, doctor findings
# ---------------------------------------------------------------------------

_SLOTS = ("A", "relax", "P", "R", "down", "up")


def hierarchy_report(amg) -> Dict[str, Any]:
    """``AMG.memory_report()``: the per-level, per-slot join of
    measured live-buffer bytes against the analytic ledger model, with
    a ``provenance`` tag and the headline ``drift_ratio``
    (measured/model over the whole hierarchy). Works evicted (all
    zeros, ``resident: False``) and never raises past a malformed
    hierarchy (``error`` field instead)."""
    out: Dict[str, Any] = {
        "provenance": "measured",
        "resident": bool(getattr(amg, "device_resident", False)),
        "tolerance": declared_tolerance(),
    }
    try:
        hier = getattr(amg, "hierarchy", None)
        levels = []
        total_meas = 0
        for i, lv in enumerate(getattr(hier, "levels", []) or []):
            slots = {}
            lv_meas = 0
            for slot in _SLOTS:
                b = measured_tree_bytes(getattr(lv, slot, None))
                if b:
                    slots[slot] = b
                lv_meas += b
            A = getattr(lv, "A", None)
            levels.append({"level": i,
                           "format": type(A).__name__ if A is not None
                           else None,
                           "bytes_measured": lv_meas,
                           "slots": slots})
            total_meas += lv_meas
        coarse_meas = measured_tree_bytes(getattr(hier, "coarse", None))
        total_meas += coarse_meas
        model_total = None
        if out["resident"]:
            try:
                led = amg.resource_ledger()
                model_total = int(led["totals"]["bytes"])
                for row, lrow in zip(levels, led.get("levels", [])):
                    row["bytes_model"] = lrow["bytes"]["total"]
                    if row["bytes_model"]:
                        row["drift_ratio"] = round(
                            row["bytes_measured"] / row["bytes_model"],
                            6)
            except Exception:        # noqa: BLE001
                model_total = None
        if model_total is None:
            out["provenance"] = "model"
        out["levels"] = levels
        out["coarse_bytes_measured"] = coarse_meas
        out["total_measured"] = total_meas
        out["total_model"] = model_total
        if model_total:
            out["drift_ratio"] = round(total_meas / model_total, 6)
        out["device"] = {k: v for k, v in device_sample().items()
                         if k != "t"}
    except Exception as e:           # noqa: BLE001
        out["error"] = repr(e)[:200]
    return out


def solve_resources(bundle) -> Optional[Dict[str, Any]]:
    """The per-solve measured record ``SolveReport.resources
    ["bytes_measured"]`` carries: live hierarchy + operator bytes with
    their provenance, plus the device-level sample. Also drops a
    ``solve`` phase point on the timeline. None when disabled."""
    if not enabled():
        return None
    try:
        hier_b = measured_tree_bytes(
            getattr(getattr(bundle, "precond", None), "hierarchy",
                    None))
        op_b = _measure_operator(bundle)
        s = snapshot("solve") or device_sample()
        return {"provenance": "measured",
                "hierarchy": hier_b, "operator": op_b,
                "total": hier_b + op_b,
                "device": {"source": s.get("source"),
                           "bytes_in_use": s.get("bytes_in_use"),
                           "peak_bytes_in_use":
                           s.get("peak_bytes_in_use")}}
    except Exception:                # noqa: BLE001
        return None


def memory_findings(mem: Optional[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """Doctor findings from a memory join record (an
    ``AMG.memory_report()``, a ``bytes_measured`` record or a selftest
    record) — the ``telemetry.diagnose(memory=...)`` fold. Pure dict
    crunching, never raises."""
    out: List[Dict[str, Any]] = []
    if not isinstance(mem, dict):
        return out

    def finding(sev, code, message, suggestion=None):
        f = {"severity": sev, "code": code, "message": message}
        if suggestion:
            f["suggestion"] = suggestion
        return f

    tol = mem.get("tolerance")
    tol = declared_tolerance() if not isinstance(tol, (int, float)) \
        else float(tol)
    dr = mem.get("drift_ratio")
    if isinstance(dr, (int, float)) and abs(dr - 1.0) > tol:
        out.append(finding(
            "warning", "mem_drift",
            "measured device bytes diverge from the analytic ledger "
            "model by %.1f%% (ratio %.3f, declared tolerance "
            "%.0f%%) — every admission/eviction decision trusting "
            "AMG.bytes() is off by that much"
            % (100 * abs(dr - 1.0), dr, 100 * tol),
            "inspect AMG.memory_report() for the drifting level/slot; "
            "on TPU, padding and layout make measured the truth — "
            "consider AMGCL_TPU_FARM_HEADROOM=measured"))
    leaked = mem.get("leaked_bytes")
    if isinstance(leaked, (int, float)) and leaked > 0:
        out.append(finding(
            "critical", "mem_leak",
            "register/evict/rebuild cycles leaked %d device bytes — "
            "measured memory did not return to baseline" % int(leaked),
            "a buffer survives eviction: check release_device() drops "
            "every cache and the flight/capsule ring is not pinning "
            "rhs/x0 arrays (AMGCL_TPU_FLIGHT_DIR unset disables the "
            "ring)"))
    owners = mem.get("owners") or []
    if isinstance(owners, list):
        total = sum(o.get("bytes_measured", 0) or 0 for o in owners
                    if isinstance(o, dict))
        un = next((o for o in owners if isinstance(o, dict)
                   and o.get("owner") == "unattributed"), None)
        if un and total > 0 and un.get("bytes_measured", 0) > 0.5 * total:
            out.append(finding(
                "info", "mem_unattributed",
                "%.0f%% of measured device bytes belong to no "
                "registered owner — workspaces, donated buffers or "
                "foreign arrays dominate the footprint"
                % (100 * un["bytes_measured"] / total),
                None))
    return out


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

def forensics_tags(max_timeline: int = 64, max_owners: int = 8
                   ) -> Dict[str, Any]:
    """The forensic payload an allocation-failure flight bundle embeds
    in its manifest: the memory timeline tail (``t`` stripped —
    perf_counter references mean nothing post-mortem) and the
    top-owner table."""
    rows = [{k: v for k, v in r.items() if k != "t"}
            for r in timeline(last=max_timeline)]
    return {"memory_timeline": rows,
            "memory_owners": owner_table()[:max_owners]}


def record_allocation_failure(seam: str, exc=None, bundle=None,
                              rhs=None, x0=None,
                              extra: Optional[Dict[str, Any]] = None
                              ) -> Optional[str]:
    """The shared tail of every typed ``AllocationError`` seam: drop an
    ``allocation_failure`` phase point on the timeline (emitting the
    ``memory`` event), then dump a flight bundle whose manifest embeds
    the timeline and top-owner table. Returns the bundle path (None
    when the recorder is off / unwritable). Never raises — forensics
    must not mask the allocation error itself."""
    try:
        snapshot("allocation_failure", fresh=True, seam=seam,
                 error=repr(exc)[:200] if exc is not None else None)
    except Exception:                # noqa: BLE001
        pass
    try:
        from amgcl_tpu.telemetry import flight as _flight
        if not _flight.enabled():
            return None
        tags: Dict[str, Any] = {"seam": seam}
        if exc is not None:
            tags["exception"] = repr(exc)[:200]
        if extra:
            tags.update(extra)
        tags.update(forensics_tags())
        return _flight.dump("allocation_failure", bundle=bundle,
                            rhs=rhs, x0=x0, tags=tags)
    except Exception:                # noqa: BLE001
        return None


# ---------------------------------------------------------------------------
# Perfetto export
# ---------------------------------------------------------------------------

def to_chrome_trace(tid: int = 6, tid_name: str = "memwatch",
                    pid: int = 0,
                    epoch: Optional[float] = None) -> Dict[str, Any]:
    """Chrome/Perfetto counter-track export of the timeline
    (``ph:'C'`` events, microseconds relative to ``epoch`` — pass the
    CLI profiler's ``_t0`` so the memory curve lines up under the
    flame graph; default epoch is the first sample). Phase snapshots
    additionally drop instant events so 'farm.evict' is visible AT the
    bytes step it caused."""
    rows = timeline()
    events: List[Dict[str, Any]] = []
    if not rows:
        return {"traceEvents": events}
    t0 = rows[0]["t"] if epoch is None else epoch
    events.append({"ph": "M", "name": "thread_name", "pid": pid,
                   "tid": tid, "args": {"name": tid_name}})
    for r in rows:
        ts = round((r["t"] - t0) * 1e6, 3)
        if r.get("bytes_in_use") is not None:
            events.append({"name": "memwatch bytes_in_use",
                           "cat": "amgcl", "ph": "C", "ts": ts,
                           "pid": pid,
                           "args": {"bytes_in_use":
                                    r["bytes_in_use"]}})
        if r.get("peak_bytes_in_use") is not None:
            events.append({"name": "memwatch peak_bytes",
                           "cat": "amgcl", "ph": "C", "ts": ts,
                           "pid": pid,
                           "args": {"peak_bytes":
                                    r["peak_bytes_in_use"]}})
        if r.get("phase") not in (None, "sampler"):
            events.append({"name": r["phase"], "cat": "amgcl",
                           "ph": "i", "s": "t", "ts": ts,
                           "pid": pid, "tid": tid,
                           "args": {k: v for k, v in r.items()
                                    if k in ("seam", "tenant",
                                             "outcome", "error")}})
    return {"traceEvents": events}


# ---------------------------------------------------------------------------
# leak-cycle selftest (bench.py --check `memwatch` record)
# ---------------------------------------------------------------------------

def selftest(cycles: int = 3, n: int = 8,
             leak_bytes: Optional[int] = None) -> Dict[str, Any]:
    """Register -> evict -> re-register x ``cycles`` through a real
    :class:`SolverFarm` on a small Poisson operator, asserting (1) the
    measured-vs-ledger join agrees per owner within the declared
    tolerance for a multi-level hierarchy, (2) eviction returns the
    hierarchy owner's measured bytes to 0, and (3) the process census
    returns to baseline every cycle — leaked owner bytes fail the
    record. ``leak_bytes`` (or ``AMGCL_TPU_MEMWATCH_LEAK_BYTES``)
    deliberately pins one device buffer per cycle: the negative
    injection that proves the gate trips."""
    import numpy as np
    import jax.numpy as jnp
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.serve.farm import SolverFarm
    from amgcl_tpu.utils.sample_problem import poisson3d

    if leak_bytes is None:
        try:
            leak_bytes = int(os.environ.get(
                "AMGCL_TPU_MEMWATCH_LEAK_BYTES", "0"))
        except ValueError:
            leak_bytes = 0
    rec: Dict[str, Any] = {"ok": False, "cycles": int(cycles),
                           "n": int(n),
                           "leak_injected_bytes": int(leak_bytes),
                           "tolerance": declared_tolerance(),
                           "checks": []}
    A, rhs = poisson3d(int(n))
    t0 = time.perf_counter()
    leaked_refs: List[Any] = []      # the deliberate leak (negative
    #                                  injection) — pins device buffers
    farm = SolverFarm(max_bytes=0, metrics_port=-1)
    try:
        prm = AMGParams(dtype=jnp.float32, coarse_enough=10,
                        max_levels=4)
        farm.register("leakcheck", A, precond=prm)
        entry = farm.tenants["leakcheck"].entry
        amg = entry.obj.precond

        # -- join check: measured vs ledger per level+slot ---------------
        report = hierarchy_report(amg)
        tol = declared_tolerance()
        join_ok = report.get("drift_ratio") is not None \
            and abs(report["drift_ratio"] - 1.0) <= tol \
            and len(report.get("levels", [])) >= 2
        for row in report.get("levels", []):
            r = row.get("drift_ratio")
            if r is not None and abs(r - 1.0) > tol:
                join_ok = False
        rec["checks"].append({"check": "join_within_tolerance",
                              "ok": join_ok,
                              "levels": len(report.get("levels", [])),
                              "drift_ratio":
                              report.get("drift_ratio")})
        rec["drift_ratio"] = report.get("drift_ratio")

        # -- leak cycle: register -> evict -> re-register ----------------
        baseline = device_sample().get("bytes_in_use") or 0
        rec["baseline_bytes"] = int(baseline)
        slack = max(1 << 16, int(0.02 * baseline))
        cycle_ok = True
        evict_ok = True
        worst_over = 0
        for c in range(int(cycles)):
            assert farm.evict("leakcheck")
            snapshot("memwatch.selftest", outcome="evict")
            if measured_tree_bytes(getattr(amg, "hierarchy",
                                           None)) != 0:
                evict_ok = False
            if leak_bytes > 0:
                leaked_refs.append(
                    jnp.zeros(max(leak_bytes // 4, 1),
                              dtype=jnp.float32))
            # bit-identical re-register: the registry HIT path
            # readmits via the numeric rebuild — the farm's
            # register/evict/rebuild residency machinery end to end
            farm.register("leakcheck", A, precond=prm)
            snapshot("memwatch.selftest", outcome="register")
            now = device_sample().get("bytes_in_use") or 0
            over = int(now - baseline)
            worst_over = max(worst_over, over)
            if over > slack:
                cycle_ok = False
        rec["leaked_bytes"] = max(worst_over, 0) \
            if not cycle_ok else 0
        rec["checks"].append({"check": "evict_zeroes_owner",
                              "ok": evict_ok})
        rec["checks"].append({"check": "cycle_returns_to_baseline",
                              "ok": cycle_ok, "slack_bytes": slack,
                              "worst_over_bytes": worst_over})
        rec["owners"] = owner_table()[:8]
        rec["findings"] = memory_findings(rec)
        rec["ok"] = bool(join_ok and evict_ok and cycle_ok)
    except Exception as e:           # noqa: BLE001
        rec["error"] = repr(e)[:300]
    finally:
        del leaked_refs
        try:
            farm.close()
        except Exception:            # noqa: BLE001
            pass
    rec["wall_s"] = round(time.perf_counter() - t0, 3)
    return rec


def _main(argv: Optional[List[str]] = None) -> int:
    """``python -m amgcl_tpu.telemetry.memwatch --selftest [cycles]``
    (the ``bench.py --check`` subprocess — forces the 8-virtual-device
    CPU topology like the analysis arm). Prints ONE JSON line; exit 0
    when the leak gate holds."""
    args = list(argv if argv is not None else sys.argv[1:])
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    nums = [a for a in args if a.isdigit()]
    # runpy executes this file as ``__main__`` — a SECOND module
    # instance with its own registry/timeline. Route through the
    # canonical package module so the owners registered by the AMG
    # builds land in the same state the selftest reads.
    from amgcl_tpu.telemetry import memwatch as _canon
    result = _canon.selftest(cycles=int(nums[0]) if nums else 3)
    from amgcl_tpu.telemetry import sink as _sink
    print(json.dumps(_sink._clean(result), default=_sink._jsonable))
    return 0 if result.get("ok") else 1


if __name__ == "__main__":
    sys.exit(_main())
