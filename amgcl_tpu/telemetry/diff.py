"""Structured report diffing — cross-run regression attribution.

The observability stack can measure everything about ONE solve (ledger,
health, roofline, compile watch, comm attribution) but until this module
could explain nothing BETWEEN solves: a gate failure printed tolerances,
a trend regression printed two numbers, and a human eyeballed the
ledger/roofline tables to find the stage that moved. This module compares
two records of the same kind — ``SolveReport.to_dict()`` outputs, bench
worker records (``BENCH_r*.json`` payloads), or structured multichip
records — stage by stage, and decomposes the headline delta into ranked
per-stage contributions:

* **wall-time split** — the exact two-term identity
  ``wall_B − wall_A = Δiters · t_iter_B + iters_A · Δt_iter`` separates
  "it takes more iterations" from "each iteration got slower" with no
  residual term.
* **stage join** — per-``(level, stage)`` measured cycle times (PR-4
  roofline rows, keyed exactly like the PR-2 ledger cycle model's stage
  keys) are joined across the two records; each joined stage contributes
  ``Δt · visits`` and the rows are ranked by share of the total
  per-stage movement. Records predating per-stage data degrade to a
  ``gaps`` note, never an error.
* **side channels** — setup seconds, ledger bytes, compile seconds /
  retraces, and (multichip) efficiency + comm-fraction deltas ride the
  same record.

Cross-platform pairs are SKIPPED for every timed quantity (the same rule
every gate applies through ``_record_platform``): a CPU-fallback run vs
a TPU baseline is a platform change, not a regression — iteration counts
and model bytes stay compared, the math is platform-independent.

IMPORTANT: stdlib-only AND free of package-relative imports, like
``telemetry/sink.py`` — ``bench.py``'s supervisor (which must never
import jax) loads this by file path for ``--why``, the ``--trend`` why
column and the gate-failure attribution. Keep it that way.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

#: schema version of the diff record itself (and the version stamped by
#: ``SolveReport.to_dict()`` — a future incompatible report layout bumps
#: both so old diffs stay interpretable)
SCHEMA = 1

#: |wall ratio − 1| below this is jitter, not signal — contributions are
#: still reported but :func:`findings` stays quiet (chained bench
#: timings move ~10-15% across sessions, the same slack the bench
#: gate's time-ratio tolerance absorbs)
_NOISE_RATIO = 0.10


# ---------------------------------------------------------------------------
# record introspection
# ---------------------------------------------------------------------------

def get_path(rec: Any, path: str) -> Any:
    """Dotted-path lookup (``"compile.totals.compile_s"``), None when
    any hop is missing — the ``metrics.extract`` contract, duplicated
    here so this module stays import-free."""
    cur = rec
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def _first(rec: Dict[str, Any], *paths: str) -> Any:
    for p in paths:
        v = get_path(rec, p)
        if v is not None:
            return v
    return None


def _num(v: Any) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v) if math.isfinite(float(v)) else None


def record_kind(rec: Dict[str, Any]) -> str:
    """One of ``"multichip"`` / ``"bench"`` / ``"solve"`` / ``"unknown"``
    — the three record families the observability stack emits. Both
    sides of a diff must agree."""
    if not isinstance(rec, dict):
        return "unknown"
    if rec.get("event") == "multichip_scaling" or (
            "solvers" in rec and "headline" in rec):
        return "multichip"
    if "metric" in rec or "value" in rec or "parsed" in rec:
        return "bench"
    if "iters" in rec and "resid" in rec:
        return "solve"
    return "unknown"


def unwrap(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Driver-wrapper bench rounds keep the worker record under
    ``"parsed"`` (the ``bench_history`` layout) — diff the payload."""
    parsed = rec.get("parsed") if isinstance(rec, dict) else None
    return parsed if isinstance(parsed, dict) else rec


def platform_of(rec: Dict[str, Any]) -> Optional[str]:
    """Device platform of any record kind — the same resolution order
    as bench.py's ``_record_platform`` plus the ``hw_provenance`` stamp
    solve-level reports carry (PR-12 satellite)."""
    rec = unwrap(rec)
    p = _first(rec, "device_platform", "provenance.device_platform",
               "hw_provenance.device_platform")
    if p is None and rec.get("fallback"):
        return "cpu"
    return p


def stage_rows(rec: Dict[str, Any]) -> Dict[Tuple[int, str],
                                            Dict[str, Any]]:
    """Measured per-``(level, stage)`` rows of a record, keyed for the
    join. Sources, in order: a full roofline record's ``stages`` (the
    ``AMG.roofline()`` rows), a bench record's compact
    ``roofline_stages``, or ``resources.roofline.stages`` on a solve
    report that carried the full measurement. Empty dict when the
    record predates per-stage data."""
    rec = unwrap(rec)
    rows = None
    for path in ("roofline.stages", "roofline_stages",
                 "resources.roofline.stages"):
        rows = get_path(rec, path)
        if isinstance(rows, list) and rows:
            break
        rows = None
    out: Dict[Tuple[int, str], Dict[str, Any]] = {}
    for r in rows or []:
        if not isinstance(r, dict):
            continue
        lvl, stage, t = r.get("level"), r.get("stage"), _num(r.get("t_s"))
        if lvl is None or stage is None or t is None:
            continue
        out[(int(lvl), str(stage))] = {
            "t_s": t, "visits": int(r.get("visits", 1) or 1),
            "model_bytes": r.get("model_bytes"),
            "model_flops": r.get("model_flops")}
    return out


def _wall(rec: Dict[str, Any], kind: str) -> Optional[float]:
    if kind == "bench":
        return _num(_first(rec, "value", "wall_per_call_s"))
    return _num(rec.get("wall_time_s"))


def _bytes(rec: Dict[str, Any]) -> Optional[float]:
    return _num(_first(rec, "ledger.hierarchy_bytes",
                       "resources.memory.bytes", "hierarchy.bytes"))


def _compile_s(rec: Dict[str, Any]) -> Optional[float]:
    return _num(_first(rec, "compile.totals.compile_s",
                       "compile.new_compile_s", "compile.compile_s"))


def _retraces(rec: Dict[str, Any]) -> Optional[float]:
    v = _first(rec, "compile.totals.retraces", "compile.retraces")
    if v is None:
        funcs = get_path(rec, "compile.functions")
        if isinstance(funcs, dict):
            v = sum(f.get("retraces", 0) for f in funcs.values()
                    if isinstance(f, dict))
    return _num(v)


def _comm_fraction(rec: Dict[str, Any]) -> Optional[float]:
    return _num(_first(rec, "headline.comm_fraction",
                       "comm.per_iteration.comm_fraction",
                       "resources.comm.per_iteration.comm_fraction"))


# ---------------------------------------------------------------------------
# the diff
# ---------------------------------------------------------------------------

def _pair(a: Optional[float], b: Optional[float],
          higher_better: bool = False) -> Optional[Dict[str, Any]]:
    """One headline row: both values, delta, ratio, and whether the
    movement is a regression in this metric's direction."""
    if a is None or b is None:
        if a is None and b is None:
            return None
        return {"a": a, "b": b, "delta": None, "ratio": None}
    out: Dict[str, Any] = {"a": a, "b": b, "delta": b - a,
                           "ratio": round(b / a, 6) if a else None}
    if a:
        worse = (b < a) if higher_better else (b > a)
        out["regressed"] = bool(worse and abs(b / a - 1.0) > 1e-9)
    return out


def _multichip_diff(a: Dict[str, Any], b: Dict[str, Any],
                    out: Dict[str, Any]) -> Dict[str, Any]:
    ha, hb = a.get("headline") or {}, b.get("headline") or {}
    skip = out["platform"]["skip"]
    head = {}
    for key, hb_better in (("weak_efficiency", True),
                           ("strong_efficiency", True),
                           ("comm_fraction", False),
                           ("imbalance", False),
                           ("wire_gbps", True)):
        if skip and key != "imbalance":
            continue
        row = _pair(_num(ha.get(key)), _num(hb.get(key)),
                    higher_better=hb_better)
        if row is not None:
            head[key] = row
    it = _pair(_num(ha.get("iters")), _num(hb.get("iters")))
    if it is not None:
        head["iters"] = it
    out["headline"] = head
    # per-solver per-mode per-iteration times on the largest shared mesh
    contributions = []
    for skey in sorted(set(a.get("solvers") or {})
                       & set(b.get("solvers") or {})):
        for mode in ("weak", "strong"):
            ca = ((a["solvers"][skey].get(mode) or {}).get("cells")
                  or [])
            cb = ((b["solvers"][skey].get(mode) or {}).get("cells")
                  or [])
            by_nd_a = {c.get("devices"): c for c in ca}
            for c in cb:
                nd = c.get("devices")
                pa = by_nd_a.get(nd)
                if pa is None:
                    continue
                ta, tb = _num(pa.get("t_iter_s")), _num(c.get("t_iter_s"))
                if ta is None or tb is None or skip:
                    continue
                contributions.append({
                    "key": "%s/%s/nd%d" % (skey, mode, nd),
                    "delta_s": tb - ta, "a_s": ta, "b_s": tb})
    tot = sum(abs(c["delta_s"]) for c in contributions) or 1.0
    for c in contributions:
        c["share"] = round(abs(c["delta_s"]) / tot, 4)
        c["delta_s"] = round(c["delta_s"], 9)
    contributions.sort(key=lambda c: -abs(c["delta_s"]))
    out["contributions"] = contributions
    cf = head.get("comm_fraction")
    slowest = contributions[0]["key"] if contributions else None
    if cf is not None and cf.get("regressed") and cf.get("delta") \
            and abs(cf["delta"]) > 0.05:
        out["top"] = "comm_fraction"
    else:
        out["top"] = slowest
    return out


def diff(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Compare record ``a`` (baseline / older) with ``b`` (candidate /
    newer). Returns the structured diff record (see module docstring);
    never raises on missing pieces — absent metrics become ``gaps``
    entries, platform mismatches skip timed rows."""
    a, b = unwrap(a or {}), unwrap(b or {})
    kind_a, kind_b = record_kind(a), record_kind(b)
    out: Dict[str, Any] = {"schema": SCHEMA, "kind": kind_a,
                           "gaps": [], "contributions": [],
                           "stages": [], "by_stage": {}, "top": None}
    plat_a, plat_b = platform_of(a), platform_of(b)
    skip = plat_a is not None and plat_b is not None and plat_a != plat_b
    out["platform"] = {"a": plat_a, "b": plat_b, "skip": skip}
    if kind_a != kind_b and "unknown" not in (kind_a, kind_b):
        out["error"] = "record kinds differ: %s vs %s" % (kind_a, kind_b)
        return out
    if kind_a == "unknown" and kind_b == "unknown":
        out["error"] = "unrecognized record kind on both sides"
        return out
    kind = kind_a if kind_a != "unknown" else kind_b
    out["kind"] = kind
    if skip:
        out["gaps"].append(
            "platform mismatch (%s vs %s): every timed comparison "
            "skipped — iteration counts and model bytes only"
            % (plat_a, plat_b))
    if kind == "multichip":
        return _multichip_diff(a, b, out)

    # -- solve / bench records ----------------------------------------------
    wall_a, wall_b = _wall(a, kind), _wall(b, kind)
    it_a, it_b = _num(a.get("iters")), _num(b.get("iters"))
    head: Dict[str, Any] = {}
    row = _pair(it_a, it_b)
    if row is not None:
        head["iters"] = row
    if not skip:
        row = _pair(wall_a, wall_b)
        if row is not None:
            head["wall_s"] = row
        row = _pair(_num(a.get("setup_s")), _num(b.get("setup_s")))
        if row is not None:
            head["setup_s"] = row
        row = _pair(_compile_s(a), _compile_s(b))
        if row is not None:
            head["compile_s"] = row
    row = _pair(_bytes(a), _bytes(b))
    if row is not None:
        head["ledger_bytes"] = row
    row = _pair(_retraces(a), _retraces(b))
    if row is not None:
        head["retraces"] = row
    row = _pair(_comm_fraction(a), _comm_fraction(b))
    if row is not None and not skip:
        head["comm_fraction"] = row
    out["headline"] = head

    # exact wall split: wall = iters * t_iter, so
    # Δwall = Δiters·t_iter_B + iters_A·Δt_iter (no residual term)
    contributions: List[Dict[str, Any]] = []
    if not skip and None not in (wall_a, wall_b, it_a, it_b) \
            and it_a > 0 and it_b > 0:
        t_a, t_b = wall_a / it_a, wall_b / it_b
        contributions.append({"key": "iterations",
                              "delta_s": (it_b - it_a) * t_b,
                              "detail": "%d -> %d iterations"
                              % (int(it_a), int(it_b))})
        contributions.append({"key": "per_iteration",
                              "delta_s": it_a * (t_b - t_a),
                              "detail": "%.3g -> %.3g s/iter"
                              % (t_a, t_b)})
        head["t_iter_s"] = _pair(t_a, t_b)
    elif None in (wall_a, wall_b) and not skip:
        out["gaps"].append("wall time missing on one side — no "
                           "iterations/per-iteration split")
    sc = head.get("setup_s")
    if sc is not None and sc.get("delta") is not None:
        contributions.append({"key": "setup", "delta_s": sc["delta"]})
    cc = head.get("compile_s")
    if cc is not None and cc.get("delta") is not None:
        contributions.append({"key": "compile", "delta_s": cc["delta"]})
    tot = sum(abs(c["delta_s"]) for c in contributions) or 1.0
    for c in contributions:
        c["share"] = round(abs(c["delta_s"]) / tot, 4)
        c["delta_s"] = round(c["delta_s"], 9)
    contributions.sort(key=lambda c: -abs(c["delta_s"]))
    out["contributions"] = contributions

    # format-decision join: bench records carry the operator X-ray's
    # compact summary (``structure``, telemetry/structure.py) — a
    # changed per-level format winner or decision reason between two
    # rounds is exactly the cross-round movement --why should name
    # (a format flip changes the per-iteration byte model before it
    # changes any timed row)
    st_a = a.get("structure") if isinstance(a.get("structure"), dict) \
        else {}
    st_b = b.get("structure") if isinstance(b.get("structure"), dict) \
        else {}
    if st_a.get("formats") and st_b.get("formats") and (
            st_a.get("formats") != st_b.get("formats")
            or st_a.get("reasons") != st_b.get("reasons")):
        out["structure"] = {
            "changed": True,
            "formats": [st_a.get("formats"), st_b.get("formats")],
            "reasons": [st_a.get("reasons"), st_b.get("reasons")]}

    # stage join: measured per-(level, stage) cycle times, ranked by
    # contribution to the total per-stage movement
    if not skip:
        sa, sb = stage_rows(a), stage_rows(b)
        if not sa or not sb:
            missing = " and ".join(
                side for side, rows in (("baseline", sa),
                                        ("candidate", sb)) if not rows)
            out["gaps"].append(
                "no per-stage roofline rows on the %s record — stage "
                "attribution unavailable (records predate per-stage "
                "data, or the roofline stage was skipped)" % missing)
        else:
            joined = sorted(set(sa) & set(sb))
            stages: List[Dict[str, Any]] = []
            by_stage: Dict[str, float] = {}
            for key in joined:
                ra, rb = sa[key], sb[key]
                visits = max(ra["visits"], rb["visits"])
                dt = (rb["t_s"] - ra["t_s"]) * visits
                stages.append({"level": key[0], "stage": key[1],
                               "a_s": ra["t_s"], "b_s": rb["t_s"],
                               "visits": visits, "delta_s": dt})
                by_stage[key[1]] = by_stage.get(key[1], 0.0) + dt
            only = sorted(set(sa) ^ set(sb))
            if only:
                out["gaps"].append(
                    "%d stage key(s) present on one side only "
                    "(structure changed): %s" % (len(only), ", ".join(
                        "level%d/%s" % k for k in only[:4])))
            stot = sum(abs(s["delta_s"]) for s in stages) or 1.0
            for s in stages:
                s["share"] = round(abs(s["delta_s"]) / stot, 4)
                s["delta_s"] = round(s["delta_s"], 9)
            stages.sort(key=lambda s: -abs(s["delta_s"]))
            out["stages"] = stages
            out["by_stage"] = {
                name: {"delta_s": round(d, 9),
                       "share": round(abs(d) / stot, 4)}
                for name, d in sorted(by_stage.items(),
                                      key=lambda kv: -abs(kv[1]))}
    out["top"] = top_contributor(out)
    return out


def top_contributor(d: Dict[str, Any]) -> Optional[str]:
    """The one name an operator reads first: the dominant joined stage
    (aggregated across levels) when per-stage rows exist and the
    per-iteration leg is what moved; the dominant coarse bucket
    (iterations / setup / compile) otherwise."""
    contributions = d.get("contributions") or []
    if not contributions:
        return None
    top = contributions[0]
    if top["key"] == "per_iteration" and d.get("by_stage"):
        stage = next(iter(d["by_stage"]))
        return "per_iteration:%s" % stage
    return top["key"]


# ---------------------------------------------------------------------------
# findings / rendering
# ---------------------------------------------------------------------------

def findings(d: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Doctor-shaped findings ({severity, code, message, suggestion})
    from a diff record — ``telemetry.diagnose(diff=...)`` folds these
    in, and the gate-failure attribution prints them."""
    out: List[Dict[str, Any]] = []
    if d.get("error"):
        return out
    head = d.get("headline") or {}
    wall = head.get("wall_s") or {}
    ratio = wall.get("ratio")
    top = d.get("top")
    if ratio is not None and ratio - 1.0 > _NOISE_RATIO:
        detail = ""
        contributions = d.get("contributions") or []
        if contributions:
            c = contributions[0]
            detail = " — top contributor %s (%+.3g s, %.0f%% of the " \
                "movement)" % (top or c["key"], c["delta_s"],
                               100 * c["share"])
        stages = d.get("stages") or []
        sugg = None
        if stages and top and top.startswith("per_iteration:"):
            s = stages[0]
            sugg = ("the per-iteration time moved and the stage join "
                    "names level %d %s (%+.3g s/cycle, %.0f%% of the "
                    "per-stage movement) — start there"
                    % (s["level"], s["stage"], s["delta_s"],
                       100 * s["share"]))
        elif top == "iterations":
            sugg = ("the iteration count grew, not the per-iteration "
                    "time — a numerics change (coarsening, smoother, "
                    "tolerance), not a kernel regression")
        elif top == "compile":
            sugg = ("compile time moved — check the retrace findings "
                    "and the persistent compilation cache")
        out.append({"severity": "warning", "code": "cross_run_regression",
                    "message": "solve wall time regressed %.2fx "
                    "(%.4g s -> %.4g s)%s"
                    % (ratio, wall.get("a"), wall.get("b"), detail),
                    **({"suggestion": sugg} if sugg else {})})
    it = head.get("iters") or {}
    if it.get("delta") and it["delta"] > 0 and not out:
        out.append({"severity": "info", "code": "cross_run_iters",
                    "message": "iteration count grew %d -> %d between "
                    "the two runs" % (int(it["a"]), int(it["b"]))})
    cf = head.get("comm_fraction") or {}
    if cf.get("regressed") and cf.get("delta") \
            and abs(cf["delta"]) > 0.05:
        out.append({"severity": "warning", "code": "cross_run_comm",
                    "message": "measured comm fraction grew %.3f -> "
                    "%.3f between the two runs" % (cf["a"], cf["b"]),
                    "suggestion": "check the collective census and the "
                    "halo-exchange plans (--dist-report attributes the "
                    "exposed wall per collective)"})
    rt = head.get("retraces") or {}
    if rt.get("delta") and rt["delta"] > 0:
        out.append({"severity": "info", "code": "cross_run_retraces",
                    "message": "retrace count grew %d -> %d — a shape "
                    "or gate-state change re-traces the solve program"
                    % (int(rt["a"]), int(rt["b"]))})
    st = d.get("structure") or {}
    if st.get("changed"):
        fm = st.get("formats") or ["-", "-"]
        rs = st.get("reasons") or ["-", "-"]
        out.append({"severity": "info", "code": "cross_run_format",
                    "message": "per-level format decisions changed "
                    "between the two runs: %s -> %s (reasons %s -> %s)"
                    % (fm[0], fm[1], rs[0], rs[1]),
                    "suggestion": "the X-ray candidate ledger "
                    "(cli --xray) attributes which structural metric "
                    "or budget moved the decision"})
    return out


def format_diff(d: Dict[str, Any], max_stages: int = 8) -> str:
    """Text rendering — the ``bench.py --why`` / gate-failure section."""
    if d.get("error"):
        return "diff: %s" % d["error"]
    lines = ["Cross-run attribution (%s records)" % d.get("kind")]
    for gap in d.get("gaps") or []:
        lines.append("  (gap: %s)" % gap)
    head = d.get("headline") or {}
    for key in ("wall_s", "t_iter_s", "iters", "setup_s", "compile_s",
                "ledger_bytes", "retraces", "comm_fraction",
                "weak_efficiency", "strong_efficiency", "imbalance",
                "wire_gbps"):
        row = head.get(key)
        if not row:
            continue
        tag = ""
        ratio = row.get("ratio")
        # the arrow marks movement beyond the session-jitter band; the
        # raw ``regressed`` boolean (any worse movement) stays in the
        # record for programmatic consumers
        if row.get("regressed") and (
                ratio is None or abs(ratio - 1.0) > _NOISE_RATIO):
            tag = "  <-- regressed"
        elif ratio is not None:
            tag = "  (%.3fx)" % ratio
        lines.append("  %-14s %12s -> %-12s%s"
                     % (key, _fmt(row.get("a")), _fmt(row.get("b")), tag))
    contributions = d.get("contributions") or []
    if contributions:
        lines.append("  delta decomposition:")
        for c in contributions:
            lines.append("    %-16s %+12.4g s  (%.0f%% of movement)%s"
                         % (c["key"], c["delta_s"], 100 * c["share"],
                            "  [" + c["detail"] + "]"
                            if c.get("detail") else ""))
    stages = d.get("stages") or []
    if stages:
        lines.append("  per-stage join (measured cycle times):")
        for s in stages[:max_stages]:
            lines.append("    level%-2d %-12s %10.4g -> %-10.4g "
                         "%+10.3g s  (%.0f%%)"
                         % (s["level"], s["stage"], s["a_s"], s["b_s"],
                            s["delta_s"], 100 * s["share"]))
        if len(stages) > max_stages:
            lines.append("    ... %d more stage row(s)"
                         % (len(stages) - max_stages))
    st = d.get("structure") or {}
    if st.get("changed"):
        lines.append("  format decisions: %s -> %s (reasons %s -> %s)"
                     % ((st.get("formats") or ["-", "-"])[0],
                        (st.get("formats") or ["-", "-"])[1],
                        (st.get("reasons") or ["-", "-"])[0],
                        (st.get("reasons") or ["-", "-"])[1]))
    if d.get("top"):
        lines.append("  top contributor: %s" % d["top"])
    for f in findings(d):
        lines.append("  [%s] %s" % (f["severity"].upper(), f["message"]))
        if f.get("suggestion"):
            lines.append("      -> %s" % f["suggestion"])
    return "\n".join(lines)


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return "%.6g" % v
    return str(v)


def why(a: Dict[str, Any], b: Dict[str, Any]) -> Optional[str]:
    """The compact ``--trend`` why-column label: the top attributed
    contributor of ``diff(a, b)``, None when nothing is attributable
    (platform skip, missing walls, kind mismatch)."""
    d = diff(a, b)
    if d.get("error") or d["platform"]["skip"]:
        return None
    return d.get("top")


def compact(d: Dict[str, Any], max_stages: int = 8) -> Dict[str, Any]:
    """Bounded copy for embedding in JSONL events / gate records: the
    full headline + contributions, stage rows truncated."""
    out = dict(d)
    stages = d.get("stages") or []
    if len(stages) > max_stages:
        out["stages"] = stages[:max_stages]
        out["stages_truncated"] = len(stages) - max_stages
    return out
