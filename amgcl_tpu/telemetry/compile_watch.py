"""Compile/retrace observer — the silent-latency leg of the telemetry
stack.

On an accelerator the two ways a solve gets slow without any kernel
getting slower are (1) running below the roofline (telemetry/roofline.py)
and (2) recompiling: jit retraces whenever a function sees a new
shape/dtype signature, and a solver loop that perturbs a shape per call
(a growing Krylov basis, a host-side int that should have been static, a
rebuilt operator with a different diagonal count) silently pays seconds
of XLA compile per iteration. Nothing in jax surfaces that per function —
this module does:

* :func:`watched_jit` — drop-in ``jax.jit`` replacement used by our jitted
  entry points. The authoritative registration list is
  :data:`DECLARED_ENTRY_POINTS` below — kept equal to the
  ``watched_jit(name=...)`` call sites in the source by the static
  auditor (analysis/jaxpr_audit.check_entry_points), so this docstring
  can no longer silently drift from reality. It counts **calls** per
  function and
  **traces** per function + abstract-signature (a trace observed for an
  already-seen function with a NEW signature after warmup is recorded
  as a **retrace** event — the "same function, new shape" smell), with
  cache hits = calls − traces.
* a process-global listener on ``jax.monitoring`` duration events
  (``/jax/core/compile/*``) attributes **backend-compile wall time** to
  the watched function currently executing (compiles triggered outside
  any watched function land in the ``<unwatched>`` bucket — probe
  kernels, library internals).
* :func:`snapshot` / :func:`delta` — JSON-clean stats for
  ``SolveReport.compile``, the JSONL sink, and ``bench.py``'s record;
  :func:`findings` turns retrace events into ``telemetry.diagnose()``-
  style findings.

``AMGCL_TPU_COMPILE_WATCH=0`` disables the watcher entirely
(:func:`watched_jit` degrades to plain ``jax.jit``). Kept free of
package-level imports so any ops module can import it without cycles.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Any, Dict, List, Optional

_LOCK = threading.Lock()

#: attribution bucket for compiles observed while no watched function runs
UNWATCHED = "<unwatched>"

#: every watched_jit registration name in the package — the docstring
#: list above, as code. The static auditor
#: (analysis/jaxpr_audit.check_entry_points) asserts this tuple is
#: EXACTLY the set of ``watched_jit(name=...)`` call sites the linter
#: discovers in the source, so the list can no longer drift from
#: reality: adding or renaming a watched entry point without updating
#: it fails `python -m amgcl_tpu.analysis`.
DECLARED_ENTRY_POINTS = (
    "capi.precond_apply",
    "coarsening.device_aggregates",
    "make_solver._solve_fn",
    "ops.dense_window_fused",
    "ops.dense_window_spmv",
    "ops.dia_fused",
    "ops.dia_residual_dot",
    "ops.dia_spmv",
    "ops.dia_spmv_dots",
    "ops.fused_down_sweep",
    "ops.fused_up_sweep",
    "ops.fused_vec",
    "ops.gather_spmv",
    "ops.gather_spmv_xla",
    "ops.level_setup",
    "ops.segment_galerkin",
    "ops.segment_spgemm",
    "ops.stencil_galerkin",
    "ops.transfer_smooth",
    "ops.windowed_ell_block_fused",
    "ops.windowed_ell_block_spmv",
    "ops.windowed_ell_block_spmv_dots",
    "ops.windowed_ell_fused",
    "ops.windowed_ell_spmv",
    "ops.windowed_ell_spmv_dots",
    "parallel.dist_amg_solve",
    "parallel.dist_cg",
    "parallel.dist_cg_pipelined",
    "parallel.dist_exchange",
    "parallel.dist_mis",
    "parallel.dist_stencil_cg",
    "pyamgcl_compat.precond_apply",
    "serve.solve_step",
    "solver.direct.device_inv",
    "telemetry.comm_halo",
    "telemetry.comm_halo_ablated",
    "telemetry.comm_iter",
    "telemetry.comm_iter_ablated",
    "telemetry.comm_psum",
    "telemetry.comm_psum_ablated",
    "telemetry.comm_shard_spmv",
)


def enabled() -> bool:
    return os.environ.get("AMGCL_TPU_COMPILE_WATCH", "1") != "0"


def signature(args, kwargs=None) -> str:
    """Abstract signature of a call: shape/dtype per array leaf (works
    on tracers — this runs at trace time, inside the traced wrapper),
    type:repr for static/python leaves."""
    import numpy as np
    try:
        import jax
        leaves = jax.tree_util.tree_leaves((args, kwargs or {}))
    except Exception:
        leaves = list(args) + list((kwargs or {}).values())
    parts = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            try:
                dt = np.dtype(leaf.dtype).name
            except TypeError:
                dt = str(leaf.dtype)
            parts.append("%s[%s]" % (dt, ",".join(str(d)
                                                  for d in leaf.shape)))
        else:
            parts.append(type(leaf).__name__ + ":" + repr(leaf)[:48])
    return "|".join(parts)


class CompileWatch:
    """Process-global trace/compile counters, keyed by function name and
    abstract signature. All methods are cheap dict updates under a lock —
    nothing here touches the device."""

    def __init__(self):
        self.functions: Dict[str, Dict[str, Any]] = {}
        self.retrace_events: List[Dict[str, Any]] = []
        # per-thread stack of watched fns currently executing — compile
        # durations attribute to the top of the COMPILING thread's stack,
        # so concurrent solves on different threads cannot cross-book
        self._tls = threading.local()
        self._installed = False

    @property
    def _stack(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    # -- bookkeeping ---------------------------------------------------------

    def _fn(self, name: str) -> Dict[str, Any]:
        rec = self.functions.get(name)
        if rec is None:
            rec = self.functions[name] = {
                "calls": 0, "traces": 0, "backend_compiles": 0,
                "compile_s": 0.0, "trace_sigs": {}, "retraces": 0}
        return rec

    def note_call(self, name: str) -> None:
        with _LOCK:
            self._fn(name)["calls"] += 1

    def note_trace(self, name: str, sig: str) -> None:
        """Called from INSIDE the traced function — fires once per actual
        jit trace (Python side effects run at trace time only)."""
        with _LOCK:
            rec = self._fn(name)
            rec["traces"] += 1
            sigs = rec["trace_sigs"]
            if sig not in sigs and sigs:
                # warmup done (>=1 signature already traced) and a NEW
                # signature arrives: the retrace smell
                rec["retraces"] += 1
                self.retrace_events.append({
                    "fn": name, "sig": sig, "prior_sigs": len(sigs)})
            sigs[sig] = sigs.get(sig, 0) + 1

    # -- jax.monitoring attribution ------------------------------------------

    def install(self) -> "CompileWatch":
        if self._installed:
            return self
        self._installed = True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                self._on_duration)
        except Exception:
            pass                  # no monitoring API: trace counts only
        return self

    def _on_duration(self, event: str, duration: float, **kw) -> None:
        # '/jax/core/compile/backend_compile_duration' et al.; everything
        # else on the channel is ignored
        if "backend_compile" not in event:
            return
        cur = self._stack[-1] if self._stack else UNWATCHED
        with _LOCK:
            rec = self._fn(cur)
            rec["backend_compiles"] += 1
            rec["compile_s"] += float(duration)

    # -- export --------------------------------------------------------------

    def snapshot(self, fn: Optional[str] = None) -> Dict[str, Any]:
        """JSON-clean stats: one function's record (``fn=``) or the whole
        table + totals. Copies — safe to diff across calls."""
        with _LOCK:
            if fn is not None:
                rec = self.functions.get(fn)
                return _export_fn(rec) if rec else {
                    "calls": 0, "traces": 0, "backend_compiles": 0,
                    "compile_s": 0.0, "signatures": 0, "retraces": 0,
                    "cache_hits": 0}
            out = {"functions": {name: _export_fn(rec)
                                 for name, rec in self.functions.items()},
                   "retrace_events": [dict(e) for e in
                                      self.retrace_events[-50:]]}
            tot = {"calls": 0, "traces": 0, "backend_compiles": 0,
                   "compile_s": 0.0, "retraces": 0}
            for rec in out["functions"].values():
                for k in tot:
                    tot[k] += rec[k]
            tot["compile_s"] = round(tot["compile_s"], 4)
            out["totals"] = tot
            return out

    def reset(self) -> None:
        with _LOCK:
            self.functions.clear()
            self.retrace_events.clear()


def _export_fn(rec: Dict[str, Any]) -> Dict[str, Any]:
    return {"calls": rec["calls"], "traces": rec["traces"],
            "backend_compiles": rec["backend_compiles"],
            "compile_s": round(rec["compile_s"], 4),
            "signatures": len(rec["trace_sigs"]),
            "retraces": rec["retraces"],
            "cache_hits": max(rec["calls"] - rec["traces"], 0)}


_watch: Optional[CompileWatch] = None


def global_watch() -> CompileWatch:
    """The process-global watcher (monitoring listener installed on first
    use)."""
    global _watch
    if _watch is None:
        _watch = CompileWatch()
    return _watch.install()


def snapshot(fn: Optional[str] = None) -> Dict[str, Any]:
    return global_watch().snapshot(fn)


#: package-level alias (``telemetry.compile_snapshot``) — the bare name
#: ``snapshot`` is too generic to re-export
compile_snapshot = snapshot


def delta(before: Dict[str, Any], after: Dict[str, Any]) -> Dict[str, Any]:
    """after − before over one function's snapshot counters (the
    per-solve ``SolveReport.compile`` delta)."""
    out = {}
    for k in ("calls", "traces", "backend_compiles", "retraces",
              "cache_hits"):
        out["new_" + k] = after.get(k, 0) - before.get(k, 0)
    out["new_compile_s"] = round(after.get("compile_s", 0.0)
                                 - before.get("compile_s", 0.0), 4)
    out["new_signatures"] = after.get("signatures", 0) \
        - before.get("signatures", 0)
    return out


def watched_jit(fn=None, name: Optional[str] = None, **jit_kw):
    """``jax.jit`` with observation: counts calls/traces/compile seconds
    per function + signature through the global watch. Usable as a direct
    call (``watched_jit(f, name=..., static_argnames=...)``) or via
    ``functools.partial`` in a decorator position, like ``jax.jit``
    itself. With ``AMGCL_TPU_COMPILE_WATCH=0`` it IS ``jax.jit``."""
    if fn is None:
        return functools.partial(watched_jit, name=name, **jit_kw)
    import jax
    if not enabled():
        return jax.jit(fn, **jit_kw)
    w = global_watch()
    label = name or getattr(fn, "__qualname__",
                            getattr(fn, "__name__", repr(fn)))

    @functools.wraps(fn)
    def traced(*a, **k):
        w.note_trace(label, signature(a, k))
        return fn(*a, **k)

    jitted = jax.jit(traced, **jit_kw)

    @functools.wraps(fn)
    def call(*a, **k):
        # no signature here: flattening the args on EVERY call would tax
        # the solve hot path — the signature is only needed at trace time
        w.note_call(label)
        stack = w._stack
        stack.append(label)
        try:
            return jitted(*a, **k)
        finally:
            stack.pop()

    call._watched_name = label
    call._jitted = jitted
    # forward the jitted-function surface callers rely on (tests clear
    # the cache to force a re-trace; cost analyses lower without calling)
    for attr in ("clear_cache", "lower", "trace", "eval_shape"):
        if hasattr(jitted, attr):
            setattr(call, attr, getattr(jitted, attr))
    return call


def findings(snap: Optional[Dict[str, Any]] = None,
             max_items: int = 5) -> List[Dict[str, Any]]:
    """Retrace events as ``telemetry.diagnose()``-style findings
    ({severity, code, message, suggestion}) — empty when nothing
    retraced."""
    snap = snap if snap is not None else snapshot()
    out = []
    for ev in snap.get("retrace_events", [])[-max_items:]:
        out.append({
            "severity": "warning", "code": "retrace",
            "message": "%s retraced on a new signature after warmup "
                       "(%d prior signature(s)): %s"
                       % (ev["fn"], ev["prior_sigs"], ev["sig"][:120]),
            "suggestion": "if the shape change is unintentional, pad "
                          "inputs to a stable shape or mark the varying "
                          "argument static; every retrace pays a full "
                          "XLA compile"})
    tot = snap.get("totals", {})
    if tot.get("compile_s", 0) > 0 and not out:
        pass                       # compiles without retraces are normal
    return out
