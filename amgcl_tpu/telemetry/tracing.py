"""Named-scope device tracing — the profiler-tree counterpart for
``jax.profiler`` traces.

``phase(name)`` wraps traced code in ``jax.named_scope`` so the compiled
ops carry an ``amgcl/...`` scope path: a ``jax.profiler.trace()`` capture of
one V-cycle then groups device time under pre_smooth / restrict /
coarse_solve / prolong / post_smooth exactly like the reference's tic/toc
tree (amgcl/profiler.hpp). Zero runtime cost — scopes only annotate op
metadata at trace time.

``annotate(name)`` is the host-side sibling (``jax.profiler
.TraceAnnotation``) for un-traced phases: setup, host packing, dispatch.

Both degrade to no-ops when the underlying jax API is unavailable, so
telemetry never becomes a hard dependency of the numerics.

:class:`RequestSpans` is the serving-path recorder: per-request phase
spans (queue wait, padding, compile, device solve, sync) measured on
the serve worker and exported as a Chrome/Perfetto track compatible
with ``utils.profiler.Profiler.to_chrome_trace``'s epoch-merge — pass
the same ``epoch`` and the request track lands on the CLI profiler's
timeline (``cli.py --serve --trace``).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager, nullcontext
from typing import Dict, List, Optional, Sequence, Tuple

from amgcl_tpu.analysis import lockwitness as _lockwitness

PREFIX = "amgcl/"


def phase(name: str):
    """Trace-time named scope ``amgcl/<name>`` for device code."""
    try:
        import jax
        return jax.named_scope(PREFIX + name)
    except Exception:
        return nullcontext()


def annotate(name: str):
    """Host-side profiler annotation ``amgcl/<name>`` for un-traced work
    (shows as a span on the host timeline of a ``jax.profiler`` trace)."""
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation(PREFIX + name)
    except Exception:
        return nullcontext()


class RequestSpans:
    """Bounded thread-safe recorder of per-request serve phases.

    ``add(request_id, phases)`` takes ``[(phase, start_s, end_s), ...]``
    in ``time.perf_counter()`` seconds; the export renders one
    ``reqNNNNN/phase`` complete event per span, same trace-event shape
    as ``Profiler.to_chrome_trace`` so the tracks merge on a shared
    epoch. Past ``max_events`` spans further requests are dropped (the
    count is carried in the export), mirroring the Profiler cap — a
    long-running service must not grow without bound."""

    def __init__(self, max_events: int = 100_000):
        self.max_events = int(max_events)
        self._lock = threading.Lock()
        # runtime lock witness seam (identity when the knob is off)
        _lockwitness.maybe_instrument(self, "tracing")
        #: (path, start_s, end_s) — the Profiler.events triple
        self.events: List[Tuple[str, float, float]] = []
        self.dropped = 0
        self._t0 = time.perf_counter()

    def add(self, request_id: int,
            phases: Sequence[Tuple[str, float, float]],
            label: str = "req") -> None:
        """``label`` prefixes the span path: per-request spans ride
        ``req<id>/...``, batch-shared phases (pad/compile/solve/sync are
        one device dispatch for the whole bucket) ride ``batch<id>/...``
        ONCE instead of B identical copies."""
        with self._lock:
            if len(self.events) + len(phases) > self.max_events:
                self.dropped += len(phases)
                return
            for name, start, end in phases:
                self.events.append(
                    ("%s%05d/%s" % (label, int(request_id), name),
                     float(start), float(end)))

    def to_chrome_trace(self, tid: int = 0,
                        tid_name: Optional[str] = None, pid: int = 0,
                        epoch: Optional[float] = None) -> Dict:
        """Chrome/Perfetto trace-event dict of the recorded spans —
        concatenate ``traceEvents`` with other tracks sharing the same
        ``epoch`` (see ``Profiler.to_chrome_trace``)."""
        t0 = self._t0 if epoch is None else epoch
        with self._lock:
            spans = list(self.events)
            dropped = self.dropped
        events = []
        if tid_name:
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": tid_name}})
        for path, start, end in spans:
            events.append({
                "name": path.rsplit("/", 1)[-1], "cat": "amgcl/serve",
                "ph": "X", "ts": round((start - t0) * 1e6, 3),
                "dur": round((end - start) * 1e6, 3),
                "pid": pid, "tid": tid, "args": {"path": path}})
        if dropped:
            last_end = spans[-1][2] if spans else t0
            events.append({
                "name": "spans_dropped", "cat": "amgcl/serve",
                "ph": "i", "s": "g",
                "ts": round((last_end - t0) * 1e6, 3),
                "pid": pid, "tid": tid,
                "args": {"dropped": dropped, "cap": self.max_events}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}


#: thread-local holder of the profiler the CURRENT hierarchy build is
#: annotating into — lets deep setup stages (device MIS, Galerkin plan
#: construction, segment kernels) attribute themselves without threading
#: a profiler argument through every coarsening policy signature
_setup_tls = threading.local()


@contextmanager
def setup_scope(prof, name: str):
    """Setup-phase instrumentation in one wrapper: a tic/toc scope on
    ``prof`` (utils/profiler.Profiler — wall time, optionally device-
    synced) AND an ``amgcl/setup/<name>`` host annotation so a
    ``jax.profiler`` capture of the build shows the same tree. ``prof``
    may be None (annotation only) — the numerics never depend on a
    profiler being attached.

    While the scope is open the profiler is published thread-locally so
    :func:`setup_substage` can attach nested stages from code that never
    sees the AMG builder (``<scope>/<substage>`` in the profile)."""
    ann = annotate("setup/" + name)
    prev = getattr(_setup_tls, "scope", None)
    _setup_tls.scope = (prof, name)
    try:
        if prof is None:
            with ann:
                yield
        else:
            with ann, prof.scope(name):
                yield
    finally:
        _setup_tls.scope = prev


@contextmanager
def setup_substage(name: str):
    """Nested setup stage under whatever :func:`setup_scope` is active
    on this thread (no-op profiler-wise outside a build): device-MIS
    rounds, plan construction and the numeric segment kernels report
    through this, so ``AMG.setup_profile`` attributes the device-setup
    path stage by stage like the host path."""
    cur = getattr(_setup_tls, "scope", None)
    ann = annotate("setup/" + (cur[1] + "/" if cur else "") + name)
    if cur is None or cur[0] is None:
        with ann:
            yield
        return
    prof, _parent = cur
    # Profiler scopes nest on a stack — the path renders as
    # "<parent>/<name>" without re-prefixing here
    with ann, prof.scope(name):
        yield
