"""Named-scope device tracing — the profiler-tree counterpart for
``jax.profiler`` traces.

``phase(name)`` wraps traced code in ``jax.named_scope`` so the compiled
ops carry an ``amgcl/...`` scope path: a ``jax.profiler.trace()`` capture of
one V-cycle then groups device time under pre_smooth / restrict /
coarse_solve / prolong / post_smooth exactly like the reference's tic/toc
tree (amgcl/profiler.hpp). Zero runtime cost — scopes only annotate op
metadata at trace time.

``annotate(name)`` is the host-side sibling (``jax.profiler
.TraceAnnotation``) for un-traced phases: setup, host packing, dispatch.

Both degrade to no-ops when the underlying jax API is unavailable, so
telemetry never becomes a hard dependency of the numerics.
"""

from __future__ import annotations

from contextlib import contextmanager, nullcontext

PREFIX = "amgcl/"


def phase(name: str):
    """Trace-time named scope ``amgcl/<name>`` for device code."""
    try:
        import jax
        return jax.named_scope(PREFIX + name)
    except Exception:
        return nullcontext()


def annotate(name: str):
    """Host-side profiler annotation ``amgcl/<name>`` for un-traced work
    (shows as a span on the host timeline of a ``jax.profiler`` trace)."""
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation(PREFIX + name)
    except Exception:
        return nullcontext()


@contextmanager
def setup_scope(prof, name: str):
    """Setup-phase instrumentation in one wrapper: a tic/toc scope on
    ``prof`` (utils/profiler.Profiler — wall time, optionally device-
    synced) AND an ``amgcl/setup/<name>`` host annotation so a
    ``jax.profiler`` capture of the build shows the same tree. ``prof``
    may be None (annotation only) — the numerics never depend on a
    profiler being attached."""
    ann = annotate("setup/" + name)
    if prof is None:
        with ann:
            yield
        return
    with ann, prof.scope(name):
        yield
