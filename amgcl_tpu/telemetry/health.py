"""Numerical-health layer: in-loop guard detection, per-level convergence
probes, and the convergence doctor.

The reference reports convergence as ``(iters, error)`` and nothing else
(make_solver.hpp, cg.hpp) — when CG stalls, BiCGStab hits an
omega-breakdown, or a mixed-precision solve drifts, the user sees an
iteration count. This module is the numerics leg of the telemetry
subsystem (time = PR 1 tracing, space = PR 2 ledger):

* **Guards** — a :class:`HealthState` carried through every Krylov
  solver's ``lax.while_loop`` (plumbed by ``HistoryMixin``): NaN/Inf
  residuals, Krylov breakdowns (rho/omega/alpha ≈ 0, Hessenberg
  breakdown), loss of positive definiteness, stagnation and divergence,
  recorded as a compact bitmask + per-flag first-trip iteration so the
  whole thing stays jit-compatible (a handful of scalar ops per
  iteration — no extra reductions, no host syncs). Fatal trips freeze
  the iterate at the last committed state and terminate the loop, so a
  breakdown returns finite history instead of NaN-filled arrays.
* **Probes** — setup-time diagnostics (:func:`two_grid_factor`,
  :func:`probe_hierarchy`, surfaced as ``AMG.probe_convergence()``):
  the measured per-level error-reduction factor of the cycle rooted at
  each level (test-vector cycling, normalized each step) and the
  smoother's spectral radius by power iteration — a bad coarsening
  level is identifiable before the first solve.
* **Doctor** — :func:`diagnose` turns report + health + ledger + probe
  into ranked human-readable findings with suggested parameter changes
  (``cli.py --doctor``).

Thresholds (env-tunable, read at trace time):

  AMGCL_TPU_DIVERGENCE_BREAK  1 (default): a divergence trip terminates
                              the while_loop instead of burning maxiter
  AMGCL_TPU_DIV_WINDOW        consecutive diverging iterations before
                              the divergence flag trips (default 5)
  AMGCL_TPU_DIV_RTOL          an iteration counts as diverging only when
                              the residual both grew AND sits this
                              factor above the best residual seen
                              (default 10) — BiCGStab/IDR(s) residuals
                              legitimately oscillate, so plain
                              consecutive-growth counting would kill
                              converging solves
  AMGCL_TPU_STAG_WINDOW       consecutive low-reduction iterations
                              before the stagnation flag (default 10)
  AMGCL_TPU_STAG_RTOL         per-iteration reduction factor below
                              which an iteration counts as stalled
                              (default 0.99: res > 0.99·prev trips)
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, List, NamedTuple, Optional

import jax.numpy as jnp

# -- flag bits (compact bitmask carried through the device loop) ------------

NAN = 1                      # non-finite residual
BREAKDOWN_RHO = 2            # <rhat, r> / shadow-space projection ≈ 0
BREAKDOWN_OMEGA = 4          # minimal-residual step length ≈ 0
BREAKDOWN_ALPHA = 8          # search-direction denominator ≈ 0
BREAKDOWN_HESSENBERG = 16    # Arnoldi h[j+1,j] ≈ 0 before convergence
INDEFINITE = 32              # p·Ap ≤ 0 under CG (operator not SPD)
STAGNATION = 64              # reduction below threshold over a window
DIVERGENCE = 128             # residual grew K consecutive iterations

FLAG_BITS = (NAN, BREAKDOWN_RHO, BREAKDOWN_OMEGA, BREAKDOWN_ALPHA,
             BREAKDOWN_HESSENBERG, INDEFINITE, STAGNATION, DIVERGENCE)
FLAG_NAMES = {
    NAN: "nan", BREAKDOWN_RHO: "breakdown_rho",
    BREAKDOWN_OMEGA: "breakdown_omega", BREAKDOWN_ALPHA: "breakdown_alpha",
    BREAKDOWN_HESSENBERG: "breakdown_hessenberg", INDEFINITE: "indefinite",
    STAGNATION: "stagnation", DIVERGENCE: "divergence"}
N_FLAGS = len(FLAG_BITS)
BREAKDOWN_MASK = (BREAKDOWN_RHO | BREAKDOWN_OMEGA | BREAKDOWN_ALPHA
                  | BREAKDOWN_HESSENBERG)
_IDX = {bit: i for i, bit in enumerate(FLAG_BITS)}


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def divergence_break_enabled() -> bool:
    return os.environ.get("AMGCL_TPU_DIVERGENCE_BREAK", "1") != "0"


def fatal_mask() -> int:
    """Flags that terminate the while_loop: NaN and Krylov breakdowns
    always (the iterate cannot recover and the state would go NaN),
    divergence behind AMGCL_TPU_DIVERGENCE_BREAK (default on). Read at
    trace time — a static constant in the compiled cond."""
    m = NAN | BREAKDOWN_MASK
    if divergence_break_enabled():
        m |= DIVERGENCE
    return m


# -- the device-loop state ---------------------------------------------------

class HealthState(NamedTuple):
    """Compact guard state carried through the ``lax.while_loop``: a
    bitmask, per-flag first-trip iterations, and the stagnation/
    divergence window counters. ~40 bytes of scalars — negligible next
    to the solver's vector carry."""
    flags: Any       # int32 bitmask of FLAG_BITS
    first_it: Any    # (N_FLAGS,) int32, -1 until the flag first trips
    prev_res: Any    # last committed residual norm (real scalar)
    best_res: Any    # best committed residual norm (divergence anchor)
    stag: Any        # consecutive iterations with reduction below rtol
    div: Any         # consecutive diverging iterations


def init_state(res0) -> HealthState:
    r0 = jnp.real(jnp.asarray(res0))
    return HealthState(
        jnp.zeros((), jnp.int32),
        jnp.full((N_FLAGS,), -1, jnp.int32),
        r0, r0,
        jnp.zeros((), jnp.int32),
        jnp.zeros((), jnp.int32))


def bad_denom(v):
    """A denominator that signals breakdown: non-finite, exactly zero,
    or underflowed to subnormal. Deliberately conservative — legitimate
    denominators shrink with the residual (rho ~ res²) but stay far
    above the subnormal threshold at any practical tolerance, so a
    converging solve never false-trips."""
    a = jnp.abs(v)
    tiny = jnp.finfo(a.dtype).tiny
    return ~jnp.isfinite(a) | (a <= tiny)


def trip(hs: HealthState, it, bit: int, cond) -> HealthState:
    """Set ``bit`` where ``cond`` (traced bool), recording the first-trip
    iteration."""
    idx = _IDX[bit]
    cond = jnp.asarray(cond)
    flags = jnp.where(cond, hs.flags | bit, hs.flags)
    first = jnp.where(cond & (hs.first_it[idx] < 0),
                      jnp.asarray(it, jnp.int32), hs.first_it[idx])
    return hs._replace(flags=flags, first_it=hs.first_it.at[idx].set(first))


def step(hs: HealthState, it, res, trips=()):
    """One guard update at iteration ``it`` with candidate residual norm
    ``res`` (the value the solver is about to commit).

    ``trips`` is a sequence of ``(bit, cond)`` or ``(bit, cond, fatal)``
    tuples for solver-specific breakdown conditions (``fatal`` defaults
    True; informational flags like INDEFINITE pass False).

    Returns ``(ok, hs)``: ``ok`` is the commit mask — False on a fatal
    trip (non-finite residual or breakdown), in which case the solver
    keeps its previous state, skips the history write and does not count
    the iteration; the loop then exits through :func:`keep_going`.
    Stagnation/divergence counters advance only on committed steps."""
    res = jnp.real(res)
    fatal = ~jnp.isfinite(res)
    hs = trip(hs, it, NAN, ~jnp.isfinite(res))
    for t in trips:
        bit, cond = t[0], jnp.asarray(t[1])
        is_fatal = t[2] if len(t) > 2 else True
        hs = trip(hs, it, bit, cond)
        if is_fatal:
            fatal = fatal | cond
    ok = ~fatal
    stag_rtol = _env_float("AMGCL_TPU_STAG_RTOL", 0.99)
    stag_win = _env_int("AMGCL_TPU_STAG_WINDOW", 10)
    div_win = _env_int("AMGCL_TPU_DIV_WINDOW", 5)
    div_rtol = _env_float("AMGCL_TPU_DIV_RTOL", 10.0)
    stalled = res > stag_rtol * hs.prev_res
    # divergence needs BOTH step-to-step growth and a residual well above
    # the best seen — non-monotone methods (BiCGStab, IDR(s)) routinely
    # grow for a few iterations near the current floor and then drop;
    # only sustained growth far off the floor is a genuine runaway
    grew = (res > hs.prev_res) & (res > div_rtol * hs.best_res)
    stag = jnp.where(ok, jnp.where(stalled, hs.stag + 1, 0), hs.stag)
    div = jnp.where(ok, jnp.where(grew, hs.div + 1, 0), hs.div)
    hs = hs._replace(stag=stag, div=div,
                     prev_res=jnp.where(ok, res, hs.prev_res),
                     best_res=jnp.where(ok, jnp.minimum(res, hs.best_res),
                                        hs.best_res))
    hs = trip(hs, it, STAGNATION, stag >= stag_win)
    hs = trip(hs, it, DIVERGENCE, div >= div_win)
    return ok, hs


def keep_going(hs: HealthState):
    """while_loop continuation term: False once any fatal flag tripped
    (NaN, breakdown, or — behind AMGCL_TPU_DIVERGENCE_BREAK — an
    explicit divergence), so a broken solve stops instead of burning
    ``maxiter``."""
    return (hs.flags & fatal_mask()) == 0


def commit(ok, new, old):
    """Commit-mask a candidate loop state: ``where(ok, new, old)`` over
    the tree, so a fatal trip freezes the iterate at the last good
    state (finite history, finite residual)."""
    import jax
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(ok, a, b), new, old)


# -- host-side decode --------------------------------------------------------

def decode(flags, first_it=None) -> Dict[str, Any]:
    """Fetched guard state -> the structured ``SolveReport.health``
    dict: tripped flag names, per-flag first-trip iteration, and the
    headline booleans (``nan``/``diverged``/``stagnated``) plus the
    breakdown kind + iteration the acceptance contract names."""
    flags = int(flags)
    fi = [int(v) for v in first_it] if first_it is not None \
        else [-1] * N_FLAGS
    names = [FLAG_NAMES[b] for b in FLAG_BITS if flags & b]
    first = {FLAG_NAMES[b]: fi[_IDX[b]] for b in FLAG_BITS
             if flags & b and fi[_IDX[b]] >= 0}
    bk_bits = [b for b in FLAG_BITS if (b & BREAKDOWN_MASK) and (flags & b)]
    bk = None
    if bk_bits:
        bk = min(bk_bits, key=lambda b: fi[_IDX[b]] if fi[_IDX[b]] >= 0
                 else 1 << 30)
    out = {
        "ok": flags == 0,
        "flags": names,
        "first_trip": first,
        "nan": bool(flags & NAN),
        "diverged": bool(flags & DIVERGENCE),
        "stagnated": bool(flags & STAGNATION),
        "indefinite": bool(flags & INDEFINITE),
        "breakdown": FLAG_NAMES[bk] if bk else None,
    }
    if bk and fi[_IDX[bk]] >= 0:
        out["breakdown_iteration"] = fi[_IDX[bk]]
    return out


# -- per-level convergence probes -------------------------------------------

def two_grid_factor(hier, level: int = 0, n_iters: int = 12,
                    seed: int = 1234, tail: int = 4) -> Dict[str, Any]:
    """Measured error-reduction factor of the multigrid cycle rooted at
    ``level``: iterate e <- e - cycle(level, A e) on a random error
    vector (zero rhs — the exact-solution trick, so the iterate IS the
    error), normalizing each step; after transients die the per-step
    norm ratio converges to the asymptotic convergence factor (the
    standard AMG quality diagnostic — per-level factors near 1 name the
    level where coarsening fails). Returns the geometric mean of the
    last ``tail`` factors plus the step series."""
    import numpy as np
    import jax
    from jax import lax
    from amgcl_tpu.ops import device as dev

    lv = hier.levels[level]
    A = lv.A
    n = A.shape[1] * getattr(A, "block", (1, 1))[1]
    dtype = A.dtype
    e0 = np.random.RandomState(seed + level).standard_normal(n)
    e0 = jnp.asarray(e0 / np.linalg.norm(e0), dtype)

    def run(h, e):
        def body(e, _):
            Ae = dev.spmv(h.levels[level].A, e)
            e2 = e - h.cycle(level, Ae)
            nrm = jnp.sqrt(jnp.abs(dev.inner_product(e2, e2)))
            return e2 / jnp.where(nrm == 0, 1.0, nrm), nrm

        _, factors = lax.scan(body, e, None, length=n_iters)
        return factors

    factors = np.asarray(jax.jit(run)(hier, e0), np.float64)
    good = factors[-tail:][np.isfinite(factors[-tail:])]
    good = good[good > 0]
    cf = float(np.exp(np.mean(np.log(good)))) if good.size else None
    return {"level": int(level), "conv_factor": cf,
            "factors": [float(f) for f in factors]}


def smoother_rho(hier, level: int, n_iters: int = 20,
                 seed: int = 4321) -> Optional[float]:
    """Spectral-radius estimate of the smoother's error operator
    E = I - W A by power iteration (one relaxation sweep on zero rhs is
    exactly one application of E). rho(E) >= 1 means the smoother alone
    diverges on that level — the doctor's 'reduce damping' finding."""
    import numpy as np
    import jax
    from jax import lax
    from amgcl_tpu.ops import device as dev

    lv = hier.levels[level]
    if lv.relax is None or lv.A is None:
        return None
    A = lv.A
    n = A.shape[1] * getattr(A, "block", (1, 1))[1]
    v0 = np.random.RandomState(seed + level).standard_normal(n)
    v0 = jnp.asarray(v0 / np.linalg.norm(v0), A.dtype)

    def run(h, v):
        lvl = h.levels[level]
        zero = jnp.zeros_like(v)

        def body(v, _):
            w = lvl.relax.apply_post(lvl.A, zero, v)
            nrm = jnp.sqrt(jnp.abs(dev.inner_product(w, w)))
            return w / jnp.where(nrm == 0, 1.0, nrm), nrm

        _, norms = lax.scan(body, v, None, length=n_iters)
        return norms

    norms = np.asarray(jax.jit(run)(hier, v0), np.float64)
    good = norms[-4:][np.isfinite(norms[-4:])]
    good = good[good > 0]
    return float(np.exp(np.mean(np.log(good)))) if good.size else None


def probe_hierarchy(hier, n_iters: int = 12, seed: int = 1234,
                    with_smoother: bool = True) -> List[Dict[str, Any]]:
    """Per-level probe rows: the cycle convergence factor rooted at each
    level (:func:`two_grid_factor`) and the smoother spectral radius.
    The coarsest (direct-solved) level is exact by construction and is
    reported with its measured (eps-level) factor for completeness."""
    rows = []
    for i, lv in enumerate(hier.levels):
        if lv.A is None:      # device_filter placeholder level
            rows.append({"level": i, "conv_factor": None})
            continue
        row = two_grid_factor(hier, i, n_iters=n_iters, seed=seed)
        row["rows"] = int(lv.A.shape[0] * getattr(lv.A, "block",
                                                  (1, 1))[0])
        if with_smoother and lv.relax is not None:
            row["smoother_rho"] = smoother_rho(hier, i, seed=seed)
        rows.append(row)
    return rows


# -- the convergence doctor --------------------------------------------------

_SEV_ORDER = {"critical": 0, "warning": 1, "info": 2}


def _finding(sev, code, message, suggestion=None):
    f = {"severity": sev, "code": code, "message": message}
    if suggestion:
        f["suggestion"] = suggestion
    return f


def serve_findings(serve: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Serve-side findings from an SLO-watchdog window summary
    (``SolverService.slo_summary()``): which thresholds tripped, WHERE
    the latency went (the span breakdown names the dominant phase), and
    whether the batching itself wastes work (padding). Pure dict
    crunching, same {severity, code, message, suggestion} shape as the
    solve-side findings — :func:`diagnose` folds these in via its
    ``serve=`` argument, and the watchdog emits them on ``slo``
    events."""
    out: List[Dict[str, Any]] = []
    trips = serve.get("trips") or []
    slo = serve.get("slo") or {}
    spans = serve.get("spans_ms") or {}
    window = serve.get("window")
    if "p99" in trips:
        # attribute the latency to the dominant phase: the fix for a
        # queue-bound p99 (batch shape) is the opposite of the fix for
        # a solve-bound one (make the solve itself faster)
        parts = {k: spans.get(k) or 0.0
                 for k in ("queue", "pad", "compile", "solve", "sync")}
        total = sum(parts.values()) or 1.0
        dom = max(parts, key=parts.get)
        msg = ("serving p99 latency %.1f ms exceeds the %.1f ms SLO "
               "over the last %s request(s) — dominated by %s_ms "
               "(%.0f%% of the span breakdown)"
               % (serve.get("p99_ms", float("nan")),
                  slo.get("p99_ms", float("nan")), window, dom,
                  100.0 * parts[dom] / total))
        sug = {
            "queue": "raise the batch bucket B or shorten the flush "
                     "deadline (AMGCL_TPU_SERVE_FLUSH_MS) so requests "
                     "spend less time queued; add worker capacity if "
                     "the queue depth keeps growing",
            "pad": "host packing dominates — submit contiguous "
                   "float buffers of the solver dtype to avoid "
                   "per-request conversion copies",
            "compile": "cold XLA compiles dominate — warm every "
                       "(shape, B) bucket at startup (submit one dummy "
                       "request per bucket) so traffic never pays them",
            "solve": "the device solve itself dominates — batching "
                     "cannot help; cut iterations (stronger "
                     "preconditioner) or move to a faster device",
            "sync": "result fetch/decode dominates — keep results on "
                    "device or batch the host round trips",
        }[dom]
        out.append(_finding("critical", "slo_p99", msg, sug))
    if "timeout_rate" in trips:
        out.append(_finding(
            "critical", "slo_timeout_rate",
            "%.1f%% of the last %s request(s) timed out in the serve "
            "queue (SLO %.1f%%)"
            % (100 * serve.get("timeout_rate", 0), window,
               100 * slo.get("timeout_rate", 0)),
            "the service is overloaded: raise AMGCL_TPU_SERVE_TIMEOUT_S "
            "only if callers tolerate the latency — otherwise add "
            "capacity or shed load (submit(block=False) backpressure)"))
    if "unhealthy_rate" in trips:
        out.append(_finding(
            "critical", "slo_unhealthy_rate",
            "%.1f%% of the last %s request(s) finished with tripped "
            "health guards (SLO %.1f%%)"
            % (100 * serve.get("unhealthy_rate", 0), window,
               100 * slo.get("unhealthy_rate", 0)),
            "inspect the per-request health decodes (serve_request "
            "events / SolveReport.health) and run cli.py --doctor on a "
            "failing rhs — a systematic breakdown is an operator/"
            "preconditioner problem, not a serving problem"))
    fill = serve.get("batch_fill")
    if fill is not None and fill < 0.5:
        out.append(_finding(
            "warning", "serve_padding_waste",
            "mean batch_fill %.2f < 0.5 — over half the padded bucket "
            "columns are zero-padding, wasted device work the ledger "
            "books as padding_waste bytes/FLOPs" % fill,
            "shrink the bucket (batch B) toward the real arrival rate, "
            "or raise the flush deadline (AMGCL_TPU_SERVE_FLUSH_MS) so "
            "batches fill before dispatch"))
    return out


def farm_findings(farm: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Farm-side findings from a :meth:`SolverFarm.stats` rollup: each
    tenant's tripped SLO window becomes the serve-side findings with
    the tenant named (one tenant's breach must be attributable without
    polluting its neighbors' rows), plus the farm-level pathologies the
    per-tenant windows cannot see — eviction thrash (the byte budget
    cycling hierarchies in and out every few batches) and a pool at
    its cap. Same {severity, code, message, suggestion} shape;
    :func:`diagnose` folds these in via ``farm=``."""
    out: List[Dict[str, Any]] = []
    if not farm:
        return out
    for row in farm.get("tenants") or []:
        summ = row.get("slo_summary") or {}
        if not summ.get("trips"):
            continue
        for f in serve_findings(summ):
            f = dict(f, tenant=row.get("tenant"),
                     message="tenant %r: %s" % (row.get("tenant"),
                                                f["message"]))
            out.append(f)
    batches = farm.get("batches") or 0
    evictions = farm.get("evictions") or 0
    if batches >= 4 and evictions > batches / 2:
        out.append(_finding(
            "warning", "farm_eviction_thrash",
            "%d eviction(s) over %d batch(es) — the HBM budget cycles "
            "hierarchies in and out faster than they amortize their "
            "rebuild cost" % (evictions, batches),
            "raise AMGCL_TPU_FARM_MAX_BYTES, shrink the working set "
            "(fewer co-resident tenants per device), or batch each "
            "tenant's traffic into longer runs so a resident "
            "hierarchy serves more solves per admission"))
    pool = farm.get("pool") or {}
    total = pool.get("total_bytes") or 0
    used = pool.get("used_bytes") or 0
    if total and used > 0.95 * total and not out:
        out.append(_finding(
            "info", "farm_pool_near_cap",
            "farm HBM pool at %.0f%% of its %d-byte budget — the next "
            "admission will evict" % (100.0 * used / total, total),
            None))
    return out


def recovery_findings(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Findings from a recovery-ladder trail (``SolveReport.recovery``,
    faults/recovery.py): how the solve was saved, whether the saving
    rung should become the configuration (escalations that recur are a
    config smell, not a fault), and thrash — the ladder re-running on
    one operator solve after solve. Same {severity, code, message,
    suggestion} shape; :func:`diagnose` folds these via ``recovery=``."""
    out: List[Dict[str, Any]] = []
    if not isinstance(rec, dict):
        return out
    attempts = rec.get("attempts") or []
    final = rec.get("final_rung")
    if rec.get("recovered"):
        flags = sorted({f for a in attempts
                        for f in (a.get("flags") or [])})
        sug = {
            "last_good": "the fault was transient (injected or "
                         "environmental) — no config change needed; "
                         "check the fault/flight events for the source",
            "precision": "f32 ran out of range/accuracy for this "
                         "system — build the bundle with "
                         "dtype=float64 (or refine>0) instead of "
                         "paying a failed f32 solve first",
            "solver": "the configured solver breaks down on this "
                      "operator — adopt the ladder's fallback solver "
                      "as the configuration",
            "smoother": "the smoother diverges on this operator — "
                        "configure damped_jacobi (or chebyshev) "
                        "directly",
        }.get(final)
        out.append(_finding(
            "warning", "recovered",
            "solve recovered on rung %r after %d attempt(s) "
            "(flags along the way: %s)"
            % (final, len(attempts), ", ".join(flags) or "none"), sug))
    elif attempts and not attempts[-1].get("ok"):
        # recovered=False with a SUCCESSFUL last attempt is the clean
        # recovery-enabled solve (one ok initial attempt, no ladder) —
        # not an exhaustion; only a failed trail is critical
        out.append(_finding(
            "critical", "recovery_exhausted",
            "recovery ladder exhausted after %d attempt(s): %s"
            % (len(attempts),
               " -> ".join(a.get("rung", "?") for a in attempts)),
            "the failure survives precision escalation, solver "
            "switching and the smoother fallback — inspect the flight "
            "bundle (reason recovery_exhausted) and the operator "
            "itself (singular? inconsistent rhs?)"))
    runs = rec.get("runs") or 0
    if runs >= 3:
        out.append(_finding(
            "warning", "recovery_thrash",
            "the recovery ladder has run %d times on this operator — "
            "every solve is paying failed attempts before the rung "
            "that works" % runs,
            "promote the recovering rung to the configuration (see "
            "the 'recovered' finding) instead of re-escalating per "
            "solve"))
    return out


def diagnose(report, ledger: Optional[Dict[str, Any]] = None,
             probe: Optional[List[Dict[str, Any]]] = None,
             tol: Optional[float] = None,
             maxiter: Optional[int] = None,
             roofline: Optional[Dict[str, Any]] = None,
             compile_stats: Optional[Dict[str, Any]] = None,
             serve: Optional[Dict[str, Any]] = None,
             comm: Optional[Dict[str, Any]] = None,
             farm: Optional[Dict[str, Any]] = None,
             diff: Optional[Dict[str, Any]] = None,
             recovery: Optional[Dict[str, Any]] = None,
             structure: Optional[Dict[str, Any]] = None,
             memory: Optional[Dict[str, Any]] = None
             ) -> List[Dict[str, Any]]:
    """Rank-ordered findings from one solve: report (+ its ``health``
    guard decode), the resource ledger, the per-level probe rows, and —
    the efficiency leg — a roofline join (``AMG.roofline()``: its ranked
    bottleneck stages ride along) and compile-watch stats (retraces
    after warmup become findings; so does compile time dominating the
    solve). ``serve`` takes an SLO-watchdog window summary
    (``SolverService.slo_summary()``) and folds in the serve-side
    findings (:func:`serve_findings`). ``comm`` takes a measured comm
    attribution (``telemetry.comm.comm_attribution()``) and folds in
    the model-vs-measured divergence findings — comm-bound iterations,
    wire rates far off the ICI peak, host-virtual-mesh caveats.
    ``farm`` takes a :meth:`SolverFarm.stats` rollup and folds in the
    per-tenant SLO breaches (tenant-named) plus the eviction-thrash /
    pool-pressure findings (:func:`farm_findings`). ``diff`` takes a
    ``telemetry.diff.diff()`` record (two solves/bench rounds compared
    stage by stage) and folds in the cross-run attribution findings —
    the doctor names the culprit stage of a regression, not just the
    regression. ``structure`` takes an operator X-ray
    (``AMG.structure_report()``) and folds in the structure findings —
    advisor reorder gains, budget-starved format decisions, padding
    waste, and (when ``roofline`` rode along too) the
    predicted-vs-achieved divergence per format. ``memory`` takes a
    measured-vs-model memory join (``AMG.memory_report()`` or a
    memwatch selftest record) and folds in the drift / leak /
    unattributed-footprint findings
    (:func:`~amgcl_tpu.telemetry.memwatch.memory_findings`). Each
    finding: {severity, code, message, suggestion}. Pure host-side
    dict-crunching — never raises on missing pieces."""
    out: List[Dict[str, Any]] = []
    health = getattr(report, "health", None) or {}
    resid = getattr(report, "resid", None)
    iters = getattr(report, "iters", None)
    rate = getattr(report, "convergence_rate", None)
    extra = getattr(report, "extra", None) or {}

    if health.get("nan"):
        it = health.get("first_trip", {}).get("nan")
        out.append(_finding(
            "critical", "nan",
            "non-finite residual%s — the iterate left the representable "
            "range" % (" at iteration %d" % it if it is not None else ""),
            "check matrix scaling / symmetric equilibration, or use "
            "dtype=float64"))
    bk = health.get("breakdown")
    if bk:
        it = health.get("breakdown_iteration")
        where = " at iteration %d" % it if it is not None else ""
        msg = {
            "breakdown_rho":
                ("Krylov breakdown (rho ≈ 0)%s — the residual became "
                 "orthogonal to the shadow space; the operator may be "
                 "singular" % where,
                 "try bicgstabl (L>=2), gmres, or verify the system is "
                 "nonsingular / the rhs is consistent"),
            "breakdown_omega":
                ("BiCGStab omega-breakdown%s (minimal-residual step "
                 "length ≈ 0)" % where,
                 "use bicgstabl (L>=2) or gmres — both cure "
                 "omega-stagnation on strongly non-symmetric systems"),
            "breakdown_alpha":
                ("search-direction breakdown (p·Ap ≈ 0)%s — "
                 "singular operator or rhs with a null-space component"
                 % where,
                 "project the null space out of the rhs (or use deflation "
                 "/ ns_search), or switch to gmres"),
            "breakdown_hessenberg":
                ("Arnoldi (Hessenberg) breakdown%s before convergence"
                 % where,
                 "the Krylov space became invariant — the operator is "
                 "likely singular; check the system or use a coarser tol"),
        }.get(bk, ("Krylov breakdown (%s)%s" % (bk, where), None))
        out.append(_finding("critical", bk, msg[0], msg[1]))
    if health.get("diverged"):
        it = health.get("first_trip", {}).get("divergence")
        out.append(_finding(
            "critical", "divergence",
            "residual grew for %s consecutive iterations%s"
            % (_env_int("AMGCL_TPU_DIV_WINDOW", 5),
               " (flagged at iteration %d)" % it if it is not None
               else ""),
            "cg requires an SPD operator — try bicgstab/gmres; if the "
            "preconditioner diverges, reduce smoother damping or raise "
            "npre/npost"))
    if health.get("indefinite") and not health.get("breakdown"):
        out.append(_finding(
            "warning", "indefinite",
            "p·Ap <= 0 observed under CG — the operator is not "
            "positive definite",
            "use bicgstab, bicgstabl or gmres instead of cg"))
    if tol is not None and resid is not None and \
            not (math.isfinite(resid) and resid <= tol * 1.0000001):
        hit_max = maxiter is not None and iters is not None \
            and iters >= maxiter
        out.append(_finding(
            "critical", "not_converged",
            "did not converge: relative residual %.3e > tol %.1e after "
            "%s iterations%s" % (resid, tol, iters,
                                 " (maxiter reached)" if hit_max else ""),
            "raise maxiter, loosen tol, or strengthen the "
            "preconditioner (npre/npost, relaxation type, coarsening)"))
    if health.get("stagnated"):
        it = health.get("first_trip", {}).get("stagnation")
        out.append(_finding(
            "warning", "stagnation",
            "residual stagnated (reduction < %.0f%% per iteration over "
            "%d iterations%s)"
            % (100 * (1 - _env_float("AMGCL_TPU_STAG_RTOL", 0.99)),
               _env_int("AMGCL_TPU_STAG_WINDOW", 10),
               ", from iteration %d" % it if it is not None else ""),
            "raise npre/npost, switch relaxation (chebyshev, ilu0), or "
            "check for an inconsistent rhs on a singular system"))
    if "df32_drift" in extra:
        d = extra["df32_drift"]
        out.append(_finding(
            "critical", "df32_drift",
            "df32 compensated-residual drift detected: reported %.3e vs "
            "host float64 %.3e — the compiled refinement loop "
            "reassociated the error-free transforms"
            % (d.get("reported", float("nan")),
               d.get("actual", float("nan"))),
            "use refine_dtype='float64' (trusted residuals) or "
            "dtype=float64"))
    if rate is not None and rate > 0.8 and not any(
            f["code"] in ("divergence", "stagnation") for f in out):
        out.append(_finding(
            "warning", "slow_convergence",
            "slow convergence: average residual reduction %.3f per "
            "iteration" % rate,
            "strengthen the cycle: raise npre/npost, try ncycle=2 "
            "(W-cycle), or a stronger smoother (chebyshev/ilu0)"))

    for row in probe or []:
        cf = row.get("conv_factor")
        lvl = row.get("level")
        if cf is not None and cf >= 0.9:
            out.append(_finding(
                "warning", "level_conv_factor",
                "level %s convergence factor %.2f — error components on "
                "this level are barely reduced per cycle" % (lvl, cf),
                "raise npre/npost or switch relaxation; if it persists, "
                "the coarsening on this level is too aggressive "
                "(lower eps_strong / aggregate size)"))
        sr = row.get("smoother_rho")
        if sr is not None and sr >= 1.0:
            out.append(_finding(
                "critical", "smoother_diverges",
                "smoother diverges on level %s (spectral radius %.2f)"
                % (lvl, sr),
                "reduce the smoother damping or switch relaxation "
                "(chebyshev bounds its spectrum explicitly)"))

    hier = getattr(report, "hierarchy", None) or (ledger or {}).get(
        "hierarchy")
    if isinstance(hier, dict):
        oc = hier.get("operator_complexity")
        if oc is not None and oc > 2.5:
            out.append(_finding(
                "info", "operator_complexity",
                "high operator complexity %.2f — setup memory and cycle "
                "cost grow with it" % oc,
                "use plain (unsmoothed) aggregation or raise the "
                "strength threshold"))
    if isinstance(ledger, dict):
        dw = ledger.get("dense_window") or {}
        if dw.get("refused"):
            out.append(_finding(
                "info", "dense_window_budget",
                "dense-window conversions were refused by the HBM "
                "budget (%d refusal(s)) — those levels fell back to "
                "gather-based SpMV" % len(dw["refused"]),
                "raise AMGCL_TPU_DWIN_MAX_BYTES if HBM allows"))

    # efficiency leg: roofline bottlenecks (telemetry/roofline.py ranks
    # them; they arrive pre-shaped as findings) and compile-watch smells
    if isinstance(roofline, dict):
        out.extend(f for f in roofline.get("bottlenecks", [])
                   if isinstance(f, dict) and "severity" in f)
    if isinstance(serve, dict):
        out.extend(serve_findings(serve))
    if isinstance(comm, dict):
        # distributed leg: measured comm attribution divergence
        # (telemetry/comm.py — pre-shaped findings ride the record, or
        # are derived fresh from a findings-free record)
        fs = comm.get("findings")
        if fs is None:
            from amgcl_tpu.telemetry.comm import comm_findings
            fs = comm_findings(comm)
        out.extend(f for f in fs
                   if isinstance(f, dict) and "severity" in f)
    if isinstance(farm, dict):
        # farm leg: per-tenant SLO breaches + eviction thrash
        out.extend(farm_findings(farm))
    rec = recovery if recovery is not None \
        else getattr(report, "recovery", None)
    if isinstance(rec, dict):
        # fault-tolerance leg: how the ladder saved (or lost) the
        # solve, and whether the escalation is thrashing
        out.extend(recovery_findings(rec))
    if isinstance(diff, dict):
        # forensics leg: cross-run regression attribution
        # (telemetry/diff.py — stdlib-only, safe to import here)
        from amgcl_tpu.telemetry import diff as _diff_mod
        out.extend(f for f in _diff_mod.findings(diff)
                   if isinstance(f, dict) and "severity" in f)
    if isinstance(structure, dict):
        # structure leg: the operator X-ray's advisor / decision-ledger
        # findings, joined against the measured roofline when both ride
        from amgcl_tpu.telemetry.structure import structure_findings
        out.extend(f for f in structure_findings(
            structure, roofline=roofline if isinstance(roofline, dict)
            else None) if isinstance(f, dict) and "severity" in f)
    if isinstance(memory, dict):
        # memory leg (ISSUE 18): the measured-vs-model join from
        # AMG.memory_report() / the memwatch selftest — drift past the
        # declared tolerance, leaked cycle bytes, unattributed
        # footprint
        from amgcl_tpu.telemetry.memwatch import memory_findings
        out.extend(f for f in memory_findings(memory)
                   if isinstance(f, dict) and "severity" in f)
    if isinstance(compile_stats, dict):
        from amgcl_tpu.telemetry import compile_watch as _cw
        out.extend(_cw.findings(compile_stats))
        wall = getattr(report, "wall_time_s", None)
        # only the PER-CALL delta is comparable to this call's wall time
        # — the snapshot totals are process-cumulative and would flag
        # every warm solve after one normal first-call compile
        comp = compile_stats.get("new_compile_s")
        first = bool((getattr(report, "extra", None) or {})
                     .get("first_call"))
        if wall and comp and not first and comp > 0.5 * wall:
            out.append(_finding(
                "warning", "compile_dominates",
                "XLA compile time (%.2fs) dominates the solve wall time "
                "(%.2fs) on a non-first call — the program is being "
                "rebuilt instead of reused" % (comp, wall),
                "keep the solver bundle alive across solves, enable the "
                "persistent compilation cache, and check the retrace "
                "findings for the shape that varies"))

    if not out:
        out.append(_finding(
            "info", "healthy",
            "no findings: converged in %s iterations at %.3e"
            % (iters, resid if resid is not None else float("nan"))))
    out.sort(key=lambda f: _SEV_ORDER.get(f["severity"], 9))
    return out


def format_findings(findings: List[Dict[str, Any]]) -> str:
    """Render diagnose() output as the doctor's text report."""
    tag = {"critical": "CRIT", "warning": "WARN", "info": "INFO"}
    lines = ["Convergence doctor: %d finding(s)" % len(findings)]
    for i, f in enumerate(findings, 1):
        lines.append("%2d. [%s] %s" % (i, tag.get(f["severity"], "????"),
                                       f["message"]))
        if f.get("suggestion"):
            lines.append("      -> %s" % f["suggestion"])
    return "\n".join(lines)
