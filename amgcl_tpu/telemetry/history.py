"""Per-iteration residual history captured INSIDE the device loop.

The reference logs residuals with a host-side print each iteration
(cg.hpp:199); on TPU a per-iteration host sync would serialize the whole
``lax.while_loop``, so instead each solver carries a preallocated
``(maxiter + overshoot,)`` buffer through the loop state and writes the
relative residual at its iteration slot with ``hist.at[it].set(...)`` —
pure device work, fetched once after the loop with everything else.

``HistoryMixin`` is deliberately NOT a dataclass: each solver declares its
own ``record_history: bool = False`` field LAST so positional construction
(``CG(100, 1e-8)``) keeps its meaning; the class attribute here is only the
default for anything that never declares the field.

Slots never written stay NaN and are sliced off by the recorded count
(make_solver fetches ``history[:iters]``), so a genuine NaN residual from a
breakdown inside the recorded range is preserved, not filtered.
"""

from __future__ import annotations

import jax.numpy as jnp


class HistoryMixin:
    """Shared history plumbing for Krylov solvers (cg, bicgstab, bicgstabl,
    gmres, lgmres, idrs, richardson, preonly)."""

    record_history = False

    def _hist_init(self, dtype, overshoot: int = 0):
        """Loop-state buffer: maxiter + overshoot slots when recording
        (solvers whose counter advances by more than 1 per loop trip pass
        the per-trip overshoot), else a 1-slot dummy so the while-loop
        carry keeps a static shape either way."""
        n = int(getattr(self, "maxiter", 1)) + int(overshoot) \
            if self.record_history else 1
        return jnp.full(max(n, 1), jnp.nan, dtype=dtype)

    def _hist_put(self, hist, idx, value, keep=None):
        """hist[idx] = value (real part, cast to the buffer dtype) when
        recording; ``keep`` optionally masks the write (traced bool — used
        by solvers whose unrolled steps commit conditionally)."""
        if not self.record_history:
            return hist
        v = jnp.real(value).astype(hist.dtype)
        if keep is not None:
            v = jnp.where(keep, v, hist[idx])
        return hist.at[idx].set(v)

    def _hist_result(self, x, iters, resid, hist):
        """The uniform solver return: ``(x, iters, resid)`` —
        ``(..., hist)`` appended when recording (make_solver slices it by
        the recorded count)."""
        if self.record_history:
            return x, iters, resid, hist
        return x, iters, resid
