"""Per-iteration residual history captured INSIDE the device loop.

The reference logs residuals with a host-side print each iteration
(cg.hpp:199); on TPU a per-iteration host sync would serialize the whole
``lax.while_loop``, so instead each solver carries a preallocated
``(maxiter + overshoot,)`` buffer through the loop state and writes the
relative residual at its iteration slot with ``hist.at[it].set(...)`` —
pure device work, fetched once after the loop with everything else.

``HistoryMixin`` is deliberately NOT a dataclass: each solver declares its
own ``record_history: bool = False`` field LAST so positional construction
(``CG(100, 1e-8)``) keeps its meaning; the class attribute here is only the
default for anything that never declares the field.

Slots never written stay NaN and are sliced off by the recorded count
(make_solver fetches ``history[:iters]``), so a genuine NaN residual from a
breakdown inside the recorded range is preserved, not filtered.

The mixin also plumbs the numerical-health guards (telemetry/health.py,
``guard=True`` by default): a compact :class:`~amgcl_tpu.telemetry.health
.HealthState` rides the while-loop carry, each iteration updates it with a
handful of scalar ops (NaN residual, solver-specific breakdown
denominators, stagnation/divergence window counters), and a fatal trip
masks the state commit — the iterate freezes at the last good step, the
loop exits early, and the fetched bitmask decodes into
``SolveReport.health``. No extra reductions, no host syncs, no cost on
the clean path beyond a few scalar compares.
"""

from __future__ import annotations

import os

import jax.numpy as jnp

from amgcl_tpu.telemetry import health as _health


def _inject_numeric(it, res, trips):
    """Numeric fault seam (faults/inject.py): when a ``numeric.*``
    rule FIRED for the dispatch currently being traced
    (``inject.begin_numeric_dispatch`` in make_solver._solve_once —
    the full after/count/p trigger logic runs there, once per
    dispatch), plant NaN/Inf into the guarded residual (or an
    artificial breakdown trip) at the rule's iteration. The pending
    spec is visible ONLY inside make_solver's faulted-dispatch window,
    which routes through a fresh throwaway jit wrap — any other trace
    (a serve bucket compile, an audit) sees None, so no cached program
    ever carries the fault. A no-op single env read when no plan is
    set."""
    if not os.environ.get("AMGCL_TPU_FAULT_PLAN"):
        return res, trips
    try:
        from amgcl_tpu.faults import inject as _inject
        spec = _inject.pending_numeric()
    except Exception:
        return res, trips
    if spec is None:
        return res, trips
    hit = jnp.asarray(it) == int(spec.get("at", 0))
    if spec["site"] == "numeric.breakdown":
        trips = tuple(trips) + ((_health.BREAKDOWN_RHO, hit),)
    else:
        bad = jnp.inf if spec["site"] == "numeric.inf" else jnp.nan
        res = jnp.where(hit, bad, res)
    return res, trips


class HistoryMixin:
    """Shared history/health plumbing for Krylov solvers (cg, bicgstab,
    bicgstabl, gmres, lgmres, idrs, richardson, preonly)."""

    record_history = False
    guard = True

    def _hist_init(self, dtype, overshoot: int = 0):
        """Loop-state buffer: maxiter + overshoot slots when recording
        (solvers whose counter advances by more than 1 per loop trip pass
        the per-trip overshoot), else a 1-slot dummy so the while-loop
        carry keeps a static shape either way."""
        n = int(getattr(self, "maxiter", 1)) + int(overshoot) \
            if self.record_history else 1
        return jnp.full(max(n, 1), jnp.nan, dtype=dtype)

    def _hist_put(self, hist, idx, value, keep=None):
        """hist[idx] = value (real part, cast to the buffer dtype) when
        recording; ``keep`` optionally masks the write (traced bool — used
        by solvers whose unrolled steps commit conditionally)."""
        if not self.record_history:
            return hist
        v = jnp.real(value).astype(hist.dtype)
        if keep is not None:
            v = jnp.where(keep, v, hist[idx])
        return hist.at[idx].set(v)

    def _hist_result(self, x, iters, resid, hist, health=None):
        """The uniform solver return: ``(x, iters, resid)`` —
        ``(..., hist)`` appended when recording (make_solver slices it by
        the recorded count), ``(..., health)`` appended when guards are
        on (make_solver decodes it into ``SolveReport.health``)."""
        out = (x, iters, resid)
        if self.record_history:
            out = out + (hist,)
        if health is not None and getattr(self, "guard", False):
            out = out + (health,)
        return out

    # -- numerical-health guards (telemetry/health.py) ----------------------

    def _guard_init(self, res0):
        """Initial HealthState for the loop carry (a few scalars; carried
        even with guard=False so the traced state structure never
        depends on runtime values — the updates below no-op and XLA
        dead-code-eliminates the whole thing)."""
        return _health.init_state(res0)

    def _guard_step(self, hs, it, res, trips=()):
        """Guard update at iteration ``it`` with candidate residual
        ``res`` and solver-specific breakdown trips. Returns
        ``(ok, hs)`` — ``ok`` masks the state commit and the history
        write; always-True when guards are off."""
        if not getattr(self, "guard", False):
            return jnp.asarray(True), hs
        res, trips = _inject_numeric(it, res, trips)
        return _health.step(hs, it, res, trips)

    def _guard_go(self, hs):
        """while_loop continuation term: False once a fatal guard
        tripped (NaN, breakdown, or divergence behind
        AMGCL_TPU_DIVERGENCE_BREAK). Python True when guards are off —
        folds away in the traced cond."""
        if not getattr(self, "guard", False):
            return True
        return _health.keep_going(hs)

    @staticmethod
    def _guard_commit(ok, new, old):
        """where(ok, new, old) over a state tree — the fatal-trip freeze."""
        return _health.commit(ok, new, old)
