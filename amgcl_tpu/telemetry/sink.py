"""JSONL metrics sink — one JSON object per line, shared by bench.py,
cli.py, make_solver and the distributed solvers.

Schema convention (shared with BENCH_*.json / PROGRESS.jsonl): flat JSON
objects; every stamped record carries ``ts`` (unix seconds) and ``ts_iso``;
solver-originated records carry an ``event`` field ("solve", "setup",
"profile", "bench", "tier1_check", "health", "doctor", ...) plus the
:class:`SolveReport` fields (iters, resid, convergence_rate,
wall_time_s, solver, history, hierarchy, health).

The process-global default sink is a no-op until configured — either
programmatically (``set_default_sink(JsonlSink(path))``) or by exporting
``AMGCL_TPU_TELEMETRY=/path/to/out.jsonl`` — so library code can call
:func:`emit` unconditionally.

IMPORTANT: this module is stdlib-only AND free of package-relative imports
on purpose: bench.py's supervisor (which must never import jax) loads it
directly by file path with importlib, bypassing ``amgcl_tpu/__init__``.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import threading
import time
from typing import Any, Dict, Optional


def _jsonable(obj):
    if hasattr(obj, "tolist"):
        return obj.tolist()
    if hasattr(obj, "item"):
        return obj.item()
    return str(obj)


def _clean(obj):
    """Replace non-finite floats with their string names ("nan"/"inf") so
    every emitted line is strict RFC JSON — json.dumps would otherwise
    write bare NaN/Infinity tokens, making exactly the records that
    describe solver breakdowns unparseable to jq/JSON.parse consumers.
    The string keeps the breakdown signal a null would erase."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else str(obj)
    if isinstance(obj, dict):
        return {k: _clean(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_clean(v) for v in obj]
    if hasattr(obj, "tolist"):
        return _clean(obj.tolist())
    if hasattr(obj, "item"):
        return _clean(obj.item())
    return obj


def stamp(record: Dict[str, Any], commit: Optional[str] = None,
          now: Optional[float] = None) -> Dict[str, Any]:
    """Copy of ``record`` with ``ts``/``ts_iso`` (and optionally
    ``commit``) appended — setdefault semantics, existing stamps win.
    Field order matches the historical bench.py last-good records so the
    on-disk artifact stays byte-compatible."""
    rec = dict(record)
    rec.setdefault("ts", time.time() if now is None else now)
    # ts_iso always renders the record's ts — a pre-stamped ts (e.g. the
    # opportunistic bench loop stamps at cycle start) must not disagree
    # with it
    rec.setdefault("ts_iso", time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime(rec["ts"])))
    if commit is not None:
        rec.setdefault("commit", commit)
    return rec


def git_commit(repo: str) -> Optional[str]:
    """Short HEAD hash of ``repo``, or None (never raises)."""
    try:
        return subprocess.run(
            ["git", "-C", repo, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10).stdout.strip() \
            or None
    except Exception:
        return None


def write_json_atomic(path: str, record: Dict[str, Any]) -> None:
    """Single-object JSON file via tmp + rename (the BENCH_LAST_GOOD.json
    write path: a reader never sees a torn file). No non-finite cleaning —
    this path reproduces the historical bench artifact byte-for-byte."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, default=_jsonable)
    os.replace(tmp, path)


def max_sink_bytes() -> int:
    """Size cap for file sinks from ``AMGCL_TPU_TELEMETRY_MAX_BYTES``
    (0 / unset / unparseable = unbounded, the historical behavior)."""
    try:
        return int(os.environ.get("AMGCL_TPU_TELEMETRY_MAX_BYTES", "0"))
    except ValueError:
        return 0


class JsonlSink:
    """Append-mode JSONL writer. ``path`` XOR ``stream``; file sinks
    open/write/close per record so concurrent emitters (supervisor +
    worker, or the opportunistic bench loop) interleave at line
    granularity and a crash never loses buffered lines.

    ``clean_records=False`` opts out of the non-finite-float cleaning for
    surfaces with a pre-existing schema contract (bench.py's stdout line,
    whose consumers round-trip bare NaN tokens via Python json).

    File sinks rotate: once the file exceeds ``max_bytes`` (default from
    ``AMGCL_TPU_TELEMETRY_MAX_BYTES``; 0 = unbounded) the next emit
    renames ``out.jsonl`` -> ``out.jsonl.1`` (replacing any previous
    ``.1``) and starts fresh — a long-running service holds at most
    ~2x the cap on disk instead of growing without bound. Rotation is
    checked before the write, so a single record never splits across
    the two files.

    The write path is serialized by a per-instance lock: the serve
    worker thread and foreground callers share the process-global sink,
    and an unlocked rotate-then-append pair can interleave — thread A
    rotates, thread B (who sized the file before the rename) rotates
    again, and A's freshly written records vanish into a replaced
    ``.1``. The lock makes size-check + rename + append one atomic
    step; stream writes take it too so two threads' lines cannot
    interleave mid-record on buffered streams."""

    def __init__(self, path: Optional[str] = None, stream=None,
                 stamp_records: bool = True, clean_records: bool = True,
                 max_bytes: Optional[int] = None):
        if (path is None) == (stream is None):
            raise ValueError("JsonlSink needs exactly one of path/stream")
        self.path = path
        self.stream = stream
        self.stamp_records = stamp_records
        self.clean_records = clean_records
        self.max_bytes = max_sink_bytes() if max_bytes is None \
            else int(max_bytes)
        self._lock = threading.Lock()

    def _maybe_rotate(self):
        if not self.max_bytes or self.max_bytes <= 0:
            return
        try:
            if os.path.getsize(self.path) >= self.max_bytes:
                os.replace(self.path, self.path + ".1")
        except OSError:
            pass          # missing file (first write) or a racing rotator

    def emit(self, record: Optional[Dict[str, Any]] = None,
             **fields) -> Dict[str, Any]:
        rec = dict(record or {})
        rec.update(fields)
        if self.stamp_records:
            rec = stamp(rec)
        line = json.dumps(_clean(rec) if self.clean_records else rec,
                          default=_jsonable)
        with self._lock:
            if self.stream is not None:
                self.stream.write(line + "\n")
                self.stream.flush()
            else:
                self._maybe_rotate()
                with open(self.path, "a") as f:
                    f.write(line + "\n")
        return rec

    def close(self):
        pass  # nothing held open

    def __repr__(self):
        return "JsonlSink(%r)" % (self.path or getattr(
            self.stream, "name", self.stream))


class NullSink:
    """Default sink: validates nothing, writes nothing."""

    def emit(self, record: Optional[Dict[str, Any]] = None,
             **fields) -> Dict[str, Any]:
        rec = dict(record or {})
        rec.update(fields)
        return rec

    def close(self):
        pass


_default_sink = None
_default_sink_explicit = False   # a set_default_sink(NullSink()) must
#                                  stick — only env-derived NullSinks are
#                                  re-resolved against the env var


def get_default_sink():
    """The process-global sink, from ``AMGCL_TPU_TELEMETRY`` (a JSONL
    path) when set, else a NullSink. The env var is re-checked while the
    default is still an env-derived NullSink, so exporting it after the
    first solve still takes effect — but an explicit set_default_sink
    (including an explicit NullSink opt-out) always wins."""
    global _default_sink
    if not _default_sink_explicit and (
            _default_sink is None or isinstance(_default_sink, NullSink)):
        path = os.environ.get("AMGCL_TPU_TELEMETRY")
        if path:
            _default_sink = JsonlSink(path)
        elif _default_sink is None:
            _default_sink = NullSink()
    return _default_sink


def set_default_sink(sink) -> None:
    """Install ``sink`` (None resets to the env-driven default)."""
    global _default_sink, _default_sink_explicit
    _default_sink = sink
    _default_sink_explicit = sink is not None


_emit_warned = False


def emit(record: Optional[Dict[str, Any]] = None, **fields) -> Dict[str, Any]:
    """Emit through the process-global default sink. Never raises:
    telemetry must not turn a converged solve into a failure (a typo'd
    AMGCL_TPU_TELEMETRY path, a read-only mount, a full disk). A failing
    sink warns on the first drop and stays quiet after."""
    global _emit_warned
    try:
        return get_default_sink().emit(record, **fields)
    except Exception as e:
        if not _emit_warned:
            _emit_warned = True
            import warnings
            warnings.warn("telemetry sink emit failed (%r) — records "
                          "will be dropped" % (e,))
        rec = dict(record or {})
        rec.update(fields)
        return rec
