"""Deterministic multilevel k-way graph partitioner.

Reference role: ``mpi::partition::parmetis`` / ``ptscotch``
(amgcl/mpi/partition/parmetis.hpp:105-199, ptscotch.hpp): compute a k-way
partition of a level operator's adjacency graph so each mesh shard's row
block couples mostly with itself, then express it as a permutation (the
reference's permutation matrix I). The reference shells out to external
libraries; neither exists in this image, and a TPU framework should not
depend on them — this is a self-contained implementation of the same
multilevel scheme those libraries use:

1. **Coarsen** by heavy-edge matching until the graph is small,
2. **Bisect** the coarse graph by its Fiedler vector (spectral — the
   continuous relaxation of min-cut; dense eigendecomposition is fine at
   the coarse size),
3. **Project + refine** back up with boundary Fiedler/FM-style passes
   (move the highest-gain boundary vertices while keeping balance),
4. **Recurse** for k-way (k need not be a power of two: each bisection
   targets the proportional fraction).

Everything is plain numpy/scipy on the host — partitioning happens at
setup time on coarse levels, never in the solve path. Determinism: node
order, matching order, and eigensolver inputs are all fixed, so the same
matrix always yields the same partition (required for the
compile-cache-friendly distributed setup).

The mesh layout needs EXACT block sizes (shard b owns rows
[b*nloc, (b+1)*nloc)), so :func:`partition_permutation` finishes with a
balance fixup that moves the least-attached rows of oversized parts.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

from amgcl_tpu.ops.csr import CSR

_DIRECT_N = 600        # bisect directly (dense Fiedler) below this size


def _graph(A: CSR) -> sp.csr_matrix:
    """Symmetric positive edge weights |A| + |A|ᵀ, zero diagonal."""
    S = (A.unblock() if A.is_block else A).to_scipy()
    W = abs(S) + abs(S.T)
    W = W.tolil()
    W.setdiag(0)
    W = W.tocsr()
    W.eliminate_zeros()
    return W


def _heavy_edge_matching(W: sp.csr_matrix, node_w: np.ndarray,
                         max_w: float, rounds: int = 4) -> np.ndarray:
    """Capped mutual heavy-edge matching, fully vectorized: in each
    round every free node proposes to its heaviest free neighbor whose
    combined weight stays under ``max_w``; mutual proposals pair up.
    The weight cap is essential — uncapped matching snowballs one
    cluster into most of the graph (rich-get-richer on accumulated edge
    weights), after which NO balanced split of the coarse graph exists.
    Deterministic tie-break: a fixed pseudo-random node priority, so
    equal-weight graphs still reach decent mutual rates.
    Returns cmap: node -> coarse node id (pairs share an id)."""
    n = W.shape[0]
    ids = np.arange(n, dtype=np.int64)
    match = ids.copy()                 # self = unmatched
    if W.nnz:
        rows = np.repeat(ids, np.diff(W.indptr))
        cols = W.indices.astype(np.int64)
        data = W.data
        # deterministic pseudo-random priority (splitmix-style hash)
        pr = ids.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
        for _ in range(rounds):
            free = match == ids
            ok = free[rows] & free[cols] & (rows != cols) \
                & (node_w[rows] + node_w[cols] <= max_w)
            if not ok.any():
                break
            r2, c2, d2 = rows[ok], cols[ok], data[ok]
            o = np.lexsort((pr[c2], d2, r2))
            r2o = r2[o]
            last = np.flatnonzero(np.r_[r2o[1:] != r2o[:-1], True])
            prop = np.full(n, -1, dtype=np.int64)
            prop[r2o[last]] = c2[o[last]]
            cand = np.flatnonzero(prop >= 0)
            mut = cand[prop[prop[cand]] == cand]
            lead = mut[mut < prop[mut]]
            match[lead] = prop[lead]
            match[prop[lead]] = lead
    cmap = np.full(n, -1, dtype=np.int64)
    leaders = np.flatnonzero(match >= ids)       # pair leaders + singletons
    cmap[leaders] = np.arange(len(leaders))
    followers = match < ids
    cmap[followers] = cmap[match[followers]]
    return cmap


def _coarsen(W: sp.csr_matrix, node_w: np.ndarray):
    """One capped heavy-edge-matching coarsening step."""
    cmap = _heavy_edge_matching(W, node_w, float(node_w.sum()) / 16.0)
    nc = int(cmap.max()) + 1
    S = sp.csr_matrix(
        (np.ones(W.shape[0]), (np.arange(W.shape[0]), cmap)),
        shape=(W.shape[0], nc))
    Wc = (S.T @ W @ S).tocsr()
    Wc = Wc.tolil()
    Wc.setdiag(0)
    Wc = Wc.tocsr()
    Wc.eliminate_zeros()
    return Wc, np.asarray(S.T @ node_w).ravel(), cmap


def _fiedler(W: sp.csr_matrix) -> np.ndarray:
    """Fiedler vector by dense symmetric eigendecomposition (the graph is
    coarse by the time this runs). Deterministic by construction."""
    n = W.shape[0]
    d = np.asarray(W.sum(axis=1)).ravel()
    L = np.diag(d) - W.toarray()
    vals, vecs = np.linalg.eigh(L)
    # second-smallest eigenvector; disconnected graphs give several ~zero
    # eigenvalues — any vector in that space still separates components
    return vecs[:, min(1, n - 1)]


def _split_by_order(score, node_w, frac):
    """side[i] = True for the 'left' part: the prefix of the score order
    holding ~frac of the total node weight. Ties broken by node id."""
    order = np.lexsort((np.arange(len(score)), score))
    cum = np.cumsum(node_w[order])
    target = frac * cum[-1]
    nleft = int(np.searchsorted(cum, target, side="left")) + 1
    nleft = min(max(nleft, 1), len(order) - 1) if len(order) > 1 else 1
    side = np.zeros(len(score), dtype=bool)
    side[order[:nleft]] = True
    return side


def _refine(W: sp.csr_matrix, side: np.ndarray, node_w, frac,
            passes: int = 4, imbalance: float = 0.05):
    """Boundary refinement: greedily flip the vertices with the largest
    cut-weight gain while total left weight stays within ``imbalance`` of
    the target. Deterministic order; one vertex moves at most once per
    pass (FM-style without the full bucket structure — coarse levels are
    small enough that O(passes * n log n) is fine)."""
    total = float(node_w.sum())
    target = frac * total
    tol = imbalance * total
    for _ in range(passes):
        sgn = np.where(side, 1.0, -1.0)
        # gain of flipping u = external - internal edge weight =
        # -sgn_u * (W sgn)_u, one spmv for the whole vector
        ext = -sgn * (W @ sgn)
        cand = np.flatnonzero(ext > 0)
        if len(cand) == 0:
            break
        order = cand[np.lexsort((cand, -ext[cand]))]
        lw = float(node_w[side].sum())
        moved = 0
        # greedy flips against stale gains (gains of a flipped node's
        # neighbors change, recomputed next pass) — the classic FM bucket
        # update is overkill at coarse-level sizes
        for u in order[:4096]:
            nlw = lw - node_w[u] if side[u] else lw + node_w[u]
            if abs(nlw - target) > tol:
                continue
            side[u] = ~side[u]
            lw = nlw
            moved += 1
        if moved == 0:
            break
    return side


def _bisect(W: sp.csr_matrix, node_w: np.ndarray, frac: float) -> np.ndarray:
    """Multilevel weighted bisection: side[i] True = left part with ~frac
    of the node weight."""
    n = W.shape[0]
    if n <= 2:
        return _split_by_order(np.arange(n, dtype=float), node_w, frac)
    if n <= _DIRECT_N:
        f = _fiedler(W)
        side = _split_by_order(f, node_w, frac)
        return _refine(W, side, node_w, frac)
    Wc, node_wc, cmap = _coarsen(W, node_w)
    if Wc.shape[0] >= n:          # matching stalled (no edges) — direct
        return _split_by_order(np.arange(n, dtype=float), node_w, frac)
    side_c = _bisect(Wc, node_wc, frac)
    side = side_c[cmap]
    return _refine(W, side, node_w, frac)


def kway_partition(A: CSR, k: int, W: sp.csr_matrix | None = None
                   ) -> np.ndarray:
    """part[i] in [0, k): recursive multilevel bisection of A's adjacency
    graph, balanced by row count. Deterministic. Pass ``W`` to reuse an
    already-built adjacency graph."""
    W = _graph(A) if W is None else W
    n = W.shape[0]
    part = np.zeros(n, dtype=np.int64)
    # (node_index_array, first_part, n_parts) work stack
    stack = [(np.arange(n, dtype=np.int64), 0, int(k))]
    while stack:
        nodes, p0, kk = stack.pop()
        if kk <= 1 or len(nodes) == 0:
            part[nodes] = p0
            continue
        k1 = kk // 2
        Wsub = W[nodes][:, nodes].tocsr()
        side = _bisect(Wsub, np.ones(len(nodes)), k1 / kk)
        stack.append((nodes[side], p0, k1))
        stack.append((nodes[~side], p0 + k1, kk - k1))
    return part


def partition_permutation(A: CSR, nd: int,
                          nloc: int | None = None) -> np.ndarray:
    """Permutation realizing a k-way partition under the mesh's EXACT
    row-block layout (shard b owns rows [b*nloc, (b+1)*nloc)): perm[p] =
    old row at new position p. Oversized parts shed their least-attached
    rows to the nearest undersized part (balance fixup), so every block
    has exactly its mesh-mandated size."""
    S = A.unblock() if A.is_block else A
    n = S.nrows
    nloc = -(-n // nd) if nloc is None else int(nloc)
    nd_eff = -(-n // nloc)
    W = _graph(S)
    part = kway_partition(S, nd_eff, W=W)
    want = [min((b + 1) * nloc, n) - min(b * nloc, n)
            for b in range(nd_eff)]
    groups = [list(np.flatnonzero(part == b)) for b in range(nd_eff)]
    # balance fixup: move weakest rows from oversized parts into the
    # undersized part with which they couple most
    over = [b for b in range(nd_eff) if len(groups[b]) > want[b]]
    under = {b for b in range(nd_eff) if len(groups[b]) < want[b]}
    for b in over:
        g = np.asarray(groups[b])
        sub = W[g][:, g]
        attach = np.asarray(sub.sum(axis=1)).ravel()
        order = np.lexsort((g, attach))          # weakest first
        excess = len(g) - want[b]
        keep = np.ones(len(g), dtype=bool)
        for idx in order[:excess]:
            u = g[idx]
            # strongest coupling among undersized parts; fallback: any
            cols = W.indices[W.indptr[u]:W.indptr[u + 1]]
            wts = W.data[W.indptr[u]:W.indptr[u + 1]]
            best, bw = None, -1.0
            for c, wt in zip(cols, wts):
                pb = part[c]
                if pb in under and wt > bw:
                    best, bw = pb, wt
            if best is None:
                best = min(under, key=lambda q: (want[q] and
                                                 len(groups[q]) - want[q]))
            groups[best].append(u)
            part[u] = best
            keep[idx] = False
            if len(groups[best]) >= want[best]:
                under.discard(best)
            if not under:
                under = {q for q in range(nd_eff)
                         if len(groups[q]) < want[q]}
                if not under:
                    break
        groups[b] = list(g[keep])

    perm = np.concatenate([np.sort(np.asarray(groups[b], dtype=np.int64))
                           for b in range(nd_eff)])
    assert len(perm) == n
    return perm
