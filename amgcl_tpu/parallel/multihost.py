"""Multi-host (multi-controller) execution.

The reference scales out with raw MPI (amgcl/mpi/util.hpp:46-250 —
communicator, datatypes, Isend/Irecv halo traffic). The TPU-native
equivalent is ``jax.distributed``: one controller process per host, a
GLOBAL ``jax.sharding.Mesh`` over every chip, and exactly the same
``shard_map`` programs — the halo ``all_to_all``s and psum dots ride ICI
within a slice and DCN across slices, scheduled by XLA instead of MPI.

Nothing else in the framework changes for multi-host:
- setup placement goes through ``mesh.put_sharded``/
  ``make_array_from_callback``, where each process materializes only its
  addressable shards;
- solve outputs come back through ``mesh.host_full`` (a process
  allgather under jax.distributed, a plain np.asarray otherwise);
- every process runs the same host-side hierarchy build (the
  single-coordinator pattern: redundant host work, zero host-side
  communication — the right trade until setup itself is sharded).

Usage (per process, before any other JAX call)::

    from amgcl_tpu.parallel import multihost
    multihost.initialize()              # env-driven (JAX_COORDINATOR, ...)
    mesh = multihost.global_mesh()      # all chips of all hosts
    s = DistAMGSolver(A, mesh, ...)     # as usual

Validated by tests/test_multihost.py: a REAL 2-process run over Gloo CPU
collectives solving the Poisson fixture with iteration parity against the
single-process mesh."""

from __future__ import annotations

import os

import jax

from amgcl_tpu.parallel.mesh import ROWS_AXIS, make_mesh


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """``jax.distributed.initialize`` with environment fallbacks
    (JAX_COORDINATOR / JAX_NUM_PROCESSES / JAX_PROCESS_ID; on TPU pods
    all three are auto-detected by JAX and may be omitted)."""
    kw = {}
    coord = coordinator_address or os.environ.get("JAX_COORDINATOR")
    if coord:
        kw["coordinator_address"] = coord
    # truthiness, not `is not None`: templated env files may export
    # empty-string values, and int("") would crash before initialize
    np_ = num_processes if num_processes is not None else \
        os.environ.get("JAX_NUM_PROCESSES")
    if np_ not in (None, ""):
        kw["num_processes"] = int(np_)
    pid = process_id if process_id is not None else \
        os.environ.get("JAX_PROCESS_ID")
    if pid not in (None, ""):
        kw["process_id"] = int(pid)
    jax.distributed.initialize(**kw)


def global_mesh(n_devices: int | None = None):
    """A 1-D ``rows`` mesh over the GLOBAL device list (every chip of
    every process). Identical to ``make_mesh`` — jax.devices() is global
    under multi-controller — but named for intent."""
    return make_mesh(n_devices)


def is_multiprocess() -> bool:
    return jax.process_count() > 1
