"""Distributed AMG: serial host construction, mesh-sharded solve.

Architecture decision (vs the reference's mpi::amg,
amgcl/mpi/amg.hpp:49-511): under single-controller JAX the host sees the
whole matrix, so the hierarchy is built once by the serial setup path (the
reference's pattern — hierarchies are always *built* on the CPU and *moved*
to the backend, README.md:22-26) and every level is then partitioned over
the mesh: level operators and transfer operators become
:class:`DistEllMatrix` with static halo plans, smoother state is sharded by
rows, and the coarsest dense solve is replicated (every shard applies the
same small inverse to the all-gathered coarse residual — the TPU equivalent
of the gather-to-masters coarse solve,
amgcl/mpi/direct_solver/solver_base.hpp:41-130).

The Krylov loop reuses the *serial* solver classes inside ``shard_map``,
exactly the reference's trick of pairing serial Krylov bodies with a
distributed matrix and a globalized inner product
(amgcl/mpi/solver/cg.hpp:41-46): the local operator adapter exposes ``.mv``
(halo exchange + local SpMV) and the inner product is psum-reduced.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from amgcl_tpu.parallel.compat import shard_map, \
    axis_size as _axis_size
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import SolverInfo
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.parallel.mesh import ROWS_AXIS, put_sharded
from amgcl_tpu.parallel.dist_ell import (DistEllMatrix,
    build_dist_ell, pack_rows_ell)
from amgcl_tpu.parallel.dist_matrix import dist_inner_product


def _pad_vec(v, nloc, nd, dtype):
    host_dt = np.complex128 if jnp.issubdtype(
        jnp.dtype(dtype), jnp.complexfloating) else np.float64
    out = np.zeros(nloc * nd, dtype=host_dt)
    out[:len(v)] = np.asarray(v, dtype=host_dt)
    return out.astype(np.dtype(dtype))   # stays numpy: see mesh.put_sharded


@register_pytree_node_class
class DistSmoother:
    """Sharded smoother state, one of five kinds (reference role: the
    mpi::relaxation::* wrapper set, amgcl/mpi/relaxation/*.hpp — except
    these shard the GLOBAL smoother state with halo plans instead of
    factoring rank-local blocks, so distributed math == serial math):

      'diag'  — per-row scale (spai0 / damped_jacobi)
      'bdiag' — per-node block scale (block spai0 / block jacobi);
                scale is (nd, ncell_loc, b, b) over the scalar row layout
      'cheb'  — Chebyshev polynomial (SpMV-only, scalars static)
      'ilu'   — global Chow-Patel factors as halo-plan ELL matrices +
                sharded inverted U-diagonal; Jacobi tri-solves are plain
                halo SpMVs (amgcl/relaxation/detail/ilu_solve.hpp:44-129)
      'gs'    — multicolor Gauss-Seidel: global coloring, masks sharded
                by row, one halo SpMV per color
      'spai1' — approximate inverse as a halo-plan ELL matrix
    """

    def __init__(self, kind, scale=None, theta=0.0, delta=1.0, degree=0,
                 Ls=None, Us=None, uinv=None, jacobi_iters=2, masks=None,
                 Msp=None):
        self.kind = kind
        self.scale = scale          # (nd, nloc) or None; dinv for 'gs'
        self.theta = float(theta)
        self.delta = float(delta)
        self.degree = int(degree)
        self.Ls = Ls                # DistEllMatrix (strict lower, 'ilu')
        self.Us = Us                # DistEllMatrix (strict upper, 'ilu')
        self.uinv = uinv            # (nd, nloc) inverted U diagonal
        self.jacobi_iters = int(jacobi_iters)
        self.masks = masks          # (nd, ncolors, nloc) color masks ('gs')
        self.Msp = Msp              # DistEllMatrix approx inverse ('spai1')

    def tree_flatten(self):
        return ((self.scale, self.Ls, self.Us, self.uinv, self.masks,
                 self.Msp),
                (self.kind, self.theta, self.delta, self.degree,
                 self.jacobi_iters))

    @classmethod
    def tree_unflatten(cls, aux, children):
        kind, theta, delta, degree, jacobi_iters = aux
        scale, Ls, Us, uinv, masks, Msp = children
        return cls(kind, scale, theta, delta, degree, Ls, Us, uinv,
                   jacobi_iters, masks, Msp)

    def spec(self):
        mat = lambda m: None if m is None else m.specs()
        vec = lambda v: None if v is None else P(
            ROWS_AXIS, *([None] * (v.ndim - 1)))
        return DistSmoother(self.kind, vec(self.scale), self.theta,
                            self.delta, self.degree, mat(self.Ls),
                            mat(self.Us), vec(self.uinv),
                            self.jacobi_iters, vec(self.masks),
                            mat(self.Msp))

    # -- inside shard_map (Aop wraps the level's halo SpMV) ----------------

    def _cheb(self, Aop, f):
        from amgcl_tpu.relaxation.chebyshev import ChebyshevState
        dinv = None if self.scale is None else self.scale[0]
        st = ChebyshevState(dinv, self.degree, self.theta, self.delta,
                            dinv is not None)
        return st.apply(Aop, f)

    def _ilu(self, f):
        from amgcl_tpu.relaxation.ilu0 import ilu_jacobi_solve
        return ilu_jacobi_solve(self.Ls.shard_mv, self.Us.shard_mv,
                                self.uinv[0], self.jacobi_iters, f)

    def _gs_sweep(self, Aop, f, u, reverse):
        masks = self.masks[0]
        dinv = self.scale[0]
        order = range(masks.shape[0] - 1, -1, -1) if reverse \
            else range(masks.shape[0])
        for c in order:
            u = u + masks[c] * (dinv * (f - Aop.mv(u)))
        return u

    def _bmul(self, f):
        b = self.scale.shape[-1]
        fb = f.reshape(-1, b)
        return jnp.einsum("nij,nj->ni", self.scale[0], fb).reshape(f.shape)

    def apply0(self, Aop, f):
        """One application from a zero initial guess."""
        if self.kind == "cheb":
            return self._cheb(Aop, f)
        if self.kind == "ilu":
            return self._ilu(f)
        if self.kind == "gs":
            return self._gs_sweep(Aop, f, jnp.zeros_like(f), False)
        if self.kind == "spai1":
            return self.Msp.shard_mv(f)
        if self.kind == "bdiag":
            return self._bmul(f)
        return self.scale[0] * f

    def sweep(self, Aop, f, u, reverse=False):
        if self.kind == "cheb":
            return u + self._cheb(Aop, f - Aop.mv(u))
        if self.kind == "ilu":
            return u + self._ilu(f - Aop.mv(u))
        if self.kind == "gs":
            return self._gs_sweep(Aop, f, u, reverse)
        if self.kind == "spai1":
            return u + self.Msp.shard_mv(f - Aop.mv(u))
        if self.kind == "bdiag":
            return u + self._bmul(f - Aop.mv(u))
        return u + self.scale[0] * (f - Aop.mv(u))


@register_pytree_node_class
class DistLevel:
    def __init__(self, A, P_op, R_op, smoother):
        self.A = A
        self.P_op = P_op        # None on the coarsest level
        self.R_op = R_op
        self.smoother = smoother

    def tree_flatten(self):
        return (self.A, self.P_op, self.R_op, self.smoother), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@register_pytree_node_class
class TransitionOps:
    """Transfers between the sharded and replicated parts of the hierarchy
    (the repartition/merge analogue: instead of shrinking to fewer ranks —
    pointless on a TPU mesh where idle chips save nothing — small levels
    are REPLICATED and every shard computes them redundantly, trading tiny
    duplicate FLOPs for zero all_to_all latency per coarse level; reference
    role: amgcl/mpi/partition/merge.hpp).

    p_cols/p_vals: (nd, nloc, K) sharded — P rows by fine shard, columns
    into the replicated coarse vector. r_cols/r_vals: (nd, nc, K) sharded —
    per-shard column-restricted R; the replicated result is the psum of the
    per-shard partial products."""

    def __init__(self, p_cols, p_vals, r_cols, r_vals):
        self.p_cols = p_cols
        self.p_vals = p_vals
        self.r_cols = r_cols
        self.r_vals = r_vals

    def tree_flatten(self):
        return (self.p_cols, self.p_vals, self.r_cols, self.r_vals), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def specs(self):
        sp = P(ROWS_AXIS, None, None)
        return TransitionOps(sp, sp, sp, sp)

    def restrict(self, r_local):
        """sharded fine residual -> replicated coarse rhs."""
        part = jnp.einsum(
            "nk,nk->n", self.r_vals[0],
            jnp.take(r_local, self.r_cols[0], axis=0))
        return lax.psum(part, ROWS_AXIS)

    def prolong(self, uc_full):
        """replicated coarse correction -> sharded fine update."""
        return jnp.einsum(
            "nk,nk->n", self.p_vals[0],
            jnp.take(uc_full, self.p_cols[0], axis=0))


@register_pytree_node_class
class DistHierarchy:
    """Sharded multilevel state; ``shard_apply`` runs inside shard_map."""

    def __init__(self, levels, rep, trans, top_A=None, npre=1, npost=1,
                 ncycle=1, pre_cycles=1, rep_rowshard=False):
        self.levels = list(levels)   # sharded levels (may be empty)
        self.rep = rep               # replicated serial sub-hierarchy
        self.trans = trans           # TransitionOps (None = whole-vector
                                     # gather/slice, the no-shard case)
        self.top_A = top_A           # system matrix when levels is empty
        self.npre = int(npre)
        self.npost = int(npost)
        self.ncycle = int(ncycle)
        self.pre_cycles = int(pre_cycles)
        self.rep_rowshard = bool(rep_rowshard)

    def tree_flatten(self):
        return ((self.levels, self.rep, self.trans, self.top_A),
                (self.npre, self.npost, self.ncycle, self.pre_cycles,
                 self.rep_rowshard))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def specs(self):
        import jax
        lvls = [DistLevel(l.A.specs(),
                          None if l.P_op is None else l.P_op.specs(),
                          None if l.R_op is None else l.R_op.specs(),
                          l.smoother.spec()) for l in self.levels]
        rep_spec = jax.tree.map(lambda _: P(), self.rep)  # fully replicated
        return DistHierarchy(
            lvls, rep_spec,
            None if self.trans is None else self.trans.specs(),
            None if self.top_A is None else self.top_A.specs(),
            self.npre, self.npost, self.ncycle, self.pre_cycles,
            self.rep_rowshard)

    # -- inside shard_map ---------------------------------------------------

    @staticmethod
    def _rowshard_mat_ok(M):
        from amgcl_tpu.ops.device import EllMatrix, DenseMatrix
        return ((isinstance(M, EllMatrix) and M.block == (1, 1))
                or isinstance(M, DenseMatrix))

    def _rowshard_ok(self):
        """The finest replicated level qualifies for row-sharded visits:
        scalar ELL or dense operator, diagonal-scaling smoother, no fused
        sweep closures (their layout assumptions are per-level). P/R may
        be anything (incl. implicit proxies) — they run replicated; the
        sharded work is the smoother/residual passes, which dominate."""
        from amgcl_tpu.relaxation.base import ScaledResidualSmoother
        rep = self.rep
        if len(rep.levels) < 2 or rep.npre < 1:
            return False
        lv = rep.levels[0]
        return (self._rowshard_mat_ok(lv.A)
                and isinstance(lv.relax, ScaledResidualSmoother)
                and lv.relax.scale.ndim == 1
                and lv.down is None and lv.up is None)

    def _rep_rowshard_visit(self, f_full):
        """cycle(0, ·) of the replicated tail with the FINEST tail level
        row-sharded over the mesh: each shard smooths/residuals its own
        row slice of the replicated operator against the replicated
        vector (no halo — x is already whole), one all_gather per op.
        Trades the tail's N-fold redundant FLOPs for a few small
        collectives; ``rep_rowshard=True`` opts in, the 8-device dryrun
        A/Bs it (ROADMAP 'coarse levels underutilize large meshes')."""
        from amgcl_tpu.ops import device as sdev
        rep = self.rep
        lv = rep.levels[0]
        A = lv.A
        n = A.shape[0]
        nd = _axis_size(ROWS_AXIS)
        nloc = -(-n // nd)
        n_pad = nloc * nd
        s = lax.axis_index(ROWS_AXIS)

        from amgcl_tpu.ops.device import EllMatrix

        def row_slice_op(M):
            """Local-rows matvec closure for an ELL or dense operator."""
            if isinstance(M, EllMatrix):
                K = M.cols.shape[1]
                cp = jnp.pad(M.cols, ((0, n_pad - n), (0, 0)))
                vp = jnp.pad(M.vals, ((0, n_pad - n), (0, 0)))
                c = lax.dynamic_slice(cp, (s * nloc, np.int32(0)), (nloc, K))
                v = lax.dynamic_slice(vp, (s * nloc, np.int32(0)), (nloc, K))
                return lambda x_full: jnp.einsum(
                    "nk,nk->n", v, jnp.take(x_full, c, axis=0),
                    preferred_element_type=f_full.dtype)
            ap = jnp.pad(M.a, ((0, n_pad - n), (0, 0)))
            a = lax.dynamic_slice(ap, (s * nloc, np.int32(0)),
                                  (nloc, M.a.shape[1]))
            return lambda x_full: (a @ x_full).astype(f_full.dtype)

        def vec_slice(v_full):
            vp = jnp.pad(v_full, (0, n_pad - v_full.shape[0]))
            return lax.dynamic_slice(vp, (s * nloc,), (nloc,))

        def allg(y_loc):
            return lax.all_gather(y_loc, ROWS_AXIS, tiled=True)[:n]

        mv_loc = row_slice_op(A)
        w_loc = vec_slice(lv.relax.scale)
        f_loc = vec_slice(f_full)

        # pre-smoothing: first sweep from zero, then scaled-residual sweeps
        u_loc = w_loc * f_loc
        for _ in range(rep.npre - 1):
            u_loc = u_loc + w_loc * (f_loc - mv_loc(allg(u_loc)))
        u_full = allg(u_loc)
        # sharded residual -> replicated restrict + coarse tail-of-tail
        r_full = allg(f_loc - mv_loc(u_full))
        fc = sdev.spmv(lv.R, r_full)
        uc = rep.cycle(1, fc)
        for _ in range(rep.ncycle - 1):
            rc = sdev.residual(fc, rep.levels[1].A, uc)
            uc = uc + rep.cycle(1, rc)
        # replicated prolong (P may be an implicit proxy), local correct,
        # then sharded post-smoothing
        u_loc = u_loc + vec_slice(sdev.spmv(lv.P, uc))
        for _ in range(rep.npost):
            u_loc = u_loc + w_loc * (f_loc - mv_loc(allg(u_loc)))
        return allg(u_loc)

    def _rep_visit(self, fc_full):
        if self.rep_rowshard and self._rowshard_ok():
            return self._rep_rowshard_visit(fc_full)
        return self.rep.cycle(0, fc_full)

    def _rep_solve(self, fc_full):
        """Replicated sub-hierarchy visit(s): every shard runs the same
        serial cycle on the full coarse vector — redundant FLOPs on tiny
        levels instead of per-level collectives (or row-sharded finest
        tail level under ``rep_rowshard``)."""
        from amgcl_tpu.ops import device as sdev
        uc = self._rep_visit(fc_full)
        for _ in range(self.ncycle - 1):
            rc = fc_full - sdev.spmv(self.rep.levels[0].A, uc)
            uc = uc + self._rep_visit(rc)
        return uc

    def shard_cycle(self, i, f):
        lv = self.levels[i]
        Aop = _LocalOp(lv.A)
        sm = lv.smoother
        if self.npre > 0:
            u = sm.apply0(Aop, f)
            for _ in range(self.npre - 1):
                u = sm.sweep(Aop, f, u)
        else:
            u = jnp.zeros_like(f)
        r = f - lv.A.shard_mv(u)
        if i == len(self.levels) - 1:
            # boundary to the replicated tail
            fc_full = self.trans.restrict(r)
            uc_full = self._rep_solve(fc_full)
            u = u + self.trans.prolong(uc_full)
        else:
            fc = lv.R_op.shard_mv(r)
            uc = self.shard_cycle(i + 1, fc)
            for _ in range(self.ncycle - 1):   # W-cycle extra coarse visits
                rc = fc - self.levels[i + 1].A.shard_mv(uc)
                uc = uc + self.shard_cycle(i + 1, rc)
            u = u + lv.P_op.shard_mv(uc)
        for _ in range(self.npost):
            u = sm.sweep(Aop, f, u, reverse=True)   # matches apply_post
        return u

    def _whole_vector_apply(self, r):
        """No sharded levels: gather the whole (small) residual, run the
        replicated hierarchy, slice the local part back."""
        M = self.rep.system_matrix
        # scalar length: ELL block matrices report shape in block units
        n_rep = M.shape[0] * getattr(M, "block", (1, 1))[0]
        nloc = r.shape[0]
        r_full = lax.all_gather(r, ROWS_AXIS, tiled=True)[:n_rep]
        u_full = self.rep.apply(r_full)
        pad = jnp.zeros(nloc * _axis_size(ROWS_AXIS), u_full.dtype)
        pad = lax.dynamic_update_slice(pad, u_full, (0,))
        s = lax.axis_index(ROWS_AXIS)
        return lax.dynamic_slice(pad, (s * nloc,), (nloc,))

    def shard_apply(self, r):
        if not self.levels:
            return self._whole_vector_apply(r)
        x = self.shard_cycle(0, r)
        for _ in range(self.pre_cycles - 1):
            rr = r - self.levels[0].A.shard_mv(x)
            x = x + self.shard_cycle(0, rr)
        return x

    def system_A(self):
        """The Krylov-loop operator. ``top_A`` takes precedence when set:
        under a narrowed precond_dtype it holds the solver-precision copy
        of the system matrix (mixing.hpp seam — the residual recursion
        must track the full-precision operator, not the bf16 hierarchy's
        finest level)."""
        return self.top_A if self.top_A is not None else self.levels[0].A


def _transition_ops(Pt: CSR, Rt: CSR, nd, nloc, mesh, dtype):
    """Build TransitionOps from the host transfer operators at the
    sharded/replicated boundary. Pt: (n_fine, nc); Rt: (nc, n_fine)."""
    n_f, nc = Pt.shape
    # P: rows sharded by the fine partition, columns global (replicated uc)
    prows = Pt.expanded_rows()
    K1 = max(int(Pt.row_nnz().max()), 1) if Pt.nnz else 1
    pc = np.zeros((nd, nloc, K1), dtype=np.int32)
    vdt = np.result_type(Pt.val.dtype, np.float64)
    pv = np.zeros((nd, nloc, K1), dtype=vdt)
    for s_ in range(nd):
        r0, r1 = min(s_ * nloc, n_f), min((s_ + 1) * nloc, n_f)
        lo, hi = int(Pt.ptr[r0]), int(Pt.ptr[r1])
        c, v = pack_rows_ell(prows[lo:hi] - r0, Pt.col[lo:hi],
                              Pt.val[lo:hi], nloc, K1)
        pc[s_], pv[s_] = c, v
    # R: per-shard column restriction; rows = full coarse vector
    rrows = Rt.expanded_rows()
    owner = np.minimum(Rt.col // nloc, nd - 1)
    K2 = 1
    for s_ in range(nd):
        sel = owner == s_
        if sel.any():
            K2 = max(K2, int(np.bincount(rrows[sel], minlength=nc).max()))
    rc = np.zeros((nd, nc, K2), dtype=np.int32)
    rv = np.zeros((nd, nc, K2), dtype=vdt)
    for s_ in range(nd):
        sel = owner == s_
        c, v = pack_rows_ell(rrows[sel], Rt.col[sel] - s_ * nloc,
                              Rt.val[sel], nc, K2)
        rc[s_], rv[s_] = c, v
    put = lambda a, dt: put_sharded(a, mesh, dt)
    return TransitionOps(put(pc, jnp.int32), put(pv, dtype),
                         put(rc, jnp.int32), put(rv, dtype))


def _build_dist_smoother(relax, Ak, Ak_s, dA, mesh, nd, dtype):
    """Shard one level's smoother state over the mesh. Every registry
    smoother family is supported with its GLOBAL state (halo-plan ELL
    factors / masks), so distributed smoothing is bit-for-bit the serial
    math — unlike the reference, whose mpi wrappers degrade ILU/GS to the
    rank-local block (amgcl/mpi/relaxation/*.hpp). Unsupported smoother
    types raise instead of silently degrading."""
    from amgcl_tpu.relaxation.chebyshev import ChebyshevState
    from amgcl_tpu.relaxation.ilu0 import ILU0, ILUT, ILUK, ILUP
    from amgcl_tpu.relaxation.gauss_seidel import GaussSeidel, \
        greedy_coloring
    from amgcl_tpu.relaxation.spai1 import Spai1

    n_pad = dA.nloc * nd

    def shard_vec(v, fill=0.0):
        host_dt = np.result_type(np.asarray(v).dtype, np.float64)
        pad = np.full(n_pad, fill, dtype=host_dt)
        pad[:len(v)] = np.asarray(v, dtype=host_dt)
        return put_sharded(pad.reshape(nd, dA.nloc), mesh, dtype)

    if isinstance(relax, (ILU0, ILUT, ILUK, ILUP)):
        Lh, Uh, udia = relax.build_host(Ak)
        # factor partitions must match the level's (possibly shrunk) one
        return DistSmoother(
            "ilu", Ls=build_dist_ell(Lh, mesh, dtype, nloc=dA.nloc,
                                     ncloc=dA.nloc),
            Us=build_dist_ell(Uh, mesh, dtype, nloc=dA.nloc,
                              ncloc=dA.nloc),
            uinv=shard_vec(1.0 / udia, fill=1.0),
            jacobi_iters=relax.jacobi_iters)
    if isinstance(relax, GaussSeidel):
        color = greedy_coloring(Ak_s.to_scipy())
        nc = int(color.max()) + 1
        masks = np.zeros((nc, n_pad))
        masks[color, np.arange(Ak_s.nrows)] = 1.0
        masks = masks.reshape(nc, nd, dA.nloc).transpose(1, 0, 2)
        return DistSmoother(
            "gs", scale=shard_vec(Ak_s.diagonal(invert=True)),
            masks=put_sharded(masks, mesh, dtype))
    if isinstance(relax, Spai1):
        Mh = relax.build_host(Ak)
        return DistSmoother("spai1", Msp=build_dist_ell(
            Mh, mesh, dtype, nloc=dA.nloc, ncloc=dA.nloc))

    st = relax.build(Ak, dtype)
    if isinstance(st, ChebyshevState):
        dinv_sh = shard_vec(st.dinv) if st.scale else None
        return DistSmoother("cheb", dinv_sh, st.theta, st.delta, st.degree)
    if hasattr(st, "scale") and np.ndim(st.scale) == 1:
        return DistSmoother("diag", shard_vec(st.scale))
    if hasattr(st, "scale") and np.ndim(st.scale) == 3:
        b = int(np.shape(st.scale)[-1])
        if dA.nloc % b:
            raise ValueError(
                "block smoother blocks (b=%d) straddle the shard boundary "
                "(nloc=%d); choose a mesh with nloc divisible by b"
                % (b, dA.nloc))
        vdt = np.result_type(np.asarray(st.scale).dtype, np.float64)
        M = np.zeros((n_pad // b, b, b), dtype=vdt)
        M[:np.shape(st.scale)[0]] = np.asarray(st.scale, dtype=vdt)
        return DistSmoother("bdiag", put_sharded(
            M.reshape(nd, dA.nloc // b, b, b), mesh, dtype))
    raise ValueError(
        "smoother %s has no distributed form; use one of damped_jacobi/"
        "spai0/spai1/chebyshev/gauss_seidel/ilu0/iluk/ilup/ilut"
        % type(relax).__name__)


class _LocalOp:
    """Shard-local operator adapter: gives the serial Krylov bodies their
    ``.mv`` while the halo exchange happens underneath."""

    def __init__(self, dist_mat):
        self.m = dist_mat

    def mv(self, x):
        return self.m.shard_mv(x)


class DistAMGSolver:
    """mpi::make_solver equivalent: distributed AMG-preconditioned Krylov
    over the mesh, one compiled SPMD program per (structure, params)."""

    def __init__(self, A, mesh, prm: Optional[AMGParams] = None,
                 solver: Any = None, replicate_below: int = 4096,
                 device_mis: bool = False, min_per_shard: int = 0,
                 repartition: float = 0.0, precond_dtype: Any = None,
                 rep_rowshard: bool = False):
        """``device_mis=True`` runs the aggregation MIS rounds sharded on
        the mesh (parallel/dist_mis.py) instead of the host greedy pass —
        the reference's distributed-PMIS role
        (amgcl/mpi/coarsening/pmis.hpp), reformulated as halo-plan row-max
        propagation.

        ``min_per_shard`` concentrates mid-size sharded levels on fewer
        shards (the repartition-merge analogue, see the level loop).

        ``repartition`` > 0 permutes any coarse sharded level whose halo
        fraction (parallel/repartition.py) exceeds the value — the
        reference's mpi::partition::parmetis/ptscotch role
        (parmetis.hpp:105-199: A <- I^T A I, P <- P I) realized as an RCM
        locality permutation of the level's index space.

        ``precond_dtype`` stores the sharded level/transfer/smoother
        arrays in a narrower dtype (e.g. bfloat16 — halves HBM bytes per
        V-cycle) while the Krylov vectors stay in ``prm.dtype`` — the
        distributed rendition of the mixing.hpp precision seam.

        ``rep_rowshard=True`` row-shards the FINEST replicated-tail
        level's smoother/residual/prolong work across the mesh (one
        all_gather per op) instead of every shard redundantly computing
        the whole tail — trades tail FLOPs for small collectives; worth
        it when the tail is fat relative to ICI latency (A/B'd in the
        multichip dryrun)."""
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.mesh = mesh
        self.prm = prm or AMGParams()
        if device_mis:
            import copy as _copy
            from amgcl_tpu.parallel.dist_mis import make_mesh_aggregator
            prm2 = _copy.copy(self.prm)
            coars = _copy.deepcopy(self.prm.coarsening)
            if not hasattr(coars, "aggregator"):
                raise ValueError(
                    "device_mis needs an aggregation-based coarsening "
                    "(smoothed_aggregation / aggregation), got %s"
                    % type(coars).__name__)
            if A.is_block or getattr(coars, "block_size", 1) > 1:
                # pointwise (block) aggregation takes a different path that
                # bypasses the aggregator hook — fail loudly rather than
                # silently running the host pass
                raise ValueError(
                    "device_mis does not support block (pointwise) "
                    "aggregation yet; unblock the system or drop "
                    "device_mis")
            coars.aggregator = make_mesh_aggregator(mesh)
            prm2.coarsening = coars
            self.prm = prm2
        if getattr(self.prm.coarsening, "stencil_setup", False):
            # the stencil setup path returns implicit transfer proxies;
            # this wrapper shards explicit CSR P/R, so keep the CSR route
            import copy as _copy
            prm2 = _copy.copy(self.prm)
            prm2.coarsening = _copy.deepcopy(self.prm.coarsening)
            prm2.coarsening.stencil_setup = False
            self.prm = prm2
        self.solver = solver or CG()
        dtype = self.prm.dtype                    # Krylov vector dtype
        mat_dtype = precond_dtype or dtype        # sharded operator dtype
        nd = mesh.shape[ROWS_AXIS]

        # serial host-side construction; the device filter skips serial
        # device states for levels this wrapper re-shards itself (they'd be
        # discarded — e.g. a second Chow-Patel factorization per level).
        # It mirrors the replicate-split rule below: a level is replicated
        # iff it is the last, or coarse enough and not the finest.
        host = AMG(A, self.prm,
                   device_filter=lambda j, sz, last: last or (
                       j > 0 and sz < replicate_below))
        self.host_amg = host
        # split: levels at or above `replicate_below` rows stay sharded;
        # the tail is replicated (the merge/repartition analogue) — at
        # minimum the coarsest level
        sizes = [h[0].nrows * h[0].block_size[0] for h in host.host_levels]
        if len(sizes) == 1:
            t = 0                      # whole hierarchy replicated
        else:
            t = next((j for j, sz in enumerate(sizes)
                      if sz < replicate_below and j > 0),
                     len(sizes) - 1)
        self._split = t
        # mid-size level shrink (reference: mpi::partition::merge,
        # merge.hpp:47-137 with min_per_proc): a level whose even spread
        # would drop below `min_per_shard` rows/shard is concentrated on
        # the first ceil(n / min_per_shard) shards instead — fewer halo
        # pairs, bigger per-shard blocks, same SPMD program
        def lvl_nloc(n_scalar):
            base = -(-n_scalar // nd)
            return max(base, min(int(min_per_shard), n_scalar)) \
                if min_per_shard else base

        nlocs = [lvl_nloc(h[0].nrows * h[0].block_size[0])
                 for h in host.host_levels[:t]]
        # the EXECUTED per-level partition (min_per_shard concentration
        # included) — the per-shard ledger derives its strip bounds from
        # exactly this, so a skewed partition reports its real imbalance
        self._nlocs = list(nlocs)
        self.repartition_report = []
        if repartition and t > 1:
            from amgcl_tpu.parallel.repartition import \
                repartition_host_levels
            # after nlocs: the halo metric must describe the EXECUTED
            # layout, incl. the min_per_shard concentration
            self.repartition_report = repartition_host_levels(
                host.host_levels, t, float(repartition), nd, nlocs)
        levels = []
        for k, (Ak, Pk, Rk) in enumerate(host.host_levels[:t]):
            Ak_s = Ak.unblock() if Ak.is_block else Ak
            dA = build_dist_ell(Ak_s, mesh, mat_dtype, nloc=nlocs[k],
                                ncloc=nlocs[k])
            dP = dR = None
            # the last sharded level's transfers become the transition ops,
            # so don't build (then discard) distributed versions of them
            if Pk is not None and k != t - 1:
                dP = build_dist_ell(
                    Pk.unblock() if Pk.is_block else Pk, mesh, mat_dtype,
                    nloc=nlocs[k], ncloc=nlocs[k + 1])
                dR = build_dist_ell(
                    Rk.unblock() if Rk.is_block else Rk, mesh, mat_dtype,
                    nloc=nlocs[k + 1], ncloc=nlocs[k])
            sm = _build_dist_smoother(self.prm.relax, Ak, Ak_s, dA, mesh,
                                      nd, mat_dtype)
            levels.append(DistLevel(dA, dP, dR, sm))

        # replicated tail = the serial device hierarchy's own levels
        from amgcl_tpu.models.amg import Hierarchy as SerialHierarchy
        rep = SerialHierarchy(host.hierarchy.levels[t:],
                              host.hierarchy.coarse,
                              self.prm.npre, self.prm.npost,
                              self.prm.ncycle, 1)
        top_A = None
        trans = None
        if t == 0:
            # no sharded levels: top_A IS the Krylov operator and nothing
            # else — always solver precision (the preconditioner runs
            # through the replicated hierarchy)
            A0 = host.host_levels[0][0]
            top_A = build_dist_ell(A0.unblock() if A0.is_block else A0,
                                   mesh, dtype)
        else:
            Pt = host.host_levels[t - 1][1]
            Rt = host.host_levels[t - 1][2]
            trans = _transition_ops(
                Pt.unblock() if Pt.is_block else Pt,
                Rt.unblock() if Rt.is_block else Rt,
                nd, levels[-1].A.nloc, mesh, mat_dtype)
        if levels and jnp.dtype(mat_dtype) != jnp.dtype(dtype):
            # mixing.hpp seam: the Krylov loop needs a solver-precision
            # system matrix; the narrowed copy serves only the cycle
            A0 = host.host_levels[0][0]
            top_A = build_dist_ell(A0.unblock() if A0.is_block else A0,
                                   mesh, dtype, nloc=nlocs[0],
                                   ncloc=nlocs[0])
        self.hier = DistHierarchy(levels, rep, trans, top_A,
                                  self.prm.npre, self.prm.npost,
                                  self.prm.ncycle, self.prm.pre_cycles,
                                  rep_rowshard=rep_rowshard)
        self.n = A.nrows * A.block_size[0]
        first_A = levels[0].A if levels else top_A
        self.n_pad = first_A.nloc * nd
        self._compiled = None

    def _build_compiled(self):
        solver = self.solver
        hier_specs = self.hier.specs()
        n_true = self.n
        nloc = self.n_pad // self.mesh.shape[ROWS_AXIS]

        def body(hier, rhs, x0):
            Aop = _LocalOp(hier.system_A())
            kw = {}
            # IDR(s) derives its shadow space from GLOBAL row indices so the
            # distributed run uses exactly the serial shadow space (see
            # solver/idrs.py); hand it the shard's global index window.
            from amgcl_tpu.solver.idrs import IDRs
            if isinstance(solver, IDRs):
                kw = dict(
                    row_index=lax.axis_index(ROWS_AXIS) * nloc
                    + jnp.arange(nloc),
                    n_valid=n_true)
            # [:3]: solvers with record_history return an extra element
            x, it, res = solver.solve(
                Aop, hier.shard_apply, rhs, x0,
                inner_product=dist_inner_product, **kw)[:3]
            return x, it, res

        fn = shard_map(
            body, mesh=self.mesh,
            in_specs=(hier_specs, P(ROWS_AXIS), P(ROWS_AXIS)),
            out_specs=(P(ROWS_AXIS), P(), P()),
            check_vma=False)
        # observed jit (telemetry/compile_watch.py): THE distributed
        # AMG solve program — a retrace per call here is the worst
        # silent-latency case on a pod
        from amgcl_tpu.telemetry.compile_watch import watched_jit
        return watched_jit(fn, name="parallel.dist_amg_solve")

    def __call__(self, rhs, x0=None):
        dtype = self.prm.dtype
        nd = self.mesh.shape[ROWS_AXIS]
        rhs_p = put_sharded(
            _pad_vec(np.asarray(rhs), self.n_pad // nd, nd, dtype),
            self.mesh)
        x0_p = jnp.zeros_like(rhs_p) if x0 is None else put_sharded(
            _pad_vec(np.asarray(x0), self.n_pad // nd, nd, dtype),
            self.mesh)
        import time as _time
        t0 = _time.perf_counter()
        first_call = self._compiled is None
        if first_call:
            self._compiled = self._build_compiled()
        x, it, res = self._compiled(self.hier, rhs_p, x0_p)
        from amgcl_tpu.parallel.mesh import host_full
        from amgcl_tpu.telemetry import emit as _tel_emit
        # it/res land here already mesh-reduced (psum dots, replicated
        # out-specs) — the report is identical on every shard
        info = SolverInfo(
            int(it), float(res),
            wall_time_s=_time.perf_counter() - t0,
            solver=type(self.solver).__name__,
            resources=self.resource_ledger(),
            extra={"devices": int(nd),
                   **({"first_call": True} if first_call else {})})
        _tel_emit(info.to_dict(), event="dist_solve", n=self.n)
        return host_full(x)[:self.n], info

    def resource_ledger(self):
        """Distributed resource ledger: per-sharded-level halo comm per
        SpMV, aggregated cycle/iteration wire volume across the mesh, and
        the memory side (sharded device bytes + the replicated tail's
        hierarchy ledger). Cached per build; never raises."""
        cached = getattr(self, "_resources_cache", None)
        if cached is not None:
            return cached
        from amgcl_tpu.telemetry import ledger as L
        try:
            nd = int(self.mesh.shape[ROWS_AXIS])
            itemsize = jnp.dtype(self.prm.dtype).itemsize
            sweeps = self.prm.npre + self.prm.npost + 1  # sweeps + resid
            lv_rows = []
            cyc = {"msgs": 0, "bytes": 0}
            for k, lv in enumerate(self.hier.levels):
                c = L.comm_model(lv.A, nd) or {"msgs": 0, "bytes": 0}
                row = {"level": k, "per_spmv": c,
                       "spmvs_per_cycle": sweeps}
                cyc["msgs"] += c["msgs"] * sweeps
                cyc["bytes"] += c["bytes"] * sweeps
                for T in (lv.P_op, lv.R_op):
                    tc = L.comm_model(T, nd) if T is not None else None
                    if tc:
                        cyc["msgs"] += tc["msgs"]
                        cyc["bytes"] += tc["bytes"]
                lv_rows.append(row)
            if self.hier.trans is not None:
                # transition restrict psums the FULL replicated coarse
                # vector across the mesh once per cycle
                nc = int(self.hier.trans.r_cols.shape[1])
                red = L.allreduce_model(nd, nc, itemsize)
                cyc["msgs"] += red["msgs"]
                cyc["bytes"] += red["bytes"]
                lv_rows.append({"level": "transition",
                                "allreduce": {"count": nc, **red}})
            pre_cycles = max(int(self.prm.pre_cycles), 1)
            top = self.hier.top_A if self.hier.top_A is not None \
                else (self.hier.levels[0].A if self.hier.levels else None)
            sname = type(self.solver).__name__
            spmvs, papps, dots, _ = L.KRYLOV_OPS.get(sname, (1, 1, 4, 4))
            top_comm = (L.comm_model(top, nd) if top is not None
                        else None) or {"msgs": 0, "bytes": 0}
            red1 = L.allreduce_model(nd, 1, itemsize)
            per_iter = {
                "msgs": (spmvs * top_comm["msgs"]
                         + papps * pre_cycles * cyc["msgs"]
                         + dots * red1["msgs"]),
                "bytes": (spmvs * top_comm["bytes"]
                          + papps * pre_cycles * cyc["bytes"]
                          + dots * red1["bytes"])}
            # per-shard imbalance (telemetry/comm.py): exact useful-work
            # rows/nnz per shard from the host CSR at the EXECUTED
            # partition — a min_per_shard concentration or a naturally
            # skewed level shows its real load factor here, padding-
            # uniform device buffers notwithstanding. Nested guard: a
            # wrapper without host_levels (StripAMGSolver reuses this
            # method) keeps its comm/memory ledger and just skips the
            # shard tables.
            from amgcl_tpu.telemetry import comm as _comm
            dist = {"devices": nd,
                    "provenance": _comm.hw_provenance(self.mesh)}
            try:
                dist_levels = []
                worst = 1.0
                nlocs = self._nlocs
                for k, lv in enumerate(self.hier.levels):
                    Ak = self.host_amg.host_levels[k][0]
                    Ak_s = Ak.unblock() if Ak.is_block else Ak
                    bounds = _comm.even_bounds(Ak_s.nrows, nd,
                                               nloc=nlocs[k])
                    row = _comm.level_shard_costs(Ak_s, bounds)
                    row["level"] = k
                    row["halo_slab"] = int(lv.A.send_idx.shape[-1]) \
                        if lv.A.send_idx is not None else 0
                    dist_levels.append(row)
                    worst = max(worst, row["imbalance"]["factor"])
                dist["levels"] = dist_levels
                dist["imbalance_factor"] = round(worst, 4)
            except Exception as e:
                dist["levels_error"] = repr(e)[:120]
            cached = {
                "comm": {"devices": nd, "levels": lv_rows,
                         "per_cycle": cyc, "per_iteration": per_iter},
                "dist": dist,
                "memory": {
                    # global logical bytes of the sharded arrays (each
                    # shard holds 1/nd of these)
                    "sharded_bytes": L._leaf_bytes(
                        (self.hier.levels, self.hier.trans,
                         self.hier.top_A)),
                    # the replicated tail lives whole on EVERY shard
                    "replicated_bytes": L._leaf_bytes(self.hier.rep),
                }}
        except Exception as e:
            cached = {"error": repr(e)[:200]}
        self._resources_cache = cached
        return cached

    def __repr__(self):
        return ("DistAMGSolver over %d devices\n%r"
                % (self.mesh.shape[ROWS_AXIS], self.host_amg))
