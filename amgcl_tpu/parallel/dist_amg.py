"""Distributed AMG: serial host construction, mesh-sharded solve.

Architecture decision (vs the reference's mpi::amg,
amgcl/mpi/amg.hpp:49-511): under single-controller JAX the host sees the
whole matrix, so the hierarchy is built once by the serial setup path (the
reference's pattern — hierarchies are always *built* on the CPU and *moved*
to the backend, README.md:22-26) and every level is then partitioned over
the mesh: level operators and transfer operators become
:class:`DistEllMatrix` with static halo plans, smoother state is sharded by
rows, and the coarsest dense solve is replicated (every shard applies the
same small inverse to the all-gathered coarse residual — the TPU equivalent
of the gather-to-masters coarse solve,
amgcl/mpi/direct_solver/solver_base.hpp:41-130).

The Krylov loop reuses the *serial* solver classes inside ``shard_map``,
exactly the reference's trick of pairing serial Krylov bodies with a
distributed matrix and a globalized inner product
(amgcl/mpi/solver/cg.hpp:41-46): the local operator adapter exposes ``.mv``
(halo exchange + local SpMV) and the inner product is psum-reduced.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import SolverInfo
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.parallel.mesh import ROWS_AXIS
from amgcl_tpu.parallel.dist_ell import DistEllMatrix, build_dist_ell
from amgcl_tpu.parallel.dist_matrix import dist_inner_product


def _pad_vec(v, nloc, nd, dtype):
    out = np.zeros(nloc * nd, dtype=np.float64)
    out[:len(v)] = np.asarray(v, dtype=np.float64)
    return jnp.asarray(out, dtype=dtype)


@register_pytree_node_class
class DistSmoother:
    """Sharded smoother state: 'diag' (spai0/jacobi scale per row) or
    'cheb' (Chebyshev polynomial — SpMV-only, scalars static)."""

    def __init__(self, kind, scale=None, theta=0.0, delta=1.0, degree=0):
        self.kind = kind
        self.scale = scale          # (nd, nloc) or None
        self.theta = float(theta)
        self.delta = float(delta)
        self.degree = int(degree)

    def tree_flatten(self):
        return (self.scale,), (self.kind, self.theta, self.delta,
                               self.degree)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(aux[0], children[0], *aux[1:])

    def spec(self):
        return DistSmoother(self.kind,
                            None if self.scale is None else P(ROWS_AXIS,
                                                              None),
                            self.theta, self.delta, self.degree)

    # -- inside shard_map (Aop wraps the level's halo SpMV) ----------------

    def _cheb(self, Aop, f):
        from amgcl_tpu.relaxation.chebyshev import ChebyshevState
        dinv = None if self.scale is None else self.scale[0]
        st = ChebyshevState(dinv, self.degree, self.theta, self.delta,
                            dinv is not None)
        return st.apply(Aop, f)

    def apply0(self, Aop, f):
        """One application from a zero initial guess."""
        if self.kind == "cheb":
            return self._cheb(Aop, f)
        return self.scale[0] * f

    def sweep(self, Aop, f, u):
        if self.kind == "cheb":
            return u + self._cheb(Aop, f - Aop.mv(u))
        return u + self.scale[0] * (f - Aop.mv(u))


@register_pytree_node_class
class DistLevel:
    def __init__(self, A, P_op, R_op, smoother):
        self.A = A
        self.P_op = P_op        # None on the coarsest level
        self.R_op = R_op
        self.smoother = smoother

    def tree_flatten(self):
        return (self.A, self.P_op, self.R_op, self.smoother), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@register_pytree_node_class
class DistHierarchy:
    """Sharded multilevel state; ``shard_apply`` runs inside shard_map."""

    def __init__(self, levels, coarse_inv, npre=1, npost=1, ncycle=1,
                 pre_cycles=1):
        self.levels = list(levels)
        self.coarse_inv = coarse_inv   # replicated (nc, nc) or None
        self.npre = int(npre)
        self.npost = int(npost)
        self.ncycle = int(ncycle)
        self.pre_cycles = int(pre_cycles)

    def tree_flatten(self):
        return ((self.levels, self.coarse_inv),
                (self.npre, self.npost, self.ncycle, self.pre_cycles))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    def specs(self):
        lvls = [DistLevel(l.A.specs(),
                          None if l.P_op is None else l.P_op.specs(),
                          None if l.R_op is None else l.R_op.specs(),
                          l.smoother.spec()) for l in self.levels]
        return DistHierarchy(lvls, None if self.coarse_inv is None else P(),
                             self.npre, self.npost, self.ncycle,
                             self.pre_cycles)

    # -- inside shard_map ---------------------------------------------------

    def shard_cycle(self, i, f):
        lv = self.levels[i]
        Aop = _LocalOp(lv.A)
        sm = lv.smoother
        if i == len(self.levels) - 1:
            if self.coarse_inv is not None:
                full = lax.all_gather(f, ROWS_AXIS, tiled=True)
                u_full = self.coarse_inv @ full
                s = lax.axis_index(ROWS_AXIS)
                return lax.dynamic_slice(u_full, (s * f.shape[0],),
                                         (f.shape[0],))
            return sm.apply0(Aop, f)
        if self.npre > 0:
            u = sm.apply0(Aop, f)
            for _ in range(self.npre - 1):
                u = sm.sweep(Aop, f, u)
        else:
            u = jnp.zeros_like(f)
        r = f - lv.A.shard_mv(u)
        fc = lv.R_op.shard_mv(r)
        uc = self.shard_cycle(i + 1, fc)
        for _ in range(self.ncycle - 1):   # W-cycle extra coarse visits
            rc = fc - self.levels[i + 1].A.shard_mv(uc)
            uc = uc + self.shard_cycle(i + 1, rc)
        u = u + lv.P_op.shard_mv(uc)
        for _ in range(self.npost):
            u = sm.sweep(Aop, f, u)
        return u

    def shard_apply(self, r):
        x = self.shard_cycle(0, r)
        for _ in range(self.pre_cycles - 1):
            rr = r - self.levels[0].A.shard_mv(x)
            x = x + self.shard_cycle(0, rr)
        return x

    def system_A(self):
        return self.levels[0].A


class _LocalOp:
    """Shard-local operator adapter: gives the serial Krylov bodies their
    ``.mv`` while the halo exchange happens underneath."""

    def __init__(self, dist_mat):
        self.m = dist_mat

    def mv(self, x):
        return self.m.shard_mv(x)


class DistAMGSolver:
    """mpi::make_solver equivalent: distributed AMG-preconditioned Krylov
    over the mesh, one compiled SPMD program per (structure, params)."""

    def __init__(self, A, mesh, prm: Optional[AMGParams] = None,
                 solver: Any = None):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.mesh = mesh
        self.prm = prm or AMGParams()
        self.solver = solver or CG()
        dtype = self.prm.dtype
        nd = mesh.shape[ROWS_AXIS]

        host = AMG(A, self.prm)          # serial host-side construction
        self.host_amg = host
        levels = []
        vec_shard = NamedSharding(mesh, P(ROWS_AXIS, None))
        for k, (Ak, Pk, Rk) in enumerate(host.host_levels):
            Ak_s = Ak.unblock() if Ak.is_block else Ak
            dA = build_dist_ell(Ak_s, mesh, dtype)
            dP = dR = None
            if Pk is not None:
                dP = build_dist_ell(
                    Pk.unblock() if Pk.is_block else Pk, mesh, dtype)
                dR = build_dist_ell(
                    Rk.unblock() if Rk.is_block else Rk, mesh, dtype)
            st = self.prm.relax.build(Ak, dtype)
            from amgcl_tpu.relaxation.chebyshev import ChebyshevState
            if isinstance(st, ChebyshevState):
                dinv_sh = None
                if st.scale:
                    pad = np.zeros(dA.nloc * nd)
                    pad[:Ak_s.nrows] = np.asarray(st.dinv, dtype=np.float64)
                    dinv_sh = jax.device_put(
                        jnp.asarray(pad.reshape(nd, dA.nloc), dtype=dtype),
                        NamedSharding(mesh, P(ROWS_AXIS, None)))
                sm = DistSmoother("cheb", dinv_sh, st.theta, st.delta,
                                  st.degree)
            else:
                if hasattr(st, "scale") and np.ndim(st.scale) == 1:
                    scale = np.asarray(st.scale, dtype=np.float64)
                else:
                    import warnings
                    warnings.warn(
                        "distributed AMG shards diagonal-type and Chebyshev "
                        "smoothers; %s falls back to damped Jacobi"
                        % type(self.prm.relax).__name__)
                    scale = 0.72 * Ak_s.diagonal(invert=True)
                pad = np.zeros(dA.nloc * nd)
                pad[:len(scale)] = scale
                sm = DistSmoother(
                    "diag",
                    jax.device_put(
                        jnp.asarray(pad.reshape(nd, dA.nloc), dtype=dtype),
                        NamedSharding(mesh, P(ROWS_AXIS, None))))
            levels.append(DistLevel(dA, dP, dR, sm))
        coarse_inv = None
        if host.hierarchy.coarse is not None:
            inv = np.asarray(host.hierarchy.coarse.inv, dtype=np.float64)
            nc_pad = levels[-1].A.nloc * nd
            padinv = np.zeros((nc_pad, nc_pad))
            padinv[:inv.shape[0], :inv.shape[1]] = inv
            coarse_inv = jnp.asarray(padinv, dtype=dtype)
        self.hier = DistHierarchy(levels, coarse_inv,
                                  self.prm.npre, self.prm.npost,
                                  self.prm.ncycle, self.prm.pre_cycles)
        self.n = A.nrows * A.block_size[0]
        self.n_pad = levels[0].A.nloc * nd
        self._compiled = None

    def _build_compiled(self):
        solver = self.solver
        hier_specs = self.hier.specs()

        def body(hier, rhs, x0):
            Aop = _LocalOp(hier.system_A())
            x, it, res = solver.solve(
                Aop, hier.shard_apply, rhs, x0,
                inner_product=dist_inner_product)
            return x, it, res

        fn = shard_map(
            body, mesh=self.mesh,
            in_specs=(hier_specs, P(ROWS_AXIS), P(ROWS_AXIS)),
            out_specs=(P(ROWS_AXIS), P(), P()),
            check_vma=False)
        return jax.jit(fn)

    def __call__(self, rhs, x0=None):
        dtype = self.prm.dtype
        nd = self.mesh.shape[ROWS_AXIS]
        vec = NamedSharding(self.mesh, P(ROWS_AXIS))
        rhs_p = jax.device_put(
            _pad_vec(np.asarray(rhs), self.n_pad // nd, nd, dtype), vec)
        x0_p = jnp.zeros_like(rhs_p) if x0 is None else jax.device_put(
            _pad_vec(np.asarray(x0), self.n_pad // nd, nd, dtype), vec)
        if self._compiled is None:
            self._compiled = self._build_compiled()
        x, it, res = self._compiled(self.hier, rhs_p, x0_p)
        return np.asarray(x)[:self.n], SolverInfo(int(it), float(res))

    def __repr__(self):
        return ("DistAMGSolver over %d devices\n%r"
                % (self.mesh.shape[ROWS_AXIS], self.host_amg))
