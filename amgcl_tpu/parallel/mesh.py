"""Mesh construction helpers.

One logical axis ``rows`` carries the domain decomposition (the analogue of
MPI ranks in the reference's distributed_matrix). Multi-axis meshes (rows ×
replicas) can be layered later; the solver code only names ``rows``.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

ROWS_AXIS = "rows"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over ``rows``. With ``n_devices=None`` uses all local
    devices (the CI path: 8 virtual CPU devices via
    --xla_force_host_platform_device_count)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(list(devices), (ROWS_AXIS,))


def put_sharded(a, mesh, dtype=None, axis=ROWS_AXIS):
    """device_put a HOST array sharded over its leading dim.

    The array must stay numpy until the put: device_put(numpy, sharding)
    slices on host and lands each shard directly on its device, while
    device_put(jnp.asarray(...), sharding) commits to one device first and
    then RESHARDS — which compiles a throwaway XLA program per (shape,
    sharding) pair and dominated round-1's distributed setup time
    (4.46s for 32^3/8dev, ~80% pjit compiles)."""
    a = np.asarray(a)
    if dtype is not None:
        a = a.astype(np.dtype(dtype))     # bf16 works via ml_dtypes
    spec = PartitionSpec(axis, *([None] * (a.ndim - 1)))
    return jax.device_put(a, NamedSharding(mesh, spec))
