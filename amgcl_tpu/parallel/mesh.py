"""Mesh construction helpers.

One logical axis ``rows`` carries the domain decomposition (the analogue of
MPI ranks in the reference's distributed_matrix). Multi-axis meshes (rows ×
replicas) can be layered later; the solver code only names ``rows``.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

ROWS_AXIS = "rows"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over ``rows``. With ``n_devices=None`` uses all local
    devices (the CI path: 8 virtual CPU devices via
    --xla_force_host_platform_device_count)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(list(devices), (ROWS_AXIS,))


def put_sharded(a, mesh, dtype=None, axis=ROWS_AXIS):
    """Place a HOST array on the mesh sharded over its leading dim.

    The array must stay numpy until the placement: per-shard host slices
    land directly on their devices, while device_put(jnp.asarray(...),
    sharding) commits to one device first and then RESHARDS — which
    compiles a throwaway XLA program per (shape, sharding) pair and
    dominated round-1's distributed setup time (4.46s for 32^3/8dev,
    ~80% pjit compiles).

    ``make_array_from_callback`` (vs plain device_put of the numpy array)
    also works under MULTI-CONTROLLER meshes: each process materializes
    only its addressable shards, so the same setup code drives a
    multi-host `jax.distributed` mesh (see parallel/multihost.py)."""
    a = np.asarray(a)
    if dtype is not None:
        a = a.astype(np.dtype(dtype))     # bf16 works via ml_dtypes
    spec = PartitionSpec(axis, *([None] * (a.ndim - 1)))
    return put_with_sharding(a, NamedSharding(mesh, spec))


def put_sharded_parts(parts, mesh, dtype=None, axis=ROWS_AXIS):
    """Per-shard host blocks -> one sharded array with leading dim
    ``len(parts)``, WITHOUT materializing the concatenation: the callback
    serves each device its own block, so host peak memory stays one part.
    Under multi-controller, entries for non-addressable shards may be
    ``None`` — the callback is only invoked for this process's shards
    (strip-parallel setup relies on both properties)."""
    nd = len(parts)
    p0 = np.asarray(next(p for p in parts if p is not None))
    dt = np.dtype(dtype) if dtype is not None else p0.dtype
    shape = (nd,) + p0.shape
    spec = PartitionSpec(axis, *([None] * p0.ndim))

    def cb(idx):
        s = idx[0].start
        return np.asarray(parts[0 if s is None else s], dtype=dt)[None]

    return jax.make_array_from_callback(
        shape, NamedSharding(mesh, spec), cb)


def put_with_sharding(a, sharding):
    """Place a host numpy array under an arbitrary NamedSharding via the
    per-shard callback path (multi-controller-safe; no reshard compile)."""
    a = np.asarray(a)
    return jax.make_array_from_callback(a.shape, sharding,
                                        lambda idx: a[idx])


def host_full(x) -> np.ndarray:
    """A row-sharded global array as full numpy on EVERY process: plain
    np.asarray single-controller, process_allgather under
    jax.distributed (where each process only holds its own shards)."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
