"""Mesh construction helpers.

One logical axis ``rows`` carries the domain decomposition (the analogue of
MPI ranks in the reference's distributed_matrix). Multi-axis meshes (rows ×
replicas) can be layered later; the solver code only names ``rows``.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

ROWS_AXIS = "rows"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over ``rows``. With ``n_devices=None`` uses all local
    devices (the CI path: 8 virtual CPU devices via
    --xla_force_host_platform_device_count)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(list(devices), (ROWS_AXIS,))
