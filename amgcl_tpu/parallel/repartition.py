"""Coarse-level repartitioning for the row-block mesh decomposition.

Reference role: ``mpi::partition::parmetis`` / ``ptscotch``
(amgcl/mpi/partition/parmetis.hpp:105-199): produce a permutation matrix I
per level and re-distribute A <- Iᵀ A I, P <- P I, R <- Iᵀ R so coarse rows
live near the rows they couple with. On a TPU mesh the shard assignment is
fixed (equal row blocks), so re-distribution IS a symmetric permutation
that groups connected rows into the same block. Two partitioners compete
per level (``best_permutation``): reverse Cuthill-McKee (contiguous
slices of the RCM order — wins on banded graphs; machinery shared with
DIA/windowed-ELL packing) and the real multilevel k-way partitioner of
``parallel/partition.py`` (heavy-edge coarsening + spectral bisection +
FM refinement — the algorithm parmetis/ptscotch themselves run, winning
on genuinely irregular graphs where bandwidth reduction cannot localize
coupling). The winner is whichever achieves the lower halo fraction.

For order-independent smoothers (spai0/jacobi/chebyshev/spai1) the math
is permutation-invariant — iteration counts do not change (pinned by
tests/test_repartition.py). Order-DEPENDENT smoothers (Chow-Patel ILU
sweeps, multicolor GS coloring) see a different but equally valid
ordering, so counts may drift a little, exactly as the reference's
repartitioners cause. What always changes is the HALO VOLUME — the
unique remote values each shard fetches per SpMV. ``halo_fraction``
measures it; ``DistAMGSolver(repartition=thr)`` permutes any coarse
level whose fraction exceeds ``thr``.
"""

from __future__ import annotations

import numpy as np

from amgcl_tpu.ops.csr import CSR


def halo_fraction(A: CSR, nd: int, nloc: int | None = None) -> float:
    """Average unique remote columns per shard under the row-block
    partition (``nloc`` rows per shard; defaults to the even nd-way
    spread), as a fraction of the block size — the per-iteration halo
    traffic of the distributed SpMV relative to the local vector."""
    S = A.unblock() if A.is_block else A
    n = S.nrows
    nloc = -(-n // nd) if nloc is None else int(nloc)
    nd = min(nd, -(-n // nloc))    # shards actually holding rows
    rows = S.expanded_rows()
    row_shard = np.minimum(rows // nloc, nd - 1)
    col_shard = np.minimum(S.col // nloc, nd - 1)
    rem = row_shard != col_shard
    if not rem.any():
        return 0.0
    keys = row_shard[rem].astype(np.int64) * n + S.col[rem]
    return len(np.unique(keys)) / float(nd * nloc)


def locality_permutation(A: CSR) -> np.ndarray:
    """RCM ordering of the level operator: contiguous index ranges become
    connectivity-local row blocks."""
    from amgcl_tpu.utils.adapters import cuthill_mckee
    return cuthill_mckee(A.unblock() if A.is_block else A)


def best_permutation(A: CSR, nd: int, nloc: int | None = None):
    """(perm, permuted_A, halo_after): the better of the k-way
    partitioner (parallel/partition.py — the parmetis/ptscotch role) and
    the RCM locality ordering, judged by the halo fraction each achieves
    under the mesh's row-block layout. RCM wins on banded problems (its
    blocks are contiguous by construction); k-way wins on genuinely
    irregular graphs where bandwidth reduction cannot localize coupling.
    The winner's permuted matrix is returned so the caller does not
    permute a second time."""
    import warnings
    from amgcl_tpu.parallel.partition import partition_permutation
    from amgcl_tpu.utils.adapters import permute
    cands = [locality_permutation(A)]
    try:
        cands.append(partition_permutation(A, nd, nloc))
    except Exception as e:         # k-way is best-effort; RCM always works
        warnings.warn("k-way partitioner failed (%r); repartitioning "
                      "falls back to RCM locality only" % (e,),
                      RuntimeWarning, stacklevel=2)
    best = None
    for perm in cands:
        Ap = permute(A, perm)
        h = halo_fraction(Ap, nd, nloc)
        if best is None or h < best[2]:
            best = (perm, Ap, h)
    return best


def _perm_cols(M: CSR, perm: np.ndarray) -> CSR:
    """Column j of the result is old column perm[j]."""
    m = M.to_scipy()[:, perm].tocsr()
    m.sort_indices()
    return CSR.from_scipy(m)


def _perm_rows(M: CSR, perm: np.ndarray) -> CSR:
    m = M.to_scipy()[perm].tocsr()
    m.sort_indices()
    return CSR.from_scipy(m)


def repartition_host_levels(host_levels, t: int, threshold: float,
                            nd: int, nlocs=None):
    """Permute coarse levels 1..t-1 (the sharded ones below the finest)
    whose halo fraction exceeds ``threshold``. host_levels entries are
    (A_k, P_k, R_k) with P_k: (n_k, n_{k+1}); ``nlocs`` gives each
    level's ACTUAL rows-per-shard (the min_per_shard shrink may
    concentrate a level on fewer shards — the metric must describe the
    executed layout). Modifies the list in place and returns
    [(level, before, after), ...] for reporting. Level 0 keeps the
    user's ordering; block-valued levels are left alone (their pointwise
    layout is already cell-grouped)."""
    report = []
    for k in range(1, t):
        Ak = host_levels[k][0]
        if Ak.is_block:
            continue
        nloc_k = None if nlocs is None else nlocs[k]
        before = halo_fraction(Ak, nd, nloc_k)
        if before <= threshold:
            continue
        perm, A_new, after = best_permutation(Ak, nd, nloc_k)
        if after >= before:
            continue            # neither partitioner helped; keep as is
        Pk, Rk = host_levels[k][1], host_levels[k][2]
        Pprev, Rprev = host_levels[k - 1][1], host_levels[k - 1][2]
        host_levels[k - 1] = (host_levels[k - 1][0],
                              _perm_cols(Pprev, perm),
                              _perm_rows(Rprev, perm))
        host_levels[k] = (
            A_new,
            None if Pk is None else _perm_rows(Pk, perm),
            None if Rk is None else _perm_cols(Rk, perm))
        report.append((k, before, after))
    return report
