"""Additive-Schwarz (block-Jacobi) distributed preconditioner: each shard
applies a local preconditioner to its diagonal block, no cross-shard
coupling in the preconditioner (reference: amgcl/mpi/block_preconditioner.hpp
— restricted additive Schwarz with overlap 0).

The local preconditioner is an ILU(0) factorization of the shard's diagonal
block: the factors of the block-diagonal matrix are themselves
block-diagonal, so they distribute as DistEll operators with an empty halo
and the factor solves run shard-locally (Jacobi-approximate triangular
solves, as in the serial ILU).
"""

from __future__ import annotations

from typing import Any

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.parallel.mesh import ROWS_AXIS
from amgcl_tpu.parallel.dist_ell import build_dist_ell
from amgcl_tpu.parallel.dist_amg import DistAMGSolver


@register_pytree_node_class
class BlockILUHierarchy:
    """Sharded ILU factors of the block-diagonal part + system matrix."""

    def __init__(self, A, Ls, Us, uinv, jacobi_iters=2):
        self.A = A
        self.Ls = Ls
        self.Us = Us
        self.uinv = uinv        # (nd, nloc)
        self.jacobi_iters = int(jacobi_iters)

    def tree_flatten(self):
        return (self.A, self.Ls, self.Us, self.uinv), (self.jacobi_iters,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, aux[0])

    def specs(self):
        return BlockILUHierarchy(self.A.specs(), self.Ls.specs(),
                                 self.Us.specs(), P(ROWS_AXIS, None),
                                 self.jacobi_iters)

    def shard_apply(self, f):
        from amgcl_tpu.relaxation.ilu0 import ilu_jacobi_solve
        return ilu_jacobi_solve(self.Ls.shard_mv, self.Us.shard_mv,
                                self.uinv[0], self.jacobi_iters, f)

    def system_A(self):
        return self.A


class DistBlockPreconditioner(DistAMGSolver):
    """Distributed Krylov with a local-ILU additive-Schwarz preconditioner
    (no coarse space — pair with deflation for scalability)."""

    def __init__(self, A, mesh, solver: Any = None, dtype=jnp.float32,
                 sweeps: int = 5, jacobi_iters: int = 2):
        # deliberately NOT calling DistAMGSolver.__init__ — reuse only the
        # compiled-solve machinery
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        if A.is_block:
            A = A.unblock()
        self.mesh = mesh
        self.solver = solver or CG()
        nd = mesh.shape[ROWS_AXIS]
        self.n = A.nrows
        nloc = -(-A.nrows // nd)
        self.n_pad = nloc * nd

        from types import SimpleNamespace
        self.prm = SimpleNamespace(dtype=dtype)

        # block-diagonal part: drop entries crossing shard boundaries
        rows = A.expanded_rows()
        same = (rows // nloc) == (A.col // nloc)
        Abd = A.filter_rows(same)
        # keep unit diagonal on padded/empty rows implicitly via udia guard
        from amgcl_tpu.relaxation.ilu0 import ILU0
        Lh, Uh, udia = ILU0(sweeps=sweeps,
                            jacobi_iters=jacobi_iters).build_host(Abd)
        dA = build_dist_ell(A, mesh, dtype)
        dL = build_dist_ell(Lh, mesh, dtype)
        dU = build_dist_ell(Uh, mesh, dtype)
        ui = np.ones(self.n_pad)
        ui[:A.nrows] = 1.0 / udia
        from amgcl_tpu.parallel.mesh import put_sharded
        self.hier = BlockILUHierarchy(
            dA, dL, dU,
            put_sharded(ui.reshape(nd, nloc), mesh, dtype),
            jacobi_iters)
        self._compiled = None

    def __repr__(self):
        return ("DistBlockPreconditioner(ILU0 additive Schwarz) over %d "
                "devices" % self.mesh.shape[ROWS_AXIS])
