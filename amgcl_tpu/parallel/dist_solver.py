"""Distributed Krylov solve: the serial CG body over shard-resident vectors,
with psum-globalized reductions — exactly the reference's recipe of reusing
the serial solver with a distributed InnerProduct
(amgcl/mpi/solver/cg.hpp:41-46).

The whole iteration (halo exchanges, local SpMVs, psum dots) is one
``shard_map``-ped ``lax.while_loop`` — a single XLA program per solve across
the mesh, compiled once per (mesh, matrix structure, solver params) and
cached for repeat solves.

Two iteration bodies:

* :func:`dist_cg` — the classical Jacobi-CG recurrence, three scalar
  psums per iteration (rho, p·Ap, ‖r‖²).
* :func:`dist_cg_pipelined` — the Ghysels–Vanroose pipelined recurrence:
  the three reductions merge into ONE psum of a stacked 3-vector per
  iteration, and the body is ordered so the collective shares no
  operands with the next SpMV + preconditioner application — XLA's
  async-collective scheduler can run the allreduce while the halo SpMV
  streams, the same overlap-by-data-independence trick as
  ``dist_matrix.dia_halo_mv``. On a network where the allreduce latency
  rivals the local SpMV (large meshes, small shards) this is the
  standard latency-hiding CG. Enabled per call (``pipelined=True``) or
  process-wide via ``AMGCL_TPU_PIPELINED_CG=1``.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from amgcl_tpu.parallel.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from amgcl_tpu.parallel.mesh import ROWS_AXIS
from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix, dist_inner_product


def pipelined_cg_enabled() -> bool:
    """AMGCL_TPU_PIPELINED_CG=1 makes :func:`dist_cg` route through the
    merged-reduction pipelined body by default (per-call ``pipelined=``
    still wins). Default off: the classical recurrence is the
    bit-familiar baseline and the pipelined one reorders the roundoff."""
    return os.environ.get("AMGCL_TPU_PIPELINED_CG", "0") == "1"


@lru_cache(maxsize=64)
def _compiled_dist_cg(mesh, offsets, shape, maxiter, tol):
    """jit-compiled distributed CG keyed on structure, not data."""
    from amgcl_tpu.telemetry import health as H
    A = DistDiaMatrix(offsets, None, shape)  # structure only; data is an arg

    def body_shard(data, f, x, di):
        dot = dist_inner_product
        spmv = partial(A.shard_mv, data)
        r = f - spmv(x)
        norm_rhs = jnp.sqrt(jnp.abs(dot(f, f)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = tol * scale

        def cond(st):
            x, r, p, rho_p, it, res, hs = st
            return (it < maxiter) & (res > eps) & H.keep_going(hs)

        def body(st):
            x, r, p, rho_p, it, res, hs = st
            s = di * r
            rho = dot(r, s)
            beta = jnp.where(rho_p == 0, 0.0, rho / rho_p)
            p_n = s + beta * p
            q = spmv(p_n)
            qp = dot(q, p_n)
            alpha = rho / jnp.where(qp == 0, 1.0, qp)
            x_n = x + alpha * p_n
            r_n = r - alpha * q
            res_n = jnp.sqrt(jnp.abs(dot(r_n, r_n)))
            # same guard set as the serial CG; every input is already
            # psum-reduced, so the trips (and the early exit they drive)
            # are bitwise identical on every shard
            ok, hs = H.step(
                hs, it, res_n / scale,
                ((H.BREAKDOWN_RHO, H.bad_denom(rho)),
                 (H.BREAKDOWN_ALPHA, H.bad_denom(qp)),
                 (H.INDEFINITE, jnp.real(qp) < 0, False)))
            x, r, p, rho, res = H.commit(
                ok, (x_n, r_n, p_n, rho, res_n), (x, r, p, rho_p, res))
            return (x, r, p, rho, it + ok.astype(jnp.int32), res, hs)

        res0 = jnp.sqrt(jnp.abs(dot(r, r)))
        st = (x, r, jnp.zeros_like(r), jnp.zeros((), f.dtype),
              jnp.zeros((), jnp.int32), res0, H.init_state(res0 / scale))
        x, r, p, rho, it, res, hs = lax.while_loop(cond, body, st)
        return x, it, res / scale, hs.flags, hs.first_it

    fn = shard_map(
        body_shard, mesh=mesh,
        in_specs=(P(None, ROWS_AXIS), P(ROWS_AXIS), P(ROWS_AXIS),
                  P(ROWS_AXIS)),
        out_specs=(P(ROWS_AXIS), P(), P(), P(), P()),
        check_vma=False)
    # observed jit (telemetry/compile_watch.py): a dist_cg that retraces
    # per solve — a drifting halo plan or maxiter/tol passed non-static —
    # shows up as a retrace finding instead of silent compile seconds
    from amgcl_tpu.telemetry.compile_watch import watched_jit
    return watched_jit(fn, name="parallel.dist_cg")


@lru_cache(maxsize=64)
def _compiled_dist_cg_pipelined(mesh, offsets, shape, maxiter, tol):
    """jit-compiled pipelined (Ghysels–Vanroose) distributed CG: ONE
    psum of a stacked (γ, δ, ‖r‖²) partial 3-vector per iteration, with
    the next SpMV + Jacobi application data-independent of the
    collective so the scheduler can overlap them."""
    from amgcl_tpu.telemetry import health as H
    A = DistDiaMatrix(offsets, None, shape)  # structure only

    def body_shard(data, f, x, di):
        spmv = partial(A.shard_mv, data)
        r = f - spmv(x)
        u = di * r
        w = spmv(u)
        # setup reductions merged too: (γ0, δ0, ‖r0‖², ‖f‖²) in one psum
        g0 = lax.psum(jnp.stack([jnp.vdot(r, u), jnp.vdot(w, u),
                                 jnp.vdot(r, r), jnp.vdot(f, f)]),
                      ROWS_AXIS)
        gamma0, delta0, rr0, ff = g0[0], g0[1], g0[2], g0[3]
        norm_rhs = jnp.sqrt(jnp.abs(ff))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = tol * scale
        res0 = jnp.sqrt(jnp.abs(rr0))
        m0 = di * w
        nv0 = spmv(m0)
        zero = jnp.zeros_like(r)
        one = jnp.ones((), f.dtype)

        def cond(st):
            it, res, hs = st[12], st[13], st[14]
            return (it < maxiter) & (res > eps) & H.keep_going(hs)

        def body(st):
            (x, r, u, w, z, q, s, p, m, nv, gam_p, alpha_p, it, res,
             hs, gam, delta) = st
            beta = jnp.where(it == 0, 0.0,
                             gam / jnp.where(gam_p == 0, 1.0, gam_p))
            denom = delta - beta * gam / alpha_p
            alpha = gam / jnp.where(denom == 0, 1.0, denom)
            z_n = nv + beta * z
            q_n = m + beta * q
            s_n = w + beta * s
            p_n = u + beta * p
            x_n = x + alpha * p_n
            r_n = r - alpha * s_n
            u_n = u - alpha * q_n
            w_n = w - alpha * z_n
            # the ONE collective of the iteration: (γ', δ', ‖r‖²) from a
            # single stacked psum of the shard-local partials ...
            g = lax.psum(jnp.stack([jnp.vdot(r_n, u_n),
                                    jnp.vdot(w_n, u_n),
                                    jnp.vdot(r_n, r_n)]), ROWS_AXIS)
            # ... while the next iteration's Jacobi apply + halo SpMV
            # stream: they consume only w_n, sharing no operands with
            # the psum RESULT, so the async-collective scheduler can
            # overlap them (same structure as dia_halo_mv's interior)
            m_n = di * w_n
            nv_n = spmv(m_n)
            gam_n, delta_n, rr = g[0], g[1], g[2]
            res_n = jnp.sqrt(jnp.abs(rr))
            # same guard family as dist_cg: γ is the rho-analogue, the
            # recurrence denominator the alpha-analogue, and δ = <Au, u>
            # the p·Ap indefiniteness probe (informational, like the
            # classical body's); every input is psum-replicated so trips
            # are bitwise identical per shard
            ok, hs = H.step(
                hs, it, res_n / scale,
                ((H.BREAKDOWN_RHO, H.bad_denom(gam)),
                 (H.BREAKDOWN_ALPHA, H.bad_denom(denom)),
                 (H.INDEFINITE, jnp.real(delta) < 0, False)))
            (x, r, u, w, z, q, s, p, m, nv, gam_p, alpha_p, res, gam,
             delta) = H.commit(
                ok,
                (x_n, r_n, u_n, w_n, z_n, q_n, s_n, p_n, m_n, nv_n,
                 gam, alpha, res_n, gam_n, delta_n),
                (x, r, u, w, z, q, s, p, m, nv, gam_p, alpha_p, res,
                 gam, delta))
            return (x, r, u, w, z, q, s, p, m, nv, gam_p, alpha_p,
                    it + ok.astype(jnp.int32), res, hs, gam, delta)

        st = (x, r, u, w, zero, zero, zero, zero, m0, nv0, one, one,
              jnp.zeros((), jnp.int32), res0,
              H.init_state(res0 / scale), gamma0, delta0)
        out = lax.while_loop(cond, body, st)
        x, it, res, hs = out[0], out[12], out[13], out[14]
        return x, it, res / scale, hs.flags, hs.first_it

    fn = shard_map(
        body_shard, mesh=mesh,
        in_specs=(P(None, ROWS_AXIS), P(ROWS_AXIS), P(ROWS_AXIS),
                  P(ROWS_AXIS)),
        out_specs=(P(ROWS_AXIS), P(), P(), P(), P()),
        check_vma=False)
    from amgcl_tpu.telemetry.compile_watch import watched_jit
    return watched_jit(fn, name="parallel.dist_cg_pipelined")


class _DistResult(tuple):
    """(x, iters, rel_resid) that additionally carries ``.report`` — the
    telemetry SolveReport built from the mesh-reduced scalars (the iters/
    residual out-specs are already psum-globalized and replicated)."""
    report = None


def dist_cg(A: DistDiaMatrix, mesh, rhs, x0=None, dinv=None,
            maxiter: int = 200, tol: float = 1e-6, pipelined=None):
    """Jacobi-preconditioned distributed CG. ``dinv`` is the (sharded)
    inverted diagonal; identity preconditioning when None.

    ``pipelined`` selects the merged-reduction Ghysels–Vanroose body
    (ONE psum of a stacked 3-vector per iteration instead of three
    scalar collectives); ``None`` reads ``AMGCL_TPU_PIPELINED_CG``.

    Returns (x, iters, rel_resid) with x sharded over rows; the tuple's
    ``.report`` attribute holds the structured SolveReport and the record
    is emitted through the process-global telemetry sink."""
    import time as _time
    from amgcl_tpu.parallel.mesh import put_with_sharding
    from amgcl_tpu.telemetry import SolveReport, emit as _tel_emit
    if pipelined is None:
        pipelined = pipelined_cg_enabled()
    t0 = _time.perf_counter()
    vec = NamedSharding(mesh, P(ROWS_AXIS))
    rhs = put_with_sharding(rhs, vec)
    x0 = jnp.zeros_like(rhs) if x0 is None else put_with_sharding(x0, vec)
    dinv = jnp.ones_like(rhs) if dinv is None else put_with_sharding(dinv,
                                                                     vec)
    build = _compiled_dist_cg_pipelined if pipelined else _compiled_dist_cg
    fn = build(mesh, A.offsets, A.shape, int(maxiter), float(tol))
    x, it, res, hflags, hfirst = fn(A.data, rhs, x0, dinv)
    from amgcl_tpu.telemetry.health import decode as _decode_health
    health = _decode_health(hflags, hfirst)
    nd = int(mesh.shape[ROWS_AXIS])
    # halo/psum wire model (telemetry/ledger.py), priced from the SAME
    # declaration the static auditor (analysis/jaxpr_audit.py) checks
    # the traced body against: classical = three scalar psums/iter,
    # pipelined = ONE psum of a stacked 3-vector
    from amgcl_tpu.telemetry.ledger import (comm_model,
                                            krylov_comm_model,
                                            DIST_CG_COLLECTIVES)
    contract = DIST_CG_COLLECTIVES[
        "dist_cg_pipelined" if pipelined else "dist_cg"]
    spmv_comm = comm_model(A, nd)
    itemsize = jnp.dtype(rhs.dtype).itemsize
    per_iter = krylov_comm_model(
        spmv_comm, nd, itemsize, spmvs=contract["spmvs"],
        dots=contract["psums"],
        elems_per_dot=contract["elems_per_psum"])
    resources = {"comm": {
        "devices": nd,
        "per_spmv": spmv_comm,
        "per_iteration": per_iter}}
    # per-shard ledger + hardware provenance (telemetry/comm.py): the
    # distributed half of SolveReport.resources — per-shard rows/nnz/
    # halo and the load-imbalance factor, plus the ICI-vs-CPU-fallback
    # tag the gates key their platform-mismatch skip on
    extra = {"devices": nd}
    try:
        from amgcl_tpu.telemetry import comm as _comm
        dist_res = _comm.dist_resources(A, nd)
        if dist_res is not None:
            resources["dist"] = dist_res
        extra["provenance"] = _comm.hw_provenance(mesh)
    except Exception:
        pass                     # observability must never fail a solve
    report = SolveReport(
        int(it), float(res), wall_time_s=_time.perf_counter() - t0,
        solver="dist_cg_pipelined" if pipelined else "dist_cg",
        resources=resources, health=health,
        extra=extra)
    _tel_emit(report.to_dict(), event="dist_solve", n=int(A.shape[0]))
    out = _DistResult((x, int(it), float(res)))
    out.report = report
    return out


def dist_cg_pipelined(A: DistDiaMatrix, mesh, rhs, x0=None, dinv=None,
                      maxiter: int = 200, tol: float = 1e-6):
    """The merged-reduction pipelined CG, explicitly (see dist_cg)."""
    return dist_cg(A, mesh, rhs, x0=x0, dinv=dinv, maxiter=maxiter,
                   tol=tol, pipelined=True)
