"""Distributed Krylov solve: the serial CG body over shard-resident vectors,
with psum-globalized reductions — exactly the reference's recipe of reusing
the serial solver with a distributed InnerProduct
(amgcl/mpi/solver/cg.hpp:41-46).

The whole iteration (halo exchanges, local SpMVs, psum dots) is one
``shard_map``-ped ``lax.while_loop`` — a single XLA program per solve across
the mesh, compiled once per (mesh, matrix structure, solver params) and
cached for repeat solves.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from amgcl_tpu.parallel.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from amgcl_tpu.parallel.mesh import ROWS_AXIS
from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix, dist_inner_product


@lru_cache(maxsize=64)
def _compiled_dist_cg(mesh, offsets, shape, maxiter, tol):
    """jit-compiled distributed CG keyed on structure, not data."""
    from amgcl_tpu.telemetry import health as H
    A = DistDiaMatrix(offsets, None, shape)  # structure only; data is an arg

    def body_shard(data, f, x, di):
        dot = dist_inner_product
        spmv = partial(A.shard_mv, data)
        r = f - spmv(x)
        norm_rhs = jnp.sqrt(jnp.abs(dot(f, f)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = tol * scale

        def cond(st):
            x, r, p, rho_p, it, res, hs = st
            return (it < maxiter) & (res > eps) & H.keep_going(hs)

        def body(st):
            x, r, p, rho_p, it, res, hs = st
            s = di * r
            rho = dot(r, s)
            beta = jnp.where(rho_p == 0, 0.0, rho / rho_p)
            p_n = s + beta * p
            q = spmv(p_n)
            qp = dot(q, p_n)
            alpha = rho / jnp.where(qp == 0, 1.0, qp)
            x_n = x + alpha * p_n
            r_n = r - alpha * q
            res_n = jnp.sqrt(jnp.abs(dot(r_n, r_n)))
            # same guard set as the serial CG; every input is already
            # psum-reduced, so the trips (and the early exit they drive)
            # are bitwise identical on every shard
            ok, hs = H.step(
                hs, it, res_n / scale,
                ((H.BREAKDOWN_RHO, H.bad_denom(rho)),
                 (H.BREAKDOWN_ALPHA, H.bad_denom(qp)),
                 (H.INDEFINITE, jnp.real(qp) < 0, False)))
            x, r, p, rho, res = H.commit(
                ok, (x_n, r_n, p_n, rho, res_n), (x, r, p, rho_p, res))
            return (x, r, p, rho, it + ok.astype(jnp.int32), res, hs)

        res0 = jnp.sqrt(jnp.abs(dot(r, r)))
        st = (x, r, jnp.zeros_like(r), jnp.zeros((), f.dtype),
              jnp.zeros((), jnp.int32), res0, H.init_state(res0 / scale))
        x, r, p, rho, it, res, hs = lax.while_loop(cond, body, st)
        return x, it, res / scale, hs.flags, hs.first_it

    fn = shard_map(
        body_shard, mesh=mesh,
        in_specs=(P(None, ROWS_AXIS), P(ROWS_AXIS), P(ROWS_AXIS),
                  P(ROWS_AXIS)),
        out_specs=(P(ROWS_AXIS), P(), P(), P(), P()),
        check_vma=False)
    # observed jit (telemetry/compile_watch.py): a dist_cg that retraces
    # per solve — a drifting halo plan or maxiter/tol passed non-static —
    # shows up as a retrace finding instead of silent compile seconds
    from amgcl_tpu.telemetry.compile_watch import watched_jit
    return watched_jit(fn, name="parallel.dist_cg")


class _DistResult(tuple):
    """(x, iters, rel_resid) that additionally carries ``.report`` — the
    telemetry SolveReport built from the mesh-reduced scalars (the iters/
    residual out-specs are already psum-globalized and replicated)."""
    report = None


def dist_cg(A: DistDiaMatrix, mesh, rhs, x0=None, dinv=None,
            maxiter: int = 200, tol: float = 1e-6):
    """Jacobi-preconditioned distributed CG. ``dinv`` is the (sharded)
    inverted diagonal; identity preconditioning when None.

    Returns (x, iters, rel_resid) with x sharded over rows; the tuple's
    ``.report`` attribute holds the structured SolveReport and the record
    is emitted through the process-global telemetry sink."""
    import time as _time
    from amgcl_tpu.parallel.mesh import put_with_sharding
    from amgcl_tpu.telemetry import SolveReport, emit as _tel_emit
    t0 = _time.perf_counter()
    vec = NamedSharding(mesh, P(ROWS_AXIS))
    rhs = put_with_sharding(rhs, vec)
    x0 = jnp.zeros_like(rhs) if x0 is None else put_with_sharding(x0, vec)
    dinv = jnp.ones_like(rhs) if dinv is None else put_with_sharding(dinv,
                                                                     vec)
    fn = _compiled_dist_cg(mesh, A.offsets, A.shape, int(maxiter), float(tol))
    x, it, res, hflags, hfirst = fn(A.data, rhs, x0, dinv)
    from amgcl_tpu.telemetry.health import decode as _decode_health
    health = _decode_health(hflags, hfirst)
    nd = int(mesh.shape[ROWS_AXIS])
    # halo/psum wire model (telemetry/ledger.py): the Jacobi-CG body runs
    # one halo SpMV and three psum'd dots per iteration
    from amgcl_tpu.telemetry.ledger import comm_model, krylov_comm_model
    spmv_comm = comm_model(A, nd)
    resources = {"comm": {
        "devices": nd,
        "per_spmv": spmv_comm,
        "per_iteration": krylov_comm_model(
            spmv_comm, nd, jnp.dtype(rhs.dtype).itemsize,
            spmvs=1, dots=3)}}
    report = SolveReport(
        int(it), float(res), wall_time_s=_time.perf_counter() - t0,
        solver="dist_cg", resources=resources, health=health,
        extra={"devices": nd})
    _tel_emit(report.to_dict(), event="dist_solve", n=int(A.shape[0]))
    out = _DistResult((x, int(it), float(res)))
    out.report = report
    return out
