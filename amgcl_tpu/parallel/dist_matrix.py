"""Row-block distributed matrices and the halo-exchange SpMV.

TPU rendition of the reference's ``distributed_matrix`` (A split into a
local part and a remote part by column ownership, with an overlapped halo
exchange feeding the remote SpMV — amgcl/mpi/distributed_matrix.hpp:316-557).
On a TPU mesh the comm pattern is static at trace time: the host-side
partitioner computes which neighbor slices each shard needs, and the device
program exchanges them with ``lax.ppermute`` (ICI neighbor traffic), then
runs the local SpMV — XLA overlaps the permute with the local compute the
same way the reference overlaps Isend/Irecv with the local product.

Round-1 scope: banded matrices (DIA) whose halo is a fixed-width edge
exchange with the two ring neighbors. The general scattered-column ELL case
(arbitrary comm pattern via all_to_all) follows the same structure and is
layered on next.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops.device import csr_to_dia
from amgcl_tpu.parallel.compat import axis_size as _axis_size
from amgcl_tpu.parallel.mesh import ROWS_AXIS


@register_pytree_node_class
class DistDiaMatrix:
    """Banded matrix sharded by row blocks over the ``rows`` mesh axis.

    data: (ndiag, n) global diagonal storage, sharded on the row dimension;
    offsets static. ``halo`` = max |offset| = the edge width exchanged with
    ring neighbors each SpMV."""

    def __init__(self, offsets, data, shape):
        self.offsets = tuple(int(o) for o in offsets)
        self.data = data
        self.shape = (int(shape[0]), int(shape[1]))

    @property
    def halo(self) -> int:
        if not self.offsets:
            return 0
        return max(max(self.offsets), -min(self.offsets), 0)

    def halo_comm(self, nd: int):
        """Wire model of ONE halo-exchange SpMV over ``nd`` shards (the
        ledger hook, telemetry/ledger.comm_model): the ring exchange in
        dia_halo_mv moves the w-row edge slab in each direction between
        every adjacent pair — 2(nd−1) messages of w elements. The thin-
        slab all_gather fallbacks move more; this models the production
        regime (w ≤ shard size)."""
        nd = int(nd)
        w = self.halo
        if nd <= 1 or w == 0:
            return {"pattern": "ring", "msgs": 0, "bytes": 0}
        itemsize = np.dtype(self.data.dtype).itemsize \
            if self.data is not None else 4
        msgs = 2 * (nd - 1)
        return {"pattern": "ring", "msgs": msgs,
                "bytes": msgs * w * itemsize, "halo_width": w}

    def tree_flatten(self):
        return (self.data,), (self.offsets, self.shape)

    @classmethod
    def tree_unflatten(cls, aux, children):
        offsets, shape = aux
        return cls(offsets, children[0], shape)

    @classmethod
    def from_csr(cls, A: CSR, mesh, dtype=jnp.float32) -> "DistDiaMatrix":
        """Host CSR -> device-sharded DIA. Rows must divide the mesh size
        (pad upstream if needed)."""
        assert not A.is_block
        n = A.nrows
        nd = mesh.shape[ROWS_AXIS]
        assert n % nd == 0, "rows must divide the mesh for round-1 DIA"
        dia = csr_to_dia(A, dtype)      # single source of the DIA packing
        out = cls(dia.offsets, dia.data, A.shape)
        if out.halo > n // nd:
            raise ValueError(
                "halo width %d exceeds the shard size %d — the ring "
                "exchange only reaches immediate neighbors; use fewer "
                "devices or a narrower band" % (out.halo, n // nd))
        sharding = NamedSharding(mesh, P(None, ROWS_AXIS))
        # numpy in, sharded out: the direct per-device path, no reshard
        # compile, multi-controller-safe (see mesh.put_with_sharding)
        from amgcl_tpu.parallel.mesh import put_with_sharding
        out.data = put_with_sharding(np.asarray(out.data), sharding)
        return out

    # -- the per-shard kernel (runs inside shard_map) -----------------------

    def shard_mv(self, data_local, x_local):
        """Overlapped halo SpMV on one shard (see dia_halo_mv)."""
        return dia_halo_mv(data_local, self.offsets, x_local)


def _ring_exchange(x_l, w, nd):
    """The real edge exchange: one ppermute per direction between every
    adjacent shard pair — (prev_tail, next_head), each ``w`` elements."""
    fwd = [(i, i + 1) for i in range(nd - 1)]
    bwd = [(i + 1, i) for i in range(nd - 1)]
    return (lax.ppermute(x_l[-w:], ROWS_AXIS, fwd),
            lax.ppermute(x_l[:w], ROWS_AXIS, bwd))


def _local_exchange(x_l, w, nd):
    """Comm-ablated stand-in for :func:`_ring_exchange`
    (telemetry/comm.py): identical shapes, dtypes and downstream compute,
    ZERO collectives — timing the two variants of the same SpMV isolates
    the collective's wall share. Numerically wrong at the shard edges on
    purpose; never dispatched by a solve (the ablation audit pins its
    collective census to exactly 0)."""
    return x_l[:w], x_l[-w:]


def _gather_ring(x_l, nd):
    """Whole-vector gather of the thin-slab fallback path."""
    return lax.all_gather(x_l, ROWS_AXIS, tiled=True)


def _gather_local(x_l, nd):
    """Comm-ablated stand-in for :func:`_gather_ring`: same output shape
    from a local tile, zero collectives (see _local_exchange)."""
    return jnp.tile(x_l, nd)


def _maybe_stall_exchange():
    """Fault seam (faults/inject.py): a ``dist.delay`` rule stalls the
    halo-exchange SpMV by ``delay_ms`` — a slow-interconnect simulation
    for the serve/SLO layers. Fires at TRACE time (once per compiled
    exchange program), never as a host callback inside the device loop:
    the comm-stage census contracts (ledger.COMM_STAGE_CONTRACTS) and
    the host-sync lint forbid runtime callbacks at this seam. One env
    read when no plan is armed."""
    import os
    if not os.environ.get("AMGCL_TPU_FAULT_PLAN"):
        return
    try:
        from amgcl_tpu.faults import inject as _inject
        spec = _inject.should_fire("dist.delay", target="dia_halo")
        if spec is not None and spec.get("delay_ms", 0) > 0:
            import time
            time.sleep(float(spec["delay_ms"]) / 1e3)
    except Exception:
        pass


def dia_halo_mv(data_l, flat_offs, x_l, exchange=_ring_exchange,
                gather=_gather_ring):
    """y = A x on one shard with comm/compute overlap.

    The reference overlaps explicitly: start_exchange → local SpMV →
    finish_exchange → remote SpMV (amgcl/mpi/distributed_matrix.hpp:520-534).
    The XLA rendition makes the same split at the DATA-DEPENDENCE level:
    the interior product (all rows, zero-filled shifts — wrong only in the
    first/last ``w`` rows) reads ONLY x_local, so it shares no operands
    with the ppermute and XLA's async-collective scheduler can run it
    while the edge exchange is in flight; the exact edge rows (2w of them,
    a sliver) are recomputed from the halo and spliced in. A naive
    concat(halo, x, halo) formulation would make EVERY fused
    multiply-add a consumer of the collective and serialize the step
    (structure asserted by tests/test_distributed overlap-HLO test).

    ``exchange``/``gather`` are the collective seams: the defaults issue
    the real ppermute/all_gather; telemetry/comm.py passes the local
    same-shape stand-ins to measure the comm-ablated variant of exactly
    this program."""
    _maybe_stall_exchange()
    w = max(max(flat_offs), -min(flat_offs), 0) if flat_offs else 0
    nl = x_l.shape[0]
    acc_dt = jnp.result_type(data_l.dtype, x_l.dtype)
    if w == 0:
        return sum(data_l[k] * x_l for k in range(len(flat_offs))) \
            if flat_offs else jnp.zeros(nl, acc_dt)

    nd = _axis_size(ROWS_AXIS)
    if nd > 1 and w > nl:
        # Diagonal reach exceeds one neighbour slab: a single ring
        # exchange cannot supply the halo (x_l[-w:] would clamp to nl
        # elements and silently misalign every subsequent slice).  Only
        # reachable on very thin coarse slabs, so assembling the global
        # vector is cheap — gather it and slice at the shard's global
        # row offset.
        xg = gather(x_l, nd)
        base = lax.axis_index(ROWS_AXIS) * nl
        xe = jnp.pad(xg, (w, w))
        y = jnp.zeros(nl, dtype=acc_dt)
        for k, s in enumerate(flat_offs):
            y = y + data_l[k] * lax.dynamic_slice(xe, (w + base + s,),
                                                  (nl,))
        return y
    if nd == 1 or 2 * w >= nl:
        # degenerate split: plain haloed product (single shard, or shard
        # too thin for an interior region)
        if nd == 1:
            xe = jnp.pad(x_l, (w, w))
        else:
            prev_tail, next_head = exchange(x_l, w, nd)
            xe = jnp.concatenate([prev_tail, x_l, next_head])
        y = jnp.zeros(nl, dtype=acc_dt)
        for k, s in enumerate(flat_offs):
            y = y + data_l[k] * lax.dynamic_slice(xe, (w + s,), (nl,))
        return y

    prev_tail, next_head = exchange(x_l, w, nd)          # in flight ...

    # ... while the interior streams: zero-filled local shifts, valid for
    # rows [w, nl-w).  On TPU the interior takes the Pallas DIA kernel —
    # its semantics ARE the zero-filled shift product, and the pallas_call
    # consumes only x_l, so it still shares no operands with the ppermutes
    # and overlaps the exchange exactly like the XLA loop.
    from amgcl_tpu.ops.pallas_spmv import pallas_mode, dia_spmv
    ip = pallas_mode(data_l.dtype, x_l.dtype)
    if ip is not None:
        y0 = dia_spmv(flat_offs, data_l, x_l, interpret=ip)
    else:
        xp = jnp.pad(x_l, (w, w))
        y0 = jnp.zeros(nl, dtype=acc_dt)
        for k, s in enumerate(flat_offs):
            y0 = y0 + data_l[k] * lax.dynamic_slice(xp, (w + s,), (nl,))

    # exact edge rows from the received halo (2w rows, O(w·ndiag) work)
    xe = jnp.concatenate([prev_tail, x_l, next_head])
    lo = jnp.zeros(w, dtype=acc_dt)
    hi = jnp.zeros(w, dtype=acc_dt)
    for k, s in enumerate(flat_offs):
        lo = lo + data_l[k, :w] * lax.dynamic_slice(xe, (w + s,), (w,))
        hi = hi + data_l[k, nl - w:] * lax.dynamic_slice(
            xe, (nl + s,), (w,))
    return jnp.concatenate([lo, y0[w:nl - w], hi])


def dist_inner_product(x_local, y_local):
    """Local dot + psum over the rows axis — the distributed InnerProduct
    seam (reference: amgcl/mpi/inner_product.hpp:45-67)."""
    return lax.psum(jnp.vdot(x_local, y_local), ROWS_AXIS)


# the psum marker the fused tiers key on (ops/device.spmv_dots,
# ops/fused_vec): "this seam is local-vdot + psum over THIS axis", so a
# fused kernel may compute the shard-local partial and globalize all its
# dots in one stacked collective instead of composing through the seam
dist_inner_product.psum_axis = ROWS_AXIS
