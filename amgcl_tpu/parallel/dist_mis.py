"""Mesh-sharded distance-2 MIS aggregation.

The reference's distributed PMIS coarsening is 1131 lines of rank-boundary
ownership resolution with dynamic messaging
(amgcl/mpi/coarsening/pmis.hpp:49-1131). On a TPU mesh the same algorithm
is data-parallel max-plus propagation: each round's root election and
distance-1/2 captures are masked row-max gathers over the strength
adjacency, and the ONLY communication is the same static halo exchange the
SpMV uses (one ``all_to_all`` per gather). Ownership resolution is free:
priorities are globally unique, so every shard deterministically agrees on
the winner of every boundary contest — no handshake, no retries.

``sharded_aggregates(A, eps, mesh)`` is a drop-in for
``plain_aggregates``: the per-entry strength filter runs on the host
(embarrassingly parallel, same cost class as one matrix pass), the MIS
rounds — the iterative, communication-heavy part that pmis.hpp spends its
complexity on — run jitted on the mesh, and the aggregate keys come back
for the host to compress and feed the tentative prolongation.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from amgcl_tpu.parallel.compat import shard_map
from jax.sharding import PartitionSpec as P

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.parallel.mesh import ROWS_AXIS, put_sharded
from amgcl_tpu.parallel.dist_ell import DistEllMatrix, build_dist_ell


def _gather_all(dS: DistEllMatrix, x_local):
    """Neighbor values of every local row over the halo plan:
    (nloc, K1 + K2) — local columns first, then halo columns."""
    send = jnp.take(x_local, dS.send_idx[0], axis=0)
    halo = lax.all_to_all(send, ROWS_AXIS, 0, 0, tiled=False).reshape(-1)
    gl = jnp.take(x_local, dS.loc_cols[0], axis=0)
    gr = jnp.take(halo, dS.rem_cols[0], axis=0)
    return jnp.concatenate([gl, gr], axis=1)


def _mis_shard_body(dS: DistEllMatrix, prio, rounds: int):
    """Runs inside shard_map. prio: (1, nloc) unique positive int32 per
    global row (0 on padding rows). Returns per-shard aggregate keys."""
    prio = prio[0]
    valid = jnp.concatenate(
        [dS.loc_vals[0] > 0, dS.rem_vals[0] > 0], axis=1)

    def row_max(x):
        return jnp.max(jnp.where(valid, _gather_all(dS, x), 0), axis=1)

    has_nbr = jnp.any(valid, axis=1)

    def cond(carry):
        key, und, r = carry
        # one scalar psum per round stops at convergence (typically ~5-10
        # rounds on stencil graphs) instead of burning the full cap's
        # collectives on an all-decided mask
        return (r < rounds) & (lax.psum(und.sum(), ROWS_AXIS) > 0)

    def body(carry):
        key, und, r = carry
        p_und = jnp.where(und, prio, 0)
        # closed 2-hop max of undecided priorities: a node wins exactly
        # when it holds the maximum of its distance-2 neighborhood
        m1 = row_max(p_und)
        m2 = jnp.maximum(row_max(jnp.maximum(m1, p_und)), m1)
        winners = und & (prio >= m2)
        key = jnp.where(winners, prio, key)
        # distance-1 capture: adopt the best adjacent new root
        pw = jnp.where(winners, prio, 0)
        w1 = row_max(pw)
        d1 = und & ~winners & (w1 > 0)
        key = jnp.where(d1, w1, key)
        # distance-2 capture: adopt the key of the best captured neighbor
        cap = winners | d1
        kcap = jnp.where(cap, key, 0)
        pcap = jnp.where(cap, prio, 0)
        best_p = row_max(pcap)
        pg = jnp.where(valid, _gather_all(dS, pcap), 0)
        kg = jnp.where(valid, _gather_all(dS, kcap), 0)
        hit = (pg > 0) & (pg == best_p[:, None])
        k2 = jnp.max(jnp.where(hit, kg, 0), axis=1)
        d2 = und & ~cap & (best_p > 0)
        key = jnp.where(d2, k2, key)
        und = und & ~(winners | d1 | d2)
        return (key, und, r + 1)

    key0 = jnp.zeros_like(prio)
    key, und, _ = lax.while_loop(cond, body, (key0, has_nbr, 0))
    # pathological leftovers become their own roots
    key = jnp.where(und, prio, key)
    return key


@lru_cache(maxsize=32)
def _compiled_mis(mesh, shape, nloc, ncloc, rounds):
    s = P(ROWS_AXIS, None, None)
    dS_spec = DistEllMatrix(s, s, s, s, s, shape, nloc, ncloc)

    def run(dS, prio):
        return _mis_shard_body(dS, prio, rounds)

    fn = shard_map(run, mesh=mesh, in_specs=(dS_spec, P(ROWS_AXIS, None)),
                   out_specs=P(ROWS_AXIS), check_vma=False)
    # observed jit (telemetry/compile_watch.py): runs once per strip
    # setup, but the lru_cache above makes it a process-lived entry
    # point — keep its compiles attributable
    from amgcl_tpu.telemetry.compile_watch import watched_jit
    return watched_jit(fn, name="parallel.dist_mis")


def sharded_aggregates(A: CSR, eps_strong: float, mesh, rounds: int = 40):
    """Drop-in for ``plain_aggregates`` running the MIS rounds on the mesh.
    Returns (agg, n_agg) in the host convention (-1 for isolated rows)."""
    from amgcl_tpu.coarsening.aggregates import strength_graph, _priority

    S = strength_graph(A, eps_strong)
    n = S.shape[0]
    Sc = CSR(S.indptr.astype(np.int64), S.indices.astype(np.int32),
             np.ones(S.nnz), n)
    dS = build_dist_ell(Sc, mesh, jnp.float32)
    nd = mesh.shape[ROWS_AXIS]
    n_pad = dS.nloc * nd
    prio = np.zeros(n_pad, dtype=np.int32)
    prio[:n] = _priority(n).astype(np.int32)
    prio_sh = put_sharded(prio.reshape(nd, dS.nloc), mesh, jnp.int32)
    fn = _compiled_mis(mesh, dS.shape, dS.nloc, dS.ncloc, int(rounds))
    key = np.asarray(fn(dS, prio_sh))[:n]
    agg = np.full(n, -1, dtype=np.int64)
    live = key > 0
    uniq, inv = np.unique(key[live], return_inverse=True)
    agg[live] = inv
    return agg, len(uniq)


def make_mesh_aggregator(mesh, rounds: int = 40):
    """An ``aggregator`` hook for the coarsening policies: aggregation runs
    sharded on this mesh (used by DistAMGSolver(device_mis=True))."""
    return lambda A, eps: sharded_aggregates(A, eps, mesh, rounds)
