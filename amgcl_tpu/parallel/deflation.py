"""Distributed subdomain deflation — two-level deflated Krylov.

The reference's scalable flagship (amgcl/mpi/subdomain_deflation.hpp:53-610,
Frank–Vuik): a coarse space Z of per-subdomain vectors (constant by default,
linear with coordinates, or user-supplied), E = ZᵀAZ assembled and
factorized on the master ranks, and the projection applied around the
preconditioned operator.

TPU rendition: Z and AZ are dense (n, k) panels sharded by rows (per-shard
tall-skinny matmuls — MXU food), E⁻¹ is tiny and replicated, and the coarse
reduction ZᵀR is a local (k,) partial followed by one psum. The deflated
preconditioner is A-DEF2: M r = P(r − AZ w) + Z w with w = E⁻¹ Zᵀ r —
wrapped around the distributed AMG hierarchy so the whole thing stays one
SPMD program.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.parallel.mesh import ROWS_AXIS
from amgcl_tpu.parallel.dist_amg import DistAMGSolver, DistHierarchy


@register_pytree_node_class
class DeflatedDistHierarchy:
    """base hierarchy + deflation panels; shard_apply runs inside shard_map.

    Z, AZ: (nd, nloc, k) sharded; Einv: (k, k) replicated."""

    def __init__(self, base, Z, AZ, Einv):
        self.base = base
        self.Z = Z
        self.AZ = AZ
        self.Einv = Einv

    def tree_flatten(self):
        return (self.base, self.Z, self.AZ, self.Einv), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def specs(self):
        s = P(ROWS_AXIS, None, None)
        return DeflatedDistHierarchy(self.base.specs(), s, s, P())

    def system_A(self):
        return self.base.system_A()

    def shard_apply(self, r):
        Z = self.Z[0]            # (nloc, k)
        AZ = self.AZ[0]
        w = self.Einv @ lax.psum(Z.T @ r, ROWS_AXIS)     # (k,)
        z = self.base.shard_apply(r - AZ @ w)
        return z + Z @ w


def constant_deflation(n: int, nd: int) -> np.ndarray:
    """One indicator vector per subdomain (reference: constant_deflation)."""
    nloc = -(-n // nd)
    Z = np.zeros((nloc * nd, nd))
    for d in range(nd):
        Z[d * nloc:min((d + 1) * nloc, n), d] = 1.0
    return Z[:n]


def linear_deflation(coords: np.ndarray, nd: int) -> np.ndarray:
    """[1, x, y, ...] per subdomain from point coordinates (reference:
    linear_deflation)."""
    n, dim = coords.shape
    nloc = -(-n // nd)
    k = dim + 1
    Z = np.zeros((n, nd * k))
    for d in range(nd):
        lo, hi = d * nloc, min((d + 1) * nloc, n)
        if hi <= lo:
            continue
        Z[lo:hi, d * k] = 1.0
        c = coords[lo:hi]
        c = c - c.mean(axis=0, keepdims=True)
        Z[lo:hi, d * k + 1:d * k + 1 + dim] = c
    return Z


class DistDeflatedSolver(DistAMGSolver):
    """Subdomain-deflated distributed AMG-Krylov. ``deflation`` is
    'constant', or an explicit (n, k) matrix of deflation vectors."""

    def __init__(self, A, mesh, prm: Optional[AMGParams] = None,
                 solver: Any = None, deflation="constant"):
        super().__init__(A, mesh, prm, solver)
        A = self.host_amg.host_levels[0][0]
        n = self.n
        nd = mesh.shape[ROWS_AXIS]
        nloc = self.n_pad // nd
        if isinstance(deflation, str):
            if deflation != "constant":
                raise ValueError("deflation must be 'constant' or a matrix")
            Z = constant_deflation(n, nd)
        else:
            Z = np.asarray(deflation, dtype=np.float64)
            if Z.ndim == 1:
                Z = Z[:, None]
        k = Z.shape[1]
        AZ = np.stack([A.spmv(Z[:, j]) for j in range(k)], axis=1)
        E = Z.T @ AZ
        Einv = np.linalg.pinv(E)

        dtype = self.prm.dtype
        from amgcl_tpu.parallel.mesh import put_sharded

        def panel(M):
            pad = np.zeros((self.n_pad, k))
            pad[:n] = M
            return put_sharded(pad.reshape(nd, nloc, k), mesh, dtype)

        self.hier = DeflatedDistHierarchy(
            self.hier, panel(Z), panel(AZ),
            jnp.asarray(Einv, dtype=dtype))
        self._compiled = None

    def __repr__(self):
        return "DistDeflatedSolver(k=%d)\n%r" % (
            self.hier.Einv.shape[0], self.host_amg)
