"""Distributed (mesh-sharded) layer.

The TPU-native equivalent of the reference's MPI layer (amgcl/mpi/):
row-block domain decomposition over a ``jax.sharding.Mesh``, halo exchange
via ``lax.ppermute``/gathers instead of Isend/Irecv, and ``lax.psum`` inner
products instead of MPI_Allreduce (reference:
amgcl/mpi/distributed_matrix.hpp:316-557, amgcl/mpi/inner_product.hpp:45-67).
"""

from amgcl_tpu.parallel.mesh import make_mesh, ROWS_AXIS

__all__ = ["make_mesh", "ROWS_AXIS"]
