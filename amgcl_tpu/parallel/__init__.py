"""Distributed (mesh-sharded) layer.

The TPU-native equivalent of the reference's MPI layer (amgcl/mpi/):
row-block domain decomposition over a ``jax.sharding.Mesh``, halo exchange
via ``lax.all_to_all``/``ppermute`` instead of Isend/Irecv, and ``lax.psum``
inner products instead of MPI_Allreduce (reference:
amgcl/mpi/distributed_matrix.hpp:316-557, amgcl/mpi/inner_product.hpp:45-67).
"""

from amgcl_tpu.parallel.mesh import make_mesh, ROWS_AXIS
from amgcl_tpu.parallel.dist_ell import DistEllMatrix, build_dist_ell
from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix, dist_inner_product
from amgcl_tpu.parallel.dist_stencil import (DistStencilSolver,
                                             dist_stencil_build)
from amgcl_tpu.parallel.dist_solver import dist_cg, dist_cg_pipelined
from amgcl_tpu.parallel.dist_amg import DistAMGSolver
from amgcl_tpu.parallel.deflation import DistDeflatedSolver
from amgcl_tpu.parallel.block_precond import DistBlockPreconditioner
from amgcl_tpu.parallel.dist_cpr import DistCPRSolver
from amgcl_tpu.parallel.dist_schur import DistSchurSolver

__all__ = ["make_mesh", "ROWS_AXIS", "DistEllMatrix", "build_dist_ell",
           "DistDiaMatrix", "dist_inner_product", "dist_cg",
           "dist_cg_pipelined", "DistAMGSolver",
           "DistDeflatedSolver", "DistBlockPreconditioner", "DistCPRSolver",
           "DistSchurSolver", "DistStencilSolver", "dist_stencil_build"]
