"""shard_map import/kwarg compatibility across jax versions.

Newer jax exposes ``jax.shard_map`` with a ``check_vma`` flag; older
releases (<= 0.4.x) only have ``jax.experimental.shard_map.shard_map``
whose equivalent flag is ``check_rep``. The distributed layer is written
against the new surface; this shim maps it onto whichever one exists so
``from amgcl_tpu.parallel.compat import shard_map`` works everywhere.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map          # jax >= 0.5
    _FLAG = "check_vma"
except ImportError:                                  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _FLAG = "check_rep"


def shard_map(f, **kw):
    for a, b in (("check_vma", "check_rep"), ("check_rep", "check_vma")):
        if a in kw and _FLAG == b:
            kw[b] = kw.pop(a)
    return _shard_map(f, **kw)


def axis_size(name) -> int:
    """Static size of a named mesh axis from inside shard_map —
    ``jax.lax.axis_size`` on new jax, ``jax.core.axis_frame`` (which
    returns the int directly) on 0.4.x."""
    from jax import lax as _lax
    if hasattr(_lax, "axis_size"):
        return _lax.axis_size(name)
    import jax.core as _core
    return int(_core.axis_frame(name))
