"""General distributed sparse matrix: row-block partition + static halo plan.

The TPU-native rendition of the reference's ``comm_pattern`` +
``distributed_matrix`` (amgcl/mpi/distributed_matrix.hpp:50-557): the
one-time handshake that discovers which remote values each rank needs
becomes a host-side plan built at setup; the per-iteration Isend/Irecv
exchange becomes one ``lax.all_to_all`` over the mesh axis; and the
local/remote SpMV split is preserved so XLA can overlap the collective with
the local product (the reference's start_exchange → local spmv →
finish_exchange → remote spmv, amgcl/mpi/distributed_matrix.hpp:520-534).

Everything is static at trace time: the plan is baked into padded index
arrays, so the whole solve compiles to one SPMD program.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.parallel.mesh import ROWS_AXIS


@register_pytree_node_class
class DistEllMatrix:
    """Row-block sharded matrix with a static halo plan.

    Arrays carry a leading shard dimension sharded over the ``rows`` axis:
      loc_cols/loc_vals: (nd, nloc, K1) — column indices local to the shard
      rem_cols/rem_vals: (nd, nloc, K2) — column indices into the halo buffer
      send_idx:          (nd, nd, C)    — per-destination local indices
    Inside ``shard_map`` each shard sees the leading dim as 1.
    """

    def __init__(self, loc_cols, loc_vals, rem_cols, rem_vals, send_idx,
                 shape, nloc, ncloc):
        self.loc_cols = loc_cols
        self.loc_vals = loc_vals
        self.rem_cols = rem_cols
        self.rem_vals = rem_vals
        self.send_idx = send_idx
        self.shape = (int(shape[0]), int(shape[1]))   # padded global shape
        self.nloc = int(nloc)      # owned rows per shard
        self.ncloc = int(ncloc)    # owned columns per shard (input partition)

    def tree_flatten(self):
        return ((self.loc_cols, self.loc_vals, self.rem_cols, self.rem_vals,
                 self.send_idx),
                (self.shape, self.nloc, self.ncloc))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def specs(self):
        """PartitionSpec pytree matching tree structure (leading dim is the
        shard axis)."""
        s = P(ROWS_AXIS, None, None)
        return DistEllMatrix(s, s, s, s, P(ROWS_AXIS, None, None),
                             self.shape, self.nloc, self.ncloc)

    # -- device kernel (inside shard_map) ----------------------------------

    def shard_mv(self, x_local):
        """Overlapped halo SpMV for the shard-local slice of the pytree
        (leading dims == 1). x_local: (ncloc,) owned input values."""
        send = jnp.take(x_local, self.send_idx[0], axis=0)   # (nd, C)
        recv = lax.all_to_all(send, ROWS_AXIS, 0, 0, tiled=False)
        halo = recv.reshape(-1)
        y_loc = _ell_mv(self.loc_cols[0], self.loc_vals[0], x_local)
        y_rem = _ell_mv(self.rem_cols[0], self.rem_vals[0], halo)
        return y_loc + y_rem


def _ell_mv(cols, vals, x):
    return jnp.einsum("nk,nk->n", vals, jnp.take(x, cols, axis=0),
                      preferred_element_type=jnp.result_type(vals.dtype,
                                                             x.dtype))


def pack_rows_ell(rr, cc, vv, nrows, K):
    """Pack (row, col, val) triples into dense (nrows, K) ELL arrays —
    the shared per-shard packing used by the halo plan and the
    sharded/replicated transition operators. The value plane keeps the
    input's dtype (complex stays complex)."""
    vv = np.asarray(vv)
    cols = np.zeros((nrows, K), dtype=np.int32)
    vals = np.zeros((nrows, K), dtype=np.result_type(vv.dtype, np.float64))
    if len(rr):
        order = np.argsort(rr, kind="stable")
        rr, cc, vv = rr[order], cc[order], vv[order]
        pos = np.arange(len(rr)) - np.concatenate(
            [[0], np.cumsum(np.bincount(rr, minlength=nrows))[:-1]])[rr]
        cols[rr, pos] = cc
        vals[rr, pos] = vv
    return cols, vals


def build_dist_ell(A: CSR, mesh, dtype=jnp.float32, nloc=None,
                   ncloc=None) -> DistEllMatrix:
    """Partition a host CSR over the mesh's ``rows`` axis and bake the halo
    plan. Rectangular operators (transfers) partition rows and columns
    independently into equal blocks, so P/R between two sharded levels just
    work.

    ``nloc``/``ncloc`` override the per-shard row/column block size (the
    default spreads evenly over all devices). A larger block concentrates
    a small operator on the FIRST few shards, trailing shards holding only
    padding — the TPU-mesh analogue of the reference's repartition-merge
    shrink for mid-size levels (amgcl/mpi/partition/merge.hpp:47-137):
    fewer boundary pairs and bigger per-shard blocks, while every device
    still participates in the (now thinner) collectives."""
    assert not A.is_block, "distribute the unblocked matrix"
    nd = mesh.shape[ROWS_AXIS]
    n, m = A.shape
    nloc = -(-n // nd) if nloc is None else int(nloc)
    ncloc = -(-m // nd) if ncloc is None else int(ncloc)
    if nloc * nd < n or ncloc * nd < m:
        raise ValueError(
            "partition override too small: %d rows/shard x %d shards < %d "
            "rows (or %d cols/shard < %d cols) — rows would be dropped"
            % (nloc, nd, n, ncloc, m))

    rows = np.repeat(np.arange(n), A.row_nnz())
    owner = np.minimum(A.col // ncloc, nd - 1).astype(np.int64)
    row_shard = np.minimum(rows // nloc, nd - 1).astype(np.int64)
    is_local = owner == row_shard

    # halo needs: for each (dst, src) pair the sorted unique global columns.
    # One lexsort/group-by over the remote entries only — O(nnz_rem log),
    # independent of the device count.
    rem = np.flatnonzero(~is_local)
    key_dst = row_shard[rem]
    key_src = owner[rem]
    key_col = A.col[rem].astype(np.int64)
    # single source of the composite key: trip derives from rem_keys, and
    # the same array drives the searchsorted position lookup below
    rem_keys = (key_dst * nd + key_src) * (ncloc * nd) + key_col
    trip = np.unique(rem_keys)
    t_pair = trip // (ncloc * nd)
    t_dst = t_pair // nd
    t_src = t_pair % nd
    t_col = trip % (ncloc * nd)
    # rank within each (dst, src) group (columns are sorted inside groups)
    grp_start = np.concatenate(
        [[True], t_pair[1:] != t_pair[:-1]]) if len(trip) else \
        np.zeros(0, bool)
    grp_idx = np.arange(len(trip)) - np.maximum.accumulate(
        np.where(grp_start, np.arange(len(trip)), 0)) if len(trip) else \
        np.zeros(0, np.int64)
    C = int(grp_idx.max()) + 1 if len(trip) else 1

    send_idx = np.zeros((nd, nd, C), dtype=np.int32)
    send_idx[t_src, t_dst, grp_idx] = (t_col - t_src * ncloc).astype(np.int32)

    # remote entry -> halo buffer position (buffer = concat over src of C
    # padded slots): one searchsorted maps every entry at once.
    loc_in_trip = np.searchsorted(trip, rem_keys)
    halo_pos_full = np.zeros(A.nnz, dtype=np.int32)
    halo_pos_full[rem] = (t_src[loc_in_trip] * C
                          + grp_idx[loc_in_trip]).astype(np.int32)

    # per-shard ELL packing
    K1 = 1
    K2 = 1
    loc_lists = []
    rem_lists = []
    for s in range(nd):
        # clamp: trailing shards may lie entirely in the padded range
        r0, r1 = min(s * nloc, n), min((s + 1) * nloc, n)
        lo, hi = int(A.ptr[r0]), int(A.ptr[r1])
        rr = rows[lo:hi] - r0
        cc = A.col[lo:hi]
        vv = A.val[lo:hi]
        lm = is_local[lo:hi]
        loc_lists.append((rr[lm], cc[lm] - s * ncloc, vv[lm]))
        rem_lists.append((rr[~lm], halo_pos_full[lo:hi][~lm], vv[~lm]))
        if len(rr[lm]):
            K1 = max(K1, int(np.bincount(rr[lm]).max()))
        if len(rr[~lm]):
            K2 = max(K2, int(np.bincount(rr[~lm]).max()))

    def pack(lists, K):
        cols = np.zeros((nd, nloc, K), dtype=np.int32)
        vals = np.zeros((nd, nloc, K),
                        dtype=np.result_type(A.val.dtype, np.float64))
        for s, (rr, cc, vv) in enumerate(lists):
            cols[s], vals[s] = pack_rows_ell(rr, cc, vv, nloc, K)
        return cols, vals

    lc, lv = pack(loc_lists, K1)
    rc, rv = pack(rem_lists, K2)

    from amgcl_tpu.parallel.mesh import put_sharded
    put = lambda a, dt: put_sharded(a, mesh, dt)
    return DistEllMatrix(
        put(lc, jnp.int32), put(lv, dtype), put(rc, jnp.int32),
        put(rv, dtype), put(send_idx, jnp.int32),
        (nloc * nd, ncloc * nd), nloc, ncloc)
