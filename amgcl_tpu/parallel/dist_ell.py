"""General distributed sparse matrix: row-block partition + static halo plan.

The TPU-native rendition of the reference's ``comm_pattern`` +
``distributed_matrix`` (amgcl/mpi/distributed_matrix.hpp:50-557): the
one-time handshake that discovers which remote values each rank needs
becomes a host-side plan built at setup; the per-iteration Isend/Irecv
exchange becomes one ``lax.all_to_all`` over the mesh axis; and the
local/remote SpMV split is preserved so XLA can overlap the collective with
the local product (the reference's start_exchange → local spmv →
finish_exchange → remote spmv, amgcl/mpi/distributed_matrix.hpp:520-534).

Everything is static at trace time: the plan is baked into padded index
arrays, so the whole solve compiles to one SPMD program.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.parallel.mesh import ROWS_AXIS


@register_pytree_node_class
class DistEllMatrix:
    """Row-block sharded matrix with a static halo plan.

    Arrays carry a leading shard dimension sharded over the ``rows`` axis:
      loc_cols/loc_vals: (nd, nloc, K1) — column indices local to the shard
      rem_cols/rem_vals: (nd, nloc, K2) — column indices into the halo buffer
      send_idx:          (nd, nd, C)    — per-destination local indices
    Inside ``shard_map`` each shard sees the leading dim as 1.
    """

    def __init__(self, loc_cols, loc_vals, rem_cols, rem_vals, send_idx,
                 shape, nloc, ncloc):
        self.loc_cols = loc_cols
        self.loc_vals = loc_vals
        self.rem_cols = rem_cols
        self.rem_vals = rem_vals
        self.send_idx = send_idx
        self.shape = (int(shape[0]), int(shape[1]))   # padded global shape
        self.nloc = int(nloc)      # owned rows per shard
        self.ncloc = int(ncloc)    # owned columns per shard (input partition)

    def tree_flatten(self):
        return ((self.loc_cols, self.loc_vals, self.rem_cols, self.rem_vals,
                 self.send_idx),
                (self.shape, self.nloc, self.ncloc))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def specs(self):
        """PartitionSpec pytree matching tree structure (leading dim is the
        shard axis)."""
        s = P(ROWS_AXIS, None, None)
        return DistEllMatrix(s, s, s, s, P(ROWS_AXIS, None, None),
                             self.shape, self.nloc, self.ncloc)

    def halo_comm(self, nd: int):
        """Wire model of ONE halo-exchange SpMV (the ledger hook,
        telemetry/ledger.comm_model): the all_to_all moves each shard's
        C-slot slab to every other shard — nd(nd−1) wire messages of C
        values (the self-slab never leaves the chip). C is the static
        padded slab width from the halo plan, so this is the scheduled
        volume, an upper bound on the useful halo values."""
        nd = int(nd)
        if nd <= 1 or self.send_idx is None:
            return {"pattern": "all_to_all", "msgs": 0, "bytes": 0}
        C = int(self.send_idx.shape[-1])
        itemsize = np.dtype(self.loc_vals.dtype).itemsize \
            if self.loc_vals is not None else 4
        msgs = nd * (nd - 1)
        return {"pattern": "all_to_all", "msgs": msgs,
                "bytes": msgs * C * itemsize, "slab_width": C}

    # -- device kernel (inside shard_map) ----------------------------------

    def shard_mv(self, x_local, exchange=None):
        """Overlapped halo SpMV for the shard-local slice of the pytree
        (leading dims == 1). x_local: (ncloc,) owned input values.

        ``exchange`` overrides the all_to_all seam — telemetry/comm.py
        passes an identity stand-in (same (nd, C) shape, zero
        collectives) to measure the comm-ablated variant of exactly this
        program; the default issues the real collective."""
        send = jnp.take(x_local, self.send_idx[0], axis=0)   # (nd, C)
        if exchange is None:
            recv = lax.all_to_all(send, ROWS_AXIS, 0, 0, tiled=False)
        else:
            recv = exchange(send)
        halo = recv.reshape(-1)
        y_loc = _ell_mv(self.loc_cols[0], self.loc_vals[0], x_local)
        y_rem = _ell_mv(self.rem_cols[0], self.rem_vals[0], halo)
        return y_loc + y_rem


def _ell_mv(cols, vals, x):
    return jnp.einsum("nk,nk->n", vals, jnp.take(x, cols, axis=0),
                      preferred_element_type=jnp.result_type(vals.dtype,
                                                             x.dtype))


def pack_rows_ell(rr, cc, vv, nrows, K):
    """Pack (row, col, val) triples into dense (nrows, K) ELL arrays —
    the shared per-shard packing used by the halo plan and the
    sharded/replicated transition operators. The value plane keeps the
    input's dtype (complex stays complex)."""
    vv = np.asarray(vv)
    cols = np.zeros((nrows, K), dtype=np.int32)
    vals = np.zeros((nrows, K), dtype=np.result_type(vv.dtype, np.float64))
    if len(rr):
        order = np.argsort(rr, kind="stable")
        rr, cc, vv = rr[order], cc[order], vv[order]
        pos = np.arange(len(rr)) - np.concatenate(
            [[0], np.cumsum(np.bincount(rr, minlength=nrows))[:-1]])[rr]
        cols[rr, pos] = cc
        vals[rr, pos] = vv
    return cols, vals


def build_dist_ell(A: CSR, mesh, dtype=jnp.float32, nloc=None,
                   ncloc=None) -> DistEllMatrix:
    """Partition a host CSR over the mesh's ``rows`` axis and bake the halo
    plan. Rectangular operators (transfers) partition rows and columns
    independently into equal blocks, so P/R between two sharded levels just
    work.

    ``nloc``/``ncloc`` override the per-shard row/column block size (the
    default spreads evenly over all devices). A larger block concentrates
    a small operator on the FIRST few shards, trailing shards holding only
    padding — the TPU-mesh analogue of the reference's repartition-merge
    shrink for mid-size levels (amgcl/mpi/partition/merge.hpp:47-137):
    fewer boundary pairs and bigger per-shard blocks, while every device
    still participates in the (now thinner) collectives."""
    assert not A.is_block, "distribute the unblocked matrix"
    nd = mesh.shape[ROWS_AXIS]
    n, m = A.shape
    nloc = -(-n // nd) if nloc is None else int(nloc)
    ncloc = -(-m // nd) if ncloc is None else int(ncloc)
    if nloc * nd < n or ncloc * nd < m:
        raise ValueError(
            "partition override too small: %d rows/shard x %d shards < %d "
            "rows (or %d cols/shard < %d cols) — rows would be dropped"
            % (nloc, nd, n, ncloc, m))
    rows = np.repeat(np.arange(n), A.row_nnz())
    triples = []
    for s in range(nd):
        # clamp: trailing shards may lie entirely in the padded range
        r0, r1 = min(s * nloc, n), min((s + 1) * nloc, n)
        lo, hi = int(A.ptr[r0]), int(A.ptr[r1])
        triples.append((rows[lo:hi] - r0, A.col[lo:hi], A.val[lo:hi]))
    return build_dist_ell_strips(triples, mesh, (n, m), dtype, nloc, ncloc)


def build_dist_ell_strips(triples, mesh, shape, dtype=jnp.float32,
                          nloc=None, ncloc=None,
                          comm=None) -> DistEllMatrix:
    """Same plan + packing as :func:`build_dist_ell`, but consuming
    per-shard (rows_rel, cols_global, vals) triples directly — the
    strip-parallel setup path (parallel/dist_setup.py) never assembles a
    global CSR, so host peak memory stays one strip + its halo.

    ``comm`` (a dist_setup comm object) makes the halo-plan union global
    under multi-controller: entries for non-owned shards may be None, the
    boundary keys are allgathered (they are O(surface), not O(nnz)), and
    every process derives the identical plan."""
    nd = mesh.shape[ROWS_AXIS]
    n, m = shape
    nloc = -(-n // nd) if nloc is None else int(nloc)
    ncloc = -(-m // nd) if ncloc is None else int(ncloc)
    my_shards = list(range(nd)) if comm is None else list(comm.my_shards)

    # halo needs: for each (dst, src) pair the sorted unique global columns.
    # Work is O(nnz_rem log) over BOUNDARY entries only.
    rem_keys_per = [None] * nd
    splits = [None] * nd
    K1 = 1
    K2 = 1
    for s in my_shards:
        rr, cc, vv = triples[s]
        owner = np.minimum(np.asarray(cc) // ncloc, nd - 1).astype(np.int64)
        lm = owner == s
        rem = ~lm
        keys = ((np.int64(s) * nd + owner[rem]) * (ncloc * nd)
                + np.asarray(cc)[rem].astype(np.int64))
        rem_keys_per[s] = keys
        splits[s] = lm
        rl = np.asarray(rr)[lm]
        if len(rl):
            K1 = max(K1, int(np.bincount(rl).max()))
        rm_ = np.asarray(rr)[rem]
        if len(rm_):
            K2 = max(K2, int(np.bincount(rm_).max()))

    if comm is not None and len(my_shards) != nd:
        all_keys = comm.allgather_concat(rem_keys_per)
        K1 = int(comm.max_scalar([K1]))
        K2 = int(comm.max_scalar([K2]))
    else:
        all_keys = np.concatenate(rem_keys_per) if rem_keys_per else \
            np.zeros(0, np.int64)
    trip = np.unique(all_keys)
    t_pair = trip // (ncloc * nd)
    t_dst = t_pair // nd
    t_src = t_pair % nd
    t_col = trip % (ncloc * nd)
    # rank within each (dst, src) group (columns are sorted inside groups)
    grp_start = np.concatenate(
        [[True], t_pair[1:] != t_pair[:-1]]) if len(trip) else \
        np.zeros(0, bool)
    grp_idx = np.arange(len(trip)) - np.maximum.accumulate(
        np.where(grp_start, np.arange(len(trip)), 0)) if len(trip) else \
        np.zeros(0, np.int64)
    C = int(grp_idx.max()) + 1 if len(trip) else 1

    send_idx = np.zeros((nd, nd, C), dtype=np.int32)
    send_idx[t_src, t_dst, grp_idx] = (t_col - t_src * ncloc).astype(np.int32)

    # per-shard ELL packing; placement is per-part (no global host array)
    val_dt = np.result_type(
        *([np.asarray(triples[s][2]).dtype for s in my_shards]
          + [np.float64]))
    lcs = [None] * nd
    lvs = [None] * nd
    rcs = [None] * nd
    rvs = [None] * nd
    for s in my_shards:
        rr, cc, vv = triples[s]
        rr = np.asarray(rr)
        cc = np.asarray(cc)
        vv = np.asarray(vv)
        lm = splits[s]
        rem = ~lm
        c1, v1 = pack_rows_ell(rr[lm], cc[lm] - s * ncloc, vv[lm],
                               nloc, K1)
        # remote entry -> halo buffer position (buffer = concat over src of
        # C padded slots)
        loc_in_trip = np.searchsorted(trip, rem_keys_per[s])
        halo_pos = (t_src[loc_in_trip] * C + grp_idx[loc_in_trip]) \
            .astype(np.int32)
        c2, v2 = pack_rows_ell(rr[rem], halo_pos, vv[rem], nloc, K2)
        lcs[s] = c1
        lvs[s] = v1.astype(val_dt)
        rcs[s] = c2
        rvs[s] = v2.astype(val_dt)

    from amgcl_tpu.parallel.mesh import put_sharded_parts
    put = lambda parts, dt: put_sharded_parts(parts, mesh, dt)
    return DistEllMatrix(
        put(lcs, jnp.int32), put(lvs, dtype), put(rcs, jnp.int32),
        put(rvs, dtype), put([send_idx[s] for s in range(nd)], jnp.int32),
        (nloc * nd, ncloc * nd), nloc, ncloc)
