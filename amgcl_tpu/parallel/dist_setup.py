"""Strip-parallel hierarchy construction for GENERAL (unstructured) matrices.

The reference builds the whole distributed hierarchy per-rank: each MPI rank
owns a row strip, and the setup-phase products run as remote-row fetch +
local product (distributed SpGEMM, amgcl/mpi/distributed_matrix.hpp:856-1066)
and triple routing (distributed transpose, amgcl/mpi/distributed_matrix.hpp:
559-716) inside mpi::amg's step_down (amgcl/mpi/amg.hpp:163-330). This module
is the TPU-native rendition of that architecture:

- the SOLVE phase is unchanged — the sharded shard_map program of
  dist_amg.py over DistEllMatrix levels;
- the SETUP phase runs strip-at-a-time on the host with the reference's
  fetch/route communication structure, so the per-strip working set is
  O(nnz/nd + halo) instead of O(nnz) — no step ever assembles a global
  matrix (level arrays are placed shard-by-shard via put_sharded_parts);
- aggregation is the already-mesh-sharded MIS (parallel/dist_mis.py), fed
  strip-built strength graphs, so the communication-heavy rounds run jitted
  on the mesh.

Under single-controller JAX the strip "communication" is in-process slicing
behind the :class:`LocalComm` seam; a multi-controller comm realizes the
same five primitives over ``jax.distributed`` so each process only ever
holds its own strips (the strip-ingestion pattern of the reference's
examples/mpi/mpi_solver.cpp:190-238).

Coarse-level numbering keeps locality by construction: each shard numbers
the MIS roots it owns contiguously from an exclusive prefix of per-shard
root counts, so coarse row blocks stay aligned with the fine row blocks
that produced them — the role of the reference's repartitioners
(amgcl/mpi/partition/*.hpp) falls out of the numbering for aggregation-type
coarsening.
"""

from __future__ import annotations

import copy
from typing import Any, Optional

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.parallel.mesh import ROWS_AXIS, put_sharded_parts

__all__ = [
    "LocalComm", "split_strips", "strip_transpose", "strip_spgemm",
    "strip_sa_hierarchy", "StripAMGSolver",
]


# ===========================================================================
# communication seam
# ===========================================================================

class LocalComm:
    """Single-controller realization of the strip-exchange primitives.

    Every method takes/returns PER-SHARD lists. A multi-controller comm
    implements the same five methods where each process holds only the
    entries at its own index and the rest move over jax.distributed
    (parallel/multihost.py)."""

    def __init__(self, nd: int):
        self.nd = int(nd)

    def max_scalar(self, per_shard) -> float:
        """Global max of one scalar per shard (MPI_Allreduce MAX)."""
        return float(max(per_shard))

    def exscan_sum(self, counts):
        """Exclusive prefix sum of one int per shard + the total
        (MPI_Exscan + Allreduce SUM)."""
        c = np.asarray(counts, dtype=np.int64)
        offs = np.concatenate([[0], np.cumsum(c)[:-1]])
        return list(offs), int(c.sum())

    def alltoall_triples(self, buckets):
        """buckets[src][dst] = (rows, cols, vals) destined for shard dst;
        returns per-dst concatenations (the reference's Isend/Irecv triple
        exchange, distributed_matrix.hpp:559-716)."""
        nd = self.nd
        out = []
        for d in range(nd):
            rs, cs, vs = [], [], []
            for s in range(nd):
                r, c, v = buckets[s][d]
                rs.append(np.asarray(r))
                cs.append(np.asarray(c))
                vs.append(np.asarray(v))
            out.append((np.concatenate(rs), np.concatenate(cs),
                        np.concatenate(vs)))
        return out

    def fetch_rows(self, strips, nloc, gids_per_shard):
        """Remote-row fetch (the reference's SpGEMM prologue,
        distributed_matrix.hpp:856-940): for each requesting shard, the
        scipy CSR stack of global rows ``gids`` (sorted unique) served by
        their owners."""
        out = []
        for gids in gids_per_shard:
            gids = np.asarray(gids)
            if len(gids) == 0:
                out.append(None)
                continue
            owner = np.minimum(gids // nloc, self.nd - 1)
            parts = []
            for o in range(self.nd):
                sel = gids[owner == o]
                if len(sel):
                    parts.append(strips[o][sel - o * nloc])
            out.append(sp.vstack(parts, format="csr") if parts else None)
        return out

    def fetch_vals(self, vals_per_shard, nloc, gids_per_shard):
        """Same as fetch_rows for one value per global row."""
        out = []
        for gids in gids_per_shard:
            gids = np.asarray(gids)
            if len(gids) == 0:
                out.append(np.zeros(0))
                continue
            owner = np.minimum(gids // nloc, self.nd - 1)
            res = np.empty(len(gids), np.asarray(vals_per_shard[0]).dtype)
            for o in range(self.nd):
                sel = owner == o
                if sel.any():
                    res[sel] = np.asarray(
                        vals_per_shard[o])[gids[sel] - o * nloc]
            out.append(res)
        return out


# ===========================================================================
# strip primitives: split / transpose / SpGEMM
# ===========================================================================

def split_strips(A, nd: int):
    """Row-strip a host matrix: per-shard scipy CSR with GLOBAL columns,
    strip s = rows [s*nloc, min((s+1)*nloc, n)). Only the entry point for
    single-host matrices — multi-host ingestion hands per-process strips
    straight to strip_sa_hierarchy without this call."""
    if isinstance(A, CSR):
        assert not A.is_block, "strip the unblocked matrix"
        A = A.to_scipy()
    A = sp.csr_matrix(A)
    n = A.shape[0]
    nloc = -(-n // nd)
    return [A[min(s * nloc, n): min((s + 1) * nloc, n)]
            for s in range(nd)], nloc


def strip_transpose(strips, nloc_in, nloc_out, shape_out, comm: LocalComm):
    """Distributed transpose by triple routing (reference:
    distributed_matrix.hpp:559-716): entry (i, j, v) of strip s is routed to
    the owner of row j in the OUTPUT partition and lands as (j, i, v)."""
    nd = comm.nd
    buckets = []
    for s, S in enumerate(strips):
        r0 = s * nloc_in
        rows_g = np.repeat(np.arange(S.shape[0]), np.diff(S.indptr)) + r0
        dst = np.minimum(S.indices // nloc_out, nd - 1)
        bk = []
        for d in range(nd):
            sel = dst == d
            bk.append((S.indices[sel], rows_g[sel], S.data[sel]))
        buckets.append(bk)
    recv = comm.alltoall_triples(buckets)
    n_out, m_out = shape_out
    out = []
    for d in range(nd):
        r0, r1 = min(d * nloc_out, n_out), min((d + 1) * nloc_out, n_out)
        rr, cc, vv = recv[d]
        T = sp.coo_matrix((vv, (rr - r0, cc)),
                          shape=(r1 - r0, m_out)).tocsr()
        T.sum_duplicates()
        T.sort_indices()
        out.append(T)
    return out


def strip_spgemm(A_strips, B_strips, nloc_B, comm: LocalComm):
    """C = A @ B with A row-stripped and B row-stripped by A's column
    partition: fetch the B rows each strip's columns touch, then multiply
    locally (reference: distributed_matrix.hpp:856-1066). Returns C strips
    on A's row partition."""
    ucols = [np.unique(S.indices) if S.nnz else np.zeros(0, np.int64)
             for S in A_strips]
    B_sub = comm.fetch_rows(B_strips, nloc_B, ucols)
    out = []
    for s, S in enumerate(A_strips):
        if S.nnz == 0 or B_sub[s] is None:
            out.append(sp.csr_matrix((S.shape[0], B_strips[0].shape[1])))
            continue
        # remap columns into the fetched row block
        pos = np.searchsorted(ucols[s], S.indices)
        Sl = sp.csr_matrix((S.data, pos, S.indptr),
                           shape=(S.shape[0], len(ucols[s])))
        C = (Sl @ B_sub[s]).tocsr()
        C.sum_duplicates()
        C.sort_indices()
        out.append(C)
    return out


# ===========================================================================
# per-level SA construction on strips
# ===========================================================================

def _strip_diag(strips, nloc):
    """Per-strip diagonal values (value at (i, r0+i))."""
    out = []
    for s, S in enumerate(strips):
        r0 = s * nloc
        m_s = S.shape[0]
        rows = np.repeat(np.arange(m_s), np.diff(S.indptr))
        d = np.zeros(m_s, S.data.dtype)
        hit = S.indices == rows + r0
        d[rows[hit]] = S.data[hit]
        out.append(d)
    return out


def _strip_filtered(strips, nloc, eps, comm):
    """Strength filter + weak-entry lumping per strip (the serial
    ``smoothed_aggregation._filtered`` with halo diagonal fetch).
    Returns (Af_strips, Dfinv_strips, strong_offdiag_masks, ucols, dj)."""
    dloc = _strip_diag(strips, nloc)
    ucols = [np.unique(S.indices) if S.nnz else np.zeros(0, np.int64)
             for S in strips]
    dj_per = comm.fetch_vals(dloc, nloc, ucols)
    Af, Dfinv, strong_masks = [], [], []
    for s, S in enumerate(strips):
        r0 = s * nloc
        m_s = S.shape[0]
        rows = np.repeat(np.arange(m_s), np.diff(S.indptr))
        di = np.abs(dloc[s])
        dj = np.abs(dj_per[s])[np.searchsorted(ucols[s], S.indices)] \
            if S.nnz else np.zeros(0)
        is_dia = S.indices == rows + r0
        strong = (np.abs(S.data) ** 2 > eps * eps * di[rows] * dj)
        keep = strong | is_dia
        # lump removed entries onto the diagonal
        removed = np.bincount(rows[~keep], weights=S.data[~keep].real,
                              minlength=m_s).astype(S.data.dtype)
        if np.iscomplexobj(S.data):
            removed = removed + 1j * np.bincount(
                rows[~keep], weights=S.data[~keep].imag, minlength=m_s)
        data = S.data[keep].copy()
        col = S.indices[keep]
        ptr = np.concatenate(
            [[0], np.cumsum(np.bincount(rows[keep], minlength=m_s))])
        F = sp.csr_matrix((data, col, ptr), shape=S.shape)
        frows = np.repeat(np.arange(m_s), np.diff(F.indptr))
        fdia = F.indices == frows + r0
        F.data[fdia] += removed[frows[fdia]]
        dF = np.zeros(m_s, F.data.dtype)
        dF[frows[fdia]] = F.data[fdia]
        Af.append(F)
        Dfinv.append(np.where(dF != 0, 1.0 / np.where(dF != 0, dF, 1), 1.0))
        strong_masks.append((strong & ~is_dia, rows))
    return Af, Dfinv, strong_masks, ucols


def _strip_mis_aggregates(strips, strong_masks, n, nloc, mesh, comm,
                          rounds=40):
    """Mesh-sharded MIS over the strip-built strength graph; coarse ids
    numbered per-owner from an exclusive prefix (locality-preserving).
    Returns (agg strips with -1 for isolated, nc)."""
    import jax
    from amgcl_tpu.coarsening.aggregates import _priority
    from amgcl_tpu.parallel.dist_ell import build_dist_ell_strips
    from amgcl_tpu.parallel.dist_mis import _compiled_mis

    nd = comm.nd
    # symmetrized strength adjacency, strip-wise: local strong pattern OR
    # its routed transpose
    pat = []
    for s, S in enumerate(strips):
        mask, rows = strong_masks[s]
        P_ = sp.csr_matrix(
            (np.ones(int(mask.sum()), np.int8),
             (rows[mask], S.indices[mask])), shape=S.shape)
        pat.append(P_)
    patT = strip_transpose(pat, nloc, nloc, (n, n), comm)
    triples = []
    for s in range(nd):
        G = ((pat[s] + patT[s]) > 0).astype(np.float32).tocsr()
        G.sort_indices()
        rows = np.repeat(np.arange(G.shape[0]), np.diff(G.indptr))
        triples.append((rows, G.indices.astype(np.int64), G.data))
    dS = build_dist_ell_strips(triples, mesh, (n, n), jnp.float32)

    prio_full = _priority(n).astype(np.int32)
    prio_parts = []
    for s in range(nd):
        r0, r1 = min(s * nloc, n), min((s + 1) * nloc, n)
        p = np.zeros(dS.nloc, np.int32)
        p[: r1 - r0] = prio_full[r0:r1]
        prio_parts.append(p)
    prio_sh = put_sharded_parts(prio_parts, mesh, jnp.int32)
    fn = _compiled_mis(mesh, dS.shape, dS.nloc, dS.ncloc, int(rounds))
    key_g = np.asarray(jax.device_get(fn(dS, prio_sh)))

    # per-owner contiguous coarse numbering from the exclusive prefix of
    # root counts (root <=> key == own priority)
    inv = np.empty(n, np.int64)
    inv[prio_full - 1] = np.arange(n)
    keys, cid_root, root_counts = [], [], []
    for s in range(nd):
        r0, r1 = min(s * nloc, n), min((s + 1) * nloc, n)
        k = key_g[s * dS.nloc: s * dS.nloc + (r1 - r0)]
        keys.append(k)
        roots = k == prio_full[r0:r1]
        root_counts.append(int(np.count_nonzero(roots & (k > 0))))
    offs, nc = comm.exscan_sum(root_counts)
    for s in range(nd):
        r0, r1 = min(s * nloc, n), min((s + 1) * nloc, n)
        k = keys[s]
        roots = (k == prio_full[r0:r1]) & (k > 0)
        cid = np.full(r1 - r0, -1, np.int64)
        cid[roots] = offs[s] + np.arange(int(np.count_nonzero(roots)))
        cid_root.append(cid)
    # captured rows adopt their root's cid: root row = inv[key-1], fetch
    # its cid from the owner
    agg = []
    root_rows = [inv[np.maximum(keys[s], 1) - 1] for s in range(nd)]
    fetched = comm.fetch_vals(cid_root, nloc, root_rows)
    for s in range(nd):
        a = np.where(keys[s] > 0, fetched[s], -1)
        agg.append(a.astype(np.int64))
    return agg, nc


def _strip_sa_level(strips, n, nloc, mesh, comm, eps, relax,
                    mis_rounds=40):
    """One SA level on strips: (P_strips, Ac_strips, nc, nloc_c). R is NOT
    formed here — between two sharded levels the caller transposes P
    (strip_transpose); at the replicated-tail boundary the local
    S.T suffices (TransitionOps), so a distributed transpose there would
    be wasted traffic.

    Mirrors the serial SmoothedAggregation.transfer_operators +
    galerkin exactly (same strength filter, same Gershgorin omega, same
    MIS — so iteration counts match the serial device_mis build up to a
    permutation of coarse unknowns)."""
    nd = comm.nd
    Af, Dfinv, strong_masks, ucols = _strip_filtered(strips, nloc, eps,
                                                     comm)
    agg, nc = _strip_mis_aggregates(strips, strong_masks, n, nloc, mesh,
                                    comm, mis_rounds)
    if nc == 0:
        raise ValueError("empty coarse level (all rows isolated)")
    nloc_c = -(-nc // nd)

    # omega = relax * 4/3 / rho(Df^-1 Af), Gershgorin (builtin.hpp:775-820)
    rho_loc = []
    for s in range(nd):
        absrow = np.abs(Af[s]).sum(axis=1)
        absrow = np.asarray(absrow).ravel()
        rho_loc.append(float(np.max(np.abs(Dfinv[s]) * absrow))
                       if len(absrow) else 0.0)
    rho = comm.max_scalar(rho_loc)
    omega = relax * (4.0 / 3.0) / max(rho, 1e-30)

    # P strip: row i of (I - omega Df^-1 Af) P_tent. P_tent[j] = e_{agg_j}
    # for agg_j >= 0, so P entries come straight from Af entries:
    # coef_ij = delta_ij - omega * Dfinv_i * Af_ij, col = agg_j.
    agg_cols = [np.unique(F.indices) if F.nnz else np.zeros(0, np.int64)
                for F in Af]
    agg_j_per = comm.fetch_vals(agg, nloc, agg_cols)
    P_strips = []
    for s, F in enumerate(Af):
        r0 = s * nloc
        m_s = F.shape[0]
        rows = np.repeat(np.arange(m_s), np.diff(F.indptr))
        aj = agg_j_per[s][np.searchsorted(agg_cols[s], F.indices)] \
            if F.nnz else np.zeros(0, np.int64)
        coef = -omega * Dfinv[s][rows] * F.data
        coef = coef + (F.indices == rows + r0)   # the identity term
        live = aj >= 0
        Pm = sp.coo_matrix(
            (coef[live], (rows[live], aj[live])), shape=(m_s, nc)).tocsr()
        Pm.sum_duplicates()
        Pm.sort_indices()
        P_strips.append(Pm)

    # Ac = P^T (A P): local product per strip, triples routed to the coarse
    # owner (this is the distributed Galerkin SpGEMM,
    # distributed_matrix.hpp:856-1066 + mpi/amg.hpp:163-330)
    AP = strip_spgemm(strips, P_strips, nloc, comm)
    buckets = []
    for s in range(nd):
        L = (P_strips[s].T.tocsr() @ AP[s]).tocoo()   # (nc, nc) local part
        dst = np.minimum(L.row // nloc_c, nd - 1)
        bk = []
        for d in range(nd):
            sel = dst == d
            bk.append((L.row[sel], L.col[sel], L.data[sel]))
        buckets.append(bk)
    recv = comm.alltoall_triples(buckets)
    Ac_strips = []
    for d in range(nd):
        r0, r1 = min(d * nloc_c, nc), min((d + 1) * nloc_c, nc)
        rr, cc, vv = recv[d]
        Ac = sp.coo_matrix((vv, (rr - r0, cc)),
                           shape=(r1 - r0, nc)).tocsr()
        Ac.sum_duplicates()
        Ac.sort_indices()
        Ac_strips.append(Ac)
    return P_strips, Ac_strips, nc, nloc_c


# ===========================================================================
# smoothers + hierarchy assembly
# ===========================================================================

def _strip_smoother(relax, strips, n, nloc, mesh, comm, dtype):
    """Strip-local DistSmoother state. Row-local families only — the
    global-factorization families (ilu*, gauss_seidel, spai1) need the
    assembled matrix and are served by the serial-build DistAMGSolver."""
    from amgcl_tpu.parallel.dist_amg import DistSmoother
    from amgcl_tpu.relaxation.spai0 import Spai0
    from amgcl_tpu.relaxation.jacobi import DampedJacobi
    from amgcl_tpu.relaxation.chebyshev import Chebyshev

    def parts_of(vec_strips, fill=0.0):
        host_dt = np.result_type(
            *([np.asarray(v).dtype for v in vec_strips] + [np.float64]))
        out = []
        for s in range(nd):
            p = np.full(nloc, fill, host_dt)
            v = vec_strips[s]
            p[:len(v)] = v
            out.append(p)
        return put_sharded_parts(out, mesh, dtype)

    def invsafe(d):
        return np.where(d != 0, 1.0 / np.where(d != 0, d, 1), 1.0)

    nd = comm.nd
    if isinstance(relax, Spai0):
        # m_i = a_ii / sum_j |a_ij|^2 (spai0.hpp:49-117) — row-local
        dia = _strip_diag(strips, nloc)
        sc = []
        for s, S in enumerate(strips):
            rows = np.repeat(np.arange(S.shape[0]), np.diff(S.indptr))
            denom = np.bincount(rows, weights=(np.abs(S.data) ** 2).real,
                                minlength=S.shape[0])
            sc.append(dia[s] / np.where(denom != 0, denom, 1.0))
        return DistSmoother("diag", parts_of(sc))
    if isinstance(relax, DampedJacobi):
        sc = [relax.damping * invsafe(d) for d in _strip_diag(strips, nloc)]
        return DistSmoother("diag", parts_of(sc))
    if isinstance(relax, Chebyshev):
        if relax.power_iters:
            raise ValueError(
                "strip setup supports Gershgorin chebyshev only "
                "(power_iters=0)")
        dia = _strip_diag(strips, nloc) if relax.scale else None
        loc = []
        for s, S in enumerate(strips):
            absrow = np.asarray(np.abs(S).sum(axis=1)).ravel()
            if relax.scale:
                absrow = np.abs(invsafe(dia[s])) * absrow
            loc.append(float(absrow.max()) if len(absrow) else 0.0)
        rho = comm.max_scalar(loc)
        a, b = rho * relax.lower, rho
        dinv_sh = parts_of([invsafe(d) for d in dia]) if relax.scale \
            else None
        return DistSmoother("cheb", dinv_sh, theta=(a + b) / 2,
                            delta=(b - a) / 2, degree=relax.degree)
    raise ValueError(
        "smoother %s has no strip-parallel build; use spai0/damped_jacobi/"
        "chebyshev, or the serial-build DistAMGSolver for ilu/gs/spai1"
        % type(relax).__name__)


def _strips_to_dist_ell(strips, mesh, shape, dtype, nloc, ncloc):
    from amgcl_tpu.parallel.dist_ell import build_dist_ell_strips
    triples = []
    for S in strips:
        rows = np.repeat(np.arange(S.shape[0]), np.diff(S.indptr))
        triples.append((rows, S.indices.astype(np.int64), S.data))
    return build_dist_ell_strips(triples, mesh, shape, dtype, nloc, ncloc)


def _gather_strips(strips, shape):
    """Assemble strips into one host CSR (used ONLY at the replicated-tail
    boundary, where the level is already small)."""
    M = sp.vstack(strips, format="csr") if strips else \
        sp.csr_matrix(shape)
    M = sp.csr_matrix(M, shape=shape)
    M.sort_indices()
    return CSR(M.indptr.astype(np.int64), M.indices.astype(np.int32),
               M.data, shape[1])


def strip_sa_hierarchy(strips, n, mesh, prm, comm=None,
                       replicate_below: int = 4096, mis_rounds: int = 40,
                       max_sharded_levels: int = 30):
    """Build the distributed hierarchy from row strips. Returns
    (DistHierarchy, level_sizes, stats). No global matrix is ever
    assembled while levels stay sharded; the replicated tail (below
    ``replicate_below`` rows) is gathered and built serially, as
    DistAMGSolver does."""
    from amgcl_tpu.coarsening.smoothed_aggregation import \
        SmoothedAggregation
    from amgcl_tpu.models.amg import AMG, Hierarchy as SerialHierarchy
    from amgcl_tpu.parallel.dist_amg import (DistLevel, DistHierarchy,
                                             TransitionOps)

    nd = mesh.shape[ROWS_AXIS]
    comm = comm or LocalComm(nd)
    c = prm.coarsening
    if not isinstance(c, SmoothedAggregation):
        raise ValueError("strip setup implements smoothed_aggregation; "
                         "got %s" % type(c).__name__)
    if c.nullspace is not None or c.block_size != 1 or c.power_iters:
        raise ValueError("strip setup supports scalar SA with Gershgorin "
                         "omega (no nullspace, block_size=1, "
                         "power_iters=0)")
    dtype = prm.dtype
    eps = float(c.eps_strong)
    nloc = -(-n // nd)
    sizes = [n]
    levels = []
    stats = {"peak_strip_nnz": max(S.nnz for S in strips),
             "level_strip_nnz": []}
    P_prev = R_prev = None

    while (n >= replicate_below and n > prm.coarse_enough
           and len(levels) + 1 < prm.max_levels
           and len(levels) < max_sharded_levels):
        try:
            P_s, Ac_s, nc, nloc_c = _strip_sa_level(
                strips, n, nloc, mesh, comm, eps, c.relax, mis_rounds)
        except ValueError:
            break       # coarsening stalled: serial build breaks too
        if nc >= n:
            break
        dA = _strips_to_dist_ell(strips, mesh, (n, n), dtype, nloc, nloc)
        sm = _strip_smoother(prm.relax, strips, n, nloc, mesh, comm, dtype)
        levels.append([dA, sm, P_s, nloc, n])
        stats["level_strip_nnz"].append(max(S.nnz for S in strips))
        stats["peak_strip_nnz"] = max(
            stats["peak_strip_nnz"],
            max(S.nnz for S in Ac_s) if Ac_s else 0)
        strips, n, nloc = Ac_s, nc, nloc_c
        eps *= 0.5
        sizes.append(n)

    # wire DistLevels: P/R between consecutive SHARDED levels become
    # DistEllMatrix; the last sharded level's P/R become TransitionOps
    dist_levels = []
    for k, (dA, sm, P_s, nloc_k, n_k) in enumerate(levels):
        dP = dR = None
        if k + 1 < len(levels):
            nloc_next = levels[k + 1][3]
            n_next = levels[k + 1][4]
            dP = _strips_to_dist_ell(P_s, mesh, (n_k, n_next), dtype,
                                     nloc_k, nloc_next)
            R_s = strip_transpose(P_s, nloc_k, nloc_next, (n_next, n_k),
                                  comm)
            dR = _strips_to_dist_ell(R_s, mesh, (n_next, n_k), dtype,
                                     nloc_next, nloc_k)
        dist_levels.append(DistLevel(dA, dP, dR, sm))

    # replicated serial tail from the gathered coarse strips
    prm_tail = copy.copy(prm)
    prm_tail.coarsening = copy.deepcopy(c)
    prm_tail.coarsening.eps_strong = eps
    prm_tail.coarsening.aggregator = None
    # the user's depth bound covers sharded + replicated levels together
    prm_tail.max_levels = max(prm.max_levels - len(levels), 1)
    A_tail = _gather_strips(strips, (n, n))
    rep_amg = AMG(A_tail, prm_tail)
    rep = SerialHierarchy(rep_amg.hierarchy.levels,
                          rep_amg.hierarchy.coarse,
                          prm.npre, prm.npost, prm.ncycle, 1)

    top_A = None
    trans = None
    if levels:
        # TransitionOps strip-wise: P rows are already fine-partitioned;
        # R per shard = (P strip)^T — column-restricted by construction
        _, _, P_s, nloc_b, n_b = levels[-1]
        K1 = max(1, int(comm.max_scalar(
            [int(np.diff(S.indptr).max()) if S.nnz else 0 for S in P_s])))
        K2 = max(1, int(comm.max_scalar(
            [int((S.T.tocsr()).getnnz(axis=1).max()) if S.nnz else 0
             for S in P_s])))
        pc_parts, pv_parts, rc_parts, rv_parts = [], [], [], []
        from amgcl_tpu.parallel.dist_ell import pack_rows_ell
        for s, S in enumerate(P_s):
            rows = np.repeat(np.arange(S.shape[0]), np.diff(S.indptr))
            cgl, vgl = pack_rows_ell(rows, S.indices, S.data, nloc_b, K1)
            pc_parts.append(cgl)
            pv_parts.append(vgl)
            T = S.T.tocsr()
            trows = np.repeat(np.arange(T.shape[0]), np.diff(T.indptr))
            crl, vrl = pack_rows_ell(trows, T.indices, T.data, n, K2)
            rc_parts.append(crl)
            rv_parts.append(vrl)
        put = lambda parts, dt: put_sharded_parts(parts, mesh, dt)
        trans = TransitionOps(put(pc_parts, jnp.int32),
                              put(pv_parts, dtype),
                              put(rc_parts, jnp.int32),
                              put(rv_parts, dtype))
    else:
        top_A = _strips_to_dist_ell(strips, mesh, (n, n), dtype, nloc,
                                    nloc)

    hier = DistHierarchy(dist_levels, rep, trans, top_A, prm.npre,
                         prm.npost, prm.ncycle, prm.pre_cycles)
    return hier, sizes, stats


class StripAMGSolver:
    """mpi::make_solver with a DISTRIBUTED setup: the hierarchy is built
    strip-parallel (strip_sa_hierarchy) and solved with the same SPMD
    program as DistAMGSolver. Accepts either a whole matrix (split
    in-process) or pre-split per-shard strips (multi-host ingestion:
    no process ever holds the global matrix)."""

    def __init__(self, A_or_strips, mesh, prm: Optional[Any] = None,
                 solver: Any = None, n: Optional[int] = None,
                 replicate_below: int = 4096, comm=None,
                 mis_rounds: int = 40):
        from amgcl_tpu.models.amg import AMGParams
        self.mesh = mesh
        self.prm = prm or AMGParams()
        from amgcl_tpu.solver.cg import CG
        self.solver = solver or CG()
        nd = mesh.shape[ROWS_AXIS]
        if isinstance(A_or_strips, (list, tuple)):
            strips = list(A_or_strips)
            if n is None:
                raise ValueError("pass n= (global rows) with strips")
            if len(strips) != nd:
                raise ValueError("need one strip per mesh device")
            # the whole strip algebra assumes the ceil(n/nd) row blocks of
            # build_dist_ell (owner = row // nloc); a floor-based MPI-style
            # split would silently misalign every diagonal and halo plan
            nloc0 = -(-int(n) // nd)
            for s, S in enumerate(strips):
                want = min((s + 1) * nloc0, int(n)) - min(s * nloc0, int(n))
                if S.shape[0] != want:
                    raise ValueError(
                        "strip %d has %d rows; the ceil(n/nd) partition "
                        "requires %d (rows [%d, %d)) — re-split with "
                        "split_strips' convention"
                        % (s, S.shape[0], want, min(s * nloc0, int(n)),
                           min((s + 1) * nloc0, int(n))))
        else:
            strips, _ = split_strips(A_or_strips, nd)
            n = sum(S.shape[0] for S in strips)
        self.hier, self.sizes, self.stats = strip_sa_hierarchy(
            strips, n, mesh, self.prm, comm=comm,
            replicate_below=replicate_below, mis_rounds=mis_rounds)
        self.n = int(n)
        first_A = self.hier.levels[0].A if self.hier.levels \
            else self.hier.top_A
        self.n_pad = first_A.nloc * nd
        self._compiled = None

    # the compiled SPMD solve program is identical to the serial-setup one
    def _build_compiled(self):
        from amgcl_tpu.parallel.dist_amg import DistAMGSolver
        return DistAMGSolver._build_compiled(self)

    def __call__(self, rhs, x0=None):
        from amgcl_tpu.parallel.dist_amg import DistAMGSolver
        return DistAMGSolver.__call__(self, rhs, x0)

    def __repr__(self):
        lines = ["StripAMGSolver over %d devices (strip-parallel setup)"
                 % self.mesh.shape[ROWS_AXIS]]
        for i, m in enumerate(self.sizes):
            lines.append("%5d %12d" % (i, m))
        return "\n".join(lines)
