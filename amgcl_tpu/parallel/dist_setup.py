"""Strip-parallel hierarchy construction for GENERAL (unstructured) matrices.

The reference builds the whole distributed hierarchy per-rank: each MPI rank
owns a row strip, and the setup-phase products run as remote-row fetch +
local product (distributed SpGEMM, amgcl/mpi/distributed_matrix.hpp:856-1066)
and triple routing (distributed transpose, amgcl/mpi/distributed_matrix.hpp:
559-716) inside mpi::amg's step_down (amgcl/mpi/amg.hpp:163-330). This module
is the TPU-native rendition of that architecture:

- the SOLVE phase is unchanged — the sharded shard_map program of
  dist_amg.py over DistEllMatrix levels;
- the SETUP phase runs strip-at-a-time on the host with the reference's
  fetch/route communication structure, so the per-strip working set is
  O(nnz/nd + halo) instead of O(nnz) — no step ever assembles a global
  matrix (level arrays are placed shard-by-shard via put_sharded_parts);
- aggregation is the already-mesh-sharded MIS (parallel/dist_mis.py), fed
  strip-built strength graphs, so the communication-heavy rounds run jitted
  on the mesh.

Under single-controller JAX the strip "communication" is in-process slicing
behind the :class:`LocalComm` seam; a multi-controller comm realizes the
same five primitives over ``jax.distributed`` so each process only ever
holds its own strips (the strip-ingestion pattern of the reference's
examples/mpi/mpi_solver.cpp:190-238).

Coarse-level numbering keeps locality by construction: each shard numbers
the MIS roots it owns contiguously from an exclusive prefix of per-shard
root counts, so coarse row blocks stay aligned with the fine row blocks
that produced them — the role of the reference's repartitioners
(amgcl/mpi/partition/*.hpp) falls out of the numbering for aggregation-type
coarsening.
"""

from __future__ import annotations

import copy
import functools
from typing import Any, Optional

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.parallel.mesh import ROWS_AXIS, put_sharded_parts

__all__ = [
    "LocalComm", "split_strips", "strip_transpose", "strip_spgemm",
    "strip_sa_hierarchy", "StripAMGSolver",
]


# ===========================================================================
# communication seam
# ===========================================================================

# Shared with the serial builder (and every coarsening policy) since r5;
# re-exported here because the strip route's callers import it from this
# module. The strip builder catches exactly this — not arbitrary
# ValueErrors — and closes the hierarchy with the replicated tail, the
# same way the serial build stops (models/amg.py stall guard).
from amgcl_tpu.coarsening.stall import CoarseningStall  # noqa: E402


class LocalComm:
    """Single-controller realization of the strip-exchange primitives.

    Every method takes/returns PER-SHARD lists (index = shard id).
    :class:`MultihostComm` implements the same interface where each
    process holds only its own shards' entries (``None`` elsewhere) and
    the data moves over jax.distributed."""

    def __init__(self, nd: int):
        self.nd = int(nd)
        self.my_shards = list(range(self.nd))

    def max_scalar(self, per_shard) -> float:
        """Global max of one scalar per owned shard (MPI_Allreduce MAX).
        -inf when nothing is owned anywhere (the allreduce identity)."""
        return float(max((v for v in per_shard if v is not None),
                         default=-np.inf))

    def _vals_meta(self, vals_per_shard):
        """(is_complex, is_int) of the value payload, from owned non-None
        entries only — safe for a process that owns no shards."""
        kinds = {np.asarray(vals_per_shard[s]).dtype.kind
                 for s in self.my_shards if vals_per_shard[s] is not None}
        return bool(kinds & {"c"}), bool(kinds & {"i", "u"})

    def alltoall(self, buckets):
        """buckets[src][dst] = (rows, cols, vals) destined for shard dst,
        for each OWNED src (None elsewhere); returns recv[dst][src] for
        each owned dst (the reference's Isend/Irecv triple exchange,
        distributed_matrix.hpp:559-716)."""
        return [[buckets[s][d] for s in range(self.nd)]
                for d in range(self.nd)]

    def allgather_concat(self, per_shard):
        """Concatenate one 1-D array per owned shard across every shard
        (MPI_Allgatherv); every caller sees the same global array."""
        return np.concatenate([np.asarray(per_shard[s])
                               for s in range(self.nd)])

    def fetch_rows(self, strips, nloc, gids_per_shard):
        """Remote-row fetch (the reference's SpGEMM prologue,
        distributed_matrix.hpp:856-940): for each owned requesting shard,
        the scipy CSR stack of global rows ``gids`` (sorted unique) served
        by their owners."""
        out = []
        for gids in gids_per_shard:
            if gids is None:
                out.append(None)
                continue
            gids = np.asarray(gids)
            if len(gids) == 0:
                out.append(None)
                continue
            owner = np.minimum(gids // nloc, self.nd - 1)
            parts = []
            for o in range(self.nd):
                sel = gids[owner == o]
                if len(sel):
                    parts.append(strips[o][sel - o * nloc])
            out.append(sp.vstack(parts, format="csr") if parts else None)
        return out

    def fetch_vals(self, vals_per_shard, nloc, gids_per_shard):
        """Same as fetch_rows for one value per global row (duplicate and
        unsorted ids allowed)."""
        out = []
        ref_dt = np.asarray(
            next(v for v in vals_per_shard if v is not None)).dtype
        for gids in gids_per_shard:
            if gids is None:
                out.append(None)
                continue
            gids = np.asarray(gids)
            if len(gids) == 0:
                out.append(np.zeros(0, ref_dt))
                continue
            owner = np.minimum(gids // nloc, self.nd - 1)
            res = np.empty(len(gids), ref_dt)
            for o in range(self.nd):
                sel = owner == o
                if sel.any():
                    res[sel] = np.asarray(
                        vals_per_shard[o])[gids[sel] - o * nloc]
            out.append(res)
        return out


class MultihostComm(LocalComm):
    """Multi-controller realization over ``jax.distributed``: each process
    holds only its addressable shards' strips; small reductions ride
    ``process_allgather`` and the bulk triple exchange is ONE device
    ``all_to_all`` over the rows mesh, so no process ever materializes
    another process's strip (reference role: the Isend/Irecv exchanges of
    distributed_matrix.hpp; ingestion pattern of
    examples/mpi/mpi_solver.cpp:190-238)."""

    def __init__(self, mesh):
        import jax
        self.mesh = mesh
        self.nd = int(mesh.shape[ROWS_AXIS])
        pid = jax.process_index()
        devs = list(np.asarray(mesh.devices).reshape(-1))
        self.my_shards = [i for i, d in enumerate(devs)
                          if d.process_index == pid]

    # -- small fixed-shape allreduce helpers --------------------------------

    def _allgather_np(self, arr, combine):
        from jax.experimental import multihost_utils
        a = np.asarray(arr)
        g = np.asarray(multihost_utils.process_allgather(a))
        # jax versions disagree on whether the process axis is stacked
        # (nproc, *shape) or tiled ((nproc*n0, ...)); normalize to stacked
        g = g.reshape((-1,) + a.shape)
        return combine(g, axis=0)

    def max_scalar(self, per_shard) -> float:
        vals = [v for v in per_shard if v is not None]
        loc = max(vals) if vals else -np.inf
        return float(self._allgather_np(np.float64(loc), np.max))

    def _vals_meta(self, vals_per_shard):
        # flags must agree across processes even when this one owns no
        # shards on the rows axis — reduce them over process_allgather
        cplx, isint = LocalComm._vals_meta(self, vals_per_shard)
        flags = self._allgather_np(np.int64([cplx, isint]), np.max)
        return bool(flags[0]), bool(flags[1])

    def _allgather_var(self, arr):
        """Allgatherv of one variable-length 1-D array per process.
        Lengths ride a separate int64 gather — never the payload dtype,
        which could not represent large counts exactly (float32 payloads
        round above 2^24)."""
        from jax.experimental import multihost_utils
        arr = np.asarray(arr)
        lens = np.asarray(
            multihost_utils.process_allgather(np.int64(arr.shape[0])))
        lens = lens.reshape(-1)
        n = int(lens.max())
        if n == 0:
            return arr
        pad = np.zeros(n, dtype=arr.dtype)
        pad[:arr.shape[0]] = arr
        g = np.asarray(multihost_utils.process_allgather(pad))
        return np.concatenate([g[p, :int(lens[p])]
                               for p in range(g.shape[0])])

    def allgather_concat(self, per_shard):
        loc = np.concatenate(
            [np.asarray(per_shard[s]) for s in self.my_shards]) \
            if self.my_shards else np.zeros(0, np.int64)
        return self._allgather_var(loc)

    # -- bulk exchange: ONE device all_to_all over the mesh -----------------

    # elements per (src,dst) slot per exchange round: bounds the padded
    # payload at nd * _CHUNK_CAP * 24B per shard per round; larger
    # messages stream over several rounds of the SAME compiled program
    # (a single global max chunk would inflate every nd^2 slot to the
    # size of the one largest message)
    _CHUNK_CAP = 1 << 16

    def alltoall(self, buckets):
        nd = self.nd
        # global max message + value dtype agreement
        loc_max = max((len(buckets[s][d][0]) for s in self.my_shards
                       for d in range(nd)), default=0)
        M = max(int(self._allgather_np(np.int64(loc_max), np.max)), 1)
        has_cplx = any(np.asarray(buckets[s][d][2]).dtype.kind == "c"
                       for s in self.my_shards for d in range(nd))
        has_cplx = bool(self._allgather_np(np.int64(has_cplx), np.max))
        vdt = np.complex128 if has_cplx else np.float64
        # power-of-two chunk, capped: quantized so _compiled_alltoall's
        # distinct jit compilations stay ~log2(range)
        C = min(1 << (M - 1).bit_length(), self._CHUNK_CAP)
        rounds = -(-M // C)

        cnt = np.zeros((nd, nd), np.int64)
        for s in self.my_shards:
            for d in range(nd):
                cnt[s, d] = len(np.asarray(buckets[s][d][0]))
        cnt = self._allgather_np(cnt, np.sum)     # zeros elsewhere

        fn = _compiled_alltoall(self.mesh, C, "c" if has_cplx else "f")
        pieces = {d: [([], [], []) for _ in range(nd)]
                  for d in self.my_shards}
        for t in range(rounds):
            lo = t * C
            idx_parts = [None] * nd
            val_parts = [None] * nd
            for s in self.my_shards:
                ip = np.zeros((nd, C, 2), np.int64)
                vp = np.zeros((nd, C), vdt)
                for d in range(nd):
                    r, c, v = buckets[s][d]
                    k = max(0, min(len(np.asarray(r)) - lo, C))
                    if k:
                        ip[d, :k, 0] = np.asarray(r)[lo:lo + k]
                        ip[d, :k, 1] = np.asarray(c)[lo:lo + k]
                        vp[d, :k] = np.asarray(v)[lo:lo + k]
                idx_parts[s] = ip
                val_parts[s] = vp
            idx_sh = put_sharded_parts(idx_parts, self.mesh, jnp.int64)
            val_sh = put_sharded_parts(
                val_parts, self.mesh,
                jnp.complex128 if has_cplx else jnp.float64)
            idx_r, val_r = fn(idx_sh, val_sh)
            got_i = {sh.index[0].start or 0: np.asarray(sh.data)[0]
                     for sh in idx_r.addressable_shards}
            got_v = {sh.index[0].start or 0: np.asarray(sh.data)[0]
                     for sh in val_r.addressable_shards}
            for d in self.my_shards:
                for s in range(nd):
                    k = max(0, min(int(cnt[s, d]) - lo, C))
                    if k:
                        rs, cs, vs = pieces[d][s]
                        rs.append(got_i[d][s, :k, 0])
                        cs.append(got_i[d][s, :k, 1])
                        vs.append(got_v[d][s, :k])

        out = [None] * nd
        z = np.zeros(0, np.int64)
        for d in self.my_shards:
            seg = []
            for s in range(nd):
                rs, cs, vs = pieces[d][s]
                seg.append((
                    np.concatenate(rs) if rs else z,
                    np.concatenate(cs) if cs else z,
                    np.concatenate(vs) if vs else np.zeros(0, vdt)))
            out[d] = seg
        return out

    # -- fetch = route requests, serve, route responses ---------------------

    def _route_requests(self, nloc, gids_per_shard):
        nd = self.nd
        req = [None] * nd
        uniq = [None] * nd
        for s in self.my_shards:
            gids = np.asarray(gids_per_shard[s]) \
                if gids_per_shard[s] is not None else np.zeros(0, np.int64)
            u = np.unique(gids)
            uniq[s] = u
            owner = np.minimum(u // nloc, nd - 1) if len(u) else u
            bk = []
            for o in range(nd):
                sel = u[owner == o] if len(u) else u
                bk.append((sel, np.zeros(len(sel), np.int64),
                           np.zeros(len(sel))))
            req[s] = bk
        return req, uniq

    def fetch_vals(self, vals_per_shard, nloc, gids_per_shard):
        nd = self.nd
        req, uniq = self._route_requests(nloc, gids_per_shard)
        recv_req = self.alltoall(req)
        resp = [None] * nd
        for o in self.my_shards:
            vals_o = np.asarray(vals_per_shard[o])
            bk = []
            for s in range(nd):
                want = np.asarray(recv_req[o][s][0], np.int64)
                served = vals_o[want - o * nloc] if len(want) else \
                    np.zeros(0, vals_o.dtype)
                bk.append((want, np.zeros(len(want), np.int64), served))
            resp[o] = bk
        recv = self.alltoall(resp)
        has_cplx, has_int = self._vals_meta(vals_per_shard)
        out = [None] * nd
        for s in self.my_shards:
            gids = np.asarray(gids_per_shard[s]) \
                if gids_per_shard[s] is not None else None
            if gids is None or len(gids) == 0:
                out[s] = np.zeros(0) if gids is not None else None
                continue
            got_g = np.concatenate([np.asarray(recv[s][o][0], np.int64)
                                    for o in range(nd)])
            got_v = np.concatenate([np.asarray(recv[s][o][2])
                                    for o in range(nd)])
            order = np.argsort(got_g)
            pos = order[np.searchsorted(got_g[order], gids)]
            vals = got_v[pos]
            if not has_cplx:
                vals = vals.real
            # integer payloads (aggregate ids) ride the float channel;
            # values are exact integers well below 2^53
            if has_int:
                vals = np.rint(vals.real).astype(np.int64)
            out[s] = vals
        return out

    def fetch_rows(self, strips, nloc, gids_per_shard):
        nd = self.nd
        req, uniq = self._route_requests(nloc, gids_per_shard)
        recv_req = self.alltoall(req)
        resp = [None] * nd
        for o in self.my_shards:
            S = strips[o]
            bk = []
            for s in range(nd):
                want = np.asarray(recv_req[o][s][0], np.int64)
                if len(want):
                    sub = S[want - o * nloc].tocoo()
                    gid_of = want[sub.row]
                    bk.append((gid_of, sub.col.astype(np.int64), sub.data))
                else:
                    bk.append((np.zeros(0, np.int64), np.zeros(0, np.int64),
                               np.zeros(0)))
            resp[o] = bk
        recv = self.alltoall(resp)
        ncols = None
        for s in self.my_shards:
            ncols = strips[s].shape[1]
            break
        out = [None] * nd
        for s in self.my_shards:
            gids = gids_per_shard[s]
            if gids is None or len(np.asarray(gids)) == 0:
                out[s] = None
                continue
            gids = np.asarray(gids)
            gg = np.concatenate([np.asarray(recv[s][o][0], np.int64)
                                 for o in range(nd)])
            cc = np.concatenate([np.asarray(recv[s][o][1], np.int64)
                                 for o in range(nd)])
            vv = np.concatenate([np.asarray(recv[s][o][2])
                                 for o in range(nd)])
            if not any(np.iscomplexobj(np.asarray(strips[t].data))
                       for t in self.my_shards):
                vv = vv.real
            rows_rel = np.searchsorted(gids, gg)   # gids sorted unique
            M = sp.coo_matrix((vv, (rows_rel, cc)),
                              shape=(len(gids), ncols)).tocsr()
            M.sum_duplicates()
            M.sort_indices()
            out[s] = M
        return out


@functools.lru_cache(maxsize=64)
def _compiled_alltoall(mesh, C, kind):
    """One jitted shard_map all_to_all for (nd, nd, C, ...) payloads."""
    import jax
    from jax import lax
    from amgcl_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    def run(idx, val):
        i = lax.all_to_all(idx[0], ROWS_AXIS, 0, 0, tiled=False)
        v = lax.all_to_all(val[0], ROWS_AXIS, 0, 0, tiled=False)
        return i[None], v[None]

    fn = shard_map(run, mesh=mesh,
                   in_specs=(P(ROWS_AXIS), P(ROWS_AXIS)),
                   out_specs=(P(ROWS_AXIS), P(ROWS_AXIS)),
                   check_vma=False)
    # observed jit (telemetry/compile_watch.py): every strip-setup
    # triple product funnels its exchanges through this cached program
    from amgcl_tpu.telemetry.compile_watch import watched_jit
    return watched_jit(fn, name="parallel.dist_exchange")


# ===========================================================================
# strip primitives: split / transpose / SpGEMM
# ===========================================================================

def split_strips(A, nd: int):
    """Row-strip a host matrix: per-shard scipy CSR with GLOBAL columns,
    strip s = rows [s*nloc, min((s+1)*nloc, n)). Only the entry point for
    single-host matrices — multi-host ingestion hands per-process strips
    straight to strip_sa_hierarchy without this call."""
    if isinstance(A, CSR):
        assert not A.is_block, "strip the unblocked matrix"
        A = A.to_scipy()
    A = sp.csr_matrix(A)
    n = A.shape[0]
    nloc = -(-n // nd)
    return [A[min(s * nloc, n): min((s + 1) * nloc, n)]
            for s in range(nd)], nloc


def strip_transpose(strips, nloc_in, nloc_out, shape_out, comm: LocalComm):
    """Distributed transpose by triple routing (reference:
    distributed_matrix.hpp:559-716): entry (i, j, v) of strip s is routed to
    the owner of row j in the OUTPUT partition and lands as (j, i, v)."""
    nd = comm.nd
    buckets = [None] * nd
    for s in comm.my_shards:
        S = strips[s]
        r0 = s * nloc_in
        rows_g = np.repeat(np.arange(S.shape[0]), np.diff(S.indptr)) + r0
        dst = np.minimum(S.indices // nloc_out, nd - 1)
        bk = []
        for d in range(nd):
            sel = dst == d
            bk.append((S.indices[sel], rows_g[sel], S.data[sel]))
        buckets[s] = bk
    recv = comm.alltoall(buckets)
    n_out, m_out = shape_out
    out = [None] * nd
    for d in comm.my_shards:
        r0, r1 = min(d * nloc_out, n_out), min((d + 1) * nloc_out, n_out)
        rr = np.concatenate([np.asarray(t[0]) for t in recv[d]])
        cc = np.concatenate([np.asarray(t[1]) for t in recv[d]])
        vv = np.concatenate([np.asarray(t[2]) for t in recv[d]])
        T = sp.coo_matrix((vv, (rr - r0, cc)),
                          shape=(r1 - r0, m_out)).tocsr()
        T.sum_duplicates()
        T.sort_indices()
        out[d] = T
    return out


def strip_spgemm(A_strips, B_strips, nloc_B, comm: LocalComm):
    """C = A @ B with A row-stripped and B row-stripped by A's column
    partition: fetch the B rows each strip's columns touch, then multiply
    locally (reference: distributed_matrix.hpp:856-1066). Returns C strips
    on A's row partition."""
    nd = comm.nd
    ucols = [None] * nd
    ncols_B = None
    for s in comm.my_shards:
        S = A_strips[s]
        ucols[s] = np.unique(S.indices) if S.nnz else np.zeros(0, np.int64)
        ncols_B = B_strips[s].shape[1]
    B_sub = comm.fetch_rows(B_strips, nloc_B, ucols)
    out = [None] * nd
    for s in comm.my_shards:
        S = A_strips[s]
        if S.nnz == 0 or B_sub[s] is None:
            out[s] = sp.csr_matrix((S.shape[0], ncols_B))
            continue
        # remap columns into the fetched row block
        pos = np.searchsorted(ucols[s], S.indices)
        Sl = sp.csr_matrix((S.data, pos, S.indptr),
                           shape=(S.shape[0], len(ucols[s])))
        C = (Sl @ B_sub[s]).tocsr()
        C.sum_duplicates()
        C.sort_indices()
        out[s] = C
    return out


# ===========================================================================
# per-level SA construction on strips
# ===========================================================================

def _strip_diag(strips, nloc, my_shards=None):
    """Per-strip diagonal values (value at (i, r0+i))."""
    out = [None] * len(strips)
    for s in (range(len(strips)) if my_shards is None else my_shards):
        S = strips[s]
        r0 = s * nloc
        m_s = S.shape[0]
        rows = np.repeat(np.arange(m_s), np.diff(S.indptr))
        d = np.zeros(m_s, S.data.dtype)
        hit = S.indices == rows + r0
        d[rows[hit]] = S.data[hit]
        out[s] = d
    return out


def _strip_filtered(strips, nloc, eps, comm, need_filtered=True):
    """Strength filter + weak-entry lumping per strip (the serial
    ``smoothed_aggregation._filtered`` with halo diagonal fetch).
    Returns (Af_strips, Dfinv_strips, strong_offdiag_masks, ucols);
    ``need_filtered=False`` (plain aggregation) skips assembling the
    lumped Af/Dfinv — only the strength masks are produced."""
    nd = comm.nd
    dloc = _strip_diag(strips, nloc, comm.my_shards)
    ucols = [None] * nd
    for s in comm.my_shards:
        S = strips[s]
        ucols[s] = np.unique(S.indices) if S.nnz else np.zeros(0, np.int64)
    dj_per = comm.fetch_vals(dloc, nloc, ucols)
    Af = [None] * nd
    Dfinv = [None] * nd
    strong_masks = [None] * nd
    for s in comm.my_shards:
        S = strips[s]
        r0 = s * nloc
        m_s = S.shape[0]
        rows = np.repeat(np.arange(m_s), np.diff(S.indptr))
        di = np.abs(dloc[s])
        dj = np.abs(dj_per[s])[np.searchsorted(ucols[s], S.indices)] \
            if S.nnz else np.zeros(0)
        is_dia = S.indices == rows + r0
        strong = (np.abs(S.data) ** 2 > eps * eps * di[rows] * dj)
        strong_masks[s] = (strong & ~is_dia, rows)
        if not need_filtered:
            continue
        keep = strong | is_dia
        # lump removed entries onto the diagonal
        removed = np.bincount(rows[~keep], weights=S.data[~keep].real,
                              minlength=m_s).astype(S.data.dtype)
        if np.iscomplexobj(S.data):
            removed = removed + 1j * np.bincount(
                rows[~keep], weights=S.data[~keep].imag, minlength=m_s)
        data = S.data[keep].copy()
        col = S.indices[keep]
        ptr = np.concatenate(
            [[0], np.cumsum(np.bincount(rows[keep], minlength=m_s))])
        F = sp.csr_matrix((data, col, ptr), shape=S.shape)
        frows = np.repeat(np.arange(m_s), np.diff(F.indptr))
        fdia = F.indices == frows + r0
        F.data[fdia] += removed[frows[fdia]]
        dF = np.zeros(m_s, F.data.dtype)
        dF[frows[fdia]] = F.data[fdia]
        Af[s] = F
        Dfinv[s] = np.where(dF != 0, 1.0 / np.where(dF != 0, dF, 1), 1.0)
    return Af, Dfinv, strong_masks, ucols


def _strip_mis_aggregates(strips, strong_masks, n, nloc, mesh, comm,
                          rounds=40):
    """Mesh-sharded MIS over the strip-built strength graph; coarse ids
    numbered per-owner from an exclusive prefix (locality-preserving).
    Returns (agg strips with -1 for isolated, nc)."""
    import jax
    from amgcl_tpu.coarsening.aggregates import _priority
    from amgcl_tpu.parallel.dist_ell import build_dist_ell_strips
    from amgcl_tpu.parallel.dist_mis import _compiled_mis

    nd = comm.nd
    # symmetrized strength adjacency, strip-wise: local strong pattern OR
    # its routed transpose
    pat = [None] * nd
    for s in comm.my_shards:
        S = strips[s]
        mask, rows = strong_masks[s]
        pat[s] = sp.csr_matrix(
            (np.ones(int(mask.sum()), np.int8),
             (rows[mask], S.indices[mask])), shape=S.shape)
    patT = strip_transpose(pat, nloc, nloc, (n, n), comm)
    triples = [None] * nd
    for s in comm.my_shards:
        G = ((pat[s] + patT[s]) > 0).astype(np.float32).tocsr()
        G.sort_indices()
        rows = np.repeat(np.arange(G.shape[0]), np.diff(G.indptr))
        triples[s] = (rows, G.indices.astype(np.int64), G.data)
    dS = build_dist_ell_strips(triples, mesh, (n, n), jnp.float32,
                               nloc=nloc, comm=comm)

    prio_full = _priority(n).astype(np.int32)
    prio_parts = [None] * nd
    for s in comm.my_shards:
        r0, r1 = min(s * nloc, n), min((s + 1) * nloc, n)
        p = np.zeros(dS.nloc, np.int32)
        p[: r1 - r0] = prio_full[r0:r1]
        prio_parts[s] = p
    prio_sh = put_sharded_parts(prio_parts, mesh, jnp.int32)
    fn = _compiled_mis(mesh, dS.shape, dS.nloc, dS.ncloc, int(rounds))
    from amgcl_tpu.parallel.mesh import host_full
    key_g = np.asarray(host_full(fn(dS, prio_sh)))

    # Coarse numbering: every process derives the same global cid map from
    # the (allgathered) MIS keys — O(n) ints, the same cost class as the
    # priority permutation itself. Roots (key == own priority) are numbered
    # per-owner contiguous, so coarse blocks stay aligned with the fine
    # blocks that produced them; captured rows adopt their root's cid via
    # the priority-inverse.
    inv = np.empty(n, np.int64)
    inv[prio_full - 1] = np.arange(n)
    keyv = key_g[: nd * dS.nloc].reshape(nd, dS.nloc)
    cid_full = np.full(n, -1, np.int64)
    nc = 0
    for s in range(nd):
        r0, r1 = min(s * nloc, n), min((s + 1) * nloc, n)
        k = keyv[s, : r1 - r0]
        roots = (k == prio_full[r0:r1]) & (k > 0)
        idx = np.flatnonzero(roots) + r0
        cid_full[idx] = nc + np.arange(len(idx))
        nc += len(idx)
    agg = [None] * nd
    for s in comm.my_shards:
        r0, r1 = min(s * nloc, n), min((s + 1) * nloc, n)
        k = keyv[s, : r1 - r0]
        root_row = inv[np.maximum(k, 1) - 1]
        agg[s] = np.where(k > 0, cid_full[root_row], -1).astype(np.int64)
    return agg, int(nc)


def _strip_sa_level(strips, n, nloc, mesh, comm, eps, relax,
                    mis_rounds=40, smooth=True, ac_scale=1.0):
    """One aggregation level on strips: (P_strips, Ac_strips, nc, nloc_c).
    ``smooth=True`` is smoothed aggregation (P = (I - w D^-1 Af) P_tent,
    Gershgorin omega); ``smooth=False`` is plain aggregation (P = P_tent,
    ``ac_scale`` applies the reference's 1/over_interp Galerkin scaling,
    aggregation.hpp:71-160). R is NOT formed here — between two sharded
    levels the caller transposes P (strip_transpose); at the
    replicated-tail boundary the local S.T suffices (TransitionOps), so a
    distributed transpose there would be wasted traffic.

    Mirrors the serial policies + galerkin exactly (same strength filter,
    same omega, same MIS — iteration counts match the serial device_mis
    build up to a permutation of coarse unknowns)."""
    nd = comm.nd
    Af, Dfinv, strong_masks, ucols = _strip_filtered(
        strips, nloc, eps, comm, need_filtered=smooth)
    agg, nc = _strip_mis_aggregates(strips, strong_masks, n, nloc, mesh,
                                    comm, mis_rounds)
    if nc == 0:
        raise CoarseningStall("empty coarse level (all rows isolated)")
    nloc_c = -(-nc // nd)

    P_strips = [None] * nd
    if smooth:
        # omega = relax * 4/3 / rho(Df^-1 Af), Gershgorin
        # (builtin.hpp:775-820)
        rho_loc = [None] * nd
        for s in comm.my_shards:
            absrow = np.asarray(np.abs(Af[s]).sum(axis=1)).ravel()
            rho_loc[s] = float(np.max(np.abs(Dfinv[s]) * absrow)) \
                if len(absrow) else 0.0
        rho = comm.max_scalar(rho_loc)
        omega = relax * (4.0 / 3.0) / max(rho, 1e-30)

        # P strip: row i of (I - omega Df^-1 Af) P_tent. P_tent[j] =
        # e_{agg_j} for agg_j >= 0, so P entries come straight from Af:
        # coef_ij = delta_ij - omega * Dfinv_i * Af_ij, col = agg_j.
        agg_cols = [None] * nd
        for s in comm.my_shards:
            F = Af[s]
            agg_cols[s] = np.unique(F.indices) if F.nnz \
                else np.zeros(0, np.int64)
        agg_j_per = comm.fetch_vals(agg, nloc, agg_cols)
        for s in comm.my_shards:
            F = Af[s]
            r0 = s * nloc
            m_s = F.shape[0]
            rows = np.repeat(np.arange(m_s), np.diff(F.indptr))
            aj = agg_j_per[s][np.searchsorted(agg_cols[s], F.indices)] \
                if F.nnz else np.zeros(0, np.int64)
            coef = -omega * Dfinv[s][rows] * F.data
            coef = coef + (F.indices == rows + r0)  # the identity term
            live = aj >= 0
            Pm = sp.coo_matrix(
                (coef[live], (rows[live], aj[live])),
                shape=(m_s, nc)).tocsr()
            Pm.sum_duplicates()
            Pm.sort_indices()
            P_strips[s] = Pm
    else:
        # plain aggregation: P_tent rows are unit vectors at the row's
        # aggregate — strictly strip-local
        for s in comm.my_shards:
            a = agg[s]
            live = np.flatnonzero(a >= 0)
            Pm = sp.coo_matrix(
                (np.ones(len(live)), (live, a[live])),
                shape=(len(a), nc)).tocsr()
            Pm.sort_indices()
            P_strips[s] = Pm

    # Ac = P^T (A P): local product per strip, triples routed to the coarse
    # owner (this is the distributed Galerkin SpGEMM,
    # distributed_matrix.hpp:856-1066 + mpi/amg.hpp:163-330)
    AP = strip_spgemm(strips, P_strips, nloc, comm)
    buckets = [None] * nd
    for s in comm.my_shards:
        L = (P_strips[s].T.tocsr() @ AP[s]).tocoo()   # (nc, nc) local part
        dst = np.minimum(L.row // nloc_c, nd - 1)
        bk = []
        for d in range(nd):
            sel = dst == d
            bk.append((L.row[sel], L.col[sel], L.data[sel]))
        buckets[s] = bk
    recv = comm.alltoall(buckets)
    Ac_strips = [None] * nd
    for d in comm.my_shards:
        r0, r1 = min(d * nloc_c, nc), min((d + 1) * nloc_c, nc)
        rr = np.concatenate([np.asarray(t[0]) for t in recv[d]])
        cc = np.concatenate([np.asarray(t[1]) for t in recv[d]])
        vv = np.concatenate([np.asarray(t[2]) for t in recv[d]])
        if ac_scale != 1.0:
            vv = vv * ac_scale
        Ac = sp.coo_matrix((vv, (rr - r0, cc)),
                           shape=(r1 - r0, nc)).tocsr()
        Ac.sum_duplicates()
        Ac.sort_indices()
        Ac_strips[d] = Ac
    return P_strips, Ac_strips, nc, nloc_c


# ===========================================================================
# smoothers + hierarchy assembly
# ===========================================================================

def _strip_smoother(relax, strips, n, nloc, mesh, comm, dtype):
    """Strip-local DistSmoother state: the row-local families plus
    SPAI-1 (whose Gram rows come from the same remote-row fetch the
    SpGEMM uses). The truly global factorizations (ilu*, gauss_seidel)
    need the assembled matrix and are served by the serial-build
    DistAMGSolver."""
    from amgcl_tpu.parallel.dist_amg import DistSmoother
    from amgcl_tpu.relaxation.spai0 import Spai0
    from amgcl_tpu.relaxation.jacobi import DampedJacobi
    from amgcl_tpu.relaxation.chebyshev import Chebyshev
    from amgcl_tpu.relaxation.spai1 import Spai1

    nd = comm.nd

    def parts_of(vec_strips, fill=0.0):
        host_dt = np.result_type(
            *([np.asarray(vec_strips[s]).dtype for s in comm.my_shards]
              + [np.float64]))
        out = [None] * nd
        for s in comm.my_shards:
            p = np.full(nloc, fill, host_dt)
            v = vec_strips[s]
            p[:len(v)] = v
            out[s] = p
        return put_sharded_parts(out, mesh, dtype)

    def invsafe(d):
        return np.where(d != 0, 1.0 / np.where(d != 0, d, 1), 1.0)

    if isinstance(relax, Spai0):
        # m_i = a_ii / sum_j |a_ij|^2 (spai0.hpp:49-117) — row-local
        dia = _strip_diag(strips, nloc, comm.my_shards)
        sc = [None] * nd
        for s in comm.my_shards:
            S = strips[s]
            rows = np.repeat(np.arange(S.shape[0]), np.diff(S.indptr))
            denom = np.bincount(rows, weights=(np.abs(S.data) ** 2).real,
                                minlength=S.shape[0])
            sc[s] = dia[s] / np.where(denom != 0, denom, 1.0)
        return DistSmoother("diag", parts_of(sc))
    if isinstance(relax, DampedJacobi):
        dia = _strip_diag(strips, nloc, comm.my_shards)
        sc = [None if dia[s] is None else relax.damping * invsafe(dia[s])
              for s in range(nd)]
        return DistSmoother("diag", parts_of(sc))
    if isinstance(relax, Chebyshev):
        if relax.power_iters:
            raise ValueError(
                "strip setup supports Gershgorin chebyshev only "
                "(power_iters=0)")
        dia = _strip_diag(strips, nloc, comm.my_shards) if relax.scale \
            else None
        loc = [None] * nd
        for s in comm.my_shards:
            absrow = np.asarray(np.abs(strips[s]).sum(axis=1)).ravel()
            if relax.scale:
                absrow = np.abs(invsafe(dia[s])) * absrow
            loc[s] = float(absrow.max()) if len(absrow) else 0.0
        rho = comm.max_scalar(loc)
        a, b = rho * relax.lower, rho
        dinv_sh = None
        if relax.scale:
            dinv_sh = parts_of(
                [None if d is None else invsafe(d) for d in dia])
        return DistSmoother("cheb", dinv_sh, theta=(a + b) / 2,
                            delta=(b - a) / 2, degree=relax.degree)
    if isinstance(relax, Spai1):
        # row-wise least squares over A's pattern (spai1.hpp:54): row i's
        # normal equations need B = A A^T restricted to J_i x J_i — every
        # needed A row is in this strip's column set, so ONE remote-row
        # fetch serves the whole Gram block. Same padded batched solve as
        # the serial build — per-row results are identical.
        from amgcl_tpu.relaxation.spai1 import (gather_sparse_entries,
                                                padded_pattern,
                                                pattern_normal_solve)
        ucols = [None] * nd
        for s in comm.my_shards:
            S = strips[s]
            ucols[s] = np.unique(S.indices) if S.nnz \
                else np.zeros(0, np.int64)
        Rsub = comm.fetch_rows(strips, nloc, ucols)
        M_strips = [None] * nd
        for s in comm.my_shards:
            S = strips[s]          # only the pattern is read; values come
            m_s = S.shape[0]       # from the fetched rows R
            if S.nnz == 0:
                M_strips[s] = sp.csr_matrix(S.shape)
                continue
            R = Rsub[s].astype(np.float64)   # rows ucols[s] of A
            posJ = np.searchsorted(ucols[s], S.indices)
            Jp, valid, rows, pos, K = padded_pattern(S.indptr, posJ)
            B = (R @ R.T).tocsr()            # strip-local Gram
            # rhs c[i, k] = A[J_ik, i_global] = R[posJ_ik, r0 + i]
            gcols = np.repeat(s * nloc + np.arange(m_s), K)
            c = gather_sparse_entries(R, Jp.ravel(), gcols).reshape(m_s, K)
            mvals = pattern_normal_solve(Jp, valid, B, c)
            M_strips[s] = sp.csr_matrix(
                (mvals[rows, pos], S.indices.copy(), S.indptr.copy()),
                shape=S.shape)
        Msp = _strips_to_dist_ell(M_strips, mesh, (n, n), dtype, nloc,
                                  nloc, comm)
        return DistSmoother("spai1", Msp=Msp)
    raise ValueError(
        "smoother %s has no strip-parallel build; use spai0/damped_jacobi/"
        "chebyshev/spai1, or the serial-build DistAMGSolver for ilu/gs"
        % type(relax).__name__)


def _strips_to_dist_ell(strips, mesh, shape, dtype, nloc, ncloc, comm):
    from amgcl_tpu.parallel.dist_ell import build_dist_ell_strips
    triples = [None] * comm.nd
    for s in comm.my_shards:
        S = strips[s]
        rows = np.repeat(np.arange(S.shape[0]), np.diff(S.indptr))
        triples[s] = (rows, S.indices.astype(np.int64), S.data)
    return build_dist_ell_strips(triples, mesh, shape, dtype, nloc, ncloc,
                                 comm=comm)


def _gather_strips(strips, shape, nloc, comm):
    """Assemble strips into one host CSR (used ONLY at the replicated-tail
    boundary, where the level is already small). Under multi-controller
    the tail triples are allgathered through the public comm interface —
    every process then runs the same replicated serial build."""
    nd = comm.nd
    rr = [None] * nd
    cc = [None] * nd
    vv = [None] * nd
    for s in comm.my_shards:
        S = strips[s].tocoo()
        rr[s] = S.row.astype(np.int64) + s * nloc
        cc[s] = S.col.astype(np.int64)
        vv[s] = S.data
    rr = comm.allgather_concat(rr)
    cc = comm.allgather_concat(cc)
    vv = comm.allgather_concat(vv)
    M = sp.coo_matrix((vv, (rr, cc)), shape=shape).tocsr()
    M.sum_duplicates()
    M.sort_indices()
    return CSR(M.indptr.astype(np.int64), M.indices.astype(np.int32),
               M.data, shape[1])


def strip_sa_hierarchy(strips, n, mesh, prm, comm=None,
                       replicate_below: int = 4096, mis_rounds: int = 40,
                       max_sharded_levels: int = 30, precond_dtype=None,
                       rep_rowshard: bool = False):
    """Build the distributed hierarchy from row strips. Returns
    (DistHierarchy, level_sizes, stats). No global matrix is ever
    assembled while levels stay sharded; the replicated tail (below
    ``replicate_below`` rows) is gathered and built serially, as
    DistAMGSolver does."""
    from amgcl_tpu.coarsening.smoothed_aggregation import \
        SmoothedAggregation
    from amgcl_tpu.models.amg import AMG, Hierarchy as SerialHierarchy
    from amgcl_tpu.parallel.dist_amg import (DistLevel, DistHierarchy,
                                             TransitionOps)

    nd = mesh.shape[ROWS_AXIS]
    if comm is None:
        import jax
        comm = MultihostComm(mesh) if jax.process_count() > 1 \
            else LocalComm(nd)
    from amgcl_tpu.coarsening.aggregation import Aggregation
    c = prm.coarsening
    if isinstance(c, SmoothedAggregation):
        smooth, ac_scale = True, 1.0
        if c.power_iters:
            raise ValueError("strip setup uses the Gershgorin omega "
                             "(power_iters=0)")
    elif isinstance(c, Aggregation):
        smooth, ac_scale = False, 1.0 / float(c.over_interp)
    else:
        raise ValueError("strip setup implements smoothed_aggregation "
                         "and aggregation; got %s" % type(c).__name__)
    if c.nullspace is not None or c.block_size != 1:
        raise ValueError("strip setup supports scalar aggregation only "
                         "(no nullspace, block_size=1)")
    if c.aggregator is not None:
        raise ValueError(
            "strip setup always aggregates with its own mesh-sharded MIS;"
            " a custom aggregator hook would be silently ignored — drop "
            "it or use the serial-build DistAMGSolver")
    dtype = precond_dtype or prm.dtype   # sharded operator dtype
    strips0, nloc0, n0 = strips, -(-n // nd), n   # finest level, for top_A
    eps = float(c.eps_strong)
    nloc = -(-n // nd)
    sizes = [n]
    levels = []

    def owned_peak(ss):
        return max((ss[s].nnz for s in comm.my_shards), default=0)

    stats = {"peak_strip_nnz": owned_peak(strips),
             "level_strip_nnz": []}

    while (n >= replicate_below and n > prm.coarse_enough
           and len(levels) + 1 < prm.max_levels
           and len(levels) < max_sharded_levels):
        try:
            P_s, Ac_s, nc, nloc_c = _strip_sa_level(
                strips, n, nloc, mesh, comm, eps,
                getattr(c, "relax", 1.0), mis_rounds,
                smooth=smooth, ac_scale=ac_scale)
        except CoarseningStall:
            break       # coarsening stalled: serial build breaks too
            # (any OTHER error propagates — a silent truncation here would
            # masquerade as a performance regression)
        if nc >= n:
            break
        dA = _strips_to_dist_ell(strips, mesh, (n, n), dtype, nloc, nloc,
                                 comm)
        sm = _strip_smoother(prm.relax, strips, n, nloc, mesh, comm, dtype)
        levels.append([dA, sm, P_s, nloc, n])
        stats["level_strip_nnz"].append(owned_peak(strips))
        stats["peak_strip_nnz"] = max(stats["peak_strip_nnz"],
                                      owned_peak(Ac_s))
        strips, n, nloc = Ac_s, nc, nloc_c
        eps *= 0.5
        sizes.append(n)

    # wire DistLevels: P/R between consecutive SHARDED levels become
    # DistEllMatrix; the last sharded level's P/R become TransitionOps
    dist_levels = []
    for k, (dA, sm, P_s, nloc_k, n_k) in enumerate(levels):
        dP = dR = None
        if k + 1 < len(levels):
            nloc_next = levels[k + 1][3]
            n_next = levels[k + 1][4]
            dP = _strips_to_dist_ell(P_s, mesh, (n_k, n_next), dtype,
                                     nloc_k, nloc_next, comm)
            R_s = strip_transpose(P_s, nloc_k, nloc_next, (n_next, n_k),
                                  comm)
            dR = _strips_to_dist_ell(R_s, mesh, (n_next, n_k), dtype,
                                     nloc_next, nloc_k, comm)
        dist_levels.append(DistLevel(dA, dP, dR, sm))

    # replicated serial tail from the gathered coarse strips
    prm_tail = copy.copy(prm)
    prm_tail.coarsening = copy.deepcopy(c)
    prm_tail.coarsening.eps_strong = eps
    # the user's depth bound covers sharded + replicated levels together
    prm_tail.max_levels = max(prm.max_levels - len(levels), 1)
    A_tail = _gather_strips(strips, (n, n), nloc, comm)
    rep_amg = AMG(A_tail, prm_tail)
    rep = SerialHierarchy(rep_amg.hierarchy.levels,
                          rep_amg.hierarchy.coarse,
                          prm.npre, prm.npost, prm.ncycle, 1)

    top_A = None
    trans = None
    if levels:
        # TransitionOps strip-wise: P rows are already fine-partitioned;
        # R per shard = (P strip)^T — column-restricted by construction
        _, _, P_s, nloc_b, n_b = levels[-1]
        K1 = max(1, int(comm.max_scalar(
            [None if P_s[s] is None else
             (int(np.diff(P_s[s].indptr).max()) if P_s[s].nnz else 0)
             for s in range(nd)])))
        K2 = max(1, int(comm.max_scalar(
            [None if P_s[s] is None else
             (int((P_s[s].T.tocsr()).getnnz(axis=1).max())
              if P_s[s].nnz else 0) for s in range(nd)])))
        pc_parts = [None] * nd
        pv_parts = [None] * nd
        rc_parts = [None] * nd
        rv_parts = [None] * nd
        from amgcl_tpu.parallel.dist_ell import pack_rows_ell
        for s in comm.my_shards:
            S = P_s[s]
            rows = np.repeat(np.arange(S.shape[0]), np.diff(S.indptr))
            pc_parts[s], pv_parts[s] = pack_rows_ell(
                rows, S.indices, S.data, nloc_b, K1)
            T = S.T.tocsr()
            trows = np.repeat(np.arange(T.shape[0]), np.diff(T.indptr))
            rc_parts[s], rv_parts[s] = pack_rows_ell(
                trows, T.indices, T.data, n, K2)
        put = lambda parts, dt: put_sharded_parts(parts, mesh, dt)
        trans = TransitionOps(put(pc_parts, jnp.int32),
                              put(pv_parts, dtype),
                              put(rc_parts, jnp.int32),
                              put(rv_parts, dtype))
    else:
        # no sharded levels: top_A is only the Krylov operator — always
        # solver precision (the preconditioner runs through `rep`)
        top_A = _strips_to_dist_ell(strips, mesh, (n, n), prm.dtype, nloc,
                                    nloc, comm)

    if dist_levels and jnp.dtype(dtype) != jnp.dtype(prm.dtype):
        # mixing.hpp seam: the Krylov loop tracks a solver-precision
        # system matrix; the narrowed operators serve only the cycle
        top_A = _strips_to_dist_ell(strips0, mesh, (n0, n0), prm.dtype,
                                    nloc0, nloc0, comm)
    hier = DistHierarchy(dist_levels, rep, trans, top_A, prm.npre,
                         prm.npost, prm.ncycle, prm.pre_cycles,
                         rep_rowshard=rep_rowshard)
    return hier, sizes, stats


class StripAMGSolver:
    """mpi::make_solver with a DISTRIBUTED setup: the hierarchy is built
    strip-parallel (strip_sa_hierarchy) and solved with the same SPMD
    program as DistAMGSolver. Accepts either a whole matrix (split
    in-process) or pre-split per-shard strips (multi-host ingestion:
    no process ever holds the global matrix)."""

    def __init__(self, A_or_strips, mesh, prm: Optional[Any] = None,
                 solver: Any = None, n: Optional[int] = None,
                 replicate_below: int = 4096, comm=None,
                 mis_rounds: int = 40, precond_dtype=None,
                 rep_rowshard: bool = False):
        import jax
        from amgcl_tpu.models.amg import AMGParams
        self.mesh = mesh
        self.prm = prm or AMGParams()
        from amgcl_tpu.solver.cg import CG
        self.solver = solver or CG()
        nd = mesh.shape[ROWS_AXIS]
        if comm is None:
            comm = MultihostComm(mesh) if jax.process_count() > 1 \
                else LocalComm(nd)
        if isinstance(A_or_strips, (list, tuple)):
            strips = list(A_or_strips)
            if n is None:
                raise ValueError("pass n= (global rows) with strips")
            if len(strips) != nd:
                raise ValueError(
                    "need one strip slot per mesh device (None for "
                    "shards owned by other processes)")
            # the whole strip algebra assumes the ceil(n/nd) row blocks of
            # build_dist_ell (owner = row // nloc); a floor-based MPI-style
            # split would silently misalign every diagonal and halo plan
            nloc0 = -(-int(n) // nd)
            for s in comm.my_shards:
                S = strips[s]
                if S is None:
                    raise ValueError("strip %d is owned by this process "
                                     "but is None" % s)
                want = min((s + 1) * nloc0, int(n)) - min(s * nloc0, int(n))
                if S.shape[0] != want:
                    raise ValueError(
                        "strip %d has %d rows; the ceil(n/nd) partition "
                        "requires %d (rows [%d, %d)) — re-split with "
                        "split_strips' convention"
                        % (s, S.shape[0], want, min(s * nloc0, int(n)),
                           min((s + 1) * nloc0, int(n))))
        else:
            strips, _ = split_strips(A_or_strips, nd)
            n = sum(S.shape[0] for S in strips)
            if len(comm.my_shards) != nd:
                strips = [strips[s] if s in set(comm.my_shards) else None
                          for s in range(nd)]
        self.hier, self.sizes, self.stats = strip_sa_hierarchy(
            strips, n, mesh, self.prm, comm=comm,
            replicate_below=replicate_below, mis_rounds=mis_rounds,
            precond_dtype=precond_dtype, rep_rowshard=rep_rowshard)
        self.n = int(n)
        first_A = self.hier.levels[0].A if self.hier.levels \
            else self.hier.top_A
        self.n_pad = first_A.nloc * nd
        self._compiled = None

    # the compiled SPMD solve program is identical to the serial-setup one
    def _build_compiled(self):
        from amgcl_tpu.parallel.dist_amg import DistAMGSolver
        return DistAMGSolver._build_compiled(self)

    def __call__(self, rhs, x0=None):
        from amgcl_tpu.parallel.dist_amg import DistAMGSolver
        return DistAMGSolver.__call__(self, rhs, x0)

    # ... and so is the resource ledger __call__ attaches to the report
    # (hier/prm/mesh carry everything the comm/memory models read)
    def resource_ledger(self):
        from amgcl_tpu.parallel.dist_amg import DistAMGSolver
        return DistAMGSolver.resource_ledger(self)

    def __repr__(self):
        lines = ["StripAMGSolver over %d devices (strip-parallel setup)"
                 % self.mesh.shape[ROWS_AXIS]]
        for i, m in enumerate(self.sizes):
            lines.append("%5d %12d" % (i, m))
        return "\n".join(lines)
