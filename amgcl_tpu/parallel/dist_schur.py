"""Distributed Schur pressure correction (reference:
amgcl/mpi/schur_pressure_correction.hpp).

The u/p field split over a sharded vector is expressed with selection
matrices S_u (nu x n) and S_p (np x n) — one entry per row — distributed as
ordinary :class:`DistEllMatrix` operators: applying them IS the
redistribution (the general all_to_all halo plan does the data movement),
and their transposes scatter the fields back. The two inner solves are full
distributed AMG hierarchies; the off-diagonal couplings are sharded
rectangular operators. Everything composes inside one shard_map program.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.parallel.mesh import ROWS_AXIS
from amgcl_tpu.parallel.dist_ell import build_dist_ell
from amgcl_tpu.parallel.dist_amg import DistAMGSolver


@register_pytree_node_class
class DistSchurHierarchy:
    def __init__(self, A_full, Su, Sp, SuT, SpT, Kup, Kpu, u_hier, p_hier):
        self.A_full = A_full
        self.Su = Su
        self.Sp = Sp
        self.SuT = SuT
        self.SpT = SpT
        self.Kup = Kup
        self.Kpu = Kpu
        self.u_hier = u_hier
        self.p_hier = p_hier

    def tree_flatten(self):
        return ((self.A_full, self.Su, self.Sp, self.SuT, self.SpT,
                 self.Kup, self.Kpu, self.u_hier, self.p_hier), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def specs(self):
        return DistSchurHierarchy(
            self.A_full.specs(), self.Su.specs(), self.Sp.specs(),
            self.SuT.specs(), self.SpT.specs(), self.Kup.specs(),
            self.Kpu.specs(), self.u_hier.specs(), self.p_hier.specs())

    def shard_apply(self, r):
        fu = self.Su.shard_mv(r)
        fp = self.Sp.shard_mv(r)
        u1 = self.u_hier.shard_apply(fu)
        p = self.p_hier.shard_apply(fp - self.Kpu.shard_mv(u1))
        u = self.u_hier.shard_apply(fu - self.Kup.shard_mv(p))
        return self.SuT.shard_mv(u) + self.SpT.shard_mv(p)

    def system_A(self):
        return self.A_full


def _selection(indices: np.ndarray, n: int) -> CSR:
    """Rows pick the listed global entries: S[i, indices[i]] = 1."""
    k = len(indices)
    return CSR(np.arange(k + 1, dtype=np.int64),
               indices.astype(np.int32), np.ones(k), n)


class DistSchurSolver(DistAMGSolver):
    """Distributed Krylov with the Schur pressure correction."""

    def __init__(self, A, mesh, pmask, usolver_prm: Optional[AMGParams] = None,
                 psolver_prm: Optional[AMGParams] = None,
                 solver: Any = None, simplec_dia: bool = True,
                 adjust_p: int = 2, dtype=jnp.float32):
        """``adjust_p`` picks the matrix the pressure hierarchy is built on
        (reference: schur_pressure_correction.hpp:443-496): 0 = Kpp,
        1 = Kpp − dia(Kpu M Kup), 2 = Kpp − Kpu M Kup (full product —
        the historical default here; the distributed psolve is a single
        AMG cycle, so the build matrix IS the p-side operator)."""
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        if adjust_p not in (0, 1, 2):
            raise ValueError("adjust_p must be 0, 1 or 2")
        pmask = np.asarray(pmask, dtype=bool)
        if pmask.shape != (A.nrows,) or not pmask.any() or pmask.all():
            raise ValueError("pmask must split the rows into two nonempty "
                             "fields")
        self.mesh = mesh
        self.solver = solver or CG()
        nd = mesh.shape[ROWS_AXIS]
        from types import SimpleNamespace
        self.prm = SimpleNamespace(dtype=dtype)

        m = A.to_scipy()
        ui = np.flatnonzero(~pmask)
        pi = np.flatnonzero(pmask)
        Kuu = CSR.from_scipy(m[ui][:, ui].tocsr())
        Kup = CSR.from_scipy(m[ui][:, pi].tocsr())
        Kpu = CSR.from_scipy(m[pi][:, ui].tocsr())
        Kpp = CSR.from_scipy(m[pi][:, pi].tocsr())
        from amgcl_tpu.models.schur import kuu_dinv, schur_pressure_build
        dinv = kuu_dinv(Kuu, simplec_dia)
        Sm, _ = schur_pressure_build(
            Kpp.to_scipy(), Kpu.to_scipy(), Kup.to_scipy(), dinv, adjust_p)
        S = CSR.from_scipy(Sm)

        self.u_solver = DistAMGSolver(Kuu, mesh,
                                      usolver_prm or AMGParams(dtype=dtype))
        self.p_solver = DistAMGSolver(S, mesh,
                                      psolver_prm or AMGParams(dtype=dtype))

        self.n = A.nrows
        dA = build_dist_ell(A, mesh, dtype)
        self.n_pad = dA.nloc * nd
        nu_pad = self.u_solver.n_pad
        np_pad = self.p_solver.n_pad

        # selection matrices, padded to the partitions on both sides
        Su = _selection(ui, self.n_pad)
        Su.ptr = np.concatenate(
            [Su.ptr, np.full(nu_pad - len(ui), Su.ptr[-1])])
        Sp = _selection(pi, self.n_pad)
        Sp.ptr = np.concatenate(
            [Sp.ptr, np.full(np_pad - len(pi), Sp.ptr[-1])])
        # transposes of the padded selections are already (n_pad, nu_pad)
        # and (n_pad, np_pad)
        SuT = CSR.from_scipy(Su.to_scipy().T.tocsr())
        SpT = CSR.from_scipy(Sp.to_scipy().T.tocsr())

        # pad off-diagonal couplings to the u/p partitions
        def pad_rect(M, rows_to, cols_to):
            out = M.copy()
            out.ptr = np.concatenate(
                [out.ptr, np.full(rows_to - out.nrows, out.ptr[-1])])
            out.ncols = cols_to
            return out

        self.hier = DistSchurHierarchy(
            dA,
            build_dist_ell(Su, mesh, dtype),
            build_dist_ell(Sp, mesh, dtype),
            build_dist_ell(SuT, mesh, dtype),
            build_dist_ell(SpT, mesh, dtype),
            build_dist_ell(pad_rect(Kup, nu_pad, np_pad), mesh, dtype),
            build_dist_ell(pad_rect(Kpu, np_pad, nu_pad), mesh, dtype),
            self.u_solver.hier, self.p_solver.hier)
        self._compiled = None

    def __repr__(self):
        return ("DistSchurSolver over %d devices\n[U]\n%r\n[P]\n%r"
                % (self.mesh.shape[ROWS_AXIS], self.u_solver.host_amg,
                   self.p_solver.host_amg))
