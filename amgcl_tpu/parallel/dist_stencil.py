"""Mesh-sharded stencil setup and solve: distributed hierarchy CONSTRUCTION.

The round-2 review's core distributed gap: the hierarchy was built serially
on one host and then sharded (reference builds it distributed —
amgcl/mpi/amg.hpp:163-330 with distributed SpGEMM,
amgcl/mpi/distributed_matrix.hpp:856-1066). For stencil problems the
device setup (ops/stencil_device.py) is already expressed as per-diagonal
streaming passes with STATIC shifts — exactly the shape `shard_map` wants:

- rows are sharded in contiguous z-slabs over the mesh's ``rows`` axis;
- every static shift becomes a ring halo exchange (``lax.ppermute`` of the
  slab edges — zero-filled at the global boundary, matching the serial
  zero-fill shift semantics);
- the Gershgorin bound and strength counts become ``pmax``/``psum``;
- the pair-product scans and the tentative parity collapse are unchanged
  (the collapse is position-local because slab boundaries align with the
  2× aggregation blocks);
- per-level, each shard holds only its slab of every diagonal — per-shard
  peak memory is the serial build's divided by the mesh size.

The solve phase reuses the same slabs: smoother, residual, and transfer
applications are halo-SpMVs (parallel/dist_matrix.py pattern), the coarse
tail below the sharded levels is a replicated serial hierarchy (the
repartition-merge analogue: amgcl/mpi/partition/merge.hpp:47-137), and the
whole AMG-preconditioned CG runs as ONE shard_map'd XLA program.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops.stencil import HostDia, host_dia_from_csr, _flat
from amgcl_tpu.ops.stencil_device import (
    _MAX_DIAGS, _osum, _oneg, _product_plan, _collapse_plan, _fnma_scan)
from amgcl_tpu.parallel.mesh import ROWS_AXIS, put_with_sharding
from amgcl_tpu.parallel.dist_matrix import (dist_inner_product,
                                            dia_halo_mv as _dia_halo_mv)


def _halo_extend(arr, w):
    """(ndiag, nl) -> (ndiag, nl + 2w): ring halo over the rows axis;
    boundary shards see zeros (global zero-fill shift semantics)."""
    if w == 0:
        return arr
    nd = lax.axis_size(ROWS_AXIS)
    if nd == 1:
        return jnp.pad(arr, ((0, 0), (w, w)))
    fwd = [(i, i + 1) for i in range(nd - 1)]
    bwd = [(i + 1, i) for i in range(nd - 1)]
    prev_tail = lax.ppermute(arr[:, -w:], ROWS_AXIS, fwd)
    next_head = lax.ppermute(arr[:, :w], ROWS_AXIS, bwd)
    return jnp.concatenate([prev_tail, arr, next_head], axis=1)




# -- sharded per-level setup program -----------------------------------------

def _sharded_level_setup(adata_l, eps_strong, relax_scale, smoother_omega,
                         offs, gdims, lz, blocks, coarse, relax_kind):
    """One hierarchy level on the mesh (runs INSIDE shard_map). Mirrors
    ops/stencil_device._level_setup with halo shifts and psum/pmax
    reductions. adata_l: (ndiag, nl) local slab; gdims global; lz local
    z-planes. Returns (m_l, mt_l, ac_l, scale_l, counts, axis_strong)."""
    d2, d1, d0 = gdims
    nl = adata_l.shape[1]
    dt = adata_l.dtype
    offs = list(offs)
    eps2 = (eps_strong * eps_strong).astype(dt)

    flats = [_flat(o, gdims) for o in offs]
    hmax = max(max(abs(f) for f in flats), 1)

    main_k = offs.index((0, 0, 0)) if (0, 0, 0) in offs else None
    dia = jnp.abs(adata_l[main_k]) if main_k is not None \
        else jnp.zeros((nl,), dt)
    dia_ext = _halo_extend(dia[None], hmax)[0]
    af_rows = [None] * len(offs)
    lump = jnp.zeros((nl,), dt)
    for k, o in enumerate(offs):
        if k == main_k:
            continue
        a = adata_l[k]
        dj = lax.dynamic_slice(dia_ext, (hmax + flats[k],), (nl,))
        strong = (a * a) > (eps2 * dia * dj)
        af_rows[k] = jnp.where(strong, a, dt.type(0))
        lump = lump + jnp.where(strong, dt.type(0), a)
    main = (adata_l[main_k] if main_k is not None
            else jnp.zeros((nl,), dt)) + lump
    if main_k is not None:
        af_rows[main_k] = main
        af_offs = list(offs)
    else:
        af_rows.append(main)
        af_offs = list(offs) + [(0, 0, 0)]
    af = jnp.stack(af_rows)
    dinv = jnp.where(main != 0, 1.0 / jnp.where(main != 0, main, 1),
                     1.0).astype(dt)

    axis_strong = []
    for ax in range(3):
        tot = jnp.zeros((), jnp.float32)
        for k, o in enumerate(af_offs):
            if [i for i, c in enumerate(o) if c != 0] == [ax]:
                tot = tot + jnp.count_nonzero(af[k]).astype(jnp.float32)
        axis_strong.append(lax.psum(tot, ROWS_AXIS))
    axis_strong = jnp.stack(axis_strong)

    rho = lax.pmax(
        jnp.max(jnp.abs(dinv) * jnp.sum(jnp.abs(af), axis=0)), ROWS_AXIS)
    omega = (relax_scale.astype(dt) * dt.type(4.0 / 3.0)
             / jnp.maximum(rho, dt.type(1e-30)))

    m = af * (dinv * omega)[None, :]
    af_flats = [_flat(o, gdims) for o in af_offs]
    hm = max(max(abs(f) for f in af_flats), 1)
    m_ext = _halo_extend(m, hm)
    mt = jnp.stack([
        lax.dynamic_slice(m_ext, (k, hm + _flat(_oneg(o), gdims)),
                          (1, nl))[0]
        for k, o in enumerate(af_offs)])
    mt_offs = [_oneg(o) for o in af_offs]

    # X = A - A·M ; S = X - Mt·X (scan pair products over halo'd sources)
    x_offs, _, _ = _product_plan(offs, af_offs, gdims)
    x_idx = {o: k for k, o in enumerate(x_offs)}
    a_slots = np.asarray([x_idx[o] for o in offs], np.int32)
    X = jnp.zeros((len(x_offs), nl), dt).at[a_slots].set(adata_l)
    x_pairs = [(ka, kb, _flat(oa, gdims), x_idx[_osum(oa, ob)])
               for ka, oa in enumerate(offs)
               for kb, ob in enumerate(af_offs)]
    pad_m = max(max(abs(p[2]) for p in x_pairs), 1)
    X = _fnma_scan(X, adata_l, _halo_extend(m, pad_m), x_pairs, pad_m, nl)

    s_offs, s_embed, s_pairs = _product_plan(mt_offs, x_offs, gdims)
    S = jnp.zeros((len(s_offs), nl), dt) \
        .at[np.asarray(s_embed, np.int32)].set(X)
    pad_x = max(max(abs(p[2]) for p in s_pairs), 1)
    S = _fnma_scan(S, mt, _halo_extend(X, pad_x), s_pairs, pad_x, nl)

    # collapse on the LOCAL slab (aligned with the 2x z-blocks)
    c_offs, parities, table = _collapse_plan(s_offs, gdims, blocks, coarse)
    b2, b1, b0 = blocks
    c2, c1, c0 = coarse
    lcz = lz // b2 if b2 > 1 else lz
    dims_p = (lcz * b2, c1 * b1, c0 * b0)
    n_cl = lcz * c1 * c0
    acc0 = jnp.zeros((len(c_offs), n_cl), dt)

    def cbody(acc, inp):
        row, slots = inp
        v3 = row.reshape(lz, d1, d0)
        if dims_p != (lz, d1, d0):
            v3 = jnp.pad(v3, ((0, dims_p[0] - lz), (0, dims_p[1] - d1),
                              (0, dims_p[2] - d0)))
        for j, (pz, py, px) in enumerate(parities):
            sl = v3[pz::b2, py::b1, px::b0].reshape(-1)
            acc = acc.at[slots[j]].add(sl)
        return acc, None

    ac_l, _ = lax.scan(cbody, acc0, (S, jnp.asarray(table)))
    counts = lax.psum(
        jnp.sum(ac_l != 0, axis=1).astype(jnp.int32), ROWS_AXIS)

    d0v = adata_l[main_k] if main_k is not None else jnp.ones((nl,), dt)
    if relax_kind == "spai0":
        denom = jnp.sum(adata_l * adata_l, axis=0)
        scale = d0v / jnp.where(denom != 0, denom, 1)
    else:
        scale = smoother_omega.astype(dt) * jnp.where(
            d0v != 0, 1.0 / jnp.where(d0v != 0, d0v, 1), 0.0).astype(dt)
    return m, mt, ac_l, scale, counts, axis_strong


# -- sharded hierarchy + solve -----------------------------------------------

@register_pytree_node_class
class DistStencilLevel:
    """One sharded level: local slabs of the operator/smoother/transfer
    diagonals plus the static grid plan."""

    def __init__(self, adata, scale, mdata, mtdata, a_flats, m_flats,
                 mt_flats, ldims, lcoarse, blocks):
        self.adata = adata          # (ndiag, nl) sharded
        self.scale = scale          # (nl,) sharded
        self.mdata = mdata
        self.mtdata = mtdata
        self.a_flats = tuple(a_flats)     # GLOBAL flat offsets
        self.m_flats = tuple(m_flats)
        self.mt_flats = tuple(mt_flats)
        self.ldims = tuple(ldims)         # local slab dims (lz, d1, d0)
        self.lcoarse = tuple(lcoarse)     # local coarse dims
        self.blocks = tuple(blocks)

    def tree_flatten(self):
        return ((self.adata, self.scale, self.mdata, self.mtdata),
                (self.a_flats, self.m_flats, self.mt_flats, self.ldims,
                 self.lcoarse, self.blocks))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    # tentative transfer over the local slab (GridTentative logic)
    def t_mv(self, uc):
        (lz, d1, d0), (cz, c1, c0), (b2, b1, b0) = \
            self.ldims, self.lcoarse, self.blocks
        u = uc.reshape(cz, 1, c1, 1, c0, 1)
        u = jnp.broadcast_to(u, (cz, b2, c1, b1, c0, b0))
        u = u.reshape(cz * b2, c1 * b1, c0 * b0)
        return u[:lz, :d1, :d0].reshape(-1)

    def t_rmv(self, v):
        (lz, d1, d0), (cz, c1, c0), (b2, b1, b0) = \
            self.ldims, self.lcoarse, self.blocks
        v3 = v.reshape(lz, d1, d0)
        if (cz * b2, c1 * b1, c0 * b0) != (lz, d1, d0):
            v3 = jnp.pad(v3, ((0, cz * b2 - lz), (0, c1 * b1 - d1),
                              (0, c0 * b0 - d0)))
        v6 = v3.reshape(cz, b2, c1, b1, c0, b0)
        return v6.sum(axis=(1, 3, 5)).reshape(-1)


@register_pytree_node_class
class DistStencilHierarchy:
    """Sharded stencil levels + replicated serial tail."""

    def __init__(self, levels, rep_hier, n_rep, npre=1, npost=1):
        self.levels = list(levels)
        self.rep_hier = rep_hier      # serial Hierarchy, replicated
        self.n_rep = int(n_rep)       # true rows of the replicated top
        self.npre = int(npre)
        self.npost = int(npost)

    def tree_flatten(self):
        return ((self.levels, self.rep_hier),
                (self.n_rep, self.npre, self.npost))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    def specs(self):
        specs_levels = []
        for lv in self.levels:
            specs_levels.append(DistStencilLevel(
                P(None, ROWS_AXIS), P(ROWS_AXIS), P(None, ROWS_AXIS),
                P(None, ROWS_AXIS), lv.a_flats, lv.m_flats, lv.mt_flats,
                lv.ldims, lv.lcoarse, lv.blocks))
        rep = jax.tree.map(lambda _: P(), self.rep_hier)
        return DistStencilHierarchy(specs_levels, rep, self.n_rep,
                                    self.npre, self.npost)

    def shard_cycle(self, i, f):
        if i == len(self.levels):
            # replicated tail: gather, serial hierarchy apply, slice local
            nd = lax.axis_size(ROWS_AXIS)
            idx = lax.axis_index(ROWS_AXIS)
            nl = f.shape[0]
            full = lax.all_gather(f, ROWS_AXIS, tiled=True)[:self.n_rep]
            u = self.rep_hier.apply(full)
            u = jnp.pad(u, (0, nl * nd - self.n_rep))
            return lax.dynamic_slice(u, (idx * nl,), (nl,))
        lv = self.levels[i]
        amv = partial(_dia_halo_mv, lv.adata, lv.a_flats)
        u = lv.scale * f
        for _ in range(self.npre - 1):
            u = u + lv.scale * (f - amv(u))
        r = f - amv(u)
        # restrict: fc = T^T (r - M^T r)
        t = r - _dia_halo_mv(lv.mtdata, lv.mt_flats, r)
        fc = lv.t_rmv(t)
        uc = self.shard_cycle(i + 1, fc)
        # prolong: u += (I - M) T uc
        t = lv.t_mv(uc)
        u = u + t - _dia_halo_mv(lv.mdata, lv.m_flats, t)
        for _ in range(self.npost):
            u = u + lv.scale * (f - amv(u))
        return u

    def shard_apply(self, r):
        return self.shard_cycle(0, r)


class DistStencilSolver:
    """AMG-preconditioned CG on a mesh with DISTRIBUTED hierarchy
    construction for stencil problems. ``DistStencilSolver(A, mesh, prm,
    solver)`` then ``x, info = s(rhs)``."""

    def __init__(self, A, mesh, prm=None, solver: Any = None,
                 rep_coarse_enough: int = 3000):
        from amgcl_tpu.models.amg import AMGParams
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.mesh = mesh
        self.prm = prm or AMGParams()
        self.solver = solver
        got = dist_stencil_build(A, mesh, self.prm, rep_coarse_enough)
        if got is None:
            raise ValueError(
                "matrix/config outside the sharded stencil fast path "
                "(needs a structured grid with z-extent divisible by "
                "2x mesh, scalar real f32, SA + spai0/jacobi)")
        self.hier, self.meta = got
        self.n = A.nrows
        self._compiled = None

    def __call__(self, rhs, x0=None):
        import jax.numpy as jnp
        from amgcl_tpu.models.make_solver import SolverInfo
        nd = self.mesh.shape[ROWS_AXIS]
        maxiter = getattr(self.solver, "maxiter", 100) if self.solver \
            else 100
        tol = getattr(self.solver, "tol", 1e-6) if self.solver else 1e-6
        vec = NamedSharding(self.mesh, P(ROWS_AXIS))
        rhs = np.asarray(rhs, np.float32)
        # levels[0].adata.shape is GLOBAL (the sharding is in the array's
        # layout, not its logical shape)
        rhs_p = np.pad(rhs, (0, self.hier.levels[0].adata.shape[1]
                             - len(rhs)))
        f = put_with_sharding(rhs_p, vec)
        x0p = jnp.zeros_like(f) if x0 is None else put_with_sharding(
            np.pad(np.asarray(x0, np.float32),
                   (0, len(rhs_p) - len(rhs))), vec)
        if self._compiled is None:
            hier_specs = self.hier.specs()

            def body(hier, f, x):
                dot = dist_inner_product
                lv0 = hier.levels[0]
                amv = partial(_dia_halo_mv, lv0.adata, lv0.a_flats)
                r = f - amv(x)
                nb = jnp.sqrt(jnp.abs(dot(f, f)))
                scale = jnp.where(nb > 0, nb, 1.0)
                eps = tol * scale

                def cond(st):
                    return (st[4] < maxiter) & (st[5] > eps)

                def it(st):
                    x, r, p, rho_p, k, res = st
                    s = hier.shard_apply(r)
                    rho = dot(r, s)
                    beta = jnp.where(rho_p == 0, 0.0, rho / rho_p)
                    p = s + beta * p
                    q = amv(p)
                    alpha = rho / dot(q, p)
                    x = x + alpha * p
                    r = r - alpha * q
                    return (x, r, p, rho, k + 1,
                            jnp.sqrt(jnp.abs(dot(r, r))))

                st = (x, r, jnp.zeros_like(r), jnp.zeros((), f.dtype), 0,
                      jnp.sqrt(jnp.abs(dot(r, r))))
                x, r, p, rho, k, res = lax.while_loop(cond, it, st)
                return x, k, res / scale

            fn = shard_map(
                body, mesh=self.mesh,
                in_specs=(hier_specs, P(ROWS_AXIS), P(ROWS_AXIS)),
                out_specs=(P(ROWS_AXIS), P(), P()),
                check_vma=False)
            self._compiled = jax.jit(fn)
        x, it, res = self._compiled(self.hier, f, x0p)
        x = np.asarray(x)[: self.n]
        return x, SolverInfo(int(it), float(res))

    def __repr__(self):
        rows = ["DistStencilSolver over %d devices (sharded setup)"
                % self.mesh.shape[ROWS_AXIS]]
        for i, m in enumerate(self.meta):
            rows.append("%5d %12d" % (i, m))
        return "\n".join(rows)


def dist_stencil_build(A: CSR, mesh, prm, rep_coarse_enough: int = 3000):
    """Sharded hierarchy construction. Returns (DistStencilHierarchy,
    per-level row counts) or None when outside the fast path."""
    from amgcl_tpu.coarsening.smoothed_aggregation import \
        SmoothedAggregation
    from amgcl_tpu.relaxation.spai0 import Spai0
    from amgcl_tpu.relaxation.jacobi import DampedJacobi
    from amgcl_tpu.ops.structured import detect_grid_csr
    from amgcl_tpu.models.amg import AMG, AMGParams

    c = prm.coarsening
    if type(c) is not SmoothedAggregation:
        return None
    if (c.nullspace is not None or c.aggregator is not None
            or c.block_size != 1 or c.power_iters):
        return None
    if A.is_block or np.iscomplexobj(A.val):
        return None
    if jnp.dtype(prm.dtype) != jnp.dtype(jnp.float32):
        return None
    if isinstance(prm.relax, Spai0):
        relax_kind, sm_omega = "spai0", 0.0
    elif isinstance(prm.relax, DampedJacobi):
        relax_kind, sm_omega = "jacobi", float(prm.relax.damping)
    else:
        return None
    grid = detect_grid_csr(A)
    if grid is None:
        return None
    nd = mesh.shape[ROWS_AXIS]
    d2, d1, d0 = grid
    if d2 % (2 * nd) != 0:
        return None
    Ad = host_dia_from_csr(A, grid, np.float32)
    if Ad is None or len(Ad.offsets3) > _MAX_DIAGS:
        return None

    dims = tuple(grid)
    offs = list(Ad.offsets3)
    sh_mat = NamedSharding(mesh, P(None, ROWS_AXIS))
    adata = put_with_sharding(np.ascontiguousarray(Ad.data), sh_mat)
    eps = float(c.eps_strong)
    n = int(np.prod(dims))
    meta = [n]
    levels = []

    while True:
        d2 = dims[0]
        lz = d2 // nd
        n = int(np.prod(dims))
        # z must split evenly over the mesh; z-COARSENING additionally
        # needs an even local slab (zb below) — semicoarsening in x/y
        # alone works with any lz
        if (n <= rep_coarse_enough or len(offs) > _MAX_DIAGS
                or d2 % nd != 0):
            break
        # Halo-width guard: _halo_extend ships w elements across ONE ring
        # hop, so w must not exceed the local slab (w > nl would make
        # arr[:, -w:] silently clamp, and a coupling reaching past the
        # immediate neighbour needs rows one ring hop cannot supply).  All
        # halo widths used inside _sharded_level_setup derive from
        # |flat(o)| over offs / af_offs / mt_offs, whose magnitudes
        # coincide with offs + the main diagonal.
        nl_guard = lz * dims[1] * dims[2]
        hmax_l = max(max(abs(_flat(o, dims)) for o in offs), 1)
        if hmax_l > nl_guard:
            break
        zb = 2 if dims[0] > 1 and lz % 2 == 0 else 1
        blocks = (zb, 2 if dims[1] > 1 else 1, 2 if dims[2] > 1 else 1)
        if all(b == 1 for b in blocks):
            break
        coarse = tuple(-(-d // b) for d, b in zip(dims, blocks))

        def run_setup(blocks, coarse):
            fn = shard_map(
                partial(_sharded_level_setup,
                        offs=tuple(offs), gdims=dims, lz=lz, blocks=blocks,
                        coarse=coarse, relax_kind=relax_kind),
                mesh=mesh,
                in_specs=(P(None, ROWS_AXIS), P(), P(), P()),
                out_specs=(P(None, ROWS_AXIS), P(None, ROWS_AXIS),
                           P(None, ROWS_AXIS), P(ROWS_AXIS), P(), P()),
                check_vma=False)
            return jax.jit(fn)(adata, jnp.float32(eps),
                               jnp.float32(c.relax), jnp.float32(sm_omega))

        m, mt, ac, scale, counts, axis_strong = run_setup(blocks, coarse)
        counts_h, axis_h = jax.device_get((counts, axis_strong))
        want = tuple(
            min(2, dims[i]) if dims[i] > 1 and axis_h[i] >= 0.5 * n else 1
            for i in range(3))
        if want != blocks:
            # semicoarsening: rerun with the measured strong axes (as the
            # device path does, ops/stencil_device.py). z-coarsening a
            # strong z-axis with an odd local slab is not expressible on
            # this mesh — fall back to the replicated tail.
            if all(b == 1 for b in want) or (want[0] == 2 and zb == 1):
                if not levels:
                    return None
                break
            blocks = want
            coarse = tuple(-(-d // b) for d, b in zip(dims, blocks))
            m, mt, ac, scale, counts, _ = run_setup(blocks, coarse)
            counts_h = jax.device_get(counts)

        main_in = (0, 0, 0) in offs
        af_offs = list(offs) + ([] if main_in else [(0, 0, 0)])
        mt_offs = [_oneg(o) for o in af_offs]
        s_offs, _, _ = _product_plan(
            mt_offs, _product_plan(offs, af_offs, dims)[0], dims)
        c_offs, _, _ = _collapse_plan(s_offs, dims, blocks, coarse)
        keep = np.flatnonzero(counts_h)
        if len(keep) == 0:
            return None
        new_offs = [c_offs[k] for k in keep]
        ac = ac[jnp.asarray(keep)]

        levels.append(DistStencilLevel(
            adata, scale, m, mt,
            [_flat(o, dims) for o in offs],
            [_flat(o, dims) for o in af_offs],
            [_flat(o, dims) for o in mt_offs],
            (lz, dims[1], dims[2]),
            (lz // 2 if blocks[0] > 1 else lz, coarse[1], coarse[2]),
            blocks))
        adata, offs, dims = ac, new_offs, coarse
        meta.append(int(np.prod(dims)))
        eps *= 0.5

    if not levels:
        return None
    # replicated serial tail from the gathered coarse level (the
    # repartition-merge analogue: few rows -> one "rank")
    Hl = HostDia(offs, np.asarray(jax.device_get(adata)), dims)
    Acsr = Hl.to_csr()
    from dataclasses import replace as _dc_replace
    prm2 = _dc_replace(
        prm, coarsening=SmoothedAggregation(eps_strong=eps,
                                            relax=c.relax),
        dtype=jnp.float32)
    rep_amg = AMG(Acsr, prm2)
    hier = DistStencilHierarchy(levels, rep_amg.hierarchy, Acsr.nrows,
                                prm.npre, prm.npost)
    return hier, meta
