"""Mesh-sharded stencil setup and solve: distributed hierarchy CONSTRUCTION.

The round-2 review's core distributed gap: the hierarchy was built serially
on one host and then sharded (reference builds it distributed —
amgcl/mpi/amg.hpp:163-330 with distributed SpGEMM,
amgcl/mpi/distributed_matrix.hpp:856-1066). For stencil problems the
device setup (ops/stencil_device.py) is already expressed as per-diagonal
streaming passes with STATIC shifts — exactly the shape `shard_map` wants:

- rows are sharded in contiguous z-slabs over the mesh's ``rows`` axis;
- every static shift becomes a ring halo exchange (``lax.ppermute`` of the
  slab edges — zero-filled at the global boundary, matching the serial
  zero-fill shift semantics);
- the Gershgorin bound and strength counts become ``pmax``/``psum``;
- the pair-product scans and the tentative parity collapse are unchanged
  (the collapse is position-local because slab boundaries align with the
  2× aggregation blocks);
- per-level, each shard holds only its slab of every diagonal — per-shard
  peak memory is the serial build's divided by the mesh size.

The solve phase reuses the same slabs: smoother, residual, and transfer
applications are halo-SpMVs (parallel/dist_matrix.py pattern), the coarse
tail below the sharded levels is a replicated serial hierarchy (the
repartition-merge analogue: amgcl/mpi/partition/merge.hpp:47-137), and the
whole AMG-preconditioned CG runs as ONE shard_map'd XLA program.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from amgcl_tpu.parallel.compat import shard_map, \
    axis_size as _axis_size
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops.stencil import HostDia, host_dia_from_csr, _flat
from amgcl_tpu.ops.stencil_device import (
    _MAX_DIAGS, _osum, _oneg, _product_plan, _collapse_plan, _fnma_scan)
from amgcl_tpu.parallel.mesh import ROWS_AXIS, put_with_sharding
from amgcl_tpu.parallel.dist_matrix import (dist_inner_product,
                                            dia_halo_mv as _dia_halo_mv)


def _halo_extend(arr, w):
    """(ndiag, nl) -> (ndiag, nl + 2w): ring halo over the rows axis;
    boundary shards see zeros (global zero-fill shift semantics)."""
    if w == 0:
        return arr
    nd = _axis_size(ROWS_AXIS)
    if nd == 1:
        return jnp.pad(arr, ((0, 0), (w, w)))
    fwd = [(i, i + 1) for i in range(nd - 1)]
    bwd = [(i + 1, i) for i in range(nd - 1)]
    prev_tail = lax.ppermute(arr[:, -w:], ROWS_AXIS, fwd)
    next_head = lax.ppermute(arr[:, :w], ROWS_AXIS, bwd)
    return jnp.concatenate([prev_tail, arr, next_head], axis=1)




def _build_fused_slab(mesh, adata, mdata, mtdata, scale, a_flats, m_flats,
                      mt_flats, ldims, lcoarse, blocks, npre=1):
    """FusedSlab for an eligible sharded stencil level, else None.

    Same eligibility logic as the single-chip builders (the shared
    geometry helpers in ops/pallas_vcycle.py) evaluated on the LOCAL
    slab, plus the ring constraint: every frame must be fillable by ONE
    neighbor hop (frame halo ≤ slab size). Matrix/scale frames are
    built once here via a shard_map'd halo extend; vectors are framed
    per cycle. The down frames are only built when ``npre == 1`` (the
    only cycle entry the zero-guess slab kernel serves)."""
    import functools
    from amgcl_tpu.ops.pallas_spmv import pallas_mode
    from amgcl_tpu.ops import pallas_vcycle as pv

    lz, d1, d0 = (int(x) for x in ldims)
    cz, c1, c0 = (int(x) for x in lcoarse)
    if tuple(blocks) != (2, 2, 2) or not a_flats or not mt_flats \
            or not m_flats:
        return None
    k = 128 // d0 if d0 and 128 % d0 == 0 else 0
    s = d1 * d0
    if (not k) or d0 % 2 or d1 % 2 or (k > 1 and d1 % k) or s % 512 \
            or lz % 2 or lz < 2:
        return None
    dt = jnp.dtype(jnp.float32)
    interpret = pallas_mode(dt)
    if interpret is None:
        return None
    nl = lz * s
    nA, nMt, nM = len(a_flats), len(mt_flats), len(m_flats)
    H, _, vmem_dn = pv.down_geometry(a_flats, mt_flats, ldims)
    down_ok = (npre == 1 and H <= nl
               and vmem_dn * dt.itemsize <= pv._VMEM_CAP_BYTES)
    hp, _, vmem_up = pv.up_geometry(a_flats, m_flats, ldims)
    up_ok = (hp <= 2 and hp <= cz and hp * 2 * s <= nl
             and vmem_up * dt.itemsize <= pv._VMEM_CAP_BYTES)
    if not (down_ok or up_ok):
        return None

    L = nl + 2 * H
    Lm = nl + 2 * hp * 2 * s
    _, fv, cv = pv._pack_shape(d1, d0, c1, c0)
    if not interpret and down_ok:
        key = ("slab_dn", tuple(a_flats), tuple(mt_flats),
               tuple(ldims), tuple(lcoarse), H)
        if key not in _SLAB_PROBE:
            try:
                av = jax.ShapeDtypeStruct((nA * L,), dt)
                mv = jax.ShapeDtypeStruct((nMt * L,), dt)
                ra = jax.ShapeDtypeStruct((cv[0], fv[0]), dt)
                rb = jax.ShapeDtypeStruct((fv[1], cv[1]), dt)
                fvec = jax.ShapeDtypeStruct((L,), dt)
                jax.jit(functools.partial(
                    pv.fused_down_sweep, offs_a=tuple(a_flats),
                    offs_m=tuple(mt_flats), dims=tuple(ldims),
                    coarse=tuple(lcoarse), H=H, zero_guess=True,
                    framed=True)).lower(
                        av, mv, ra, rb, fvec, fvec).compile()
                _SLAB_PROBE[key] = True
            except Exception:
                _SLAB_PROBE[key] = False
        down_ok = _SLAB_PROBE[key]
    if not interpret and up_ok:
        key = ("slab_up", tuple(a_flats), tuple(m_flats),
               tuple(ldims), tuple(lcoarse), hp)
        if key not in _SLAB_PROBE:
            try:
                av = jax.ShapeDtypeStruct((nA, nl), dt)
                mv = jax.ShapeDtypeStruct((nM * Lm,), dt)
                ea = jax.ShapeDtypeStruct((fv[0], cv[0]), dt)
                eb = jax.ShapeDtypeStruct((cv[1], fv[1]), dt)
                rv = jax.ShapeDtypeStruct(
                    (cz + 2 * hp, cv[0], cv[1]), dt)
                fvec = jax.ShapeDtypeStruct((nl,), dt)
                uv = jax.ShapeDtypeStruct((nl + 2 * hp * 2 * s,), dt)
                jax.jit(functools.partial(
                    pv.fused_up_sweep, offs_a=tuple(a_flats),
                    offs_m=tuple(m_flats), dims=tuple(ldims),
                    coarse=tuple(lcoarse), halo_planes=hp,
                    framed=True)).lower(
                        av, mv, ea, eb, rv, fvec, fvec, uv).compile()
                _SLAB_PROBE[key] = True
            except Exception:
                _SLAB_PROBE[key] = False
        up_ok = _SLAB_PROBE[key]
    if not (down_ok or up_ok):
        return None

    if k == 1:
        red_a = pv._pair_sum(c1, d1, dt)
        red_b = pv._pair_sum(c0, d0, dt).T
        exp_a, exp_b = red_a.T, red_b.T
    else:
        red_a = jnp.eye(fv[0], dtype=dt)
        red_b = pv._packed_reduce(d0, k, c0, dt)
        exp_a, exp_b = red_a, red_b.T

    def body(ad, mtd, md, sc):
        outs = ()
        if down_ok:
            outs = (_halo_extend(ad, H)[None], _halo_extend(mtd, H)[None],
                    _halo_extend(sc[None], H)[0][None])
        if up_ok:
            outs = outs + (_halo_extend(md, hp * 2 * s)[None],)
        return outs

    out_specs = ()
    if down_ok:
        out_specs = (P(ROWS_AXIS, None, None), P(ROWS_AXIS, None, None),
                     P(ROWS_AXIS, None))
    if up_ok:
        out_specs = out_specs + (P(ROWS_AXIS, None, None),)
    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(None, ROWS_AXIS), P(None, ROWS_AXIS),
                             P(None, ROWS_AXIS), P(ROWS_AXIS)),
                   out_specs=out_specs, check_vma=False)
    got = list(jax.jit(fn)(adata, mtdata, mdata, scale))
    a_fr, mt_fr, w_fr = (got[:3] if down_ok else (None, None, None))
    m_fr = got[-1] if up_ok else None

    if not interpret:
        # real-hardware value check vs the composed slab chain — the
        # slab shapes (thin lz, H == nl windows) are never exercised by
        # the single-chip checks, and a silent Mosaic miscompute here
        # would corrupt the distributed preconditioner with no fallback
        afl, mfl, mtfl = tuple(a_flats), tuple(m_flats), tuple(mt_flats)
        # grid-plan-only level instance: reuses t_mv/t_rmv instead of
        # re-inlining the tentative reshape chains
        plan = DistStencilLevel(None, None, None, None, afl, mfl, mtfl,
                                ldims, lcoarse, blocks)
        frames = []
        frame_specs = []
        if down_ok:
            frames += [a_fr, mt_fr, w_fr]
            frame_specs += [P(ROWS_AXIS, None, None)] * 2 \
                + [P(ROWS_AXIS, None)]
        if up_ok:
            frames.append(m_fr)
            frame_specs.append(P(ROWS_AXIS, None, None))

        def chk(ad, mtd, md, sc, f_l, *fr):
            u_ref = sc * f_l
            outs = ()
            if down_ok:
                afr, mtfr, wfr = fr[:3]
                r = f_l - _dia_halo_mv(ad, afl, u_ref)
                t = r - _dia_halo_mv(mtd, mtfl, r)
                fc_ref = plan.t_rmv(t)
                f_fr = _halo_extend(f_l[None], H)[0]
                rc3, u_z = pv.fused_down_sweep(
                    afr[0].reshape(-1), mtfr[0].reshape(-1),
                    red_a, red_b, f_fr, wfr[0],
                    offs_a=afl, offs_m=mtfl, dims=ldims, coarse=lcoarse,
                    H=H, zero_guess=True, framed=True)
                outs = (fc_ref, rc3.reshape(-1), u_ref, u_z)
            if up_ok:
                mfr = fr[-1]
                uc = plan.t_rmv(f_l)
                tt = plan.t_mv(uc)
                u1 = u_ref + tt - _dia_halo_mv(md, mfl, tt)
                u2_ref = u1 + sc * (f_l - _dia_halo_mv(ad, afl, u1))
                uc_fr = _halo_extend(uc[None], hp * c1 * c0)[0]
                rc3p = uc_fr.reshape(cz + 2 * hp, cv[0], cv[1])
                u_fr = _halo_extend(u_ref[None], hp * 2 * s)[0]
                u2 = pv.fused_up_sweep(
                    ad, mfr[0].reshape(-1), exp_a, exp_b, rc3p, f_l,
                    sc, u_fr, offs_a=afl, offs_m=mfl, dims=ldims,
                    coarse=lcoarse, halo_planes=hp, framed=True)
                outs = outs + (u2_ref, u2)
            return outs

        n_out = (4 if down_ok else 0) + (2 if up_ok else 0)
        cfn = shard_map(
            chk, mesh=mesh,
            in_specs=(P(None, ROWS_AXIS), P(None, ROWS_AXIS),
                      P(None, ROWS_AXIS), P(ROWS_AXIS), P(ROWS_AXIS))
            + tuple(frame_specs),
            out_specs=(P(ROWS_AXIS),) * n_out, check_vma=False)
        rng = np.random.RandomState(23)
        fprobe = put_with_sharding(
            rng.rand(adata.shape[1]).astype(np.float32),
            NamedSharding(mesh, P(ROWS_AXIS)))
        vals = jax.jit(cfn)(adata, mtdata, mdata, scale, fprobe, *frames)
        i = 0
        if down_ok:
            ok = pv._values_agree(vals[1], vals[0], dt) \
                and pv._values_agree(vals[3], vals[2], dt)
            if not ok:
                down_ok = False
                a_fr = mt_fr = w_fr = None
            i = 4
        if up_ok and not pv._values_agree(vals[i + 1], vals[i], dt):
            up_ok = False
            m_fr = None
        if not (down_ok or up_ok):
            return None

    return FusedSlab(
        a_fr, mt_fr, w_fr, m_fr,
        red_a, red_b, exp_a if up_ok else None,
        exp_b if up_ok else None, H, hp, ldims, lcoarse,
        a_flats, mt_flats, m_flats, interpret)


_SLAB_PROBE = {}


# -- sharded per-level setup program -----------------------------------------

def _sharded_level_setup(adata_l, eps_strong, relax_scale, smoother_omega,
                         offs, gdims, lz, blocks, coarse, relax_kind):
    """One hierarchy level on the mesh (runs INSIDE shard_map). Mirrors
    ops/stencil_device._level_setup with halo shifts and psum/pmax
    reductions. adata_l: (ndiag, nl) local slab; gdims global; lz local
    z-planes. Returns (m_l, mt_l, ac_l, scale_l, counts, axis_strong)."""
    d2, d1, d0 = gdims
    nl = adata_l.shape[1]
    dt = adata_l.dtype
    offs = list(offs)
    eps2 = (eps_strong * eps_strong).astype(dt)

    flats = [_flat(o, gdims) for o in offs]
    hmax = max(max(abs(f) for f in flats), 1)

    main_k = offs.index((0, 0, 0)) if (0, 0, 0) in offs else None
    dia = jnp.abs(adata_l[main_k]) if main_k is not None \
        else jnp.zeros((nl,), dt)
    dia_ext = _halo_extend(dia[None], hmax)[0]
    af_rows = [None] * len(offs)
    lump = jnp.zeros((nl,), dt)
    for k, o in enumerate(offs):
        if k == main_k:
            continue
        a = adata_l[k]
        dj = lax.dynamic_slice(dia_ext, (hmax + flats[k],), (nl,))
        strong = (a * a) > (eps2 * dia * dj)
        af_rows[k] = jnp.where(strong, a, dt.type(0))
        lump = lump + jnp.where(strong, dt.type(0), a)
    main = (adata_l[main_k] if main_k is not None
            else jnp.zeros((nl,), dt)) + lump
    if main_k is not None:
        af_rows[main_k] = main
        af_offs = list(offs)
    else:
        af_rows.append(main)
        af_offs = list(offs) + [(0, 0, 0)]
    af = jnp.stack(af_rows)
    dinv = jnp.where(main != 0, 1.0 / jnp.where(main != 0, main, 1),
                     1.0).astype(dt)

    axis_strong = []
    for ax in range(3):
        tot = jnp.zeros((), jnp.float32)
        for k, o in enumerate(af_offs):
            if [i for i, c in enumerate(o) if c != 0] == [ax]:
                tot = tot + jnp.count_nonzero(af[k]).astype(jnp.float32)
        axis_strong.append(lax.psum(tot, ROWS_AXIS))
    axis_strong = jnp.stack(axis_strong)

    rho = lax.pmax(
        jnp.max(jnp.abs(dinv) * jnp.sum(jnp.abs(af), axis=0)), ROWS_AXIS)
    omega = (relax_scale.astype(dt) * dt.type(4.0 / 3.0)
             / jnp.maximum(rho, dt.type(1e-30)))

    m = af * (dinv * omega)[None, :]
    af_flats = [_flat(o, gdims) for o in af_offs]
    hm = max(max(abs(f) for f in af_flats), 1)
    m_ext = _halo_extend(m, hm)
    mt = jnp.stack([
        lax.dynamic_slice(m_ext, (k, hm + _flat(_oneg(o), gdims)),
                          (1, nl))[0]
        for k, o in enumerate(af_offs)])
    mt_offs = [_oneg(o) for o in af_offs]

    # X = A - A·M ; S = X - Mt·X (scan pair products over halo'd sources)
    x_offs, _, _ = _product_plan(offs, af_offs, gdims)
    x_idx = {o: k for k, o in enumerate(x_offs)}
    a_slots = np.asarray([x_idx[o] for o in offs], np.int32)
    X = jnp.zeros((len(x_offs), nl), dt).at[a_slots].set(adata_l)
    x_pairs = [(ka, kb, _flat(oa, gdims), x_idx[_osum(oa, ob)])
               for ka, oa in enumerate(offs)
               for kb, ob in enumerate(af_offs)]
    pad_m = max(max(abs(p[2]) for p in x_pairs), 1)
    X = _fnma_scan(X, adata_l, _halo_extend(m, pad_m), x_pairs, pad_m, nl)

    s_offs, s_embed, s_pairs = _product_plan(mt_offs, x_offs, gdims)
    S = jnp.zeros((len(s_offs), nl), dt) \
        .at[np.asarray(s_embed, np.int32)].set(X)
    pad_x = max(max(abs(p[2]) for p in s_pairs), 1)
    S = _fnma_scan(S, mt, _halo_extend(X, pad_x), s_pairs, pad_x, nl)

    # collapse on the LOCAL slab (aligned with the 2x z-blocks)
    c_offs, parities, table = _collapse_plan(s_offs, gdims, blocks, coarse)
    b2, b1, b0 = blocks
    c2, c1, c0 = coarse
    lcz = lz // b2 if b2 > 1 else lz
    dims_p = (lcz * b2, c1 * b1, c0 * b0)
    n_cl = lcz * c1 * c0
    acc0 = jnp.zeros((len(c_offs), n_cl), dt)

    def cbody(acc, inp):
        row, slots = inp
        v3 = row.reshape(lz, d1, d0)
        if dims_p != (lz, d1, d0):
            v3 = jnp.pad(v3, ((0, dims_p[0] - lz), (0, dims_p[1] - d1),
                              (0, dims_p[2] - d0)))
        for j, (pz, py, px) in enumerate(parities):
            sl = v3[pz::b2, py::b1, px::b0].reshape(-1)
            acc = acc.at[slots[j]].add(sl)
        return acc, None

    ac_l, _ = lax.scan(cbody, acc0, (S, jnp.asarray(table)))
    counts = lax.psum(
        jnp.sum(ac_l != 0, axis=1).astype(jnp.int32), ROWS_AXIS)

    d0v = adata_l[main_k] if main_k is not None else jnp.ones((nl,), dt)
    if relax_kind == "spai0":
        denom = jnp.sum(adata_l * adata_l, axis=0)
        scale = d0v / jnp.where(denom != 0, denom, 1)
    else:
        scale = smoother_omega.astype(dt) * jnp.where(
            d0v != 0, 1.0 / jnp.where(d0v != 0, d0v, 1), 0.0).astype(dt)
    return m, mt, ac_l, scale, counts, axis_strong


# -- sharded hierarchy + solve -----------------------------------------------

@register_pytree_node_class
class FusedSlab:
    """Per-shard framed operands for the fused V-cycle kernels
    (ops/pallas_vcycle.py) on a distributed stencil level.

    The single-chip kernels' zero frames become halo frames filled with
    REAL neighbor-slab values at build time (matrix data, smoother
    scale — static per solve) or per cycle (f, u, uc — one
    ``_halo_extend`` ppermute each, replacing the per-op exchanges of
    the composed slab chain). The flat offsets are identical on the
    slab because shards split whole z-planes."""

    def __init__(self, a_fr, mt_fr, w_fr, m_fr, red_a, red_b, exp_a,
                 exp_b, H, hp, ldims, lcoarse, offs_a, offs_mt, offs_m,
                 interpret):
        self.a_fr = a_fr        # (nd, nA, L) sharded: framed A diagonals
        self.mt_fr = mt_fr      # (nd, nMt, L): framed Mᵀ diagonals
        self.w_fr = w_fr        # (nd, L): framed smoother scale
        self.m_fr = m_fr        # (nd, nM, Lm) or None: framed M (up)
        self.red_a = red_a
        self.red_b = red_b
        self.exp_a = exp_a      # None when the up direction is gated
        self.exp_b = exp_b
        self.H = int(H)
        self.hp = int(hp)
        self.ldims = tuple(int(d) for d in ldims)
        self.lcoarse = tuple(int(c) for c in lcoarse)
        self.offs_a = tuple(int(o) for o in offs_a)
        self.offs_mt = tuple(int(o) for o in offs_mt)
        self.offs_m = tuple(int(o) for o in offs_m)
        self.interpret = bool(interpret)

    @property
    def up_ok(self):
        return self.m_fr is not None

    def tree_flatten(self):
        return ((self.a_fr, self.mt_fr, self.w_fr, self.m_fr,
                 self.red_a, self.red_b, self.exp_a, self.exp_b),
                (self.H, self.hp, self.ldims, self.lcoarse, self.offs_a,
                 self.offs_mt, self.offs_m, self.interpret))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def spec(self):
        sh3 = P(ROWS_AXIS, None, None)
        opt = lambda v, sp: None if v is None else sp
        return FusedSlab(
            opt(self.a_fr, sh3), opt(self.mt_fr, sh3),
            opt(self.w_fr, P(ROWS_AXIS, None)), opt(self.m_fr, sh3),
            P(), P(), opt(self.exp_a, P()), opt(self.exp_b, P()),
            self.H, self.hp, self.ldims, self.lcoarse, self.offs_a,
            self.offs_mt, self.offs_m, self.interpret)


@register_pytree_node_class
class DistStencilLevel:
    """One sharded level: local slabs of the operator/smoother/transfer
    diagonals plus the static grid plan."""

    def __init__(self, adata, scale, mdata, mtdata, a_flats, m_flats,
                 mt_flats, ldims, lcoarse, blocks, fused=None):
        self.adata = adata          # (ndiag, nl) sharded
        self.scale = scale          # (nl,) sharded
        self.mdata = mdata
        self.mtdata = mtdata
        self.a_flats = tuple(a_flats)     # GLOBAL flat offsets
        self.m_flats = tuple(m_flats)
        self.mt_flats = tuple(mt_flats)
        self.ldims = tuple(ldims)         # local slab dims (lz, d1, d0)
        self.lcoarse = tuple(lcoarse)     # local coarse dims
        self.blocks = tuple(blocks)
        self.fused = fused                # FusedSlab or None

    def tree_flatten(self):
        return ((self.adata, self.scale, self.mdata, self.mtdata,
                 self.fused),
                (self.a_flats, self.m_flats, self.mt_flats, self.ldims,
                 self.lcoarse, self.blocks))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], children[2], children[3],
                   *aux, fused=children[4])

    # tentative transfer over the local slab (GridTentative logic)
    def t_mv(self, uc):
        (lz, d1, d0), (cz, c1, c0), (b2, b1, b0) = \
            self.ldims, self.lcoarse, self.blocks
        u = uc.reshape(cz, 1, c1, 1, c0, 1)
        u = jnp.broadcast_to(u, (cz, b2, c1, b1, c0, b0))
        u = u.reshape(cz * b2, c1 * b1, c0 * b0)
        return u[:lz, :d1, :d0].reshape(-1)

    def t_rmv(self, v):
        (lz, d1, d0), (cz, c1, c0), (b2, b1, b0) = \
            self.ldims, self.lcoarse, self.blocks
        v3 = v.reshape(lz, d1, d0)
        if (cz * b2, c1 * b1, c0 * b0) != (lz, d1, d0):
            v3 = jnp.pad(v3, ((0, cz * b2 - lz), (0, c1 * b1 - d1),
                              (0, c0 * b0 - d0)))
        v6 = v3.reshape(cz, b2, c1, b1, c0, b0)
        return v6.sum(axis=(1, 3, 5)).reshape(-1)


@register_pytree_node_class
class DistStencilHierarchy:
    """Sharded stencil levels + replicated serial tail."""

    def __init__(self, levels, rep_hier, n_rep, npre=1, npost=1):
        self.levels = list(levels)
        self.rep_hier = rep_hier      # serial Hierarchy, replicated
        self.n_rep = int(n_rep)       # true rows of the replicated top
        self.npre = int(npre)
        self.npost = int(npost)

    def tree_flatten(self):
        return ((self.levels, self.rep_hier),
                (self.n_rep, self.npre, self.npost))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    def specs(self):
        specs_levels = []
        for lv in self.levels:
            specs_levels.append(DistStencilLevel(
                P(None, ROWS_AXIS), P(ROWS_AXIS), P(None, ROWS_AXIS),
                P(None, ROWS_AXIS), lv.a_flats, lv.m_flats, lv.mt_flats,
                lv.ldims, lv.lcoarse, lv.blocks,
                None if lv.fused is None else lv.fused.spec()))
        rep = jax.tree.map(lambda _: P(), self.rep_hier)
        return DistStencilHierarchy(specs_levels, rep, self.n_rep,
                                    self.npre, self.npost)

    def shard_cycle(self, i, f):
        if i == len(self.levels):
            # replicated tail: gather, serial hierarchy apply, slice local
            nd = _axis_size(ROWS_AXIS)
            idx = lax.axis_index(ROWS_AXIS)
            nl = f.shape[0]
            full = lax.all_gather(f, ROWS_AXIS, tiled=True)[:self.n_rep]
            u = self.rep_hier.apply(full)
            u = jnp.pad(u, (0, nl * nd - self.n_rep))
            return lax.dynamic_slice(u, (idx * nl,), (nl,))
        lv = self.levels[i]
        amv = partial(_dia_halo_mv, lv.adata, lv.a_flats)
        fz = lv.fused
        if fz is not None and fz.a_fr is not None and self.npre == 1:
            # whole down-sweep as one per-shard kernel on halo frames
            from amgcl_tpu.ops.pallas_vcycle import fused_down_sweep
            f_fr = _halo_extend(f[None], fz.H)[0]
            rc3, u = fused_down_sweep(
                fz.a_fr[0].reshape(-1), fz.mt_fr[0].reshape(-1),
                fz.red_a, fz.red_b, f_fr, fz.w_fr[0],
                offs_a=fz.offs_a, offs_m=fz.offs_mt, dims=fz.ldims,
                coarse=fz.lcoarse, H=fz.H, zero_guess=True, framed=True,
                interpret=fz.interpret)
            fc = rc3.reshape(-1)
        else:
            u = lv.scale * f
            for _ in range(self.npre - 1):
                u = u + lv.scale * (f - amv(u))
            r = f - amv(u)
            # restrict: fc = T^T (r - M^T r)
            t = r - _dia_halo_mv(lv.mtdata, lv.mt_flats, r)
            fc = lv.t_rmv(t)
        uc = self.shard_cycle(i + 1, fc)
        if fz is not None and fz.up_ok and self.npost >= 1:
            # prolong + correct + first post-sweep as one kernel
            from amgcl_tpu.ops.pallas_vcycle import (fused_up_sweep,
                                                     _pack_shape)
            cz, pc1xpc0 = fz.lcoarse[0], fz.lcoarse[1] * fz.lcoarse[2]
            _, _, cv = _pack_shape(fz.ldims[1], fz.ldims[2],
                                   fz.lcoarse[1], fz.lcoarse[2])
            uc_fr = _halo_extend(uc[None], fz.hp * pc1xpc0)[0]
            rc3p = uc_fr.reshape(cz + 2 * fz.hp, cv[0], cv[1])
            s2 = 2 * fz.ldims[1] * fz.ldims[2]
            u_fr = _halo_extend(u[None], fz.hp * s2)[0]
            u = fused_up_sweep(
                lv.adata, fz.m_fr[0].reshape(-1), fz.exp_a, fz.exp_b,
                rc3p, f, lv.scale, u_fr,
                offs_a=fz.offs_a, offs_m=fz.offs_m, dims=fz.ldims,
                coarse=fz.lcoarse, halo_planes=fz.hp, framed=True,
                interpret=fz.interpret)
            extra = self.npost - 1
        else:
            t = lv.t_mv(uc)
            u = u + t - _dia_halo_mv(lv.mdata, lv.m_flats, t)
            extra = self.npost
        for _ in range(extra):
            u = u + lv.scale * (f - amv(u))
        return u

    def shard_apply(self, r):
        return self.shard_cycle(0, r)


class DistStencilSolver:
    """AMG-preconditioned CG on a mesh with DISTRIBUTED hierarchy
    construction for stencil problems. ``DistStencilSolver(A, mesh, prm,
    solver)`` then ``x, info = s(rhs)``."""

    def __init__(self, A, mesh, prm=None, solver: Any = None,
                 rep_coarse_enough: int = 3000):
        from amgcl_tpu.models.amg import AMGParams
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.mesh = mesh
        self.prm = prm or AMGParams()
        self.solver = solver
        got = dist_stencil_build(A, mesh, self.prm, rep_coarse_enough)
        if got is None:
            raise ValueError(
                "matrix/config outside the sharded stencil fast path "
                "(needs a structured grid with z-extent divisible by "
                "2x mesh, scalar real f32, SA + spai0/jacobi)")
        self.hier, self.meta = got
        self.n = A.nrows
        self._compiled = None

    def __call__(self, rhs, x0=None):
        import jax.numpy as jnp
        from amgcl_tpu.models.make_solver import SolverInfo
        nd = self.mesh.shape[ROWS_AXIS]
        maxiter = getattr(self.solver, "maxiter", 100) if self.solver \
            else 100
        tol = getattr(self.solver, "tol", 1e-6) if self.solver else 1e-6
        vec = NamedSharding(self.mesh, P(ROWS_AXIS))
        rhs = np.asarray(rhs, np.float32)
        # levels[0].adata.shape is GLOBAL (the sharding is in the array's
        # layout, not its logical shape)
        rhs_p = np.pad(rhs, (0, self.hier.levels[0].adata.shape[1]
                             - len(rhs)))
        f = put_with_sharding(rhs_p, vec)
        x0p = jnp.zeros_like(f) if x0 is None else put_with_sharding(
            np.pad(np.asarray(x0, np.float32),
                   (0, len(rhs_p) - len(rhs))), vec)
        if self._compiled is None:
            hier_specs = self.hier.specs()

            def body(hier, f, x):
                dot = dist_inner_product
                lv0 = hier.levels[0]
                amv = partial(_dia_halo_mv, lv0.adata, lv0.a_flats)
                r = f - amv(x)
                nb = jnp.sqrt(jnp.abs(dot(f, f)))
                scale = jnp.where(nb > 0, nb, 1.0)
                eps = tol * scale

                def cond(st):
                    return (st[4] < maxiter) & (st[5] > eps)

                def it(st):
                    x, r, p, rho_p, k, res = st
                    s = hier.shard_apply(r)
                    rho = dot(r, s)
                    beta = jnp.where(rho_p == 0, 0.0, rho / rho_p)
                    p = s + beta * p
                    q = amv(p)
                    alpha = rho / dot(q, p)
                    x = x + alpha * p
                    r = r - alpha * q
                    return (x, r, p, rho, k + 1,
                            jnp.sqrt(jnp.abs(dot(r, r))))

                st = (x, r, jnp.zeros_like(r), jnp.zeros((), f.dtype), 0,
                      jnp.sqrt(jnp.abs(dot(r, r))))
                x, r, p, rho, k, res = lax.while_loop(cond, it, st)
                return x, k, res / scale

            fn = shard_map(
                body, mesh=self.mesh,
                in_specs=(hier_specs, P(ROWS_AXIS), P(ROWS_AXIS)),
                out_specs=(P(ROWS_AXIS), P(), P()),
                check_vma=False)
            # observed jit (telemetry/compile_watch.py): the stencil
            # solver's whole-mesh CG program is a repeat-solve entry
            # point like dist_cg
            from amgcl_tpu.telemetry.compile_watch import watched_jit
            self._compiled = watched_jit(fn,
                                         name="parallel.dist_stencil_cg")
        x, it, res = self._compiled(self.hier, f, x0p)
        x = np.asarray(x)[: self.n]
        from amgcl_tpu.telemetry import emit as _tel_emit
        info = SolverInfo(int(it), float(res), solver="dist_stencil_cg",
                          extra={"devices": int(nd)})
        _tel_emit(info.to_dict(), event="dist_solve", n=self.n)
        return x, info

    def __repr__(self):
        rows = ["DistStencilSolver over %d devices (sharded setup)"
                % self.mesh.shape[ROWS_AXIS]]
        for i, m in enumerate(self.meta):
            rows.append("%5d %12d" % (i, m))
        return "\n".join(rows)


def dist_stencil_build(A: CSR, mesh, prm, rep_coarse_enough: int = 3000):
    """Sharded hierarchy construction. Returns (DistStencilHierarchy,
    per-level row counts) or None when outside the fast path."""
    from amgcl_tpu.coarsening.smoothed_aggregation import \
        SmoothedAggregation
    from amgcl_tpu.relaxation.spai0 import Spai0
    from amgcl_tpu.relaxation.jacobi import DampedJacobi
    from amgcl_tpu.ops.structured import detect_grid_csr
    from amgcl_tpu.models.amg import AMG, AMGParams

    c = prm.coarsening
    if type(c) is not SmoothedAggregation:
        return None
    if (c.nullspace is not None or c.aggregator is not None
            or c.block_size != 1 or c.power_iters):
        return None
    if A.is_block or np.iscomplexobj(A.val):
        return None
    if jnp.dtype(prm.dtype) != jnp.dtype(jnp.float32):
        return None
    if isinstance(prm.relax, Spai0):
        relax_kind, sm_omega = "spai0", 0.0
    elif isinstance(prm.relax, DampedJacobi):
        relax_kind, sm_omega = "jacobi", float(prm.relax.damping)
    else:
        return None
    grid = detect_grid_csr(A)
    if grid is None:
        return None
    nd = mesh.shape[ROWS_AXIS]
    d2, d1, d0 = grid
    if d2 % (2 * nd) != 0:
        return None
    Ad = host_dia_from_csr(A, grid, np.float32)
    if Ad is None or len(Ad.offsets3) > _MAX_DIAGS:
        return None

    dims = tuple(grid)
    offs = list(Ad.offsets3)
    sh_mat = NamedSharding(mesh, P(None, ROWS_AXIS))
    adata = put_with_sharding(np.ascontiguousarray(Ad.data), sh_mat)
    eps = float(c.eps_strong)
    n = int(np.prod(dims))
    meta = [n]
    levels = []

    while True:
        d2 = dims[0]
        lz = d2 // nd
        n = int(np.prod(dims))
        # z must split evenly over the mesh; z-COARSENING additionally
        # needs an even local slab (zb below) — semicoarsening in x/y
        # alone works with any lz
        if (n <= rep_coarse_enough or len(offs) > _MAX_DIAGS
                or d2 % nd != 0):
            break
        # Halo-width guard: _halo_extend ships w elements across ONE ring
        # hop, so w must not exceed the local slab (w > nl would make
        # arr[:, -w:] silently clamp, and a coupling reaching past the
        # immediate neighbour needs rows one ring hop cannot supply).  All
        # halo widths used inside _sharded_level_setup derive from
        # |flat(o)| over offs / af_offs / mt_offs, whose magnitudes
        # coincide with offs + the main diagonal.
        nl_guard = lz * dims[1] * dims[2]
        hmax_l = max(max(abs(_flat(o, dims)) for o in offs), 1)
        if hmax_l > nl_guard:
            break
        zb = 2 if dims[0] > 1 and lz % 2 == 0 else 1
        blocks = (zb, 2 if dims[1] > 1 else 1, 2 if dims[2] > 1 else 1)
        if all(b == 1 for b in blocks):
            break
        coarse = tuple(-(-d // b) for d, b in zip(dims, blocks))

        def run_setup(blocks, coarse):
            fn = shard_map(
                partial(_sharded_level_setup,
                        offs=tuple(offs), gdims=dims, lz=lz, blocks=blocks,
                        coarse=coarse, relax_kind=relax_kind),
                mesh=mesh,
                in_specs=(P(None, ROWS_AXIS), P(), P(), P()),
                out_specs=(P(None, ROWS_AXIS), P(None, ROWS_AXIS),
                           P(None, ROWS_AXIS), P(ROWS_AXIS), P(), P()),
                check_vma=False)
            return jax.jit(fn)(adata, jnp.float32(eps),
                               jnp.float32(c.relax), jnp.float32(sm_omega))

        m, mt, ac, scale, counts, axis_strong = run_setup(blocks, coarse)
        counts_h, axis_h = jax.device_get((counts, axis_strong))
        want = tuple(
            min(2, dims[i]) if dims[i] > 1 and axis_h[i] >= 0.5 * n else 1
            for i in range(3))
        if want != blocks:
            # semicoarsening: rerun with the measured strong axes (as the
            # device path does, ops/stencil_device.py). z-coarsening a
            # strong z-axis with an odd local slab is not expressible on
            # this mesh — fall back to the replicated tail.
            if all(b == 1 for b in want) or (want[0] == 2 and zb == 1):
                if not levels:
                    return None
                break
            blocks = want
            coarse = tuple(-(-d // b) for d, b in zip(dims, blocks))
            m, mt, ac, scale, counts, _ = run_setup(blocks, coarse)
            counts_h = jax.device_get(counts)

        main_in = (0, 0, 0) in offs
        af_offs = list(offs) + ([] if main_in else [(0, 0, 0)])
        mt_offs = [_oneg(o) for o in af_offs]
        s_offs, _, _ = _product_plan(
            mt_offs, _product_plan(offs, af_offs, dims)[0], dims)
        c_offs, _, _ = _collapse_plan(s_offs, dims, blocks, coarse)
        keep = np.flatnonzero(counts_h)
        if len(keep) == 0:
            return None
        new_offs = [c_offs[k] for k in keep]
        ac = ac[jnp.asarray(keep)]

        a_fl = [_flat(o, dims) for o in offs]
        m_fl = [_flat(o, dims) for o in af_offs]
        mt_fl = [_flat(o, dims) for o in mt_offs]
        ld = (lz, dims[1], dims[2])
        lc = (lz // 2 if blocks[0] > 1 else lz, coarse[1], coarse[2])
        levels.append(DistStencilLevel(
            adata, scale, m, mt, a_fl, m_fl, mt_fl, ld, lc, blocks,
            fused=_build_fused_slab(mesh, adata, m, mt, scale, a_fl,
                                    m_fl, mt_fl, ld, lc, blocks,
                                    npre=prm.npre)))
        adata, offs, dims = ac, new_offs, coarse
        meta.append(int(np.prod(dims)))
        eps *= 0.5

    if not levels:
        return None
    # replicated serial tail from the gathered coarse level (the
    # repartition-merge analogue: few rows -> one "rank")
    Hl = HostDia(offs, np.asarray(jax.device_get(adata)), dims)
    Acsr = Hl.to_csr()
    from dataclasses import replace as _dc_replace
    prm2 = _dc_replace(
        prm, coarsening=SmoothedAggregation(eps_strong=eps,
                                            relax=c.relax),
        dtype=jnp.float32)
    rep_amg = AMG(Acsr, prm2)
    hier = DistStencilHierarchy(levels, rep_amg.hierarchy, Acsr.nrows,
                                prm.npre, prm.npost)
    return hier, meta
