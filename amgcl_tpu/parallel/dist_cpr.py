"""Distributed CPR — constrained pressure residual over the mesh
(reference: amgcl/mpi/cpr.hpp).

Composition of existing sharded pieces: the quasi-IMPES weight contraction
is a per-shard batched einsum, the pressure stage is a full distributed AMG
hierarchy (nested ``shard_apply``), and the global stage is a sharded
diagonal-type smoother sweep on the full block system — everything runs in
the same shard_map program as the outer Krylov loop.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.models.cpr import _pressure_matrix
from amgcl_tpu.relaxation.spai0 import Spai0
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.parallel.mesh import ROWS_AXIS
from amgcl_tpu.parallel.dist_ell import build_dist_ell
from amgcl_tpu.parallel.dist_amg import DistAMGSolver, _LocalOp


@register_pytree_node_class
class DistCPRHierarchy:
    """A_full: sharded scalar view of the block system; W: (nd, ncell_loc, b)
    sharded weights; p_hier: distributed pressure hierarchy; scale:
    (nd, nloc) sharded global-smoother diagonal."""

    def __init__(self, A_full, W, p_hier, scale, block):
        self.A_full = A_full
        self.W = W
        self.p_hier = p_hier
        self.scale = scale
        self.block = int(block)

    def tree_flatten(self):
        return (self.A_full, self.W, self.p_hier, self.scale), (self.block,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, aux[0])

    def specs(self):
        return DistCPRHierarchy(
            self.A_full.specs(), P(ROWS_AXIS, None, None),
            self.p_hier.specs(), P(ROWS_AXIS, None), self.block)

    def shard_apply(self, r):
        b = self.block
        rb = r.reshape(-1, b)
        rp = jnp.einsum("nb,nb->n", self.W[0], rb)
        dp = self.p_hier.shard_apply(rp)
        x = jnp.zeros_like(rb).at[:, 0].set(dp).reshape(r.shape)
        # global smoothing of the remaining residual
        res = r - self.A_full.shard_mv(x)
        return x + self.scale[0] * res

    def system_A(self):
        return self.A_full


class DistCPRSolver(DistAMGSolver):
    """Distributed Krylov with the CPR preconditioner. ``A`` must be a
    block CSR (or scalar + block_size)."""

    def __init__(self, A, mesh, block_size: Optional[int] = None,
                 pressure_prm: Optional[AMGParams] = None,
                 solver: Any = None, relax: Any = None,
                 dtype=jnp.float32):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        if not A.is_block:
            if not block_size or block_size < 2:
                raise ValueError("CPR needs a block system (block_size >= 2)")
            A = A.to_block(block_size)
        b = A.block_size[0]
        self.mesh = mesh
        self.solver = solver or CG()
        nd = mesh.shape[ROWS_AXIS]
        from types import SimpleNamespace
        self.prm = SimpleNamespace(dtype=dtype)

        # pressure stage: distributed AMG on the quasi-IMPES reduced matrix
        W = A.diagonal(invert=True)[:, 0, :]
        App = _pressure_matrix(A, W)
        pprm = pressure_prm or AMGParams(dtype=dtype)
        p_solver = DistAMGSolver(App, mesh, pprm)
        # global smoother on the scalar view of the block system
        As = A.unblock()
        dA = build_dist_ell(As, mesh, dtype)
        st = (relax or Spai0()).build(A, dtype)
        if hasattr(st, "scale") and np.ndim(st.scale) == 1:
            scale = np.asarray(st.scale, dtype=np.float64)
        else:
            # scalar spai0 of the unblocked system beats plain Jacobi and
            # needs no block-state sharding (block-M sharding: round 2)
            import warnings
            warnings.warn(
                "distributed CPR shards diagonal-type global smoothers; "
                "%s falls back to scalar SPAI-0"
                % type(relax or Spai0()).__name__)
            scale = np.asarray(Spai0().build(As, dtype).scale,
                               dtype=np.float64)
        self.n = As.nrows
        nloc = dA.nloc
        self.n_pad = nloc * nd
        pad = np.zeros(self.n_pad)
        pad[:len(scale)] = scale
        # weights padded to the cell partition of the scalar padding:
        # n_pad is a multiple of nd; require it to also tile into b-cells
        if nloc % b:
            raise ValueError(
                "shard size %d does not tile into %d-cell blocks — pad the "
                "system or choose a divisible mesh" % (nloc, b))
        # the scalar partition's cell view must coincide with the pressure
        # hierarchy's own partition, so the nested shard_apply sees aligned
        # local vectors
        first = (p_solver.hier.levels[0].A if p_solver.hier.levels
                 else p_solver.hier.top_A)
        if first.nloc * b != nloc:
            raise ValueError(
                "pressure partition (%d cells/shard) does not align with "
                "the block partition (%d rows/shard)" % (first.nloc, nloc))
        Wpad = np.zeros((self.n_pad // b, b))
        Wpad[:A.nrows] = W
        shard3 = NamedSharding(mesh, P(ROWS_AXIS, None, None))
        shard2 = NamedSharding(mesh, P(ROWS_AXIS, None))
        self.hier = DistCPRHierarchy(
            dA,
            jax.device_put(jnp.asarray(
                Wpad.reshape(nd, nloc // b, b), dtype=dtype), shard3),
            p_solver.hier,
            jax.device_put(jnp.asarray(
                pad.reshape(nd, nloc), dtype=dtype), shard2),
            b)
        self._compiled = None

    def __repr__(self):
        return "DistCPRSolver over %d devices" % self.mesh.shape[ROWS_AXIS]
