"""Distributed CPR — constrained pressure residual over the mesh
(reference: amgcl/mpi/cpr.hpp).

Composition of existing sharded pieces: the quasi-IMPES weight contraction
is a per-shard batched einsum, the pressure stage is a full distributed AMG
hierarchy (nested ``shard_apply``), and the global stage is a sharded
diagonal-type smoother sweep on the full block system — everything runs in
the same shard_map program as the outer Krylov loop.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.models.cpr import CPR, CPRDRS, _pressure_matrix
from amgcl_tpu.relaxation.spai0 import Spai0
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.parallel.mesh import ROWS_AXIS
from amgcl_tpu.parallel.dist_ell import build_dist_ell
from amgcl_tpu.parallel.dist_amg import (DistAMGSolver, _LocalOp,
    _build_dist_smoother)


@register_pytree_node_class
class DistCPRHierarchy:
    """A_full: sharded scalar view of the block system; W: (nd, ncell_loc, b)
    sharded weights; p_hier: distributed pressure hierarchy; smoother:
    sharded global-stage DistSmoother (any registry smoother — block spai0,
    ILU, GS, ... — the reference's cpr.hpp relax policy)."""

    def __init__(self, A_full, W, p_hier, smoother, block):
        self.A_full = A_full
        self.W = W
        self.p_hier = p_hier
        self.smoother = smoother
        self.block = int(block)

    def tree_flatten(self):
        return ((self.A_full, self.W, self.p_hier, self.smoother),
                (self.block,))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, aux[0])

    def specs(self):
        return DistCPRHierarchy(
            self.A_full.specs(), P(ROWS_AXIS, None, None),
            self.p_hier.specs(), self.smoother.spec(), self.block)

    def shard_apply(self, r):
        b = self.block
        rb = r.reshape(-1, b)
        rp = jnp.einsum("nb,nb->n", self.W[0], rb)
        dp = self.p_hier.shard_apply(rp)
        x = jnp.zeros_like(rb).at[:, 0].set(dp).reshape(r.shape)
        # global smoothing of the remaining residual
        res = r - self.A_full.shard_mv(x)
        return x + self.smoother.apply0(_LocalOp(self.A_full), res)

    def system_A(self):
        return self.A_full


class DistCPRSolver(DistAMGSolver):
    """Distributed Krylov with the CPR preconditioner. ``A`` must be a
    block CSR (or scalar + block_size)."""

    def __init__(self, A, mesh, block_size: Optional[int] = None,
                 pressure_prm: Optional[AMGParams] = None,
                 solver: Any = None, relax: Any = None,
                 dtype=jnp.float32, weighting: str = "quasi_impes",
                 **wkw):
        """``weighting``: 'quasi_impes' (cpr.hpp) or 'drs' (cpr_drs.hpp
        dynamic row sums, with ``eps_dd`` / ``eps_ps`` / user ``weights``)
        — the same weight policies as the serial CPR/CPRDRS.
        ``active_rows`` is serial-only: the distributed pressure partition
        must align with the block partition, which a truncated pressure
        system breaks — use the serial CPR for appended-well systems."""
        if wkw.pop("active_rows", 0):
            raise NotImplementedError(
                "active_rows is not supported by the distributed CPR; "
                "use the serial CPR/CPRDRS")
        bad = set(wkw) - {"eps_dd", "eps_ps", "weights"}
        if bad:
            raise TypeError("unexpected keyword arguments: %s"
                            % ", ".join(sorted(bad)))
        if wkw and weighting != "drs":
            import warnings
            warnings.warn("DRS knobs (%s) only apply to weighting='drs'; "
                          "ignored under weighting=%r"
                          % (", ".join(sorted(wkw)), weighting))
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        if not A.is_block:
            if not block_size or block_size < 2:
                raise ValueError("CPR needs a block system (block_size >= 2)")
            A = A.to_block(block_size)
        b = A.block_size[0]
        self.mesh = mesh
        self.solver = solver or CG()
        self.weighting = weighting
        nd = mesh.shape[ROWS_AXIS]
        from types import SimpleNamespace
        self.prm = SimpleNamespace(dtype=dtype)

        # pressure stage: distributed AMG on the weight-reduced matrix
        # (same weight policies as the serial CPR/CPRDRS)
        if weighting == "quasi_impes":
            W = CPR._weights(A)
        elif weighting == "drs":
            W = CPRDRS._weights(A, **wkw)
        else:
            raise ValueError("weighting must be 'quasi_impes' or 'drs'")
        App = _pressure_matrix(A, W)
        pprm = pressure_prm or AMGParams(dtype=dtype)
        p_solver = DistAMGSolver(App, mesh, pprm)
        # global smoother on the full block system, sharded with the same
        # machinery as the AMG levels (any registry smoother; the block
        # spai0 default matches the serial CPR exactly)
        As = A.unblock()
        dA = build_dist_ell(As, mesh, dtype)
        self.n = As.nrows
        nloc = dA.nloc
        self.n_pad = nloc * nd
        # weights padded to the cell partition of the scalar padding:
        # n_pad is a multiple of nd; require it to also tile into b-cells
        if nloc % b:
            raise ValueError(
                "shard size %d does not tile into %d-cell blocks — pad the "
                "system or choose a divisible mesh" % (nloc, b))
        # the scalar partition's cell view must coincide with the pressure
        # hierarchy's own partition, so the nested shard_apply sees aligned
        # local vectors
        first = (p_solver.hier.levels[0].A if p_solver.hier.levels
                 else p_solver.hier.top_A)
        if first.nloc * b != nloc:
            raise ValueError(
                "pressure partition (%d cells/shard) does not align with "
                "the block partition (%d rows/shard)" % (first.nloc, nloc))
        Wpad = np.zeros((self.n_pad // b, b))
        Wpad[:A.nrows] = W
        sm = _build_dist_smoother(relax or Spai0(), A, As, dA, mesh, nd,
                                  dtype)
        from amgcl_tpu.parallel.mesh import put_sharded
        self.hier = DistCPRHierarchy(
            dA, put_sharded(Wpad.reshape(nd, nloc // b, b), mesh, dtype),
            p_solver.hier, sm, b)
        self._compiled = None

    def __repr__(self):
        return "DistCPRSolver over %d devices" % self.mesh.shape[ROWS_AXIS]
