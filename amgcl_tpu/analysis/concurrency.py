"""Concurrency contract analyzer — lock-order / guarded-by / CV- and
handoff-discipline static checks over the threaded host control plane.

The jaxpr auditor (PR 6) put the DEVICE-side invariants under contract;
this module does the same one layer up, for the host-side threaded
serving stack (``serve/service.py``, ``serve/farm.py``, the telemetry
recorders, ``faults/recovery.py`` — the :data:`CONCURRENT_MODULES`
set). Every rule encodes a bug class this codebase has actually paid
for: the PR-11 race-fix commit (atomic re-registration, admission
rollback), the PR-13 review-hardening passes (stranded futures,
worker-death teardown ordering, ``done()`` guards), the PR-8
stats-read race. Four analyses, all stdlib ``ast``, jax-free:

``lock-order``
    every statically nested lock acquisition (``with self._X:`` scopes
    followed through the intra-module call graph) must be an edge of
    the transitively-closed ``LOCK_ORDER`` partial order DECLARED next
    to the code it governs (serve/farm.py, serve/service.py — the
    PR-6 contracts-next-to-models pattern), and the union graph must
    be acyclic. A nested acquisition of a plain (non-reentrant)
    ``Lock`` already held is reported as a self-deadlock.
``guarded-by``
    for each ``self._x`` field (and module-global) of a concurrent
    module, the dominant guarding lock is inferred from the lock-held
    WRITE sites; a write outside the inferred guard, or a read outside
    it from code reachable from a thread entry point
    (``threading.Thread``/``Timer`` targets and callback arguments to
    ``MetricsServer`` — the scrape path), is a finding unless the
    field is listed in the module's declared ``UNGUARDED_OK``
    allowlist with a reason (single-writer disciplines, double-checked
    fast paths).
``cv-discipline``
    a bare ``Condition.wait()`` must sit inside a ``while`` predicate
    loop (``wait_for`` carries its own predicate and is exempt), wait
    and ``notify``/``notify_all`` must run with the condition's lock
    held on every statically known call path.
``handoff-discipline``
    ``Future.set_result``/``set_exception`` must not execute while any
    registry/stats lock is held (a done-callback would run arbitrary
    caller code under the control-plane lock), and must come AFTER the
    function's locked stats commits (the resolve-last discipline: a
    caller who saw its future done reads stats that already include
    its batch). Blocking calls — ``time.sleep``, a thread ``join``, a
    ``queue.get``/``put`` without timeout, ``block_until_ready``, a
    ``Future.result`` — inside a lock-held region are findings
    (``Condition.wait`` releases the lock and is exempt).

Findings use the lint schema — plain dicts keyed ``(rule, file,
symbol)`` — and ride the same ``ANALYSIS_BASELINE.json`` budget with
reasoned suppressions. ``python -m amgcl_tpu.analysis`` runs this
module by default; ``bench.py --check`` embeds the counts.

:func:`static_lock_graph` exports the canonicalized allowed-edge set
(declared closure + derived leaf locks) that the runtime lock witness
(``analysis/lockwitness.py``, ``AMGCL_TPU_LOCK_WITNESS=1``) validates
its actually-witnessed edges against — witnessed ⊆ static is the
check that keeps this analyzer honest (an analyzer that models edges
no execution ever takes, or misses edges executions do take, fails
there, not in review).
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from amgcl_tpu.analysis.lint import (REPO, _Module, _attr_tail,
                                     _blocking_call_shape,
                                     _enclosing_symbol, finding)

#: the declared concurrent-module set the analyzer (and the runtime
#: lock witness) covers — repo-relative under ``amgcl_tpu/``. Adding a
#: threaded module means adding it here (and, if it declares locks,
#: giving it a LOCK_ORDER/UNGUARDED_OK declaration when the analyzer
#: asks for one).
CONCURRENT_MODULES: Tuple[str, ...] = (
    "serve/service.py",
    "serve/farm.py",
    "serve/registry.py",
    "serve/storm.py",
    "telemetry/flight.py",
    "telemetry/live.py",
    "telemetry/memwatch.py",
    "telemetry/sink.py",
    "telemetry/tracing.py",
    "faults/recovery.py",
    "faults/inject.py",
)

#: the rules this module implements, in report order
CONCURRENCY_RULES = ("lock-order", "guarded-by", "cv-discipline",
                     "handoff-discipline")

#: thread-entry constructors: callable arguments to these are thread
#: roots for the reachability analysis (Thread/Timer run targets on a
#: worker; MetricsServer runs its callbacks on the scrape thread)
_THREAD_ENTRY_CALLS = frozenset({"Thread", "Timer", "MetricsServer"})

#: deque/dict/list/set mutator method names — an ``x.append(...)``
#: counts as a WRITE to ``x`` for the guarded-by inference
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear",
    "update", "setdefault", "sort", "reverse",
})

#: depth bound on the interprocedural held-set walk (call chains in
#: these modules are shallow; the bound only guards pathological
#: fixtures)
_MAX_CALL_DEPTH = 12


# ---------------------------------------------------------------------------
# lock discovery
# ---------------------------------------------------------------------------

def _lock_ctor_kind(mod: _Module, node: ast.AST) -> Optional[str]:
    """'lock' | 'rlock' | 'cond' when ``node`` is a Call constructing a
    threading primitive (directly, or wrapped one level in a
    ``maybe_wrap(name, Lock())`` witness seam)."""
    if not isinstance(node, ast.Call):
        return None
    tail = _attr_tail(node.func)
    if tail == "Lock":
        return "lock"
    if tail == "RLock":
        return "rlock"
    if tail == "Condition":
        return "cond"
    if tail and tail.endswith("wrap"):
        # the witness seam in any import spelling (maybe_wrap,
        # _wit_wrap, ...): the wrapped constructor is the lock
        for arg in node.args:
            kind = _lock_ctor_kind(mod, arg)
            if kind:
                return kind
    return None


def _cond_underlying(node: ast.Call) -> Optional[str]:
    """Attribute name of the lock a ``Condition(self._x)`` rides, or
    None for a Condition on its own internal lock."""
    if node.args:
        arg = node.args[0]
        if isinstance(arg, ast.Attribute) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id == "self":
            return arg.attr
        if isinstance(arg, ast.Name):
            return arg.id
    return None


class _LockModel:
    """Per-module lock table: attr/global name -> kind, condition
    aliasing, the declared LOCK_ORDER / UNGUARDED_OK contracts, and the
    canonical (module-qualified) naming the witness shares."""

    def __init__(self, mod: _Module):
        self.mod = mod
        self.stem = os.path.splitext(os.path.basename(mod.rel))[0]
        #: lock name (self-attr or module global) -> kind
        self.locks: Dict[str, str] = {}
        #: condition name -> underlying lock name (same module); a
        #: Condition() on its own internal lock maps to itself
        self.alias: Dict[str, str] = {}
        #: declared partial order, canonicalized pairs
        self.declared: List[Tuple[str, str]] = []
        #: declared unguarded-field allowlist {field: reason}
        self.unguarded_ok: Dict[str, str] = {}
        self._discover()

    def _discover(self) -> None:
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            name = None
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                name = tgt.attr
            elif isinstance(tgt, ast.Name):
                name = tgt.id
            if name is None:
                continue
            kind = _lock_ctor_kind(self.mod, node.value)
            if kind is None:
                continue
            self.locks[name] = kind
            if kind == "cond":
                call = node.value
                tail = _attr_tail(call.func) \
                    if isinstance(call, ast.Call) else None
                if tail and tail.endswith("wrap"):
                    call = next((a for a in call.args
                                 if isinstance(a, ast.Call)), call)
                under = _cond_underlying(call) \
                    if isinstance(call, ast.Call) else None
                self.alias[name] = under if under is not None else name
        # declared contracts are module-level literals
        for node in self.mod.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name):
                continue
            tname = node.targets[0].id
            if tname == "LOCK_ORDER" and isinstance(node.value,
                                                    (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if isinstance(elt, (ast.Tuple, ast.List)) \
                            and len(elt.elts) == 2 \
                            and all(isinstance(e, ast.Constant)
                                    and isinstance(e.value, str)
                                    for e in elt.elts):
                        self.declared.append(
                            (self.canonical(elt.elts[0].value),
                             self.canonical(elt.elts[1].value)))
            elif tname == "UNGUARDED_OK" and isinstance(node.value,
                                                        ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str) \
                            and isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        self.unguarded_ok[k.value] = v.value

    def canonical(self, name: str) -> str:
        """Module-qualified canonical lock name: ``farm._mem_lock``;
        conditions resolve to their underlying lock (``_mem_cond`` ->
        ``farm._mem_lock``). Names already carrying a dot (declared
        cross-module edges like ``registry._lock``) pass through."""
        if "." in name:
            return name
        name = self.alias.get(name, name)
        return "%s.%s" % (self.stem, name)

    def kind_of(self, name: str) -> Optional[str]:
        """Kind of the UNDERLYING primitive: a Condition on an RLock is
        reentrant, one on its own internal lock is not."""
        under = self.alias.get(name, name)
        k = self.locks.get(under)
        if k == "cond":
            return "lock"       # Condition() internal lock: plain Lock
        return k

    def lock_expr_name(self, expr: ast.AST) -> Optional[str]:
        """Local lock name when ``expr`` denotes one of this module's
        locks (``self._x`` or a module-global ``_x``)."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and expr.attr in self.locks:
            return expr.attr
        if isinstance(expr, ast.Name) and expr.id in self.locks:
            return expr.id
        return None


# ---------------------------------------------------------------------------
# thread-entry reachability (lint rule 8's machinery, extended with
# callback arguments to scrape/timer constructors)
# ---------------------------------------------------------------------------

def _thread_root_names(mod: _Module) -> Set[str]:
    roots: Set[str] = set()
    for call in mod._calls():
        tail = _attr_tail(call.func)
        if tail not in _THREAD_ENTRY_CALLS:
            continue
        cands: List[ast.AST] = []
        cands += [kw.value for kw in call.keywords
                  if kw.arg in ("target", "health_cb", "metrics_cb")]
        if tail == "Timer" and len(call.args) >= 2:
            cands.append(call.args[1])
        if tail == "MetricsServer":
            cands += call.args[1:]
        for tgt in cands:
            if isinstance(tgt, ast.Name):
                roots.add(tgt.id)
            elif isinstance(tgt, ast.Attribute):
                roots.add(tgt.attr)
    return roots


def _reachable_from_threads(mod: _Module) -> Set[str]:
    """Function NAMES reachable from a thread root through same-module
    ``self.X()`` / ``X()`` calls."""
    seen: Set[str] = set()
    work = sorted(_thread_root_names(mod))
    while work:
        name = work.pop()
        if name in seen:
            continue
        seen.add(name)
        for fn in mod.by_name.get(name, ()):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self":
                    work.append(f.attr)
                elif isinstance(f, ast.Name):
                    work.append(f.id)
    return seen


# ---------------------------------------------------------------------------
# the interprocedural held-set walk
# ---------------------------------------------------------------------------

class _Access:
    __slots__ = ("field", "write", "held", "func", "line", "qual")

    def __init__(self, field, write, held, func, line, qual):
        self.field = field
        self.write = write
        self.held = held          # tuple of canonical lock names
        self.func = func          # function NAME the access sits in
        self.line = line
        self.qual = qual          # display qualname


class _ModuleAnalysis:
    """One module's walk products: observed nested-acquisition edges,
    field accesses with held-sets, CV/handoff/blocking findings raised
    in-flight."""

    def __init__(self, mod: _Module, model: _LockModel):
        self.mod = mod
        self.model = model
        #: (src_canonical, dst_canonical) -> [(qualname, line), ...]
        self.edges: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
        self.accesses: List[_Access] = []
        self.findings: List[Dict[str, Any]] = []
        #: module-global names tracked for guarded-by (assigned at
        #: module level to a container, or named in a `global` stmt)
        self.globals: Set[str] = set()
        self._seen_ctx: Set[Tuple[int, Tuple[str, ...]]] = set()
        self._finding_keys: Set[Tuple] = set()
        self._discover_globals()

    # -- setup ---------------------------------------------------------------

    def _discover_globals(self) -> None:
        for node in self.mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                val = node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                name = node.target.id
                val = node.value
            else:
                continue
            if isinstance(val, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(val, ast.Call)
                    and _attr_tail(val.func) in ("deque", "dict",
                                                 "list", "set")):
                self.globals.add(name)
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Global):
                self.globals.update(node.names)
        self.globals -= set(self.model.locks)

    # -- helpers -------------------------------------------------------------

    def _emit(self, rule: str, line: int, symbol: str,
              message: str) -> None:
        key = (rule, symbol, message)
        if key in self._finding_keys:
            return
        self._finding_keys.add(key)
        self.findings.append(finding(rule, self.mod.rel, line, symbol,
                                     message))

    def _callees(self, node: ast.Call) -> List[str]:
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            return [f.attr]
        if isinstance(f, ast.Name):
            return [f.id]
        return []

    # -- the walk ------------------------------------------------------------

    def run(self) -> None:
        roots = self._roots()
        for fn in roots:
            self._walk_function(fn, held=(), depth=0)

    def _roots(self) -> List[ast.AST]:
        """Entry contexts with nothing held: functions never called
        intra-module, plus every public (non-underscore) function —
        external callers arrive lock-free. ``_locked``-suffix helpers
        are only analyzed under their propagated calling contexts."""
        called: Set[str] = set()
        for call in self.mod._calls():
            for name in self._callees(call):
                called.add(name)
        out = []
        for fn, qn in self.mod.qualnames.items():
            name = getattr(fn, "name", "")
            if name not in called or not name.startswith("_"):
                out.append(fn)
        return out

    def _walk_function(self, fn: ast.AST, held: Tuple[str, ...],
                       depth: int) -> None:
        ctx = (id(fn), held)
        if ctx in self._seen_ctx or depth > _MAX_CALL_DEPTH:
            return
        self._seen_ctx.add(ctx)
        qual = self.mod.qualnames.get(fn, "<module>")
        for stmt in fn.body:
            self._visit(stmt, fn, qual, held, depth, while_depth=0)

    def _visit(self, node: ast.AST, fn: ast.AST, qual: str,
               held: Tuple[str, ...], depth: int,
               while_depth: int) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs (timer callbacks built inline) are analyzed
            # as their own roots / call targets, not as inline code
            return
        if isinstance(node, ast.With):
            new_held = held
            for item in node.items:
                name = self.model.lock_expr_name(item.context_expr)
                if name is None:
                    self._visit(item.context_expr, fn, qual, held,
                                depth, while_depth)
                    continue
                canon = self.model.canonical(name)
                self._note_acquire(canon, name, qual,
                                   item.context_expr.lineno, new_held)
                if canon not in new_held:
                    new_held = new_held + (canon,)
            for child in node.body:
                self._visit(child, fn, qual, new_held, depth,
                            while_depth)
            return
        if isinstance(node, ast.While):
            for child in ast.iter_child_nodes(node):
                self._visit(child, fn, qual, held, depth,
                            while_depth + 1)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, fn, qual, held, depth, while_depth)
            for child in ast.iter_child_nodes(node):
                self._visit(child, fn, qual, held, depth, while_depth)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                self._note_target(tgt, qual, held)
            for child in ast.iter_child_nodes(node):
                if child not in targets:
                    self._visit(child, fn, qual, held, depth,
                                while_depth)
            return
        if isinstance(node, ast.Attribute):
            self._note_attr(node, qual, held, write=False)
        elif isinstance(node, ast.Name):
            self._note_global(node.id, qual, held,
                              write=isinstance(node.ctx, ast.Store),
                              line=node.lineno)
        for child in ast.iter_child_nodes(node):
            self._visit(child, fn, qual, held, depth, while_depth)

    # -- acquisition + edges -------------------------------------------------

    def _note_acquire(self, canon: str, local: str, qual: str,
                      line: int, held: Tuple[str, ...]) -> None:
        kind = self.model.kind_of(local)
        for h in held:
            if h == canon:
                if kind == "lock":
                    self._emit(
                        "lock-order", line, qual,
                        "re-acquisition of non-reentrant lock %s while "
                        "already held — self-deadlock" % canon)
                return
        for h in held:
            self.edges.setdefault((h, canon), []).append((qual, line))

    # -- calls: CV / handoff / blocking checks + propagation -----------------

    def _visit_call(self, node: ast.Call, fn: ast.AST, qual: str,
                    held: Tuple[str, ...], depth: int,
                    while_depth: int) -> None:
        f = node.func
        tail = _attr_tail(f)
        # condition-variable sites: self._cond.wait(...) etc.
        recv_lock = None
        if isinstance(f, ast.Attribute):
            recv_lock = self.model.lock_expr_name(f.value)
        if recv_lock is not None and tail in ("wait", "wait_for",
                                              "notify", "notify_all"):
            canon = self.model.canonical(recv_lock)
            if canon not in held:
                self._emit(
                    "cv-discipline", node.lineno, qual,
                    "%s.%s() on a statically lock-free path — the "
                    "condition's lock (%s) must be held"
                    % (recv_lock, tail, canon))
            if tail == "wait" and while_depth == 0:
                self._emit(
                    "cv-discipline", node.lineno, qual,
                    "bare %s.wait() outside a while-predicate loop — "
                    "wakeups are spurious and the predicate must be "
                    "re-checked under the lock (use a while loop or "
                    "wait_for)" % recv_lock)
            return
        if recv_lock is not None and tail in ("acquire",):
            canon = self.model.canonical(recv_lock)
            self._note_acquire(canon, recv_lock, qual, node.lineno, held)
            return
        # future handoff under a lock
        if tail in ("set_result", "set_exception") and held:
            self._emit(
                "handoff-discipline", node.lineno, qual,
                "Future.%s while holding %s — a done-callback runs "
                "arbitrary caller code under the control-plane lock; "
                "resolve futures after the locked region" %
                (tail, ", ".join(held)))
        # blocking calls under a lock — THE shared classifier
        # (lint._blocking_call_shape), so rule 9's lexical twin can
        # never drift from this one on what counts as blocking
        if held:
            reason = _blocking_call_shape(node)
            if reason:
                self._emit(
                    "handoff-discipline", node.lineno, qual,
                    "%s while holding %s — blocking under a "
                    "control-plane lock stalls every thread behind it"
                    % (reason, ", ".join(held)))
        # propagate into intra-module callees with the current held-set
        for name in self._callees(node):
            for callee in self.mod.by_name.get(name, ()):
                self._walk_function(callee, held, depth + 1)

    # -- field accesses ------------------------------------------------------

    def _note_target(self, tgt: ast.AST, qual: str,
                     held: Tuple[str, ...]) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._note_target(elt, qual, held)
            return
        node = tgt
        # self._x[...] = v and self._x.y = v are writes to _x
        while isinstance(node, (ast.Subscript, ast.Attribute)) \
                and not (isinstance(node, ast.Attribute)
                         and isinstance(node.value, ast.Name)
                         and node.value.id == "self"):
            node = node.value
        if isinstance(node, ast.Attribute):
            self._note_attr(node, qual, held, write=True)
        elif isinstance(node, ast.Name):
            self._note_global(node.id, qual, held, write=True,
                              line=node.lineno)

    def _note_attr(self, node: ast.Attribute, qual: str,
                   held: Tuple[str, ...], write: bool) -> None:
        if not (isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return
        field = node.attr
        if field in self.model.locks:
            return
        self.accesses.append(_Access(
            field, write, held, qual.rsplit(".", 1)[-1], node.lineno,
            qual))

    def _note_global(self, name: str, qual: str, held: Tuple[str, ...],
                     write: bool, line: int) -> None:
        if name not in self.globals:
            return
        self.accesses.append(_Access(
            "<module>." + name, write, held, qual.rsplit(".", 1)[-1],
            line, qual))

def _upgrade_mutator_writes(analysis: _ModuleAnalysis) -> None:
    """``self._x.append(v)`` / ``_ring.clear()`` record as reads of
    ``_x`` during the walk (the Attribute leaf is a Load); upgrade an
    access to a WRITE when its line holds a mutator call on the same
    receiver."""
    mut_lines: Dict[Tuple[str, int], bool] = {}
    for node in ast.walk(analysis.mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in _MUTATORS):
            continue
        recv = f.value
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self":
            mut_lines[(recv.attr, node.lineno)] = True
        elif isinstance(recv, ast.Name):
            mut_lines[("<module>." + recv.id, node.lineno)] = True
    for acc in analysis.accesses:
        if not acc.write and (acc.field, acc.line) in mut_lines:
            acc.write = True


# ---------------------------------------------------------------------------
# rule evaluation over the walk products
# ---------------------------------------------------------------------------

def _closure(pairs: Iterable[Tuple[str, str]]) -> Set[Tuple[str, str]]:
    edges = set(pairs)
    changed = True
    while changed:
        changed = False
        for a, b in list(edges):
            for c, d in list(edges):
                if b == c and (a, d) not in edges:
                    edges.add((a, d))
                    changed = True
    return edges


def _find_cycle(edges: Set[Tuple[str, str]]) -> Optional[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in set(graph)
             | {b for _, b in edges}}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GRAY
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color[m] == GRAY:
                return stack[stack.index(m):] + [m]
            if color[m] == WHITE:
                got = dfs(m)
                if got:
                    return got
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(color):
        if color[n] == WHITE:
            got = dfs(n)
            if got:
                return got
    return None


def _check_lock_order(analysis: _ModuleAnalysis,
                      declared_all: Set[Tuple[str, str]],
                      leaves: Set[str]) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    model = analysis.model
    for (src, dst), sites in sorted(analysis.edges.items()):
        if (src, dst) in declared_all:
            continue
        # leaf allowance is CROSS-module only (utility locks like the
        # live registry's): an undeclared intra-module nesting is a
        # finding even when the inner lock nests nothing further —
        # that is exactly how an inversion of a 2-lock order looks
        if dst in leaves and dst.split(".")[0] != src.split(".")[0]:
            continue
        qual, line = sites[0]
        out.append(finding(
            "lock-order", analysis.mod.rel, line,
            "%s->%s" % (src, dst),
            "nested acquisition %s -> %s is not an edge of the "
            "declared LOCK_ORDER partial order — declare the edge "
            "with the rest of the order or restructure "
            "(%d site(s), first at %s)" %
            (src, dst, len(sites), qual)))
    # declaration sanity: the declared order itself must be acyclic
    cyc = _find_cycle(set(model.declared))
    if cyc:
        out.append(finding(
            "lock-order", analysis.mod.rel, 0,
            "LOCK_ORDER", "declared LOCK_ORDER contains a cycle: %s"
            % " -> ".join(cyc)))
    return out


def _check_guarded_by(analysis: _ModuleAnalysis,
                      thread_reachable: Set[str]) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    model = analysis.model
    by_field: Dict[str, List[_Access]] = {}
    for acc in analysis.accesses:
        by_field.setdefault(acc.field, []).append(acc)
    for field, accs in sorted(by_field.items()):
        if field.split(".")[-1] in model.unguarded_ok:
            continue
        # dedupe per (line, held) — a function analyzed under multiple
        # contexts must not double-count a site — and drop constructor
        # accesses: __init__ runs before the object is shared, so its
        # lock-free writes are not races
        seen: Set[Tuple[int, Tuple[str, ...], bool]] = set()
        uniq: List[_Access] = []
        for acc in accs:
            if acc.func == "__init__":
                continue
            key = (acc.line, acc.held, acc.write)
            if key not in seen:
                seen.add(key)
                uniq.append(acc)
        writes = [a for a in uniq if a.write]
        guard_votes: Dict[str, int] = {}
        for a in writes:
            for h in a.held:
                guard_votes[h] = guard_votes.get(h, 0) + 1
        if not guard_votes:
            continue
        guard, votes = max(sorted(guard_votes.items()),
                           key=lambda kv: kv[1])
        if votes * 2 < len(writes):
            continue            # no dominant guard — not a lock-
        #                         managed field
        bad_writes = [a for a in writes if guard not in a.held]
        bad_reads = [a for a in uniq
                     if not a.write and guard not in a.held
                     and a.func in thread_reachable]
        if not bad_writes and not bad_reads:
            continue
        sites = ", ".join(sorted({"%s:%d" % (a.qual, a.line)
                                  for a in bad_writes + bad_reads}))
        kinds = []
        if bad_writes:
            kinds.append("%d write(s)" % len(bad_writes))
        if bad_reads:
            kinds.append("%d thread-reachable read(s)" % len(bad_reads))
        symbol = field if field.startswith("<module>") \
            else _owning_class(analysis, field)
        out.append(finding(
            "guarded-by", analysis.mod.rel,
            (bad_writes + bad_reads)[0].line, symbol,
            "field %s is dominantly guarded by %s (%d/%d locked "
            "writes) but %s bypass it (%s) — guard them or declare "
            "the field in UNGUARDED_OK with a reason" %
            (field, guard, votes, len(writes),
             " + ".join(kinds), sites)))
    return out


def _owning_class(analysis: _ModuleAnalysis, field: str) -> str:
    """Display symbol ``Class._field`` from the first qualname that
    touches the field."""
    for acc in analysis.accesses:
        if acc.field == field and "." in acc.qual:
            return "%s.%s" % (acc.qual.split(".")[0], field)
    return field


# ---------------------------------------------------------------------------
# handoff ordering (resolve-last) — lexical per-function check
# ---------------------------------------------------------------------------

def _check_resolve_last(mod: _Module,
                        model: _LockModel) -> List[Dict[str, Any]]:
    """A ``set_result``/``set_exception`` lexically BEFORE a later
    locked stats-commit block in the same function breaks the
    resolve-last discipline: a caller who saw its future done reads
    stats that miss its own batch."""
    out: List[Dict[str, Any]] = []
    for fn, qual in mod.qualnames.items():
        resolves: List[int] = []
        commits: List[int] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and _attr_tail(node.func) in ("set_result",
                                                  "set_exception"):
                resolves.append(node.lineno)
            elif isinstance(node, ast.With):
                if any(model.lock_expr_name(it.context_expr)
                       for it in node.items) \
                        and _has_self_counter_write(node):
                    commits.append(node.lineno)
        if resolves and commits and min(resolves) < max(commits):
            out.append(finding(
                "handoff-discipline", mod.rel, min(resolves), qual,
                "future resolved at line %d but a locked stats commit "
                "follows at line %d — resolve futures LAST, after "
                "every locked accounting commit (a caller who saw its "
                "future done must read stats that include its batch)"
                % (min(resolves), max(commits))))
    return out


def _has_self_counter_write(with_node: ast.With) -> bool:
    for node in ast.walk(with_node):
        tgt = None
        if isinstance(node, ast.AugAssign):
            tgt = node.target
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        if isinstance(tgt, ast.Attribute) \
                and isinstance(tgt.value, ast.Name) \
                and tgt.value.id == "self":
            return True
    return False


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _load_modules(root: Optional[str],
                  modules: Optional[Iterable[str]]) -> List[_Module]:
    root = root or os.path.join(REPO, "amgcl_tpu")
    base = os.path.dirname(root.rstrip(os.sep)) or REPO
    declared = modules is None
    names = tuple(modules) if modules is not None else CONCURRENT_MODULES
    out: List[_Module] = []
    for rel in names:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            if declared:
                # the real declared set: a rename/typo must fail the
                # gate loudly, never silently drop a module's
                # lock-order/guarded-by coverage (the lint discipline:
                # a file the analyzer cannot read cannot be audited)
                raise FileNotFoundError(
                    "declared concurrent module %r is missing under %s"
                    " — fix CONCURRENT_MODULES or restore the file"
                    % (rel, root))
            continue              # explicit fixture subsets may probe
        with open(path) as f:
            src = f.read()
        relpath = os.path.relpath(path, base).replace(os.sep, "/")
        out.append(_Module(path, relpath, ast.parse(src, filename=path)))
    return out


def _analyze(root: Optional[str] = None,
             modules: Optional[Iterable[str]] = None
             ) -> List[_ModuleAnalysis]:
    out = []
    for mod in _load_modules(root, modules):
        model = _LockModel(mod)
        analysis = _ModuleAnalysis(mod, model)
        analysis.run()
        _upgrade_mutator_writes(analysis)
        out.append(analysis)
    return out


def static_lock_graph(root: Optional[str] = None,
                      modules: Optional[Iterable[str]] = None
                      ) -> Dict[str, Any]:
    """The canonicalized static lock graph the runtime witness checks
    against: ``allowed`` (transitive closure of every declared
    LOCK_ORDER plus all statically observed intra-module edges),
    ``leaves`` (locks with no outgoing edge anywhere — an edge INTO a
    leaf is always legal), ``locks`` (canonical name -> kind) and
    ``observed`` (the statically derived edges with site counts)."""
    analyses = _analyze(root, modules)
    declared: Set[Tuple[str, str]] = set()
    observed: Dict[Tuple[str, str], int] = {}
    locks: Dict[str, str] = {}
    for a in analyses:
        declared |= set(a.model.declared)
        for (src, dst), sites in a.edges.items():
            observed[(src, dst)] = observed.get((src, dst), 0) \
                + len(sites)
        for name in a.model.locks:
            kind = a.model.locks[name]
            if kind == "cond" and a.model.alias.get(name) != name:
                continue        # canonicalizes onto its rlock
            locks[a.model.canonical(name)] = a.model.kind_of(name) \
                or kind
    allowed = _closure(declared | set(observed))
    srcs = {a for a, _ in allowed}
    leaves = {name for name in locks if name not in srcs}
    return {"allowed": sorted(allowed), "leaves": sorted(leaves),
            "locks": locks,
            "observed": {"%s->%s" % k: v
                         for k, v in sorted(observed.items())},
            "declared": sorted(declared)}


def run_concurrency(root: Optional[str] = None,
                    modules: Optional[Iterable[str]] = None
                    ) -> List[Dict[str, Any]]:
    """Run the four concurrency rules over the declared module set
    (or ``modules`` under ``root`` for fixtures). Returns findings in
    the lint schema, (file, line, rule) order."""
    analyses = _analyze(root, modules)
    declared_all: Set[Tuple[str, str]] = set()
    for a in analyses:
        declared_all |= set(a.model.declared)
    declared_all = _closure(declared_all)
    # leaves derive from the UNION graph: a lock is a leaf only when NO
    # module's code acquires anything while holding it
    srcs = {src for a in analyses for (src, _d) in a.edges} \
        | {a for a, _b in declared_all}
    all_locks: Set[str] = set()
    for a in analyses:
        for name in a.model.locks:
            all_locks.add(a.model.canonical(name))
    leaves = all_locks - srcs
    out: List[Dict[str, Any]] = []
    for a in analyses:
        thread_reachable = _reachable_from_threads(a.mod)
        out += a.findings
        out += _check_lock_order(a, declared_all, leaves)
        out += _check_guarded_by(a, thread_reachable)
        out += _check_resolve_last(a.mod, a.model)
    # cross-module cycle check over the union of everything
    union = declared_all | {e for a in analyses for e in a.edges}
    cyc = _find_cycle(set(union))
    if cyc:
        out.append(finding(
            "lock-order", "amgcl_tpu/analysis/concurrency.py", 0,
            "<union-graph>",
            "the union lock graph (declared + observed) contains a "
            "cycle: %s — a cross-module deadlock is reachable"
            % " -> ".join(cyc)))
    out.sort(key=lambda f: (f["file"], f["line"], f["rule"]))
    return out
