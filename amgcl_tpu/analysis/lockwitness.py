"""Runtime lock witness — the dynamic half of the concurrency
contract (``analysis/concurrency.py`` is the static half).

A static lock-order analyzer is only as honest as its model: it can
declare edges no execution ever takes, or miss edges executions DO
take (callbacks, cross-module calls, monkeypatched seams). The witness
closes that loop. Opt-in via ``AMGCL_TPU_LOCK_WITNESS=1``, it wraps
the declared concurrent modules' ``Lock``/``RLock``/``Condition``
objects (explicit ``maybe_instrument``/``maybe_wrap`` seams in each
constructor — no monkeypatching) and records, per process:

* **witnessed acquisition-order edges** — for every acquisition while
  other witnessed locks are held, one ``held -> acquired`` edge with a
  count. :func:`check_witness` asserts witnessed ⊆ static (the
  canonicalized graph :func:`concurrency.static_lock_graph` exports:
  declared ``LOCK_ORDER`` closure + statically observed edges +
  cross-module edges into leaf locks). Run under the chaos matrix
  (``faults/chaos.py`` folds the verdict in) this validates the
  analyzer against real multi-threaded executions.
* **hold-time histogram** — per lock: acquisition count, max and total
  held milliseconds (condition waits excluded — the lock is released
  while waiting). The ``lock_witness_max_hold_ms`` gauge source.
* **starvation/deadlock watchdog** — a blocking acquire that has not
  landed within ``AMGCL_TPU_LOCK_WITNESS_TIMEOUT_S`` (default 30)
  records a trip (lock name, waited seconds, holder at the time) and
  keeps waiting; a deadlock therefore shows up as repeating trips
  instead of a silent hang. Zero trips is a chaos-matrix acceptance
  criterion.

:func:`validate` is the one-call verdict (subset check + zero trips),
optionally emitting the ``lock_witness`` JSONL event and publishing
the ``lock_witness_*`` gauges onto a live registry.

Stdlib-only (the instrumented modules must stay importable without
jax); the bookkeeping path is a few dict updates under one meta-lock,
cheap enough to leave on for an entire chaos run.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))


def enabled() -> bool:
    """Kill switch — read per call so tests can flip it; wrapping
    itself happens at construction/import time of the instrumented
    objects."""
    return os.environ.get("AMGCL_TPU_LOCK_WITNESS") == "1"


def watchdog_timeout_s() -> float:
    """Blocking-acquire patience before a starvation trip (seconds)."""
    try:
        return float(os.environ.get("AMGCL_TPU_LOCK_WITNESS_TIMEOUT_S",
                                    "30"))
    except ValueError:
        return 30.0


# ---------------------------------------------------------------------------
# the witness state (process-global)
# ---------------------------------------------------------------------------

class _Witness:
    def __init__(self):
        self._meta = threading.Lock()      # plain, never wrapped
        self._tls = threading.local()
        #: (src, dst) -> count
        self.edges: Dict[Tuple[str, str], int] = {}
        #: name -> {count, max_ms, total_ms}
        self.holds: Dict[str, Dict[str, float]] = {}
        #: watchdog trip rows: {lock, waited_s, thread}
        self.trips: List[Dict[str, Any]] = []

    # -- per-thread held stack ----------------------------------------------

    def _stack(self) -> List[List[Any]]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def note_acquired(self, name: str) -> None:
        st = self._stack()
        reentrant = any(row[0] == name for row in st)
        if not reentrant:
            held = []
            for row in st:
                if row[0] not in held and row[0] != name:
                    held.append(row[0])
            if held:
                with self._meta:
                    for h in held:
                        key = (h, name)
                        self.edges[key] = self.edges.get(key, 0) + 1
        st.append([name, time.perf_counter()])

    def note_released(self, name: str) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] == name:
                row = st.pop(i)
                break
        else:
            return
        if any(r[0] == name for r in st):
            return          # still reentrantly held — not the
        #                     outermost release
        held_ms = (time.perf_counter() - row[1]) * 1e3
        with self._meta:
            h = self.holds.setdefault(
                name, {"count": 0, "max_ms": 0.0, "total_ms": 0.0})
            h["count"] += 1
            h["total_ms"] += held_ms
            if held_ms > h["max_ms"]:
                h["max_ms"] = held_ms

    def suspend_for_wait(self, name: str) -> int:
        """Condition.wait releases the lock: pop every reentrant frame
        of ``name`` from this thread's stack (closing the hold
        interval) and return how many to restore after the wakeup."""
        st = self._stack()
        depth = sum(1 for r in st if r[0] == name)
        if depth:
            # close the hold interval once (outermost), drop the rest
            self.note_released(name)
            self._tls.stack = [r for r in self._stack()
                               if r[0] != name]
        return depth

    def resume_after_wait(self, name: str, depth: int) -> None:
        # restore EXACTLY what was suspended: a wait() that raised
        # because the lock was never witness-held suspended zero
        # frames, and pushing one anyway would leave a phantom
        # permanently-held frame poisoning every later edge
        st = self._stack()
        now = time.perf_counter()
        for _ in range(depth):
            st.append([name, now])
        # deliberately NO edge recording: the wakeup re-acquires the
        # same lock the wait released — the ordering edge (if any) was
        # recorded at the original acquisition

    def note_trip(self, name: str, waited_s: float) -> None:
        with self._meta:
            self.trips.append({
                "lock": name, "waited_s": round(waited_s, 3),
                "thread": threading.current_thread().name})

    def snapshot(self) -> Dict[str, Any]:
        with self._meta:
            edges = [{"src": s, "dst": d, "count": c}
                     for (s, d), c in sorted(self.edges.items())]
            holds = {k: dict(v) for k, v in sorted(self.holds.items())}
            trips = list(self.trips)
        max_hold = max((h["max_ms"] for h in holds.values()),
                       default=0.0)
        return {"edges": edges, "edges_total": len(edges),
                "holds": holds, "max_hold_ms": round(max_hold, 3),
                "watchdog_trips": len(trips), "trips": trips}

    def reset(self) -> None:
        with self._meta:
            self.edges.clear()
            self.holds.clear()
            self.trips.clear()


_WITNESS = _Witness()


def _reset_for_tests() -> None:
    _WITNESS.reset()


# ---------------------------------------------------------------------------
# proxies
# ---------------------------------------------------------------------------

class _WitnessLock:
    """Transparent Lock/RLock proxy: same acquire/release surface,
    plus edge + hold bookkeeping and the starvation watchdog on
    indefinite blocking acquires."""

    __slots__ = ("_inner", "name")

    def __init__(self, name: str, inner):
        self._inner = inner
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if not blocking or (timeout is not None and timeout >= 0):
            ok = self._inner.acquire(blocking, -1 if timeout is None
                                     else timeout)
            if ok:
                _WITNESS.note_acquired(self.name)
            return ok
        patience = watchdog_timeout_s()
        t0 = time.perf_counter()
        while True:
            if self._inner.acquire(True, patience):
                _WITNESS.note_acquired(self.name)
                return True
            _WITNESS.note_trip(self.name, time.perf_counter() - t0)

    def release(self):
        _WITNESS.note_released(self.name)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # RLock internals (Condition's _release_save/_acquire_restore
    # protocol) pass through to the raw primitive — a Condition built
    # directly on a proxy still works, its wait instrumented only when
    # it is a _WitnessCondition
    def __getattr__(self, attr):
        return getattr(self._inner, attr)


class _WitnessCondition:
    """Condition proxy sharing a :class:`_WitnessLock` for its lock
    surface: ``with cond:`` acquisitions are witnessed under the
    LOCK's canonical name (a Condition on the module's RLock IS that
    lock), and ``wait`` suspends the hold bookkeeping for its
    duration — wait time must not pollute the hold histogram, and the
    wakeup re-acquisition is not a fresh ordering edge."""

    __slots__ = ("_cond", "_proxy")

    def __init__(self, cond: "threading.Condition", proxy: _WitnessLock):
        self._cond = cond
        self._proxy = proxy

    @property
    def name(self) -> str:
        return self._proxy.name

    def acquire(self, *a, **kw):
        return self._proxy.acquire(*a, **kw)

    def release(self):
        self._proxy.release()

    def __enter__(self):
        self._proxy.acquire()
        return self

    def __exit__(self, *exc):
        self._proxy.release()
        return False

    def wait(self, timeout: Optional[float] = None):
        depth = _WITNESS.suspend_for_wait(self._proxy.name)
        try:
            return self._cond.wait(timeout)
        finally:
            _WITNESS.resume_after_wait(self._proxy.name, depth)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        depth = _WITNESS.suspend_for_wait(self._proxy.name)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            _WITNESS.resume_after_wait(self._proxy.name, depth)

    def notify(self, n: int = 1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __getattr__(self, attr):
        return getattr(self._cond, attr)


# ---------------------------------------------------------------------------
# wrapping seams
# ---------------------------------------------------------------------------

def maybe_wrap(name: str, lock):
    """Module-level seam: ``_lock = maybe_wrap("flight._lock",
    threading.Lock())``. Identity when the witness is off (the
    decision is frozen at import/construction time — the chaos runner
    sets the env before anything imports)."""
    if not enabled():
        return lock
    if isinstance(lock, _LOCK_TYPES):
        return _WitnessLock(name, lock)
    if isinstance(lock, threading.Condition):
        proxy = _WitnessLock(name, lock._lock)
        return _WitnessCondition(lock, proxy)
    return lock


def maybe_instrument(obj, prefix: str) -> None:
    """Constructor seam: replace every ``threading`` lock/condition in
    ``obj.__dict__`` with a witnessed proxy named
    ``<prefix>.<attr>``. A Condition whose lock IS one of the object's
    own locks shares that lock's proxy (and its canonical name) — the
    ``_mem_cond``-rides-``_mem_lock`` idiom. No-op when the witness is
    off."""
    if not enabled():
        return
    lock_proxies: Dict[int, _WitnessLock] = {}
    items = list(vars(obj).items())
    for attr, val in items:
        if isinstance(val, _LOCK_TYPES):
            proxy = _WitnessLock("%s.%s" % (prefix, attr), val)
            lock_proxies[id(val)] = proxy
            setattr(obj, attr, proxy)
    for attr, val in items:
        if isinstance(val, threading.Condition):
            raw = val._lock
            proxy = lock_proxies.get(id(raw))
            if proxy is None:
                proxy = _WitnessLock("%s.%s" % (prefix, attr), raw)
            setattr(obj, attr, _WitnessCondition(val, proxy))


# ---------------------------------------------------------------------------
# reporting + the witnessed-⊆-static check
# ---------------------------------------------------------------------------

def report() -> Dict[str, Any]:
    """Snapshot of everything witnessed so far (JSON-clean)."""
    out = _WITNESS.snapshot()
    out["enabled"] = enabled()
    return out


def check_witness(graph: Optional[Dict[str, Any]] = None,
                  snapshot: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """Witnessed ⊆ static: every witnessed edge must be in the static
    graph's allowed set (declared LOCK_ORDER closure + statically
    observed edges) or point INTO a leaf lock of another module (the
    utility-lock allowance the static side grants too). Returns
    {ok, violations, edges_total, watchdog_trips, max_hold_ms}."""
    if graph is None:
        from amgcl_tpu.analysis import concurrency as _conc
        graph = _conc.static_lock_graph()
    snap = snapshot or report()
    allowed = {tuple(e) for e in graph.get("allowed", ())}
    leaves = set(graph.get("leaves", ()))
    violations = []
    for row in snap["edges"]:
        src, dst = row["src"], row["dst"]
        if (src, dst) in allowed:
            continue
        if dst in leaves and dst.split(".")[0] != src.split(".")[0]:
            continue
        violations.append(dict(row, reason="edge not in the static "
                               "lock graph"))
    ok = not violations and snap["watchdog_trips"] == 0
    return {"ok": ok, "violations": violations,
            "edges_total": snap["edges_total"],
            "watchdog_trips": snap["watchdog_trips"],
            "max_hold_ms": snap["max_hold_ms"]}


def publish_gauges(registry, snapshot: Optional[Dict[str, Any]] = None
                   ) -> None:
    """Publish the witness gauges onto a live registry
    (telemetry/live.py METRICS declares the names — the
    metric-name-literal contract)."""
    snap = snapshot or report()
    registry.set_gauge("lock_witness_edges", snap["edges_total"])
    registry.set_gauge("lock_witness_max_hold_ms", snap["max_hold_ms"])
    registry.set_gauge("lock_witness_watchdog_trips",
                       snap["watchdog_trips"])


def validate(emit: bool = False, registry=None) -> Dict[str, Any]:
    """The one-call verdict: subset check + zero watchdog trips, with
    the witnessed edges attached. ``emit=True`` writes one
    ``lock_witness`` JSONL event (the metrics.EVENT_FIELDS rollup
    spec aggregates it); ``registry`` additionally receives the
    ``lock_witness_*`` gauges."""
    snap = report()
    out = check_witness(snapshot=snap)
    out["edges"] = snap["edges"]
    if snap["trips"]:
        out["trips"] = snap["trips"]
    if registry is not None:
        try:
            publish_gauges(registry, snap)
        except Exception:          # noqa: BLE001 — a gauge publish
            pass                   # must not fail the verdict
    if emit:
        try:
            from amgcl_tpu.telemetry import sink as _sink
            _sink.emit({"event": "lock_witness", "ok": out["ok"],
                        "edges_total": out["edges_total"],
                        "max_hold_ms": out["max_hold_ms"],
                        "watchdog_trips": out["watchdog_trips"],
                        "edges": snap["edges"],
                        "violations": out["violations"]})
        except Exception:          # noqa: BLE001 — best-effort emit
            pass
    return out
