"""``python -m amgcl_tpu.analysis`` — run the linter and the jaxpr
auditor against the committed findings budget (ANALYSIS_BASELINE.json).

Exit status 0 when there are no NEW lint findings (anything not in the
baseline's suppression list) and no audit contract errors; 1 otherwise
— the same gate shape as ``bench.py --gate``. ``bench.py --check`` runs
this module and embeds the record.

The auditor needs a multi-device mesh for the collective census; when
jax has not been imported yet this module forces the test topology
(CPU backend, 8 virtual devices) exactly like tests/conftest.py, so the
audit sees the same programs CI tests.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_test_topology() -> None:
    """CPU backend, 8 virtual devices, x64 on — the tests/conftest.py
    topology, FORCED unconditionally: the audit is static (nothing
    executes), so the accelerator an ambient ``JAX_PLATFORMS`` points at
    is irrelevant, while the collective census silently degrades to a
    skip without the virtual mesh. jax reads XLA_FLAGS lazily at BACKEND
    initialization, so this works even though importing amgcl_tpu (which
    ``python -m`` does before this module runs) already imported jax —
    as long as no computation has happened yet, which is the case at
    CLI startup."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    # same defeat-the-plugin-override dance as tests/conftest.py
    from amgcl_tpu.utils.axon_guard import force_cpu_backend
    force_cpu_backend()
    import jax
    jax.config.update("jax_enable_x64", True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m amgcl_tpu.analysis", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--json", action="store_true",
                    help="emit the full record as one JSON object")
    ap.add_argument("--baseline", metavar="PATH",
                    help="findings-budget file (default: the committed "
                         "ANALYSIS_BASELINE.json)")
    ap.add_argument("--no-audit", action="store_true",
                    help="lint + concurrency only (no jax import; fast "
                         "enough for a pre-commit hook)")
    ap.add_argument("--no-concurrency", action="store_true",
                    help="skip the concurrency contract analyzer "
                         "(analysis/concurrency.py; default ON)")
    ap.add_argument("--root", metavar="DIR",
                    help="package root to analyze instead of the "
                         "installed amgcl_tpu/ (negative-injection "
                         "fixtures and forks)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline accepting every current "
                         "finding (reasons are kept for keys already "
                         "suppressed; new entries get a TODO reason to "
                         "fill in before committing)")
    args = ap.parse_args(argv)

    from amgcl_tpu import analysis

    baseline_path = args.baseline or analysis.BASELINE_PATH
    baseline = analysis.load_baseline(baseline_path)

    if args.write_baseline:
        findings = analysis.run_lint(root=args.root)
        if not args.no_concurrency:
            findings = findings + analysis.run_concurrency(
                root=args.root)
        old = {(s["rule"], s["file"], s["symbol"]): s.get("reason", "")
               for s in (baseline or {}).get("suppressions", [])}
        seen, sup = set(), []
        for f in findings:
            key = analysis.finding_key(f)
            if key in seen:
                continue
            seen.add(key)
            sup.append({"rule": key[0], "file": key[1], "symbol": key[2],
                        "reason": old.get(key,
                                          "TODO: justify or fix")})
        if args.no_concurrency:
            # a lint-only rewrite ran no concurrency rules: keep the
            # existing concurrency budget verbatim instead of silently
            # dropping it (the default run would then fail on 'new'
            # findings the analyzer had already accepted)
            for s in (baseline or {}).get("suppressions", []):
                if s.get("rule") in analysis.CONCURRENCY_RULES \
                        and analysis.finding_key(s) not in seen:
                    sup.append(s)
        with open(baseline_path, "w") as fh:
            json.dump({"version": 1, "suppressions": sup}, fh, indent=1)
            fh.write("\n")
        print("wrote %d suppression(s) to %s"
              % (len(sup), baseline_path))
        return 0

    if not args.no_audit:
        _force_test_topology()
    rec = analysis.run_all(baseline=baseline,
                           with_audit=not args.no_audit,
                           with_concurrency=not args.no_concurrency,
                           root=args.root)
    if args.json:
        print(json.dumps(rec, default=str))
    else:
        lint_rec = rec["lint"]
        print("Lint: %d finding(s), %d suppressed by baseline, %d new"
              % (lint_rec["total"], lint_rec["suppressed"],
                 len(lint_rec["new"])))
        if lint_rec["new"]:
            print(analysis.format_findings(lint_rec["new"]))
        if "concurrency" in rec:
            conc = rec["concurrency"]
            print("Concurrency: %d finding(s) over %d declared "
                  "module(s), %d suppressed by baseline, %d new"
                  % (conc["total"], len(conc["modules"]),
                     conc["suppressed"], len(conc["new"])))
            if conc["new"]:
                print(analysis.format_findings(conc["new"]))
        for s in lint_rec["stale_suppressions"]:
            print("stale suppression (finding gone — remove from "
                  "baseline): %s %s %s" % (s["rule"], s["file"],
                                           s["symbol"]))
        if "audit" in rec:
            from amgcl_tpu.analysis import jaxpr_audit
            print()
            print(jaxpr_audit.format_report(rec["audit"]))
        print()
        print("ANALYSIS %s" % ("OK" if rec["ok"] else "FAIL"))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
