"""Static analysis — the before-execution leg of the telemetry stack.

Three passes over three representations of the same programs:

* :mod:`amgcl_tpu.analysis.lint` — stdlib-``ast`` JAX-hazard linter over
  the source (bare ``jax.jit`` bypassing the compile watch, host syncs
  in traced loop bodies, ``np.*`` on tracers, undocumented
  ``AMGCL_TPU_*`` knobs, mutable defaults, Pallas calls without the
  ``interpret=`` CI seam, blocking calls under ad-hoc locks).
  Importable without jax.
* :mod:`amgcl_tpu.analysis.concurrency` — whole-module thread-safety
  analyzer over the declared concurrent control-plane modules
  (serve/service, serve/farm, the telemetry recorders): lock-order
  graph vs the ``LOCK_ORDER`` contracts declared next to the code,
  guarded-by inference with ``UNGUARDED_OK`` allowlists,
  condition-variable discipline, and future-handoff ordering. Its
  runtime counterpart, :mod:`amgcl_tpu.analysis.lockwitness`
  (``AMGCL_TPU_LOCK_WITNESS=1``), validates witnessed lock-order edges
  against the static graph under the chaos matrix.
* :mod:`amgcl_tpu.analysis.jaxpr_audit` — abstract-traces the solver /
  distributed / ``make_solver`` entry points (``jax.make_jaxpr``, no
  execution) and verifies the declared contracts: collective census vs
  ``ledger.DIST_CG_COLLECTIVES``, fused-tier engagement + vector-stream
  recount vs ``ledger.KRYLOV_VEC_STREAMS_FUSED``, dtype discipline,
  host callbacks in iteration bodies, buffer-donation state vs
  ``ledger.DONATION_CONTRACTS``, and the compile-watch entry-point
  drift check.

``python -m amgcl_tpu.analysis`` runs all of them against the committed
findings budget (``ANALYSIS_BASELINE.json``): new findings exit
nonzero, like the bench gate. ``bench.py --check`` embeds the same run
in its CI record.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from amgcl_tpu.analysis.lint import (  # noqa: F401  (public surface)
    RULES, apply_baseline, declared_metric_names, finding_key,
    format_findings, run_lint, undocumented_knobs, watched_entry_points,
)
from amgcl_tpu.analysis.concurrency import (  # noqa: F401
    CONCURRENCY_RULES, CONCURRENT_MODULES, run_concurrency,
    static_lock_graph,
)

#: committed findings budget at the repo root
BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "ANALYSIS_BASELINE.json")


def load_baseline(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    path = path or BASELINE_PATH
    try:
        with open(path) as f:
            return json.load(f)
    except OSError:
        return None


def run_all(baseline: Optional[Dict[str, Any]] = None,
            with_audit: bool = True,
            with_concurrency: bool = True,
            root: Optional[str] = None) -> Dict[str, Any]:
    """Lint + concurrency analyzer (+ jaxpr audit) against the one
    shared baseline. Returns a JSON-clean record with ``ok`` false on
    any new finding or audit error; the ``concurrency`` sub-record
    carries the counts ``bench.py --check`` embeds."""
    if baseline is None:
        baseline = load_baseline()
    findings = run_lint(root=root)
    conc = run_concurrency(root=root) if with_concurrency else []
    split = apply_baseline(findings + conc, baseline)
    conc_rules = set(CONCURRENCY_RULES)
    new_lint = [f for f in split["new"] if f["rule"] not in conc_rules]
    new_conc = [f for f in split["new"] if f["rule"] in conc_rules]
    sup_conc = sum(1 for f in split["suppressed"]
                   if f["rule"] in conc_rules)
    stale = split["stale"]
    if not with_concurrency:
        # a lint-only run produced no concurrency findings — the
        # committed concurrency suppressions are DISABLED here, not
        # stale, and must not be reported for removal
        stale = [s for s in stale if s["rule"] not in conc_rules]
    out: Dict[str, Any] = {
        "lint": {
            "total": len(findings),
            "new": new_lint,
            "suppressed": len(split["suppressed"]) - sup_conc,
            "stale_suppressions": stale,
            "rules": list(RULES),
        },
        "ok": not split["new"],
    }
    if with_concurrency:
        out["concurrency"] = {
            "total": len(conc),
            "new": new_conc,
            "suppressed": sup_conc,
            "modules": list(CONCURRENT_MODULES),
            "rules": list(CONCURRENCY_RULES),
        }
    if with_audit:
        from amgcl_tpu.analysis import jaxpr_audit
        audit = jaxpr_audit.run_audit()
        out["audit"] = {
            "records": audit["records"],
            "findings": audit["findings"],
            "errors": audit["errors"],
            "ok": audit["ok"],
        }
        out["ok"] = out["ok"] and audit["ok"]
    return out
