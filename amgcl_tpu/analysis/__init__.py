"""Static analysis — the before-execution leg of the telemetry stack.

Two passes over two representations of the same programs:

* :mod:`amgcl_tpu.analysis.lint` — stdlib-``ast`` JAX-hazard linter over
  the source (bare ``jax.jit`` bypassing the compile watch, host syncs
  in traced loop bodies, ``np.*`` on tracers, undocumented
  ``AMGCL_TPU_*`` knobs, mutable defaults, Pallas calls without the
  ``interpret=`` CI seam). Importable without jax.
* :mod:`amgcl_tpu.analysis.jaxpr_audit` — abstract-traces the solver /
  distributed / ``make_solver`` entry points (``jax.make_jaxpr``, no
  execution) and verifies the declared contracts: collective census vs
  ``ledger.DIST_CG_COLLECTIVES``, fused-tier engagement + vector-stream
  recount vs ``ledger.KRYLOV_VEC_STREAMS_FUSED``, dtype discipline,
  host callbacks in iteration bodies, buffer-donation state vs
  ``ledger.DONATION_CONTRACTS``, and the compile-watch entry-point
  drift check.

``python -m amgcl_tpu.analysis`` runs both against the committed
findings budget (``ANALYSIS_BASELINE.json``): new findings exit
nonzero, like the bench gate. ``bench.py --check`` embeds the same run
in its CI record.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from amgcl_tpu.analysis.lint import (  # noqa: F401  (public surface)
    RULES, apply_baseline, declared_metric_names, finding_key,
    format_findings, run_lint, undocumented_knobs, watched_entry_points,
)

#: committed findings budget at the repo root
BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "ANALYSIS_BASELINE.json")


def load_baseline(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    path = path or BASELINE_PATH
    try:
        with open(path) as f:
            return json.load(f)
    except OSError:
        return None


def run_all(baseline: Optional[Dict[str, Any]] = None,
            with_audit: bool = True) -> Dict[str, Any]:
    """Lint (+ jaxpr audit) against the baseline. Returns a JSON-clean
    record with ``ok`` false on any new lint finding or audit error."""
    if baseline is None:
        baseline = load_baseline()
    findings = run_lint()
    split = apply_baseline(findings, baseline)
    out: Dict[str, Any] = {
        "lint": {
            "total": len(findings),
            "new": split["new"],
            "suppressed": len(split["suppressed"]),
            "stale_suppressions": split["stale"],
            "rules": list(RULES),
        },
        "ok": not split["new"],
    }
    if with_audit:
        from amgcl_tpu.analysis import jaxpr_audit
        audit = jaxpr_audit.run_audit()
        out["audit"] = {
            "records": audit["records"],
            "findings": audit["findings"],
            "errors": audit["errors"],
            "ok": audit["ok"],
        }
        out["ok"] = out["ok"] and audit["ok"]
    return out
