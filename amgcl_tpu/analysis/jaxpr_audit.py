"""Jaxpr contract auditor — static verification of the backend contract.

PRs 2-5 built *models* of the solver programs (ledger byte/FLOP models,
comm models, fused stream tables, compile watch); this module checks the
*programs* against those models before anything executes. A jaxpr is a
complete, cheap-to-obtain IR: ``jax.make_jaxpr`` abstractly traces an
entry point without running it, and every property the models assert —
how many collectives an iteration issues, whether the fused vector tier
actually engaged, where precision changes — is a countable fact of that
IR. The passes:

* **collective census** — count ``psum``/``ppermute``/``all_gather``
  per iteration body (the outermost ``while`` of the traced solve) and
  assert equality with the declared comm contracts
  (``telemetry.ledger.DIST_CG_COLLECTIVES`` — the same table
  ``parallel.dist_solver`` prices its comm model from, so the model and
  the program are checked against ONE declaration). The pipelined CG's
  single stacked psum is verified down to its element count.
* **fusion engagement** — count the fused vector-algebra passes
  (``ops.fused_vec._fused_pass`` call sites in the iteration body) and
  recompute the per-iteration n-vector stream count from the jaxpr; the
  result must match ``ledger.KRYLOV_VEC_STREAMS_FUSED`` where the
  contract declares an exact value. A silently-dead fused path (env on,
  kernels not engaged) changes both counts and fails the audit.
* **dtype discipline** — flag ``convert_element_type`` on vector-sized
  values that narrows (f64→f32) or widens outside the declared
  mixed-precision seams (make_solver's precond cast, the df32 pair).
* **host sync / transfer** — flag ``pure_callback`` / debug callbacks /
  infeed-outfeed inside iteration bodies (a host round trip per
  iteration is the dispatch-overhead failure mode of VERDICT r5).
* **donation audit** — read the lowered program's input/output aliasing
  and assert it matches ``DONATION_CONTRACTS`` (all zero today: the
  groundwork check for ROADMAP item 1's resident solve loop — when
  donation lands, the contract is updated in the same commit or CI
  fails).

Vector-stream counting model (mirrors how KRYLOV_VEC_STREAMS_FUSED was
derived — the streaming floor of a perfectly fused backend):

* an engaged fused pass (``_fused_pass``, the compound kernels) moves
  exactly its vector operands: reads + writes, dots ride free;
* a standalone reduction (``dot_general``/``reduce_sum`` to a scalar)
  re-reads each distinct vector operand once;
* a maximal connected group of elementwise ops is ONE pass: its
  distinct external vector inputs are read once, its externally
  consumed vector outputs written once (XLA's elementwise fusion);
* operator applications (the SpMV kernels) and the preconditioner are
  charged by ``mv_cost``/``cycle_cost_model``, not as vector streams;
* guard-commit merges (``select_n`` / ``_where``) are register-level
  selects the floor does not charge.

Avals of size k·n count as k streams (Krylov basis matrices). ``n`` is
known to the audit (it builds the probe problem).
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "iter_eqns", "find_while_bodies", "collective_census",
    "vector_streams", "dtype_casts", "host_callbacks", "donation_audit",
    "audit_solver", "audit_dist_cg", "audit_make_solver", "audit_serve",
    "audit_setup", "check_setup", "audit_structure", "check_structure",
    "audit_entry_points", "audit_gather", "check_gather",
    "run_audit", "format_report",
]

# ---------------------------------------------------------------------------
# eqn classification
# ---------------------------------------------------------------------------

#: pjit callee names -> role. Operator kernels and the preconditioner
#: are charged by the ledger's mv_cost/cycle models, not as vector
#: streams; select merges are free at the streaming floor.
PJIT_ROLES = {
    "_fused_pass": "fused_vec",
    "dia_spmv": "spmv", "dia_spmv_dots": "spmv", "_dia_fused": "spmv",
    "dia_residual_dot": "spmv", "dia_residual_df": "spmv",
    "dense_window_spmv": "spmv", "dense_window_fused": "spmv",
    "windowed_ell_spmv": "spmv", "windowed_ell_fused": "spmv",
    "windowed_ell_spmv_dots": "spmv",
    "windowed_ell_block_spmv": "spmv", "windowed_ell_block_fused": "spmv",
    "windowed_ell_block_spmv_dots": "spmv",
    "gather_spmv": "spmv", "gather_spmv_xla": "spmv",
    "audit_precond": "precond", "apply": "precond",
    "_where": "select",
}

_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "neg", "abs", "sign", "max",
    "min", "exp", "log", "sqrt", "rsqrt", "integer_pow", "pow",
    "floor", "ceil", "round", "is_finite", "and", "or", "not", "xor",
    "eq", "ne", "lt", "le", "gt", "ge", "real", "imag", "conj",
    "convert_element_type", "broadcast_in_dim", "copy", "nextafter",
    "square", "tanh", "logistic", "erf", "clamp",
})

_REDUCE = frozenset({"reduce_sum", "reduce_max", "reduce_min",
                     "reduce_and", "reduce_or", "reduce_prod",
                     "dot_general", "argmax", "argmin"})

_COLLECTIVES = ("psum", "ppermute", "all_gather", "all_to_all",
                "pmax", "pmin", "axis_index")

_CONTROL = frozenset({"while", "scan", "cond"})

#: sub-jaxprs we deliberately do NOT descend into: Pallas kernel bodies
#: are VMEM-register programs (their internals are covered by the kernel
#: tests, and their memory behavior is what the stream model charges at
#: the call site).
_NO_DESCEND = frozenset({"pallas_call"})


def _subjaxprs(eqn) -> Iterable[Tuple[str, Any]]:
    """(param_name, jaxpr) for every jaxpr-valued param of ``eqn``."""
    if eqn.primitive.name in _NO_DESCEND:
        return
    for key, val in eqn.params.items():
        vals = val if isinstance(val, (list, tuple)) else [val]
        for v in vals:
            j = getattr(v, "jaxpr", v)
            if hasattr(j, "eqns"):
                yield key, j


def iter_eqns(jaxpr, path: str = "") -> Iterable[Tuple[Any, str]]:
    """Yield (eqn, path) over ``jaxpr`` and every sub-jaxpr (while/scan/
    cond/pjit/shard_map/custom_* bodies; Pallas kernels excluded)."""
    for eqn in jaxpr.eqns:
        yield eqn, path
        for key, sub in _subjaxprs(eqn):
            yield from iter_eqns(
                sub, path + "/" + eqn.primitive.name + ":" + key)


def find_while_bodies(jaxpr) -> List[Any]:
    """Body jaxprs of every ``while`` eqn, outermost first — index 0 is
    the solver's iteration body for every Krylov loop in this repo."""
    out = []
    for eqn, _ in iter_eqns(jaxpr):
        if eqn.primitive.name == "while":
            out.append(eqn.params["body_jaxpr"].jaxpr)
    return out


def _aval(v):
    return getattr(v, "aval", None)


def _size(v) -> int:
    a = _aval(v)
    shape = getattr(a, "shape", None)
    if shape is None:
        return 0
    return int(np.prod(shape)) if shape else 1


def _vec_weight(v, n: int) -> int:
    """Stream weight of a value: k for a size-k·n aval (k >= 1), else 0.
    Scalars, flags and small state buffers are free."""
    size = _size(v)
    if n <= 0 or size < n or size % n:
        return 0
    return size // n


# ---------------------------------------------------------------------------
# collective census
# ---------------------------------------------------------------------------

def collective_census(jaxpr) -> Dict[str, Any]:
    """Counts of the collective primitives in ``jaxpr`` (recursive),
    plus the element count each psum carries (the wire payload of the
    merged-reduction contract)."""
    counts: Dict[str, int] = {}
    psum_elems: List[int] = []
    for eqn, _ in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in _COLLECTIVES:
            counts[name] = counts.get(name, 0) + 1
            if name == "psum":
                psum_elems.append(sum(_size(v) for v in eqn.invars))
    out: Dict[str, Any] = {k: counts.get(k, 0)
                           for k in ("psum", "ppermute", "all_gather",
                                     "all_to_all")}
    out["psum_elems"] = psum_elems
    return out


# ---------------------------------------------------------------------------
# vector-stream counting
# ---------------------------------------------------------------------------

class _Node:
    __slots__ = ("kind", "role", "prim", "vin", "vout", "win", "wout")

    def __init__(self, kind, role, prim, vin, vout, win, wout):
        self.kind = kind          # elementwise | reduce | opaque | other
        self.role = role          # for opaque: fused_vec/spmv/precond/...
        self.prim = prim
        self.vin = vin            # [value ids] vector inputs
        self.vout = vout          # [value ids] vector outputs
        self.win = win            # [weights] aligned with vin
        self.wout = wout


def _flatten(jaxpr, n: int,
             roles: Optional[Dict[str, str]] = None
             ) -> Tuple[List[_Node], set]:
    """Flatten ``jaxpr`` into stream-model nodes. Unrecognized pjit
    calls are inlined (their eqns join the flat graph with value
    identity preserved across the call boundary); recognized kernel
    pjits stay opaque with their declared role."""
    roles = dict(PJIT_ROLES, **(roles or {}))
    nodes: List[_Node] = []
    counter = [0]

    def fresh():
        counter[0] += 1
        return counter[0]

    def run(jx, sub):
        def vid(atom):
            if not hasattr(atom, "count") and not hasattr(atom, "aval"):
                return None
            if type(atom).__name__ == "Literal":
                return None
            if atom not in sub:
                sub[atom] = fresh()
            return sub[atom]

        for eqn in jx.eqns:
            prim = eqn.primitive.name
            if prim == "pjit":
                pname = str(eqn.params.get("name", ""))
                role = roles.get(pname)
                if role is None:
                    inner = eqn.params["jaxpr"].jaxpr
                    isub: Dict[Any, int] = {}
                    for cv in inner.constvars:
                        isub[cv] = fresh()
                    for iv, outer in zip(inner.invars, eqn.invars):
                        oid = vid(outer)
                        isub[iv] = oid if oid is not None else fresh()
                    run(inner, isub)
                    for ov, outer in zip(inner.outvars, eqn.outvars):
                        iid = isub.get(ov)
                        sub[outer] = iid if iid is not None else fresh()
                    continue
                vin = [(vid(v), _vec_weight(v, n)) for v in eqn.invars]
                vout = [(vid(v), _vec_weight(v, n)) for v in eqn.outvars]
                nodes.append(_Node(
                    "opaque", role, pname,
                    [i for i, w in vin if w], [i for i, w in vout if w],
                    [w for _, w in vin if w], [w for _, w in vout if w]))
                continue
            if prim in ("select_n",):
                # guard-commit merge: free at the streaming floor, but
                # keep value identity so clusters stay connected
                for v in eqn.outvars:
                    vid(v)
                continue
            kind = ("elementwise" if prim in _ELEMENTWISE
                    else "reduce" if prim in _REDUCE
                    else "control" if prim in _CONTROL
                    else "other")
            vin = [(vid(v), _vec_weight(v, n)) for v in eqn.invars]
            vout = [(vid(v), _vec_weight(v, n)) for v in eqn.outvars]
            nodes.append(_Node(
                kind, None, prim,
                [i for i, w in vin if w and i is not None],
                [i for i, w in vout if w and i is not None],
                [w for i, w in vin if w and i is not None],
                [w for i, w in vout if w and i is not None]))

    sub: Dict[Any, int] = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        sub[v] = fresh()
    run(jaxpr, sub)
    # body outvars are externally consumed (loop carries)
    out_ids = {sub[v] for v in jaxpr.outvars if v in sub}
    return nodes, out_ids


def vector_streams(jaxpr, n: int,
                   roles: Optional[Dict[str, str]] = None
                   ) -> Dict[str, Any]:
    """Per-iteration n-vector stream count of a loop body, under the
    streaming-floor model documented in the module docstring. Returns
    the total plus its breakdown (fused passes, reductions, elementwise
    clusters, unmodeled 'other' nodes)."""
    nodes, out_ids = _flatten(jaxpr, n, roles)

    produced_by: Dict[int, _Node] = {}
    consumers: Dict[int, List[_Node]] = {}
    for node in nodes:
        for i in node.vout:
            produced_by[i] = node
        for i in node.vin:
            consumers.setdefault(i, []).append(node)

    # union-find over elementwise nodes connected by vector values
    parent: Dict[int, int] = {}

    def find(i):
        while parent.get(i, i) != i:
            parent[i] = parent.get(parent[i], parent[i])
            i = parent[i]
        return i

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    ew = [node for node in nodes if node.kind == "elementwise"]
    index = {id(node): k for k, node in enumerate(nodes)}
    for node in ew:
        parent.setdefault(index[id(node)], index[id(node)])
    for node in ew:
        for i in node.vin:
            prod = produced_by.get(i)
            if prod is not None and prod.kind == "elementwise":
                union(index[id(node)], index[id(prod)])

    clusters: Dict[int, List[_Node]] = {}
    for node in ew:
        clusters.setdefault(find(index[id(node)]), []).append(node)

    total = 0
    fused_passes = 0
    breakdown = {"fused": 0, "reduce": 0, "elementwise": 0, "other": 0}
    others: List[str] = []
    for node in nodes:
        if node.kind == "opaque":
            if node.role == "fused_vec":
                fused_passes += 1
                s = sum(node.win) + sum(node.wout)
                total += s
                breakdown["fused"] += s
            # spmv/precond/select: charged by the operator/cycle models
        elif node.kind == "reduce":
            s = sum(w for i, w in
                    dict(zip(node.vin, node.win)).items())
            total += s
            breakdown["reduce"] += s
        elif node.kind in ("other", "control"):
            s = sum(node.win) + sum(node.wout)
            total += s
            breakdown["other"] += s
            if s:
                others.append(node.prim)
    for members in clusters.values():
        member_set = {id(m) for m in members}
        ins: Dict[int, int] = {}
        outs: Dict[int, int] = {}
        for node in members:
            for i, w in zip(node.vin, node.win):
                prod = produced_by.get(i)
                if prod is None or id(prod) not in member_set:
                    ins[i] = w
            for i, w in zip(node.vout, node.wout):
                cons = consumers.get(i, [])
                ext = any(id(c) not in member_set for c in cons)
                if ext or i in out_ids:
                    outs[i] = w
        s = sum(ins.values()) + sum(outs.values())
        total += s
        breakdown["elementwise"] += s
    return {"streams": int(total), "fused_passes": int(fused_passes),
            "breakdown": breakdown, "unmodeled": sorted(set(others))}


# ---------------------------------------------------------------------------
# dtype discipline
# ---------------------------------------------------------------------------

def dtype_casts(jaxpr, n: int) -> List[Dict[str, Any]]:
    """Every ``convert_element_type`` on a vector-sized float value that
    changes the float width: the narrowings are the df32-path hazards,
    the widenings the literal-promotion drift."""
    out = []
    for eqn, path in iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = _aval(eqn.invars[0])
        dst = _aval(eqn.outvars[0])
        if src is None or dst is None or not _vec_weight(eqn.outvars[0], n):
            continue
        try:
            sdt, ddt = np.dtype(src.dtype), np.dtype(dst.dtype)
        except TypeError:
            continue
        if sdt.kind not in "fc" or ddt.kind not in "fc":
            continue
        if sdt.itemsize == ddt.itemsize:
            continue
        out.append({
            "kind": "downcast" if ddt.itemsize < sdt.itemsize
            else "upcast",
            "from": sdt.name, "to": ddt.name,
            "elements": _size(eqn.outvars[0]), "path": path})
    return out


# ---------------------------------------------------------------------------
# host sync / transfer
# ---------------------------------------------------------------------------

_HOST_PRIMS = ("pure_callback", "debug_callback", "io_callback",
               "infeed", "outfeed", "host_callback", "debug_print")


def host_callbacks(jaxpr) -> List[Dict[str, str]]:
    """Host round trips inside the (traced) program — each one inside
    an iteration body serializes the loop on the host."""
    out = []
    for eqn, path in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if any(name.startswith(p) or p in name for p in _HOST_PRIMS):
            out.append({"primitive": name, "path": path})
    return out


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------

def donation_audit(fn, *args, **kwargs) -> Dict[str, Any]:
    """Lower ``fn`` (a jitted/watched callable) and read the program's
    input->output buffer aliasing. Donation shows up in the StableHLO as
    ``tf.aliasing_output`` arg attributes; zero means every solve call
    allocates fresh result buffers (the resident-loop gap, ROADMAP 1)."""
    lower = getattr(fn, "lower", None)
    if lower is None:
        import jax
        fn = jax.jit(fn)
        lower = fn.lower
    lowered = lower(*args, **kwargs)
    try:
        text = lowered.as_text()
    except Exception:
        text = ""
    donated = text.count("tf.aliasing_output")
    return {"donated_args": int(donated),
            "aliasing_present": donated > 0}


# ---------------------------------------------------------------------------
# probe problems + env control
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _env(**overrides):
    """Set env knobs for the duration of a trace (every gate in ops/*
    reads its knob at trace time). ``None`` removes the variable."""
    saved = {}
    for key, val in overrides.items():
        saved[key] = os.environ.get(key)
        if val is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = str(val)
    try:
        yield
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val


@functools.lru_cache(maxsize=4)
def _probe_problem(m: int = 8):
    """Small 3-D Poisson DIA operator + rhs + Jacobi diagonal, f32 —
    large enough that every vector is unmistakably 'vector-sized'."""
    import jax.numpy as jnp
    from amgcl_tpu.ops import device as dev
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, rhs = poisson3d(m)
    Ad = dev.to_device(A, "dia", jnp.float32)
    rhs32 = jnp.asarray(rhs, jnp.float32)
    dinv = jnp.asarray(1.0 / A.diagonal(), jnp.float32)
    return Ad, rhs32, dinv


def _audit_precond(dinv):
    """A named, jitted Jacobi preconditioner: shows up in the traced
    body as one opaque ``audit_precond`` pjit (role 'precond'), exactly
    like the real hierarchy apply is priced — by the cycle model, not as
    Krylov vector streams."""
    import jax

    def audit_precond(r):
        return dinv * r
    return jax.jit(audit_precond)


#: trace-time env for the ENGAGED configuration: fused tier on and the
#: kernels routed through the interpret seam so the audit sees the
#: production jaxpr on any backend.
_ENGAGED_ENV = dict(AMGCL_TPU_FUSED_VEC="1", AMGCL_TPU_PALLAS="1",
                    AMGCL_TPU_PALLAS_INTERPRET="1")


def solver_registry() -> Dict[str, Any]:
    from amgcl_tpu import solver as S
    return {"CG": S.CG, "BiCGStab": S.BiCGStab, "BiCGStabL": S.BiCGStabL,
            "GMRES": S.GMRES, "FGMRES": S.FGMRES, "LGMRES": S.LGMRES,
            "IDRs": S.IDRs, "Richardson": S.Richardson,
            "PreOnly": S.PreOnly}


def audit_solver(name: str, fused: bool = True, m: int = 8,
                 solver=None, precond=None) -> Dict[str, Any]:
    """Abstractly trace one Krylov solver's ``solve`` and measure its
    iteration body: fused passes, vector streams, collectives, dtype
    casts, host callbacks. No execution — ``jax.make_jaxpr`` only.
    ``solver``/``precond`` override the probe defaults (the negative
    tests inject hazards through them; a custom precond must be a
    jitted function named ``audit_precond`` to keep the stream model's
    role classification)."""
    import jax
    Ad, rhs, dinv = _probe_problem(m)
    n = int(rhs.shape[0])
    if solver is None:
        solver = solver_registry()[name](maxiter=10)
    if precond is None:
        precond = _audit_precond(dinv)
    env = dict(_ENGAGED_ENV)
    if not fused:
        env["AMGCL_TPU_FUSED_VEC"] = "0"
    with _env(**env):
        jx = jax.make_jaxpr(
            lambda b: solver.solve(Ad, precond, b))(rhs)
    bodies = find_while_bodies(jx.jaxpr)
    rec: Dict[str, Any] = {"entry": "solver." + name, "n": n,
                           "fused_env": bool(fused),
                           "while_loops": len(bodies)}
    if not bodies:                        # PreOnly has no loop
        rec.update(streams=0, fused_passes=0,
                   collectives=collective_census(jx.jaxpr),
                   casts=dtype_casts(jx.jaxpr, n),
                   host_callbacks=host_callbacks(jx.jaxpr))
        return rec
    body = bodies[0]
    vs = vector_streams(body, n)
    rec.update(streams=vs["streams"], fused_passes=vs["fused_passes"],
               stream_breakdown=vs["breakdown"],
               unmodeled=vs["unmodeled"],
               collectives=collective_census(body),
               casts=dtype_casts(body, n),
               host_callbacks=host_callbacks(body))
    return rec


def audit_dist_cg(pipelined: bool = False, m: int = 8,
                  mesh=None) -> Dict[str, Any]:
    """Trace the distributed CG body over the available mesh and take
    the collective census of its iteration body."""
    import jax
    import jax.numpy as jnp
    from amgcl_tpu.parallel.mesh import (make_mesh, put_with_sharding,
                                         ROWS_AXIS)
    from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix
    from amgcl_tpu.parallel import dist_solver as ds
    from amgcl_tpu.utils.sample_problem import poisson3d
    from jax.sharding import NamedSharding, PartitionSpec as P

    nd_avail = len(jax.devices())
    if mesh is None:
        mesh = make_mesh(nd_avail)
    nd = int(mesh.shape[ROWS_AXIS])
    entry = "parallel.dist_cg_pipelined" if pipelined \
        else "parallel.dist_cg"
    if nd < 2:
        return {"entry": entry, "skipped":
                "collective census needs >= 2 devices (have %d); run "
                "via `python -m amgcl_tpu.analysis`, which forces a "
                "virtual 8-device mesh" % nd}
    A, rhs = poisson3d(m)
    Ad = DistDiaMatrix.from_csr(A, mesh)
    build = ds._compiled_dist_cg_pipelined if pipelined \
        else ds._compiled_dist_cg
    fn = build(mesh, Ad.offsets, Ad.shape, 10, 1e-6)
    vec = NamedSharding(mesh, P(ROWS_AXIS))
    f = put_with_sharding(jnp.ones(Ad.shape[0]), vec)
    x0 = put_with_sharding(jnp.zeros(Ad.shape[0]), vec)
    di = put_with_sharding(jnp.ones(Ad.shape[0]), vec)
    jx = jax.make_jaxpr(fn._jitted)(Ad.data, f, x0, di)
    bodies = find_while_bodies(jx.jaxpr)
    rec: Dict[str, Any] = {"entry": entry, "devices": nd,
                           "halo_width": int(Ad.halo),
                           "while_loops": len(bodies)}
    body = bodies[0]
    rec["collectives"] = collective_census(body)
    rec["host_callbacks"] = host_callbacks(body)
    rec["setup_collectives"] = collective_census(jx.jaxpr)
    return rec


def audit_comm_stages(mesh=None, m: int = 8) -> List[Dict[str, Any]]:
    """Abstractly trace every comm-measurement stage pair
    (telemetry/comm.py: halo / psum / representative iteration, measured
    + comm-ablated) over the available mesh and take the collective
    census of each — checked by :func:`check_comm_stages` against
    ``ledger.COMM_STAGE_CONTRACTS``. The measured variants must issue
    exactly the declared collectives; the ablated stand-ins must issue
    NONE (a collective surviving ablation poisons the subtraction that
    attributes comm wall time). ``jax.make_jaxpr`` only, no execution."""
    import jax
    from amgcl_tpu.parallel.mesh import make_mesh, ROWS_AXIS
    if mesh is None:
        mesh = make_mesh(len(jax.devices()))
    nd = int(mesh.shape[ROWS_AXIS])
    if nd < 2:
        return [{"entry": "telemetry.comm_stages", "skipped":
                 "collective census needs >= 2 devices (have %d); run "
                 "via `python -m amgcl_tpu.analysis`, which forces a "
                 "virtual 8-device mesh" % nd}]
    from amgcl_tpu.telemetry import comm as C
    from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix
    from amgcl_tpu.parallel.dist_ell import build_dist_ell
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, _ = poisson3d(m)
    ops = [DistDiaMatrix.from_csr(A, mesh), build_dist_ell(A, mesh)]
    recs: List[Dict[str, Any]] = []
    seen = set()
    for op in ops:
        for pipelined in (False, True):
            for st in C.comm_stages(op, mesh, pipelined=pipelined):
                for ablated in (False, True):
                    key = (st["contract"], ablated)
                    if key in seen:
                        continue        # halo/psum repeat across bodies
                    seen.add(key)
                    fn = st["fn_ablated"] if ablated else st["fn"]
                    jx = jax.make_jaxpr(getattr(fn, "_jitted", fn))(
                        *st["args"])
                    recs.append({
                        "entry": getattr(
                            fn, "_watched_name",
                            "telemetry.comm_%s%s"
                            % (st["key"],
                               "_ablated" if ablated else "")),
                        "stage": st["contract"], "ablated": ablated,
                        "devices": nd,
                        "collectives": collective_census(jx.jaxpr)})
    return recs


def check_comm_stages(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Findings for one audit_comm_stages record: measured stages must
    match ``ledger.COMM_STAGE_CONTRACTS`` collective for collective;
    ablated stand-ins must census to exactly 0."""
    from amgcl_tpu.telemetry.ledger import COMM_STAGE_CONTRACTS
    out: List[Dict[str, Any]] = []
    if rec.get("skipped"):
        out.append({"severity": "info", "pass": "collectives",
                    "entry": rec["entry"], "message": rec["skipped"]})
        return out
    kinds = ("psum", "ppermute", "all_gather", "all_to_all")
    got = {k: rec["collectives"].get(k, 0) for k in kinds}
    if rec["ablated"]:
        total = sum(got.values())
        if total != 0:
            out.append({
                "severity": "error", "pass": "collectives",
                "entry": rec["entry"],
                "message": "comm-ablated stand-in issues %d "
                "collective(s) (%s) — the ablation contract is a "
                "census of EXACTLY 0; any surviving collective "
                "poisons the measured-comm subtraction"
                % (total, {k: v for k, v in got.items() if v})})
        return out
    contract = COMM_STAGE_CONTRACTS.get(rec["stage"])
    if contract is None:
        return out
    want = {k: contract.get(k, 0) for k in kinds}
    if got != want:
        out.append({
            "severity": "error", "pass": "collectives",
            "entry": rec["entry"],
            "message": "measured comm stage %r census %s, contract "
            "says %s (ledger.COMM_STAGE_CONTRACTS) — the stage no "
            "longer measures what the model prices"
            % (rec["stage"], {k: v for k, v in got.items() if v},
               {k: v for k, v in want.items() if v})})
    return out


def audit_make_solver(mixed: bool = False, m: int = 8) -> Dict[str, Any]:
    """Trace ``make_solver._solve_fn`` (the fused P+S program) and audit
    dtype discipline across the whole program: with ``mixed`` the
    preconditioner runs one float width below the Krylov loop and the
    declared seam is exactly one downcast + one upcast per apply."""
    import jax
    import jax.numpy as jnp
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.solver.cg import CG
    from amgcl_tpu.utils.sample_problem import poisson3d

    A, rhs = poisson3d(m)
    n = len(rhs)
    if mixed:
        x64 = jax.config.jax_enable_x64
        if not x64:
            return {"entry": "make_solver._solve_fn", "mixed": True,
                    "skipped": "mixed-precision audit needs x64"}
        ms = make_solver(A, AMGParams(dtype=jnp.float32,
                                      coarse_enough=50),
                         solver=CG(maxiter=10),
                         solver_dtype=jnp.float64)
    else:
        ms = make_solver(A, AMGParams(dtype=jnp.float32,
                                      coarse_enough=50),
                         solver=CG(maxiter=10))
    rhs_dev = jnp.asarray(rhs, ms.solver_dtype)
    x0 = jnp.zeros_like(rhs_dev)
    with _env(**_ENGAGED_ENV):
        jx = jax.make_jaxpr(ms._solve_fn)(
            ms.A_dev, ms.A_dev64, ms.precond.hierarchy, rhs_dev, x0)
        # donation must be read off the PRODUCTION wrap (the same
        # watched_jit call __call__ runs), not a fresh jax.jit — donate
        # args configured there would be invisible to a re-wrap
        don = donation_audit(
            ms._wrapped_solve_fn(),
            ms.A_dev, ms.A_dev64, ms.precond.hierarchy, rhs_dev, x0)
    bodies = find_while_bodies(jx.jaxpr)
    body = bodies[0] if bodies else jx.jaxpr
    casts = dtype_casts(body, n)
    return {"entry": "make_solver._solve_fn", "mixed": bool(mixed),
            "n": n, "while_loops": len(bodies),
            "casts_per_iteration": casts,
            "downcasts": sum(1 for c in casts if c["kind"] == "downcast"),
            "upcasts": sum(1 for c in casts if c["kind"] == "upcast"),
            "host_callbacks": host_callbacks(body),
            "donation": don}


def audit_serve(m: int = 8, batch: int = 2) -> Dict[str, Any]:
    """Lower the resident serve loop's ACTUAL jit wrap
    (serve/service.py: ``SolverService._entry``, iterate buffer donated
    via ``donate_argnums``) over a stacked (n, B) probe and read the
    input→output buffer aliasing out of the lowered program — the
    static proof that the resident loop reuses its workspace instead of
    allocating per batch (ROADMAP item 1's donation contract)."""
    import jax.numpy as jnp
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.serve.service import SolverService
    from amgcl_tpu.solver.cg import CG
    from amgcl_tpu.utils.sample_problem import poisson3d

    A, rhs = poisson3d(m)
    ms = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=50),
                     solver=CG(maxiter=10))
    svc = SolverService(ms, batch=batch)
    rhs2 = jnp.tile(jnp.asarray(rhs, jnp.float32)[:, None], (1, batch))
    x0 = jnp.zeros_like(rhs2)
    don = donation_audit(svc._entry, ms.A_dev, ms.A_dev64,
                         ms.precond.hierarchy, rhs2, x0)
    return {"entry": "serve.solve_step", "n": len(rhs),
            "batch": int(batch), "donation": don}


def audit_setup(m: int = 6) -> List[Dict[str, Any]]:
    """Abstractly trace every device-setup entry point (the traced
    per-level hierarchy build: MIS rounds, segment-Galerkin, smoothing
    SpGEMM, stencil pair-Galerkin) and record host callbacks,
    collectives and float-width casts — checked by :func:`check_setup`
    against ``ledger.SETUP_CONTRACTS``. ``jax.make_jaxpr`` only, no
    execution."""
    import jax
    import jax.numpy as jnp
    from amgcl_tpu.coarsening import device_mis
    from amgcl_tpu.ops import segment_spgemm as seg

    recs: List[Dict[str, Any]] = []

    def record(entry, jx, n):
        recs.append({
            "entry": entry, "n": n,
            "collectives": collective_census(jx.jaxpr),
            "casts": [c for c in dtype_casts(jx.jaxpr, 1)
                      if c["elements"] >= n],
            "host_callbacks": host_callbacks(jx.jaxpr)})

    # MIS rounds: (n, K) ELL strength adjacency, static round count
    npad = 64
    cols = jnp.zeros((npad, 8), jnp.int32)
    valid = jnp.zeros((npad, 8), bool)
    prio = jnp.arange(1, npad + 1, dtype=jnp.int32)
    jx = jax.make_jaxpr(
        lambda c, v, p: device_mis.device_aggregates(c, v, p, rounds=4))(
        cols, valid, prio)
    record("coarsening.device_aggregates", jx, npad)

    nnz, nnz_c = 48, 16
    vals = jnp.ones(nnz, jnp.float32)
    take = jnp.arange(nnz, dtype=jnp.int32)
    sidx = jnp.zeros(nnz, jnp.int32)
    jx = jax.make_jaxpr(
        lambda v, t, s: seg._galerkin_kernel(
            v, t, s, jnp.float32(1.0), nnz_c))(vals, take, sidx)
    record("ops.segment_galerkin", jx, nnz)

    jx = jax.make_jaxpr(
        lambda a, b, ia, ib, s: seg._spgemm_kernel(a, b, ia, ib, s,
                                                   nnz_c))(
        vals, vals, take, take, sidx)
    record("ops.segment_spgemm", jx, nnz)

    jx = jax.make_jaxpr(
        lambda a, d, t, s: seg._smooth_kernel(
            a, d, t, s, jnp.float32(0.5), 8, nnz_c))(
        vals, vals, take, jnp.zeros(8 + nnz, jnp.int32))
    record("ops.transfer_smooth", jx, nnz)

    # stencil pair-Galerkin: a real small grid plan's generated device fn
    from amgcl_tpu.ops.stencil import StencilGalerkinPlan, \
        host_dia_from_csr
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, _ = poisson3d(m)
    Ad = host_dia_from_csr(A, (m, m, m), np.float32)
    plan = StencilGalerkinPlan(
        Ad.offsets3, Ad.offsets3, Ad.dims, (2, 2, 2),
        tuple(-(-d // 2) for d in (m, m, m)), np.float32)
    fn = plan._build_device_fn()
    a_dev = jnp.asarray(Ad.data)
    jx = jax.make_jaxpr(fn._jitted if hasattr(fn, "_jitted") else fn)(
        a_dev, a_dev)
    record("ops.stencil_galerkin", jx, int(Ad.nrows))
    return recs


def check_setup(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Findings for one audit_setup record against
    ``ledger.SETUP_CONTRACTS``: the traced per-level build must stay
    free of host callbacks and collectives, and must not change float
    width on matrix-sized values (the dtype seam is the host boundary,
    not the kernels)."""
    from amgcl_tpu.telemetry.ledger import SETUP_CONTRACTS
    contract = SETUP_CONTRACTS.get(rec["entry"])
    out: List[Dict[str, Any]] = []
    if contract is None:
        return out
    if len(rec["host_callbacks"]) != contract["host_callbacks"]:
        out.append({
            "severity": "error", "pass": "host-sync",
            "entry": rec["entry"],
            "message": "host callback %r inside the traced setup "
            "program — the per-level build must run device-side "
            "without host round trips"
            % rec["host_callbacks"][0]["primitive"]})
    cen = rec["collectives"]
    n_coll = sum(cen.get(k, 0) for k in ("psum", "ppermute",
                                         "all_gather", "all_to_all"))
    if n_coll != contract["collectives"]:
        out.append({
            "severity": "error", "pass": "collectives",
            "entry": rec["entry"],
            "message": "%d collective(s) in the serial setup program, "
            "contract says %d (the sharded MIS path has its own "
            "contract)" % (n_coll, contract["collectives"])})
    narrowing = [c for c in rec["casts"] if c["kind"] == "downcast"]
    if len(narrowing) != contract["narrowing_casts"]:
        out.append({
            "severity": "error", "pass": "dtype",
            "entry": rec["entry"],
            "message": "%d narrowing float cast(s) on matrix-sized "
            "values inside the setup kernel (contract: %d) — numeric "
            "rebuilds must stay bit-stable in the build dtype"
            % (len(narrowing), contract["narrowing_casts"])})
    return out


def audit_structure(m: int = 6) -> Dict[str, Any]:
    """Audit the operator X-ray's host-purity contract
    (``ledger.STRUCTURE_CONTRACTS``), two halves:

    * **static** — AST-scan ``telemetry/structure.py`` for imports of
      ``jax`` or of jax-importing ``amgcl_tpu.ops`` modules
      (``ops.csr`` is numpy-only and allowed): any hit means the
      "host-side analytics only" claim is structurally false.
    * **dynamic** — build a small hierarchy, snapshot the
      compile-watch totals, run a FULL ``structure_report`` (advisor
      included, every level) plus ``structure_findings``, and record
      the trace/compile delta: the X-ray must compile nothing beyond
      the entry points the build already created.
    """
    import ast
    import os as _os

    path = _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "telemetry", "structure.py")
    with open(path) as f:
        tree = ast.parse(f.read())
    jax_imports = []
    for node in ast.walk(tree):
        names = []
        if isinstance(node, ast.Import):
            names = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module:
            names = [node.module]
        for name in names:
            root = name.split(".")[0]
            if root == "jax" or (
                    name.startswith("amgcl_tpu.ops")
                    and not name.startswith("amgcl_tpu.ops.csr")):
                jax_imports.append(name)

    rec: Dict[str, Any] = {"entry": "telemetry.structure",
                           "jax_imports": len(jax_imports),
                           "jax_import_names": jax_imports}
    try:
        from amgcl_tpu.utils.sample_problem import poisson3d
        from amgcl_tpu.models.amg import AMG, AMGParams
        from amgcl_tpu.telemetry import compile_watch as cw
        from amgcl_tpu.telemetry.structure import structure_findings
        A, _ = poisson3d(m)
        amg = AMG(A, AMGParams(coarse_enough=20))
        before = cw.snapshot()["totals"]
        xray = amg.structure_report(advise=True)
        structure_findings(xray)
        after = cw.snapshot()["totals"]
        rec["new_traces"] = after["traces"] - before["traces"]
        rec["new_backend_compiles"] = (after["backend_compiles"]
                                       - before["backend_compiles"])
        rec["n_levels"] = len(xray.get("levels", []))
    except Exception as e:
        rec["skipped"] = "dynamic half failed: %r" % (e,)
    return rec


def check_structure(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Findings for the audit_structure record against
    ``ledger.STRUCTURE_CONTRACTS`` — the X-ray path must stay
    host-side (no jax imports) and compile-free (compile_watch delta
    0)."""
    from amgcl_tpu.telemetry.ledger import STRUCTURE_CONTRACTS
    contract = STRUCTURE_CONTRACTS.get(rec["entry"])
    out: List[Dict[str, Any]] = []
    if contract is None:
        return out
    if rec["jax_imports"] != contract["jax_imports"]:
        out.append({
            "severity": "error", "pass": "host-sync",
            "entry": rec["entry"],
            "message": "telemetry/structure.py imports %s — the "
            "operator X-ray is host-side analytics only (the module "
            "may use numpy/scipy and ops.csr, never jax or a "
            "jax-importing ops module)"
            % ", ".join(rec.get("jax_import_names", []))})
    if rec.get("skipped"):
        out.append({"severity": "info", "pass": "host-sync",
                    "entry": rec["entry"], "message": rec["skipped"]})
        return out
    for key in ("new_traces", "new_backend_compiles"):
        if rec.get(key, 0) != contract[key]:
            out.append({
                "severity": "error", "pass": "host-sync",
                "entry": rec["entry"],
                "message": "structure_report(advise=True) moved the "
                "process %s counter by %d (contract: %d) — the X-ray "
                "path compiled device work; it must stay predict-only"
                % (key, rec.get(key, 0), contract[key])})
    return out


def audit_gather() -> List[Dict[str, Any]]:
    """Abstractly trace the gather-SpMV pair (ops/pallas_gather.py) —
    the per-slot unrolled kernel (interpret build, so the trace works
    on any backend; the Pallas body itself is _NO_DESCEND territory)
    and its take-along XLA fallback — and record the same census
    :func:`audit_setup` keeps: host callbacks, collectives, float-width
    casts on matrix-sized values. Checked by :func:`check_gather`
    against ``ledger.GATHER_CONTRACTS``. ``jax.make_jaxpr`` only, no
    execution."""
    import jax
    import jax.numpy as jnp
    from amgcl_tpu.ops import pallas_gather as pg

    n_tiles, tile, K = 2, 1024, 4
    win = 2048
    n = n_tiles * tile
    starts = jnp.zeros(n_tiles, jnp.int32)
    cols = jnp.zeros((n_tiles, tile, K), jnp.int32)
    vals = jnp.ones((n_tiles, tile, K), jnp.float32)
    x = jnp.ones(n, jnp.float32)
    recs: List[Dict[str, Any]] = []
    for entry, fn in (
            ("ops.gather_spmv",
             lambda s, c, v, xv: pg.gather_spmv(
                 s, c, v, xv, win=win, n_out=n, interpret=True)),
            ("ops.gather_spmv_xla",
             lambda s, c, v, xv: pg.gather_spmv_xla(
                 s, c, v, xv, n_out=n))):
        try:
            jx = jax.make_jaxpr(fn)(starts, cols, vals, x)
            recs.append({
                "entry": entry, "n": n,
                "collectives": collective_census(jx.jaxpr),
                "casts": [c for c in dtype_casts(jx.jaxpr, 1)
                          if c["elements"] >= n],
                "host_callbacks": host_callbacks(jx.jaxpr)})
        except Exception as e:
            recs.append({"entry": entry,
                         "skipped": "trace failed: %r" % (e,)})
    return recs


def check_gather(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Findings for one audit_gather record against
    ``ledger.GATHER_CONTRACTS`` — the gather-SpMV pair must stay a pure
    streaming SpMV: no host callbacks, no collectives, no float-width
    casts on matrix-sized values."""
    from amgcl_tpu.telemetry.ledger import GATHER_CONTRACTS
    contract = GATHER_CONTRACTS.get(rec["entry"])
    out: List[Dict[str, Any]] = []
    if contract is None:
        return out
    if rec.get("skipped"):
        out.append({"severity": "info", "pass": "host-sync",
                    "entry": rec["entry"], "message": rec["skipped"]})
        return out
    if len(rec["host_callbacks"]) != contract["host_callbacks"]:
        out.append({
            "severity": "error", "pass": "host-sync",
            "entry": rec["entry"],
            "message": "host callback %r inside the gather-SpMV "
            "program — a device->host round trip per Krylov iteration "
            "serializes the solve"
            % rec["host_callbacks"][0]["primitive"]})
    cen = rec["collectives"]
    n_coll = sum(cen.get(k, 0) for k in ("psum", "ppermute",
                                         "all_gather", "all_to_all"))
    if n_coll != contract["collectives"]:
        out.append({
            "severity": "error", "pass": "collectives",
            "entry": rec["entry"],
            "message": "%d collective(s) in the single-device "
            "gather-SpMV, contract says %d (the sharded SpMV lives in "
            "parallel/)" % (n_coll, contract["collectives"])})
    narrowing = [c for c in rec["casts"] if c["kind"] == "downcast"]
    if len(narrowing) != contract["narrowing_casts"]:
        out.append({
            "severity": "error", "pass": "dtype",
            "entry": rec["entry"],
            "message": "%d narrowing float cast(s) on matrix-sized "
            "values inside the gather-SpMV (contract: %d) — the kernel "
            "accumulates in the value dtype; widening happens only at "
            "the declared output seam"
            % (len(narrowing), contract["narrowing_casts"])})
    return out


def check_serve(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Donation contract of the resident loop: the lowered program must
    alias exactly ``DONATION_CONTRACTS['serve.solve_step']`` argument
    buffers (1 — the donated iterate). Zero means every batch allocates
    fresh result storage; more means an undeclared donation landed."""
    from amgcl_tpu.telemetry.ledger import DONATION_CONTRACTS
    out = []
    if rec.get("skipped"):
        out.append({"severity": "info", "pass": "donation",
                    "entry": rec["entry"], "message": rec["skipped"]})
        return out
    want = DONATION_CONTRACTS.get(rec["entry"], 0)
    got = rec["donation"]["donated_args"]
    if got != want:
        out.append({
            "severity": "error", "pass": "donation",
            "entry": rec["entry"],
            "message": "resident serve loop aliases %d arg buffer(s), "
            "contract declares %d — the donated iterate buffer was "
            "lost (or a new donation is undeclared); update "
            "ledger.DONATION_CONTRACTS in the same commit" % (got, want)})
    return out


# ---------------------------------------------------------------------------
# contract checks
# ---------------------------------------------------------------------------

def check_solver(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Findings for one audit_solver record against the declared
    contracts (ledger.KRYLOV_FUSED_PASSES / KRYLOV_VEC_STREAMS_FUSED)."""
    from amgcl_tpu.telemetry.ledger import (KRYLOV_FUSED_PASSES,
                                            KRYLOV_VEC_STREAMS_FUSED)
    name = rec["entry"].split(".", 1)[1]
    out = []
    contract = KRYLOV_FUSED_PASSES.get(name)
    if rec.get("skipped") or contract is None:
        return out
    if rec["fused_env"]:
        want_passes, exact_streams = contract
        if rec["fused_passes"] != want_passes:
            out.append({
                "severity": "error", "pass": "fusion",
                "entry": rec["entry"],
                "message": "fused vector tier not engaged as declared: "
                "%d _fused_pass call(s) per iteration, contract says %d "
                "(AMGCL_TPU_FUSED_VEC on; a dead fused path shows up "
                "exactly like this)" % (rec["fused_passes"],
                                        want_passes)})
        if exact_streams and rec["streams"] != \
                KRYLOV_VEC_STREAMS_FUSED.get(name):
            out.append({
                "severity": "error", "pass": "fusion",
                "entry": rec["entry"],
                "message": "per-iteration vector streams = %d but the "
                "ledger's fused model charges %d "
                "(KRYLOV_VEC_STREAMS_FUSED['%s']) — either the body or "
                "the byte model drifted" % (
                    rec["streams"],
                    KRYLOV_VEC_STREAMS_FUSED.get(name), name)})
    else:
        if rec["fused_passes"] != 0:
            out.append({
                "severity": "error", "pass": "fusion",
                "entry": rec["entry"],
                "message": "AMGCL_TPU_FUSED_VEC=0 but %d fused pass(es) "
                "still trace in" % rec["fused_passes"]})
    out += _common_body_checks(rec)
    return out


def _common_body_checks(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    out = []
    for cb in rec.get("host_callbacks", []):
        out.append({
            "severity": "error", "pass": "host-sync",
            "entry": rec["entry"],
            "message": "host callback %r inside the iteration body "
            "(path %s): one host round trip per iteration"
            % (cb["primitive"], cb["path"] or "/")})
    for c in rec.get("casts", []):
        out.append({
            "severity": "error" if c["kind"] == "downcast" else "warning",
            "pass": "dtype", "entry": rec["entry"],
            "message": "%s %s->%s on a %d-element value inside the "
            "iteration body (no declared seam here)"
            % (c["kind"], c["from"], c["to"], c["elements"])})
    return out


def check_dist(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Collective census vs the declared comm contract — the same table
    dist_solver prices its SolveReport comm model from."""
    from amgcl_tpu.telemetry.ledger import DIST_CG_COLLECTIVES
    out = []
    if rec.get("skipped"):
        out.append({"severity": "info", "pass": "collectives",
                    "entry": rec["entry"], "message": rec["skipped"]})
        return out
    key = rec["entry"].rsplit(".", 1)[1]
    contract = DIST_CG_COLLECTIVES[key]
    census = rec["collectives"]
    if census["psum"] != contract["psums"]:
        out.append({
            "severity": "error", "pass": "collectives",
            "entry": rec["entry"],
            "message": "%d psum(s) per iteration, contract says %d — "
            "a collective crept into (or fell out of) the body; the "
            "SolveReport comm model prices dots=%d" % (
                census["psum"], contract["psums"], contract["psums"])})
    if contract.get("elems_per_psum") and census["psum_elems"] and \
            max(census["psum_elems"]) != contract["elems_per_psum"]:
        out.append({
            "severity": "error", "pass": "collectives",
            "entry": rec["entry"],
            "message": "stacked psum carries %r elements, contract says "
            "%d" % (census["psum_elems"], contract["elems_per_psum"])})
    want_pp = contract["spmvs"] * (2 if rec.get("halo_width", 0) > 0
                                   and rec.get("devices", 1) > 1 else 0)
    if census["ppermute"] != want_pp:
        out.append({
            "severity": "error", "pass": "collectives",
            "entry": rec["entry"],
            "message": "%d ppermute(s) per iteration, halo contract "
            "says %d (%d SpMV(s) x fwd+bwd ring exchange)"
            % (census["ppermute"], want_pp, contract["spmvs"])})
    for cb in rec.get("host_callbacks", []):
        out.append({
            "severity": "error", "pass": "host-sync",
            "entry": rec["entry"],
            "message": "host callback %r inside the distributed "
            "iteration body" % cb["primitive"]})
    return out


def check_make_solver(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    from amgcl_tpu.telemetry.ledger import DONATION_CONTRACTS
    out = []
    if rec.get("skipped"):
        out.append({"severity": "info", "pass": "dtype",
                    "entry": rec["entry"], "message": rec["skipped"]})
        return out
    allowed_down = 1 if rec["mixed"] else 0
    allowed_up = 1 if rec["mixed"] else 0
    if rec["downcasts"] != allowed_down or rec["upcasts"] != allowed_up:
        out.append({
            "severity": "error", "pass": "dtype",
            "entry": rec["entry"],
            "message": "iteration body has %d downcast(s)/%d upcast(s) "
            "of vector values; the declared mixed-precision seam allows "
            "exactly %d/%d (precond apply: r down, z up)"
            % (rec["downcasts"], rec["upcasts"], allowed_down,
               allowed_up)})
    for cb in rec.get("host_callbacks", []):
        out.append({
            "severity": "error", "pass": "host-sync",
            "entry": rec["entry"],
            "message": "host callback %r inside _solve_fn's iteration "
            "body" % cb["primitive"]})
    want = DONATION_CONTRACTS.get(rec["entry"], 0)
    got = rec["donation"]["donated_args"]
    if got != want:
        out.append({
            "severity": "error", "pass": "donation",
            "entry": rec["entry"],
            "message": "lowered program aliases %d arg buffer(s), "
            "contract declares %d — update "
            "ledger.DONATION_CONTRACTS with the resident-loop change "
            "that did this" % (got, want)})
    elif want == 0:
        out.append({
            "severity": "info", "pass": "donation",
            "entry": rec["entry"],
            "message": "no donated buffers: every solve allocates fresh "
            "x/r storage (ROADMAP item 1's resident loop will flip this "
            "contract)"})
    return out


def check_entry_points() -> List[Dict[str, Any]]:
    """Drift check: the watched_jit registrations the linter discovers
    in the source must be exactly compile_watch.DECLARED_ENTRY_POINTS
    (the once-upon-a-time docstring list, now code)."""
    from amgcl_tpu.analysis import lint
    from amgcl_tpu.telemetry import compile_watch as cw
    found = set(lint.watched_entry_points())
    declared = set(cw.DECLARED_ENTRY_POINTS)
    out = []
    for name in sorted(found - declared):
        out.append({
            "severity": "error", "pass": "entry-points", "entry": name,
            "message": "watched_jit(name=%r) exists in source but is "
            "not in compile_watch.DECLARED_ENTRY_POINTS" % name})
    for name in sorted(declared - found):
        out.append({
            "severity": "error", "pass": "entry-points", "entry": name,
            "message": "compile_watch.DECLARED_ENTRY_POINTS lists %r "
            "but no watched_jit registration with that name exists"
            % name})
    return out


def audit_entry_points() -> Dict[str, Any]:
    from amgcl_tpu.analysis import lint
    from amgcl_tpu.telemetry import compile_watch as cw
    return {"entry": "compile_watch.DECLARED_ENTRY_POINTS",
            "found": sorted(lint.watched_entry_points()),
            "declared": sorted(cw.DECLARED_ENTRY_POINTS)}


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def run_audit(solvers: Optional[Sequence[str]] = None,
              dist: bool = True) -> Dict[str, Any]:
    """Run every auditor pass; returns {"records": [...], "findings":
    [...], "ok": bool} with ok = no error-severity findings. Infos
    (donation groundwork, skipped passes) never fail the audit."""
    records: List[Dict[str, Any]] = []
    findings: List[Dict[str, Any]] = []
    names = list(solvers) if solvers else sorted(solver_registry())
    for name in names:
        for fused in (True, False):
            rec = audit_solver(name, fused=fused)
            records.append(rec)
            findings += check_solver(rec)
    if dist:
        for pipelined in (False, True):
            rec = audit_dist_cg(pipelined=pipelined)
            records.append(rec)
            findings += check_dist(rec)
        for rec in audit_comm_stages():
            records.append(rec)
            findings += check_comm_stages(rec)
    for mixed in (False, True):
        rec = audit_make_solver(mixed=mixed)
        records.append(rec)
        findings += check_make_solver(rec)
    rec = audit_serve()
    records.append(rec)
    findings += check_serve(rec)
    for rec in audit_setup():
        records.append(rec)
        findings += check_setup(rec)
    rec = audit_structure()
    records.append(rec)
    findings += check_structure(rec)
    for rec in audit_gather():
        records.append(rec)
        findings += check_gather(rec)
    findings += check_entry_points()
    errors = [f for f in findings if f["severity"] == "error"]
    return {"records": records, "findings": findings,
            "errors": len(errors), "ok": not errors}


def format_report(result: Dict[str, Any]) -> str:
    lines = ["Jaxpr audit: %d record(s), %d finding(s), %s" % (
        len(result["records"]), len(result["findings"]),
        "OK" if result["ok"] else "FAIL")]
    for rec in result["records"]:
        if rec.get("skipped"):
            lines.append("  %-34s SKIPPED (%s)" % (rec["entry"],
                                                   rec["skipped"]))
            continue
        bits = []
        if "streams" in rec:
            bits.append("streams=%d fused_passes=%d (tier %s)"
                        % (rec["streams"], rec["fused_passes"],
                           "on" if rec.get("fused_env") else "off"))
        cen = rec.get("collectives")
        if cen and (cen["psum"] or cen["ppermute"]):
            bits.append("psum=%d%s ppermute=%d" % (
                cen["psum"],
                "x%d" % max(cen["psum_elems"])
                if cen.get("psum_elems") else "",
                cen["ppermute"]))
        if "downcasts" in rec:
            bits.append("casts %dv/%d^ donated=%d" % (
                rec["downcasts"], rec["upcasts"],
                rec["donation"]["donated_args"]))
        elif "donation" in rec:
            bits.append("batch=%s donated=%d" % (
                rec.get("batch", "-"),
                rec["donation"]["donated_args"]))
        lines.append("  %-34s %s" % (rec["entry"], "  ".join(bits)))
    for f in result["findings"]:
        lines.append("  [%s/%s] %s: %s" % (f["severity"], f["pass"],
                                           f["entry"], f["message"]))
    return "\n".join(lines)
