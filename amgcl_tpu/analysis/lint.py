"""JAX-hazard AST linter — repo-wide static checks, stdlib ``ast`` only.

The runtime telemetry stack (PRs 1-4) observes what a solve DID; this
module checks what the source CAN do, before anything executes. Every
rule encodes a hazard this codebase has actually paid for (or a
discipline the jaxpr auditor depends on):

``bare-jit``
    ``jax.jit`` used directly instead of ``watched_jit``
    (telemetry/compile_watch.py). A bare-jit entry point compiles
    invisibly: its traces, retraces and compile seconds land in the
    ``<unwatched>`` bucket, so the PR-4 compile accounting undercounts
    exactly when it matters. Probe compiles (``.lower().compile()`` with
    the result thrown away) and one-shot setup programs are legitimate —
    they carry suppressions with reasons in ANALYSIS_BASELINE.json.
``host-sync-in-loop``
    ``.item()`` / ``float()`` / ``int()`` / ``bool()`` / ``np.asarray``
    / ``jax.device_get`` inside a ``lax.while_loop``/``scan``/
    ``fori_loop`` body function. Loop bodies are traced: these either
    fail at trace time or, worse, silently freeze a traced value into a
    Python constant.
``np-in-jit``
    ``np.*`` computation applied inside a traced loop body. NumPy calls
    on tracers raise ``TracerArrayConversionError`` at best; at worst a
    constant-folding call bakes trace-time values into the compiled
    program. Shape/dtype helpers (``np.dtype``, ``np.int32(3)`` style
    constants) are allowlisted.
``undocumented-knob``
    an ``AMGCL_TPU_*`` environment variable referenced under
    ``amgcl_tpu/`` with no row in README's environment-variable table —
    a knob nobody can discover is a knob that does not exist.
    (Generalizes tests/test_env_docs.py's grep; that test now asserts
    through this rule so there is ONE implementation.)
``mutable-default``
    a mutable literal (list/dict/set) as a default argument — the
    classic shared-state bug, and in solver parameter dataclasses a
    cross-instance parameter leak.
``pallas-no-interpret``
    a ``pl.pallas_call(...)`` without an ``interpret=`` argument. The CI
    story for every kernel in this repo is the interpret seam
    (AMGCL_TPU_PALLAS_INTERPRET routes the production dispatch through
    the kernels on CPU); a pallas_call that cannot be interpreted is a
    kernel CI cannot exercise.
``metric-name-literal``
    a live-registry update (``.inc(...)`` / ``.set_gauge(...)`` /
    ``.observe(...)``) whose metric name is not a string literal from
    the declared ``telemetry/live.py`` ``METRICS`` table — the one
    table the ``/metrics`` endpoint serves and the runtime registry
    validates against. An ad-hoc name would raise at serve time (or,
    with a private registry spec, scrape as a metric no dashboard
    knows); the rule makes both impossible to merge. Labeled updates
    (``inc("farm_tenant_requests_total", tenant=...)``) are checked the
    same way: every label KEY must be a literal keyword declared for
    that metric in the ``METRIC_LABELS`` table (label values stay
    runtime-free). The registry implementation itself
    (telemetry/live.py) is exempt — it passes names through variables
    by construction.

``swallowed-worker-exception``
    a bare ``except:`` (or ``except Exception/BaseException:``) whose
    body is only ``pass``/``continue``/``...`` inside the call tree of
    a thread-target function (``threading.Thread(target=...)`` /
    ``threading.Timer(..., fn)``, followed through same-module
    ``self.X()``/``X()`` calls). A worker loop that swallows an
    exception silently strands the futures riding on it — the exact
    failure mode the serve-worker supervisor (serve/service.py
    ``_worker_died``) exists to prevent; worker-path errors must route
    to futures or telemetry. Best-effort emit paths (flight-recorder
    dumps, ledger models) that genuinely have nowhere to route carry
    suppressions with reasons in ANALYSIS_BASELINE.json.

``blocking-call-under-lock``
    a known-blocking call — ``time.sleep``, a timeout-less thread
    ``join``, a ``queue.get``/``put`` with no timeout, a device sync,
    a ``Future.result`` — lexically inside a ``with <lock>:`` body.
    The cheap single-function version of the concurrency analyzer's
    handoff check (analysis/concurrency.py rule 4): the DECLARED
    concurrent modules get the full interprocedural treatment there
    and are skipped here, so one-off lock-holding helpers elsewhere
    stay covered. ``Condition.wait``/``wait_for`` are exempt (they
    release the lock while blocked).

Findings are plain dicts keyed for the baseline by ``(rule, file,
symbol)`` — line numbers are carried for display but excluded from the
key so unrelated edits above a finding do not churn the baseline.

The module also exposes :func:`watched_entry_points` — the statically
discovered ``watched_jit(..., name=...)`` call sites — which the jaxpr
auditor cross-checks against ``compile_watch.DECLARED_ENTRY_POINTS``
(the drift check between the PR-4 docstring list and reality).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

#: repo root (two levels above this file: amgcl_tpu/analysis/lint.py)
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: the rules this module implements, in report order
RULES = ("bare-jit", "host-sync-in-loop", "np-in-jit",
         "undocumented-knob", "mutable-default", "pallas-no-interpret",
         "metric-name-literal", "swallowed-worker-exception",
         "blocking-call-under-lock")

#: live-registry update methods the metric-name rule inspects (the
#: LiveRegistry public write surface, telemetry/live.py)
_METRIC_METHODS = frozenset({"inc", "set_gauge", "observe"})

_ENV_VAR = re.compile(r"AMGCL_TPU_[A-Z0-9_]+")
#: a documented row in README: a table cell holding the backticked
#: knob name (no example name in this comment — the reference scan
#: over amgcl_tpu/ would count it as an undocumented knob)
_ENV_ROW = re.compile(r"\|\s*`(AMGCL_TPU_[A-Z0-9_]+)`")

#: np.* attributes that are safe inside traced code (constants, dtype
#: and metadata helpers — they never touch a tracer's VALUES)
_NP_SAFE = frozenset({
    "dtype", "int8", "int16", "int32", "int64", "uint8", "uint16",
    "uint32", "uint64", "float16", "float32", "float64", "complex64",
    "complex128", "bool_", "intp", "pi", "e", "inf", "nan", "newaxis",
    "finfo", "iinfo", "ndim", "shape", "size", "promote_types",
    "result_type", "issubdtype", "floating", "complexfloating",
    "integer", "prod",
})

#: builtin calls that force a device sync / python conversion on a tracer
#: (``len`` is fine: shapes are static at trace time)
_HOST_SYNC_BUILTINS = frozenset({"float", "int", "bool", "complex"})


def finding(rule: str, file: str, line: int, symbol: str,
            message: str) -> Dict[str, Any]:
    return {"rule": rule, "file": file, "line": int(line),
            "symbol": symbol, "message": message}


def finding_key(f: Dict[str, Any]) -> Tuple[str, str, str]:
    """Baseline identity of a finding: (rule, file, symbol) — stable
    across unrelated edits that only move line numbers."""
    return (f["rule"], f["file"], f["symbol"])


# ---------------------------------------------------------------------------
# per-file AST analysis
# ---------------------------------------------------------------------------

class _Module:
    """One parsed file: alias maps, module-level string constants,
    function table with qualnames, loop-body function set."""

    def __init__(self, path: str, rel: str, tree: ast.Module):
        self.path = path
        self.rel = rel
        self.tree = tree
        #: local alias -> canonical module path ('numpy', 'jax',
        #: 'jax.lax', 'jax.experimental.pallas', ...)
        self.aliases: Dict[str, str] = {}
        #: names bound by `from M import n [as a]` -> 'M.n'
        self.from_imports: Dict[str, str] = {}
        #: module-level `NAME = "literal"` constants (watched_jit name=)
        self.str_consts: Dict[str, str] = {}
        #: every FunctionDef/AsyncFunctionDef/Lambda -> qualname
        self.qualnames: Dict[ast.AST, str] = {}
        #: function name -> [nodes] (for loop-body resolution by name)
        self.by_name: Dict[str, List[ast.AST]] = {}
        #: nodes that are lax.while_loop/scan/fori_loop body/cond fns
        self.loop_bodies: Set[ast.AST] = set()
        self._index()

    # -- indexing -----------------------------------------------------------

    def _index(self) -> None:
        # imports anywhere in the file (function-local `import jax` is
        # the norm in the lazy-import modules — capi, pyamgcl_compat)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    self.aliases[al.asname or al.name.split(".")[0]] = \
                        al.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for al in node.names:
                    self.from_imports[al.asname or al.name] = \
                        node.module + "." + al.name
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                self.str_consts[node.targets[0].id] = node.value.value
        # qualnames via a parent-tracking walk
        stack: List[str] = []

        def visit(node):
            is_scope = isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = ".".join(stack + [node.name])
                self.qualnames[node] = qn
                self.by_name.setdefault(node.name, []).append(node)
            if is_scope:
                stack.append(node.name)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()

        visit(self.tree)
        # loop-body discovery: names passed to lax loop combinators
        body_names: Set[str] = set()
        for call in self._calls():
            tail = _attr_tail(call.func)
            if tail in ("while_loop", "scan", "fori_loop") \
                    and self._is_laxish(call.func):
                for arg in call.args:
                    if isinstance(arg, ast.Name):
                        body_names.add(arg.id)
        for name in body_names:
            for node in self.by_name.get(name, ()):
                self.loop_bodies.add(node)

    def _calls(self) -> Iterable[ast.Call]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                yield node

    def _is_laxish(self, func: ast.AST) -> bool:
        """True when `func` is <x>.while_loop/... with <x> resolving to
        jax.lax (import jax; jax.lax.X / from jax import lax; lax.X /
        aliased _lax)."""
        if not isinstance(func, ast.Attribute):
            return False
        base = func.value
        if isinstance(base, ast.Name):
            target = self.from_imports.get(base.id) \
                or self.aliases.get(base.id)
            return target in ("jax.lax", "lax") or base.id in ("lax",
                                                               "_lax")
        if isinstance(base, ast.Attribute) and base.attr == "lax":
            return True
        return False

    # -- alias resolution ---------------------------------------------------

    def resolves_to(self, node: ast.AST, module: str,
                    attr: str) -> bool:
        """Does `node` (a Call.func) denote ``module.attr``? Handles
        `import module [as m]` + `m.attr`, and
        `from module import attr [as a]` + `a(...)`."""
        if isinstance(node, ast.Attribute) and node.attr == attr:
            base = node.value
            if isinstance(base, ast.Name):
                return self.aliases.get(base.id) == module \
                    or self.from_imports.get(base.id) == module
            return False
        if isinstance(node, ast.Name):
            return self.from_imports.get(node.id) == module + "." + attr
        return False

    def np_alias(self) -> Optional[str]:
        for alias, mod in self.aliases.items():
            if mod == "numpy":
                return alias
        return None


def _attr_tail(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _enclosing_symbol(mod: _Module, node: ast.AST) -> str:
    """Qualname of the innermost FunctionDef containing `node` (by line
    span), or '<module>'."""
    best, best_span = "<module>", None
    for fn, qn in mod.qualnames.items():
        lo = fn.lineno
        hi = getattr(fn, "end_lineno", fn.lineno)
        line = getattr(node, "lineno", None)
        if line is None or not (lo <= line <= hi):
            continue
        span = hi - lo
        if best_span is None or span < best_span:
            best, best_span = qn, span
    return best


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def _rule_bare_jit(mod: _Module) -> List[Dict[str, Any]]:
    out = []
    if mod.rel.endswith("telemetry/compile_watch.py"):
        return out          # the watcher wraps jax.jit by definition
    msg = ("jax.jit bypasses watched_jit: traces/retraces/compile "
           "seconds land in the <unwatched> bucket "
           "(telemetry/compile_watch.py)")
    for call in mod._calls():
        if mod.resolves_to(call.func, "jax", "jit"):
            out.append(finding("bare-jit", mod.rel, call.lineno,
                               _enclosing_symbol(mod, call), msg))
    # bare `@jax.jit` decorators are Attribute nodes, not Calls
    for fn, qn in mod.qualnames.items():
        for dec in getattr(fn, "decorator_list", ()):
            if not isinstance(dec, ast.Call) \
                    and mod.resolves_to(dec, "jax", "jit"):
                out.append(finding("bare-jit", mod.rel, dec.lineno, qn,
                                   msg))
    out.sort(key=lambda f: f["line"])
    return out


def _is_self_attr(node: ast.AST) -> bool:
    """``self.x`` (or ``self.x.y``) — solver config attributes are
    trace-time Python constants, not traced values."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name) and node.id == "self"


def _rule_loop_hazards(mod: _Module) -> List[Dict[str, Any]]:
    """host-sync-in-loop + np-in-jit over the discovered loop bodies."""
    out = []
    np_alias = mod.np_alias()
    for body in mod.loop_bodies:
        qn = mod.qualnames.get(body, "<module>")
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            tail = _attr_tail(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                out.append(finding(
                    "host-sync-in-loop", mod.rel, node.lineno, qn,
                    ".item() inside a traced loop body forces a device "
                    "sync / fails on a tracer"))
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in _HOST_SYNC_BUILTINS \
                    and node.args \
                    and not isinstance(node.args[0], ast.Constant) \
                    and not _is_self_attr(node.args[0]):
                out.append(finding(
                    "host-sync-in-loop", mod.rel, node.lineno, qn,
                    "%s() on a traced value inside a loop body is a "
                    "host sync (or a trace-time constant-fold)"
                    % node.func.id))
            elif mod.resolves_to(node.func, "jax", "device_get"):
                out.append(finding(
                    "host-sync-in-loop", mod.rel, node.lineno, qn,
                    "jax.device_get inside a traced loop body"))
            elif np_alias is not None \
                    and isinstance(node.func, ast.Attribute):
                # walk np.linalg.norm-style chains down to the base name
                chain = []
                base = node.func
                while isinstance(base, ast.Attribute):
                    chain.append(base.attr)
                    base = base.value
                if isinstance(base, ast.Name) and base.id == np_alias \
                        and chain[-1] not in _NP_SAFE:
                    out.append(finding(
                        "np-in-jit", mod.rel, node.lineno, qn,
                        "np.%s(...) inside a traced loop body operates "
                        "on tracers (use jnp or hoist to trace time)"
                        % ".".join(reversed(chain))))
            del tail
    return out


def _rule_mutable_default(mod: _Module) -> List[Dict[str, Any]]:
    out = []
    for fn, qn in mod.qualnames.items():
        args = fn.args
        for default in list(args.defaults) + [
                d for d in args.kw_defaults if d is not None]:
            bad = isinstance(default, (ast.List, ast.Dict, ast.Set))
            if not bad and isinstance(default, ast.Call):
                t = _attr_tail(default.func)
                bad = t in ("list", "dict", "set") and not default.args \
                    and not default.keywords
            if bad:
                out.append(finding(
                    "mutable-default", mod.rel, default.lineno, qn,
                    "mutable default argument is shared across calls"))
    return out


def _rule_pallas_interpret(mod: _Module) -> List[Dict[str, Any]]:
    out = []
    for call in mod._calls():
        if _attr_tail(call.func) != "pallas_call":
            continue
        kwargs = {kw.arg for kw in call.keywords}
        if "interpret" not in kwargs and None not in kwargs:
            out.append(finding(
                "pallas-no-interpret", mod.rel, call.lineno,
                _enclosing_symbol(mod, call),
                "pallas_call without an interpret= seam cannot be "
                "exercised by CPU CI (AMGCL_TPU_PALLAS_INTERPRET)"))
    return out


# ---------------------------------------------------------------------------
# swallowed-worker-exception rule (worker loops must route errors)
# ---------------------------------------------------------------------------

def _thread_target_functions(mod: _Module) -> List[ast.AST]:
    """Function nodes reachable from a thread entry point: the
    ``target=`` of a ``threading.Thread`` (or the callable of a
    ``threading.Timer``), closed transitively over same-module
    ``self.X()`` / bare ``X()`` calls — the static approximation of
    'code that runs on a worker thread'."""
    roots: Set[str] = set()
    for call in mod._calls():
        tail = _attr_tail(call.func)
        is_thread = tail == "Thread" \
            or mod.resolves_to(call.func, "threading", "Thread")
        is_timer = tail == "Timer" \
            or mod.resolves_to(call.func, "threading", "Timer")
        if not (is_thread or is_timer):
            continue
        tgt = next((kw.value for kw in call.keywords
                    if kw.arg == "target"), None)
        if tgt is None and is_timer and len(call.args) >= 2:
            tgt = call.args[1]
        if isinstance(tgt, ast.Name):
            roots.add(tgt.id)
        elif isinstance(tgt, ast.Attribute):
            roots.add(tgt.attr)
    nodes: List[ast.AST] = []
    seen: Set[str] = set()
    work = sorted(roots)
    while work:
        name = work.pop()
        if name in seen:
            continue
        seen.add(name)
        for fn in mod.by_name.get(name, ()):
            nodes.append(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self":
                    work.append(f.attr)
                elif isinstance(f, ast.Name):
                    work.append(f.id)
    return nodes


def _broad_handler(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True
    elts = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    return any(isinstance(e, ast.Name)
               and e.id in ("Exception", "BaseException")
               for e in elts)


def _trivial_body(body: List[ast.stmt]) -> bool:
    for st in body:
        if isinstance(st, (ast.Pass, ast.Continue)):
            continue
        if isinstance(st, ast.Expr) \
                and isinstance(st.value, ast.Constant) \
                and st.value.value is Ellipsis:
            continue
        return False
    return True


def _rule_swallowed_worker(mod: _Module) -> List[Dict[str, Any]]:
    out = []
    seen_handlers: Set[int] = set()
    for fn in _thread_target_functions(mod):
        for node in ast.walk(fn):
            if not isinstance(node, ast.ExceptHandler) \
                    or id(node) in seen_handlers:
                continue
            seen_handlers.add(id(node))
            if _broad_handler(node) and _trivial_body(node.body):
                out.append(finding(
                    "swallowed-worker-exception", mod.rel, node.lineno,
                    _enclosing_symbol(mod, node),
                    "broad except with a pass-only body inside a "
                    "thread-target call tree — a swallowed worker "
                    "error strands the futures riding on it; route it "
                    "to futures/telemetry (or suppress with a reason "
                    "for genuinely best-effort emits)"))
    out.sort(key=lambda f: f["line"])
    return out


# ---------------------------------------------------------------------------
# blocking-call-under-lock rule (rule 9 — the cheap lexical version of
# the concurrency analyzer's handoff check, for every module OUTSIDE
# the declared concurrent set so one-off lock-holding helpers are
# still covered)
# ---------------------------------------------------------------------------

#: with-item receivers that look like a mutual-exclusion primitive
_LOCKISH = re.compile(r"lock|cond|mutex", re.I)


def _lockish_name(expr: ast.AST) -> Optional[str]:
    """Name of a lock-looking ``with`` context (``self._lock`` /
    ``_LOCK`` / ``pool.lock``) — None for everything else, including
    calls (``open(...)``, ``lock_for(x)`` factories are out of
    scope)."""
    if isinstance(expr, ast.Attribute):
        return expr.attr if _LOCKISH.search(expr.attr) else None
    if isinstance(expr, ast.Name):
        return expr.id if _LOCKISH.search(expr.id) else None
    return None


def _blocking_call_shape(node: ast.Call) -> Optional[str]:
    """Human name of a known-blocking call shape, or None. Condition
    ``wait``/``wait_for`` are exempt (they release the lock). THE one
    classifier — the concurrency analyzer's interprocedural rule 4
    (analysis/concurrency.py) delegates here, so the two rules can
    never drift on what counts as blocking."""
    tail = _attr_tail(node.func)
    f = node.func
    kw = {k.arg for k in node.keywords}
    recv = f.value if isinstance(f, ast.Attribute) else None
    rname = recv.attr if isinstance(recv, ast.Attribute) \
        else recv.id if isinstance(recv, ast.Name) else ""
    if tail == "sleep" and (recv is None or rname == "time"):
        return "time.sleep()"
    if tail == "block_until_ready":
        return "jax.block_until_ready() (device sync)"
    if tail == "join" and recv is not None and not node.args \
            and "timeout" not in kw \
            and ("thread" in rname.lower() or rname in ("th", "worker")):
        return "%s.join() without a timeout" % rname
    if tail in ("get", "put") and recv is not None \
            and ("queue" in rname.lower() or rname == "q"):
        nonblocking = any(
            k.arg == "block" and isinstance(k.value, ast.Constant)
            and k.value.value is False for k in node.keywords)
        if "timeout" not in kw and len(node.args) < 2 \
                and not nonblocking:
            return "%s.%s() without a timeout" % (rname, tail)
    if tail == "result" and recv is not None \
            and "fut" in rname.lower() and "timeout" not in kw \
            and not node.args:
        return "%s.result() without a timeout" % rname
    return None


def _rule_blocking_under_lock(mod: _Module) -> List[Dict[str, Any]]:
    out = []

    def visit(node: ast.AST, lock: Optional[str]) -> None:
        if isinstance(node, ast.With):
            inner_lock = next((_lockish_name(it.context_expr)
                               for it in node.items
                               if _lockish_name(it.context_expr)),
                              None) or lock
            for it in node.items:
                visit(it, lock)
            for child in node.body:
                visit(child, inner_lock)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a closure DEFINED under the lock does not RUN under it —
            # its body restarts lock-free (and is reached exactly once)
            for child in ast.iter_child_nodes(node):
                visit(child, None)
            return
        if isinstance(node, ast.Call) and lock is not None \
                and _attr_tail(node.func) not in ("wait", "wait_for"):
            shape = _blocking_call_shape(node)
            if shape:
                out.append(finding(
                    "blocking-call-under-lock", mod.rel, node.lineno,
                    _enclosing_symbol(mod, node),
                    "%s inside a `with %s:` body — blocking while "
                    "holding a lock stalls every thread behind it "
                    "(move the blocking call outside the locked "
                    "region)" % (shape, lock)))
        for child in ast.iter_child_nodes(node):
            visit(child, lock)

    visit(mod.tree, None)
    out.sort(key=lambda f: f["line"])
    return out


# ---------------------------------------------------------------------------
# live-metric declaration rule (the /metrics contract)
# ---------------------------------------------------------------------------

def declared_metric_names(root: Optional[str] = None) -> Set[str]:
    """The keys of the ``METRICS`` dict literal in
    ``telemetry/live.py`` under ``root`` — parsed statically, so this
    is exactly the table the runtime registry (and therefore the
    ``/metrics`` endpoint) validates against. Empty when the file or
    the table is absent."""
    root = root or os.path.join(REPO, "amgcl_tpu")
    path = os.path.join(root, "telemetry", "live.py")
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return set()
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if any(isinstance(t, ast.Name) and t.id == "METRICS"
                   for t in targets) \
                    and isinstance(node.value, ast.Dict):
                return {k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
    return set()


def declared_metric_labels(root: Optional[str] = None
                           ) -> Dict[str, Tuple[str, ...]]:
    """The ``METRIC_LABELS`` dict literal in ``telemetry/live.py`` —
    metric name -> allowed label keys, parsed statically (the same
    table the runtime registry validates labeled updates against).
    Empty when the file or the table is absent."""
    root = root or os.path.join(REPO, "amgcl_tpu")
    path = os.path.join(root, "telemetry", "live.py")
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return {}
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if any(isinstance(t, ast.Name) and t.id == "METRIC_LABELS"
                   for t in targets) \
                    and isinstance(node.value, ast.Dict):
                out: Dict[str, Tuple[str, ...]] = {}
                for k, v in zip(node.value.keys, node.value.values):
                    if not (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)
                            and isinstance(v, (ast.Tuple, ast.List))):
                        continue
                    out[k.value] = tuple(
                        e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str))
                return out
    return {}


#: registry-method keyword args that are NOT metric labels (the write
#: surface's own parameters) — anything else keyword-shaped on an
#: inc/set_gauge/observe call is a label key the rule validates
_METRIC_KWARGS = frozenset({"name", "by", "value"})


def _rule_metric_name_literal(
        mod: _Module, declared: Set[str],
        declared_labels: Optional[Dict[str, Tuple[str, ...]]] = None
        ) -> List[Dict[str, Any]]:
    if mod.rel.endswith("telemetry/live.py"):
        return []       # the registry implementation: names arrive in
        #                 variables, validated at runtime against METRICS
    declared_labels = declared_labels or {}
    out = []
    for call in mod._calls():
        if not isinstance(call.func, ast.Attribute) \
                or call.func.attr not in _METRIC_METHODS:
            continue
        # the metric name may ride positionally or as name= (the
        # registry methods accept both) — resolve either form
        arg = call.args[0] if call.args else next(
            (kw.value for kw in call.keywords if kw.arg == "name"),
            None)
        if arg is None:
            continue
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in declared:
                out.append(finding(
                    "metric-name-literal", mod.rel, call.lineno,
                    arg.value,
                    "live metric %r is not declared in telemetry/live"
                    ".py METRICS — the /metrics endpoint serves only "
                    "the declared table, and the registry raises on "
                    "unknown names" % arg.value))
                continue
            # labeled update: every label KEY must be declared for this
            # metric in METRIC_LABELS (label values stay runtime-free —
            # tenant names arrive with traffic); a **splat hides the
            # keys from static analysis, so it is rejected outright
            allowed = declared_labels.get(arg.value, ())
            for kw in call.keywords:
                if kw.arg in _METRIC_KWARGS:
                    continue
                if kw.arg is None:
                    out.append(finding(
                        "metric-name-literal", mod.rel, call.lineno,
                        arg.value,
                        "labels for live metric %r must be literal "
                        "keyword arguments (no **splat) so the "
                        "declared METRIC_LABELS keys are statically "
                        "checkable" % arg.value))
                elif kw.arg not in allowed:
                    out.append(finding(
                        "metric-name-literal", mod.rel, call.lineno,
                        arg.value,
                        "label %r is not declared for live metric %r "
                        "in telemetry/live.py METRIC_LABELS — the "
                        "registry raises on undeclared label keys"
                        % (kw.arg, arg.value)))
        else:
            out.append(finding(
                "metric-name-literal", mod.rel, call.lineno,
                _enclosing_symbol(mod, call),
                "live metric name must be a string literal from the "
                "declared telemetry/live.py METRICS table (no ad-hoc "
                "or computed metric names)"))
    return out


# ---------------------------------------------------------------------------
# env-knob documentation rule (the test_env_docs implementation)
# ---------------------------------------------------------------------------

def referenced_env_vars(root: Optional[str] = None) -> Set[str]:
    """Every AMGCL_TPU_* name referenced under ``amgcl_tpu/`` (prose
    stems like ``AMGCL_TPU_PEAK_{GBPS,FLOPS}`` keep their stem with the
    trailing underscore stripped)."""
    root = root or os.path.join(REPO, "amgcl_tpu")
    refs: Set[str] = set()
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                for match in _ENV_VAR.findall(f.read()):
                    refs.add(match.rstrip("_"))
    return refs


def documented_env_vars(readme: Optional[str] = None) -> Set[str]:
    readme = readme or os.path.join(REPO, "README.md")
    try:
        with open(readme) as f:
            return set(_ENV_ROW.findall(f.read()))
    except OSError:
        return set()


def undocumented_knobs(root: Optional[str] = None,
                       readme: Optional[str] = None) -> List[str]:
    """Referenced-but-undocumented knob names (the rule's payload; a
    stem is covered when a longer documented name extends it)."""
    refs = referenced_env_vars(root)
    documented = documented_env_vars(readme)
    return sorted(v for v in refs - documented
                  if not any(d.startswith(v + "_") for d in documented))


def _rule_undocumented_knob(root: Optional[str],
                            readme: Optional[str]) -> List[Dict[str, Any]]:
    return [finding(
        "undocumented-knob", "README.md", 0, var,
        "%s is referenced under amgcl_tpu/ but has no row in README's "
        "environment-variable table" % var)
        for var in undocumented_knobs(root, readme)]


# ---------------------------------------------------------------------------
# watched_jit discovery (consumed by the jaxpr auditor's drift check)
# ---------------------------------------------------------------------------

def watched_entry_points(root: Optional[str] = None) -> Dict[str, List[str]]:
    """Statically discovered ``watched_jit(...)`` call sites:
    ``{watch name: [file:line, ...]}``. The ``name=`` argument is
    resolved from a string literal or a module-level string constant;
    call sites with a dynamic name map under ``<dynamic>``."""
    out: Dict[str, List[str]] = {}
    for mod in _modules(root):
        if mod.rel.endswith("telemetry/compile_watch.py"):
            continue        # the definition site, not a registration
        for call in mod._calls():
            tail = _attr_tail(call.func)
            if tail not in ("watched_jit", "_watched_jit"):
                # decorator form: functools.partial(watched_jit, name=...)
                if not (tail == "partial" and call.args
                        and _attr_tail(call.args[0])
                        in ("watched_jit", "_watched_jit")):
                    continue
            name = "<dynamic>"
            for kw in call.keywords:
                if kw.arg != "name":
                    continue
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    name = kw.value.value
                elif isinstance(kw.value, ast.Name):
                    name = mod.str_consts.get(kw.value.id, "<dynamic>")
            out.setdefault(name, []).append(
                "%s:%d" % (mod.rel, call.lineno))
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _modules(root: Optional[str] = None) -> List[_Module]:
    root = root or os.path.join(REPO, "amgcl_tpu")
    base = os.path.dirname(root.rstrip(os.sep)) or REPO
    mods = []
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, base).replace(os.sep, "/")
            with open(path) as f:
                src = f.read()
            # a SyntaxError propagates: a file the linter cannot parse
            # cannot be audited, and python itself will not import it —
            # fail loudly rather than silently skipping the file
            tree = ast.parse(src, filename=path)
            mods.append(_Module(path, rel, tree))
    return mods


def run_lint(root: Optional[str] = None,
             readme: Optional[str] = None,
             rules: Optional[Iterable[str]] = None) -> List[Dict[str, Any]]:
    """Run the AST rules over ``root`` (default: the installed
    ``amgcl_tpu`` package) and the knob-doc rule against ``readme``.
    Returns findings in (file, line) order."""
    want = set(rules) if rules is not None else set(RULES)
    out: List[Dict[str, Any]] = []
    ast_rules = want & {"bare-jit", "host-sync-in-loop", "np-in-jit",
                        "mutable-default", "pallas-no-interpret",
                        "metric-name-literal",
                        "swallowed-worker-exception",
                        "blocking-call-under-lock"}
    concurrent_set: Tuple[str, ...] = ()
    if "blocking-call-under-lock" in want:
        # the declared concurrent modules get the FULL interprocedural
        # check (analysis/concurrency.py rule 4); this cheap lexical
        # rule covers everything else. Function-level import — the
        # concurrency module imports this one at module level.
        from amgcl_tpu.analysis.concurrency import CONCURRENT_MODULES
        concurrent_set = CONCURRENT_MODULES
    declared = declared_metric_names(root) \
        if "metric-name-literal" in want else set()
    declared_labels = declared_metric_labels(root) \
        if "metric-name-literal" in want else {}
    for mod in (_modules(root) if ast_rules else []):
        if "bare-jit" in want:
            out += _rule_bare_jit(mod)
        if want & {"host-sync-in-loop", "np-in-jit"}:
            out += [f for f in _rule_loop_hazards(mod)
                    if f["rule"] in want]
        if "mutable-default" in want:
            out += _rule_mutable_default(mod)
        if "pallas-no-interpret" in want:
            out += _rule_pallas_interpret(mod)
        if "metric-name-literal" in want:
            out += _rule_metric_name_literal(mod, declared,
                                             declared_labels)
        if "swallowed-worker-exception" in want:
            out += _rule_swallowed_worker(mod)
        if "blocking-call-under-lock" in want \
                and not any(mod.rel.endswith(rel)
                            for rel in concurrent_set):
            out += _rule_blocking_under_lock(mod)
    if "undocumented-knob" in want:
        out += _rule_undocumented_knob(root, readme)
    out.sort(key=lambda f: (f["file"], f["line"], f["rule"]))
    return out


# ---------------------------------------------------------------------------
# baseline: accepted findings with reasons (the findings budget)
# ---------------------------------------------------------------------------

def apply_baseline(findings: List[Dict[str, Any]],
                   baseline: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Split findings against a baseline's suppression list.

    ``baseline["suppressions"]`` entries carry {rule, file, symbol,
    reason}; a finding whose :func:`finding_key` matches is accepted.
    Returns {"new": [...], "suppressed": [...], "stale": [...]} — new
    findings fail the gate (like the bench gate's regressions), stale
    suppressions are reported so the baseline can shrink."""
    sup = {(s["rule"], s["file"], s["symbol"]): s
           for s in (baseline or {}).get("suppressions", [])}
    new, suppressed = [], []
    seen = set()
    for f in findings:
        key = finding_key(f)
        seen.add(key)
        if key in sup:
            suppressed.append(dict(f, reason=sup[key].get("reason", "")))
        else:
            new.append(f)
    stale = [dict(zip(("rule", "file", "symbol"), key),
                  reason=s.get("reason", ""))
             for key, s in sup.items() if key not in seen]
    return {"new": new, "suppressed": suppressed, "stale": stale}


def format_findings(findings: List[Dict[str, Any]]) -> str:
    if not findings:
        return "(no findings)"
    return "\n".join("%s:%s: [%s] %s (%s)" % (
        f["file"], f.get("line", "?"), f["rule"], f["message"],
        f["symbol"]) for f in findings)
