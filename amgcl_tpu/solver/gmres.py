"""Restarted GMRES(m) and flexible FGMRES(m).

Arnoldi with classical Gram-Schmidt (one reorthogonalization pass — CGS2,
the right choice on TPU where the two passes are two big matmuls instead of
j sequential dots) and Givens rotations for the least-squares update
(reference behavior: amgcl/solver/gmres.hpp:72-322,
amgcl/solver/detail/givens_rotations.hpp; flexible variant
amgcl/solver/fgmres.hpp). The inner Arnoldi iteration is a
``lax.while_loop`` whose carry holds the (m+1, n) basis; early exit on
convergence leaves unwritten columns zero, which the masked triangular solve
treats as inactive.

GMRES is left-preconditioned (residual measured in the preconditioned norm);
FGMRES is right-preconditioned with a per-step preconditioner space Z —
usable with a nonstationary preconditioner.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops import fused_vec as fv
from amgcl_tpu.telemetry.history import HistoryMixin


def _givens(a, b):
    """Complex-safe Givens rotation: c real, s = phase(a)·conj(b)/h, so that
    [c s; -conj(s) c] @ [a; b] = [phase(a)·h; 0] (LAPACK zrotg convention)."""
    absa = jnp.abs(a)
    h = jnp.sqrt(absa ** 2 + jnp.abs(b) ** 2)
    h = jnp.where(h == 0, 1.0, h)
    pha = jnp.where(absa == 0, jnp.ones_like(a),
                    a / jnp.where(absa == 0, 1.0, absa))
    return (absa / h).astype(a.dtype), pha * jnp.conj(b) / h


def _arnoldi_cycle(apply_op, r0, m, eps, dot, direction=None, n_steps=None,
                   hist=None, hist_base=0, hist_scale=1.0, health=None,
                   guard_step=None):
    """One restart cycle. apply_op(v) -> (w, z) where z is the direction to
    accumulate into x (z == v for plain GMRES, z == M v for flexible).

    ``direction(j, V)`` optionally overrides the expansion direction at step
    j (LGMRES passes its stored corrections for the augmented tail);
    ``n_steps`` (traced or static) caps the cycle below m. When ``hist`` is
    given (the caller's history buffer), each step writes its relative
    residual ``res / hist_scale`` at slot ``hist_base + j`` — inside the
    device loop, no host sync (telemetry/history.py).

    ``health``/``guard_step`` thread the caller's HealthState through the
    cycle (telemetry/health.py): guard_step(hs, it, res, trips) runs each
    step with the Hessenberg-breakdown trip (h[j+1,j] ≈ 0 while res > eps
    — a 'lucky' breakdown at convergence is not an error), and a fatal
    trip masks the step's commits so the assembled correction stays
    finite. Returns (dx, steps, res, hist, health)."""
    from amgcl_tpu.telemetry import health as He
    n = r0.shape[0]
    dtype = r0.dtype
    beta = jnp.sqrt(jnp.abs(dot(r0, r0)))
    safe_beta = jnp.where(beta == 0, 1.0, beta)
    V0 = jnp.zeros((m + 1, n), dtype)
    V0 = V0.at[0].set(r0 / safe_beta)
    Z0 = jnp.zeros((m, n), dtype)
    R0 = jnp.eye(m, dtype=dtype)          # unwritten columns stay identity
    g0 = jnp.zeros(m + 1, dtype).at[0].set(beta)
    cs0 = jnp.ones(m, dtype)
    sn0 = jnp.zeros(m, dtype)
    cap = m if n_steps is None else n_steps
    record = hist is not None
    if not record:       # 1-slot dummy keeps the carry structure static
        hist = jnp.zeros(1, r0.real.dtype)
    if health is None:   # structural dummy when the caller has no guards
        health = He.init_state(jnp.real(beta))

    def cond(st):
        V, Z, R, g, cs, sn, j, res, hst, hs = st
        go = He.keep_going(hs) if guard_step is not None else True
        return (j < cap) & (res > eps) & go

    def body(st):
        # hst is the residual-history buffer; h below is the Hessenberg
        # column — distinct names, both live in the carry
        V, Z, R, g, cs, sn, j, res, hst, hs = st
        v = V[j] if direction is None else direction(j, V)
        w, z = apply_op(v)
        # CGS2: h = V w; w -= V^T h; second pass for stability. The basis
        # dots go through the seam-aware batched dot (ops/fused_vec.py
        # stack_dots): one read of V per pass, and inside shard_map the
        # m+1 per-column psums merge into ONE collective of the stacked
        # partials — a raw V @ w would silently compute shard-local
        # (unreduced) products.
        h1 = fv.stack_dots(V, w, ip=dot)
        w = w - V.T @ h1
        h2 = fv.stack_dots(V, w, ip=dot)
        w = w - V.T @ h2
        h = h1 + h2
        hn = jnp.sqrt(jnp.abs(dot(w, w)))

        # apply stored rotations k = 0..j-1 to h
        def rot(k, hv):
            a = hv[k]
            b = hv[k + 1]
            apply = k < j
            c, s = cs[k], sn[k]
            ha = jnp.where(apply, c * a + s * b, a)
            hb = jnp.where(apply, -jnp.conj(s) * a + c * b, b)
            return hv.at[k].set(ha).at[k + 1].set(hb)

        h = h.at[j + 1].set(hn)
        h = lax.fori_loop(0, m, rot, h)
        c, s = _givens(h[j], h[j + 1])
        rjj = c * h[j] + s * h[j + 1]
        h = h.at[j].set(rjj).at[j + 1].set(0.0)
        gj = g[j]
        res_n = jnp.abs(-jnp.conj(s) * gj)
        if guard_step is not None:
            # Hessenberg breakdown: the new R diagonal rjj ≈ 0 while the
            # PRE-step residual is still above eps — the Krylov space
            # became invariant without solving the system, and accepting
            # the column would make the triangular solve singular (an
            # all-NaN dx). A 'lucky' breakdown (hn ≈ 0 with h[j] normal)
            # keeps rjj = h[j] and converges cleanly; and res_n is NOT
            # usable here: on a null-space rhs the zero-column Givens
            # rotation annihilates g[j+1], so the post-rotation residual
            # reads 0 exactly when the solve is most broken.
            ok, hs = guard_step(
                hs, hist_base + j, res_n / hist_scale,
                ((He.BREAKDOWN_HESSENBERG,
                  He.bad_denom(rjj) & (res > eps)),))
        else:
            ok = jnp.asarray(True)
        # commits masked by ok: a fatal trip leaves column j unwritten
        # (identity placeholder, g[j] untouched, Z[j] zero), so the
        # masked triangular solve assembles dx from committed steps only
        Z = Z.at[j].set(jnp.where(ok, z, Z[j]))
        V = V.at[j + 1].set(jnp.where(
            ok, w / jnp.where(hn == 0, 1.0, hn), V[j + 1]))
        cs = cs.at[j].set(jnp.where(ok, c, cs[j]))
        sn = sn.at[j].set(jnp.where(ok, s, sn[j]))
        g = g.at[j].set(jnp.where(ok, c * gj, g[j])) \
             .at[j + 1].set(jnp.where(ok, -jnp.conj(s) * gj, g[j + 1]))
        # write column j of R (rows 0..j live; keep the identity placeholder
        # in columns never reached so the masked solve stays nonsingular)
        col = jnp.where(ok & (jnp.arange(m) <= j), h[:m], R[:, j])
        R = R.at[:, j].set(col)
        res = jnp.where(ok, res_n, res)
        if record:
            hst = hst.at[hist_base + j].set(jnp.where(
                ok, (res_n / hist_scale).real.astype(hst.dtype),
                hst[hist_base + j]))
        return (V, Z, R, g, cs, sn, j + ok.astype(jnp.int32), res, hst,
                hs)

    st = (V0, Z0, R0, g0, cs0, sn0, jnp.zeros((), jnp.int32), beta, hist,
          health)
    V, Z, R, g, cs, sn, j, res, hist, health = lax.while_loop(cond, body,
                                                              st)
    # masked triangular solve: unwritten columns have R[k,k]=1, g[k]=0
    y = jax.scipy.linalg.solve_triangular(R, g[:m], lower=False)
    dx = Z.T @ y
    return dx, j, res, hist, health


@dataclass
class GMRES(HistoryMixin):
    """Restarted GMRES(M) (reference default M=30). ``pside`` selects the
    preconditioning side (reference: amgcl/solver/precond_side.hpp,
    gmres.hpp:77-96 — the reference defaults to right; here the historical
    default is left, with right sharing the flexible machinery: for a
    constant preconditioner FGMRES *is* right-preconditioned GMRES)."""
    M: int = 30
    maxiter: int = 100
    tol: float = 1e-8
    pside: str = "left"
    record_history: bool = False  # per-iteration relative residuals
    guard: bool = True      # in-loop health guards (telemetry/health.py)

    flexible = False

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        if rhs.ndim == 2:
            # stacked multi-RHS entry (serve/batched.py)
            from amgcl_tpu.serve.batched import vmap_solve
            return vmap_solve(self, A, precond, rhs, x0, inner_product)
        dot = inner_product
        x = jnp.zeros_like(rhs) if x0 is None else x0
        if self.pside not in ("left", "right"):
            raise ValueError("pside must be 'left' or 'right'")

        if self.flexible or self.pside == "right":
            def apply_op(v):
                z = precond(v)
                return dev.spmv(A, z), z

            def resid0(x):
                return dev.residual(rhs, A, x)
        else:
            def apply_op(v):
                w = precond(dev.spmv(A, v))
                return w, v

            def resid0(x):
                return precond(dev.residual(rhs, A, x))

        # norm of (preconditioned) rhs for the relative criterion
        bref = resid0(jnp.zeros_like(rhs))
        norm_rhs = jnp.sqrt(jnp.abs(dot(bref, bref)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = self.tol * scale

        def cond(st):
            x, it, res, hist, hs = st
            return (it < self.maxiter) & (res > eps) & self._guard_go(hs)

        def body(st):
            x, it, res, hist, hs = st
            r = resid0(x)
            dx, steps, res, hist, hs = _arnoldi_cycle(
                apply_op, r, self.M, eps, dot,
                hist=hist if self.record_history else None,
                hist_base=it, hist_scale=scale, health=hs,
                guard_step=self._guard_step if self.guard else None)
            return (x + dx, it + steps, res, hist, hs)

        r0 = resid0(x)
        res0 = jnp.sqrt(jnp.abs(dot(r0, r0)))
        # a restart cycle started at it = maxiter - 1 may run M more steps
        hist0 = self._hist_init(rhs.real.dtype, overshoot=self.M)
        st = (x, jnp.zeros((), jnp.int32), res0, hist0,
              self._guard_init(res0 / scale))
        x, it, res, hist, hs = lax.while_loop(cond, body, st)
        return self._hist_result(x, it, res / scale, hist, health=hs)


@dataclass
class FGMRES(GMRES):
    """Flexible (right-preconditioned) GMRES — the preconditioner may change
    between iterations (reference: amgcl/solver/fgmres.hpp)."""
    flexible = True
