"""LGMRES: restarted GMRES augmented with error-correction directions from
previous restart cycles, which damps the restart stalling of plain GMRES(m)
(reference: amgcl/solver/lgmres.hpp, defaults M=30, K=3).

Reuses the Arnoldi/Givens cycle from :mod:`gmres`: the first ``M-K``
expansion directions are the Krylov basis vectors, the last ``K`` are the
stored outer corrections (the ``direction`` hook); the accumulated Z
directions always hold whatever each step expanded with, so the LS update
applies uniformly.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.solver.gmres import _arnoldi_cycle


@dataclass
class LGMRES:
    M: int = 30
    K: int = 3
    maxiter: int = 100
    tol: float = 1e-8

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        dot = inner_product
        m, K = self.M, self.K
        mk = max(m - K, 1)
        n = rhs.shape[0]
        dtype = rhs.dtype
        x = jnp.zeros_like(rhs) if x0 is None else x0

        def apply_op(v):
            return precond(dev.spmv(A, v)), v

        def presid(x):
            return precond(dev.residual(rhs, A, x))

        bref = presid(jnp.zeros_like(rhs))
        norm_rhs = jnp.sqrt(jnp.abs(dot(bref, bref)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = self.tol * scale

        def outer_cond(st):
            x, aug, n_aug, it, res = st
            return (it < self.maxiter) & (res > eps)

        def outer_body(st):
            x, aug, n_aug, it, res = st
            r = presid(x)

            def direction(j, V):
                return jnp.where(j < mk, V[jnp.minimum(j, mk - 1)],
                                 aug[jnp.clip(j - mk, 0, K - 1)])

            dx, steps, res = _arnoldi_cycle(
                apply_op, r, m, eps, dot, direction=direction,
                n_steps=mk + jnp.minimum(n_aug, K))
            nrm = jnp.sqrt(jnp.abs(dot(dx, dx)))
            aug = jnp.roll(aug, 1, axis=0).at[0].set(
                dx / jnp.where(nrm == 0, 1.0, nrm))
            return (x + dx, aug, jnp.minimum(n_aug + 1, K), it + steps, res)

        r0 = presid(x)
        st = (x, jnp.zeros((K, n), dtype), 0, 0,
              jnp.sqrt(jnp.abs(dot(r0, r0))))
        x, aug, n_aug, it, res = lax.while_loop(outer_cond, outer_body, st)
        return x, it, res / scale