"""LGMRES: restarted GMRES augmented with error-correction directions from
previous restart cycles, which damps the restart stalling of plain GMRES(m)
(reference: amgcl/solver/lgmres.hpp, defaults M=30, K=3).

Reuses the Arnoldi/Givens cycle from :mod:`gmres`: the first ``M-K``
expansion directions are the Krylov basis vectors, the last ``K`` are the
stored outer corrections (the ``direction`` hook); the accumulated Z
directions always hold whatever each step expanded with, so the LS update
applies uniformly.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.solver.gmres import _arnoldi_cycle
from amgcl_tpu.telemetry.history import HistoryMixin


@dataclass
class LGMRES(HistoryMixin):
    """``pside`` selects the preconditioning side (reference:
    amgcl/solver/lgmres.hpp params, default side::right there; here the
    historical default stays left). With ``pside='right'`` the Arnoldi
    directions live in the unpreconditioned W-space and the
    preconditioner is applied ONCE to the assembled correction per cycle
    (lgmres.hpp:384-389), with true residuals tracked."""
    M: int = 30
    K: int = 3
    maxiter: int = 100
    tol: float = 1e-8
    pside: str = "left"
    record_history: bool = False  # per-iteration relative residuals
    guard: bool = True      # in-loop health guards (telemetry/health.py)

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        if rhs.ndim == 2:
            # stacked multi-RHS entry (serve/batched.py)
            from amgcl_tpu.serve.batched import vmap_solve
            return vmap_solve(self, A, precond, rhs, x0, inner_product)
        dot = inner_product
        m, K = self.M, self.K
        mk = max(m - K, 1)
        n = rhs.shape[0]
        dtype = rhs.dtype
        x = jnp.zeros_like(rhs) if x0 is None else x0
        if self.pside not in ("left", "right"):
            raise ValueError("pside must be 'left' or 'right'")
        left = self.pside == "left"

        if left:
            def apply_op(v):
                return precond(dev.spmv(A, v)), v

            def presid(x):
                return precond(dev.residual(rhs, A, x))
        else:
            # preconditioner::spmv(side::right): w = A (M z); the stored
            # directions are the z themselves, M lands on the assembled dx
            def apply_op(v):
                return dev.spmv(A, precond(v)), v

            def presid(x):
                return dev.residual(rhs, A, x)

        bref = presid(jnp.zeros_like(rhs))
        norm_rhs = jnp.sqrt(jnp.abs(dot(bref, bref)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = self.tol * scale

        def outer_cond(st):
            x, aug, n_aug, it, res, hist, hs = st
            return (it < self.maxiter) & (res > eps) & self._guard_go(hs)

        def outer_body(st):
            x, aug, n_aug, it, res, hist, hs = st
            r = presid(x)

            def direction(j, V):
                return jnp.where(j < mk, V[jnp.minimum(j, mk - 1)],
                                 aug[jnp.clip(j - mk, 0, K - 1)])

            dx, steps, res, hist, hs = _arnoldi_cycle(
                apply_op, r, m, eps, dot, direction=direction,
                n_steps=mk + jnp.minimum(n_aug, K),
                hist=hist if self.record_history else None,
                hist_base=it, hist_scale=scale, health=hs,
                guard_step=self._guard_step if self.guard else None)
            # augmentation stores the W-space correction for BOTH sides
            # (lgmres.hpp:363-371 normalizes dx before the P application)
            nrm = jnp.sqrt(jnp.abs(dot(dx, dx)))
            aug = jnp.roll(aug, 1, axis=0).at[0].set(
                dx / jnp.where(nrm == 0, 1.0, nrm))
            step = dx if left else precond(dx)
            return (x + step, aug, jnp.minimum(n_aug + 1, K),
                    it + steps, res, hist, hs)

        r0 = presid(x)
        res0 = jnp.sqrt(jnp.abs(dot(r0, r0)))
        # a cycle runs up to mk + K steps — more than m when K >= M
        st = (x, jnp.zeros((K, n), dtype), 0, jnp.zeros((), jnp.int32),
              res0, self._hist_init(rhs.real.dtype, overshoot=mk + K),
              self._guard_init(res0 / scale))
        x, aug, n_aug, it, res, hist, hs = lax.while_loop(
            outer_cond, outer_body, st)
        return self._hist_result(x, it, res / scale, hist, health=hs)