"""Direct solver for the coarsest AMG level.

The reference factorizes the gathered coarse matrix with a Cuthill-McKee +
skyline LU (amgcl/solver/skyline_lu.hpp:80-311, used when the level is below
``coarse_enough`` rows). On TPU the right shape for a <=few-thousand-row
solve is dense, and the per-cycle coarse solve becomes a single MXU matmul
— no triangular dependency chains on device.

The inverse itself: on TPU it is computed ON DEVICE in float32 and
polished by two Newton-Schulz steps (X <- X(2I - AX), three MXU matmuls —
quadratic residual reduction, so the f32 LU's eps*kappa error drops toward
the f32 cast floor the host f64 path lands on anyway). A ~3000-row host
float64 inversion costs ~1s of setup; the device version is milliseconds.
AMGCL_TPU_DEVICE_INV=1/0 forces/disables it (CPU backends default to the
host float64 path)."""

from __future__ import annotations

import functools
import os
import warnings

import numpy as np
import scipy.linalg
import jax
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR


@register_pytree_node_class
class DenseDirectSolver:
    """Coarse direct solve as y = A⁻¹ f with the inverse precomputed on host."""

    def __init__(self, inv, block=1):
        self.inv = inv
        self.block = int(block)

    def tree_flatten(self):
        return (self.inv,), (self.block,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0])

    def solve(self, f):
        return self.inv @ f

    @classmethod
    def build(cls, A: CSR, dtype=jnp.float32) -> "DenseDirectSolver":
        S = A.unblock() if A.is_block else A
        dense = S.to_dense().astype(
            np.complex128 if np.iscomplexobj(S.val) else np.float64)
        n = dense.shape[0]
        if n == 0:
            return cls(jnp.zeros((0, 0), dtype=dtype))
        block = A.block_size[0] if A.is_block else 1

        flag = os.environ.get("AMGCL_TPU_DEVICE_INV")
        want_device = (flag == "1" or (flag != "0"
                                       and jax.default_backend() == "tpu"))
        if (want_device and not np.iscomplexobj(dense)
                and jnp.dtype(dtype).itemsize <= 4):
            Ad = jnp.asarray(dense, dtype=jnp.float32)
            X, rnorm = _device_inv(Ad)
            # accept only a demonstrably good inverse: near-singular coarse
            # operators (cond >> 1/eps_f32) give a FINITE but useless f32
            # inverse that Newton-Schulz makes worse — those fall through
            # to the host f64 LU / pinv regularization
            if bool(jnp.isfinite(rnorm)) and float(rnorm) < 1e-3:
                return cls(X.astype(jnp.dtype(dtype)), block)
            if bool(jnp.isfinite(rnorm)) and float(rnorm) < 1e-2:
                # borderline: a host f64 LU would do better — take it, but
                # leave an attributable trace for convergence forensics
                warnings.warn(
                    "device f32 coarse inverse rejected near the gate "
                    "(||AX-I||_F/sqrt(n) = %.2e); using host f64 path"
                    % float(rnorm), RuntimeWarning, stacklevel=2)

        # regularize the (often singular-up-to-constant) coarse operator the
        # pragmatic way: pseudo-inverse fallback when LU is too ill-posed.
        # The pinv branch switches semantics to a least-squares solve —
        # the right thing for operators singular up to constants (pure
        # Neumann coarse levels), and what the reference's skyline LU
        # degenerates to with its tiny-pivot clamp. Announced, not silent.
        try:
            inv = scipy.linalg.inv(dense)
            if not np.all(np.isfinite(inv)):
                raise np.linalg.LinAlgError
        except (np.linalg.LinAlgError, scipy.linalg.LinAlgError):
            inv = np.linalg.pinv(dense)
            warnings.warn(
                "singular coarse operator: coarse solve uses the "
                "pseudo-inverse (least-squares solve)", RuntimeWarning,
                stacklevel=2)
        return cls(jnp.asarray(inv, dtype=dtype), block)


from amgcl_tpu.telemetry.compile_watch import watched_jit as _watched_jit


@functools.partial(_watched_jit, name="solver.direct.device_inv")
def _device_inv(Ad):
    """f32 inverse + two Newton-Schulz polish steps (X <- X(2I - A X)):
    quadratic residual contraction, all MXU matmuls. Returns
    (X, ||A X - I||_F / sqrt(n)) — the column-averaged residual the
    caller gates acceptance on."""
    n = Ad.shape[0]
    I = jnp.eye(n, dtype=Ad.dtype)
    X = jnp.linalg.inv(Ad)
    for _ in range(2):
        X = X @ (2.0 * I - Ad @ X)
    rnorm = jnp.linalg.norm(Ad @ X - I) / jnp.sqrt(jnp.float32(max(n, 1)))
    return X, rnorm
