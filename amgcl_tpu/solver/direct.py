"""Direct solver for the coarsest AMG level.

The reference factorizes the gathered coarse matrix with a Cuthill-McKee +
skyline LU (amgcl/solver/skyline_lu.hpp:80-311, used when the level is below
``coarse_enough`` rows). On TPU the right shape for a <=few-thousand-row
solve is dense: the inverse is computed once on the host in float64 and the
per-cycle coarse solve becomes a single MXU matmul — no triangular
dependency chains on device.
"""

from __future__ import annotations

import numpy as np
import scipy.linalg
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR


@register_pytree_node_class
class DenseDirectSolver:
    """Coarse direct solve as y = A⁻¹ f with the inverse precomputed on host."""

    def __init__(self, inv, block=1):
        self.inv = inv
        self.block = int(block)

    def tree_flatten(self):
        return (self.inv,), (self.block,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0])

    def solve(self, f):
        return self.inv @ f

    @classmethod
    def build(cls, A: CSR, dtype=jnp.float32) -> "DenseDirectSolver":
        S = A.unblock() if A.is_block else A
        dense = S.to_dense().astype(
            np.complex128 if np.iscomplexobj(S.val) else np.float64)
        n = dense.shape[0]
        if n == 0:
            return cls(jnp.zeros((0, 0), dtype=dtype))
        # regularize the (often singular-up-to-constant) coarse operator the
        # pragmatic way: pseudo-inverse fallback when LU is too ill-posed
        try:
            inv = scipy.linalg.inv(dense)
            if not np.all(np.isfinite(inv)):
                raise np.linalg.LinAlgError
        except (np.linalg.LinAlgError, scipy.linalg.LinAlgError):
            inv = np.linalg.pinv(dense)
        return cls(jnp.asarray(inv, dtype=dtype),
                   A.block_size[0] if A.is_block else 1)
