"""BiCGStab(L): BiCG steps combined with an L-step minimal-residual
polynomial update (Sleijpen–Fokkema), curing the omega-breakdowns of plain
BiCGStab on strongly non-symmetric/indefinite problems (reference:
amgcl/solver/bicgstabl.hpp, default L=2).

``pside`` selects the preconditioning side (default right, matching the
reference): right runs the recurrence on op = A∘M in correction form with
TRUE residuals tracked; left runs on op = M∘A with preconditioned
residuals. L is static, so the inner BiCG/MR parts unroll into
straight-line XLA code over an (L+1, n) stacked residual basis.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops import fused_vec as fv
from amgcl_tpu.telemetry.history import HistoryMixin


@dataclass
class BiCGStabL(HistoryMixin):
    """``delta`` enables the reliable-update scheme of bicgstabl.hpp:
    386-409 — when the recursive residual has dropped far enough below
    its running peaks, the TRUE residual of the inner operator is
    recomputed (curing recursion drift), and on the stronger condition
    the accumulated correction is flushed into the solution and the
    effective rhs re-centered. delta=0 (the reference default) disables
    the machinery entirely."""
    L: int = 2
    maxiter: int = 100
    tol: float = 1e-8
    pside: str = "right"  # the reference default (bicgstabl.hpp:137)
    delta: float = 0.0    # reliable-update threshold (bicgstabl.hpp:110)
    record_history: bool = False  # per-iteration relative residuals
    guard: bool = True    # in-loop health guards (telemetry/health.py)

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        if rhs.ndim == 2:
            # stacked multi-RHS entry (serve/batched.py)
            from amgcl_tpu.serve.batched import vmap_solve
            return vmap_solve(self, A, precond, rhs, x0, inner_product)
        dot = inner_product
        Lp = self.L
        if self.pside not in ("left", "right"):
            raise ValueError("pside must be 'left' or 'right'")
        right = self.pside == "right"
        x_init = jnp.zeros_like(rhs) if x0 is None else x0

        if right:
            # recurrence runs on op = A∘M in y-space from y = 0 (correction
            # form); x = x0 + M y at the end. The tracked residuals are the
            # TRUE residuals of the original system.
            def op(v):
                return dev.spmv(A, precond(v))

            def op_dot_rhat(v, rhat):
                # fused spmv + <rhat, op(v)> on the DIA path; spmv_dots
                # yields <y, rhat> — conjugate (identity for real)
                y, _, _, yr = dev.spmv_dots(A, precond(v), rhat, dot)
                return y, jnp.conj(yr)

            b_p = rhs
            # fused residual + <r,r> — zeta0 rides the operator pass
            r0, zz0 = fv.residual_dot(rhs, A, x_init, ip=dot)
            x = jnp.zeros_like(rhs)
        else:
            def op(v):
                return precond(dev.spmv(A, v))

            def op_dot_rhat(v, rhat):
                y = op(v)
                return y, dot(rhat, y)

            b_p = precond(rhs)
            r0 = b_p - op(x_init)
            zz0 = dot(r0, r0)
            x = x_init
        norm_rhs = jnp.sqrt(jnp.abs(dot(b_p, b_p)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = self.tol * scale
        rhat = r0
        n = rhs.shape[0]
        dtype = rhs.dtype
        use_delta = self.delta > 0
        zeta0 = jnp.sqrt(jnp.abs(zz0))
        if use_delta and not right:
            # reliable updates need the correction form on BOTH sides:
            # run from Xc = 0 against B = r0, flush into xbase
            x = jnp.zeros_like(rhs)

        from amgcl_tpu.telemetry import health as He

        def cond(st):
            res, it = st[7], st[6]
            return (it < self.maxiter) & (res > eps) \
                & self._guard_go(st[-1])

        def body(st):
            if use_delta:
                (x, R, U, rho, alpha, omega, it, res,
                 xbase, B, rnc, rnt, hist, hs) = st
            else:
                x, R, U, rho, alpha, omega, it, res, hist, hs = st
            # the reference exits the whole solve the moment ||R[0]|| drops
            # below eps INSIDE the BiCG stage (bicgstabl.hpp:296-299,
            # `goto done`) — without that, a near-exact preconditioner
            # makes the post-convergence step divide ~0/~0 and poison the
            # state with NaN. Traced control flow cannot goto, so each
            # unrolled step commits its candidate state only while `live`.
            live = res > eps
            took = jnp.zeros((), jnp.int32)
            guard_on = bool(self.guard)
            false0 = jnp.zeros((), bool)
            trip_rho, trip_gamma, nan_seen = false0, false0, false0

            def commit(m, new, old):
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(m, a, b), new, old)

            def finite_or_pass(z):
                # when guarding, a non-finite step residual is never
                # committed (the health flags below stop the loop); with
                # guards off the historical NaN-exit path is preserved
                return jnp.isfinite(z) if guard_on else jnp.asarray(True)

            rho = -omega * rho
            # -- BiCG part --
            for j in range(Lp):
                rho1 = dot(rhat, R[j])
                beta = alpha * rho1 / jnp.where(rho == 0, 1.0, rho)
                Uc = U
                for i in range(j + 1):
                    Uc = Uc.at[i].set(R[i] - beta * Uc[i])
                ujp1, gamma = op_dot_rhat(Uc[j], rhat)
                Uc = Uc.at[j + 1].set(ujp1)
                alpha_c = rho1 / jnp.where(gamma == 0, 1.0, gamma)
                # R[0]'s update carries the zeta reduction in the same
                # pass (ops/fused_vec.py); the remaining rows are plain
                # axpys with no dependent dot
                r0c, zz = fv.axpby_dot(-alpha_c, Uc[1], 1.0, R[0],
                                       ip=dot)
                Rc = R.at[0].set(r0c)
                for i in range(1, j + 1):
                    Rc = Rc.at[i].set(Rc[i] - alpha_c * Uc[i + 1])
                Rc = Rc.at[j + 1].set(op(Rc[j]))
                xc = x + alpha_c * Uc[0]
                zeta = jnp.sqrt(jnp.abs(zz))
                if guard_on:
                    trip_rho = trip_rho | (live & He.bad_denom(rho1))
                    trip_gamma = trip_gamma | (live & He.bad_denom(gamma))
                    nan_seen = nan_seen | (live & ~jnp.isfinite(zeta))
                step_ok = live & finite_or_pass(zeta)
                hist = self._hist_put(hist, it + took, zeta / scale,
                                      keep=step_ok)
                took = took + step_ok.astype(jnp.int32)
                x, R, U, rho, alpha, res = commit(
                    step_ok, (xc, Rc, Uc, rho1, alpha_c, zeta),
                    (x, R, U, rho, alpha, res))
                if use_delta:
                    # peaks track EVERY inner step (bicgstabl.hpp:292-294)
                    # so intra-cycle spikes arm the recompute triggers
                    rnc = jnp.where(step_ok, jnp.maximum(rnc, zeta), rnc)
                    rnt = jnp.where(step_ok, jnp.maximum(rnt, zeta), rnt)
                live = live & (zeta > eps) & finite_or_pass(zeta)
            # -- MR part: minimize ||R[0] - sum_j g_j R[j]|| over j=1..L --
            # Gram products through the seam-aware batched dot
            # (ops/fused_vec.py block_dots): ONE read of the stacked
            # basis — and inside shard_map ONE psum of the (L, L+1)
            # partial matrix instead of L(L+1) scalar collectives; a raw
            # conj(Z)@Z.T would be shard-local and silently wrong
            # distributed, which is exactly what block_dots' psum seam
            # handling prevents.
            Z = R[1:]                       # (L, n)
            gram = fv.block_dots(Z, R, ip=dot)       # (L, L+1)
            G = gram[:, 1:]
            rhs_g = gram[:, 0]
            gam = jnp.linalg.solve(
                G + 1e-300 * jnp.eye(Lp, dtype=dtype), rhs_g)
            xc = x + jnp.tensordot(gam, R[:Lp], axes=1)
            Rc = R.at[0].set(R[0] - jnp.tensordot(gam, R[1:], axes=1))
            Uc = U.at[0].set(U[0] - jnp.tensordot(gam, U[1:], axes=1))
            res_c = jnp.sqrt(jnp.abs(dot(Rc[0], Rc[0])))
            if guard_on:
                nan_seen = nan_seen | (live & ~jnp.isfinite(res_c))
            mr_ok = live & finite_or_pass(res_c)
            x, R, U, omega, res = commit(
                mr_ok, (xc, Rc, Uc, gam[Lp - 1], res_c), (x, R, U, omega,
                                                          res))
            # the cycle's last counted step ends at the post-MR committed
            # residual — overwrite its slot so history[-1] == returned res
            hist = self._hist_put(hist, it + took - 1, res / scale,
                                  keep=took > 0)
            # one guard update per cycle, on the committed (finite)
            # residual; the per-step trips collected above ride along
            _, hs = self._guard_step(
                hs, it + jnp.maximum(took - 1, 0), res / scale,
                ((He.BREAKDOWN_RHO, trip_rho),
                 (He.BREAKDOWN_ALPHA, trip_gamma),
                 (He.NAN, nan_seen)))
            if not use_delta:
                return (x, R, U, rho, alpha, omega, it + took, res, hist,
                        hs)

            # -- reliable updates (bicgstabl.hpp:386-409): recompute the
            # true inner-operator residual when the recursive one has
            # dropped below delta times its running peaks; on the stronger
            # condition also flush the correction into the solution and
            # re-center the effective rhs
            rnc = jnp.maximum(res, rnc)
            rnt = jnp.maximum(res, rnt)
            update_x = (res < self.delta * zeta0) & (zeta0 <= rnc) & live
            recomp = (((res < self.delta * rnt) & (res <= rnt))
                      | update_x) & live

            def do_flush(args):
                xc, Rr, xb, Bc, rc, rt = args
                # compute M xc once and reuse it for both the true
                # residual and the flush (the reference's *T intermediate,
                # bicgstabl.hpp:394-404) — a second precond application
                # here would be a whole extra V-cycle
                Mx = precond(xc) if right else xc
                r_true = Bc - (dev.spmv(A, Mx) if right else op(xc))
                Rr = Rr.at[0].set(r_true)

                def do_up(a):
                    xc2, xb2, Bc2, rc2 = a
                    return jnp.zeros_like(xc2), xb2 + Mx, r_true, res

                xc, xb, Bc, rc = lax.cond(update_x, do_up, lambda a: a,
                                          (xc, xb, Bc, rc))
                return xc, Rr, xb, Bc, rc, res

            x, R, xbase, B, rnc, rnt = lax.cond(
                recomp, do_flush, lambda a: a,
                (x, R, xbase, B, rnc, rnt))
            return (x, R, U, rho, alpha, omega, it + took, res,
                    xbase, B, rnc, rnt, hist, hs)

        R0 = jnp.zeros((Lp + 1, n), dtype).at[0].set(r0)
        U0 = jnp.zeros((Lp + 1, n), dtype)
        one = jnp.ones((), dtype)
        st = (x, R0, U0, one, jnp.zeros((), dtype), one, 0, zeta0)
        if use_delta:
            st = st + (x_init, r0, zeta0, zeta0)
        st = st + (self._hist_init(rhs.real.dtype, overshoot=Lp),
                   self._guard_init(zeta0 / scale))
        out = lax.while_loop(cond, body, st)
        x, it, res, hist, hs = out[0], out[6], out[7], out[-2], out[-1]
        if use_delta:
            xbase = out[8]
            x = xbase + (precond(x) if right else x)
        elif right:
            x = x_init + precond(x)
        return self._hist_result(x, it, res / scale, hist, health=hs)
