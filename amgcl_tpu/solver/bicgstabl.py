"""BiCGStab(L): BiCG steps combined with an L-step minimal-residual
polynomial update (Sleijpen–Fokkema), curing the omega-breakdowns of plain
BiCGStab on strongly non-symmetric/indefinite problems (reference:
amgcl/solver/bicgstabl.hpp, default L=2).

``pside`` selects the preconditioning side (default right, matching the
reference): right runs the recurrence on op = A∘M in correction form with
TRUE residuals tracked; left runs on op = M∘A with preconditioned
residuals. L is static, so the inner BiCG/MR parts unroll into
straight-line XLA code over an (L+1, n) stacked residual basis.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev


@dataclass
class BiCGStabL:
    L: int = 2
    maxiter: int = 100
    tol: float = 1e-8
    pside: str = "right"  # the reference default (bicgstabl.hpp:137)

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        dot = inner_product
        Lp = self.L
        if self.pside not in ("left", "right"):
            raise ValueError("pside must be 'left' or 'right'")
        right = self.pside == "right"
        x_init = jnp.zeros_like(rhs) if x0 is None else x0

        if right:
            # recurrence runs on op = A∘M in y-space from y = 0 (correction
            # form); x = x0 + M y at the end. The tracked residuals are the
            # TRUE residuals of the original system.
            def op(v):
                return dev.spmv(A, precond(v))

            def op_dot_rhat(v, rhat):
                # fused spmv + <rhat, op(v)> on the DIA path; spmv_dots
                # yields <y, rhat> — conjugate (identity for real)
                y, _, _, yr = dev.spmv_dots(A, precond(v), rhat, dot)
                return y, jnp.conj(yr)

            b_p = rhs
            r0 = dev.residual(rhs, A, x_init)
            x = jnp.zeros_like(rhs)
        else:
            def op(v):
                return precond(dev.spmv(A, v))

            def op_dot_rhat(v, rhat):
                y = op(v)
                return y, dot(rhat, y)

            b_p = precond(rhs)
            r0 = b_p - op(x_init)
            x = x_init
        norm_rhs = jnp.sqrt(jnp.abs(dot(b_p, b_p)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = self.tol * scale
        rhat = r0
        n = rhs.shape[0]
        dtype = rhs.dtype

        def cond(st):
            x, R, U, rho, alpha, omega, it, res = st
            return (it < self.maxiter) & (res > eps)

        def body(st):
            x, R, U, rho, alpha, omega, it, res = st
            rho = -omega * rho
            # -- BiCG part --
            for j in range(Lp):
                rho1 = dot(rhat, R[j])
                beta = alpha * rho1 / jnp.where(rho == 0, 1.0, rho)
                rho = rho1
                for i in range(j + 1):
                    U = U.at[i].set(R[i] - beta * U[i])
                ujp1, gamma = op_dot_rhat(U[j], rhat)
                U = U.at[j + 1].set(ujp1)
                alpha = rho / jnp.where(gamma == 0, 1.0, gamma)
                for i in range(j + 1):
                    R = R.at[i].set(R[i] - alpha * U[i + 1])
                R = R.at[j + 1].set(op(R[j]))
                x = x + alpha * U[0]
            # -- MR part: minimize ||R[0] - sum_j g_j R[j]|| over j=1..L --
            # Gram products go through the inner-product seam (vmapped) so
            # they stay globally reduced inside shard_map; a raw conj(Z)@Z.T
            # would be shard-local and silently wrong distributed.
            Z = R[1:]                       # (L, n)
            G = jax.vmap(lambda zi: jax.vmap(lambda zj: dot(zi, zj))(Z))(Z)
            rhs_g = jax.vmap(lambda zi: dot(zi, R[0]))(Z)
            gam = jnp.linalg.solve(
                G + 1e-300 * jnp.eye(Lp, dtype=dtype), rhs_g)
            x = x + jnp.tensordot(gam, R[:Lp], axes=1)
            R = R.at[0].set(R[0] - jnp.tensordot(gam, R[1:], axes=1))
            U = U.at[0].set(U[0] - jnp.tensordot(gam, U[1:], axes=1))
            omega = gam[Lp - 1]
            res = jnp.sqrt(jnp.abs(dot(R[0], R[0])))
            return (x, R, U, rho, alpha, omega, it + Lp, res)

        R0 = jnp.zeros((Lp + 1, n), dtype).at[0].set(r0)
        U0 = jnp.zeros((Lp + 1, n), dtype)
        one = jnp.ones((), dtype)
        st = (x, R0, U0, one, jnp.zeros((), dtype), one, 0,
              jnp.sqrt(jnp.abs(dot(r0, r0))))
        x, R, U, rho, alpha, omega, it, res = lax.while_loop(cond, body, st)
        if right:
            x = x_init + precond(x)
        return x, it, res / scale
