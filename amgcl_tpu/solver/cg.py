"""Preconditioned conjugate gradients.

The iteration runs entirely on device inside one ``lax.while_loop`` — the
TPU-native rendition of the reference's CG whose loop body is pure backend
primitives (reference: amgcl/solver/cg.hpp:140-207).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops import fused_vec as fv
from amgcl_tpu.telemetry.history import HistoryMixin


@dataclass
class CG(HistoryMixin):
    maxiter: int = 100
    tol: float = 1e-8
    abstol: float = 0.0
    ns_search: bool = False  # keep iterating on a zero rhs to find
    #                          null-space vectors (cg.hpp:90-94,163-168)
    verbose: bool = False   # print residual every 5 iterations (cg.hpp:199)
    record_history: bool = False  # per-iteration relative residuals
    guard: bool = True      # in-loop health guards (telemetry/health.py)

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product,
              abstol=None):
        """Returns (x, iters, relative_residual). ``precond`` is a traceable
        function r -> approximate solution of A z = r. ``abstol`` may be a
        traced value (used by iterative refinement to stop correction solves
        exactly at the global target)."""
        if rhs.ndim == 2:
            # stacked multi-RHS entry (serve/batched.py): one program
            # retires every column, per-RHS convergence masking + guards
            from amgcl_tpu.serve.batched import vmap_solve
            return vmap_solve(self, A, precond, rhs, x0, inner_product,
                              abstol=abstol)
        dot = inner_product
        x = jnp.zeros_like(rhs) if x0 is None else x0
        # fused residual + <r,r> (ops/fused_vec.py): one operator pass
        # yields both the initial residual and res0 below
        r, rr0 = fv.residual_dot(rhs, A, x, ip=dot)
        norm_rhs = jnp.sqrt(jnp.abs(dot(rhs, rhs)))
        # if ||rhs|| == 0 the solution is x = 0 (reference cg.hpp:144-149)
        norm_scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        if abstol is None:
            abstol = jnp.asarray(self.abstol, rhs.dtype).real
        eps = jnp.maximum(self.tol * norm_scale, abstol)

        from amgcl_tpu.telemetry import health as H
        # ns_search drives the iterates INTO the null space, where the
        # breakdown denominators legitimately vanish — guards off there
        guard_trips = self.guard and not self.ns_search

        def cond(state):
            x, r, p, rho_prev, it, res, hist, hs = state
            return (it < self.maxiter) & (res > eps) & self._guard_go(hs)

        def body(state):
            x, r, p, rho_prev, it, res, hist, hs = state
            s = precond(r)
            rho = dot(r, s)
            beta = jnp.where(rho_prev == 0, 0.0, rho / rho_prev)
            p_n = dev.axpby(1.0, s, beta, p)
            q, qp = dev.spmv_dot(A, p_n, dot)
            # guarded: the safe division only protects the candidate that
            # the breakdown trip below will discard anyway; unguarded:
            # keep the raw division so a singular direction poisons the
            # state and the loop NaN-exits through `res > eps` — the
            # historical failure signal guard=False callers rely on
            alpha = rho / (jnp.where(qp == 0, 1.0, qp) if guard_trips
                           else qp)
            # fused tail (ops/fused_vec.py): x += alpha p, r -= alpha q
            # and <r,r> from ONE read of {p,q,x,r} — the residual
            # reduction rides the update instead of re-streaming r
            x_n, r_n, rr = fv.xr_update(alpha, p_n, q, x, r, ip=dot)
            res_n = jnp.sqrt(jnp.abs(rr))
            if guard_trips:
                # rho: residual orthogonal to the preconditioned residual;
                # qp ≈ 0: singular direction; qp < 0: not positive
                # definite (informational — CG may still proceed)
                ok, hs = self._guard_step(
                    hs, it, res_n / norm_scale,
                    ((H.BREAKDOWN_RHO, H.bad_denom(rho)),
                     (H.BREAKDOWN_ALPHA, H.bad_denom(qp)),
                     (H.INDEFINITE, jnp.real(qp) < 0, False)))
            elif self.guard:
                # ns_search: the breakdown/stagnation/divergence guards
                # are off (iterating INTO the null space is the point),
                # but a NaN residual is still a failure — watch for it so
                # the returned HealthState stays honest
                nan_trip = ~jnp.isfinite(jnp.real(res_n))
                hs = H.trip(hs, it, H.NAN, nan_trip)
                ok = ~nan_trip
            else:
                ok = jnp.asarray(True)
            x, r, p, rho, res = self._guard_commit(
                ok, (x_n, r_n, p_n, rho, res_n), (x, r, p, rho_prev, res))
            hist = self._hist_put(hist, it, res_n / norm_scale, keep=ok)
            if self.verbose:
                import jax
                jax.lax.cond(
                    (it + 1) % 5 == 0,
                    lambda: jax.debug.print("iter {i}: resid {r:.6e}",
                                            i=it + 1, r=res / norm_scale),
                    lambda: None)
            return (x, r, p, rho, it + ok.astype(jnp.int32), res, hist, hs)

        res0 = jnp.sqrt(jnp.abs(rr0))
        hist0 = self._hist_init(rhs.real.dtype)
        state = (x, r, jnp.zeros_like(r), jnp.zeros((), rhs.dtype),
                 jnp.zeros((), jnp.int32), res0, hist0,
                 self._guard_init(res0 / norm_scale))
        x, r, p, rho, iters, res, hist, hs = lax.while_loop(cond, body,
                                                            state)
        if not self.ns_search:
            # ||rhs|| == 0 => the solution is x = 0; with ns_search the
            # iterates from a nonzero x0 approach a null-space vector
            # instead (reference cg.hpp:163-168)
            x = jnp.where(norm_rhs > 0, x, jnp.zeros_like(x))
        return self._hist_result(x, iters, res / norm_scale, hist,
                                 health=hs)
