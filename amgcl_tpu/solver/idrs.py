"""IDR(s): induced dimension reduction with biorthogonalization
(van Gijzen & Sonneveld 2011 prototype; reference: amgcl/solver/idrs.hpp,
default s=4, deterministic shadow space).

The shadow space P is a fixed pseudo-random (s, n) block generated
per-COLUMN from the global row index (``jax.random.fold_in`` of a fixed
key), then orthonormalized with modified Gram-Schmidt routed through the
inner-product seam.  That makes the shadow space a function of the GLOBAL
problem only: inside ``shard_map`` each shard hashes its own global row
indices and the MGS dots psum-reduce, so the distributed run uses exactly
the serial shadow space (the round-1 version drew P from the local vector
length — a different space per shard — and its P-dots were shard-local).
s is static, so the inner k-loop unrolls with masked slices instead of
dynamic shapes.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops import fused_vec as fv
from amgcl_tpu.telemetry.history import HistoryMixin


def _shadow_block(s, row_index, n_valid, dtype, dot):
    """Deterministic (s, nloc) shadow block: column j is a hash of the
    GLOBAL row index j, zeroed on padding rows (>= n_valid), then MGS-
    orthonormalized with globally-reduced dots."""
    key = jax.random.PRNGKey(4321)
    cols = jax.vmap(
        lambda j: jax.random.normal(jax.random.fold_in(key, j), (s,)))(
            row_index)                       # (nloc, s)
    P = cols.T.astype(dtype)
    if n_valid is not None:
        P = P * (row_index < n_valid).astype(dtype)[None, :]
    for i in range(s):
        for l in range(i):
            P = P.at[i].add(-dot(P[l], P[i]) * P[l])
        nrm = jnp.sqrt(jnp.abs(dot(P[i], P[i])))
        P = P.at[i].set(P[i] / jnp.where(nrm == 0, 1.0, nrm))
    return P


@dataclass
class IDRs(HistoryMixin):
    s: int = 4
    maxiter: int = 100
    tol: float = 1e-8
    replacement: bool = False   # interface parity; smoothing not needed here
    record_history: bool = False  # per-iteration relative residuals
    guard: bool = True      # in-loop health guards (telemetry/health.py)

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product,
              row_index=None, n_valid=None):
        if rhs.ndim == 2:
            # stacked multi-RHS entry (serve/batched.py); the shadow-
            # space row index plumbing stays per-column-identical
            from amgcl_tpu.serve.batched import vmap_solve
            return vmap_solve(self, A, precond, rhs, x0, inner_product,
                              row_index=row_index, n_valid=n_valid)
        dot = inner_product
        s = self.s
        n = rhs.shape[0]
        dtype = rhs.dtype
        x = jnp.zeros_like(rhs) if x0 is None else x0

        idx = jnp.arange(n) if row_index is None else row_index
        P = _shadow_block(s, idx, n_valid, dtype, dot)
        # all shadow-space products below go through the seam-aware
        # batched dot (ops/fused_vec.py): one read of P per block, and
        # inside shard_map the s per-column psums merge into ONE
        # collective of the stacked partials
        def pdots(Pm, v):
            return fv.stack_dots(Pm, v, ip=dot)

        norm_rhs = jnp.sqrt(jnp.abs(dot(rhs, rhs)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = self.tol * scale

        r0, rr0 = fv.residual_dot(rhs, A, x, ip=dot)

        from amgcl_tpu.telemetry import health as He
        guard_on = bool(self.guard)

        def cond(st):
            x, r, G, U, M, om, it, res, hist, hs = st
            return (it < self.maxiter) & (res > eps) & self._guard_go(hs)

        def body(st):
            x, r, G, U, M, om, it, res, hist, hs = st
            f = pdots(P, r)                           # (s,)
            # `alive` masks the unrolled sub-steps after a guard trip the
            # way bicgstabl's `live` masks post-convergence steps: the
            # candidate state of a broken sub-step is never committed, so
            # the returned iterate/history stay finite
            alive = jnp.ones((), bool)
            false0 = jnp.zeros((), bool)
            trip_rho, trip_om, nan_seen = false0, false0, false0
            took = jnp.zeros((), jnp.int32)
            for k in range(s):
                # solve the lower-right (s-k) system M[k:,k:] c = f[k:],
                # done as a masked full solve: rows/cols < k act as identity
                mask = jnp.arange(s) >= k
                Mk = jnp.where(mask[:, None] & mask[None, :], M,
                               jnp.eye(s, dtype=dtype))
                fk = jnp.where(mask, f, 0.0)
                c = jnp.linalg.solve(Mk, fk)          # zeros for i<k
                v = r - jnp.tensordot(c, G, axes=1)
                v = precond(v)
                u = om * v + jnp.tensordot(c, U, axes=1)
                g = dev.spmv(A, u)
                # biorthogonalize against P[0..k-1]
                for i in range(k):
                    al = dot(P[i], g) / M[i, i]
                    g = g - al * G[i]
                    u = u - al * U[i]
                G = G.at[k].set(g)
                U = U.at[k].set(u)
                M = M.at[:, k].set(pdots(P, g))
                beta = f[k] / jnp.where(M[k, k] == 0, 1.0, M[k, k])
                if guard_on or self.record_history:
                    # fused sub-step tail: x += beta U[k], r -= beta G[k]
                    # and the <r,r> the guard/history needs, in one pass
                    x_n, r_n, rr_k = fv.xr_update(beta, U[k], G[k], x, r,
                                                  ip=dot)
                else:
                    r_n = r - beta * G[k]
                    x_n = x + beta * U[k]
                f_n = f - beta * M[:, k]
                if guard_on:
                    # M[k,k] = <P_k, g> ≈ 0: the residual left the shadow
                    # space — the IDR(s) analogue of a rho-breakdown
                    bad = He.bad_denom(M[k, k])
                    res_k = jnp.sqrt(jnp.abs(rr_k))
                    trip_rho = trip_rho | (alive & bad)
                    nan_seen = nan_seen | (alive & ~jnp.isfinite(res_k))
                    step_ok = alive & ~bad & jnp.isfinite(res_k)
                    r, x, f = He.commit(step_ok, (r_n, x_n, f_n),
                                        (r, x, f))
                    res = jnp.where(step_ok, res_k, res)
                    if self.record_history:
                        hist = self._hist_put(hist, it + k, res_k / scale,
                                              keep=step_ok)
                    took = took + step_ok.astype(jnp.int32)
                    alive = step_ok
                else:
                    r, x, f = r_n, x_n, f_n
                    took = took + 1
                    if self.record_history:
                        # the extra reduction per sub-step only exists
                        # when history is requested — the default path
                        # is untouched (and fused, it rides the update)
                        hist = self._hist_put(
                            hist, it + k,
                            jnp.sqrt(jnp.abs(rr_k)) / scale)
            # dimension-reduction step into the next Sonneveld space
            # (fused spmv + <t,t>/<t,r> on the DIA path — one HBM pass)
            v = precond(r)
            t, tt, _, tr = dev.spmv_dots(A, v, r, dot)
            om_n = tr / jnp.where(tt == 0, 1.0, tt)
            # fused tail: x += om v, r -= om t and <r,r> in one pass
            x_n, r_n, rr_n = fv.xr_update(om_n, v, t, x, r, ip=dot)
            res_n = jnp.sqrt(jnp.abs(rr_n))
            if guard_on:
                bad = He.bad_denom(tt)
                trip_om = trip_om | (alive & bad)
                nan_seen = nan_seen | (alive & ~jnp.isfinite(res_n))
                fin_ok = alive & ~bad & jnp.isfinite(res_n)
                x, r, om = He.commit(fin_ok, (x_n, r_n, om_n), (x, r, om))
                res = jnp.where(fin_ok, res_n, res)
                hist = self._hist_put(hist, it + s, res_n / scale,
                                      keep=fin_ok)
                took = took + fin_ok.astype(jnp.int32)
                _, hs = self._guard_step(
                    hs, it + jnp.maximum(took - 1, 0), res / scale,
                    ((He.BREAKDOWN_RHO, trip_rho),
                     (He.BREAKDOWN_OMEGA, trip_om),
                     (He.NAN, nan_seen)))
            else:
                x, r, om, res = x_n, r_n, om_n, res_n
                hist = self._hist_put(hist, it + s, res / scale)
                took = took + 1
            return (x, r, G, U, M, om, it + took, res, hist, hs)

        res0 = jnp.sqrt(jnp.abs(rr0))
        st = (x, r0, jnp.zeros((s, n), dtype), jnp.zeros((s, n), dtype),
              jnp.eye(s, dtype=dtype), jnp.ones((), dtype),
              jnp.zeros((), jnp.int32), res0,
              self._hist_init(rhs.real.dtype, overshoot=s + 1),
              self._guard_init(res0 / scale))
        x, r, G, U, M, om, it, res, hist, hs = lax.while_loop(cond, body,
                                                              st)
        return self._hist_result(x, it, res / scale, hist, health=hs)
