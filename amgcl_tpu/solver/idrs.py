"""IDR(s): induced dimension reduction with biorthogonalization
(van Gijzen & Sonneveld 2011 prototype; reference: amgcl/solver/idrs.hpp,
default s=4, deterministic shadow space).

The shadow space P is a fixed pseudo-random (s, n) block seeded
deterministically (the reference seeds per-rank the same way); s is static,
so the inner k-loop unrolls with masked slices instead of dynamic shapes.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev


@dataclass
class IDRs:
    s: int = 4
    maxiter: int = 100
    tol: float = 1e-8
    replacement: bool = False   # interface parity; smoothing not needed here

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        dot = inner_product
        s = self.s
        n = rhs.shape[0]
        dtype = rhs.dtype
        x = jnp.zeros_like(rhs) if x0 is None else x0

        rng = np.random.RandomState(4321)
        Pm = rng.randn(s, n)
        # orthonormalize the shadow block on the host
        Pm, _ = np.linalg.qr(Pm.T)
        P = jnp.asarray(Pm.T, dtype=dtype)

        norm_rhs = jnp.sqrt(jnp.abs(dot(rhs, rhs)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = self.tol * scale

        r0 = dev.residual(rhs, A, x)

        def cond(st):
            x, r, G, U, M, om, it, res = st
            return (it < self.maxiter) & (res > eps)

        def body(st):
            x, r, G, U, M, om, it, res = st
            f = jnp.conj(P) @ r                       # (s,)
            for k in range(s):
                # solve the lower-right (s-k) system M[k:,k:] c = f[k:],
                # done as a masked full solve: rows/cols < k act as identity
                mask = jnp.arange(s) >= k
                Mk = jnp.where(mask[:, None] & mask[None, :], M,
                               jnp.eye(s, dtype=dtype))
                fk = jnp.where(mask, f, 0.0)
                c = jnp.linalg.solve(Mk, fk)          # zeros for i<k
                v = r - jnp.tensordot(c, G, axes=1)
                v = precond(v)
                u = om * v + jnp.tensordot(c, U, axes=1)
                g = dev.spmv(A, u)
                # biorthogonalize against P[0..k-1]
                for i in range(k):
                    al = (jnp.conj(P[i]) @ g) / M[i, i]
                    g = g - al * G[i]
                    u = u - al * U[i]
                G = G.at[k].set(g)
                U = U.at[k].set(u)
                M = M.at[:, k].set(jnp.conj(P) @ g)
                beta = f[k] / jnp.where(M[k, k] == 0, 1.0, M[k, k])
                r = r - beta * G[k]
                x = x + beta * U[k]
                f = f - beta * M[:, k]
            # dimension-reduction step into the next Sonneveld space
            v = precond(r)
            t = dev.spmv(A, v)
            tt = dot(t, t)
            om = dot(t, r) / jnp.where(tt == 0, 1.0, tt)
            x = x + om * v
            r = r - om * t
            res = jnp.sqrt(jnp.abs(dot(r, r)))
            return (x, r, G, U, M, om, it + s + 1, res)

        st = (x, r0, jnp.zeros((s, n), dtype), jnp.zeros((s, n), dtype),
              jnp.eye(s, dtype=dtype), jnp.ones((), dtype), 0,
              jnp.sqrt(jnp.abs(dot(r0, r0))))
        x, r, G, U, M, om, it, res = lax.while_loop(cond, body, st)
        return x, it, res / scale
