"""Iterative (Krylov) solvers. Each solver is constructed with parameters and
called as ``solve(A, precond, rhs, x0) -> (x, iters, resid)``, with the whole
iteration compiled as a single ``lax.while_loop`` XLA program (reference
contract: amgcl/solver/cg.hpp:63-252). The ``inner_product`` argument is the
seam the distributed layer uses to globalize reductions (reference:
amgcl/solver/detail/default_inner_product.hpp)."""

from amgcl_tpu.solver.cg import CG
from amgcl_tpu.solver.direct import DenseDirectSolver

__all__ = ["CG", "DenseDirectSolver"]
