"""Iterative (Krylov) solvers. Each solver is constructed with parameters and
called as ``solve(A, precond, rhs, x0) -> (x, iters, resid)``, with the whole
iteration compiled as a single ``lax.while_loop`` XLA program (reference
contract: amgcl/solver/cg.hpp:63-252). The ``inner_product`` argument is the
seam the distributed layer uses to globalize reductions (reference:
amgcl/solver/detail/default_inner_product.hpp).

Every solver mixes in :class:`amgcl_tpu.telemetry.history.HistoryMixin`:
with ``record_history=True`` the per-iteration relative residuals are
recorded inside the device loop and returned as a trailing element
(``(x, iters, resid, history)``), which ``make_solver`` folds into the
:class:`~amgcl_tpu.telemetry.SolveReport`. With ``guard=True`` (the
default) a compact numerical-health state rides the loop as well —
NaN/breakdown/stagnation/divergence detection with early exit
(telemetry/health.py) — appended as the final trailing element and
decoded into ``SolveReport.health``.
"""

from amgcl_tpu.solver.cg import CG
from amgcl_tpu.solver.bicgstab import BiCGStab
from amgcl_tpu.solver.bicgstabl import BiCGStabL
from amgcl_tpu.solver.gmres import GMRES, FGMRES
from amgcl_tpu.solver.lgmres import LGMRES
from amgcl_tpu.solver.idrs import IDRs
from amgcl_tpu.solver.richardson import Richardson
from amgcl_tpu.solver.preonly import PreOnly
from amgcl_tpu.solver.direct import DenseDirectSolver

__all__ = ["CG", "BiCGStab", "BiCGStabL", "GMRES", "FGMRES", "LGMRES",
           "IDRs", "Richardson", "PreOnly", "DenseDirectSolver"]
